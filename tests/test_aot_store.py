"""AOT program store (parallel/aot_store.py, ISSUE 18): content-
addressed executables keyed by (family, shape signature, knobs,
jax/backend runtime, topology). The contracts under test: keys are
stable across processes (the pre-warm CLI's whole value), any version /
mesh / knob skew can only MISS (a wrong-program load is impossible by
keying), a corrupt entry degrades to JIT with a counter instead of
crashing, a warmed engine decodes bit-identically to a cold one with
zero JIT traces and a 1.0 hit rate, AOT_STRICT=require turns a miss
into a hard error, the supervisor runs its pre-warm hook on re-mesh,
and the manifest cross-check catches both uncovered signatures and
stale keys."""

import json
import os
import shutil
import signal
import subprocess
import sys
import textwrap
import threading
import time

import jax
import jax.numpy as jnp
import pytest

from distributed_pytorch_tpu.config import LLMConfig
from distributed_pytorch_tpu.engine import DecodeEngine
from distributed_pytorch_tpu.models.gpt import LLM
from distributed_pytorch_tpu.parallel import aot_store
from distributed_pytorch_tpu.parallel.aot_store import (AOTMissError,
                                                        AOTStore)
from distributed_pytorch_tpu.train import supervisor as sup

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Keying.
# ---------------------------------------------------------------------------

_KEY_SCRIPT = textwrap.dedent("""
    import sys
    sys.path.insert(0, %r)
    import jax
    import jax.numpy as jnp
    from distributed_pytorch_tpu.parallel.aot_store import AOTStore
    s = AOTStore(sys.argv[1])
    avals = ({"w": jax.ShapeDtypeStruct((4, 8), jnp.float32)},
             jax.ShapeDtypeStruct((2,), jnp.int32))
    print(s.key("step", avals, {"kind": "engine", "n_slots": 2}))
""") % REPO


def test_key_stable_across_processes(tmp_path):
    """Two separate interpreters derive the SAME key for the same
    (family, avals, env) — pre-warming in one process and loading in
    another works only because nothing process-local (device ids,
    pickled treedefs, dict order) leaks into the hash."""
    keys = []
    for _ in range(2):
        out = subprocess.run(
            [sys.executable, "-c", _KEY_SCRIPT, str(tmp_path)],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 0, out.stderr
        keys.append(out.stdout.strip())
    assert keys[0] == keys[1]
    assert keys[0].startswith("step-")


def _trivial():
    jitted = jax.jit(lambda x: x + 1)
    avals = [jax.ShapeDtypeStruct((4,), jnp.float32)]
    return jitted, avals


def test_any_skew_changes_the_key(tmp_path):
    """Version, topology, mesh-shape, knob, shape, and family skews each
    produce a DIFFERENT key — the only cross-version/config failure mode
    is a miss, never a wrong-program load."""
    rt = {"jax": "0.4.37", "jaxlib": "0.4.36", "backend": "cpu",
          "platform_version": "", "device_kind": "cpu",
          "n_devices": 1, "n_processes": 1}
    s = AOTStore(str(tmp_path), _runtime=rt)
    _, avals = _trivial()
    base = s.key("step", avals, {"kind": "engine"})
    skews = [
        AOTStore(str(tmp_path),
                 _runtime={**rt, "jaxlib": "0.4.35"}),       # version
        AOTStore(str(tmp_path),
                 _runtime={**rt, "n_processes": 2}),         # topology
        AOTStore(str(tmp_path),
                 _runtime={**rt, "device_kind": "TPU v4"}),  # silicon
    ]
    for other in skews:
        assert other.key("step", avals, {"kind": "engine"}) != base
    # env (mesh/geometry), shape, and family skews on the same runtime
    assert s.key("step", avals,
                 {"kind": "engine", "mesh": {"model": 2}}) != base
    assert s.key("step", [jax.ShapeDtypeStruct((8,), jnp.float32)],
                 {"kind": "engine"}) != base
    assert s.key("fused_step", avals, {"kind": "engine"}) != base


def test_knob_skew_changes_the_key(tmp_path, monkeypatch):
    """PROGRAM_KNOBS are key material: flipping one (here a flash block
    size that changes the compiled kernel) re-keys every program."""
    s = AOTStore(str(tmp_path))
    _, avals = _trivial()
    base = s.key("step", avals, {"kind": "engine"})
    monkeypatch.setenv("FLASH_BLOCK_Q", "128")  # default is 256
    assert s.key("step", avals, {"kind": "engine"}) != base


def test_miss_compiles_and_second_store_hits(tmp_path):
    jitted, avals = _trivial()
    s1 = AOTStore(str(tmp_path))
    fn = s1.build("step", jitted, avals, {"kind": "t"})
    assert (s1.misses, s1.hits, s1.saves) == (1, 0, 1)
    assert fn(jnp.zeros((4,), jnp.float32)).tolist() == [1.0] * 4
    s2 = AOTStore(str(tmp_path))  # fresh handle = fresh counters
    fn2 = s2.build("step", jitted, avals, {"kind": "t"})
    assert (s2.misses, s2.hits) == (0, 1)
    assert s2.compile_ms == 0.0 and s2.load_ms > 0.0
    assert fn2(jnp.ones((4,), jnp.float32)).tolist() == [2.0] * 4
    # a DIFFERENT program never loads from the populated store
    s3 = AOTStore(str(tmp_path))
    s3.build("step", jitted, avals, {"kind": "t", "other": 1})
    assert (s3.misses, s3.hits) == (1, 0)


def test_corrupt_entry_falls_back_to_jit(tmp_path):
    """A torn/garbage .bin must count load_errors and recompile — never
    crash, never return a broken callable."""
    jitted, avals = _trivial()
    s1 = AOTStore(str(tmp_path))
    s1.build("step", jitted, avals, {"kind": "t"})
    [bin_path] = [os.path.join(tmp_path, n) for n in os.listdir(tmp_path)
                  if n.endswith(".bin")]
    with open(bin_path, "wb") as f:
        f.write(b"not a pickled executable")
    s2 = AOTStore(str(tmp_path))
    fn = s2.build("step", jitted, avals, {"kind": "t"})
    assert s2.load_errors == 1 and s2.misses == 1 and s2.hits == 0
    assert fn(jnp.zeros((4,), jnp.float32)).tolist() == [1.0] * 4
    # the recompile rewrote the entry: a third store hits again
    s3 = AOTStore(str(tmp_path))
    s3.build("step", jitted, avals, {"kind": "t"})
    assert (s3.hits, s3.load_errors) == (1, 0)


def test_strict_require_raises_on_miss(tmp_path):
    jitted, avals = _trivial()
    s = AOTStore(str(tmp_path), strict="require")
    with pytest.raises(AOTMissError):
        s.build("step", jitted, avals, {"kind": "t"})
    # ... and is satisfied once another store populated the entry
    AOTStore(str(tmp_path)).build("step", jitted, avals, {"kind": "t"})
    s.build("step", jitted, avals, {"kind": "t"})
    assert s.hits == 1


# ---------------------------------------------------------------------------
# Engine integration: warmed spin-up == cold spin-up, bit for bit.
# ---------------------------------------------------------------------------

def _tiny_cfg():
    return LLMConfig(vocab_size=97, block_size=64, n_embd=48, n_head=4,
                     n_kv_heads=2, attn="gqa", n_layer=2, up_dim=64,
                     non_linearity="swiglu", pos_emb="rope", dropout=0.0)


PROMPTS = [[1, 2, 3], [5, 6, 7, 8, 9, 10, 11], [20] * 17, [42, 43]]


@pytest.fixture(scope="module")
def tiny_model():
    cfg = _tiny_cfg()
    model = LLM(cfg, attn_impl="naive")
    rng = jax.random.PRNGKey(0)
    x = jnp.zeros((1, cfg.block_size), jnp.int32)
    variables = model.init({"params": rng, "dropout": rng}, x, x)
    return model, dict(variables)


@pytest.fixture(scope="module")
def warm_root(tiny_model, tmp_path_factory):
    """A store populated by one engine's warm walk (origin='warm' — the
    aot_warm.py path), shared by the hit-rate/parity/crosscheck tests."""
    model, variables = tiny_model
    root = str(tmp_path_factory.mktemp("aot_warm_store"))
    eng = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                       min_bucket=8, aot_store=AOTStore(root))
    eng.warm_aot(origin="warm")
    assert eng.aot_store.misses > 0  # it actually compiled the universe
    return root


def test_warmed_engine_bit_identical_zero_traces(tiny_model, warm_root):
    model, variables = tiny_model
    cold = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                        min_bucket=8, aot_store=False)
    ref = cold.run(PROMPTS, max_new_tokens=6)

    store = AOTStore(warm_root)  # fresh handle: the restarted replica
    warm = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                        min_bucket=8, aot_store=store)
    warm.warm_aot(origin="runtime")
    out = warm.run(PROMPTS, max_new_tokens=6)

    assert out == ref  # greedy decode is bit-identical warmed vs cold
    # hit rate 1.0: every program came from the store...
    assert store.misses == 0 and store.hits > 0
    assert store.fallbacks == 0 and store.compile_ms == 0.0
    # ...and NOTHING was traced/JIT-compiled in the warmed process
    assert warm.step_traces == 0
    assert warm.fused_step_traces == 0
    assert sum(warm.admit_traces.values()) == 0


def test_crosscheck_clean_then_uncovered_then_stale(warm_root, tmp_path):
    """The commscheck cross-check: the warm manifest set must equal the
    static enumeration — deleting a warm entry (uncovered signature) or
    planting an unrequestable one (stale key) both produce errors."""
    assert aot_store.crosscheck(AOTStore(warm_root)) == []

    # uncovered: drop one warmed admit bucket from a copy of the store
    holey = str(tmp_path / "holey")
    shutil.copytree(warm_root, holey)
    victim = next(k for k, m in AOTStore(holey).manifests().items()
                  if m["family"] == "admit")
    os.remove(os.path.join(holey, victim + ".json"))
    os.remove(os.path.join(holey, victim + ".bin"))
    errs = aot_store.crosscheck(AOTStore(holey))
    assert errs and any("admit" in e for e in errs)

    # stale: an admit entry for a bucket no engine geometry can request
    stale = str(tmp_path / "stale")
    shutil.copytree(warm_root, stale)
    st = AOTStore(stale)
    donor = next(m for m in st.manifests().values()
                 if m["family"] == "admit")
    bogus = dict(donor, key="admit-0000feed",
                 env=dict(donor["env"], bucket=7))  # not block-multiple
    with open(os.path.join(stale, "admit-0000feed.json"), "w") as f:
        json.dump(bogus, f)
    with open(os.path.join(stale, "admit-0000feed.bin"), "wb") as f:
        f.write(b"x")
    errs = aot_store.crosscheck(st)
    assert any("stale key" in e for e in errs)


def test_resolve_store_knob_gate(tmp_path, monkeypatch):
    monkeypatch.delenv("AOT_STORE", raising=False)
    monkeypatch.delenv("AOT_STORE_DIR", raising=False)
    assert aot_store.resolve_store() is None          # auto + no dir
    assert not aot_store.store_configured()
    monkeypatch.setenv("AOT_STORE_DIR", str(tmp_path))
    s = aot_store.resolve_store()                     # auto + dir = on
    assert s is not None and s.root == str(tmp_path)
    assert aot_store.store_configured()
    monkeypatch.setenv("AOT_STORE", "off")            # off wins over dir
    assert aot_store.resolve_store() is None
    assert not aot_store.store_configured()


# ---------------------------------------------------------------------------
# Supervisor re-mesh pre-warm (stub workers + stub pre-warm cmd).
# ---------------------------------------------------------------------------

_STUB = textwrap.dedent("""
    import json, os, sys, time
    hb = os.environ.get("SUPERVISOR_HB_FILE", "")
    interval = float(os.environ.get("SUPERVISOR_HB_INTERVAL_S", "0.1"))
    stop_file = sys.argv[1]
    seq = 0
    while True:
        if hb:
            tmp = hb + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"pid": os.getpid(), "seq": seq}, f)
            os.replace(tmp, hb)
        seq += 1
        if os.path.exists(stop_file):
            sys.exit(0)
        time.sleep(interval)
""")


@pytest.fixture()
def in_tmp(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    return tmp_path


def _events(run_dir):
    try:
        with open(os.path.join(run_dir, sup.TIMELINE_FILE)) as f:
            return [json.loads(line) for line in f if line.strip()]
    except (OSError, ValueError):
        return []


def _wait(predicate, timeout=20.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.02)
    raise TimeoutError(f"timed out waiting for {msg}")


def test_supervisor_prewarms_on_remesh(in_tmp):
    """A held-dead host forces the rung-down re-mesh; the supervisor
    must run prewarm_cmd(new_n) SYNCHRONOUSLY before the survivor gang
    starts and put an `aot_prewarm` record (rc 0, new topology) on the
    timeline. The stub cmd writes a marker instead of compiling."""
    stub = in_tmp / "stub_worker.py"
    stub.write_text(_STUB)
    stop_file = str(in_tmp / "stop_ok")
    marker = str(in_tmp / "prewarmed")
    cfg = sup.SupervisorConfig(
        hosts=2, run_name="aot", poll_s=0.02, hb_timeout_s=60.0,
        max_restarts=4, backoff_base_s=0.05, backoff_cap_s=0.1,
        remesh_deadline_s=0.4, hb_interval_s=0.05)
    prewarm_calls = []

    def prewarm_cmd(n):
        prewarm_calls.append(n)
        return [sys.executable, "-c",
                f"open({marker!r}, 'w').write('{n}')"]

    s = sup.Supervisor(
        cfg, worker_cmd=lambda slot, n, resume: [
            sys.executable, str(stub), stop_file],
        prewarm_cmd=prewarm_cmd, log=lambda m: None)
    rc = {}
    t = threading.Thread(target=lambda: rc.update(code=s.run()),
                         daemon=True)
    t.start()
    run_dir = os.path.join("runs", "aot")

    def state():
        try:
            with open(os.path.join(run_dir, sup.STATE_FILE)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    _wait(lambda: state().get("status") == "running", msg="gang up")
    victim = max(state()["workers"], key=lambda w: w["slot"])
    with open(os.path.join(run_dir, f"hold_{victim['slot']}"), "w") as f:
        f.write("dead host\n")
    os.kill(victim["os_pid"], signal.SIGKILL)

    _wait(lambda: any(e["event"] == "aot_prewarm"
                      for e in _events(run_dir)), msg="pre-warm event")
    open(stop_file, "w").close()
    t.join(timeout=20)
    assert not t.is_alive() and rc["code"] == sup.EXIT_OK
    ev = next(e for e in _events(run_dir) if e["event"] == "aot_prewarm")
    assert ev["n_hosts"] == 1 and ev["rc"] == 0
    assert prewarm_calls == [1]
    with open(marker) as f:
        assert f.read() == "1"  # the subprocess really ran
    names = [e["event"] for e in _events(run_dir)]
    # ordering: the pre-warm lands with the re-mesh decision, before
    # the survivor gang's restart record
    assert names.index("aot_prewarm") > names.index("remesh")


def test_default_prewarm_cmd_gated_on_knobs(in_tmp, monkeypatch):
    """The built-in pre-warm hook is a no-op unless the store knobs are
    live (a disabled store must cost no subprocess), and shells out to
    the aot_store CLI with the run's own train argv when they are."""
    cfg = sup.SupervisorConfig(hosts=2, run_name="aot", cpu_devices=2,
                               train_argv=["--dataset", "synthetic"])
    s = sup.Supervisor(cfg, worker_cmd=lambda *a: ["true"],
                       log=lambda m: None)
    monkeypatch.delenv("AOT_STORE", raising=False)
    monkeypatch.delenv("AOT_STORE_DIR", raising=False)
    assert s._default_prewarm_cmd(1) is None
    monkeypatch.setenv("AOT_STORE", "off")
    monkeypatch.setenv("AOT_STORE_DIR", str(in_tmp))
    assert s._default_prewarm_cmd(1) is None  # off beats a configured dir
    monkeypatch.setenv("AOT_STORE", "auto")
    cmd = s._default_prewarm_cmd(1)
    assert cmd is not None
    assert "distributed_pytorch_tpu.parallel.aot_store" in cmd
    assert cmd[cmd.index("--hosts") + 1] == "1"
    assert cmd[cmd.index("--cpu-devices") + 1] == "2"
    assert cmd[-2:] == ["--dataset", "synthetic"]
