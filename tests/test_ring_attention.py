"""Sequence-parallel attention (ring + Ulysses) vs the full-sequence
oracle, on the 8-device CPU mesh (SURVEY.md §5 long-context — a capability
the reference lacks; these tests are its correctness contract)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.ops.attention_core import _naive_sdpa, sdpa
from distributed_pytorch_tpu.ops.ring_attention import sp_sdpa
from distributed_pytorch_tpu.parallel import context
from distributed_pytorch_tpu.parallel.mesh import MeshPlan, build_mesh


def rand_qkv(key, B, T, nh, nkv, hs):
    kq, kk, kv = jax.random.split(key, 3)
    return (jax.random.normal(kq, (B, T, nh, hs)),
            jax.random.normal(kk, (B, T, nkv, hs)),
            jax.random.normal(kv, (B, T, nkv, hs)))


@pytest.fixture
def mesh24():
    return build_mesh(MeshPlan(data=2, seq=4))


@pytest.mark.parametrize("impl", ["ring", "zigzag", "ulysses"])
def test_sp_matches_full_attention(mesh24, impl):
    B, T, nh, hs = 4, 128, 4, 16
    q, k, v = rand_qkv(jax.random.PRNGKey(0), B, T, nh, nh, hs)
    scale = 1.0 / hs ** 0.5
    ref = _naive_sdpa(q, k, v, scale=scale, q_offset=0, causal=True)
    with context.use_mesh(mesh24):
        out = jax.jit(lambda q, k, v: sp_sdpa(q, k, v, scale=scale,
                                              impl=impl))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_gqa(mesh24):
    B, T, nh, nkv, hs = 2, 64, 4, 2, 16
    q, k, v = rand_qkv(jax.random.PRNGKey(1), B, T, nh, nkv, hs)
    scale = 1.0 / hs ** 0.5
    ref = _naive_sdpa(q, k, v, scale=scale, q_offset=0, causal=True)
    with context.use_mesh(mesh24):
        out = jax.jit(lambda q, k, v: sp_sdpa(q, k, v, scale=scale,
                                              impl="ring"))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("impl", ["ring", "zigzag"])
def test_ring_gradients_match(mesh24, impl):
    """Both ring schedules (contiguous with hop-skipping cond, and the
    load-balanced zig-zag) must backprop identically to the oracle."""
    B, T, nh, hs = 2, 64, 4, 16
    q, k, v = rand_qkv(jax.random.PRNGKey(2), B, T, nh, nh, hs)
    scale = 1.0 / hs ** 0.5
    w = jax.random.normal(jax.random.PRNGKey(3), q.shape)

    def loss_ring(q, k, v):
        return jnp.sum(sp_sdpa(q, k, v, scale=scale, impl=impl) * w)

    def loss_ref(q, k, v):
        return jnp.sum(_naive_sdpa(q, k, v, scale=scale, q_offset=0,
                                   causal=True) * w)

    with context.use_mesh(mesh24):
        gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gn = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gr, gn, "q k v".split()):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-4, err_msg=f"d{name} mismatch")


def test_sdpa_auto_routes_to_ring(mesh24):
    """Under an ambient mesh with seq>1, impl='auto' must use the sp path
    (same numbers as the oracle) without the caller doing anything."""
    B, T, nh, hs = 2, 64, 4, 16
    q, k, v = rand_qkv(jax.random.PRNGKey(4), B, T, nh, nh, hs)
    ref = sdpa(q, k, v, causal=True, impl="naive")
    with context.use_mesh(mesh24):
        out = jax.jit(lambda q, k, v: sdpa(q, k, v, causal=True,
                                           impl="auto"))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_sdpa_decode_shapes_bypass_sp(mesh24):
    """KV-cached decode (T=1, q_offset traced) must not try shard_map."""
    B, nh, hs, S = 2, 4, 16, 64
    q = jax.random.normal(jax.random.PRNGKey(5), (B, 1, nh, hs))
    k = jax.random.normal(jax.random.PRNGKey(6), (B, S, nh, hs))
    v = jax.random.normal(jax.random.PRNGKey(7), (B, S, nh, hs))
    with context.use_mesh(mesh24):
        out = sdpa(q, k, v, causal=True, q_offset=jnp.int32(S - 1),
                   impl="auto")
    ref = sdpa(q, k, v, causal=True, q_offset=jnp.int32(S - 1), impl="naive")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6,
                               atol=1e-6)


def _replay_sp_keep_mask(B, T, nh, rate, rng, dp):
    """Host replay of the sp dropout mask (ops/ring_attention
    _hop_dropout_mask + sp_sdpa's per-data-shard seed fold)."""
    from distributed_pytorch_tpu.ops.flash_attention import (
        _mix_bits, dropout_threshold, fold_seed_for_data_shard)
    seed = jax.random.randint(rng, (2,), -2 ** 31, 2 ** 31 - 1, jnp.int32)
    shape = (B // dp, nh, T, T)
    keeps = []
    for d in range(dp):
        sd = fold_seed_for_data_shard(seed, d)
        row = (jax.lax.broadcasted_iota(jnp.uint32, shape, 0)
               * jnp.uint32(nh)
               + jax.lax.broadcasted_iota(jnp.uint32, shape, 1))
        qp = jax.lax.broadcasted_iota(jnp.uint32, shape, 2)
        kp = jax.lax.broadcasted_iota(jnp.uint32, shape, 3)
        bits = _mix_bits(sd[0], sd[1], row, qp, kp)
        keeps.append((np.asarray(bits) >= np.uint32(
            dropout_threshold(rate))).astype(np.float32) / (1 - rate))
    return np.concatenate(keeps, axis=0)               # (B, nh, T, T)


def _sp_dropout_oracle(q, k, v, scale, rate, rng, dp):
    """Full naive softmax, then the exact replayed keep mask, then @ v."""
    B, T, nh, hs = q.shape
    keep = _replay_sp_keep_mask(B, T, nh, rate, rng, dp)

    nkv = k.shape[2]
    kk = np.repeat(np.asarray(k), nh // nkv, axis=2)
    vv = np.repeat(np.asarray(v), nh // nkv, axis=2)
    s = np.einsum("btnh,bsnh->bnts", np.asarray(q, np.float32),
                  kk.astype(np.float32)) * scale
    mask = np.tril(np.ones((T, T), bool))
    s = np.where(mask[None, None], s, -np.inf)
    attn = np.exp(s - s.max(-1, keepdims=True))
    attn /= attn.sum(-1, keepdims=True)
    return np.einsum("bnts,bsnh->btnh", attn * keep, vv)


@pytest.mark.parametrize("impl", ["ring", "zigzag"])
def test_sp_dropout_exact_vs_replayed_mask(mesh24, impl):
    """Round 5: dropout no longer disables sp. The einsum hops draw a
    global-position-keyed mask, so the distributed result must EXACTLY
    match a host oracle replaying the same mask — including the per-data-
    shard seed fold (mesh24 is data=2 x seq=4)."""
    B, T, nh, hs = 4, 128, 4, 16
    scale, rate = 1.0 / hs ** 0.5, 0.3
    rng = jax.random.PRNGKey(11)
    q, k, v = rand_qkv(jax.random.PRNGKey(8), B, T, nh, nh, hs)
    with context.use_mesh(mesh24):
        out = sp_sdpa(q, k, v, scale=scale, causal=True, impl=impl,
                      dropout_rate=rate, dropout_rng=rng)
    ref = _sp_dropout_oracle(q, k, v, scale, rate, rng, dp=2)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


def test_sp_dropout_grads_match_oracle(mesh24):
    B, T, nh, hs = 4, 64, 4, 16
    scale, rate = 1.0 / hs ** 0.5, 0.2
    rng = jax.random.PRNGKey(12)
    q, k, v = rand_qkv(jax.random.PRNGKey(13), B, T, nh, nh, hs)
    w = jax.random.normal(jax.random.PRNGKey(14), q.shape)

    def f(q, k, v):
        return jnp.sum(sp_sdpa(q, k, v, scale=scale, causal=True,
                               impl="ring", dropout_rate=rate,
                               dropout_rng=rng) * w)

    with context.use_mesh(mesh24):
        g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    # oracle grads: differentiate the replayed-mask einsum directly
    keep = jnp.asarray(_replay_sp_keep_mask(B, T, nh, rate, rng, dp=2))

    def oracle(q, k, v):
        s = jnp.einsum("btnh,bsnh->bnts", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * scale
        cm = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(cm[None, None], s, -jnp.inf)
        attn = jax.nn.softmax(s, axis=-1) * keep
        return jnp.sum(jnp.einsum("bnts,bsnh->btnh", attn, v) * w)

    g_ref = jax.grad(oracle, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_sdpa_auto_routes_dropout_to_sp(mesh24):
    """The dispatcher must keep the sp path for dropout>0 (round-4 demoted
    to full-sequence naive attention with a warning; round 5 composes)."""
    B, T, nh, hs = 4, 64, 4, 16
    q, k, v = rand_qkv(jax.random.PRNGKey(8), B, T, nh, nh, hs)
    rng = jax.random.PRNGKey(9)
    with context.use_mesh(mesh24):
        out = sdpa(q, k, v, causal=True, dropout_rate=0.1,
                   dropout_rng=rng, impl="auto")
        ref = sp_sdpa(q, k, v, scale=1.0 / hs ** 0.5, causal=True,
                      impl="zigzag", dropout_rate=0.1, dropout_rng=rng)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_sp_training_step_with_ring_matches_oracle():
    """End-to-end: the sp recipe's train step (ring attention active via
    'auto') reproduces the single-device optimizer step."""
    from distributed_pytorch_tpu.config import LLMConfig, TrainConfig
    from distributed_pytorch_tpu.parallel import sharding as shd
    from distributed_pytorch_tpu.parallel.mesh import resolve_plan
    from distributed_pytorch_tpu.train.state import create_train_state
    from distributed_pytorch_tpu.train.step import make_train_step
    from jax.sharding import NamedSharding

    mc = LLMConfig(vocab_size=128, block_size=32, n_embd=32, n_head=4,
                   n_kv_heads=4, n_layer=2, up_dim=64, pos_emb="rope",
                   attn="mha")
    rng = np.random.default_rng(0)
    x = rng.integers(0, 128, size=(2, 4, 32)).astype(np.int32)
    y = rng.integers(0, 128, size=(2, 4, 32)).astype(np.int32)

    def run(recipe, mesh, **kw):
        tc = TrainConfig(total_batch_size=2 * 4 * 32, batch_size=4,
                         parallelism=recipe, **kw)
        model, tx, state, st_sh = create_train_state(mc, tc, mesh)
        step = make_train_step(model, tx, mc, tc, mesh, st_sh)
        xb, yb = jnp.asarray(x), jnp.asarray(y)
        if mesh is not None:
            bsh = NamedSharding(mesh, shd.batch_pspec(recipe, mesh,
                                                      leading_accum=True))
            xb = jax.device_put(xb, bsh)
            yb = jax.device_put(yb, bsh)
        state, m = step(state, xb, yb)
        return float(m["loss"]), jax.device_get(state.params)

    loss_1, params_1 = run("single", None)
    mesh = build_mesh(resolve_plan("sp", 8, sp_size=4))
    loss_sp, params_sp = run("sp", mesh, sp_size=4)
    assert abs(loss_1 - loss_sp) < 1e-4, (loss_1, loss_sp)
    flat1 = jax.tree_util.tree_leaves(params_1)
    flat2 = jax.tree_util.tree_leaves(params_sp)
    for a, b in zip(flat1, flat2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-4)


def test_ring_without_mesh_fails_loudly():
    """De-trap (round-3 VERDICT #9): an explicit ring/ulysses request traced
    WITHOUT an ambient mesh must raise, not silently lose sequence
    parallelism (the routing is a trace-time decision outside jit's cache
    key)."""
    import pytest
    from distributed_pytorch_tpu.ops.attention_core import sdpa

    q = jnp.zeros((2, 16, 4, 8))
    for impl in ("ring", "ulysses"):
        with pytest.raises(ValueError, match="seq"):
            sdpa(q, q, q, impl=impl)
    # decode-shaped calls (KV longer than Q, cache offset) legitimately
    # fall back — sp never applies to decode even in sp training
    kv = jnp.zeros((2, 32, 4, 8))
    out = sdpa(q[:, :1], kv, kv, impl="ring", q_offset=31)
    assert out.shape == (2, 1, 4, 8)


def test_zigzag_permutation_roundtrip():
    from distributed_pytorch_tpu.ops.ring_attention import zigzag_permutation
    perm, inv = zigzag_permutation(32, 4)
    assert sorted(perm.tolist()) == list(range(32))
    assert (perm[inv] == np.arange(32)).all()
    # shard 0 holds stripe 0 (earliest) and stripe 7 (latest)
    assert perm[:4].tolist() == [0, 1, 2, 3]
    assert perm[4:8].tolist() == [28, 29, 30, 31]


def test_zigzag_matches_contiguous_ring():
    """Zig-zag is a pure re-scheduling: same output as the contiguous ring
    (and hence as full attention) to numerical tolerance."""
    from distributed_pytorch_tpu.ops.ring_attention import (
        ring_attention_local, zigzag_ring_attention_local,
        zigzag_permutation)
    from distributed_pytorch_tpu.parallel.mesh import build_mesh, resolve_plan
    from jax.sharding import PartitionSpec as P

    B, T, H, D, sp = 2, 32, 4, 8, 4
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, T, H, D))
    k = jax.random.normal(ks[1], (B, T, H, D))
    v = jax.random.normal(ks[2], (B, T, H, D))
    mesh = build_mesh(resolve_plan("sp", 8, sp_size=sp))
    spec = P("data", "seq", None, None)
    scale = 1.0 / D ** 0.5

    import functools

    from distributed_pytorch_tpu import compat
    ring = compat.shard_map(
        functools.partial(ring_attention_local, scale=scale, sp=sp),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec)(q, k, v)

    perm, inv = zigzag_permutation(T, sp)
    zz = compat.shard_map(
        functools.partial(zigzag_ring_attention_local, scale=scale, sp=sp),
        mesh=mesh, in_specs=(spec,) * 3,
        out_specs=spec)(q[:, perm], k[:, perm], v[:, perm])[:, inv]

    np.testing.assert_allclose(np.asarray(zz), np.asarray(ring),
                               rtol=2e-5, atol=2e-6)


def test_ring_fallback_when_stripes_dont_divide():
    """T not divisible by 2*sp: sp_sdpa silently uses the contiguous ring
    and still matches full attention."""
    from distributed_pytorch_tpu.ops.attention_core import sdpa
    from distributed_pytorch_tpu.parallel import context
    from distributed_pytorch_tpu.parallel.mesh import build_mesh, resolve_plan

    B, T, H, D, sp = 2, 24, 4, 8, 4   # 24 % 8 != 0 -> contiguous
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (B, T, H, D))
    k = jax.random.normal(ks[1], (B, T, H, D))
    v = jax.random.normal(ks[2], (B, T, H, D))
    oracle = sdpa(q, k, v, impl="naive")
    mesh = build_mesh(resolve_plan("sp", 8, sp_size=sp))
    with context.use_mesh(mesh):
        got = sdpa(q, k, v, impl="ring")
    np.testing.assert_allclose(np.asarray(got), np.asarray(oracle),
                               rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("impl", ["ring", "zigzag"])
def test_ring_flash_hops_match_oracle(monkeypatch, mesh24, impl):
    """The flash-kernel hop path (per-hop (out, lse) pairs merged online,
    VMEM softmax, dlse-aware backward) must reproduce full attention —
    values AND gradients. Forced on via the interpret-mode pallas idiom."""
    import jax.experimental.pallas as pl
    import distributed_pytorch_tpu.ops.attention_core as core
    import distributed_pytorch_tpu.ops.flash_attention as fa

    orig = pl.pallas_call
    monkeypatch.setattr(
        fa.pl, "pallas_call",
        lambda *a, **kw: orig(*a, **{**kw, "interpret": True}))
    monkeypatch.setattr(core, "_on_tpu", lambda: True)

    B, T, nh, hs = 2, 64, 4, 16
    q, k, v = rand_qkv(jax.random.PRNGKey(7), B, T, nh, nh, hs)
    scale = 1.0 / hs ** 0.5
    w = jax.random.normal(jax.random.PRNGKey(8), q.shape)

    def loss_sp(q, k, v):
        return jnp.sum(sp_sdpa(q, k, v, scale=scale, impl=impl) * w)

    def loss_ref(q, k, v):
        return jnp.sum(_naive_sdpa(q, k, v, scale=scale, q_offset=0,
                                   causal=True) * w)

    with context.use_mesh(mesh24):
        out = jax.jit(lambda a, b, c: sp_sdpa(a, b, c, scale=scale,
                                              impl=impl))(q, k, v)
        gr = jax.jit(jax.grad(loss_sp, argnums=(0, 1, 2)))(q, k, v)
    ref = _naive_sdpa(q, k, v, scale=scale, q_offset=0, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    gn = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gr, gn, "q k v".split()):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-4,
                                   atol=3e-4, err_msg=f"d{name} mismatch")


@pytest.mark.slow
def test_long_context_sp_train_step():
    """Long-context capability smoke: a full sp train step at T=2048 on the
    8-device mesh (seq=4) — 16x the reference's practical context — runs,
    produces a finite loss, and the zigzag ring keeps per-device score
    slabs at (T/sp)^2 (this would OOM the reference's O(T^2) mask path
    long before 32k; SURVEY §5 long-context)."""
    from distributed_pytorch_tpu.config import LLMConfig, TrainConfig
    from distributed_pytorch_tpu.parallel import sharding as shd
    from distributed_pytorch_tpu.parallel.mesh import resolve_plan
    from distributed_pytorch_tpu.train.state import create_train_state
    from distributed_pytorch_tpu.train.step import make_train_step
    from jax.sharding import NamedSharding

    T = 2048
    mc = LLMConfig(vocab_size=256, block_size=T, n_embd=64, n_head=4,
                   n_kv_heads=4, n_layer=2, up_dim=128, pos_emb="rope",
                   attn="mha")
    tc = TrainConfig(total_batch_size=2 * T, batch_size=2,
                     parallelism="sp", sp_size=4)
    mesh = build_mesh(resolve_plan("sp", 8, sp_size=4))
    model, tx, state, st_sh = create_train_state(mc, tc, mesh)
    step = make_train_step(model, tx, mc, tc, mesh, st_sh)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.integers(0, 256, (1, 2, T)), jnp.int32)
    y = jnp.asarray(rng.integers(0, 256, (1, 2, T)), jnp.int32)
    bsh = NamedSharding(mesh, shd.batch_pspec("sp", mesh,
                                              leading_accum=True))
    x = jax.device_put(x, bsh)
    y = jax.device_put(y, bsh)
    state, m = step(state, x, y)
    loss = float(jax.device_get(m["loss"]))
    assert np.isfinite(loss) and 4.0 < loss < 7.0, loss
