"""Streaming HTTP front-end (serve/server.py) e2e on localhost: 32
concurrent SSE streams with mixed prompt lengths and mid-stream client
disconnects, bit-identical to offline DecodeEngine greedy decoding;
/metrics exposes non-empty TTFT/ITL histograms; queue-full maps to 429.

Every async body runs under a hard `asyncio.wait_for` so a hung stream
fails fast here AND in the dedicated CI step (tier1.yml runs this file
under `timeout`)."""

import asyncio
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.config import LLMConfig
from distributed_pytorch_tpu.engine import DecodeEngine
from distributed_pytorch_tpu.models.gpt import LLM
from distributed_pytorch_tpu.serve.scheduler import Scheduler
from distributed_pytorch_tpu.serve.server import ServeApp


def tiny_cfg(**kw):
    base = dict(vocab_size=97, block_size=64, n_embd=48, n_head=4,
                n_kv_heads=2, attn="gqa", n_layer=2, up_dim=64,
                non_linearity="swiglu", pos_emb="rope", dropout=0.0)
    base.update(kw)
    return LLMConfig(**base)


@pytest.fixture(scope="module")
def mv():
    cfg = tiny_cfg()
    model = LLM(cfg, attn_impl="naive")
    rng = jax.random.PRNGKey(0)
    x = jnp.zeros((1, cfg.block_size), jnp.int32)
    variables = dict(model.init({"params": rng, "dropout": rng}, x, x))
    return cfg, model, variables


def run_async(coro, timeout=300):
    return asyncio.run(asyncio.wait_for(coro, timeout))


# ----------------------------------------------------------------------
# minimal stdlib HTTP/SSE client
# ----------------------------------------------------------------------

async def http_get(port, path):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {path} HTTP/1.1\r\nHost: t\r\n\r\n".encode())
    await writer.drain()
    data = await reader.read()
    writer.close()
    head, _, body = data.partition(b"\r\n\r\n")
    status = int(head.split(b" ")[1])
    return status, body.decode()


async def http_post(port, path, obj):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    body = json.dumps(obj).encode()
    writer.write(f"POST {path} HTTP/1.1\r\nHost: t\r\n"
                 f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
    await writer.drain()
    return reader, writer


async def sse_client(port, prompt, max_tokens, cancel_after=None):
    """POST a streaming completion; return (tokens, done_event). With
    `cancel_after`, hard-close the connection after that many tokens —
    the mid-stream disconnect the server must turn into a cancel."""
    reader, writer = await http_post(
        port, "/v1/completions",
        {"prompt": prompt, "max_tokens": max_tokens})
    status_line = await reader.readline()
    status = int(status_line.split(b" ")[1])
    assert status == 200, status_line
    while (await reader.readline()).strip():      # drain headers
        pass
    tokens, done = [], None
    while True:
        line = (await reader.readline()).decode().strip()
        if not line:
            continue
        assert line.startswith("data: ")
        payload = line[len("data: "):]
        if payload == "[DONE]":
            break
        ev = json.loads(payload)
        if "token" in ev:
            tokens.append(ev["token"])
            if cancel_after is not None and len(tokens) >= cancel_after:
                writer.close()                    # mid-stream disconnect
                return tokens, {"cancelled_by_client": True}
        elif "done" in ev:
            done = ev
        elif "error" in ev:
            done = ev
            break
    writer.close()
    return tokens, done


# ----------------------------------------------------------------------

N_REQ = 32
CANCEL_EVERY = 5      # requests 0, 5, 10, ... disconnect mid-stream
CANCEL_AFTER = 2


def _workload(vocab):
    """Seeded mixed-length workload; cancel targets get budgets too large
    to finish before the disconnect lands, so cancellation is
    deterministic."""
    rng = np.random.default_rng(0)
    prompts = [list(map(int, rng.integers(1, vocab,
                                          int(rng.integers(1, 21)))))
               for _ in range(N_REQ)]
    budgets = [int(rng.integers(2, 9)) for _ in range(N_REQ)]
    cancels = set(range(0, N_REQ, CANCEL_EVERY))
    for i in cancels:
        budgets[i] = 30
    return prompts, budgets, cancels


def test_http_e2e_32_streams_parity_cancel_metrics(mv):
    cfg, model, variables = mv
    prompts, budgets, cancels = _workload(cfg.vocab_size)

    async def main():
        eng = DecodeEngine(model, variables, n_slots=4, temperature=0.0,
                           min_bucket=8)
        sched = Scheduler(eng, max_queue=64)
        app = ServeApp(sched, port=0)
        await sched.start()
        await app.start()

        results = await asyncio.gather(*(
            sse_client(app.port, p, b,
                       cancel_after=CANCEL_AFTER if i in cancels else None)
            for i, (p, b) in enumerate(zip(prompts, budgets))))

        # disconnect-driven cancels land asynchronously; drain them
        deadline = asyncio.get_running_loop().time() + 60
        while eng.n_live and asyncio.get_running_loop().time() < deadline:
            await asyncio.sleep(0.05)
        health = await http_get(app.port, "/healthz")
        metrics = await http_get(app.port, "/metrics")
        await app.stop()
        await sched.stop()
        return eng, sched, results, health, metrics

    eng, sched, results, (h_status, h_body), (m_status, m_body) = \
        run_async(main())

    # --- bit-identical to the offline engine (greedy), same budgets ---
    ref_eng = DecodeEngine(model, variables, n_slots=4, temperature=0.0,
                           min_bucket=8)
    refs = ref_eng.run(prompts, budgets)
    for i, ((tokens, done), p, ref) in enumerate(zip(results, prompts,
                                                     refs)):
        gen_ref = ref[len(p):]
        if i in cancels:
            assert tokens == gen_ref[:CANCEL_AFTER], \
                f"cancelled stream {i} diverged before the disconnect"
        else:
            assert tokens == gen_ref, f"stream {i} diverged from offline"
            assert done["done"] and done["reason"] == "budget"

    # --- cancellation freed every disconnected slot ---
    assert eng.n_live == 0
    assert eng.retire_counts["cancelled"] == len(cancels)
    assert sched.metrics.counters["cancelled"] == len(cancels)

    # --- health + metrics surface ---
    assert h_status == 200 and json.loads(h_body)["ok"]
    assert json.loads(h_body)["live_slots"] == 0
    assert m_status == 200
    lines = dict(
        l.rsplit(" ", 1) for l in m_body.splitlines()
        if l and not l.startswith("#"))
    assert float(lines["serve_ttft_seconds_count"]) == N_REQ
    assert float(lines["serve_itl_seconds_count"]) > 0
    assert float(lines['serve_requests_total{event="admitted"}']) == N_REQ
    assert float(lines['serve_requests_total{event="shed"}']) == 0
    # zero starvation: every request reached a slot, worst queue wait
    # bounded well inside the test budget
    assert sched.metrics.queue_wait.count == N_REQ
    assert sched.metrics.queue_wait.max < 120


def test_http_queue_full_is_429(mv):
    _, model, variables = mv

    async def main():
        eng = DecodeEngine(model, variables, n_slots=1, temperature=0.0,
                           min_bucket=8)
        sched = Scheduler(eng, max_queue=1)
        app = ServeApp(sched, port=0)
        await sched.start()
        await app.start()

        # stream A occupies the slot; read its first token so it is live
        ra, wa = await http_post(app.port, "/v1/completions",
                                 {"prompt": [1, 2, 3], "max_tokens": 40})
        await ra.readline()                        # status
        while (await ra.readline()).strip():       # headers
            pass
        await ra.readline()                        # first SSE event

        # B fills the queue (fire and background-drain)
        b_task = asyncio.ensure_future(
            sse_client(app.port, [4, 5], 2))
        while sched.queue_depth < 1:
            await asyncio.sleep(0.01)

        # C must be shed with an HTTP 429, immediately
        rc, wc = await http_post(app.port, "/v1/completions",
                                 {"prompt": [6], "max_tokens": 2})
        status = int((await rc.readline()).split(b" ")[1])
        body = (await rc.read()).split(b"\r\n\r\n")[-1]
        wc.close()

        wa.close()                                 # disconnect A -> cancel
        await b_task
        await app.stop()
        await sched.stop()
        return sched, status, json.loads(body)

    sched, status, body = run_async(main())
    assert status == 429
    assert body["cause"] == "queue_full"
    assert sched.metrics.shed_counts == {"queue_full": 1}


def test_http_bad_requests(mv):
    _, model, variables = mv

    async def main():
        eng = DecodeEngine(model, variables, n_slots=1, temperature=0.0,
                           min_bucket=8)
        sched = Scheduler(eng, max_queue=4)
        app = ServeApp(sched, port=0, encoder=None)
        await sched.start()
        await app.start()
        out = {}
        out["nf"], _ = await http_get(app.port, "/nope")
        r, w = await http_post(app.port, "/v1/completions",
                               {"prompt": "text without a tokenizer"})
        out["text"] = int((await r.readline()).split(b" ")[1])
        w.close()
        r, w = await http_post(app.port, "/v1/completions",
                               {"prompt": []})
        out["empty"] = int((await r.readline()).split(b" ")[1])
        w.close()
        r, w = await http_post(app.port, "/v1/completions",
                               {"prompt": [1], "max_tokens": 0})
        out["zero"] = int((await r.readline()).split(b" ")[1])
        w.close()
        # non-streaming mode still works
        r, w = await http_post(app.port, "/v1/completions",
                               {"prompt": [1, 2], "max_tokens": 3,
                                "stream": False})
        status = int((await r.readline()).split(b" ")[1])
        data = await r.read()
        w.close()
        out["json"] = (status, json.loads(data.split(b"\r\n\r\n")[-1]))
        await app.stop()
        await sched.stop()
        return out

    out = run_async(main())
    assert out["nf"] == 404
    assert out["text"] == 400
    assert out["empty"] == 400
    assert out["zero"] == 400
    status, body = out["json"]
    assert status == 200
    assert body["reason"] == "budget" and len(body["tokens"]) == 3


def test_http_stalled_client_gets_408_and_frees_connection(mv):
    """A slowloris client that never finishes its request head (or body)
    must not hold a connection slot indefinitely: the per-connection
    read timeout answers 408 and closes."""
    _, model, variables = mv

    async def main():
        eng = DecodeEngine(model, variables, n_slots=1, temperature=0.0,
                           min_bucket=8)
        sched = Scheduler(eng, max_queue=4)
        app = ServeApp(sched, port=0, request_timeout_s=0.2)
        await sched.start()
        await app.start()

        # stalled HEAD: open, write half a request line, go silent
        r1, w1 = await asyncio.open_connection("127.0.0.1", app.port)
        w1.write(b"GET /healthz HT")
        await w1.drain()
        head1 = await asyncio.wait_for(r1.read(), 10)
        w1.close()

        # stalled BODY: full head promising bytes that never come
        r2, w2 = await asyncio.open_connection("127.0.0.1", app.port)
        w2.write(b"POST /v1/completions HTTP/1.1\r\nHost: t\r\n"
                 b"Content-Length: 64\r\n\r\n{\"pro")
        await w2.drain()
        head2 = await asyncio.wait_for(r2.read(), 10)
        w2.close()

        # the server still serves a well-behaved client afterwards
        status, _ = await http_get(app.port, "/healthz")
        await app.stop()
        await sched.stop()
        return head1, head2, status

    head1, head2, status = run_async(main(), timeout=60)
    assert head1.startswith(b"HTTP/1.1 408")
    assert head2.startswith(b"HTTP/1.1 408")
    assert status == 200


def test_healthz_is_readiness_503_on_drain_and_engine_death(mv):
    """healthz is a readiness probe: 200 only while admitting. Draining
    flips it 503 (with drained-state detail once quiesced); a dead step
    loop flips it 503 with the failure. The router tier health-gates on
    exactly this."""
    _, model, variables = mv

    async def main():
        eng = DecodeEngine(model, variables, n_slots=1, temperature=0.0,
                           min_bucket=8)
        sched = Scheduler(eng, max_queue=4)
        app = ServeApp(sched, port=0)
        await sched.start()
        await app.start()
        s_ok, b_ok = await http_get(app.port, "/healthz")

        # drain via the admin endpoint -> 503 draining, then drained
        r, w = await http_post(app.port, "/admin/drain", {})
        drain_status = int((await r.readline()).split(b" ")[1])
        w.close()
        s_drain, b_drain = await http_get(app.port, "/healthz")
        deadline = asyncio.get_running_loop().time() + 10
        while (not sched.drained
               and asyncio.get_running_loop().time() < deadline):
            await asyncio.sleep(0.02)
        s_drained, b_drained = await http_get(app.port, "/healthz")

        # engine death on a fresh stack -> 503 failed
        eng2 = DecodeEngine(model, variables, n_slots=1, temperature=0.0,
                            min_bucket=8)
        eng2.step = lambda: (_ for _ in ()).throw(RuntimeError("boom"))
        sched2 = Scheduler(eng2, max_queue=4)
        app2 = ServeApp(sched2, port=0)
        await sched2.start()
        await app2.start()
        h = sched2.submit([1, 2, 3], 4)
        try:
            await h.result()
        except Exception:
            pass
        s_dead, b_dead = await http_get(app2.port, "/healthz")

        await app.stop()
        await sched.stop()
        await app2.stop()
        await sched2.stop()
        return (s_ok, json.loads(b_ok), drain_status, s_drain,
                json.loads(b_drain), s_drained, json.loads(b_drained),
                s_dead, json.loads(b_dead))

    (s_ok, b_ok, drain_status, s_drain, b_drain, s_drained, b_drained,
     s_dead, b_dead) = run_async(main(), timeout=120)
    assert s_ok == 200 and b_ok["ok"] and not b_ok["draining"]
    assert drain_status == 200
    assert s_drain == 503 and b_drain["draining"] and not b_drain["ok"]
    assert s_drained == 503 and b_drained["drained"]
    assert s_dead == 503 and not b_dead["ok"]
    assert "boom" in b_dead["failed"]
