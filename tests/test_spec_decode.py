"""Speculative decoding (ISSUE 16): host n-gram drafter units, engine-vs-
`generate` bit-parity with SPEC_DECODE on across attention flavors /
int8 KV / prefix reuse / preemption, acceptance-length edge cases
(0 accepted, all-K accepted, EOS inside the accepted span), the one-
spec-trace pin, and scheduler stream ordering under multi-token
emission. Greedy verification is exact, so every assertion here is
bit-equality — never a tolerance."""

import asyncio

import jax
import jax.numpy as jnp
import pytest

from distributed_pytorch_tpu.config import LLMConfig
from distributed_pytorch_tpu.engine import DecodeEngine
from distributed_pytorch_tpu.engine import decode as decode_mod
from distributed_pytorch_tpu.engine.decode import (
    enumerate_trace_signatures, ngram_propose)
from distributed_pytorch_tpu.models.generate import generate
from distributed_pytorch_tpu.models.gpt import LLM
from distributed_pytorch_tpu.serve.scheduler import Scheduler


def tiny_cfg(**kw):
    base = dict(vocab_size=97, block_size=64, n_embd=48, n_head=4,
                n_kv_heads=2, attn="gqa", n_layer=2, up_dim=64,
                non_linearity="swiglu", pos_emb="rope", dropout=0.0,
                q_latent_dim=16, kv_latent_dim=16, rope_head_dim=8)
    base.update(kw)
    return LLMConfig(**base)


def build(cfg, seed=0, attn_impl="naive"):
    model = LLM(cfg, attn_impl=attn_impl)
    rng = jax.random.PRNGKey(seed)
    x = jnp.zeros((1, cfg.block_size), jnp.int32)
    variables = model.init({"params": rng, "dropout": rng}, x, x)
    return model, {k: v for k, v in variables.items()}


def spec_engine(model, variables, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("min_bucket", 8)
    kw.setdefault("spec_k", 4)
    return DecodeEngine(model, variables, temperature=0.0,
                        spec_decode=True, **kw)


def oracle(model, variables, prompt, n):
    return generate(model, variables, jnp.asarray(prompt, jnp.int32)[None],
                    n, temperature=0.0)[0].tolist()


# repetitive suffixes (n-gram hits) mixed with structureless prompts
PROMPTS = [[1, 2, 3, 1, 2, 3, 1, 2], [5, 6, 7, 8, 9, 10, 11],
           [20] * 17, [4, 9, 4, 9, 4], [9]]


# ----------------------------------------------------------------------
# drafter units
# ----------------------------------------------------------------------

def test_ngram_hit_proposes_continuation():
    # suffix [1,2] last occurred earlier at index 0, followed by 3,4,5
    assert ngram_propose([1, 2, 3, 4, 5, 1, 2], 3) == [3, 4, 5]


def test_ngram_prefers_longest_match():
    # suffix [7,1,2] (n=3) matches at index 0 -> 9; the shorter [1,2]
    # match elsewhere must not win
    toks = [7, 1, 2, 9, 5, 1, 2, 8, 7, 1, 2]
    assert ngram_propose(toks, 2) == [9, 5]


def test_ngram_takes_most_recent_occurrence():
    # suffix [1,2] occurs at 0 (-> 5) and at 3 (-> 6): most recent wins
    assert ngram_propose([1, 2, 5, 1, 2, 6, 1, 2], 1) == [6]


def test_ngram_miss_and_degenerate_inputs():
    assert ngram_propose([1, 2, 3, 4, 5], 4) == []     # no repeat
    assert ngram_propose([1, 2], 4) == []              # too short
    assert ngram_propose([1, 2, 3, 1, 2], 0) == []     # k=0
    assert ngram_propose([], 4) == []


def test_ngram_clamps_to_k():
    toks = [1, 2, 3, 4, 5, 6, 7, 1, 2]
    assert ngram_propose(toks, 2) == [3, 4]
    assert ngram_propose(toks, 100) == [3, 4, 5, 6, 7, 1, 2]


# ----------------------------------------------------------------------
# engine-vs-generate bit parity, spec on
# ----------------------------------------------------------------------

@pytest.mark.parametrize("kw", [
    dict(attn="gqa", n_kv_heads=2, pos_emb="rope"),
    dict(attn="mla", pos_emb="rope"),
    dict(attn="mha", pos_emb="learn"),
], ids=["gqa-rope", "mla-rope", "mha-learn"])
def test_spec_matches_generate(kw):
    """Ragged continuous batching with speculation on is token-identical
    to decoding each prompt alone — accepted prefixes, correction tokens,
    rejected-tail garbage rows and per-slot strides must all be
    invisible."""
    cfg = tiny_cfg(**kw)
    model, variables = build(cfg)
    eng = spec_engine(model, variables)
    outs = eng.run(PROMPTS, max_new_tokens=8)
    for p, o in zip(PROMPTS, outs):
        assert o == oracle(model, variables, p, 8), \
            f"spec engine diverged from generate for prompt {p}"
    assert eng.spec_drafted_tokens > 0, "drafter never fired"


def test_spec_matches_spec_off_engine_int8_kv():
    """int8 KV: quantize/dequantize must round-trip identically through
    the K+1-row verify writes — pinned engine-vs-engine (both int8), and
    both against the bf16 spec-off run being unnecessary: int8 changes
    logits, so the invariant is spec-on == spec-off at the SAME dtype."""
    cfg = tiny_cfg()
    model, variables = build(cfg)
    on = spec_engine(model, variables, cache_dtype="int8")
    off = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                       min_bucket=8, cache_dtype="int8", spec_decode=False)
    outs_on = on.run(PROMPTS, max_new_tokens=8)
    outs_off = off.run(PROMPTS, max_new_tokens=8)
    assert outs_on == outs_off
    assert on.spec_drafted_tokens > 0


def test_spec_with_prefix_reuse():
    """Prompts resolving to cached prefix blocks still verify/accept
    correctly (the verify window starts mid-sequence over shared
    blocks)."""
    cfg = tiny_cfg()
    model, variables = build(cfg)
    eng = spec_engine(model, variables, block_size=8)
    shared = [3, 1, 4, 1, 5, 9, 2, 6] * 3            # 3 full 8-blocks
    prompts = [shared + [30], shared + [40, 41]]
    outs = eng.run(prompts, max_new_tokens=8)
    out2 = eng.run(prompts, max_new_tokens=8)        # second pass: hits
    for p, o in zip(prompts, outs):
        assert o == oracle(model, variables, p, 8)
    assert outs == out2
    assert eng.prefix_hit_tokens > 0


def test_spec_under_preemption():
    """A tight pool preempts mid-decode; the resumed sequence (requeued
    with its tokens as the new prompt) must still land bit-exact, with
    speculation active on both sides of the preemption."""
    cfg = tiny_cfg()
    model, variables = build(cfg)
    eng = spec_engine(model, variables, n_slots=2, block_size=8,
                      n_blocks=9)                    # tight: forces preempt
    prompts = [[1, 2, 3, 1, 2, 3, 1], [5] * 9]
    outs = eng.run(prompts, max_new_tokens=40)
    for p, o in zip(prompts, outs):
        assert o == oracle(model, variables, p, 40)
    assert eng.retire_counts["preempted"] > 0, \
        "pool never got tight — test is vacuous"
    assert eng.block_pool.n_referenced == 0          # nothing leaked


def test_spec_budget_boundary_exact():
    """The draft clamp `n <= max_new - n_new - 1` makes overshooting the
    budget impossible: output length is EXACTLY prompt + budget even when
    every draft would be accepted."""
    cfg = tiny_cfg()
    model, variables = build(cfg)
    eng = spec_engine(model, variables, spec_k=6)
    for budget in (1, 2, 5):
        (out,) = eng.run([[7] * 12], max_new_tokens=budget)
        assert out == oracle(model, variables, [7] * 12, budget)
        assert len(out) == 12 + budget


def test_spec_max_len_boundary():
    """Near the cache end speculation falls back to the plain step (the
    rope-slice clamp hazard) and the engine still retires at exactly
    max_len + 1 tokens, like the spec-off contract."""
    cfg = tiny_cfg(block_size=16)
    model, variables = build(cfg)
    eng = spec_engine(model, variables, n_slots=1)
    off = DecodeEngine(model, variables, n_slots=1, temperature=0.0,
                       min_bucket=8, spec_decode=False)
    (out,) = eng.run([[1, 2, 1, 2, 1]], max_new_tokens=1000)
    assert len(out) == cfg.block_size + 1
    assert eng.retire_counts["cache_full"] == 1
    assert out == off.run([[1, 2, 1, 2, 1]], max_new_tokens=1000)[0]


# ----------------------------------------------------------------------
# acceptance-length edges (deterministic via a controlled drafter)
# ----------------------------------------------------------------------

def _run_with_drafter(model, variables, prompt, n, drafter, monkeypatch,
                      **kw):
    monkeypatch.setattr(decode_mod, "ngram_propose", drafter)
    eng = spec_engine(model, variables, **kw)
    (out,) = eng.run([prompt], max_new_tokens=n)
    return eng, out


def test_zero_accepted_still_exact(monkeypatch):
    """A drafter that is ALWAYS wrong (proposes ref+1 at every position)
    accepts nothing — every spec step emits exactly the plain step's one
    correction token and the output stays bit-identical."""
    cfg = tiny_cfg()
    model, variables = build(cfg)
    prompt, n = [1, 2, 3, 1, 2, 3], 8
    ref = oracle(model, variables, prompt, n)

    def wrong(tokens, k, **_kw):
        i = len(tokens)
        return [(ref[i + j] + 1) % cfg.vocab_size
                for j in range(min(k, len(ref) - i))]

    eng, out = _run_with_drafter(model, variables, prompt, n, wrong,
                                 monkeypatch)
    assert out == ref
    assert eng.spec_drafted_tokens > 0
    assert eng.spec_accepted_tokens == 0


def test_all_k_accepted(monkeypatch):
    """An oracle drafter (proposes the exact greedy continuation) gets
    every valid draft token accepted: accepted == drafted, and each spec
    step advances multiple tokens."""
    cfg = tiny_cfg()
    model, variables = build(cfg)
    prompt, n = [1, 2, 3, 1, 2, 3], 9
    ref = oracle(model, variables, prompt, n)

    def perfect(tokens, k, **_kw):
        i = len(tokens)
        return ref[i:i + k]

    eng, out = _run_with_drafter(model, variables, prompt, n, perfect,
                                 monkeypatch, spec_k=3)
    assert out == ref
    assert eng.spec_drafted_tokens > 0
    assert eng.spec_accepted_tokens == eng.spec_drafted_tokens
    # 9 tokens in ceil(9/4)=3 spec steps (3 accepted + 1 correction each)
    assert eng.tokens_per_step > 1.0


def test_eos_inside_accepted_span(monkeypatch):
    """EOS landing INSIDE an accepted draft prefix truncates the emission
    at the EOS token: nothing past it is streamed, the slot retires with
    reason 'eos', and tokens == the oracle cut at its EOS."""
    cfg = tiny_cfg()
    model, variables = build(cfg)
    prompt = [1, 2, 3, 1, 2, 3]
    ref = oracle(model, variables, prompt, 10)
    eos = ref[len(prompt) + 2]          # third generated token

    def perfect(tokens, k, **_kw):
        i = len(tokens)
        return ref[i:i + k]

    monkeypatch.setattr(decode_mod, "ngram_propose", perfect)
    eng = spec_engine(model, variables, eos_id=eos, spec_k=6)
    (out,) = eng.run([prompt], max_new_tokens=10)
    stop = ref.index(eos, len(prompt))
    assert out == ref[:stop + 1]
    assert out[-1] == eos


# ----------------------------------------------------------------------
# trace discipline
# ----------------------------------------------------------------------

def test_spec_one_trace_across_mixes():
    """Every draft mix — hits, misses, ragged lengths, retiring slots —
    shares ONE compiled spec_step program; the plain step and the admit
    buckets keep their own budgets; nothing exceeds a TraceGuard."""
    cfg = tiny_cfg()
    model, variables = build(cfg)
    eng = spec_engine(model, variables, n_slots=3)
    eng.run(PROMPTS, max_new_tokens=7)
    eng.run([[2, 4, 2, 4, 2], [8, 8, 8, 8, 8, 8, 8, 8, 8]],
            max_new_tokens=5)
    assert eng.spec_step_traces == 1
    assert eng.step_traces <= 1
    assert all(g.excess == 0 for g in eng.trace_guards.values())


def test_enumerate_trace_signatures_spec_family():
    sig = enumerate_trace_signatures(min_bucket=16, block_size=16,
                                     max_len=64, prefill_chunk=0,
                                     spec_k=4)
    assert sig["spec_step"] == 1
    off = enumerate_trace_signatures(min_bucket=16, block_size=16,
                                     max_len=64, prefill_chunk=0)
    assert off["spec_step"] == 0
    chunked = enumerate_trace_signatures(min_bucket=16, block_size=16,
                                         max_len=64, prefill_chunk=32,
                                         spec_k=4)
    assert chunked["spec_step"] == 1 and chunked["fused_step"] == 1


def test_spec_knob_gating():
    """SPEC_DECODE resolution: off at temperature > 0 (verify is greedy-
    only), off at spec_k=0, and the explicit constructor arg wins."""
    cfg = tiny_cfg()
    model, variables = build(cfg)
    hot = DecodeEngine(model, variables, n_slots=2, temperature=0.7,
                       min_bucket=8, spec_decode=True, spec_k=4)
    assert not hot.spec_decode
    k0 = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                      min_bucket=8, spec_decode=True, spec_k=0)
    assert not k0.spec_decode
    off = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                       min_bucket=8, spec_decode=False)
    assert not off.spec_decode
    out_on = spec_engine(model, variables).run(PROMPTS[:2], 6)
    out_off = off.run(PROMPTS[:2], 6)
    assert out_on == out_off


def test_chunked_prefill_with_spec():
    """A chunked engine speculates on chunk-free steps only; parity and
    both trace pins hold with the fused and spec programs coexisting."""
    cfg = tiny_cfg()
    model, variables = build(cfg)
    eng = spec_engine(model, variables, prefill_chunk=16)
    prompts = [[1, 2, 3, 1, 2, 3], list(range(1, 40)), [7] * 10]
    outs = eng.run(prompts, max_new_tokens=8)
    for p, o in zip(prompts, outs):
        assert o == oracle(model, variables, p, 8)
    assert eng.fused_step_traces == 1
    assert eng.spec_step_traces == 1


# ----------------------------------------------------------------------
# scheduler stream ordering under multi-token emission
# ----------------------------------------------------------------------

def test_scheduler_streams_spec_tokens_in_order():
    """Multi-token StepResult lists fan into the per-request streams in
    generation order: each handle's token sequence equals the offline
    oracle's continuation, TTFT fires once, and the spec counters land
    on the scheduler's metrics registry."""
    cfg = tiny_cfg()
    model, variables = build(cfg)
    prompts = [[1, 2, 3, 1, 2, 3, 1, 2], [4, 9, 4, 9, 4, 9], [20] * 10]
    budget = 8

    async def main():
        eng = spec_engine(model, variables, n_slots=2)
        sched = Scheduler(eng, max_queue=8)
        await sched.start()
        handles = [sched.submit(p, budget) for p in prompts]
        await asyncio.gather(*(h.result() for h in handles))
        await sched.stop()
        return eng, sched, handles

    eng, sched, handles = asyncio.run(asyncio.wait_for(main(), 300))
    for p, h in zip(prompts, handles):
        ref = oracle(model, variables, p, budget)
        assert h.tokens == ref[len(p):], \
            "streamed tokens out of order or diverged"
        assert h.retired.reason == "budget"
    m = sched.metrics.counters
    assert m["spec_drafted_tokens"] == eng.spec_drafted_tokens > 0
    assert m["spec_accepted_tokens"] == eng.spec_accepted_tokens
    assert m["tokens_out"] == len(prompts) * budget
    # gauges registered and live
    snap = sched.metrics.snapshot()["gauges"]
    assert snap["serve_spec_accepted_token_rate"] == pytest.approx(
        eng.accepted_token_rate, abs=1e-6)
    assert snap["serve_engine_tokens_per_step"] > 0
