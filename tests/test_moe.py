"""DeepSeekMoE semantics tests (reference single-gpu/model.py:409-506):
dense-dispatch equivalence to a per-expert loop, aux-free bias updates,
classic aux loss, shared-expert bypass, active-param accounting."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.config import LLMConfig
from distributed_pytorch_tpu.models import LLM
from distributed_pytorch_tpu.models.mlp import MoE, mlp_apply
from distributed_pytorch_tpu.models.gpt import count_params

VOCAB = 64


def moe_config(**kw):
    base = dict(vocab_size=VOCAB, block_size=32, n_embd=32, n_head=4,
                n_kv_heads=2, n_layer=2, up_dim=48, pos_emb="rope",
                attn="gqa", non_linearity="swiglu", dropout=0.0,
                moe=True, n_exp=6, n_shared=2, n_act=4,
                coeff=0.01, aux_free=True, alpha=1e-4, gamma=1e-2)
    base.update(kw)
    return LLMConfig(**base)


def make_moe(cfg, B=2, T=8, seed=0):
    moe = MoE(cfg)
    x = jax.random.normal(jax.random.PRNGKey(seed), (B, T, cfg.n_embd))
    variables = moe.init(jax.random.PRNGKey(1), x)
    return moe, variables, x


@pytest.mark.parametrize("aux_free", [True, False])
def test_moe_forward_and_aux(aux_free):
    cfg = moe_config(aux_free=aux_free)
    moe, variables, x = make_moe(cfg)
    (y, aux), _ = moe.apply(variables, x, mutable=["moe_state"])
    assert y.shape == x.shape
    assert jnp.isfinite(y).all()
    assert float(aux) >= 0.0  # pi*fi >= 0


def test_moe_dense_dispatch_matches_loop():
    """The combine-matrix einsum must equal an explicit python loop over
    routed experts (the reference's dispatch semantics, model.py:489-506)."""
    cfg = moe_config(aux_free=False)
    moe, variables, x = make_moe(cfg)
    (y, _), _ = moe.apply(variables, x, mutable=["moe_state"])

    p = variables["params"]
    xf = np.asarray(x.reshape(-1, cfg.n_embd))
    fc = np.asarray(p["experts_fc"])
    pj = np.asarray(p["experts_proj"])
    gate = np.asarray(p["gate"])
    n_sh, n_rt, k = cfg.n_shared, cfg.n_routed, cfg.n_act_routed

    def apply_mlp(x_, wf, wp):
        return np.asarray(mlp_apply(jnp.asarray(x_), jnp.asarray(wf),
                                    jnp.asarray(wp), cfg.non_linearity))

    out = np.zeros_like(xf)
    for e in range(n_sh):  # shared experts: all tokens
        out += apply_mlp(xf, fc[e], pj[e])
    logits = xf @ gate
    topk = np.argsort(-logits, axis=1)[:, :k]
    for t in range(xf.shape[0]):
        sel = logits[t, topk[t]]
        gates = np.exp(sel - sel.max())
        gates /= gates.sum()
        for slot, e in enumerate(topk[t]):
            out[t] += gates[slot] * apply_mlp(xf[t:t + 1], fc[n_sh + e],
                                              pj[n_sh + e])[0]
    np.testing.assert_allclose(np.asarray(y).reshape(-1, cfg.n_embd), out,
                               atol=2e-5)


def test_aux_free_bias_updates_toward_uniform():
    cfg = moe_config(aux_free=True, gamma=0.1)
    moe, variables, x = make_moe(cfg)
    bias0 = variables["moe_state"]["expert_bias"]
    assert jnp.all(bias0 == 0)
    # training mode (deterministic=False) mutates the bias...
    _, mut = moe.apply(variables, x, deterministic=False,
                       mutable=["moe_state"])
    bias1 = mut["moe_state"]["expert_bias"]
    assert not jnp.allclose(bias1, 0)
    # bias += gamma*(1/n_routed - fi) (reference model.py:466-470); since
    # sum_e fi = k (each token routes to k experts), deltas sum to gamma*(1-k)
    assert jnp.allclose(bias1.sum(), cfg.gamma * (1 - cfg.n_act_routed),
                        atol=1e-6)
    # ...eval mode does not
    _, mut_eval = moe.apply(variables, x, deterministic=True,
                            mutable=["moe_state"])
    assert jnp.allclose(mut_eval["moe_state"]["expert_bias"], 0)


def test_aux_free_selection_respects_bias():
    """A large positive bias on one expert must pull tokens to it even when
    its logits are unremarkable (selection uses biased logits, gates use
    original — reference model.py:451-458)."""
    cfg = moe_config(aux_free=True)
    moe, variables, x = make_moe(cfg)
    big = variables["moe_state"]["expert_bias"].at[0].set(1e4)
    variables_biased = {"params": variables["params"],
                        "moe_state": {"expert_bias": big}}
    (y_b, _), _ = moe.apply(variables_biased, x, mutable=["moe_state"])
    (y_0, _), _ = moe.apply(variables, x, mutable=["moe_state"])
    # forcing expert 0 into every token's top-k changes the output
    assert not jnp.allclose(y_b, y_0)


def test_scatter_matches_dense_at_generous_capacity():
    """With capacity >= worst-case expert load, the sort/scatter dispatch
    must reproduce the dense oracle (same params, same input) — only
    summation order may differ."""
    cfg_d = moe_config(aux_free=False, moe_impl="dense")
    cfg_s = moe_config(aux_free=False, moe_impl="scatter",
                       capacity_factor=float(cfg_d.n_routed))  # cap >= N*k
    moe_d, variables, x = make_moe(cfg_d, B=2, T=16)
    moe_s = MoE(cfg_s)
    (y_d, aux_d), _ = moe_d.apply(variables, x, mutable=["moe_state"])
    (y_s, aux_s), _ = moe_s.apply(variables, x, mutable=["moe_state"])
    np.testing.assert_allclose(np.asarray(y_s), np.asarray(y_d), atol=2e-5)
    np.testing.assert_allclose(float(aux_s), float(aux_d), rtol=1e-6)


def test_scatter_capacity_drop():
    """With capacity 0 slots... the minimum (k), overloaded experts drop
    tokens: output differs from dense but stays finite, and a dropped
    token's routed contribution is partially/fully missing — never NaN."""
    cfg_s = moe_config(aux_free=False, moe_impl="scatter",
                       capacity_factor=1e-9)  # floor: capacity = k
    moe_s, variables, x = make_moe(cfg_s, B=2, T=16)
    cfg_d = moe_config(aux_free=False, moe_impl="dense")
    (y_d, _), _ = MoE(cfg_d).apply(variables, x, mutable=["moe_state"])
    (y_s, _), _ = moe_s.apply(variables, x, mutable=["moe_state"])
    assert jnp.isfinite(y_s).all()
    assert not np.allclose(np.asarray(y_s), np.asarray(y_d))


def test_scatter_position_priority_exact():
    """Hand-checkable drop semantics: every token routes to the same single
    expert; with capacity C only the first C tokens get its contribution,
    the rest exactly the shared-experts output."""
    cfg = moe_config(aux_free=True, n_exp=3, n_shared=1, n_act=2,
                     moe_impl="scatter", capacity_factor=1e-9)  # capacity=1
    moe, variables, x = make_moe(cfg, B=1, T=8)
    # huge bias forces expert 0 into every token's top-1 (selection uses
    # biased logits)
    big = variables["moe_state"]["expert_bias"].at[0].set(1e4)
    variables = {"params": variables["params"],
                 "moe_state": {"expert_bias": big}}
    (y, _), _ = moe.apply(variables, x, mutable=["moe_state"])

    p = variables["params"]
    xf = x.reshape(-1, cfg.n_embd)
    shared = mlp_apply(xf, p["experts_fc"][0], p["experts_proj"][0],
                       cfg.non_linearity)
    y = np.asarray(y).reshape(-1, cfg.n_embd)
    # token 0 won the single slot: shared + gated expert-0 output
    assert not np.allclose(y[0], np.asarray(shared)[0], atol=1e-6)
    # tokens 1..7 dropped: shared output only (top-1 gate softmax == 1, so
    # the dropped contribution is the whole routed path)
    np.testing.assert_allclose(y[1:], np.asarray(shared)[1:], atol=2e-5)


def test_scatter_grads_flow():
    cfg = moe_config(aux_free=False, moe_impl="scatter", capacity_factor=2.0)
    model = LLM(cfg)
    idx = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, VOCAB)
    tgt = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, VOCAB)
    variables = model.init(jax.random.PRNGKey(0), idx, tgt)

    def loss_fn(params):
        (_, loss, _), _ = model.apply(
            {"params": params, "moe_state": variables.get("moe_state", {})},
            idx, tgt, mutable=["moe_state"])
        return loss

    grads = jax.grad(loss_fn)(variables["params"])
    assert float(jnp.abs(grads["block_0"]["moe"]["gate"]).max()) > 0
    assert float(jnp.abs(grads["block_0"]["moe"]["experts_fc"]).max()) > 0
    for leaf in jax.tree_util.tree_leaves(grads):
        assert jnp.isfinite(leaf).all()


def test_moe_in_full_model_and_active_params():
    cfg = moe_config()
    model = LLM(cfg)
    idx = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, VOCAB)
    tgt = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, VOCAB)
    variables = model.init(jax.random.PRNGKey(0), idx, tgt)
    (logits, loss, _), mut = model.apply(variables, idx, tgt,
                                         mutable=["moe_state"])
    assert jnp.isfinite(loss)
    total, active = count_params(variables["params"], cfg)
    assert active < total  # 2 of 4 routed experts inactive
    # per-expert MLP params: fc (C,2*up) + proj (up,C)
    per_expert = (cfg.n_embd * 2 * cfg.up_dim) + (cfg.up_dim * cfg.n_embd)
    expected_inactive = cfg.n_layer * (cfg.n_routed - cfg.n_act_routed) * per_expert
    assert total - active == expected_inactive


def test_moe_grads_flow_to_gate_and_experts():
    cfg = moe_config(aux_free=False)
    model = LLM(cfg)
    idx = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, VOCAB)
    tgt = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, VOCAB)
    variables = model.init(jax.random.PRNGKey(0), idx, tgt)

    def loss_fn(params):
        (_, loss, _), _ = model.apply(
            {"params": params, "moe_state": variables.get("moe_state", {})},
            idx, tgt, mutable=["moe_state"])
        return loss

    grads = jax.grad(loss_fn)(variables["params"])
    g_gate = grads["block_0"]["moe"]["gate"]
    g_fc = grads["block_0"]["moe"]["experts_fc"]
    assert float(jnp.abs(g_gate).max()) > 0
    assert float(jnp.abs(g_fc).max()) > 0


def test_scatter_dispatch_buffers_sharded_over_data():
    """Round-3 VERDICT #4: the (E, capacity, C) dispatch buffers must shard
    their capacity axis over 'data' (and expert axis over 'expert'), so
    per-device dispatch memory is independent of dp size. Verified via
    compile-time sharding inspection on a dp=4 x ep=2 CPU mesh."""
    from distributed_pytorch_tpu.models.mlp import _expert_constraint
    from distributed_pytorch_tpu.parallel import context
    from distributed_pytorch_tpu.parallel.mesh import build_mesh, resolve_plan

    mesh = build_mesh(resolve_plan("ep", 8, ep_size=2))  # data=4, expert=2

    # return the constrained array itself: its committed sharding IS the
    # constraint GSPMD honored (jax.debug.inspect_array_sharding's compile-
    # time callback crashes with an INTERNAL error on jax 0.4.x, so the
    # assertion moved from compile-time inspection to the result array)
    with context.use_mesh(mesh):
        # E=4 (divisible by ep=2), capacity=8 (divisible by dp=4), C=16
        out = jax.jit(_expert_constraint)(jnp.zeros((4, 8, 16)))
    spec = out.sharding.spec
    spec = tuple(spec) + (None,) * (3 - len(tuple(spec)))
    assert spec[0] == "expert", spec
    assert spec[1] == "data", spec
    shard = out.addressable_shards[0].data
    assert shard.shape == (2, 2, 16), shard.shape  # E/ep x cap/dp x C


def test_scatter_capacity_rounds_to_data_axis():
    """The chosen capacity is rounded up to a multiple of dp so the
    capacity axis is always shardable; rounding only adds empty slots
    (parity with the dense oracle is untouched — covered by the fsdp_x_ep
    trajectory test in test_parallel.py)."""
    from distributed_pytorch_tpu.parallel import context
    from distributed_pytorch_tpu.parallel.mesh import build_mesh, resolve_plan

    cfg_d = moe_config(aux_free=False, moe_impl="dense")
    cfg_s = moe_config(aux_free=False, moe_impl="scatter",
                       capacity_factor=float(cfg_d.n_routed))
    moe_d, variables, x = make_moe(cfg_d, B=2, T=16)
    (y_d, _), _ = moe_d.apply(variables, x, mutable=["moe_state"])

    mesh = build_mesh(resolve_plan("dp", 8))  # data=8
    with context.use_mesh(mesh):
        (y_s, _), _ = MoE(cfg_s).apply(variables, x, mutable=["moe_state"])
    np.testing.assert_allclose(np.asarray(y_s), np.asarray(y_d), atol=2e-5)
