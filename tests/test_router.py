"""Fault-tolerant router tier (serve/router.py) over in-process replica
servers: least-loaded dispatch, health gating with backoff rejoin,
draining restarts, explicit shed on retry exhaustion, and the core
failover-idempotency property — a replica killed mid-stream must leave
the client-observed token sequence gapless, duplicate-free, and
bit-identical to offline engine greedy.

Replicas here are real ServeApp/Scheduler/DecodeEngine stacks on
localhost ports; a 'kill' is `ServeApp.abort()` (every open transport
ripped out, listening socket closed — what SIGKILL does to the process,
minus the process). Every async body runs under a hard wait_for so a
routing bug fails fast instead of hanging the suite."""

import asyncio
import json
import time

import jax
import jax.numpy as jnp
import pytest

from distributed_pytorch_tpu.config import LLMConfig
from distributed_pytorch_tpu.engine import DecodeEngine
from distributed_pytorch_tpu.models.gpt import LLM
from distributed_pytorch_tpu.serve.metrics import RouterMetrics
from distributed_pytorch_tpu.serve.router import (NoReplica, Replica,
                                                  Router, RouterApp)
from distributed_pytorch_tpu.serve.scheduler import Scheduler, ShedError
from distributed_pytorch_tpu.serve.server import ServeApp


def tiny_cfg(**kw):
    base = dict(vocab_size=97, block_size=64, n_embd=48, n_head=4,
                n_kv_heads=2, attn="gqa", n_layer=2, up_dim=64,
                non_linearity="swiglu", pos_emb="rope", dropout=0.0)
    base.update(kw)
    return LLMConfig(**base)


@pytest.fixture(scope="module")
def mv():
    cfg = tiny_cfg()
    model = LLM(cfg, attn_impl="naive")
    rng = jax.random.PRNGKey(0)
    x = jnp.zeros((1, cfg.block_size), jnp.int32)
    variables = dict(model.init({"params": rng, "dropout": rng}, x, x))
    return cfg, model, variables


def run_async(coro, timeout=300):
    return asyncio.run(asyncio.wait_for(coro, timeout))


class Rep:
    """One in-process replica: engine + scheduler + HTTP server.
    `step_delay` throttles the engine so a test can reliably land a kill
    mid-stream (tiny-model steps are sub-ms otherwise)."""

    def __init__(self, mv, *, port=0, n_slots=2, step_delay=0.0):
        _, model, variables = mv
        self.eng = DecodeEngine(model, variables, n_slots=n_slots,
                                temperature=0.0, min_bucket=8)
        if step_delay:
            orig = self.eng.step

            def slow_step():
                time.sleep(step_delay)
                return orig()

            self.eng.step = slow_step
        self.sched = Scheduler(self.eng, max_queue=32)
        self.app = ServeApp(self.sched, port=port)

    async def start(self):
        await self.sched.start()
        await self.app.start()
        return self

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self.app.port}"

    async def kill(self):
        """Crash, not shutdown: abort every transport, then stop the
        scheduler so the dead replica's engine stops burning CPU."""
        self.app.abort()
        await self.sched.stop()

    async def stop(self):
        await self.app.stop()
        await self.sched.stop()


def make_router(*reps, **kw):
    kw.setdefault("probe_interval_s", 0.05)
    kw.setdefault("probe_timeout_s", 1.0)
    kw.setdefault("fail_threshold", 2)
    kw.setdefault("backoff_base_s", 0.05)
    kw.setdefault("backoff_cap_s", 0.5)
    kw.setdefault("connect_timeout_s", 1.0)
    return Router([r.addr if isinstance(r, Rep) else r for r in reps],
                  **kw)


def offline_ref(mv, prompts, budgets):
    _, model, variables = mv
    eng = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                       min_bucket=8)
    return eng.run(prompts, budgets)


# ----------------------------------------------------------------------
# minimal SSE client against the RouterApp (HTTP e2e)
# ----------------------------------------------------------------------

async def http_post(port, path, obj):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    body = json.dumps(obj).encode()
    writer.write(f"POST {path} HTTP/1.1\r\nHost: t\r\n"
                 f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
    await writer.drain()
    return reader, writer


async def http_get(port, path):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {path} HTTP/1.1\r\nHost: t\r\n\r\n".encode())
    await writer.drain()
    data = await reader.read()
    writer.close()
    head, _, body = data.partition(b"\r\n\r\n")
    return int(head.split(b" ")[1]), body.decode()


async def read_sse(reader, on_token=None):
    """Drain one SSE stream: returns (tokens, done_or_error_event).
    `on_token(i)` fires after the i-th token — the kill hook."""
    tokens, done = [], None
    while True:
        line = (await reader.readline()).decode().strip()
        if not line:
            continue
        assert line.startswith("data: "), line
        payload = line[len("data: "):]
        if payload == "[DONE]":
            break
        ev = json.loads(payload)
        if "token" in ev:
            tokens.append(ev["token"])
            if on_token is not None:
                await on_token(len(tokens))
        else:
            done = ev
            if "error" in ev:
                break
    return tokens, done


# ----------------------------------------------------------------------
# pick(): pure failure-detector / load logic, no sockets
# ----------------------------------------------------------------------

def test_pick_least_loaded_and_exclusion():
    r = Router(["127.0.0.1:1", "127.0.0.1:2", "127.0.0.1:3"])
    a, b, c = (r.replicas[f"127.0.0.1:{i}"] for i in (1, 2, 3))
    for rep in (a, b, c):
        rep.state = "healthy"
    a.queue_depth, b.queue_depth, c.queue_depth = 3, 1, 2
    assert r.pick().name == b.name
    # the router-side inflight term counts toward the score
    b.inflight = 4
    assert r.pick().name == c.name
    # exclusion (the tried set) skips a replica even if least loaded
    assert r.pick(exclude={c.name}).name == a.name
    # non-healthy states never dispatch
    a.state, b.state, c.state = "down", "draining", "init"
    with pytest.raises(NoReplica):
        r.pick()


def test_replica_addr_parsing():
    assert (Replica("http://10.0.0.5:8001").host,
            Replica("http://10.0.0.5:8001").port) == ("10.0.0.5", 8001)
    assert Replica("localhost:9/x").port == 9


# ----------------------------------------------------------------------
# dispatch + parity
# ----------------------------------------------------------------------

def test_dispatch_spreads_load_and_matches_offline(mv):
    prompts = [[1, 2, 3], [5, 6, 7, 8], [20] * 7, [42, 43], [9],
               [60, 61, 62]]
    budgets = [4, 5, 3, 6, 4, 5]

    async def main():
        reps = [await Rep(mv).start() for _ in range(2)]
        router = make_router(*reps)
        await router.start()
        outs = await asyncio.gather(*(
            router.complete(p, b) for p, b in zip(prompts, budgets)))
        await router.stop()
        for r in reps:
            await r.stop()
        return router, outs

    router, outs = run_async(main())
    refs = offline_ref(mv, prompts, budgets)
    for p, b, out, ref in zip(prompts, budgets, outs, refs):
        assert out["tokens"] == ref[len(p):], f"diverged for {p}"
        assert out["reason"] == "budget" and out["failovers"] == 0
    m = router.metrics
    assert m.counters["completed"] == len(prompts)
    assert m.counters["shed"] == 0
    # least-loaded + round-robin tiebreak: both replicas served traffic
    assert len(m.dispatch_counts) == 2
    assert all(n > 0 for n in m.dispatch_counts.values())


# ----------------------------------------------------------------------
# the tentpole property: failover idempotency (HTTP e2e)
# ----------------------------------------------------------------------

def test_failover_mid_stream_gapless_bit_identical(mv):
    """Kill the serving replica mid-SSE-stream: the client sees ONE
    stream — no gap, no duplicate, no error — and the full token
    sequence is bit-identical to an uninterrupted offline greedy run.
    The kill lands deterministically: replica A (throttled) is the only
    replica at dispatch time; B is registered after the 4th token, then
    A is killed."""
    prompt, budget = [1, 2, 3], 24

    async def main():
        rep_a = await Rep(mv, step_delay=0.05).start()
        rep_b = await Rep(mv).start()
        router = make_router(rep_a)            # A is the only choice
        await router.start()
        app = RouterApp(router, port=0)
        await app.start()

        killed = asyncio.Event()

        async def on_token(i):
            if i == 4 and not killed.is_set():
                killed.set()
                router.add_replica(rep_b.addr)
                await router.probe_all()       # B healthy before the kill
                await rep_a.kill()

        reader, writer = await http_post(
            app.port, "/v1/completions",
            {"prompt": prompt, "max_tokens": budget})
        status = int((await reader.readline()).split(b" ")[1])
        assert status == 200
        while (await reader.readline()).strip():
            pass
        tokens, done = await read_sse(reader, on_token=on_token)
        writer.close()

        health = await http_get(app.port, "/healthz")
        metrics_txt = await http_get(app.port, "/metrics")
        await app.stop()
        await router.stop()
        await rep_b.stop()
        return router, rep_b, tokens, done, health, metrics_txt

    router, rep_b, tokens, done, (h_status, h_body), (m_status, m_body) \
        = run_async(main())
    (ref,) = offline_ref(mv, [prompt], [budget])
    gen_ref = ref[len(prompt):]
    # gapless + duplicate-free + bit-identical, through a mid-stream kill
    assert tokens == gen_ref
    assert done is not None and done.get("done")
    assert done["reason"] == "budget"
    assert done["failovers"] >= 1
    m = router.metrics
    assert m.counters["failovers"] >= 1
    assert m.counters["completed"] == 1
    assert m.counters["shed"] == 0
    assert m.counters["replica_down"] >= 1
    # the failover resumed on B with the streamed prefix as prompt
    assert rep_b.eng.n_admitted >= 1
    # surfaces: router healthz still OK on the survivor; prometheus text
    assert h_status == 200 and json.loads(h_body)["ok"]
    assert m_status == 200
    assert 'router_requests_total{event="failovers"} 1' in m_body


def test_failover_under_concurrent_load_zero_failed(mv):
    """The acceptance property at test scale: N concurrent streams over
    2 replicas, one replica killed mid-drive and restarted on the same
    port — every request completes its FULL budget bit-identical to
    offline greedy; nothing fails, nothing is shed, the restarted
    replica rejoins."""
    n_req = 8
    prompts = [[i + 1, i + 2, i + 3] for i in range(n_req)]
    budgets = [14] * n_req

    async def main():
        rep_a = await Rep(mv, n_slots=4, step_delay=0.03).start()
        rep_b = await Rep(mv, n_slots=4, step_delay=0.03).start()
        port_a = rep_a.app.port
        # warm both replicas' prefill + fused-step traces so the drive
        # streams tokens immediately (compile latency would otherwise
        # let the kill land before any stream has a token)
        for rep in (rep_a, rep_b):
            await rep.sched.submit([1, 2, 3], 2).result()
        router = make_router(rep_a, rep_b, retry_budget=4)
        await router.start()

        consumers = [asyncio.ensure_future(router.complete(p, b))
                     for p, b in zip(prompts, budgets)]
        # kill only once the victim is demonstrably mid-stream: >= 2
        # fused steps of tokens fanned out across its live slots
        deadline = asyncio.get_running_loop().time() + 10
        while (rep_a.sched.metrics.counters["tokens_out"] < 10
               and asyncio.get_running_loop().time() < deadline):
            await asyncio.sleep(0.01)
        await rep_a.kill()
        await asyncio.sleep(0.2)
        rep_a2 = await Rep(mv, n_slots=4, port=port_a).start()
        outs = await asyncio.gather(*consumers)

        # the restarted replica rejoins through the backoff prober
        deadline = asyncio.get_running_loop().time() + 5
        while (router.replicas[rep_a2.addr].state != "healthy"
               and asyncio.get_running_loop().time() < deadline):
            await asyncio.sleep(0.05)
        rejoined = router.replicas[rep_a2.addr].state
        post = await router.complete([7, 7, 7], 3)
        await router.stop()
        for r in (rep_b, rep_a2):
            await r.stop()
        return router, outs, rejoined, post

    router, outs, rejoined, post = run_async(main())
    refs = offline_ref(mv, prompts, budgets)
    for p, b, out, ref in zip(prompts, budgets, outs, refs):
        assert out["tokens"] == ref[len(p):], "failed-over stream diverged"
        assert out["reason"] == "budget"
    m = router.metrics
    assert m.counters["completed"] == n_req + 1
    assert m.counters["shed"] == 0                 # zero failed OR shed
    assert m.counters["failovers"] >= 1            # the kill hit streams
    assert rejoined == "healthy"
    (post_ref,) = offline_ref(mv, [[7, 7, 7]], [3])
    assert post["tokens"] == post_ref[3:]


# ----------------------------------------------------------------------
# explicit shed: retry budget, no replicas
# ----------------------------------------------------------------------

def test_kill_with_no_survivor_is_explicit_shed_not_hang(mv):
    async def main():
        rep = await Rep(mv, step_delay=0.05).start()
        router = make_router(rep, retry_budget=2)
        await router.start()
        tokens, err = [], None
        try:
            async for ev in router.stream([1, 2, 3], 30):
                if "token" in ev:
                    tokens.append(ev["token"])
                    if len(tokens) == 2:
                        await rep.kill()
        except ShedError as e:
            err = e
        await router.stop()
        return router, tokens, err

    router, tokens, err = run_async(main(), timeout=60)
    assert err is not None, "mid-stream kill with no survivor must shed"
    assert err.cause in ("replica_failure", "retries_exhausted",
                         "no_replica")
    m = router.metrics
    assert m.counters["shed"] == 1
    assert m.counters["completed"] == 0


def test_no_healthy_replica_sheds_immediately(mv):
    async def main():
        # a port with nothing listening: the probe can never succeed
        router = make_router("127.0.0.1:1")
        await router.start()
        err = None
        try:
            await router.complete([1, 2], 4)
        except ShedError as e:
            err = e
        app = RouterApp(router, port=0)
        await app.start()
        h_status, _ = await http_get(app.port, "/healthz")
        r, w = await http_post(app.port, "/v1/completions",
                               {"prompt": [1], "max_tokens": 2})
        status = int((await r.readline()).split(b" ")[1])
        body = (await r.read()).split(b"\r\n\r\n")[-1]
        w.close()
        await app.stop()
        await router.stop()
        return err, h_status, status, json.loads(body)

    err, h_status, status, body = run_async(main(), timeout=60)
    assert err is not None and err.cause == "no_replica"
    assert h_status == 503
    assert status == 503 and body["cause"] == "no_replica"


# ----------------------------------------------------------------------
# draining restart
# ----------------------------------------------------------------------

def test_drain_hands_over_without_stream_loss(mv):
    """Drain the replica serving a live stream: the stream runs to
    completion (drain never cancels live work), new traffic goes to the
    survivor only, and the drained replica's healthz flips 503 with
    `drained: true` once quiesced — the kill-safe restart window."""

    async def main():
        rep_a = await Rep(mv, step_delay=0.03).start()
        rep_b = await Rep(mv).start()
        router = make_router(rep_a)            # stream lands on A
        await router.start()

        tokens = []
        agen = router.stream([1, 2, 3], 16)
        async for ev in agen:
            tokens.append(ev["token"])
            break                              # live on A now
        router.add_replica(rep_b.addr)
        await router.probe_all()
        drain_resp = await router.drain(rep_a.addr)

        # new requests must go to B (A is gated out)
        before = dict(router.metrics.dispatch_counts)
        outs = await asyncio.gather(*(router.complete([9, 8], 3)
                                      for _ in range(3)))
        after = dict(router.metrics.dispatch_counts)

        # the live stream on A still finishes, gapless
        done = None
        async for ev in agen:
            if "token" in ev:
                tokens.append(ev["token"])
            else:
                done = ev
        # A quiesces: healthz 503, draining, drained
        deadline = asyncio.get_running_loop().time() + 10
        while (not rep_a.sched.drained
               and asyncio.get_running_loop().time() < deadline):
            await asyncio.sleep(0.02)
        h_status, h_body = await http_get(rep_a.app.port, "/healthz")
        await router.stop()
        await rep_a.stop()
        await rep_b.stop()
        return (router, tokens, done, drain_resp, before, after, outs,
                h_status, json.loads(h_body))

    (router, tokens, done, drain_resp, before, after, outs, h_status,
     h_body) = run_async(main())
    assert drain_resp["status"] == 200 and drain_resp["draining"]
    (ref,) = offline_ref(mv, [[1, 2, 3]], [16])
    assert tokens == ref[3:]                   # drain lost nothing
    assert done is not None and done["reason"] == "budget"
    a_name = next(n for n in router.replicas if before.get(n))
    assert after.get(a_name, 0) == before.get(a_name, 0), \
        "drained replica received new traffic"
    for out in outs:
        assert out["reason"] == "budget" and len(out["tokens"]) == 3
    assert h_status == 503
    assert h_body["draining"] and h_body["drained"]
    assert not h_body["ok"]


# ----------------------------------------------------------------------
# failure detector: down -> backoff -> rejoin
# ----------------------------------------------------------------------

def test_down_replica_backs_off_and_rejoins(mv):
    async def main():
        rep = await Rep(mv).start()
        port = rep.app.port
        router = make_router(rep)
        await router.start()
        name = rep.addr
        await rep.kill()
        # probes trip the detector within fail_threshold * interval
        deadline = asyncio.get_running_loop().time() + 5
        while (router.replicas[name].state != "down"
               and asyncio.get_running_loop().time() < deadline):
            await asyncio.sleep(0.02)
        state_after_kill = router.replicas[name].state
        gate = router.replicas[name].next_probe_at - time.perf_counter()
        down_events = router.metrics.counters["replica_down"]

        rep2 = await Rep(mv, port=port).start()
        deadline = asyncio.get_running_loop().time() + 5
        while (router.replicas[name].state != "healthy"
               and asyncio.get_running_loop().time() < deadline):
            await asyncio.sleep(0.02)
        state_after_restart = router.replicas[name].state
        out = await router.complete([4, 5, 6], 4)
        await router.stop()
        await rep2.stop()
        return (router, state_after_kill, gate, down_events,
                state_after_restart, out)

    (router, state_after_kill, gate, down_events, state_after_restart,
     out) = run_async(main(), timeout=60)
    assert state_after_kill == "down"
    assert gate > -1.0                 # a backoff gate was scheduled
    assert down_events >= 1
    assert state_after_restart == "healthy"
    assert router.metrics.counters["replica_up"] >= 2  # start + rejoin
    assert out["reason"] == "budget" and len(out["tokens"]) == 4


def test_router_metrics_render_smoke():
    m = RouterMetrics()
    m.inc("submitted")
    m.dispatched("127.0.0.1:1")
    m.shed("no_replica")
    m.ttft.observe(0.01)
    txt = m.render_prometheus()
    assert 'router_requests_total{event="dispatched"} 1' in txt
    assert 'router_shed_total{cause="no_replica"} 1' in txt
    assert 'router_dispatch_total{replica="127.0.0.1:1"} 1' in txt
    assert "router_ttft_seconds_count 1" in txt
    s = m.summary()
    assert s["dispatch_by_replica"] == {"127.0.0.1:1": 1}
    assert s["shed_by_cause"] == {"no_replica": 1}


# ----------------------------------------------------------------------
# cache-aware (digest-sticky) dispatch
# ----------------------------------------------------------------------

def test_sticky_dispatch_follows_prefix_digest(mv):
    """Requests sharing a multi-block prefix concentrate on the replica
    whose advertised radix digest matches, instead of spreading
    least-loaded — and the streams stay bit-identical to offline greedy.
    Unrelated prompts keep plain least-loaded dispatch (no sticky hit).
    """
    sys_prompt = [(7 * i + 3) % 97 for i in range(24)]   # 3 blocks @ bs 8
    tails = [5, 8, 11, 14]
    prompts = [sys_prompt + [t] for t in tails]
    other = [90, 91, 92]                                  # sub-block

    async def main():
        reps = [await Rep(mv).start() for _ in range(2)]
        router = make_router(*reps)
        await router.start()
        first = await router.complete(prompts[0], 3)
        # let a probe cycle pick up the serving replica's digest advert
        for _ in range(40):
            await asyncio.sleep(0.05)
            if any(r.kv_digest for r in router.replicas.values()):
                break
        served = [i for i, r in enumerate(reps)
                  if r.sched.metrics.counters["admitted"] > 0]
        outs = [await router.complete(p, 3) for p in prompts[1:]]
        plain = await router.complete(other, 3)
        admitted = [r.sched.metrics.counters["admitted"] for r in reps]
        await router.stop()
        for r in reps:
            await r.stop()
        return router, first, served, outs, plain, admitted

    (router, first, served, outs, plain,
     admitted) = run_async(main(), timeout=120)
    # exactly one replica served the first request, and every
    # same-prefix follow-up stuck to it
    assert len(served) == 1
    assert admitted[served[0]] >= len(prompts)
    assert router.metrics.counters["sticky_hits"] >= len(prompts) - 1
    # the advertisement round-tripped the health probe
    rep = list(router.replicas.values())
    assert any(r.digest_block_size > 0 and r.kv_digest for r in rep)
    # parity: sticky routing never changes tokens
    refs = offline_ref(mv, prompts + [other], [3] * 5)
    for p, out, ref in zip(prompts, [first] + outs, refs):
        assert out["tokens"] == ref[len(p):], f"diverged for tail {p[-1]}"
    assert plain["tokens"] == refs[-1][len(other):]
    assert plain["reason"] == "budget"
