"""Test harness: force an 8-device CPU platform so every parallelism recipe
is exercised with real XLA collectives and no TPU (SURVEY.md §4 — the
reference has zero tests; this virtual mesh replaces its manual 2-GPU
Kaggle smoke runs).

Note: env vars alone are NOT enough here — the image's sitecustomize
imports jax at interpreter start (TPU tunnel registration), so JAX's config
is already initialized by the time conftest runs. `jax.config.update`
before first backend use still works because backend clients are created
lazily."""

import os

# Best-effort for subprocesses spawned by tests.
os.environ["JAX_PLATFORMS"] = "cpu"

# Compile-time trim: tiny test shapes gain nothing from LLVM's expensive
# optimization passes, and XLA:CPU compile time dominates suite wall-clock
# (~40% faster overall). Parsed when the first backend client is created,
# which hasn't happened yet even though sitecustomize imported jax.
_FAST_COMPILE = ("--xla_backend_optimization_level=0 "
                 "--xla_llvm_disable_expensive_passes=true")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " "
                           + _FAST_COMPILE).strip()

import jax  # noqa: E402

from distributed_pytorch_tpu import compat  # noqa: E402

jax.config.update("jax_platforms", "cpu")
compat.request_cpu_devices(8)  # jax_num_cpu_devices, or XLA_FLAGS on 0.4.x

# Persistent compile cache: the suite is compile-dominated (VERDICT r4
# weak #7, ~14 min wall-clock), and most test invocations recompile
# identical tiny-shape programs. Harmless no-op where unsupported.
try:
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_ccache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
except Exception:
    pass


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process / long-running tests")
