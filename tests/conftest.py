"""Test harness: force an 8-device CPU platform so every parallelism recipe
is exercised with real XLA collectives and no TPU (SURVEY.md §4 — the
reference has zero tests; this virtual mesh replaces its manual 2-GPU
Kaggle smoke runs).

Note: env vars alone are NOT enough here — the image's sitecustomize
imports jax at interpreter start (TPU tunnel registration), so JAX's config
is already initialized by the time conftest runs. `jax.config.update`
before first backend use still works because backend clients are created
lazily."""

import os

# Best-effort for subprocesses spawned by tests.
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
