"""Parallelism-recipe tests on the 8-device CPU mesh: every recipe must
(a) compile + execute with real XLA collectives, (b) produce the SAME
optimizer step as the single-device oracle given the same init and batch —
the sharded-vs-single parity suite SURVEY.md §4 prescribes (replacing the
reference's manual 2-GPU Kaggle smoke runs, kaggle-ddp.py:4-5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_pytorch_tpu.config import LLMConfig, TrainConfig
from distributed_pytorch_tpu.parallel import sharding as shd
from distributed_pytorch_tpu.parallel.mesh import MeshPlan, build_mesh, resolve_plan
from distributed_pytorch_tpu.train.state import create_train_state
from distributed_pytorch_tpu.train.step import make_train_step

TINY = dict(vocab_size=128, block_size=32, n_embd=32, n_head=4,
            n_kv_heads=2, n_layer=2, up_dim=64)
MOE = dict(**TINY, moe=True, n_exp=8, n_shared=1, n_act=3)
# scatter vs single-device dense oracle: generous capacity -> no drops, so
# the trajectories must agree (the ep recipe's production dispatch)
MOE_SCATTER = dict(**MOE, moe_impl="scatter", capacity_factor=8.0)
# dropless grouped kernel (ops/grouped_matmul.py): the sharded step runs
# the Pallas dispatch inside shard_map over ('data','expert'); its oracle
# runs the same kernel unsharded — grouped-vs-dense parity is covered at
# module level in test_grouped_matmul.py
MOE_GROUPED = dict(**MOE, moe_impl="grouped")
# pp x MoE with moe_impl='grouped': the pipeline vmaps Blocks, so the
# dispatch degrades to the dense combine (identical dropless semantics)
# while stats_weight keeps masking bubble slots — the config must train
# and match the oracle either way
PP_MOE_GROUPED = dict(**MOE, moe_impl="grouped", pp_stages=2,
                      pp_microbatches=4)
# forced T-chunked fused CE (ops/losses.py lax.scan path): tiny vocab never
# auto-chunks, so an explicit loss_chunk makes sharded runs exercise the
# scan + checkpoint over 'data'/'model'-sharded embeddings
TINY_CHUNKED = dict(**TINY, loss_chunk=8)
# pipeline parallelism (models/pipeline.py): stacked blocks over 'pipe',
# 4 microbatches of 2 sequences through a 2-deep layer stack
TINY_PP = dict(**TINY, pp_stages=2, pp_microbatches=4)
# MLA under tensor parallelism: the latent up-projections (W_uq/W_uk/W_uv)
# are column-parallel and W_o row-parallel in the TP table
MLA = dict(vocab_size=128, block_size=32, n_embd=32, n_head=4,
           n_kv_heads=4, n_layer=2, up_dim=64, attn="mla",
           q_latent_dim=8, kv_latent_dim=8, rope_head_dim=4)


def _batch(mc, accum, B, seed=0):
    rng = np.random.default_rng(seed)
    starts = rng.integers(0, mc.vocab_size, size=(accum, B, 1))
    seq = (starts + np.arange(mc.block_size + 1)) % mc.vocab_size
    return (np.asarray(seq[..., :-1], np.int32),
            np.asarray(seq[..., 1:], np.int32))


def _run_steps(mc, tc, mesh, x, y, n_steps=2):
    model, tx, state, state_sh = create_train_state(mc, tc, mesh)
    step = make_train_step(model, tx, mc, tc, mesh, state_sh)
    if mesh is not None:
        bsh = NamedSharding(mesh, shd.batch_pspec(tc.parallelism, mesh,
                                                  leading_accum=True))
        x = jax.device_put(jnp.asarray(x), bsh)
        y = jax.device_put(jnp.asarray(y), bsh)
    else:
        x, y = jnp.asarray(x), jnp.asarray(y)
    losses = []
    for _ in range(n_steps):
        state, m = step(state, x, y)
        losses.append(float(m["loss"]))
    return state, losses


def test_mesh_plan_resolution():
    assert resolve_plan("single", 8) == MeshPlan(1, 1, 1, 1)
    assert resolve_plan("fsdp", 8) == MeshPlan(8, 1, 1, 1)
    assert resolve_plan("tp", 8, tp_size=2) == MeshPlan(4, 1, 1, 2)
    assert resolve_plan("ep", 8, ep_size=4) == MeshPlan(2, 1, 4, 1)
    assert resolve_plan("sp", 8, sp_size=2) == MeshPlan(4, 2, 1, 1)
    with pytest.raises(AssertionError):
        resolve_plan("tp", 8, tp_size=3)
    # axis sizes compose with ANY recipe (round-3 VERDICT #3): fsdp x ep is
    # the MoE-at-scale config, fsdp x sp the long-context one
    assert resolve_plan("fsdp", 8, ep_size=2) == MeshPlan(4, 1, 2, 1)
    assert resolve_plan("fsdp", 8, sp_size=2) == MeshPlan(4, 2, 1, 1)
    assert resolve_plan("fsdp", 8, tp_size=2, sp_size=2) == MeshPlan(2, 2, 1, 2)


def test_fsdp_params_actually_sharded():
    """FSDP must shard parameter storage (FULL_SHARD semantics,
    kaggle-fsdp.py:1076-1086), not just compute."""
    mc = LLMConfig(**TINY)
    tc = TrainConfig(total_batch_size=8 * 32, batch_size=1,
                     parallelism="fsdp")
    mesh = build_mesh(resolve_plan("fsdp", 8))
    _, _, state, _ = create_train_state(mc, tc, mesh)
    sharded = 0
    for leaf in jax.tree_util.tree_leaves(state.params):
        spec = leaf.sharding.spec
        if any(ax is not None for ax in spec):
            sharded += 1
            shard = leaf.addressable_shards[0].data
            assert shard.size < leaf.size
    assert sharded >= 5, f"only {sharded} param leaves sharded"


def test_zero1_opt_state_sharded_params_replicated():
    """ZeRO-1: optimizer moments sharded, params replicated
    (kaggle-zero1.py:1071-1078)."""
    mc = LLMConfig(**TINY)
    tc = TrainConfig(total_batch_size=8 * 32, batch_size=1,
                     parallelism="zero1")
    mesh = build_mesh(resolve_plan("zero1", 8))
    _, _, state, _ = create_train_state(mc, tc, mesh)
    for leaf in jax.tree_util.tree_leaves(state.params):
        assert all(ax is None for ax in leaf.sharding.spec), \
            "zero1 params must be replicated"
    mom_sharded = sum(
        1 for leaf in jax.tree_util.tree_leaves(state.opt_state)
        if hasattr(leaf, "sharding") and leaf.ndim >= 1
        and any(ax is not None for ax in leaf.sharding.spec))
    assert mom_sharded >= 5, f"only {mom_sharded} moment leaves sharded"


RECIPES = [
    ("dp", TINY, {}),
    ("zero1", TINY, {}),
    ("zero2", TINY, {}),
    ("fsdp", TINY, {}),
    ("tp", TINY, {"tp_size": 2}),
    ("fsdp_tp", TINY, {"tp_size": 2}),
    ("sp", TINY, {"sp_size": 2}),
    ("ep", MOE, {"ep_size": 2}),
    ("ep", MOE_SCATTER, {"ep_size": 2}),
    # composed recipes (round-3 VERDICT #3): ZeRO-3 param sharding x a
    # second live axis — the configs real MoE / long-context runs need
    ("fsdp", MOE_SCATTER, {"ep_size": 2}),
    ("fsdp", TINY, {"sp_size": 2}),
    # chunked fused CE under sharded embeddings (fsdp 'data'-sharded, tp
    # vocab-parallel): the scan path must match the oracle exactly
    ("fsdp", TINY_CHUNKED, {}),
    ("tp", TINY_CHUNKED, {"tp_size": 2}),
    # pipeline parallelism: dp=4 x pipe=2 — the interleaved schedule must
    # reproduce the oracle trajectory exactly (same stacked init)
    ("pp", TINY_PP, {"pp_size": 2}),
    # ring attention + capacity-bounded MoE dispatch in one model: the
    # long-context MoE configuration
    ("fsdp", MOE_SCATTER, {"sp_size": 2}),
    # MLA's absorbed projections under megatron-style TP
    ("fsdp_tp", MLA, {"tp_size": 2}),
    # dropless grouped dispatch under expert parallelism (round 7): pure
    # ep, and composed with ZeRO-3 param sharding (the MoE-at-scale mesh)
    ("ep", MOE_GROUPED, {"ep_size": 2}),
    ("fsdp", MOE_GROUPED, {"ep_size": 2}),
    # pp x MoE exercising stats_weight with moe_impl='grouped'
    ("pp", PP_MOE_GROUPED, {"pp_size": 2}),
]
_RECIPE_IDS = [r[0] for r in RECIPES[:-11]] + [
    "ep_scatter", "fsdp_x_ep", "fsdp_x_sp", "fsdp_chunked_ce",
    "tp_chunked_ce", "pp", "moe_x_sp", "mla_x_tp",
    "ep_grouped", "fsdp_x_ep_grouped", "pp_moe_grouped"]


_ORACLE_CACHE: dict = {}


def _oracle_losses(mc, x, y):
    """Single-device loss trajectory, computed once per model config — the
    9 recipe cases share 3 distinct configs, and each oracle run costs a
    full train-step compile (suite wall-clock, round-1 weak #9)."""
    if mc not in _ORACLE_CACHE:
        tc = TrainConfig(total_batch_size=2 * 8 * 32 // 2, batch_size=8,
                         learning_rate=1e-3, warmup_steps=2,
                         parallelism="single")
        _ORACLE_CACHE[mc] = _run_steps(mc, tc, None, x, y)[1]
    return _ORACLE_CACHE[mc]


@pytest.mark.parametrize("recipe,mdict,kw", RECIPES, ids=_RECIPE_IDS)
def test_recipe_matches_single_device_oracle(recipe, mdict, kw):
    """Same init + same global batch -> same loss trajectory and params as
    the single-device trainer (DDP≡ZeRO≡FSDP≡single equivalence)."""
    mc = LLMConfig(**mdict)
    x, y = _batch(mc, 2, 8, seed=11)

    # NB total_batch_size is informational to the loop, not the step; the
    # step consumes whatever (accum, B, T) it is given.
    # The pp oracle is the plain LOOP model: the pipeline run starts from
    # the stacked loop init (train/state.py), so its trajectory must match
    # the non-pipelined model's — the strongest parity claim available.
    import dataclasses as _dc
    oracle_cfg = _dc.replace(mc, pp_stages=1, pp_microbatches=0) \
        if mc.pp_stages > 1 else mc
    oracle_losses = _oracle_losses(oracle_cfg, x, y)

    tc = TrainConfig(total_batch_size=2 * 8 * 32 // 2, batch_size=1,
                     learning_rate=1e-3, warmup_steps=2,
                     parallelism=recipe, **kw)
    mesh = build_mesh(resolve_plan(
        recipe, 8, tp_size=kw.get("tp_size", 1),
        ep_size=kw.get("ep_size", 1), sp_size=kw.get("sp_size", 1)))
    _, losses = _run_steps(mc, tc, mesh, x, y)

    np.testing.assert_allclose(losses, oracle_losses, rtol=2e-4,
                               err_msg=f"{recipe} diverged from oracle")


def test_tp_spec_assignment():
    """TP table: qkv/up projections column-parallel, output projections
    row-parallel over 'model'."""
    mc = LLMConfig(**TINY)
    tc = TrainConfig(parallelism="tp", tp_size=2)
    mesh = build_mesh(resolve_plan("tp", 8, tp_size=2))
    model, tx, state, _ = create_train_state(mc, tc, mesh)
    from flax.traverse_util import flatten_dict
    flat = flatten_dict(state.params)
    qkv = next(v for k, v in flat.items() if "c_attn" in k and k[-1] == "kernel")
    proj = next(v for k, v in flat.items()
                if "attn" in str(k) and "c_proj" in k and k[-1] == "kernel")
    assert qkv.sharding.spec[1] == "model"
    assert proj.sharding.spec[0] == "model"


def test_tp_embedding_vocab_sharded():
    """The tied embedding/lm_head — 39% of GPT-124M's params — must be
    vocab-sharded over 'model' under tp (round-1: replicated)."""
    mc = LLMConfig(**TINY)
    tc = TrainConfig(parallelism="tp", tp_size=2)
    mesh = build_mesh(resolve_plan("tp", 8, tp_size=2))
    _, _, state, _ = create_train_state(mc, tc, mesh)
    emb = state.params["tkn_emb"]["embedding"]
    assert emb.sharding.spec[0] == "model", emb.sharding.spec


def test_ep_expert_axis_sharded():
    mc = LLMConfig(**MOE)
    tc = TrainConfig(parallelism="ep", ep_size=2)
    mesh = build_mesh(resolve_plan("ep", 8, ep_size=2))
    _, _, state, _ = create_train_state(mc, tc, mesh)
    from flax.traverse_util import flatten_dict
    flat = flatten_dict(state.params)
    fc = next(v for k, v in flat.items() if k[-1] == "experts_fc")
    assert fc.sharding.spec[0] == "expert", fc.sharding.spec
