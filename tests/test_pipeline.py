"""Pipeline parallelism (models/pipeline.py): the interleaved schedule must
be a pure re-scheduling of the loop model — identical forward, identical
gradients, stage-sharded params — plus stack/unstack round trips for
checkpoint interop. The reference names PP as a goal but has no code
(/root/reference/README.md:7); the oracle here is our own loop model."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.config import LLMConfig
from distributed_pytorch_tpu.models.gpt import LLM
from distributed_pytorch_tpu.models.pipeline import (stack_block_params,
                                                     unstack_block_params)

KW = dict(vocab_size=96, block_size=32, n_embd=32, n_head=4, n_kv_heads=2,
          n_layer=4, up_dim=48, pos_emb="rope", attn="gqa",
          non_linearity="swiglu")


def _models(pp_microbatches=4):
    loop_cfg = LLMConfig(**KW)
    pp_cfg = LLMConfig(**KW, pp_stages=2, pp_microbatches=pp_microbatches)
    idx = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 96)
    tgt = jax.random.randint(jax.random.PRNGKey(2), (8, 16), 0, 96)
    loop_model, pp_model = LLM(loop_cfg), LLM(pp_cfg)
    variables = loop_model.init(jax.random.PRNGKey(0), idx, tgt)
    pp_params = stack_block_params(variables["params"], KW["n_layer"])
    return loop_model, pp_model, variables, pp_params, idx, tgt


def test_pp_init_structure_matches_stacked_loop():
    """model.init of the pp model and stack_block_params of the loop init
    must agree on tree structure AND leaf shapes — this is the contract
    that lets train/state.py seed pipelines from loop weights."""
    loop_model, pp_model, variables, pp_params, idx, tgt = _models()
    pp_init = pp_model.init(jax.random.PRNGKey(0), idx, tgt)
    assert jax.tree_util.tree_structure(pp_init["params"]) == \
        jax.tree_util.tree_structure(pp_params)
    jax.tree_util.tree_map(lambda a, b: None if a.shape == b.shape else
                           pytest.fail(f"{a.shape} != {b.shape}"),
                           pp_init["params"], pp_params)


def test_pp_forward_matches_loop():
    loop_model, pp_model, variables, pp_params, idx, tgt = _models()
    _, loss_loop, _ = loop_model.apply(variables, idx, tgt)
    _, loss_pp, _ = pp_model.apply({"params": pp_params}, idx, tgt)
    np.testing.assert_allclose(float(loss_pp), float(loss_loop), rtol=1e-6)


@pytest.mark.parametrize("m", [1, 2, 8])
def test_pp_microbatch_count_invariance(m):
    """The schedule result cannot depend on how the batch is sliced."""
    loop_model, pp_model, variables, pp_params, idx, tgt = _models(m)
    _, loss_loop, _ = loop_model.apply(variables, idx, tgt)
    _, loss_pp, _ = pp_model.apply({"params": pp_params}, idx, tgt)
    np.testing.assert_allclose(float(loss_pp), float(loss_loop), rtol=1e-6)


def test_pp_gradients_match_loop():
    loop_model, pp_model, variables, pp_params, idx, tgt = _models()

    g_loop = jax.grad(
        lambda p: loop_model.apply({"params": p}, idx, tgt)[1])(
        variables["params"])
    g_pp = jax.grad(
        lambda p: pp_model.apply({"params": p}, idx, tgt)[1])(pp_params)
    g_pp_unstacked = unstack_block_params(g_pp, KW["n_layer"])
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=5e-4, atol=1e-5),
        g_loop, g_pp_unstacked)


def test_stack_unstack_roundtrip():
    loop_model, _, variables, pp_params, _, _ = _models()
    back = unstack_block_params(pp_params, KW["n_layer"])
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        variables["params"], back)


def test_pp_params_sharded_over_pipe():
    """Under the pp recipe the stacked layer axis is the stage assignment:
    every blocks/ leaf must carry 'pipe' on axis 0."""
    from distributed_pytorch_tpu.config import TrainConfig
    from distributed_pytorch_tpu.parallel.mesh import build_mesh, resolve_plan
    from distributed_pytorch_tpu.train.state import create_train_state

    mc = LLMConfig(**KW, pp_stages=2, pp_microbatches=4)
    tc = TrainConfig(parallelism="pp", pp_size=2, batch_size=8,
                     total_batch_size=8 * 32)
    mesh = build_mesh(resolve_plan("pp", 8, pp_size=2))  # data=4 x pipe=2
    _, _, state, _ = create_train_state(mc, tc, mesh)
    stacked = state.params["blocks"]["stack"]
    for leaf in jax.tree_util.tree_leaves(stacked):
        assert leaf.sharding.spec[0] == "pipe", leaf.sharding.spec
        assert leaf.addressable_shards[0].data.shape[0] == KW["n_layer"] // 2


def test_pp_rejects_decode_caches():
    mc = LLMConfig(**KW, pp_stages=2)
    model = LLM(mc)
    idx = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 96)
    variables = model.init(jax.random.PRNGKey(0), idx, idx)
    from distributed_pytorch_tpu.models.gpt import init_cache
    caches = init_cache(mc, 2)
    with pytest.raises(ValueError, match="decoding"):
        model.apply(variables, idx, None, caches, 0)


def test_pp_checkpoint_unstacks_for_sampling(tmp_path, monkeypatch):
    """End-to-end: train 2 steps under pp, checkpoint, unstack, and verify
    the loop model reproduces the pipeline model's eval loss."""
    monkeypatch.chdir(tmp_path)
    from distributed_pytorch_tpu.config import TrainConfig
    from distributed_pytorch_tpu.train.loop import train

    mc = LLMConfig(vocab_size=256, block_size=32, n_embd=32, n_head=4,
                   n_kv_heads=2, n_layer=2, up_dim=48,
                   pp_stages=2, pp_microbatches=2)
    tc = TrainConfig(dataset="synthetic", data_dir="bench_data",
                     # 8 CPU devices -> pipe=2, leftover dp=4: global batch
                     # = batch_size*dp = 16 sequences of 32 tokens
                     total_batch_size=16 * 32, batch_size=4, max_iters=2,
                     parallelism="pp", pp_size=2, save_model=True,
                     save_stats=False, file_name="ppruns")
    stats = train(mc, tc, log=lambda s: None)

    pp_params = jax.device_get(stats["state"].params)
    loop_params = unstack_block_params(pp_params, mc.n_layer)
    loop_cfg = dataclasses.replace(mc, pp_stages=1, pp_microbatches=0)
    idx = jax.random.randint(jax.random.PRNGKey(5), (2, 32), 0, 256)
    _, l_loop, _ = LLM(loop_cfg).apply({"params": loop_params}, idx, idx)
    _, l_pp, _ = LLM(mc).apply({"params": pp_params}, idx, idx)
    np.testing.assert_allclose(float(l_loop), float(l_pp), rtol=1e-5)


MOE_KW = dict(moe=True, n_exp=4, n_shared=1, n_act=2, alpha=1e-2,
              gamma=0.1, coeff=0.01)


def _moe_models(pp_microbatches, **extra):
    kw = {**KW, **MOE_KW, **extra}
    loop_cfg = LLMConfig(**kw)
    pp_cfg = LLMConfig(**kw, pp_stages=2, pp_microbatches=pp_microbatches)
    idx = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 96)
    tgt = jax.random.randint(jax.random.PRNGKey(2), (8, 16), 0, 96)
    loop_model, pp_model = LLM(loop_cfg), LLM(pp_cfg)
    variables = loop_model.init(jax.random.PRNGKey(0), idx, tgt)
    pp_vars = {"params": stack_block_params(variables["params"],
                                            KW["n_layer"])}
    if "moe_state" in variables:  # aux_free only
        pp_vars["moe_state"] = stack_block_params(variables["moe_state"],
                                                  KW["n_layer"])
    return loop_model, pp_model, variables, pp_vars, idx, tgt


@pytest.mark.parametrize("aux_free", [True, False])
def test_pp_moe_matches_loop_single_microbatch(aux_free):
    """MoE x pp at M=1: one microbatch IS the full batch, so loss (incl.
    the aux term) must be bit-comparable to the loop model — this also
    proves bubble-slot masking, since at M=1 all but one slot per tick is
    a bubble whose zero-token routing would otherwise contribute aux."""
    loop_model, pp_model, variables, pp_vars, idx, tgt = \
        _moe_models(1, aux_free=aux_free)
    (_, loss_loop, _), _ = loop_model.apply(variables, idx, tgt,
                                            mutable=["moe_state"])
    (_, loss_pp, _), _ = pp_model.apply(pp_vars, idx, tgt,
                                        mutable=["moe_state"])
    np.testing.assert_allclose(float(loss_pp), float(loss_loop), rtol=1e-6)


def test_pp_moe_main_loss_microbatch_invariant():
    """With the aux coefficient zeroed, the MoE pp loss must equal the loop
    model at any M (token outputs are exact; only the aux statistics are
    per-microbatch, documented in run_pipeline)."""
    loop_model, pp_model, variables, pp_vars, idx, tgt = \
        _moe_models(4, alpha=0.0, aux_free=True)
    (_, loss_loop, _), _ = loop_model.apply(variables, idx, tgt,
                                            mutable=["moe_state"])
    (_, loss_pp, _), _ = pp_model.apply(pp_vars, idx, tgt,
                                        mutable=["moe_state"])
    np.testing.assert_allclose(float(loss_pp), float(loss_loop), rtol=1e-6)


def test_pp_moe_bias_update_matches_loop_m1():
    """Training-mode apply at M=1: the aux-free bias update must be exactly
    the loop model's (same fi over the full batch, one gamma step per
    layer) — any bubble-slot pollution or scan-carry mistake shows here."""
    loop_model, pp_model, variables, pp_vars, idx, tgt = _moe_models(1)
    rngs = {"dropout": jax.random.PRNGKey(3)}
    _, upd_loop = loop_model.apply(variables, idx, tgt,
                                   deterministic=False,
                                   mutable=["moe_state"], rngs=rngs)
    _, upd_pp = pp_model.apply(pp_vars, idx, tgt, deterministic=False,
                               mutable=["moe_state"], rngs=rngs)
    pp_unstacked = unstack_block_params(upd_pp["moe_state"], KW["n_layer"])
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a),
                                                np.asarray(b), atol=1e-6),
        upd_loop["moe_state"], pp_unstacked)
    # and the update must actually move
    moved = jax.tree_util.tree_map(
        lambda a, b: not np.allclose(np.asarray(a), np.asarray(b)),
        variables["moe_state"], upd_loop["moe_state"])
    assert any(jax.tree_util.tree_leaves(moved))


def test_pp_moe_bias_step_is_microbatch_invariant():
    """The aux-free bias must move by gamma * mean-over-microbatches(delta)
    per optimizer step regardless of M (the per-microbatch delta is scaled
    by 1/M in _PipeTick): M=1 vs M=4 training applies from the same init
    must land within the per-microbatch routing-variation envelope, NOT at
    ~M x the movement (the round-5 ADVICE drift). The M=1 leg is exactly
    the loop model (test_pp_moe_bias_update_matches_loop_m1), so it anchors
    the scale."""
    rngs = {"dropout": jax.random.PRNGKey(3)}
    moved = {}
    for m in (1, 4):
        _, pp_model, _, pp_vars, idx, tgt = _moe_models(m)
        _, upd = pp_model.apply(pp_vars, idx, tgt, deterministic=False,
                                mutable=["moe_state"], rngs=rngs)
        delta = jax.tree_util.tree_map(
            lambda a, b: np.asarray(a) - np.asarray(b),
            upd["moe_state"], pp_vars["moe_state"])
        moved[m] = np.concatenate(
            [l.ravel() for l in jax.tree_util.tree_leaves(delta)])
        # bias must actually move at every M
        assert np.abs(moved[m]).max() > 0
    # per-step movement magnitude must be M-invariant (same gamma scale).
    # Routing statistics differ per microbatch slice, so allow a 2x band —
    # the pre-fix behavior was a ~4x (=M) inflation at M=4.
    r = np.abs(moved[4]).sum() / np.abs(moved[1]).sum()
    assert 0.5 < r < 2.0, f"bias movement scaled by {r:.2f} with M=4"


def test_pp_moe_train_step_runs():
    """One jitted train step with MoE x pp on the 8-device mesh (pipe=2 x
    data=4): finite loss, bias moves."""
    from distributed_pytorch_tpu.config import TrainConfig
    from distributed_pytorch_tpu.parallel.mesh import build_mesh, resolve_plan
    from distributed_pytorch_tpu.train.state import create_train_state
    from distributed_pytorch_tpu.train.step import make_train_step
    from distributed_pytorch_tpu.parallel import context

    mc = LLMConfig(**{**KW, **MOE_KW}, pp_stages=2, pp_microbatches=2)
    tc = TrainConfig(total_batch_size=8 * 32, batch_size=8, max_iters=2,
                     parallelism="pp", pp_size=2)
    mesh = build_mesh(resolve_plan("pp", 8, pp_size=2))
    with context.use_mesh(mesh):
        model, tx, state, state_sh = create_train_state(mc, tc, mesh)
        step = make_train_step(model, tx, mc, tc, mesh, state_sh)
        # np.array: a zero-copy asarray view would alias the donated buffer
        bias0 = [np.array(b) for b in
                 jax.tree_util.tree_leaves(state.moe_state)]
        assert bias0 and bias0[0].shape[0] == KW["n_layer"]  # layer-stacked
        x = jax.random.randint(jax.random.PRNGKey(7), (1, 8, 32), 0, 96)
        y = jax.random.randint(jax.random.PRNGKey(8), (1, 8, 32), 0, 96)
        state, m = step(state, x, y)
        assert np.isfinite(float(m["loss"]))
        bias1 = jax.tree_util.tree_leaves(state.moe_state)
        assert any(not np.allclose(np.asarray(a), np.asarray(b))
                   for a, b in zip(bias0, bias1))


@pytest.mark.parametrize("policy", ["block", "attn"])
def test_pp_act_recomp_matches_plain(policy):
    """Remat under pp is a pure memory/FLOPs trade: same loss as plain pp
    (and hence as the loop oracle)."""
    loop_model, pp_model, variables, pp_params, idx, tgt = _models()
    cfg_r = LLMConfig(**KW, pp_stages=2, pp_microbatches=4,
                      act_recomp=True, act_recomp_policy=policy)
    _, loss_pp, _ = pp_model.apply({"params": pp_params}, idx, tgt)
    _, loss_r, _ = LLM(cfg_r).apply({"params": pp_params}, idx, tgt)
    np.testing.assert_allclose(float(loss_r), float(loss_pp), rtol=1e-6)


def test_pp_moe_eval_apply_without_mutable():
    """Read-only apply (eval/estimate_loss path — no mutable moe_state)
    must work under pp x moe: caught live in round 5 when the real-data
    run's first eval crashed with a scan-carry pytree mismatch (immutable
    collections drop out of the carry output). alpha=0 isolates the main
    loss — at M=2 the aux term is per-microbatch by design."""
    loop_model, pp_model, variables, pp_vars, idx, tgt = \
        _moe_models(2, alpha=0.0)
    _, loss_loop, _ = loop_model.apply(variables, idx, tgt)
    _, loss_pp, _ = pp_model.apply(pp_vars, idx, tgt)
    np.testing.assert_allclose(float(loss_pp), float(loss_loop), rtol=1e-6)


# ---------------------------------------------------------------------------
# Interleaved-1F1B schedule (ISSUE 19): a pure re-scheduling of the carry
# schedule — bitwise-identical loss, gradients equal up to backward
# reduction order — with the bubble on the static timeline within 20% of
# the (S-1)/(vpp*M) Megatron model.
# ---------------------------------------------------------------------------

from distributed_pytorch_tpu.models.pipeline import (  # noqa: E402
    _build_1f1b_schedule, resolve_schedule, resolve_vpp, schedule_timeline)


def _ab_models(schedule_a="carry", schedule_b="1f1b", m=8):
    cfg_a = LLMConfig(**KW, pp_stages=2, pp_microbatches=m,
                      pp_schedule=schedule_a)
    cfg_b = LLMConfig(**KW, pp_stages=2, pp_microbatches=m,
                      pp_schedule=schedule_b)
    idx = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 96)
    tgt = jax.random.randint(jax.random.PRNGKey(2), (8, 16), 0, 96)
    params = LLM(cfg_a).init(jax.random.PRNGKey(0), idx, tgt)["params"]
    return LLM(cfg_a), LLM(cfg_b), params, idx, tgt


def _bitwise_equal_trees(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return all(np.asarray(x).tobytes() == np.asarray(y).tobytes()
               for x, y in zip(la, lb))


def test_1f1b_loss_bitwise_equals_carry():
    m_carry, m_1f1b, params, idx, tgt = _ab_models()
    _, loss_c, _ = m_carry.apply({"params": params}, idx, tgt)
    _, loss_i, _ = m_1f1b.apply({"params": params}, idx, tgt)
    assert np.asarray(loss_c).tobytes() == np.asarray(loss_i).tobytes(), \
        f"1f1b loss {float(loss_i)!r} != carry loss {float(loss_c)!r}"


def test_1f1b_gradients_match_carry():
    # the forward is bitwise identical (test above), but the backward
    # accumulates cotangents through the interleaved hand-backs in a
    # different reduction order than the carry scan, so shared-parameter
    # gradients can differ in the last float32 ULPs — assert tight
    # allclose, not bytes
    m_carry, m_1f1b, params, idx, tgt = _ab_models()
    g_c = jax.grad(lambda p: m_carry.apply({"params": p}, idx, tgt)[1])(
        params)
    g_i = jax.grad(lambda p: m_1f1b.apply({"params": p}, idx, tgt)[1])(
        params)
    for x, y in zip(jax.tree_util.tree_leaves(g_c),
                    jax.tree_util.tree_leaves(g_i)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-5, atol=1e-7)


def test_1f1b_is_the_auto_schedule_when_admissible():
    cfg = LLMConfig(**KW, pp_stages=2)           # 4 % (2*vpp=4) == 0
    assert resolve_schedule(cfg) == "1f1b"
    assert resolve_vpp(cfg) == KW["n_layer"] // 2


def test_1f1b_auto_falls_back_to_carry_when_inadmissible():
    """MoE needs the carry schedule (moe_state rides the scan carry), so
    auto falls back silently and an explicit 1f1b ask fails loudly."""
    cfg = LLMConfig(**KW, pp_stages=2, moe=True, n_exp=4, n_shared=1,
                    n_act=2)
    assert resolve_schedule(cfg) == "carry"
    with pytest.raises(ValueError):
        resolve_schedule(dataclasses.replace(cfg, pp_schedule="1f1b"))


def test_pp_schedule_knob_overrides_config(monkeypatch):
    cfg = LLMConfig(**KW, pp_stages=2)
    monkeypatch.setenv("PP_SCHEDULE", "carry")
    assert resolve_schedule(cfg) == "carry"
    monkeypatch.delenv("PP_SCHEDULE")
    assert resolve_schedule(cfg) == "1f1b"


def test_1f1b_schedule_table_covers_every_chunk_microbatch_once():
    S, vpp, M = 2, 2, 8
    sched = _build_1f1b_schedule(S, vpp, M)
    seen = set()
    for t in range(sched.ticks):
        for s in range(S):
            if sched.valid[t, s]:
                key = (int(sched.q_idx[t, s]), int(sched.mb_idx[t, s]))
                assert key not in seen, f"duplicate work unit {key}"
                seen.add(key)
    assert len(seen) == S * vpp * M              # every (chunk, mb) once
    assert int(np.sum(sched.inject)) == M        # every mb injected once
    # each microbatch exits at the tick its LAST chunk runs
    for m in range(M):
        t = int(sched.exit_ticks[m])
        assert sched.valid[t].any()


@pytest.mark.parametrize("S,vpp,M", [(2, 2, 8), (4, 2, 8), (2, 4, 4)])
def test_1f1b_bubble_within_20pct_of_model(S, vpp, M):
    _, summary = schedule_timeline(S, vpp, M)
    frac, model = summary["bubble_frac"], summary["bubble_model"]
    assert abs(frac - model) / model <= 0.20, \
        f"measured bubble {frac} vs model {model}"


def test_1f1b_timeline_rows_interleave_chunks_per_stage():
    """Per-chunk interleaving on the phase rows: a stage alternates
    between its vpp virtual chunks across microbatches instead of
    draining one chunk's microbatches first (the interleave that shrinks
    warmup to (S-1)/vpp), and the backward half is the exact mirror —
    it interleaves the same chunks in reverse."""
    rows, summary = schedule_timeline(2, 2, 8)
    assert len(rows) == 2 * 2 * 2 * 8            # S * 2 phases * vpp * M
    stage0 = [r for r in rows if r["stage"] == 0]
    fwd = [r for r in stage0 if r["phase"] == "fwd"]
    bwd = [r for r in stage0 if r["phase"] == "bwd"]
    assert len(fwd) == len(bwd) == 2 * 8         # vpp * M each way
    # the chunk sequence must SWITCH chunks before finishing either one
    fwd_chunks = [r["chunk"] for r in fwd]
    first_switch = next(i for i, q in enumerate(fwd_chunks)
                        if q != fwd_chunks[0])
    assert first_switch < 8, "chunk 0 drained all microbatches first"
    assert fwd_chunks[0] in fwd_chunks[first_switch:], \
        "never returned to the first chunk: not interleaved"
    # mirror: bwd rows are the fwd rows reversed, same (chunk, mb) pairs
    assert [(r["chunk"], r["microbatch"]) for r in bwd] == \
        [(r["chunk"], r["microbatch"]) for r in reversed(fwd)]
