"""Config / CLI override system tests (reference single-gpu/train.py:
136-206): flag surface, generic routing onto the owning dataclass,
`--total_batch_size_str "2**14"` arithmetic evaluation, cross-field
attention normalization, act_recomp linking, and validation failures. The
reference has no tests for any of this (SURVEY.md §4)."""

import dataclasses

import pytest

from distributed_pytorch_tpu.config import (LLMConfig, TrainConfig,
                                            build_parser, configs_from_args,
                                            flagship_gpt124m)


def _parse(argv):
    args = build_parser().parse_args(argv)
    return configs_from_args(args)


def test_every_field_has_a_flag():
    """Flag surface covers both dataclasses (reference exposes ~33 flags;
    ours exposes all fields, a superset)."""
    parser = build_parser()
    flags = {a.dest for a in parser._actions}
    for cfg in (LLMConfig(), TrainConfig()):
        for f in dataclasses.fields(cfg):
            want = ("total_batch_size_str" if f.name == "total_batch_size"
                    else f.name)
            assert want in flags, f"missing --{want}"


def test_defaults_round_trip():
    mc, tc = _parse([])
    assert mc == LLMConfig()
    assert tc == TrainConfig()


def test_total_batch_size_str_expression():
    # reference eval()'s the string (train.py:186-188); ours is AST-gated
    _, tc = _parse(["--total_batch_size_str", "2**14"])
    assert tc.total_batch_size == 16384
    with pytest.raises(ValueError):
        _parse(["--total_batch_size_str", "__import__('os')"])


def test_routing_to_owning_dataclass():
    mc, tc = _parse(["--n_embd", "128", "--learning_rate", "1e-2",
                     "--attn", "MQA"])
    assert mc.n_embd == 128
    assert tc.learning_rate == pytest.approx(1e-2)
    assert mc.attn == "mqa"  # strings lowercased (reference train.py:192)


def test_non_linearity_case_preserved():
    # the reference exempts non_linearity from lowercasing; our ACTIVATIONS
    # check is case-insensitive but the value must pass through
    mc, _ = _parse(["--non_linearity", "SwiGLU"])
    assert mc.non_linearity == "SwiGLU"


def test_attention_normalization():
    # mha -> n_kv_heads = n_head; mqa -> 1 (reference train.py:198-206)
    mc, _ = _parse(["--attn", "mha", "--n_head", "8", "--n_kv_heads", "2"])
    assert mc.n_kv_heads == 8
    mc, _ = _parse(["--attn", "mqa", "--n_head", "8"])
    assert mc.n_kv_heads == 1


def test_act_recomp_linked_into_model_config():
    # train flag wins and is copied into the model config (train.py:189-190)
    mc, tc = _parse(["--act_recomp"])
    assert tc.act_recomp and mc.act_recomp


def test_bool_flags():
    mc, tc = _parse(["--moe", "--eval"])
    assert mc.moe and tc.eval
    # default-True flags expose --no-<name>
    _, tc = _parse(["--no-save_stats"])
    assert not tc.save_stats


def test_validation_failures():
    with pytest.raises(AssertionError):
        LLMConfig(attn="gqa", n_head=8, n_kv_heads=3)
    with pytest.raises(ValueError):
        LLMConfig(attn="nope")
    with pytest.raises(AssertionError):
        LLMConfig(loss_chunk=100)          # must divide block_size
    with pytest.raises(AssertionError):
        LLMConfig(n_layer=6, pp_stages=4)  # must divide n_layer
    # pp x moe is SUPPORTED since round 5 (models/pipeline.py)
    assert LLMConfig(moe=True, pp_stages=2, n_layer=4).moe
    with pytest.raises(AssertionError):
        TrainConfig(parallelism="5d")


def test_parallelism_and_axis_flags():
    _, tc = _parse(["--parallelism", "pp", "--pp_size", "2",
                    "--tp_size", "2"])
    assert tc.parallelism == "pp" and tc.pp_size == 2 and tc.tp_size == 2


def test_flagship_config():
    c = flagship_gpt124m()
    assert (c.n_embd, c.n_layer, c.n_head) == (768, 12, 12)
    c2 = flagship_gpt124m(act_recomp=True)
    assert c2.act_recomp and c2.n_embd == 768


def test_cli_main_smoke(tmp_path, monkeypatch):
    """End-to-end `python -m distributed_pytorch_tpu` on a tiny synthetic
    run: the five reference trainer invocations collapsed into one CLI."""
    monkeypatch.chdir(tmp_path)
    from distributed_pytorch_tpu.__main__ import main
    main(["--dataset", "synthetic", "--data_dir", str(tmp_path),
          "--vocab_size", "256", "--block_size", "32", "--n_embd", "32",
          "--n_head", "4", "--n_kv_heads", "2", "--n_layer", "2",
          "--up_dim", "48", "--max_iters", "3", "--batch_size", "2",
          "--total_batch_size_str", "8*2*32", "--parallelism", "dp",
          "--no-save_stats"])
