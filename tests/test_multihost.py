"""Real multi-process training test: two coordinated JAX processes (Gloo
over localhost), each with 4 CPU devices, train fsdp on the 8-device global
mesh. This is the capability the reference gets from torchrun + NCCL
(multi-gpu/ddp/train.py:19-25) and the row SURVEY/VERDICT marked 'never
executed multi-process anywhere' — and it caught a real bug: in jax 0.9,
`jax.distributed.initialize()` only auto-detects TPU/Slurm/MPI, so the
explicit JAX_* env convention must be forwarded as arguments
(train/loop.py maybe_initialize_distributed)."""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

# One worker script serves both legs: the 2-process run (MH_DEVICES=4 per
# process) and the single-process oracle (MH_DEVICES=8, no JAX_* env) —
# the experiment definition cannot drift between them.
_WORKER = textwrap.dedent("""
    import os, sys, json
    sys.path.insert(0, __REPO__)
    import jax
    jax.config.update("jax_platforms", "cpu")
    from distributed_pytorch_tpu import compat
    compat.request_cpu_devices(int(os.environ["MH_DEVICES"]))
    from distributed_pytorch_tpu.config import LLMConfig, TrainConfig
    from distributed_pytorch_tpu.train.loop import train

    mc = LLMConfig(vocab_size=256, block_size=32, n_embd=32, n_head=4,
                   n_kv_heads=2, n_layer=2, up_dim=48)
    tc = TrainConfig(dataset="synthetic", data_dir=os.environ["MH_DATA"],
                     total_batch_size=8 * 1 * 32, batch_size=1, max_iters=3,
                     parallelism="fsdp", save_stats=False)
    stats = train(mc, tc, log=lambda s: None)
    print(json.dumps({"procs": jax.process_count(),
                      "devices": len(jax.devices()),
                      "losses": stats["train_losses"]}))
""")


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_training_matches_single(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER.replace("__REPO__", repr(repo)))
    data_dir = str(tmp_path / "data")
    port = _free_port()  # fixed ports collide across concurrent runs

    def run(pid):
        env = dict(os.environ,
                   JAX_COORDINATOR_ADDRESS=f"localhost:{port}",
                   JAX_NUM_PROCESSES="2", JAX_PROCESS_ID=str(pid),
                   MH_DATA=data_dir, MH_DEVICES="4",
                   PYTHONPATH=repo + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        # workers pin their own platform/devices; drop the suite's env
        env.pop("JAX_PLATFORMS", None)
        env.pop("XLA_FLAGS", None)
        return subprocess.Popen([sys.executable, str(worker)], env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE)

    procs = [run(0), run(1)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, err.decode()[-2000:]
            import json
            outs.append(json.loads(out.decode().strip().splitlines()[-1]))
    finally:
        for p in procs:  # a failure above must not leak a blocked worker
            if p.poll() is None:
                p.kill()
                p.wait()

    for o in outs:
        assert o["procs"] == 2, f"processes ran disconnected: {o}"
        assert o["devices"] == 8
    # both processes observe the same global loss trajectory...
    assert outs[0]["losses"] == outs[1]["losses"]

    # ...and it equals the single-process 8-device run of the SAME worker
    # script (no JAX_* env, MH_DEVICES=8): the counter-based loader + GSPMD
    # make the math process-count-invariant (the reference's +rank seed
    # offsets cannot offer this).
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS",
                        "JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                        "JAX_PROCESS_ID")}
    env.update(MH_DATA=data_dir, MH_DEVICES="8",
               PYTHONPATH=repo + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    single = subprocess.run([sys.executable, str(worker)],
                            capture_output=True, timeout=300, env=env)
    assert single.returncode == 0, single.stderr.decode()[-2000:]
    import json
    oracle = json.loads(single.stdout.decode().strip().splitlines()[-1])
    assert oracle["procs"] == 1 and oracle["devices"] == 8
    np.testing.assert_allclose(outs[0]["losses"], oracle["losses"],
                               rtol=2e-4)
