"""Collective-matmul tests (ops/collective_matmul.py): the ppermute-ring
all-gather ⊗ matmul / matmul ⊗ reduce-scatter primitives must be exact
(fwd AND grad) against the plain GSPMD einsum on the 8-device CPU mesh,
for both ring directions, and the full OVERLAP=on train step must
reproduce the single-device oracle for every ZeRO-3 recipe with and
without grad accumulation (rings at accum=1, hoisted gathers at accum>1).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding

from distributed_pytorch_tpu.config import LLMConfig, TrainConfig
from distributed_pytorch_tpu.ops import collective_matmul as cm
from distributed_pytorch_tpu.parallel import context
from distributed_pytorch_tpu.parallel import sharding as shd
from distributed_pytorch_tpu.parallel.mesh import build_mesh, resolve_plan

TINY = dict(vocab_size=128, block_size=32, n_embd=32, n_head=4,
            n_kv_heads=2, n_layer=2, up_dim=64)


@pytest.fixture()
def overlap_on(monkeypatch):
    monkeypatch.setenv("OVERLAP", "on")
    yield
    # env restored by monkeypatch


def _fsdp_mesh():
    return build_mesh(resolve_plan("fsdp", 8))


# ---------------------------------------------------------------------------
# primitive parity: fwd + grads vs the plain matmul, all shard layouts
# ---------------------------------------------------------------------------

CASES = [
    # (names, w shape, transpose_b): c_fc shards its OUTPUT dim over
    # 'data' (N-ring), c_proj its contraction dim (K-ring), the embedding
    # rings vocab slices of the transposed lm-head matmul; the attention
    # projections (round 7: routed via _OverlapDense, models/attention.py)
    # ring whatever axis the fsdp table picked for their kernels
    (("c_fc",), (32, 96), False),
    (("c_proj",), (64, 32), False),
    (("tkn_emb", "embedding"), (128, 32), True),
    (("c_attn", "kernel"), (32, 64), False),
    (("c_proj", "kernel"), (32, 32), False),
]


@pytest.mark.parametrize("ring", ["uni", "bidir"])
@pytest.mark.parametrize("names,wshape,tb", CASES,
                         ids=["c_fc", "c_proj", "lm_head", "attn_qkv",
                              "attn_out"])
def test_ring_matches_plain_matmul(monkeypatch, ring, names, wshape, tb):
    monkeypatch.setenv("OVERLAP", "on")
    monkeypatch.setenv("OVERLAP_RING", ring)
    mesh = _fsdp_mesh()
    k = wshape[1] if tb else wshape[0]
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 16, k))
    w = jax.random.normal(jax.random.PRNGKey(1), wshape)

    def ringed(x, w):
        y = cm.maybe_overlap_matmul(x, w, names=names, transpose_b=tb)
        assert y is not None, "dispatcher declined a qualifying matmul"
        return y

    def plain(x, w):
        return x @ (w.T if tb else w)

    with context.use_mesh(mesh), context.use_overlap("on", "fsdp"):
        y = jax.jit(ringed)(x, w)
        gx, gw = jax.jit(jax.grad(
            lambda x, w: (ringed(x, w) ** 2).sum(), argnums=(0, 1)))(x, w)
    y0 = plain(x, w)
    gx0, gw0 = jax.grad(
        lambda x, w: (plain(x, w) ** 2).sum(), argnums=(0, 1))(x, w)
    # forward is summation-order-exact to f32 ulps; grads carry value-
    # dependent cotangents (**2 loss) where ring vs single-matmul
    # accumulation order differs in the last ulp, hence the wider band
    np.testing.assert_allclose(np.asarray(y), np.asarray(y0),
                               rtol=2e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx0),
                               rtol=2e-3, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(gw0),
                               rtol=2e-3, atol=1e-4)


def test_dispatcher_declines_without_optin(monkeypatch):
    """OVERLAP unset/auto or a non-ZeRO-3 recipe must leave the caller on
    the plain GSPMD path (None) — 'auto' is the known-good default until a
    hardware number exists."""
    monkeypatch.delenv("OVERLAP", raising=False)
    mesh = _fsdp_mesh()
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 16, 64))
    w = jax.random.normal(jax.random.PRNGKey(1), (64, 32))
    with context.use_mesh(mesh), context.use_overlap("auto", "fsdp"):
        assert cm.maybe_overlap_matmul(x, w, names=("c_proj",)) is None
    with context.use_mesh(mesh), context.use_overlap("on", "dp"):
        assert cm.maybe_overlap_matmul(x, w, names=("c_proj",)) is None
    monkeypatch.setenv("OVERLAP", "off")
    with context.use_mesh(mesh), context.use_overlap("on", "fsdp"):
        assert cm.maybe_overlap_matmul(x, w, names=("c_proj",)) is None


def test_dispatcher_declines_inside_hoisted_scan(monkeypatch):
    monkeypatch.setenv("OVERLAP", "on")
    mesh = _fsdp_mesh()
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 16, 64))
    w = jax.random.normal(jax.random.PRNGKey(1), (64, 32))
    with context.use_mesh(mesh), context.use_overlap("on", "fsdp"), \
            context.hoisted_gathers(True):
        assert cm.maybe_overlap_matmul(x, w, names=("c_proj",)) is None


def test_resolve_mode_env_wins(monkeypatch):
    monkeypatch.setenv("OVERLAP", "on")
    assert cm.resolve_mode("off") == "on"
    monkeypatch.setenv("OVERLAP", "off")
    assert cm.resolve_mode("on") == "off"
    monkeypatch.delenv("OVERLAP", raising=False)
    assert cm.resolve_mode("auto") == cm._AUTO_RESOLVES_TO
    with pytest.raises(ValueError):
        cm.resolve_mode("sideways")


# ---------------------------------------------------------------------------
# end-to-end: OVERLAP=on train step == single-device oracle
# ---------------------------------------------------------------------------

def _batch(mc, accum, B, seed=11):
    rng = np.random.default_rng(seed)
    starts = rng.integers(0, mc.vocab_size, size=(accum, B, 1))
    seq = (starts + np.arange(mc.block_size + 1)) % mc.vocab_size
    return (np.asarray(seq[..., :-1], np.int32),
            np.asarray(seq[..., 1:], np.int32))


def _run(mc, recipe, mesh, accum, **kw):
    from distributed_pytorch_tpu.train.state import create_train_state
    from distributed_pytorch_tpu.train.step import make_train_step
    tc = TrainConfig(total_batch_size=accum * 8 * 32 // 2, batch_size=1,
                     learning_rate=1e-3, warmup_steps=2,
                     parallelism=recipe, **kw)
    model, tx, state, sh = create_train_state(mc, tc, mesh)
    step = make_train_step(model, tx, mc, tc, mesh, sh)
    x, y = _batch(mc, accum, 8)
    if mesh is not None:
        bsh = NamedSharding(mesh, shd.batch_pspec(recipe, mesh,
                                                  leading_accum=True))
        x = jax.device_put(jnp.asarray(x), bsh)
        y = jax.device_put(jnp.asarray(y), bsh)
    losses = []
    for _ in range(2):
        state, m = step(state, x, y)
        losses.append(float(m["loss"]))
    return losses


OVERLAP_RECIPES = [("fsdp", {}), ("fsdp_tp", {"tp_size": 2}),
                   ("sp", {"sp_size": 2})]


@pytest.mark.parametrize("accum", [1, 2], ids=["rings", "hoisted_accum"])
@pytest.mark.parametrize("recipe,kw", OVERLAP_RECIPES,
                         ids=[r[0] for r in OVERLAP_RECIPES])
def test_overlap_step_matches_oracle(overlap_on, recipe, kw, accum):
    """Loss parity (<= 1e-5 rel, acceptance bar 2e-4) of the OVERLAP=on
    step against the single-device oracle: accum=1 exercises the in-model
    rings (MLP + lm-head), accum=2 the hoisted-gather path with per-micro-
    step reduce-scattered grads."""
    mc = LLMConfig(**TINY)
    oracle = _run(mc, "single", None, accum)
    mesh = build_mesh(resolve_plan(
        recipe, 8, tp_size=kw.get("tp_size", 1),
        sp_size=kw.get("sp_size", 1)))
    losses = _run(mc, recipe, mesh, accum, **kw)
    np.testing.assert_allclose(losses, oracle, rtol=2e-4,
                               err_msg=f"{recipe} overlap diverged")


def test_overlap_rings_actually_engage(monkeypatch):
    """Guard against the dispatcher silently declining everywhere (which
    would make the parity suite vacuous): under OVERLAP=on + fsdp mesh the
    MLP matmuls AND the attention projections (c_attn / attention c_proj,
    the round-7 call sites) must take the ring path."""
    monkeypatch.setenv("OVERLAP", "on")
    calls = []
    seen_names = []
    orig = cm._build_cm
    orig_dispatch = cm.maybe_overlap_matmul

    def spy(*a, **k):
        calls.append(a)
        return orig(*a, **k)

    def spy_dispatch(x, w, *, names, **k):
        y = orig_dispatch(x, w, names=names, **k)
        if y is not None:
            seen_names.append(names)
        return y

    monkeypatch.setattr(cm, "_build_cm", spy)
    monkeypatch.setattr(cm, "maybe_overlap_matmul", spy_dispatch)
    # the model modules import the dispatcher lazily from the module, so
    # the monkeypatched symbol is what they call
    mc = LLMConfig(**TINY)
    mesh = _fsdp_mesh()
    _run(mc, "fsdp", mesh, 1)
    assert calls, "OVERLAP=on fsdp step never reached the ring builder"
    assert ("c_attn", "kernel") in seen_names, \
        "fused qkv projection never rang (attention overlap call site)"
    assert ("c_proj", "kernel") in seen_names, \
        "attention out-projection never rang"
