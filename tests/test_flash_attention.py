"""Pallas flash-attention kernel vs the naive einsum oracle.

Runs the kernel in interpret mode (no TPU needed) and checks forward and
backward numerics against `_naive_sdpa` — the reference-semantics path
(reference model.py:149 SDPA / :225-226 causal mask).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.ops.attention_core import _naive_sdpa
from distributed_pytorch_tpu.ops.flash_attention import (
    flash_attention, flash_attention_usable)


def rand_qkv(key, B, T, S, nh, nkv, hs, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, T, nh, hs), dtype)
    k = jax.random.normal(kk, (B, S, nkv, hs), dtype)
    v = jax.random.normal(kv, (B, S, nkv, hs), dtype)
    return q, k, v


CASES = [
    # (T, S, nh, nkv, hs, block)
    (128, 128, 4, 4, 32, 64),     # MHA, small head dim
    (256, 256, 4, 2, 64, 128),    # GQA group 2
    (128, 128, 4, 1, 64, 64),     # MQA
    (64, 256, 2, 2, 64, 64),      # prefill: S > T (cache buffer tail masked)
    (96, 96, 2, 2, 64, 32),       # non-power-of-two T, odd block split
]


@pytest.mark.parametrize("T,S,nh,nkv,hs,block", CASES)
def test_forward_matches_naive(T, S, nh, nkv, hs, block):
    q, k, v = rand_qkv(jax.random.PRNGKey(0), 2, T, S, nh, nkv, hs)
    scale = 1.0 / hs ** 0.5
    out = flash_attention(q, k, v, scale=scale, block_q=block, block_k=block,
                          interpret=True)
    ref = _naive_sdpa(q, k, v, scale=scale, q_offset=0, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_backward_matches_naive():
    T, nh, nkv, hs = 128, 4, 2, 64
    q, k, v = rand_qkv(jax.random.PRNGKey(1), 2, T, T, nh, nkv, hs)
    scale = 1.0 / hs ** 0.5
    w = jax.random.normal(jax.random.PRNGKey(2), q.shape)

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, scale=scale, block_q=64, block_k=64,
                              interpret=True)
        return jnp.sum(out * w)

    def loss_naive(q, k, v):
        return jnp.sum(_naive_sdpa(q, k, v, scale=scale, q_offset=0,
                                   causal=True) * w)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gn, "q k v".split()):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-4, err_msg=f"d{name} mismatch")


def test_bf16_forward_close():
    T, nh, hs = 128, 2, 64
    q, k, v = rand_qkv(jax.random.PRNGKey(3), 1, T, T, nh, nh, hs,
                       dtype=jnp.bfloat16)
    scale = 1.0 / hs ** 0.5
    out = flash_attention(q, k, v, scale=scale, block_q=64, block_k=64,
                          interpret=True)
    ref = _naive_sdpa(q, k, v, scale=scale, q_offset=0, causal=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_usable_gate():
    q, k, v = rand_qkv(jax.random.PRNGKey(0), 1, 128, 128, 2, 2, 64)
    assert flash_attention_usable(q, k, v, causal=True)
    # round 4: the kernel grew a full-attention mode (ring off-diagonal
    # chunks), so non-causal shapes are usable too
    assert flash_attention_usable(q, k, v, causal=False)
    # decode-step shape: single query row -> naive path
    assert not flash_attention_usable(q[:, :1], k, v, causal=True)
    # fp16 not supported on TPU path
    assert not flash_attention_usable(
        q.astype(jnp.float16), k.astype(jnp.float16), v.astype(jnp.float16),
        causal=True)


def test_model_trains_with_pallas_interpret(monkeypatch):
    """End-to-end: the GQA module routed through the pallas impl (interpret
    mode via monkeypatched pallas_call) matches the xla impl."""
    import distributed_pytorch_tpu.ops.flash_attention as fa
    import jax.experimental.pallas as pl

    orig = pl.pallas_call
    monkeypatch.setattr(
        fa.pl, "pallas_call",
        lambda *a, **kw: orig(*a, **{**kw, "interpret": True}))
    # force the dispatcher to believe pallas is available
    import distributed_pytorch_tpu.ops.attention_core as core
    monkeypatch.setattr(core, "_on_tpu", lambda: True)

    from distributed_pytorch_tpu.config import LLMConfig
    from distributed_pytorch_tpu.models.gpt import LLM

    cfg = LLMConfig(vocab_size=128, block_size=64, n_embd=64, n_head=4,
                    n_kv_heads=2, attn="gqa", n_layer=2, up_dim=128,
                    non_linearity="swiglu", pos_emb="rope")
    x = jax.random.randint(jax.random.PRNGKey(0), (2, 64), 0, 128, jnp.int32)

    def run(impl):
        model = LLM(cfg, attn_impl=impl)
        variables = model.init(jax.random.PRNGKey(5), x, x)

        def loss(params):
            _, l, _ = model.apply({"params": params}, x, x)
            return l
        l, g = jax.value_and_grad(loss)(variables["params"])
        return l, g

    l_p, g_p = run("pallas")
    l_x, g_x = run("xla")
    np.testing.assert_allclose(float(l_p), float(l_x), rtol=1e-5)
    flat_p = jax.tree_util.tree_leaves(g_p)
    flat_x = jax.tree_util.tree_leaves(g_x)
    for a, b in zip(flat_p, flat_x):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4,
                                   atol=5e-4)


def _naive_out_lse(q, k, v, scale, causal):
    nh, nkv = q.shape[2], k.shape[2]
    if nkv != nh:
        k = jnp.repeat(k, nh // nkv, axis=2)
        v = jnp.repeat(v, nh // nkv, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        T, S = q.shape[1], k.shape[1]
        mask = jnp.arange(T)[:, None] >= jnp.arange(S)[None, :]
        s = jnp.where(mask[None, None], s, -1e30)
    lse = jax.nn.logsumexp(s, axis=-1)                    # (B,H,T)
    p = jnp.exp(s - lse[..., None])
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out, jnp.transpose(lse, (0, 2, 1))             # BTNH, (B,T,H)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_lse_matches_naive(causal):
    """(out, lse) parity for both masking modes — lse is the ring merge's
    contract (ops/ring_attention.py)."""
    from distributed_pytorch_tpu.ops.flash_attention import flash_attention_lse
    q, k, v = rand_qkv(jax.random.PRNGKey(3), 2, 64, 64, 4, 2, 16)
    scale = 0.25
    ref_o, ref_l = _naive_out_lse(q, k, v, scale, causal)
    out, lse = flash_attention_lse(q, k, v, scale=scale, causal=causal,
                                   interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_o),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_l),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_lse_gradients_including_dlse(causal):
    """A loss that touches BOTH outputs: the custom vjp must fold d/dlse
    into the delta term correctly (ds = p*(dp - delta + dlse))."""
    from distributed_pytorch_tpu.ops.flash_attention import flash_attention_lse
    q, k, v = rand_qkv(jax.random.PRNGKey(4), 1, 32, 32, 2, 2, 16)
    scale = 0.25
    w = jax.random.normal(jax.random.PRNGKey(5), q.shape)
    u = jax.random.normal(jax.random.PRNGKey(6), (1, 32, 2))

    def loss_flash(q, k, v):
        o, l = flash_attention_lse(q, k, v, scale=scale, causal=causal,
                                   interpret=True)
        return jnp.sum(o * w) + jnp.sum(l * u)

    def loss_naive(q, k, v):
        o, l = _naive_out_lse(q, k, v, scale, causal)
        return jnp.sum(o * w) + jnp.sum(l * u)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gn, "q k v".split()):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-4, err_msg=f"d{name}")


@pytest.mark.parametrize("bq,bk", [(32, 64), (64, 32), (128, 64)])
def test_rectangular_blocks_fwd_bwd(bq, bk):
    """block_q != block_k exercises the causal-frontier math on
    rectangular tiles (_last_visible_kv/_first_visible_q and the
    DMA-clamp index maps) — the production default is 256x512."""
    T, nh, nkv, hs = 128, 4, 2, 32
    q, k, v = rand_qkv(jax.random.PRNGKey(5), 2, T, T, nh, nkv, hs)
    scale = 1.0 / hs ** 0.5
    w = jax.random.normal(jax.random.PRNGKey(6), q.shape)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) * w)

    flash = loss(lambda q, k, v: flash_attention(
        q, k, v, scale=scale, block_q=bq, block_k=bk, interpret=True))
    naive = loss(lambda q, k, v: _naive_sdpa(
        q, k, v, scale=scale, q_offset=0, causal=True))
    np.testing.assert_allclose(np.asarray(flash(q, k, v)),
                               np.asarray(naive(q, k, v)),
                               rtol=2e-4, atol=2e-4)
    g_f = jax.grad(flash, argnums=(0, 1, 2))(q, k, v)
    g_n = jax.grad(naive, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_f, g_n):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("bh", [2, 4, 8])
def test_row_group_blocking_fwd_bwd(bh):
    """block_h > 1 batches several (batch, head) rows per grid step (the
    grid-overhead fix, PERF.md round 4); MHA only — parity incl. grads."""
    B, T, nh, hs = 2, 128, 4, 32
    q, k, v = rand_qkv(jax.random.PRNGKey(7), B, T, T, nh, nh, hs)
    scale = 1.0 / hs ** 0.5
    w = jax.random.normal(jax.random.PRNGKey(8), q.shape)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) * w)

    flash = loss(lambda q, k, v: flash_attention(
        q, k, v, scale=scale, block_q=64, block_k=64, block_h=bh,
        interpret=True))
    naive = loss(lambda q, k, v: _naive_sdpa(
        q, k, v, scale=scale, q_offset=0, causal=True))
    np.testing.assert_allclose(np.asarray(flash(q, k, v)),
                               np.asarray(naive(q, k, v)),
                               rtol=2e-4, atol=2e-4)
    g_f = jax.grad(flash, argnums=(0, 1, 2))(q, k, v)
    g_n = jax.grad(naive, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_f, g_n):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_row_group_defaults_to_one_for_gqa():
    """GQA (rep > 1) must not group rows (kv tiles would need strides):
    the default picks g=1 and an explicit block_h > 1 fails loudly."""
    q, k, v = rand_qkv(jax.random.PRNGKey(9), 2, 64, 64, 4, 2, 32)
    out = flash_attention(q, k, v, scale=0.18, block_q=32, block_k=32,
                          interpret=True)  # default g -> 1, works
    ref = _naive_sdpa(q, k, v, scale=0.18, q_offset=0, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    with pytest.raises(AssertionError):
        flash_attention(q, k, v, scale=0.18, block_q=32, block_k=32,
                        block_h=4, interpret=True)
