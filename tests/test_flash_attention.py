"""Pallas flash-attention kernel vs the naive einsum oracle.

Runs the kernel in interpret mode (no TPU needed) and checks forward and
backward numerics against `_naive_sdpa` — the reference-semantics path
(reference model.py:149 SDPA / :225-226 causal mask).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.ops.attention_core import _naive_sdpa
from distributed_pytorch_tpu.ops.flash_attention import (
    flash_attention, flash_attention_usable)


def rand_qkv(key, B, T, S, nh, nkv, hs, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, T, nh, hs), dtype)
    k = jax.random.normal(kk, (B, S, nkv, hs), dtype)
    v = jax.random.normal(kv, (B, S, nkv, hs), dtype)
    return q, k, v


CASES = [
    # (T, S, nh, nkv, hs, block)
    (128, 128, 4, 4, 32, 64),     # MHA, small head dim
    (256, 256, 4, 2, 64, 128),    # GQA group 2
    (128, 128, 4, 1, 64, 64),     # MQA
    (64, 256, 2, 2, 64, 64),      # prefill: S > T (cache buffer tail masked)
    (96, 96, 2, 2, 64, 32),       # non-power-of-two T, odd block split
]


@pytest.mark.parametrize("T,S,nh,nkv,hs,block", CASES)
def test_forward_matches_naive(T, S, nh, nkv, hs, block):
    q, k, v = rand_qkv(jax.random.PRNGKey(0), 2, T, S, nh, nkv, hs)
    scale = 1.0 / hs ** 0.5
    out = flash_attention(q, k, v, scale=scale, block_q=block, block_k=block,
                          interpret=True)
    ref = _naive_sdpa(q, k, v, scale=scale, q_offset=0, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_backward_matches_naive():
    T, nh, nkv, hs = 128, 4, 2, 64
    q, k, v = rand_qkv(jax.random.PRNGKey(1), 2, T, T, nh, nkv, hs)
    scale = 1.0 / hs ** 0.5
    w = jax.random.normal(jax.random.PRNGKey(2), q.shape)

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, scale=scale, block_q=64, block_k=64,
                              interpret=True)
        return jnp.sum(out * w)

    def loss_naive(q, k, v):
        return jnp.sum(_naive_sdpa(q, k, v, scale=scale, q_offset=0,
                                   causal=True) * w)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gn, "q k v".split()):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-4, err_msg=f"d{name} mismatch")


def test_bf16_forward_close():
    T, nh, hs = 128, 2, 64
    q, k, v = rand_qkv(jax.random.PRNGKey(3), 1, T, T, nh, nh, hs,
                       dtype=jnp.bfloat16)
    scale = 1.0 / hs ** 0.5
    out = flash_attention(q, k, v, scale=scale, block_q=64, block_k=64,
                          interpret=True)
    ref = _naive_sdpa(q, k, v, scale=scale, q_offset=0, causal=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_usable_gate():
    q, k, v = rand_qkv(jax.random.PRNGKey(0), 1, 128, 128, 2, 2, 64)
    assert flash_attention_usable(q, k, v, causal=True)
    # round 4: the kernel grew a full-attention mode (ring off-diagonal
    # chunks), so non-causal shapes are usable too
    assert flash_attention_usable(q, k, v, causal=False)
    # decode-step shape: single query row -> naive path
    assert not flash_attention_usable(q[:, :1], k, v, causal=True)
    # fp16 not supported on TPU path
    assert not flash_attention_usable(
        q.astype(jnp.float16), k.astype(jnp.float16), v.astype(jnp.float16),
        causal=True)


def test_model_trains_with_pallas_interpret(monkeypatch):
    """End-to-end: the GQA module routed through the pallas impl (interpret
    mode via monkeypatched pallas_call) matches the xla impl."""
    import distributed_pytorch_tpu.ops.flash_attention as fa
    import jax.experimental.pallas as pl

    orig = pl.pallas_call
    monkeypatch.setattr(
        fa.pl, "pallas_call",
        lambda *a, **kw: orig(*a, **{**kw, "interpret": True}))
    # force the dispatcher to believe pallas is available
    import distributed_pytorch_tpu.ops.attention_core as core
    monkeypatch.setattr(core, "_on_tpu", lambda: True)

    from distributed_pytorch_tpu.config import LLMConfig
    from distributed_pytorch_tpu.models.gpt import LLM

    cfg = LLMConfig(vocab_size=128, block_size=64, n_embd=64, n_head=4,
                    n_kv_heads=2, attn="gqa", n_layer=2, up_dim=128,
                    non_linearity="swiglu", pos_emb="rope")
    x = jax.random.randint(jax.random.PRNGKey(0), (2, 64), 0, 128, jnp.int32)

    def run(impl):
        model = LLM(cfg, attn_impl=impl)
        variables = model.init(jax.random.PRNGKey(5), x, x)

        def loss(params):
            _, l, _ = model.apply({"params": params}, x, x)
            return l
        l, g = jax.value_and_grad(loss)(variables["params"])
        return l, g

    l_p, g_p = run("pallas")
    l_x, g_x = run("xla")
    np.testing.assert_allclose(float(l_p), float(l_x), rtol=1e-5)
    flat_p = jax.tree_util.tree_leaves(g_p)
    flat_x = jax.tree_util.tree_leaves(g_x)
    for a, b in zip(flat_p, flat_x):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4,
                                   atol=5e-4)


def _naive_out_lse(q, k, v, scale, causal):
    nh, nkv = q.shape[2], k.shape[2]
    if nkv != nh:
        k = jnp.repeat(k, nh // nkv, axis=2)
        v = jnp.repeat(v, nh // nkv, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        T, S = q.shape[1], k.shape[1]
        mask = jnp.arange(T)[:, None] >= jnp.arange(S)[None, :]
        s = jnp.where(mask[None, None], s, -1e30)
    lse = jax.nn.logsumexp(s, axis=-1)                    # (B,H,T)
    p = jnp.exp(s - lse[..., None])
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out, jnp.transpose(lse, (0, 2, 1))             # BTNH, (B,T,H)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_lse_matches_naive(causal):
    """(out, lse) parity for both masking modes — lse is the ring merge's
    contract (ops/ring_attention.py)."""
    from distributed_pytorch_tpu.ops.flash_attention import flash_attention_lse
    q, k, v = rand_qkv(jax.random.PRNGKey(3), 2, 64, 64, 4, 2, 16)
    scale = 0.25
    ref_o, ref_l = _naive_out_lse(q, k, v, scale, causal)
    out, lse = flash_attention_lse(q, k, v, scale=scale, causal=causal,
                                   interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_o),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_l),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_lse_gradients_including_dlse(causal):
    """A loss that touches BOTH outputs: the custom vjp must fold d/dlse
    into the delta term correctly (ds = p*(dp - delta + dlse))."""
    from distributed_pytorch_tpu.ops.flash_attention import flash_attention_lse
    q, k, v = rand_qkv(jax.random.PRNGKey(4), 1, 32, 32, 2, 2, 16)
    scale = 0.25
    w = jax.random.normal(jax.random.PRNGKey(5), q.shape)
    u = jax.random.normal(jax.random.PRNGKey(6), (1, 32, 2))

    def loss_flash(q, k, v):
        o, l = flash_attention_lse(q, k, v, scale=scale, causal=causal,
                                   interpret=True)
        return jnp.sum(o * w) + jnp.sum(l * u)

    def loss_naive(q, k, v):
        o, l = _naive_out_lse(q, k, v, scale, causal)
        return jnp.sum(o * w) + jnp.sum(l * u)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gn, "q k v".split()):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-4, err_msg=f"d{name}")


@pytest.mark.parametrize("bq,bk", [(32, 64), (64, 32), (128, 64)])
def test_rectangular_blocks_fwd_bwd(bq, bk):
    """block_q != block_k exercises the causal-frontier math on
    rectangular tiles (_last_visible_kv/_first_visible_q and the
    DMA-clamp index maps) — the production default is 256x512."""
    T, nh, nkv, hs = 128, 4, 2, 32
    q, k, v = rand_qkv(jax.random.PRNGKey(5), 2, T, T, nh, nkv, hs)
    scale = 1.0 / hs ** 0.5
    w = jax.random.normal(jax.random.PRNGKey(6), q.shape)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) * w)

    flash = loss(lambda q, k, v: flash_attention(
        q, k, v, scale=scale, block_q=bq, block_k=bk, interpret=True))
    naive = loss(lambda q, k, v: _naive_sdpa(
        q, k, v, scale=scale, q_offset=0, causal=True))
    np.testing.assert_allclose(np.asarray(flash(q, k, v)),
                               np.asarray(naive(q, k, v)),
                               rtol=2e-4, atol=2e-4)
    g_f = jax.grad(flash, argnums=(0, 1, 2))(q, k, v)
    g_n = jax.grad(naive, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_f, g_n):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("bh", [2, 4, 8])
def test_row_group_blocking_fwd_bwd(bh):
    """block_h > 1 batches several (batch, head) rows per grid step (the
    grid-overhead fix, PERF.md round 4); MHA only — parity incl. grads."""
    B, T, nh, hs = 2, 128, 4, 32
    q, k, v = rand_qkv(jax.random.PRNGKey(7), B, T, T, nh, nh, hs)
    scale = 1.0 / hs ** 0.5
    w = jax.random.normal(jax.random.PRNGKey(8), q.shape)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) * w)

    flash = loss(lambda q, k, v: flash_attention(
        q, k, v, scale=scale, block_q=64, block_k=64, block_h=bh,
        interpret=True))
    naive = loss(lambda q, k, v: _naive_sdpa(
        q, k, v, scale=scale, q_offset=0, causal=True))
    np.testing.assert_allclose(np.asarray(flash(q, k, v)),
                               np.asarray(naive(q, k, v)),
                               rtol=2e-4, atol=2e-4)
    g_f = jax.grad(flash, argnums=(0, 1, 2))(q, k, v)
    g_n = jax.grad(naive, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_f, g_n):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_row_group_defaults_to_one_for_gqa():
    """GQA (rep > 1) must not group rows (kv tiles would need strides):
    the default picks g=1 and an explicit block_h > 1 fails loudly."""
    q, k, v = rand_qkv(jax.random.PRNGKey(9), 2, 64, 64, 4, 2, 32)
    out = flash_attention(q, k, v, scale=0.18, block_q=32, block_k=32,
                          interpret=True)  # default g -> 1, works
    ref = _naive_sdpa(q, k, v, scale=0.18, q_offset=0, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    with pytest.raises(AssertionError):
        flash_attention(q, k, v, scale=0.18, block_q=32, block_k=32,
                        block_h=4, interpret=True)


class TestDropout:
    """In-kernel attention-weight dropout (round 5; reference
    model.py:149-151 SDPA dropout). The mask is regenerated from the tile
    coordinates in forward and both backward kernels, so the strongest
    check is jax.test_util.check_grads: finite differences validate the
    custom VJP against the (deterministic, seeded) forward itself."""

    def test_rate_zero_identical(self):
        q, k, v = rand_qkv(jax.random.PRNGKey(3), 2, 64, 64, 4, 4, 32)
        base = flash_attention(q, k, v, scale=0.18, block_q=32, block_k=32,
                               interpret=True)
        zero = flash_attention(q, k, v, scale=0.18, block_q=32, block_k=32,
                               dropout_rate=0.0, interpret=True)
        np.testing.assert_array_equal(np.asarray(base), np.asarray(zero))

    def test_dropout_changes_output_and_is_seed_deterministic(self):
        q, k, v = rand_qkv(jax.random.PRNGKey(4), 2, 64, 64, 4, 4, 32)
        rng = jax.random.PRNGKey(7)
        f = functools.partial(flash_attention, scale=0.18, block_q=32,
                              block_k=32, interpret=True, dropout_rate=0.3)
        a = f(q, k, v, dropout_rng=rng)
        b = f(q, k, v, dropout_rng=rng)
        c = f(q, k, v, dropout_rng=jax.random.PRNGKey(8))
        base = flash_attention(q, k, v, scale=0.18, block_q=32, block_k=32,
                               interpret=True)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert not np.allclose(np.asarray(a), np.asarray(c))
        assert not np.allclose(np.asarray(a), np.asarray(base))

    def test_dropout_mean_preserving(self):
        """Inverted dropout: E[out] == undropped out. Mean over many seeds
        of a single attention row should approach the base output."""
        q, k, v = rand_qkv(jax.random.PRNGKey(5), 1, 32, 32, 2, 2, 32)
        base = flash_attention(q, k, v, scale=0.18, block_q=32, block_k=32,
                               interpret=True)
        outs = [flash_attention(q, k, v, scale=0.18, block_q=32, block_k=32,
                                dropout_rate=0.25,
                                dropout_rng=jax.random.PRNGKey(100 + s),
                                interpret=True)
                for s in range(48)]
        mean = np.mean([np.asarray(o) for o in outs], axis=0)
        # noisy statistic: elementwise tolerance is loose, the bias check
        # is the mean-over-everything one
        np.testing.assert_allclose(mean.mean(), np.asarray(base).mean(),
                                   atol=0.05)
        assert np.abs(mean - np.asarray(base)).mean() < 0.15

    @pytest.mark.parametrize("nh,nkv", [(4, 4), (4, 2)])
    def test_dropout_grads_vs_finite_differences(self, nh, nkv):
        from jax.test_util import check_grads
        q, k, v = rand_qkv(jax.random.PRNGKey(6), 1, 32, 32, nh, nkv, 32)
        rng = jax.random.PRNGKey(11)

        def f(q, k, v):
            return flash_attention(q, k, v, scale=0.18, block_q=16,
                                   block_k=16, dropout_rate=0.2,
                                   dropout_rng=rng, interpret=True)

        check_grads(f, (q, k, v), order=1, modes=["rev"], atol=2e-2,
                    rtol=2e-2)

    def test_dispatcher_routes_dropout_to_naive_off_tpu(self):
        """Off-TPU the dispatcher must keep the naive dropout path (the
        flash route is TPU-gated)."""
        from distributed_pytorch_tpu.ops.attention_core import sdpa
        q, k, v = rand_qkv(jax.random.PRNGKey(12), 2, 32, 32, 4, 4, 32)
        out = sdpa(q, k, v, dropout_rate=0.5,
                   dropout_rng=jax.random.PRNGKey(0), impl="auto")
        assert np.isfinite(np.asarray(out)).all()

    @pytest.mark.parametrize("nh,nkv", [(2, 2), (4, 2)])
    def test_dropout_exact_vs_replayed_mask_oracle(self, nh, nkv):
        """The hash mask is keyed on absolute positions, so the test can
        replay it on the host and feed an explicit-mask einsum oracle:
        flash-with-dropout must match EXACTLY (not just statistically)."""
        from distributed_pytorch_tpu.ops.flash_attention import _dropout_bits
        B, T, hs, rate = 2, 64, 32, 0.3
        q, k, v = rand_qkv(jax.random.PRNGKey(13), B, T, T, nh, nkv, hs)
        scale = 1.0 / hs ** 0.5
        rng = jax.random.PRNGKey(21)
        out = flash_attention(q, k, v, scale=scale, block_q=32, block_k=16,
                              dropout_rate=rate, dropout_rng=rng,
                              interpret=True)

        seed = jax.random.randint(rng, (2,), -2 ** 31, 2 ** 31 - 1,
                                  jnp.int32)
        bits = _dropout_bits(seed[0], seed[1], 0, 0, 0, (B * nh, T, T))
        thresh = np.uint32(min(int(rate * 2.0 ** 32), 2 ** 32 - 1))
        keep = (np.asarray(bits) >= thresh).astype(np.float32) / (1 - rate)
        keep = keep.reshape(B, nh, T, T)

        kk = np.repeat(np.asarray(k), nh // nkv, axis=2)
        vv = np.repeat(np.asarray(v), nh // nkv, axis=2)
        s = np.einsum("btnh,bsnh->bnts", np.asarray(q, np.float32),
                      kk.astype(np.float32)) * scale
        mask = np.tril(np.ones((T, T), bool))
        s = np.where(mask[None, None], s, -np.inf)
        attn = np.exp(s - s.max(-1, keepdims=True))
        attn /= attn.sum(-1, keepdims=True)
        ref = np.einsum("bnts,bsnh->btnh", attn * keep, vv)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5,
                                   atol=2e-5)


class TestSlabLayout:
    """'slab' kernel layout (round 5): reads (B, T, N*H) slabs directly —
    no HBM transposes — with in-VMEM head-major relayout, in-kernel GQA
    expansion, and write-step dk/dv group-sum. Must be numerically
    identical in semantics to the rows layout and the naive oracle.
    Head-slab widths are chosen lane-aligned ((n*hs) % 128 == 0)."""

    CASES = [(4, 4, 32), (4, 2, 64), (8, 1, 16)]  # (nh, nkv, hs)

    @pytest.mark.parametrize("nh,nkv,hs", CASES)
    def test_forward_matches_naive(self, nh, nkv, hs):
        q, k, v = rand_qkv(jax.random.PRNGKey(0), 2, 128, 128, nh, nkv, hs)
        scale = 1.0 / hs ** 0.5
        out = flash_attention(q, k, v, scale=scale, block_q=64, block_k=32,
                              layout="slab", interpret=True)
        ref = _naive_sdpa(q, k, v, scale=scale, q_offset=0, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("nh,nkv,hs", CASES)
    def test_grads_match_naive(self, nh, nkv, hs):
        q, k, v = rand_qkv(jax.random.PRNGKey(1), 2, 128, 128, nh, nkv, hs)
        scale = 1.0 / hs ** 0.5
        w = jax.random.normal(jax.random.PRNGKey(2), q.shape)

        def f(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, scale=scale, block_q=64, block_k=32,
                layout="slab", interpret=True) * w)

        def n(q, k, v):
            return jnp.sum(_naive_sdpa(q, k, v, scale=scale, q_offset=0,
                                       causal=True) * w)

        gf = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        gn = jax.grad(n, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gn):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)

    def test_prefill_longer_cache(self):
        """S > T (prefill into a longer zero-padded cache): positional
        causal mask must hide the tail, as in the rows layout."""
        q, k, v = rand_qkv(jax.random.PRNGKey(3), 2, 64, 256, 4, 4, 32)
        out = flash_attention(q, k, v, scale=0.18, block_q=32, block_k=32,
                              layout="slab", interpret=True)
        ref = _naive_sdpa(q, k, v, scale=0.18, q_offset=0, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_noncausal(self):
        q, k, v = rand_qkv(jax.random.PRNGKey(4), 2, 64, 64, 4, 2, 32)
        out = flash_attention(q, k, v, scale=0.18, block_q=32, block_k=32,
                              layout="slab", causal=False, interpret=True)
        ref = _naive_sdpa(q, k, v, scale=0.18, q_offset=0, causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_dropout_identical_masks_across_layouts(self):
        """The dropout hash is keyed on absolute positions, so rows and
        slab layouts must produce bit-identical dropped outputs."""
        q, k, v = rand_qkv(jax.random.PRNGKey(5), 2, 64, 64, 4, 4, 32)
        rng = jax.random.PRNGKey(9)
        a = flash_attention(q, k, v, scale=0.18, block_q=32, block_k=32,
                            layout="rows", dropout_rate=0.3,
                            dropout_rng=rng, interpret=True)
        b = flash_attention(q, k, v, scale=0.18, block_q=16, block_k=64,
                            layout="slab", dropout_rate=0.3,
                            dropout_rng=rng, interpret=True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5)

    def test_lse_and_dlse_match_rows(self):
        """The differentiable-lse contract (ring merge) must hold for the
        slab path too: same lse values, same d/dlse folding."""
        from distributed_pytorch_tpu.ops.flash_attention import (
            flash_attention_lse)
        q, k, v = rand_qkv(jax.random.PRNGKey(6), 2, 64, 64, 4, 4, 32)
        wl = jax.random.normal(jax.random.PRNGKey(7), (2, 64, 4))
        wo = jax.random.normal(jax.random.PRNGKey(8), q.shape)

        def loss(layout):
            def f(q, k, v):
                out, lse = flash_attention_lse(
                    q, k, v, scale=0.18, block_q=32, block_k=32,
                    layout=layout, interpret=True)
                return jnp.sum(out * wo) + jnp.sum(lse * wl)
            return f

        (la, ga) = jax.value_and_grad(loss("rows"), argnums=(0, 1, 2))(
            q, k, v)
        (lb, gb) = jax.value_and_grad(loss("slab"), argnums=(0, 1, 2))(
            q, k, v)
        np.testing.assert_allclose(float(la), float(lb), rtol=1e-5)
        for a, b in zip(ga, gb):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)

    def test_usable_gate_slab(self):
        from distributed_pytorch_tpu.ops.flash_attention import (
            slab_attention_usable)
        assert slab_attention_usable(2, 1024, 1024, 12, 12, 64, jnp.bfloat16)
        assert not slab_attention_usable(2, 1024, 1024, 3, 3, 24,
                                         jnp.bfloat16)  # 72 lanes


def test_pallas_dp_mesh_shard_map_wrap(monkeypatch):
    """Under a live multi-device mesh, the dispatcher must run the flash
    kernel per data shard via shard_map (GSPMD can't partition a
    pallas_call) and match the naive oracle."""
    from distributed_pytorch_tpu.ops import attention_core as core
    from distributed_pytorch_tpu.ops import flash_attention as fa
    from distributed_pytorch_tpu.parallel import context
    from distributed_pytorch_tpu.parallel.mesh import MeshPlan, build_mesh

    monkeypatch.setattr(core, "_on_tpu", lambda: True)
    # interpret-mode kernel: patch the public entry the dispatcher calls
    orig = fa.flash_attention
    import functools as ft
    monkeypatch.setattr(
        "distributed_pytorch_tpu.ops.flash_attention.flash_attention",
        ft.partial(orig, interpret=True))
    # assert the shard_map wrap actually engages (gates hold: B % dp == 0)
    calls = []
    orig_wrap = core._shard_map_over_data

    def spy(fn, q, has_rng=False):
        w = orig_wrap(fn, q, has_rng)
        calls.append(w is not None)
        return w

    monkeypatch.setattr(core, "_shard_map_over_data", spy)

    q, k, v = rand_qkv(jax.random.PRNGKey(0), 8, 64, 64, 4, 4, 32)
    mesh = build_mesh(MeshPlan(data=8))
    with context.use_mesh(mesh):
        out = core.sdpa(q, k, v, causal=True, impl="pallas")
    ref = _naive_sdpa(q, k, v, scale=1.0 / 32 ** 0.5, q_offset=0,
                      causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    # and the dropout path (per-shard folded rng): finite + correct shape
    with context.use_mesh(mesh):
        outd = core.sdpa(q, k, v, causal=True, impl="pallas",
                         dropout_rate=0.2,
                         dropout_rng=jax.random.PRNGKey(1))
    assert outd.shape == q.shape
    assert np.isfinite(np.asarray(outd)).all()
    assert not np.allclose(np.asarray(outd), np.asarray(out))
    assert calls == [True, True], calls
