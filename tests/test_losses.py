"""Fused (chunked) cross-entropy vs the unchunked oracle.

The fused path is the round-4 MFU fix (never materializes (B, T, V) fp32
logits — ops/losses.py); these tests pin its numerics and gradients to the
full-logits oracle, which itself mirrors reference single-gpu/model.py:
687-692 (ignore_index=-1 mean CE)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.config import LLMConfig
from distributed_pytorch_tpu.models import LLM
from distributed_pytorch_tpu.ops.losses import (_chunk_for,
                                                fused_cross_entropy,
                                                unchunked_cross_entropy)


def _data(B=2, T=32, C=16, V=64, seed=0):
    kx, ke, kt = jax.random.split(jax.random.PRNGKey(seed), 3)
    x = jax.random.normal(kx, (B, T, C), jnp.float32)
    emb = jax.random.normal(ke, (V, C), jnp.float32) * 0.1
    tgt = jax.random.randint(kt, (B, T), 0, V)
    return x, emb, tgt


@pytest.mark.parametrize("chunk", [4, 8, 16])
def test_fused_matches_unchunked(chunk):
    x, emb, tgt = _data()
    ref = unchunked_cross_entropy(x, emb, tgt)
    got = fused_cross_entropy(x, emb, tgt, chunk=chunk)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-6)


def test_fused_gradients_match():
    x, emb, tgt = _data()

    g_ref = jax.grad(lambda a, e: unchunked_cross_entropy(a, e, tgt),
                     argnums=(0, 1))(x, emb)
    g_fused = jax.grad(lambda a, e: fused_cross_entropy(a, e, tgt, chunk=8),
                       argnums=(0, 1))(x, emb)
    for r, f in zip(g_ref, g_fused):
        np.testing.assert_allclose(np.asarray(f), np.asarray(r),
                                   rtol=1e-5, atol=1e-6)


def test_fused_ignore_index():
    x, emb, tgt = _data()
    tgt = tgt.at[:, 16:].set(-1)
    ref = unchunked_cross_entropy(x, emb, tgt)
    got = fused_cross_entropy(x, emb, tgt, chunk=8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-6)
    # all-masked: finite zero, not NaN (denominator clamps at 1)
    all_masked = jnp.full_like(tgt, -1)
    got0 = fused_cross_entropy(x, emb, all_masked, chunk=8)
    assert float(got0) == 0.0


def test_chunk_autoselect():
    # tiny vocab / short T: never chunk (scan overhead would hurt)
    assert _chunk_for(32, 96) == 0
    assert _chunk_for(128, 96) == 0
    # GPT-scale: chunk divides T and is <= the target
    c = _chunk_for(1024, 50304)
    assert c > 0 and 1024 % c == 0 and c <= 128
    # awkward T (prime / tiny-divisor-only): degenerate chunks would scan
    # near-per-token — must fall back to unchunked, not chunk=1/2
    assert _chunk_for(1021, 50304) == 0
    assert _chunk_for(2 * 509, 50304) == 0


def test_model_loss_impl_parity():
    """End-to-end: LLM with loss_impl='fused' (forced chunking) matches
    loss_impl='unchunked' bit-for-bit in fp32, gradients included."""
    kw = dict(vocab_size=96, block_size=32, n_embd=32, n_head=4,
              n_kv_heads=2, n_layer=2, up_dim=48, pos_emb="rope",
              attn="gqa", non_linearity="swiglu")
    cfg_f = LLMConfig(**kw, loss_impl="fused", loss_chunk=4)
    cfg_u = LLMConfig(**kw, loss_impl="unchunked")
    idx = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 96)
    tgt = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, 96)
    model_f, model_u = LLM(cfg_f), LLM(cfg_u)
    variables = model_u.init(jax.random.PRNGKey(0), idx, tgt)

    _, loss_u, _ = model_u.apply(variables, idx, tgt)
    _, loss_f, _ = model_f.apply(variables, idx, tgt)
    np.testing.assert_allclose(np.asarray(loss_f), np.asarray(loss_u),
                               rtol=1e-6)

    def lf(m):
        return lambda p: m.apply({"params": p}, idx, tgt)[1]

    g_u = jax.grad(lf(model_u))(variables["params"])
    g_f = jax.grad(lf(model_f))(variables["params"])
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=1e-5, atol=1e-6),
        g_f, g_u)


# ---------------------------------------------------------------------------
# Pallas streaming CE (ops/fused_ce.py) vs the oracle, interpret mode on CPU
# ---------------------------------------------------------------------------

from distributed_pytorch_tpu.ops.fused_ce import (pallas_ce_usable,
                                                  pallas_cross_entropy)


def _pdata(B=2, T=32, C=128, V=100, seed=0, dtype=jnp.float32):
    kx, ke, kt = jax.random.split(jax.random.PRNGKey(seed), 3)
    x = jax.random.normal(kx, (B, T, C), dtype)
    emb = (jax.random.normal(ke, (V, C), jnp.float32) * 0.1).astype(dtype)
    tgt = jax.random.randint(kt, (B, T), 0, V)
    return x, emb, tgt


@pytest.mark.parametrize("V", [100, 64, 96])   # 100: vocab-padding path
def test_pallas_ce_matches_unchunked(V):
    x, emb, tgt = _pdata(V=V)
    ref = unchunked_cross_entropy(x, emb, tgt)
    got = pallas_cross_entropy(x, emb, tgt, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-6)


def test_pallas_ce_gradients_match():
    x, emb, tgt = _pdata()
    g_ref = jax.grad(lambda a, e: unchunked_cross_entropy(a, e, tgt),
                     argnums=(0, 1))(x, emb)
    g_got = jax.grad(
        lambda a, e: pallas_cross_entropy(a, e, tgt, interpret=True),
        argnums=(0, 1))(x, emb)
    for r, g in zip(g_ref, g_got):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=2e-5, atol=2e-6)


def test_pallas_ce_ignore_index():
    x, emb, tgt = _pdata()
    tgt = tgt.at[:, -5:].set(-1)
    ref = unchunked_cross_entropy(x, emb, tgt)
    got = pallas_cross_entropy(x, emb, tgt, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-6)
    # ignored rows must contribute zero gradient
    g = jax.grad(
        lambda a: pallas_cross_entropy(a, emb, tgt, interpret=True))(x)
    np.testing.assert_allclose(np.asarray(g[:, -5:]), 0.0, atol=1e-7)


def test_pallas_ce_bf16():
    x, emb, tgt = _pdata(dtype=jnp.bfloat16)
    ref = unchunked_cross_entropy(x, emb, tgt)
    got = pallas_cross_entropy(x, emb, tgt, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)


def test_pallas_ce_usable_gate():
    assert pallas_ce_usable(16384, 768, jnp.bfloat16)
    assert not pallas_ce_usable(16384, 120, jnp.bfloat16)   # C not lane-mult
    assert not pallas_ce_usable(16384, 768, jnp.float16)


def test_pallas_ce_dp_shard_map_parity():
    """The shard_map('data') wrapper path: same value + grads as the
    oracle when the ambient mesh shards the batch over 8 devices."""
    import jax
    from distributed_pytorch_tpu.parallel import context
    from distributed_pytorch_tpu.parallel.mesh import mesh_for

    x, emb, tgt = _pdata(B=8, T=16)
    mesh = mesh_for("dp")
    ref, g_ref = jax.value_and_grad(
        lambda a, e: unchunked_cross_entropy(a, e, tgt), argnums=(0, 1))(
        x, emb)
    with context.use_mesh(mesh):
        got, g_got = jax.value_and_grad(
            lambda a, e: pallas_cross_entropy(a, e, tgt, interpret=True),
            argnums=(0, 1))(x, emb)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-6)
    for r, g in zip(g_ref, g_got):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("chunk", [0, 8])
def test_sp_fused_ce_matches_oracle(chunk):
    """Sequence-parallel chunked CE (round-5: replaces the unchunked
    fallback under a live 'seq' axis): value and grads must match the
    full-logits oracle on a data=4 x seq=2 mesh, with and without an
    explicit chunk size, including masked targets."""
    from distributed_pytorch_tpu.ops.losses import sp_fused_cross_entropy
    from distributed_pytorch_tpu.parallel import context
    from distributed_pytorch_tpu.parallel.mesh import mesh_for

    x, emb, tgt = _data(B=8, T=32, C=16, V=64, seed=3)
    tgt = tgt.at[:, 28:].set(-1)
    ref, g_ref = jax.value_and_grad(
        lambda a, e: unchunked_cross_entropy(a, e, tgt), argnums=(0, 1))(
        x, emb)
    mesh = mesh_for("sp", sp_size=2)
    with context.use_mesh(mesh):
        got, g_got = jax.value_and_grad(
            lambda a, e: sp_fused_cross_entropy(a, e, tgt, chunk=chunk),
            argnums=(0, 1))(x, emb)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-6)
    for r, g in zip(g_ref, g_got):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=2e-5, atol=2e-6)


def test_sp_train_step_uses_chunked_loss():
    """End-to-end: an sp-recipe train step at fused loss_impl must agree
    with the single-device oracle (this now routes through
    sp_fused_cross_entropy at trace time)."""
    from distributed_pytorch_tpu.config import TrainConfig
    from distributed_pytorch_tpu.parallel import context
    from distributed_pytorch_tpu.parallel.mesh import mesh_for
    from distributed_pytorch_tpu.train.state import create_train_state
    from distributed_pytorch_tpu.train.step import make_train_step

    mc = LLMConfig(vocab_size=128, block_size=32, n_embd=32, n_head=4,
                   n_kv_heads=4, n_layer=2, up_dim=64, loss_impl="fused",
                   loss_chunk=8)
    x = jax.random.randint(jax.random.PRNGKey(1), (1, 8, 32), 0, 128)
    y = jax.random.randint(jax.random.PRNGKey(2), (1, 8, 32), 0, 128)

    tc1 = TrainConfig(total_batch_size=8 * 32, batch_size=8, max_iters=2,
                      parallelism="single")
    model, tx, state, _ = create_train_state(mc, tc1, None)
    step = make_train_step(model, tx, mc, tc1, None, None)
    _, m_ref = step(state, x, y)

    tc2 = TrainConfig(total_batch_size=8 * 32, batch_size=8, max_iters=2,
                      parallelism="sp", sp_size=2)
    mesh = mesh_for("sp", sp_size=2)
    with context.use_mesh(mesh):
        model2, tx2, state2, sh2 = create_train_state(mc, tc2, mesh)
        step2 = make_train_step(model2, tx2, mc, tc2, mesh, sh2)
        _, m_sp = step2(state2, x, y)
    np.testing.assert_allclose(float(m_sp["loss"]), float(m_ref["loss"]),
                               rtol=2e-5)


def test_pallas_ce_real_vocab_padding():
    """GPT-2 vocab 50304 pads to 51200 (25 x 2048 tiles): the production
    padding path with the last tile 1152-valid, tiny N/C to keep
    interpret mode fast."""
    x, emb, tgt = _pdata(B=2, T=32, C=128, V=50304)
    ref = unchunked_cross_entropy(x, emb, tgt)
    got = pallas_cross_entropy(x, emb, tgt, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-6)
    g_ref = jax.grad(lambda e: unchunked_cross_entropy(x, e, tgt))(emb)
    g_got = jax.grad(
        lambda e: pallas_cross_entropy(x, e, tgt, interpret=True))(emb)
    np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_ref),
                               rtol=2e-5, atol=2e-6)
