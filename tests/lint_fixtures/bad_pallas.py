"""Fixture: a pallas_call module with no *_usable capability gate.
Never imported — parsed as AST only (tests/test_lint.py)."""
from jax.experimental import pallas as pl


def kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2


def run(x):
    # no `<something>_usable` gate anywhere in this module -> finding
    return pl.pallas_call(kernel, out_shape=x)(x)
