"""Fixture: env reads scripts/lint.py must route through the config.py
knob registry. Never imported — parsed as AST only (tests/test_lint.py)."""
import os


def read_knobs():
    a = os.environ.get("MY_TUNABLE", "1")     # bypasses ENV_KNOBS
    b = os.getenv("OTHER_TUNABLE")            # ditto
    c = os.environ["REQUIRED_TUNABLE"]        # ditto (subscript read)
    os.environ["DERIVED"] = "x"               # a WRITE — not flagged
    os.environ.setdefault("BOOT", "1")        # bootstrap write — not flagged
    d = os.environ.get("TAGGED", "")  # lint: allow(env-read)
    return a, b, c, d
