"""Fixture: every host-sync pattern scripts/lint.py must flag. Never
imported — parsed as AST only (tests/test_lint.py)."""
import jax
import jax.numpy as jnp
import numpy as np


def traced_body(x, first):
    y = jax.device_get(x)            # device_get on the hot path
    z = x.item()                     # .item() sync
    f = float(jnp.mean(x))           # float() on a device value
    i = int(jax.device_get(first))   # int() on a device value
    a = np.asarray(x)                # np.asarray materializes on host
    b = np.array(x)                  # np.array copies to host too
    t = x.tolist()                   # .tolist() drains the whole array
    return y, z, f, i, a, b, t


def allowed_body(x):
    # the tag suppresses exactly one line
    return jax.device_get(x)  # lint: allow(host-sync)
