"""Fixture: wall-clock reads scripts/lint.py must flag in obs/ modules.
Never imported — parsed as AST only (tests/test_lint.py)."""
import time


def record_span():
    t0 = time.time()                 # NTP slew breaks span durations
    t1 = time.monotonic()            # fine
    t2 = time.perf_counter()         # fine
    anchored = time.time()  # lint: allow(wall-clock)
    return t0, t1, t2, anchored
