"""Fixture: a gated pallas_call module — zero findings expected.
Never imported — parsed as AST only (tests/test_lint.py)."""
from jax.experimental import pallas as pl


def run_usable() -> bool:
    return False


def kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2


def run(x):
    if not run_usable():
        return x * 2
    return pl.pallas_call(kernel, out_shape=x)(x)
