"""Fleet observability (ISSUE 14): cross-process metrics federation,
SLO burn-rate accounting, and the timeline-replay cost-model extractor.

Three layers under test:

* serve/metrics.py federation — histogram snapshots merge EXACTLY (a
  fleet page is bit-equal to summing per-replica scrapes), reservoirs
  concatenate-and-cap with bounded quantile error, and `render_fleet`
  emits fleet-summed series next to per-replica labeled ones;
* obs/slo.py — declarative targets turned into multi-window burn rates
  and error-budget gauges, driven here by an injected clock;
* obs/replay.py + the train/supervisor registries — the deterministic
  analyzer fits the PERF.md step model on synthetic timelines with a
  known ground truth, and the supervisor's opt-in telemetry serves the
  same /metrics.json federation snapshot the replicas do.

The e2e test reuses the test_router.py idiom: real in-process
ServeApp/Scheduler/DecodeEngine replicas behind a Router whose
federation pull is cranked down to the probe cadence.
"""

import asyncio
import json
import os
import random
import urllib.request

import pytest

from distributed_pytorch_tpu.obs.flight import FlightRecorder
from distributed_pytorch_tpu.obs.slo import SLOTarget, SLOTracker
from distributed_pytorch_tpu.serve.metrics import (Histogram,
                                                   LATENCY_BUCKETS,
                                                   ServeMetrics,
                                                   merge_histograms,
                                                   render_fleet,
                                                   render_hist_snap)


# ----------------------------------------------------------------------
# histogram merge exactness
# ----------------------------------------------------------------------

@pytest.mark.parametrize("seed", [0, 7, 1729])
def test_merge_bit_equal_to_single_process(seed):
    """N-replica merge == single-process observation, bit-equal on
    bucket counts/count and exact (modulo float addition order) on sum:
    the federation invariant the fleet page advertises."""
    rng = random.Random(seed)
    vals = [rng.expovariate(10.0) for _ in range(3000)]
    whole = Histogram("h", "x")
    parts = [Histogram("h", "x") for _ in range(3)]
    for i, v in enumerate(vals):
        whole.observe(v)
        parts[i % 3].observe(v)
    merged = merge_histograms([p.to_dict() for p in parts])
    assert merged["counts"] == whole.counts          # bit-equal ints
    assert merged["count"] == whole.count
    assert merged["sum"] == pytest.approx(whole.sum, rel=1e-12)
    # and the rendered cumulative bucket lines agree line-for-line
    # (all but `_sum`, whose float addition order legitimately differs)
    drop = f"{merged['name']}_sum"
    assert ([ln for ln in render_hist_snap(merged)[2:]
             if not ln.startswith(drop)]
            == [ln for ln in render_hist_snap(whole.to_dict())[2:]
                if not ln.startswith(drop)])


def test_merge_rejects_bucket_mismatch():
    a = Histogram("h", "x", buckets=(0.1, 1.0))
    b = Histogram("h", "x", buckets=(0.2, 1.0))
    b.observe(0.15)
    with pytest.raises(ValueError, match="bucket mismatch"):
        a.merge_from(b.to_dict())


def test_merged_reservoir_cap_and_quantile_bounds():
    """Reservoirs concatenate capped at max_samples; the merged quantile
    stays within the bucket grid's resolution of the exact pooled
    quantile (same seeded distribution in every shard, so truncation
    keeps the estimate honest)."""
    rng = random.Random(3)
    shards = []
    pooled = []
    for _ in range(4):
        h = Histogram("h", "x")
        for _ in range(500):
            v = rng.uniform(0.0, 1.0)
            h.observe(v)
            pooled.append(v)
        shards.append(h.to_dict())
    cap = 600                       # < 2000 pooled: truncation engages
    merged = Histogram.from_dict(shards[0], max_samples=cap)
    for s in shards[1:]:
        merged.merge_from(s)
    assert len(merged._samples) == cap
    assert merged.count == 2000     # counts are NEVER truncated
    exact = sorted(pooled)[len(pooled) // 2]
    assert merged.quantile(0.5) == pytest.approx(exact, abs=0.1)


def test_count_le_exact_at_bucket_edges():
    h = Histogram("h", "x")
    obs = [0.003, 0.05, 0.049, 0.051, 0.5, 2.0]
    for v in obs:
        h.observe(v)
    assert 0.05 in LATENCY_BUCKETS and 0.5 in LATENCY_BUCKETS
    assert h.count_le(0.05) == sum(1 for v in obs if v <= 0.05)
    assert h.count_le(0.5) == sum(1 for v in obs if v <= 0.5)
    assert h.count_le(1e9) == h.count


# ----------------------------------------------------------------------
# render_fleet (pure, no sockets)
# ----------------------------------------------------------------------

def test_render_fleet_sums_and_labels():
    reps = {}
    rng = random.Random(11)
    expected_completed = 0
    for i in range(3):
        m = ServeMetrics()
        for _ in range(50):
            m.ttft.observe(rng.expovariate(5.0))
        n = rng.randrange(1, 9)
        m.inc("completed", n)
        expected_completed += n
        m.set_weights_version(f"step_10-cafe{i:04d}")
        reps[f"127.0.0.1:800{i}"] = m.snapshot()
    page = render_fleet(reps)
    lines = page.splitlines()
    assert "serve_fleet_replicas 3" in lines
    # the unlabeled fleet series is bit-equal to merging the snapshots
    merged = merge_histograms(
        [s["histograms"]["serve_ttft_seconds"] for s in reps.values()])
    for want in render_hist_snap(merged, header=False):
        assert want in lines, want
    # every replica appears as a labeled series of the same histogram
    for r, snap in reps.items():
        cnt = snap["histograms"]["serve_ttft_seconds"]["count"]
        assert f'serve_ttft_seconds_count{{replica="{r}"}} {cnt}' in lines
        wv = snap["weights_version"]
        assert (f'serve_weights_version{{replica="{r}",'
                f'version="{wv}"}} 1' in lines)
    assert ('serve_fleet_requests_total{event="completed"} '
            f"{expected_completed}" in lines)


# ----------------------------------------------------------------------
# SLO tracker (injected clock)
# ----------------------------------------------------------------------

def _tracker(windows=(10.0, 100.0)):
    clock = {"t": 0.0}
    targets = [SLOTarget("lat", "latency", objective=0.99,
                         threshold_s=0.05),
               SLOTarget("avail", "availability", objective=0.9)]
    tr = SLOTracker(targets, windows_s=windows,
                    now_fn=lambda: clock["t"])
    return tr, clock


def test_slo_burn_rate_windows_and_budget():
    tr, clock = _tracker()
    tr.update({"lat": (0, 0), "avail": (0, 0)})
    # 100 events, 2 bad -> bad fraction 2% = 2x the 1% budget
    clock["t"] = 5.0
    tr.update({"lat": (98, 100), "avail": (100, 100)})
    assert tr.burn_rate("lat", 10.0) == pytest.approx(2.0)
    assert tr.burn_rate("avail", 10.0) == 0.0
    assert tr.budget_remaining("lat") == pytest.approx(1 - 0.02 / 0.01)
    # the bad burst ages OUT of the short window but still counts
    # against the cumulative budget
    clock["t"] = 50.0
    tr.update({"lat": (198, 200), "avail": (200, 200)})
    assert tr.burn_rate("lat", 10.0) == 0.0        # clean recent window
    assert tr.burn_rate("lat", 100.0) == pytest.approx(1.0)
    assert tr.budget_remaining("lat") == pytest.approx(0.0)
    assert tr.budget_remaining("avail") == 1.0


def test_slo_budget_exhaustion_goes_negative():
    tr, clock = _tracker()
    tr.update({"avail": (0, 0)})
    clock["t"] = 1.0
    tr.update({"avail": (50, 100)})    # 50% bad vs a 10% budget
    assert tr.budget_remaining("avail") < 0
    snap = tr.snapshot()
    assert snap["avail"]["budget_remaining"] < 0
    assert snap["avail"]["burn_rate"]["10"] == pytest.approx(5.0)
    txt = "\n".join(tr.render_prometheus())
    assert 'slo_burn_rate{slo="avail",window_s="10"} 5.000000' in txt
    assert 'slo_error_budget_remaining{slo="avail"} -4.000000' in txt


def test_slo_no_events_is_silent():
    tr, _ = _tracker()
    assert tr.burn_rate("lat", 10.0) == 0.0
    assert tr.budget_remaining("lat") == 1.0


# ----------------------------------------------------------------------
# timeline replay: known ground truth
# ----------------------------------------------------------------------

def _write_jsonl(path, records):
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


def test_replay_fits_known_step_model(tmp_path):
    """Synthetic engine timeline with step_ms = 2 + 0.01·prefill_tokens
    exactly; the fit must recover (a, b) and exclude the planted compile
    outlier."""
    from distributed_pytorch_tpu.obs import replay
    rng = random.Random(5)
    recs = [{"step": 0, "step_ms": 500.0, "prefill_tokens": 0,
             "n_live": 1}]                       # compile step
    for i in range(1, 200):
        x = rng.choice([0, 0, 0, 64, 128, 256])
        recs.append({"step": i, "step_ms": 2.0 + 0.01 * x,
                     "prefill_tokens": x, "n_live": 4})
    _write_jsonl(tmp_path / "timeline.jsonl", recs)
    _write_jsonl(tmp_path / "trace.jsonl", [
        {"trace": "t", "span": i, "parent": None, "name": name,
         "cat": "sched", "t0": 0.0, "dur": dur, "attrs": {}}
        for i, (name, dur) in enumerate(
            [("sched.queue", 0.004), ("sched.queue", 0.006),
             ("sched.prefill", 0.010), ("sched.prefill", 0.012)])])
    a = replay.write_report(str(tmp_path))
    assert not a["degenerate"] and not a["notes"]
    m = a["engine"]["step_model"]
    assert m["a_ms"] == pytest.approx(2.0, abs=1e-6)
    assert m["b_ms_per_prefill_token"] == pytest.approx(0.01, abs=1e-9)
    assert m["mae_pct"] == pytest.approx(0.0, abs=1e-6)
    assert m["warmup_excluded"] == 1
    tm = a["trace"]["ttft_model"]
    assert tm["predicted_ttft_p50_ms"] == pytest.approx(4 + 10, abs=2.1)
    # artifacts on disk, machine-readable model round-trips
    with open(a["cost_model_json"]) as f:
        cm = json.load(f)
    assert cm["engine"]["step_model"] == m
    assert os.path.exists(a["report_md"])
    assert "step_ms ≈ 2.0 + 0.01" in open(a["report_md"]).read()


def test_replay_supervisor_and_train_sections(tmp_path):
    from distributed_pytorch_tpu.obs import replay
    _write_jsonl(tmp_path / "supervisor_timeline.jsonl", [
        {"event": "gang_spawn", "t": 0.0},
        {"event": "worker_down", "t": 5.0},
        {"event": "gang_restart", "t": 6.5},
        {"event": "completed", "t": 20.0}])
    _write_jsonl(tmp_path / "train_timeline.jsonl", [
        {"it": i, "loss": 5.0 - 0.1 * i, "step_ms": 10.0 + (i == 0) * 400,
         "data_ms": 1.0, "sync_ms": 0.5, "ckpt_ms": 0.0,
         "tokens_per_s": 1000.0, "grad_norm": 1.0,
         "compile_window": i == 0} for i in range(20)])
    a = replay.analyze(str(tmp_path))
    assert not a["degenerate"]
    sup = a["supervisor"]
    assert sup["events"]["worker_down"] == 1
    assert sup["final_event"] == "completed"
    assert sup["recovery_s"]["p50"] == pytest.approx(1.5)
    trn = a["train"]
    assert trn["iterations"] == 20
    assert trn["loss_first"] == 5.0 and trn["loss_last"] == 3.1
    assert trn["compile_windows"] == 1


def test_replay_degenerate_on_empty_dir(tmp_path):
    from distributed_pytorch_tpu.obs import replay
    (tmp_path / "noise.jsonl").write_text('{"unrelated": 1}\n')
    a = replay.analyze(str(tmp_path))
    assert a["degenerate"]
    assert a["files"]["skipped"]


def test_obs_report_cli_exit_codes(tmp_path):
    """scripts/obs_report.py: 0 on a clean fit, 2 on a degenerate run
    dir — the CI gate's contract."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(os.path.dirname(__file__), "..",
                                   "scripts", "obs_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    good = tmp_path / "good"
    good.mkdir()
    _write_jsonl(good / "timeline.jsonl",
                 [{"step": i, "step_ms": 2.0, "prefill_tokens": 0,
                   "n_live": 1} for i in range(30)])
    assert mod.main([str(good)]) == 0
    empty = tmp_path / "empty"
    empty.mkdir()
    assert mod.main([str(empty)]) == 2
    assert mod.main([str(tmp_path / "missing")]) == 2


# ----------------------------------------------------------------------
# supervisor/train registries + TelemetryServer federation route
# ----------------------------------------------------------------------

def test_supervisor_metrics_snapshot_and_server():
    from distributed_pytorch_tpu.train.telemetry import (SupervisorMetrics,
                                                         TelemetryServer)

    class Tel:                        # duck-typed: .metrics + .flight
        metrics = SupervisorMetrics()
        flight = FlightRecorder(capacity=16)

    m = Tel.metrics
    m.event("gang_spawn")
    m.event("worker_down")
    m.event("gang_restart")
    m.set_build_info(run="t", hosts=2)
    m.register_gauge("supervisor_generation", lambda: 2.0)
    m.set_heartbeat_ages_fn(lambda: {0: 0.25, 1: 1.5})
    txt = m.render_prometheus()
    assert 'supervisor_events_total{event="worker_down"} 1' in txt
    assert 'supervisor_heartbeat_age_seconds{slot="1"} 1.5' in txt
    assert "supervisor_generation 2.0" in txt

    srv = TelemetryServer(Tel(), port=0,
                          status_fn=lambda: {"ok": True}).start()
    try:
        snap = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics.json",
            timeout=5).read())
    finally:
        srv.stop()
    assert snap["kind"] == "supervisor"
    assert snap["counters"] == {"gang_spawn": 1, "worker_down": 1,
                                "gang_restart": 1}
    assert snap["histograms"] == {}
    assert snap["heartbeat_age_s"] == {"0": 0.25, "1": 1.5}
    assert snap["gauges"]["supervisor_generation"] == 2.0


def test_train_metrics_snapshot_shape():
    from distributed_pytorch_tpu.train.telemetry import TrainMetrics
    m = TrainMetrics()
    m.observe_phases(step_s=0.01, data_s=0.001, sync_s=0.0)
    snap = m.snapshot()
    assert snap["kind"] == "train"
    assert snap["histograms"]["train_step_seconds"]["count"] == 1
    # the federation snapshot merges with the serve-side machinery
    merged = merge_histograms(
        [snap["histograms"]["train_step_seconds"]] * 2)
    assert merged["count"] == 2


# ----------------------------------------------------------------------
# e2e: replicas + router federation pull + /metrics/fleet
# ----------------------------------------------------------------------

def test_fleet_endpoint_e2e():
    """3 real in-process replicas behind a Router with the federation
    pull on the probe cadence: /metrics/fleet's unlabeled bucket sums
    are bit-equal to merging the replicas' own /metrics.json scrapes,
    per-replica labeled series are present, and the router's /metrics
    carries the SLO gauges."""
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp
    from distributed_pytorch_tpu.config import LLMConfig
    from distributed_pytorch_tpu.engine import DecodeEngine
    from distributed_pytorch_tpu.models.gpt import LLM
    from distributed_pytorch_tpu.serve.router import Router, RouterApp
    from distributed_pytorch_tpu.serve.scheduler import Scheduler
    from distributed_pytorch_tpu.serve.server import ServeApp

    cfg = LLMConfig(vocab_size=97, block_size=64, n_embd=48, n_head=4,
                    n_kv_heads=2, attn="gqa", n_layer=2, up_dim=64,
                    non_linearity="swiglu", pos_emb="rope", dropout=0.0)
    model = LLM(cfg, attn_impl="naive")
    rng = jax.random.PRNGKey(0)
    x = jnp.zeros((1, cfg.block_size), jnp.int32)
    variables = dict(model.init({"params": rng, "dropout": rng}, x, x))

    class Rep:
        def __init__(self):
            self.eng = DecodeEngine(model, variables, n_slots=2,
                                    temperature=0.0, min_bucket=8)
            self.sched = Scheduler(self.eng, max_queue=32)
            self.sched.metrics.set_weights_version("demo")
            self.app = ServeApp(self.sched, port=0)

        async def start(self):
            await self.sched.start()
            await self.app.start()
            return self

        @property
        def addr(self):
            return f"127.0.0.1:{self.app.port}"

        async def stop(self):
            await self.app.stop()
            await self.sched.stop()

    async def http_get(port, path):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(f"GET {path} HTTP/1.1\r\nHost: t\r\n\r\n".encode())
        await writer.drain()
        data = await reader.read()
        writer.close()
        head, _, body = data.partition(b"\r\n\r\n")
        return int(head.split(b" ")[1]), body.decode()

    async def main():
        reps = [await Rep().start() for _ in range(3)]
        router = Router([r.addr for r in reps], probe_interval_s=0.05,
                        probe_timeout_s=2.0, fleet_poll_interval_s=0.0)
        await router.start()
        app = RouterApp(router, port=0)
        await app.start()
        prompts = [[i + 1, i + 2, i + 3] for i in range(6)]
        outs = await asyncio.gather(*(router.complete(p, 4)
                                      for p in prompts))
        # wait until every replica's final counts have federated in
        deadline = asyncio.get_running_loop().time() + 10
        while asyncio.get_running_loop().time() < deadline:
            snaps = router.fleet_snapshots()
            done = sum(s["counters"]["completed"]
                       for s in snaps.values())
            if len(snaps) == 3 and done == len(prompts):
                break
            await asyncio.sleep(0.05)
        direct = {}
        for r in reps:
            status, body = await http_get(r.app.port, "/metrics.json")
            assert status == 200
            direct[r.addr] = json.loads(body)
        f_status, fleet = await http_get(app.port, "/metrics/fleet")
        m_status, rmetrics = await http_get(app.port, "/metrics")
        j_status, rjson = await http_get(app.port, "/metrics.json")
        await app.stop()
        await router.stop()
        for r in reps:
            await r.stop()
        return outs, direct, (f_status, fleet), (m_status, rmetrics), \
            (j_status, rjson)

    outs, direct, (f_status, fleet), (m_status, rmetrics), \
        (j_status, rjson) = asyncio.run(asyncio.wait_for(main(), 300))
    assert all(o["reason"] == "budget" for o in outs)
    assert f_status == 200
    lines = fleet.splitlines()
    assert "serve_fleet_replicas 3" in lines
    # bit-equality: the unlabeled fleet series == merging the replicas'
    # OWN scrapes (every histogram name, every bucket line)
    for hn in ("serve_ttft_seconds", "serve_itl_seconds",
               "serve_e2e_seconds"):
        merged = merge_histograms(
            [s["histograms"][hn] for s in direct.values()])
        for want in render_hist_snap(merged, header=False):
            assert want in lines, want
    for addr, snap in direct.items():
        assert (f'serve_fleet_requests_total{{event="completed",'
                f'replica="{addr}"}} {snap["counters"]["completed"]}'
                in lines)
        assert (f'serve_weights_version{{replica="{addr}",'
                f'version="demo"}} 1' in lines)
    done_total = sum(s["counters"]["completed"] for s in direct.values())
    assert f'serve_fleet_requests_total{{event="completed"}} {done_total}' \
        in lines
    # router /metrics carries the SLO gauges; /metrics.json federates
    assert m_status == 200
    assert 'slo_burn_rate{slo="ttft_p99",window_s="300"}' in rmetrics
    assert 'slo_error_budget_remaining{slo="availability"}' in rmetrics
    assert j_status == 200 and json.loads(rjson)["kind"] == "router"
