"""Host-RAM KV tier (ops/kv_tier.py + the engine/scheduler/router
wiring): HostTier budget/LRU accounting, demote-at-eviction, promote-hit
bit parity against a never-evicted baseline across attention flavors and
cache dtypes, COW safety when a promoted chain forks, preemption-resume
through a demoted prefix, the one-promote-trace pin, knob gating, and
the radix-prefix digest advertisement the cache-aware router matches."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.config import LLMConfig
from distributed_pytorch_tpu.engine import DecodeEngine
from distributed_pytorch_tpu.models.generate import generate
from distributed_pytorch_tpu.models.gpt import LLM
from distributed_pytorch_tpu.ops import kv_tier


def tiny_cfg(**kw):
    base = dict(vocab_size=97, block_size=64, n_embd=48, n_head=4,
                n_kv_heads=2, attn="gqa", n_layer=2, up_dim=64,
                non_linearity="swiglu", pos_emb="rope", dropout=0.0,
                q_latent_dim=16, kv_latent_dim=16, rope_head_dim=8)
    base.update(kw)
    return LLMConfig(**base)


def build(cfg, seed=0, attn_impl="naive"):
    model = LLM(cfg, attn_impl=attn_impl)
    rng = jax.random.PRNGKey(seed)
    x = jnp.zeros((1, cfg.block_size), jnp.int32)
    variables = model.init({"params": rng, "dropout": rng}, x, x)
    return model, {k: v for k, v in variables.items()}


# all prompt tokens MUST stay < vocab_size: out-of-vocab ids embed to
# NaN rows which poison recycled cache blocks through exact masking
A = [(7 * i + 3) % 97 for i in range(27)]        # 3 full blocks @ bs 8
CHURN = [[(11 * i + j + 1) % 97 for i in range(33)] for j in range(3)]
SCHEDULE = [(A, 6)] + [(c, 8) for c in CHURN] + [(A, 6)]


def tier_engine(model, variables, cache_dtype=None, *, n_blocks=12,
                host_tier=True, host_blocks=64, n_slots=2):
    """Engine with a pool tiny enough that the CHURN prompts genuinely
    evict A's chain (11 usable blocks vs ~18 of churn working set)."""
    return DecodeEngine(model, variables, n_slots=n_slots,
                        temperature=0.0, min_bucket=8,
                        cache_dtype=cache_dtype, n_blocks=n_blocks,
                        host_tier=host_tier, host_blocks=host_blocks)


def run_schedule(eng, schedule):
    """One request at a time, in order — deterministic eviction order."""
    return [eng.run([p], b)[0] for p, b in schedule]


# ----------------------------------------------------------------------
# HostTier unit tests (no device work)
# ----------------------------------------------------------------------

def test_host_tier_lru_cap_and_counters():
    tier = kv_tier.HostTier(2)
    rows = {"k": np.ones((4, 2), np.float32)}     # 32 bytes
    tier.demote(("a",), rows)
    tier.demote(("b",), rows)
    assert tier.n_blocks == 2 and tier.occupancy == 1.0
    tier.demote(("c",), rows)                     # cap: LRU ("a") dropped
    assert tier.counters()["dropped"] == 1
    assert not tier.contains(("a",)) and tier.contains(("b",))
    # re-demoting a resident key refreshes LRU position, no double store
    tier.demote(("b",), rows)
    assert tier.n_blocks == 2 and tier.counters()["demoted"] == 3
    tier.demote(("d",), rows)                     # "c" is now LRU
    assert not tier.contains(("c",)) and tier.contains(("b",))
    # promotion CONSUMES the entry: one copy across the two tiers
    got = tier.pop(("b",))
    assert np.array_equal(got["k"], rows["k"])
    assert not tier.contains(("b",))
    c = tier.counters()
    assert c["promoted"] == 1 and c["resident_blocks"] == 1
    assert tier.drain_promote_events() == [32]
    assert tier.drain_promote_events() == []      # drained
    # probe accounting feeds the hit-rate gauge
    assert 0.0 < tier.hit_rate < 1.0


def test_host_tier_needs_positive_budget():
    with pytest.raises(AssertionError):
        kv_tier.HostTier(0)


# ----------------------------------------------------------------------
# engine: demote at eviction, promote on radix hit, bit parity
# ----------------------------------------------------------------------

@pytest.mark.parametrize("kw,cache_dtype", [
    (dict(attn="mha", n_kv_heads=4), None),
    (dict(attn="mha", n_kv_heads=4), "int8"),
    (dict(attn="gqa", n_kv_heads=2), "bfloat16"),
    (dict(attn="gqa", n_kv_heads=2), "int8"),
    (dict(attn="mla"), "bfloat16"),
    (dict(attn="mla"), "int8"),
], ids=["mha-native", "mha-int8", "gqa-bf16", "gqa-int8",
        "mla-bf16", "mla-int8"])
def test_promote_hit_parity_vs_never_evicted(kw, cache_dtype):
    """Run A, churn the tiny pool until A's chain demotes to host RAM,
    run A again (promote path). Every output must be bit-identical to
    the same schedule on a pool big enough that nothing ever evicts —
    the promoted rows ARE the rows that were demoted."""
    cfg = tiny_cfg(**kw)
    model, variables = build(cfg)
    eng = tier_engine(model, variables, cache_dtype)
    outs = run_schedule(eng, SCHEDULE)
    c = eng.host_tier.counters()
    assert c["demoted"] > 0, "churn never evicted — the pool is too big"
    assert c["promoted"] > 0, "re-admitting A never promoted"
    assert c["dropped"] == 0
    assert eng.promote_traces == 1       # ONE compiled promote program
    base = tier_engine(model, variables, cache_dtype, n_blocks=64,
                       host_tier=False)
    refs = run_schedule(base, SCHEDULE)
    assert base.host_tier is None and base.promote_traces == 0
    for (p, _), out, ref in zip(SCHEDULE, outs, refs):
        assert out == ref, f"promote path diverged for prompt {p[:4]}..."


def test_promote_hit_matches_offline_generate():
    """The full demote->promote round trip against the offline one-shot
    path (native cache): re-admitted A continues exactly as generate."""
    cfg = tiny_cfg()
    model, variables = build(cfg)
    eng = tier_engine(model, variables)
    outs = run_schedule(eng, SCHEDULE)
    assert eng.host_tier.counters()["promoted"] > 0
    ref = generate(model, variables, jnp.asarray(A, jnp.int32)[None], 6,
                   temperature=0.0)[0].tolist()
    assert outs[0] == ref and outs[-1] == ref


def test_cow_fork_on_promoted_chain():
    """Two concurrent requests fork off the SAME promoted prefix with
    different suffixes: the shared promoted blocks must stay immutable
    (partial tails are always private), and both streams must match the
    never-evicted baseline."""
    cfg = tiny_cfg()
    model, variables = build(cfg)
    fork = [[t for t in A] + [50], [t for t in A] + [60]]
    eng = tier_engine(model, variables)
    run_schedule(eng, SCHEDULE[:-1])     # A cached, then demoted by churn
    outs = eng.run(fork, max_new_tokens=5)
    assert eng.host_tier.counters()["promoted"] > 0
    base = tier_engine(model, variables, n_blocks=64, host_tier=False)
    run_schedule(base, SCHEDULE[:-1])
    refs = base.run(fork, max_new_tokens=5)
    assert outs == refs


def test_preemption_resume_through_demoted_prefix():
    """Pool pressure mid-decode preempts the youngest sequence; with the
    tier on, the blocks its resume needs may have been demoted in the
    meantime. run() requeues, the resume promotes, and the output stays
    bit-identical to an unpressured run."""
    cfg = tiny_cfg()
    model, variables = build(cfg)
    prompts = [[(5 * i + j + 2) % 97 for i in range(30)] for j in range(3)]
    eng = tier_engine(model, variables)
    outs = eng.run(prompts, max_new_tokens=20)
    assert eng.retire_counts["preempted"] > 0, \
        "pool never preempted — pressure too low for the test to bite"
    assert eng.host_tier.counters()["demoted"] > 0
    base = tier_engine(model, variables, n_blocks=64, host_tier=False)
    refs = base.run(prompts, max_new_tokens=20)
    assert outs == refs


def test_host_lru_cap_bounds_tier_and_counts_drops():
    """A 2-block host budget under heavy churn: the tier never holds
    more than its cap and every overflow is a counted drop — the only
    way tier-managed KV is ever lost."""
    cfg = tiny_cfg()
    model, variables = build(cfg)
    eng = tier_engine(model, variables, host_blocks=2)
    run_schedule(eng, SCHEDULE)
    c = eng.host_tier.counters()
    assert c["resident_blocks"] <= 2
    assert c["dropped"] > 0
    assert c["dropped"] + c["promoted"] + c["resident_blocks"] \
        == c["demoted"]


# ----------------------------------------------------------------------
# gating: knobs, prefix_cache, tier-off engines
# ----------------------------------------------------------------------

def test_tier_gating_constructor_and_knobs(monkeypatch):
    cfg = tiny_cfg()
    model, variables = build(cfg)
    # constructor off beats any knob
    monkeypatch.setenv("KV_HOST_TIER", "on")
    eng = DecodeEngine(model, variables, n_slots=1, temperature=0.0,
                       min_bucket=8, host_tier=False)
    assert eng.host_tier is None and eng.block_pool.on_evict is None
    # knob on, no budget: defaults to mirroring the HBM pool
    eng = DecodeEngine(model, variables, n_slots=1, temperature=0.0,
                       min_bucket=8)
    assert eng.host_tier is not None
    assert eng.host_tier.capacity == eng.n_blocks
    # auto + zero budget = off; auto + budget = on with that budget
    monkeypatch.setenv("KV_HOST_TIER", "auto")
    eng = DecodeEngine(model, variables, n_slots=1, temperature=0.0,
                       min_bucket=8)
    assert eng.host_tier is None
    monkeypatch.setenv("KV_HOST_BLOCKS", "7")
    eng = DecodeEngine(model, variables, n_slots=1, temperature=0.0,
                       min_bucket=8)
    assert eng.host_tier is not None and eng.host_tier.capacity == 7
    # no radix index -> nothing to key demotions under -> forced off
    eng = DecodeEngine(model, variables, n_slots=1, temperature=0.0,
                       min_bucket=8, prefix_cache=False, host_tier=True)
    assert eng.host_tier is None


# ----------------------------------------------------------------------
# the router-facing radix-prefix digest
# ----------------------------------------------------------------------

def test_kv_digest_matches_router_prompt_digests():
    """The engine's advertised chain digests and the router's
    client-side prompt digests are the same fold: after serving A, a
    same-prefix prompt must match at exactly A's full-block depth — and
    the advertisement works with the tier OFF too (stickiness pays for
    plain HBM reuse)."""
    from distributed_pytorch_tpu.serve.router import prompt_chain_digests
    cfg = tiny_cfg()
    model, variables = build(cfg)
    for tier in (True, False):
        eng = tier_engine(model, variables, n_blocks=64, host_tier=tier)
        assert eng.kv_digest()["entries"] == []      # nothing cached yet
        eng.run([A], max_new_tokens=6)
        adv = eng.kv_digest()
        assert adv["block_size"] == eng.block_size
        depths = [d for d, _ in adv["entries"]]
        assert depths == sorted(depths, reverse=True)  # deepest first
        assert eng.kv_digest(1)["entries"] == adv["entries"][:1]
        index = {hx: d for d, hx in adv["entries"]}
        cands = prompt_chain_digests([t for t in A] + [50],
                                     adv["block_size"])
        match = next((d for d, hx in cands if hx in index), 0)
        assert match == len(A) // eng.block_size, \
            "same-prefix prompt must match at its full-block depth"
        # an unrelated prompt matches nothing
        other = prompt_chain_digests([96 - t for t in A],
                                     adv["block_size"])
        assert all(hx not in index for _, hx in other)


def test_prompt_chain_digests_shape():
    from distributed_pytorch_tpu.serve.router import prompt_chain_digests
    assert prompt_chain_digests([1, 2, 3], 8) == []      # no full block
    assert prompt_chain_digests([1] * 20, 0) == []       # no advert yet
    two = prompt_chain_digests([1] * 20, 8)              # 2 full blocks
    assert [d for d, _ in two] == [2, 1]
    # digests are chain (ancestry) digests: depth 1 of a different
    # prefix differs, same prefix agrees
    assert prompt_chain_digests([1] * 9, 8)[0][1] == two[1][1]
    assert prompt_chain_digests([2] * 9, 8)[0][1] != two[1][1]
