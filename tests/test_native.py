"""Native C++ sampler (csrc/sampler.cpp) vs the NumPy reference: the two
backends must produce bit-identical batches, so a run can move between
machines with/without a toolchain (or resume across them) without changing
its data stream."""

import numpy as np
import pytest

from distributed_pytorch_tpu.data.loader import DataLoader, make_synthetic_bin
from distributed_pytorch_tpu.data import native


@pytest.fixture(scope="module")
def bin_path(tmp_path_factory):
    p = tmp_path_factory.mktemp("native") / "train.bin"
    return make_synthetic_bin(str(p), n_tokens=2 ** 15)


needs_native = pytest.mark.skipif(not native.native_available(),
                                  reason="g++ toolchain unavailable")


@needs_native
def test_native_matches_numpy_offsets():
    """The C++ sample_offset and the NumPy philox_offsets must be
    bit-identical — asserted directly on the exported offset stream."""
    rows = np.arange(64, dtype=np.uint32)
    for seed, step, hi in [(1729, 3, 10_000), (42, 0, 7), (2 ** 63, 11, 31),
                           (0, 2 ** 40, 999_983)]:
        a = native.philox_offsets(seed, step, rows, hi)
        b = native.native_offsets(seed, step, rows, hi)
        assert (a == b).all(), (seed, step, hi)
    a = native.philox_offsets(1729, 3, rows, 10_000)
    c = native.philox_offsets(1729, 4, rows, 10_000)
    assert (a != c).any()  # step changes the stream
    d = native.philox_offsets(42, 3, rows, 10_000)
    assert (a != d).any()  # seed changes the stream


@needs_native
def test_native_loader_matches_numpy_loader(bin_path):
    ln = DataLoader(bin_path, 4, 32, grad_accum=2, seed=7, backend="native")
    lp = DataLoader(bin_path, 4, 32, grad_accum=2, seed=7, backend="numpy")
    assert ln.backend == "native" and lp.backend == "numpy"
    for _ in range(3):
        xn, yn = ln.next_batch()
        xp, yp = lp.next_batch()
        assert (np.asarray(xn) == np.asarray(xp)).all()
        assert (np.asarray(yn) == np.asarray(yp)).all()


@needs_native
def test_native_row_subset_matches_full(bin_path):
    s = native.NativeSampler(bin_path)
    x_full, y_full = s.sample(7, 5, 8, 32)
    rows = np.array([1, 3, 6], np.uint32)
    x_sub, y_sub = s.sample_rows(7, 5, rows, 32)
    assert (x_sub == x_full[rows]).all()
    assert (y_sub == y_full[rows]).all()
    s.close()


@needs_native
def test_native_prefetch_consistency(bin_path):
    """Sequential steps hit the prefetch buffer; results must equal cold
    gathers."""
    s1 = native.NativeSampler(bin_path)
    seq = [s1.sample(9, step, 4, 16) for step in range(5)]  # warm path
    s2 = native.NativeSampler(bin_path)
    for step in [4, 2, 0]:  # cold, out-of-order
        x, y = s2.sample(9, step, 4, 16)
        assert (x == seq[step][0]).all() and (y == seq[step][1]).all()
    s1.close()
    s2.close()


@needs_native
def test_shift_invariant(bin_path):
    s = native.NativeSampler(bin_path)
    x, y = s.sample(11, 0, 4, 32)
    assert (x[:, 1:] == y[:, :-1]).all()
    s.close()


def test_numpy_fallback_loader_works(bin_path):
    loader = DataLoader(bin_path, 2, 16, backend="numpy")
    x, y = loader.next_batch()
    assert x.shape == (1, 2, 16)
    assert (np.asarray(x)[:, :, 1:] == np.asarray(y)[:, :, :-1]).all()
