"""Control plane (serve/control.py + sim/fleetsim.py): SLO-class
admission and voluntary batch preemption in the scheduler, per-tenant
token-bucket fairness and class-aware retry accounting in the router,
the forecast autoscaler's decide() policy and its actuation wiring, and
the discrete-event fleet simulator's byte-determinism.

The live e2e here is the acceptance drill from the control-plane round:
three CPU replicas driven through the router with a mixed-class
overload — interactive TTFT p99 must hold within SLO_TTFT_P99_S while
batch absorbs 100% of the preemptions and loses ZERO streams (every
batch token sequence bit-identical to the offline engine)."""

import asyncio
import json
import time
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import pytest

from distributed_pytorch_tpu.config import LLMConfig, knob
from distributed_pytorch_tpu.engine import DecodeEngine
from distributed_pytorch_tpu.models.gpt import LLM
from distributed_pytorch_tpu.serve.control import (Autoscaler,
                                                   ClassPolicy,
                                                   FleetSample,
                                                   TokenBucketFairness,
                                                   normalize_class)
from distributed_pytorch_tpu.serve.router import Router, RouterApp
from distributed_pytorch_tpu.serve.scheduler import Scheduler, ShedError
from distributed_pytorch_tpu.serve.server import ServeApp


def tiny_cfg(**kw):
    base = dict(vocab_size=97, block_size=64, n_embd=48, n_head=4,
                n_kv_heads=2, attn="gqa", n_layer=2, up_dim=64,
                non_linearity="swiglu", pos_emb="rope", dropout=0.0)
    base.update(kw)
    return LLMConfig(**base)


@pytest.fixture(scope="module")
def mv():
    cfg = tiny_cfg()
    model = LLM(cfg, attn_impl="naive")
    rng = jax.random.PRNGKey(0)
    x = jnp.zeros((1, cfg.block_size), jnp.int32)
    variables = dict(model.init({"params": rng, "dropout": rng}, x, x))
    return cfg, model, variables


def run_async(coro, timeout=300):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def make_engine(mv, n_slots=2, **kw):
    _, model, variables = mv
    kw.setdefault("temperature", 0.0)
    kw.setdefault("min_bucket", 8)
    return DecodeEngine(model, variables, n_slots=n_slots, **kw)


def slow_engine(mv, n_slots=2, step_delay=0.005, **kw):
    """Engine with throttled decode steps so batch work stays live long
    enough for an interactive burst to land mid-decode."""
    eng = make_engine(mv, n_slots=n_slots, **kw)
    orig = eng.step

    def slow_step():
        time.sleep(step_delay)
        return orig()

    eng.step = slow_step
    return eng


def offline_ref(mv, prompts, budgets):
    _, model, variables = mv
    eng = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                       min_bucket=8)
    return eng.run(prompts, budgets)


class Rep:
    def __init__(self, mv, *, n_slots=2, step_delay=0.0, max_queue=32):
        self.eng = (slow_engine(mv, n_slots=n_slots,
                                step_delay=step_delay)
                    if step_delay else make_engine(mv, n_slots=n_slots))
        self.sched = Scheduler(self.eng, max_queue=max_queue)
        self.app = ServeApp(self.sched, port=0)

    async def start(self):
        await self.sched.start()
        await self.app.start()
        return self

    @property
    def addr(self):
        return f"127.0.0.1:{self.app.port}"

    async def stop(self):
        await self.app.stop()
        await self.sched.stop()


def make_router(*reps, **kw):
    kw.setdefault("probe_interval_s", 0.05)
    kw.setdefault("probe_timeout_s", 1.0)
    kw.setdefault("fail_threshold", 2)
    kw.setdefault("backoff_base_s", 0.05)
    kw.setdefault("backoff_cap_s", 0.5)
    kw.setdefault("connect_timeout_s", 1.0)
    return Router([r.addr if isinstance(r, Rep) else r for r in reps],
                  **kw)


async def http_req(port, path, obj, headers=None):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    body = json.dumps(obj).encode()
    extra = "".join(f"{k}: {v}\r\n" for k, v in (headers or {}).items())
    writer.write(f"POST {path} HTTP/1.1\r\nHost: t\r\n{extra}"
                 f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
    await writer.drain()
    data = await reader.read()
    writer.close()
    head, _, payload = data.partition(b"\r\n\r\n")
    return int(head.split(b" ")[1]), payload.decode()


# ----------------------------------------------------------------------
# pure policy units (no engine, injected clocks)
# ----------------------------------------------------------------------

def test_normalize_class():
    assert normalize_class(None) == knob("SLO_CLASS_DEFAULT")
    assert normalize_class("", default="batch") == "batch"
    assert normalize_class("  Batch ") == "batch"
    with pytest.raises(ValueError):
        normalize_class("premium")


def test_token_bucket_burst_then_sustained_rate():
    t = [0.0]
    fb = TokenBucketFairness(rate_tokens_s=2.0, burst=3.0,
                             now_fn=lambda: t[0])
    assert [fb.admit("hot") for _ in range(4)] == [True] * 3 + [False]
    # a different tenant's bucket is untouched by hot's exhaustion
    assert fb.admit("polite")
    # anonymous traffic is never limited
    assert all(fb.admit(None) for _ in range(50))
    t[0] = 1.0                          # refill 2 tokens at 2/s
    assert [fb.admit("hot") for _ in range(3)] == [True, True, False]
    snap = fb.snapshot()
    assert snap["hot"]["admitted"] == 5 and snap["hot"]["rejected"] == 2
    assert snap["polite"]["rejected"] == 0
    # rate <= 0 disables fairness entirely (the A/B off arm)
    off = TokenBucketFairness(rate_tokens_s=0.0, now_fn=lambda: t[0])
    assert not off.enabled
    assert all(off.admit("hot") for _ in range(100))


def _q(*specs):
    return [SimpleNamespace(slo_class=c, resumed=r) for c, r in specs]


def test_class_policy_queue_ordering():
    # interactive enters ahead of the batch section, FCFS within class
    q = _q(("interactive", False), ("batch", False), ("batch", False))
    assert ClassPolicy.insert_index(q, "interactive") == 1
    assert ClassPolicy.insert_index(q, "batch") == 3
    # resumed batch: FRONT of the batch section (behind interactive,
    # behind earlier resumes — order within the resumed group holds)
    q = _q(("interactive", False), ("batch", True), ("batch", False))
    assert ClassPolicy.insert_index(q, "batch", resumed=True) == 2
    assert ClassPolicy.insert_index(q, "interactive", resumed=True) == 0
    assert ClassPolicy.queued_interactive(q) == 1


def test_class_policy_preempt_count_and_victims():
    # free slots cover the backlog -> no preemption
    assert ClassPolicy.preempt_count(2, 2, 5) == 0
    # backlog beyond free slots, capped at the evictable population
    assert ClassPolicy.preempt_count(4, 1, 2) == 2
    assert ClassPolicy.preempt_count(4, 0, 10) == 4
    live = [SimpleNamespace(admitted_at=t, served=s, name=n)
            for n, t, s in (("old", 1.0, 50), ("new", 3.0, 2),
                            ("mid", 2.0, 10))]
    # most recently admitted evicted first: least progress lost
    assert [v.name for v in ClassPolicy.pick_victims(live, 2)] \
        == ["new", "mid"]


def test_autoscaler_scales_ahead_of_ramp_and_down_with_hysteresis():
    t = [0.0]
    a = Autoscaler(min_replicas=2, max_replicas=32, lead_s=15.0,
                   knee_occupancy=0.85, cooldown_s=0.0,
                   now_fn=lambda: t[0])
    n = 4
    # occupancy ramping 2%/s: the slope forecast must trigger scale-up
    # BEFORE occupancy itself reaches the knee
    occ = 0.0
    grew_at_occ = None
    for i in range(40):
        t[0] = float(i)
        occ = min(0.97, 0.30 + 0.02 * i)
        d = a.decide(FleetSample(t=t[0], n_replicas=n, occupancy=occ))
        if d > 0 and grew_at_occ is None:
            grew_at_occ = occ
        n += max(0, d)
    assert grew_at_occ is not None and grew_at_occ < 0.85
    assert n > 4 and a.scaled_up >= n - 4
    # quiet fleet: drains one at a time, never below min_replicas
    for i in range(40, 140):
        t[0] = float(i)
        d = a.decide(FleetSample(t=t[0], n_replicas=n, occupancy=0.05))
        assert d >= -1
        n += d
    assert n == 2 == a.min_replicas
    # burn rate alone is scale-up pressure even at low occupancy
    t[0] = 200.0
    assert a.decide(FleetSample(t=200.0, n_replicas=n, occupancy=0.1,
                                worst_burn=2.5)) > 0


def test_autoscaler_cooldown_gates_consecutive_actions():
    t = [0.0]
    a = Autoscaler(min_replicas=1, max_replicas=16, lead_s=10.0,
                   knee_occupancy=0.85, cooldown_s=5.0,
                   now_fn=lambda: t[0])
    assert a.decide(FleetSample(t=0.0, n_replicas=2,
                                occupancy=0.95)) > 0
    t[0] = 1.0      # inside the cooldown: hold even under pressure
    assert a.decide(FleetSample(t=1.0, n_replicas=2,
                                occupancy=0.99)) == 0
    t[0] = 6.0
    assert a.decide(FleetSample(t=6.0, n_replicas=2,
                                occupancy=0.99)) > 0


# ----------------------------------------------------------------------
# scheduler: voluntary class preemption, lossless resume
# ----------------------------------------------------------------------

def test_interactive_preempts_batch_losslessly(mv):
    """Both slots full of live batch work; an interactive burst must
    evict batch through the engine's preempt/requeue path and the
    evicted batch streams must still produce their full budget,
    bit-identical to the offline engine."""
    b_prompts = [[1, 2, 3], [5, 6, 7]]
    b_budgets = [40, 40]

    async def main():
        eng = slow_engine(mv, n_slots=2, step_delay=0.005)
        sched = Scheduler(eng, max_queue=16)
        await sched.start()
        batch = [sched.submit(p, b, slo_class="batch")
                 for p, b in zip(b_prompts, b_budgets)]
        drains = [asyncio.create_task(h.result()) for h in batch]
        # preempt only once the victims hold whole retained blocks, so
        # the resume demonstrably re-admits through the prefix cache
        while min(len(h.tokens) for h in batch) < 16:
            await asyncio.sleep(0.005)
        inter = [sched.submit([40 + i], 5, slo_class="interactive")
                 for i in range(2)]
        await asyncio.gather(*drains,
                             *(h.result() for h in inter))
        await sched.stop()
        return eng, sched, batch, inter

    eng, sched, batch, inter = run_async(main(), timeout=120)
    m = sched.metrics
    # batch absorbed every preemption; interactive was never evicted
    assert m.class_counts.get("preempted|batch", 0) >= 2
    assert m.class_counts.get("preempted|interactive", 0) == 0
    assert m.counters["shed"] == 0
    # interactive reached slots while batch work was still outstanding
    for h in inter:
        assert h.retired.reason == "budget" and len(h.tokens) == 5
    # lossless resume: full budget, bit-exact vs offline greedy
    refs = offline_ref(mv, b_prompts, b_budgets)
    for h, p, ref in zip(batch, b_prompts, refs):
        assert h.retired.reason == "budget"
        assert h.tokens == ref[len(p):]
    # the resume re-admits through the retained prefix (cache hit)
    assert eng.prefix_hit_tokens > 0
    # per-class TTFT histograms exist for both classes
    assert m.ttft_class("interactive") is not None
    assert m.ttft_class("batch") is not None


def test_resumed_batch_timeout_sheds_with_cause(mv):
    """With SLO_BATCH_RESUME_TIMEOUT_S set, a preempted batch request
    that cannot re-admit inside the window sheds with the dedicated
    cause instead of waiting forever (default 0 = never)."""

    async def main():
        eng = slow_engine(mv, n_slots=1, step_delay=0.01)
        sched = Scheduler(eng, max_queue=16,
                          batch_resume_timeout_s=0.01)
        await sched.start()
        b = sched.submit([1, 2, 3], 60, slo_class="batch")
        while b.admitted_at is None:
            await asyncio.sleep(0.005)
        # a stream of interactive work monopolizes the single slot
        inter = [sched.submit([50 + i], 25, slo_class="interactive")
                 for i in range(4)]
        results = await asyncio.gather(
            *(h.result() for h in [b] + inter), return_exceptions=True)
        await sched.stop()
        return sched, results

    sched, results = run_async(main(), timeout=120)
    errs = [r for r in results if isinstance(r, ShedError)]
    assert errs and errs[0].cause == "preempted_batch_timeout"
    assert sched.metrics.shed_class_counts.get(
        "preempted_batch_timeout|batch", 0) >= 1


# ----------------------------------------------------------------------
# router: tenant fairness + class isolation e2e (3 CPU replicas)
# ----------------------------------------------------------------------

def test_router_tenant_fairness_sheds_hot_tenant_only(mv):
    async def main():
        rep = await Rep(mv).start()
        fairness = TokenBucketFairness(rate_tokens_s=0.001, burst=2.0)
        router = make_router(rep, fairness=fairness)
        await router.start()
        hot_ok, hot_shed = 0, 0
        for i in range(5):
            try:
                out = await router.complete([1 + i], 2, tenant="hot")
                assert out["reason"] == "budget"
                hot_ok += 1
            except ShedError as e:
                assert e.cause == "rate_limited"
                hot_shed += 1
        # the polite tenant and anonymous traffic are untouched
        polite = await router.complete([9], 2, tenant="polite")
        anon = await router.complete([11], 2)
        await router.stop()
        await rep.stop()
        return router, hot_ok, hot_shed, polite, anon

    router, hot_ok, hot_shed, polite, anon = run_async(main(), timeout=120)
    assert hot_ok == 2 and hot_shed == 3      # burst spent, then capped
    assert polite["reason"] == "budget" and anon["reason"] == "budget"
    m = router.metrics
    assert m.shed_tenant_counts.get("rate_limited|hot", 0) == 3
    assert m.shed_class_counts.get("rate_limited|interactive", 0) == 3
    # the shed ledger reaches the fleet page with tenant labels
    page = router.render_fleet()
    assert 'router_shed_total{cause="rate_limited",tenant="hot"} 3' in page


def test_mixed_class_overload_isolation_three_replicas(mv):
    """The acceptance drill: 3 CPU replicas, batch saturating every
    slot, then an interactive wave through the router. Interactive TTFT
    p99 holds within SLO_TTFT_P99_S; batch absorbs 100% of preemptions,
    zero batch streams lost (bit-exact vs offline)."""
    n_batch, n_inter = 9, 9
    b_prompts = [[1 + i, 2 + i, 3 + i] for i in range(n_batch)]
    b_budgets = [28] * n_batch
    i_prompts = [[60 + i] for i in range(n_inter)]
    i_budgets = [6] * n_inter

    async def main():
        reps = [Rep(mv, n_slots=2, step_delay=0.004) for _ in range(3)]
        # warm every prefill bucket and the decode trace per engine
        # BEFORE the measured phase — the SLO claim is about scheduling
        # under load, not about first-compile latency
        await asyncio.gather(*(
            asyncio.to_thread(r.eng.run,
                              [[1, 2, 3], [2] * 12, [3] * 24, [5]],
                              [28, 4, 4, 6])
            for r in reps))
        for r in reps:
            await r.start()
        router = make_router(*reps, fleet_poll_interval_s=0.05)
        await router.start()
        batch_tasks = [
            asyncio.create_task(router.complete(p, b, slo_class="batch"))
            for p, b in zip(b_prompts, b_budgets)]
        # let batch reach the slots before the interactive wave lands
        await asyncio.sleep(0.3)
        inter_outs = await asyncio.gather(*(
            router.complete(p, b, slo_class="interactive")
            for p, b in zip(i_prompts, i_budgets)))
        batch_outs = await asyncio.gather(*batch_tasks)
        await asyncio.sleep(0.3)       # one federation pull post-traffic
        page = router.render_fleet()
        scheds = [r.sched for r in reps]
        await router.stop()
        for r in reps:
            await r.stop()
        return router, scheds, batch_outs, inter_outs, page

    router, scheds, batch_outs, inter_outs, page = \
        run_async(main(), timeout=300)

    # zero batch streams lost, token-exact resume parity vs offline
    refs = offline_ref(mv, b_prompts, b_budgets)
    for p, out, ref in zip(b_prompts, batch_outs, refs):
        assert out["reason"] == "budget"
        assert out["tokens"] == ref[len(p):], f"batch diverged for {p}"
    for out in inter_outs:
        assert out["reason"] == "budget" and len(out["tokens"]) == 6

    # batch absorbed 100% of the preemptions
    pre_batch = sum(s.metrics.class_counts.get("preempted|batch", 0)
                    for s in scheds)
    pre_inter = sum(s.metrics.class_counts.get("preempted|interactive", 0)
                    for s in scheds)
    assert pre_batch >= 1, "overload was sized to force preemption"
    assert pre_inter == 0
    assert sum(s.metrics.counters["shed"] for s in scheds) == 0
    assert router.metrics.counters["shed"] == 0

    # interactive TTFT p99 within SLO while the fleet was saturated
    h = router.metrics.ttft_class("interactive")
    assert h is not None and h.count == n_inter
    assert h.quantile(0.99) <= knob("SLO_TTFT_P99_S"), \
        f"interactive p99 {h.quantile(0.99):.3f}s blew the SLO"
    # per-class series are rendered on the federated fleet page
    assert 'class="interactive"' in page and 'class="batch"' in page


def test_http_class_and_tenant_plumbing(mv):
    """HTTP edge: X-SLO-Class/X-Tenant-Id headers reach the policies;
    an unknown class is a 400, a rate-limited tenant a 429 with the
    explicit cause."""

    async def main():
        rep = await Rep(mv).start()
        fairness = TokenBucketFairness(rate_tokens_s=0.001, burst=1.0)
        router = make_router(rep, fairness=fairness)
        await router.start()
        app = RouterApp(router, port=0, default_slo_class="batch")
        await app.start()
        bad = await http_req(app.port, "/v1/completions",
                             {"prompt": [1], "max_tokens": 2},
                             headers={"X-SLO-Class": "premium"})
        ok = await http_req(app.port, "/v1/completions",
                            {"prompt": [1], "max_tokens": 2},
                            headers={"X-Tenant-Id": "hog"})
        limited = await http_req(app.port, "/v1/completions",
                                 {"prompt": [2], "max_tokens": 2},
                                 headers={"X-Tenant-Id": "hog"})
        await app.stop()
        await router.stop()
        await rep.stop()
        return router, bad, ok, limited

    router, bad, ok, limited = run_async(main(), timeout=120)
    assert bad[0] == 400 and "premium" in bad[1]
    assert ok[0] == 200
    assert limited[0] == 429
    assert json.loads(limited[1])["cause"] == "rate_limited"
    # header absent -> the app-level default class was applied
    assert router.metrics.shed_class_counts.get(
        "rate_limited|batch", 0) == 1


# ----------------------------------------------------------------------
# router: autoscaler actuation wiring (fake launcher, no subprocesses)
# ----------------------------------------------------------------------

def test_autoscale_tick_spawns_through_launcher(mv):
    class FakeLauncher:
        def __init__(self, addrs):
            self.pending = list(addrs)
            self.procs = {}
            self.terminated = []

        def spawn(self):
            addr = self.pending.pop(0)
            self.procs[addr] = object()
            return addr

        def terminate(self, addr, timeout_s=5.0):
            self.terminated.append(addr)
            return self.procs.pop(addr, None) is not None

        def shutdown(self):
            pass

    async def main():
        reps = [await Rep(mv).start() for _ in range(2)]
        spare = await Rep(mv).start()
        launcher = FakeLauncher([spare.addr])
        scaler = Autoscaler(min_replicas=1, max_replicas=3, lead_s=5.0,
                            knee_occupancy=0.85, cooldown_s=0.0)
        router = make_router(*reps, autoscaler=scaler, launcher=launcher,
                             autoscale_interval_s=3600.0)  # manual ticks
        await router.start()
        for _ in range(60):
            if all(r.state == "healthy"
                   for r in router.replicas.values()):
                break
            await asyncio.sleep(0.05)
        # forge pressure: sheds since the last sample force scale-up
        router.metrics.shed("queue_full")
        await router._autoscale_tick()
        spawned = list(launcher.procs)
        joined = spawned and spawned[0] in router.replicas
        await router.stop()
        for r in reps + [spare]:
            await r.stop()
        return scaler, spawned, joined

    scaler, spawned, joined = run_async(main(), timeout=120)
    assert scaler.scaled_up >= 1
    assert spawned and joined, "spawned replica must join the pool"


# ----------------------------------------------------------------------
# simulator: determinism + policy parity
# ----------------------------------------------------------------------

def test_fleetsim_deterministic_and_uses_live_policies():
    from sim import fleetsim

    def run():
        return fleetsim.run_report(seed=7, n_replicas=6, duration_s=4.0,
                                   cost_model=None, smoke=True)

    a, b = run(), run()
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)
    # the sim drives the LIVE policy classes, not a fork
    assert a["meta"]["policies"] == ["ClassPolicy", "TokenBucketFairness",
                                     "Autoscaler", "SLOTracker"]
    for name in ("fairness", "autoscale", "preemption"):
        assert name in a["scenarios"]
        assert a["scenarios"][name]["accept"]
    # a different seed produces a different trajectory
    c = fleetsim.run_report(seed=8, n_replicas=6, duration_s=4.0,
                            cost_model=None, smoke=True)
    assert json.dumps(a, sort_keys=True) != json.dumps(c, sort_keys=True)
    # preemption invariants hold even at smoke scale
    on = a["scenarios"]["preemption"]["arms"]["preempt_on"]
    assert on["preempted_then_shed"] == 0
    assert on["preempted_by_class"].get("interactive", 0) == 0
