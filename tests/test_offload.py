"""ZeRO-Offload tests (ISSUE 19): the split host-update step must be a
pure re-placement of the in-HBM AdamW step — params AND moments bitwise
identical after N steps on the same backend — plus the gate resolution
(OFFLOAD knob > TrainConfig.offload > memplan auto), the host sharding
tree checkpoint restore uses, interrupt/resume parity through the loop,
and the supervisor's prewarm gate (the offload step is not one
AOT-serializable program).

The full 2-process supervisor gang-restart with offload lives in
scripts/fault_inject_train.py (CI smoke leg), mirroring the
test_elastic.py split."""

import os
import signal

import jax
import numpy as np
import pytest

from distributed_pytorch_tpu.config import LLMConfig, TrainConfig
from distributed_pytorch_tpu.train import checkpoint as ckpt
from distributed_pytorch_tpu.train import memplan
from distributed_pytorch_tpu.train import offload
from distributed_pytorch_tpu.train import supervisor as sup
from distributed_pytorch_tpu.train.loop import train
from distributed_pytorch_tpu.train.state import TrainState, create_train_state
from distributed_pytorch_tpu.train.step import make_train_step

TINY = dict(vocab_size=128, block_size=32, n_embd=32, n_head=4,
            n_kv_heads=2, n_layer=2, up_dim=64)


def _tc(**kw):
    base = dict(dataset="synthetic", data_dir="bench_data",
                total_batch_size=2 * 2 * 32, batch_size=2,
                max_iters=5, parallelism="single", eval=False,
                log_interval=100, save_stats=False, learning_rate=1e-3,
                warmup_steps=2)
    base.update(kw)
    return TrainConfig(**base)


@pytest.fixture()
def in_tmp(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    return tmp_path


def _fake_batch(mc, accum, B, seed=0):
    rng = np.random.default_rng(seed)
    starts = rng.integers(0, mc.vocab_size, size=(accum, B, 1))
    seq = (starts + np.arange(mc.block_size + 1)) % mc.vocab_size
    import jax.numpy as jnp
    return (jnp.asarray(seq[..., :-1], jnp.int32),
            jnp.asarray(seq[..., 1:], jnp.int32))


def _tree_bytes(tree):
    return [np.asarray(l).tobytes() for l in jax.tree_util.tree_leaves(tree)]


# ---------------------------------------------------------------------------
# Bit-parity: offload vs in-HBM AdamW.
# ---------------------------------------------------------------------------

def test_offload_bitwise_parity_with_in_hbm_adamw():
    """3 steps, same batches: params, moments AND per-step loss must be
    byte-identical between the split host-update step and the fused
    in-HBM step — offload is a re-placement, not an approximation."""
    mc = LLMConfig(**TINY)
    tc = TrainConfig(total_batch_size=4 * 32, batch_size=2, max_iters=10,
                     warmup_steps=2, learning_rate=1e-2,
                     parallelism="single")
    model_a, tx_a, state_a, _ = create_train_state(mc, tc, None)
    model_b, tx_b, state_b, _ = create_train_state(mc, tc, None)
    assert _tree_bytes(state_a.params) == _tree_bytes(state_b.params), \
        "create_train_state init must be deterministic for this A/B"
    step_hbm = make_train_step(model_a, tx_a, mc, tc, None, None)
    step_off = make_train_step(model_b, tx_b, mc, tc, None, None,
                               offload=True)
    assert getattr(step_off, "offload", False), \
        "offload=True must dispatch to the split step"
    for i in range(3):
        x, y = _fake_batch(mc, 2, 2, seed=i)
        state_a, ma = step_hbm(state_a, x, y)
        state_b, mb = step_off(state_b, x, y)
        assert np.asarray(ma["loss"]).tobytes() == \
            np.asarray(mb["loss"]).tobytes(), f"loss diverged at step {i}"
    assert _tree_bytes(state_a.params) == _tree_bytes(state_b.params), \
        "params diverged after 3 steps"
    assert _tree_bytes(state_a.opt_state) == _tree_bytes(state_b.opt_state), \
        "optimizer moments diverged after 3 steps"
    assert int(jax.device_get(state_b.step)) == 3


def test_offload_reseeds_host_cache_on_replayed_state():
    """Replaying the SAME state (a restore / supervisor rejoin shape)
    must produce the same result as the first pass: the host master
    cache is keyed by the step counter and re-seeds on discontinuity."""
    mc = LLMConfig(**TINY)
    tc = TrainConfig(total_batch_size=4 * 32, batch_size=2, max_iters=10,
                     warmup_steps=2, learning_rate=1e-2,
                     parallelism="single")
    model, tx, state0, _ = create_train_state(mc, tc, None)
    step_off = make_train_step(model, tx, mc, tc, None, None, offload=True)
    keep = jax.tree_util.tree_map(np.array, state0.params)
    x, y = _fake_batch(mc, 2, 2, seed=11)
    s1, _ = step_off(state0, x, y)
    first = _tree_bytes(s1.params)
    replay = TrainState(
        step=np.zeros((), np.int32),
        params=jax.tree_util.tree_map(np.array, keep),
        opt_state=tx.init(jax.tree_util.tree_map(np.array, keep)),
        moe_state=state0.moe_state)
    s2, _ = step_off(replay, x, y)
    assert _tree_bytes(s2.params) == first


# ---------------------------------------------------------------------------
# Gate resolution: OFFLOAD knob > TrainConfig.offload > memplan auto.
# ---------------------------------------------------------------------------

def test_resolve_offload_knob_overrides_config(monkeypatch):
    mc = LLMConfig(**TINY)
    monkeypatch.setenv("OFFLOAD", "on")
    assert offload.resolve_offload(mc, _tc(offload="off")) is True
    monkeypatch.setenv("OFFLOAD", "off")
    assert offload.resolve_offload(mc, _tc(offload="on")) is False


def test_resolve_offload_config_modes(monkeypatch):
    monkeypatch.delenv("OFFLOAD", raising=False)
    mc = LLMConfig(**TINY)
    assert offload.resolve_offload(mc, _tc(offload="on")) is True
    assert offload.resolve_offload(mc, _tc(offload="off")) is False


def test_resolve_offload_is_single_controller_only(monkeypatch):
    """A multi-process gang cannot offload: no process addresses the
    whole grads/opt trees and the host clip would see local shards only.
    'on' must fail loudly at spin-up; 'auto' resolves to in-HBM."""
    monkeypatch.delenv("OFFLOAD", raising=False)
    mc = LLMConfig(**TINY)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    assert offload.resolve_offload(mc, _tc(offload="auto")) is False
    with pytest.raises(ValueError, match="single-controller"):
        offload.resolve_offload(mc, _tc(offload="on"))
    assert offload.resolve_offload(mc, _tc(offload="off")) is False


def test_resolve_offload_auto_is_a_memplan_decision(monkeypatch):
    """Auto turns on iff the in-HBM plan busts the budget AND the
    offload plan fits under it — probed by squeezing hbm_gb between the
    two analytic peaks."""
    monkeypatch.delenv("OFFLOAD", raising=False)
    mc = LLMConfig(**TINY)
    tc = _tc(offload="auto")
    base, _ = memplan.predicted_train_peak_gb(mc, tc, None)
    off, _ = memplan.predicted_train_peak_gb(mc, tc, None, offload=True)
    assert off < base  # moments out of the plan
    mid = (base + off) / 2
    assert offload.resolve_offload(mc, tc, None, hbm_gb=mid) is True
    # a budget both plans fit: stay in-HBM (no behavior cliff)
    assert offload.resolve_offload(mc, tc, None, hbm_gb=base * 2) is False
    # a budget neither fits: offload would not save the run — stay off
    assert offload.resolve_offload(mc, tc, None, hbm_gb=off / 2) is False


# ---------------------------------------------------------------------------
# Host sharding tree (checkpoint restore placement).
# ---------------------------------------------------------------------------

def test_host_state_sharding_repoints_only_opt_state():
    marker = object()
    tree = TrainState(step=marker, params={"w": marker},
                      opt_state={"mu": 0, "nu": {"a": 1}}, moe_state=marker)
    host = offload.host_state_sharding(tree)
    assert host.step is marker and host.params["w"] is marker
    assert host.moe_state is marker
    for leaf in jax.tree_util.tree_leaves(host.opt_state):
        assert isinstance(leaf, jax.sharding.SingleDeviceSharding)
        assert leaf._device == offload.host_device()


# ---------------------------------------------------------------------------
# Loop integration: interrupt + resume parity with offload on.
# ---------------------------------------------------------------------------

def test_offload_run_interrupts_and_resumes_bit_identical(in_tmp):
    """train() with offload='on': SIGINT mid-run checkpoints, the resumed
    run replays the exact tail of an uninterrupted run — restore lands
    the moments on the host device and the step re-seeds its master copy
    from the restored state."""
    mc = LLMConfig(**TINY)
    quiet = lambda s: None
    full = train(mc, _tc(max_iters=8, file_name="offfull", offload="on"),
                 log=quiet)
    assert all(np.isfinite(l) for l in full["train_losses"])

    fired = []

    def log_and_interrupt(s):
        if "iter" in s and not fired:
            fired.append(1)
            os.kill(os.getpid(), signal.SIGINT)

    interrupted = train(mc, _tc(max_iters=8, file_name="offrun",
                                log_interval=1, offload="on"),
                        log=log_and_interrupt)
    assert fired
    assert len(interrupted["train_losses"]) < 9, "SIGINT did not stop"
    assert ckpt.latest_step_dir(os.path.join("checkpoints", "offrun"))

    resumed = train(mc, _tc(max_iters=8, file_name="offrun", resume=True,
                            offload="on"), log=quiet)
    assert resumed["train_losses"] == \
        full["train_losses"][-len(resumed["train_losses"]):]


def test_offload_matches_in_hbm_loop_losses(in_tmp):
    """The whole loop (data, eval-off, ckpt) produces the same loss
    curve with the gate on and off — same backend, same numerics."""
    mc = LLMConfig(**TINY)
    quiet = lambda s: None
    on = train(mc, _tc(max_iters=4, file_name="gateon", offload="on"),
               log=quiet)
    off = train(mc, _tc(max_iters=4, file_name="gateoff", offload="off"),
                log=quiet)
    assert on["train_losses"] == off["train_losses"]


# ---------------------------------------------------------------------------
# Supervisor prewarm gate.
# ---------------------------------------------------------------------------

def test_supervisor_prewarm_skipped_under_offload(in_tmp, monkeypatch):
    monkeypatch.setenv("AOT_STORE", "on")
    monkeypatch.setenv("AOT_STORE_DIR", str(in_tmp / "store"))
    monkeypatch.delenv("OFFLOAD", raising=False)
    cfg = sup.SupervisorConfig(hosts=1, train_argv=("-m", "x"),
                               run_name="pw")
    s = sup.Supervisor(cfg, log=lambda m: None)
    assert s._default_prewarm_cmd(1), "store on: prewarm cmd expected"
    monkeypatch.setenv("OFFLOAD", "on")
    assert s._default_prewarm_cmd(1) is None, \
        "offload step is a program pair — nothing to AOT-prewarm"
