"""Block pool allocator (ops/block_pool.py) + the paged engine's
lifecycle over it: pool exhaustion, refcount release on cancel/EOS,
copy-on-write fork correctness (shared prefix blocks stay immutable while
forks diverge), and LRU eviction of unreferenced prefix blocks."""

import jax
import jax.numpy as jnp
import pytest

from distributed_pytorch_tpu.config import LLMConfig
from distributed_pytorch_tpu.engine import DecodeEngine, NoFreeBlocks
from distributed_pytorch_tpu.models.generate import generate
from distributed_pytorch_tpu.models.gpt import LLM
from distributed_pytorch_tpu.ops.block_pool import BlockPool, chain_keys


# ----------------------------------------------------------------------
# host-side allocator unit tests (no device work)
# ----------------------------------------------------------------------

def test_pool_exhaustion_and_all_or_nothing_alloc():
    pool = BlockPool(5, 8)                   # null + 4 allocatable
    got = [pool.alloc() for _ in range(4)]
    assert sorted(got) == [1, 2, 3, 4]       # block 0 reserved (null)
    assert pool.alloc() is None              # exhausted, all referenced
    assert pool.alloc_many(1) is None
    pool.release(got[0])
    # all-or-nothing: asking for 2 with 1 free must not leak the 1
    assert pool.alloc_many(2) is None
    assert pool.n_free == 1
    assert pool.alloc_many(1) == [got[0]]


def test_refcounted_sharing_and_release_order():
    pool = BlockPool(6, 8)
    a = pool.alloc()
    pool.register(a, ("k",))
    pool.ref(a)                              # second sequence shares it
    pool.release(a)
    assert pool.n_referenced == 1            # still held by the first
    assert pool.n_cached == 0
    pool.release(a)
    assert pool.n_cached == 1                # registered -> LRU, not freed
    assert pool.lookup(("k",)) == a
    b = pool.alloc()                         # free list first
    assert b != a and pool.lookup(("k",)) == a


def test_lru_eviction_of_unreferenced_prefix_blocks():
    pool = BlockPool(4, 8)                   # 3 allocatable
    blocks = pool.alloc_many(3)
    for i, blk in enumerate(blocks):
        pool.register(blk, ("key", i))
    pool.release_all(blocks)                 # tail-first: LRU order 2,1,0
    assert pool.n_cached == 3 and pool.n_free == 0
    fresh = pool.alloc()                     # must evict the LRU entry
    assert fresh == blocks[2]                # deepest block evicted first
    assert pool.lookup(("key", 2)) is None   # its key is gone
    assert pool.lookup(("key", 0)) == blocks[0]
    assert pool.n_evicted == 1


def test_chain_keys_are_prefix_sensitive():
    a = chain_keys([1, 2, 3, 4], 2, 2)
    b = chain_keys([9, 9, 3, 4], 2, 2)
    assert a[0] != b[0]
    # same block content, different ancestry -> different key (a radix
    # path, not a flat content hash)
    assert a[1] != b[1]
    assert chain_keys([1, 2, 3, 4], 2, 2) == a


# ----------------------------------------------------------------------
# engine lifecycle over the pool
# ----------------------------------------------------------------------

def tiny_cfg(**kw):
    base = dict(vocab_size=97, block_size=64, n_embd=48, n_head=4,
                n_kv_heads=2, attn="gqa", n_layer=2, up_dim=64,
                non_linearity="swiglu", pos_emb="rope", dropout=0.0)
    base.update(kw)
    return LLMConfig(**base)


@pytest.fixture(scope="module")
def mv():
    cfg = tiny_cfg()
    model = LLM(cfg, attn_impl="naive")
    rng = jax.random.PRNGKey(0)
    x = jnp.zeros((1, cfg.block_size), jnp.int32)
    return cfg, model, dict(model.init({"params": rng, "dropout": rng},
                                       x, x))


def test_release_on_cancel_and_eos(mv):
    """Cancelling (or finishing) a sequence releases its refs: the blocks
    become cached prefix blocks (registered full ones) or free blocks
    (the partial tail) — the pool never leaks."""
    _, model, variables = mv
    eng = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                       min_bucket=8)
    pool = eng.block_pool
    adm = eng.admit(list(range(1, 20)), 50)   # 19 tokens: 2 full blocks
    assert pool.n_referenced > 0
    eng.cancel(adm.seq_id)
    assert pool.n_referenced == 0
    assert pool.n_cached == 2                 # full blocks published
    assert pool.n_free == pool.capacity - 2
    # EOS retirement releases the same way
    ref = generate(model, variables, jnp.asarray([[40, 41, 42]], jnp.int32),
                   5, temperature=0.0)[0].tolist()
    eng2 = DecodeEngine(model, variables, n_slots=1, temperature=0.0,
                        min_bucket=8, eos_id=ref[3])
    eng2.run([[40, 41, 42]], max_new_tokens=50)
    assert eng2.retire_counts["eos"] == 1
    assert eng2.block_pool.n_referenced == 0


def test_cow_fork_shares_prefix_and_diverges(mv):
    """Two live sequences sharing a cached prompt prefix reference the
    SAME physical blocks; their divergent tails are private (copy-on-
    write at block granularity), so both decode bit-identically to the
    one-shot oracle."""
    _, model, variables = mv
    eng = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                       min_bucket=8)
    shared = list(range(1, 25))               # 24 tokens = 3 full 8-blocks
    p1, p2 = shared + [30, 31], shared + [40, 41, 42]
    a1 = eng.admit(p1, 8)
    a2 = eng.admit(p2, 8)
    assert a1.prefix_len == 0 and a1.prefilled == len(p1)
    assert a2.prefix_len == 24 and a2.prefilled == len(p2) - 24
    s1, s2 = eng._slots.values()
    assert s1.blocks[:3] == s2.blocks[:3]     # physically shared prefix
    assert set(s1.blocks[3:]).isdisjoint(s2.blocks[3:])  # private tails
    outs = {a1.seq_id: list(p1) + [a1.first_token],
            a2.seq_id: list(p2) + [a2.first_token]}
    done = {}
    while eng.n_live:
        res = eng.step()
        for sid, toks in res.emitted.items():
            outs[sid].extend(toks)
        done.update(res.retired)
    for p, sid in ((p1, a1.seq_id), (p2, a2.seq_id)):
        ref = generate(model, variables, jnp.asarray(p, jnp.int32)[None], 8,
                       temperature=0.0)[0].tolist()
        assert done[sid].tokens == ref, "fork diverged from the oracle"
    assert eng.prefix_hit_rate > 0.4


def test_admit_rolls_back_prefix_refs_on_pool_exhaustion(mv):
    """An admission that matches cached blocks but cannot allocate its
    suffix must release the prefix refs it took (no leak) and raise
    NoFreeBlocks — the scheduler keeps such a request queued."""
    _, model, variables = mv
    eng = DecodeEngine(model, variables, n_slots=3, temperature=0.0,
                       min_bucket=8, n_blocks=9)    # capacity 8 blocks
    shared = list(range(1, 25))                     # 3 full 8-blocks
    a = eng.admit(shared, 60)                       # bucket 32 -> 4 blocks
    b = eng.admit([90, 91, 92, 93, 94, 95, 96, 80, 81, 82], 60)  # 2 blocks
    pool = eng.block_pool
    before = pool.n_referenced
    assert before == 6
    # shares the 3-block prefix (refs taken) but its 20-token suffix
    # bucket needs 4 blocks with only 3 left -> all-or-nothing rollback
    with pytest.raises(NoFreeBlocks):
        eng.admit(shared + list(range(30, 50)), 4)
    assert pool.n_referenced == before              # refs rolled back
    assert sorted(eng.live_seq_ids) == sorted([a.seq_id, b.seq_id])
    # after a retirement frees blocks, the queued-equivalent admit works
    eng.set_budget(a.seq_id, 1)
    eng.step()
    assert eng.n_live == 1
    adm = eng.admit(shared + list(range(30, 46)), 2)
    assert adm.prefix_len == 24                     # resumed from the LRU
