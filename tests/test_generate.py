"""Generation tests: KV-cached decode vs full-recompute oracle.

The oracle re-runs the whole (growing) sequence through the model with NO
cache each step and takes argmax — reference semantics without any cache
machinery. Greedy (temperature=0) cached generation must match it exactly
for every attention flavor; this is the end-to-end version of the MLA
absorbed-vs-materialized parity test (the reference's 16-hour train/eval
divergence bug, model.py:195,290) plus the GQA cache path.
"""

import jax
import jax.numpy as jnp
import pytest

from distributed_pytorch_tpu.config import LLMConfig
from distributed_pytorch_tpu.models.generate import (generate,
                                                     make_generate_fn,
                                                     sample_token)
from distributed_pytorch_tpu.models.gpt import LLM


def tiny_cfg(**kw):
    base = dict(vocab_size=97, block_size=32, n_embd=48, n_head=4,
                n_kv_heads=4, attn="mha", n_layer=2, up_dim=64,
                non_linearity="swiglu", pos_emb="rope", dropout=0.0,
                q_latent_dim=16, kv_latent_dim=16, rope_head_dim=8)
    base.update(kw)
    return LLMConfig(**base)


def build(cfg, seed=0):
    model = LLM(cfg, attn_impl="naive")
    rng = jax.random.PRNGKey(seed)
    x = jnp.zeros((1, cfg.block_size), jnp.int32)
    variables = model.init({"params": rng, "dropout": rng}, x, x)
    return model, {k: v for k, v in variables.items()}


def greedy_oracle(model, variables, prompt, n_new):
    """No-cache greedy decode: full forward over the growing sequence."""
    seq = prompt
    for _ in range(n_new):
        inp = seq[:, -model.config.block_size:]
        logits, _, _ = model.apply(variables, inp, deterministic=True)
        nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    return seq


FLAVORS = [
    dict(attn="mha", pos_emb="rope"),
    dict(attn="gqa", n_kv_heads=2, pos_emb="learn"),
    dict(attn="mqa", pos_emb="sin"),
    dict(attn="mla", pos_emb="learn"),   # NaiveMLA absorbed decode
    dict(attn="mla", pos_emb="rope"),    # FullMLA decoupled-rope decode
]


@pytest.mark.parametrize("kw", FLAVORS,
                         ids=[f"{k['attn']}-{k['pos_emb']}" for k in FLAVORS])
def test_cached_greedy_matches_full_recompute(kw):
    cfg = tiny_cfg(**kw)
    model, variables = build(cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(7), (2, 5), 0,
                                cfg.vocab_size, jnp.int32)
    n_new = 8
    out = generate(model, variables, prompt, n_new, temperature=0.0)
    ref = greedy_oracle(model, variables, prompt, n_new)
    assert out.shape == (2, 5 + n_new)
    assert (out == ref).all(), (
        f"cached decode diverged from full recompute for {kw}")


def test_sliding_window_generates_past_cache():
    """Decoding past the cache size must not crash and must keep producing
    in-vocab tokens (reference trims caches to block_size-1,
    model.py:711-730; here the ring write overwrites the oldest slot)."""
    cfg = tiny_cfg(attn="mha", pos_emb="rope", block_size=16)
    model, variables = build(cfg)
    prompt = jnp.array([[1, 2, 3]], jnp.int32)
    n_new = 30  # 3 + 30 >> block_size
    out = generate(model, variables, prompt, n_new, temperature=1.0,
                   top_k=10, rng=jax.random.PRNGKey(3))
    assert out.shape == (1, 33)
    assert ((out >= 0) & (out < cfg.vocab_size)).all()


def test_topk1_equals_greedy():
    cfg = tiny_cfg()
    model, variables = build(cfg)
    prompt = jnp.array([[4, 8, 15]], jnp.int32)
    greedy = generate(model, variables, prompt, 6, temperature=0.0)
    topk1 = generate(model, variables, prompt, 6, temperature=0.7, top_k=1,
                     rng=jax.random.PRNGKey(0))
    assert (greedy == topk1).all()


def test_sample_token_topk_masks_tail():
    logits = jnp.array([[0.0, 1.0, 2.0, 3.0]])
    draws = [int(sample_token(logits, jax.random.PRNGKey(i),
                              temperature=1.0, top_k=2)[0])
             for i in range(32)]
    assert set(draws) <= {2, 3}


def test_moe_generation_runs():
    cfg = tiny_cfg(moe=True, n_exp=4, n_shared=1, n_act=2, aux_free=True)
    model, variables = build(cfg)
    prompt = jnp.array([[1, 2]], jnp.int32)
    out = generate(model, variables, prompt, 5, temperature=0.0)
    ref = greedy_oracle(model, variables, prompt, 5)
    assert (out == ref).all()


def test_generate_fn_reuse_and_batching():
    cfg = tiny_cfg()
    model, variables = build(cfg)
    gen = make_generate_fn(model, 4, temperature=0.0)
    p = jax.random.randint(jax.random.PRNGKey(0), (3, 6), 0, cfg.vocab_size,
                           jnp.int32)
    out1 = gen(variables, p, jax.random.PRNGKey(1))
    out2 = gen(variables, p, jax.random.PRNGKey(2))
    assert out1.shape == (3, 10)
    # greedy: rng must not matter
    assert (out1 == out2).all()


@pytest.mark.parametrize("kw", [
    dict(attn="mha", pos_emb="rope"),
    dict(attn="gqa", n_kv_heads=2, pos_emb="learn"),
    dict(attn="mqa", pos_emb="sin"),
], ids=["mha-rope", "gqa-learn", "mqa-sin"])
def test_flash_decode_greedy_matches_oracle(kw, monkeypatch):
    """Greedy decode with the split-KV flash-decode kernel forced on
    (interpret mode on CPU) is token-identical to the teacher-forced
    full-recompute argmax AND to the naive decode path — the end-to-end
    acceptance check for ops/flash_decode.py."""
    monkeypatch.setenv("FLASH_DECODE", "on")
    cfg = tiny_cfg(**kw)
    model = LLM(cfg, attn_impl="auto")  # 'naive' would pin the oracle path
    rng = jax.random.PRNGKey(0)
    x = jnp.zeros((1, cfg.block_size), jnp.int32)
    variables = model.init({"params": rng, "dropout": rng}, x, x)
    prompt = jax.random.randint(jax.random.PRNGKey(9), (2, 5), 0,
                                cfg.vocab_size, jnp.int32)
    out = generate(model, variables, prompt, 8, temperature=0.0)
    ref = greedy_oracle(model, variables, prompt, 8)
    assert (out == ref).all(), f"flash-decode diverged from oracle for {kw}"
    monkeypatch.setenv("FLASH_DECODE", "off")
    naive = generate(model, variables, prompt, 8, temperature=0.0)
    assert (out == naive).all()


def test_bucketed_prompt_len_matches_unpadded():
    """Right-padded bucketed prompts (`prompt_len`) decode the same tokens
    as the exact-shape call: pad rows are causally invisible and the
    per-sequence positions pick up from each row's true length."""
    cfg = tiny_cfg()
    model, variables = build(cfg)
    lens = [3, 7, 5]
    bucket = 8
    rows = [list(range(1, L + 1)) for L in lens]
    padded = jnp.asarray([r + [0] * (bucket - len(r)) for r in rows],
                         jnp.int32)
    gen = make_generate_fn(model, 6, temperature=0.0)
    out = gen(variables, padded, jax.random.PRNGKey(0),
              jnp.asarray(lens, jnp.int32))
    for i, (r, L) in enumerate(zip(rows, lens)):
        ref = generate(model, variables, jnp.asarray(r, jnp.int32)[None], 6,
                       temperature=0.0)[0].tolist()
        got = out[i].tolist()
        got = got[:L] + got[bucket:]  # splice out the pad tail
        assert got == ref, f"row {i} (len {L}) diverged under padding"


def test_prompt_len_full_rows_match_plain_call():
    """prompt_len == T0 for every row must reproduce the plain
    (no prompt_len) greedy decode exactly."""
    cfg = tiny_cfg()
    model, variables = build(cfg)
    p = jax.random.randint(jax.random.PRNGKey(3), (2, 6), 0, cfg.vocab_size,
                           jnp.int32)
    gen = make_generate_fn(model, 5, temperature=0.0)
    plain = gen(variables, p, jax.random.PRNGKey(1))
    ragged = gen(variables, p, jax.random.PRNGKey(1),
                 jnp.full((2,), 6, jnp.int32))
    assert (plain == ragged).all()


def test_sharded_sampling_cli(tmp_path, monkeypatch, capsys):
    """Round-3 weak #7: a checkpoint from a sharded run can be sampled with
    --shard, restoring directly into the recipe's mesh layout (no
    single-device materialization) and decoding under the ambient mesh."""
    monkeypatch.chdir(tmp_path)
    from distributed_pytorch_tpu.config import LLMConfig, TrainConfig
    from distributed_pytorch_tpu.train.loop import train
    from distributed_pytorch_tpu import sample
    # force the comma-separated-ids prompt path regardless of whether
    # tiktoken can load its vocab in this environment
    monkeypatch.setattr(sample, "_encoder", lambda: None)

    mc = LLMConfig(vocab_size=256, block_size=32, n_embd=32, n_head=4,
                   n_kv_heads=2, n_layer=2, up_dim=48)
    tc = TrainConfig(dataset="synthetic", data_dir=str(tmp_path / "d"),
                     total_batch_size=8 * 2 * 32, batch_size=2, max_iters=2,
                     parallelism="fsdp", save_model=True, save_stats=False,
                     file_name="shardrun")
    train(mc, tc, log=lambda s: None)

    sample.main(["--ckpt", "checkpoints/shardrun", "--shard",
                 "--prompt", "1,2,3", "--max_new_tokens", "8",
                 "--num_samples", "1"])
    out = capsys.readouterr().out
    assert "sharded restore: mesh" in out
    # generated ids line: prompt + 8 new tokens
    last = [l for l in out.splitlines() if l.startswith("[")][-1]
    assert len(eval(last)) == 3 + 8


def test_quantized_sampling_cli(tmp_path, monkeypatch, capsys):
    """--cache-dtype int8 --quant-weights route the CLI through the
    DecodeEngine's quantized serving path and the tok/s summary line says
    so (round-9 satellite)."""
    monkeypatch.chdir(tmp_path)
    from distributed_pytorch_tpu.config import LLMConfig, TrainConfig
    from distributed_pytorch_tpu.train.loop import train
    from distributed_pytorch_tpu import sample
    monkeypatch.setattr(sample, "_encoder", lambda: None)

    mc = LLMConfig(vocab_size=256, block_size=32, n_embd=32, n_head=4,
                   n_kv_heads=2, n_layer=2, up_dim=48)
    tc = TrainConfig(dataset="synthetic", data_dir=str(tmp_path / "d"),
                     total_batch_size=2 * 32, batch_size=2, max_iters=2,
                     parallelism="single", save_model=True,
                     save_stats=False, file_name="qrun")
    train(mc, tc, log=lambda s: None)

    sample.main(["--ckpt", "checkpoints/qrun", "--prompt", "1,2,3",
                 "--max_new_tokens", "6", "--num_samples", "2",
                 "--cache-dtype", "int8", "--quant-weights"])
    out = capsys.readouterr().out
    assert "cache=int8" in out and "quant_w=True" in out
    lines = [l for l in out.splitlines() if l.startswith("[")]
    assert len(lines) == 2
    assert all(len(eval(l)) == 3 + 6 for l in lines)
