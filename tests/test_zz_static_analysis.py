"""Round-16 static-analysis subsystem in one suite: the config knob
registry, TraceGuard retrace accounting, scripts/lint.py rules (each
demonstrated by a fixture under tests/lint_fixtures/), and the
device-free shardcheck golden matrix + seeded spec-table mutations.

Named zz_ deliberately: everything here is cheap meta-tooling, and
sorting it last keeps tier-1's wall-clock budget spent on the
compile-heavy kernel/recipe parity suites first.
"""

import importlib.util
import json
import pathlib
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from distributed_pytorch_tpu import config
from distributed_pytorch_tpu.config import (PARALLELISM_RECIPES, PRESETS,
                                            TrainConfig)
from distributed_pytorch_tpu.obs.retrace import (RetraceError, TraceGuard,
                                                 guarded)
from distributed_pytorch_tpu.parallel import commscheck, shardcheck, \
    sharding as shd
from distributed_pytorch_tpu.parallel.mesh import AXES, MeshPlan, build_mesh

REPO = pathlib.Path(__file__).resolve().parents[1]
FIXTURES = REPO / "tests" / "lint_fixtures"

# scripts/ is not a package — load by path
_spec = importlib.util.spec_from_file_location(
    "repo_lint", REPO / "scripts" / "lint.py")
lint = importlib.util.module_from_spec(_spec)
sys.modules["repo_lint"] = lint  # dataclasses resolve types via sys.modules
_spec.loader.exec_module(lint)


# ---------------------------------------------------------------------------
# config.py env-knob registry
# ---------------------------------------------------------------------------

def test_knob_defaults_read_without_env():
    assert config.knob("FLASH_BLOCK_Q") == 256
    assert config.knob("TRACE_GUARD") == "warn"
    assert config.knob("FLASH_DECODE") == "auto"


def test_knob_env_override_is_live(monkeypatch):
    """Knob.read consults os.environ per call, so monkeypatch.setenv works
    mid-process — the property mfu_sweep and the tests depend on."""
    monkeypatch.setenv("FLASH_BLOCK_Q", "128")
    assert config.knob("FLASH_BLOCK_Q") == 128
    monkeypatch.delenv("FLASH_BLOCK_Q")
    assert config.knob("FLASH_BLOCK_Q") == 256


def test_knob_unregistered_name_fails_loudly():
    with pytest.raises(KeyError):
        config.knob("FLASH_BLOK_Q")  # typo'd name must not silently default


def test_knob_onoff_validation(monkeypatch):
    monkeypatch.setenv("FLASH_DECODE", "bogus")
    with pytest.raises(ValueError, match="auto|on|off"):
        config.knob("FLASH_DECODE")
    monkeypatch.setenv("FLASH_DECODE", "ON")
    assert config.knob("FLASH_DECODE") == "on"


def test_knobs_table_marks_overrides(monkeypatch):
    monkeypatch.setenv("FLASH_BLOCK_K", "1024")
    table = config.knobs_table()
    lines = {ln.split()[0]: ln for ln in table.splitlines()[1:]}
    assert set(lines) == set(config.ENV_KNOBS)
    assert "1024*" in lines["FLASH_BLOCK_K"]      # override marker
    assert "*" not in lines["FLASH_BLOCK_Q"].split()[2]


def test_register_knob_round_trip(monkeypatch):
    k = config.register_knob("TEST_ONLY_KNOB", "7", int, "test fixture")
    try:
        assert config.knob("TEST_ONLY_KNOB") == 7
        monkeypatch.setenv("TEST_ONLY_KNOB", "9")
        assert k.read() == 9
    finally:
        del config.ENV_KNOBS["TEST_ONLY_KNOB"]


# ---------------------------------------------------------------------------
# obs/retrace.py TraceGuard
# ---------------------------------------------------------------------------

def test_guard_counts_and_excess():
    g = TraceGuard("t", budget=2)
    g.mark()
    g.mark()
    assert (g.count, g.excess) == (2, 0)
    g.mark()  # default mode: warn, not raise
    assert (g.count, g.excess) == (3, 1)
    assert g.stats() == {"count": 3, "budget": 2, "excess": 1}


def test_guard_allow_raises_budget():
    g = TraceGuard("t", budget=0)
    g.allow()
    g.mark()
    assert g.excess == 0
    g.allow(2)
    g.mark()
    g.mark()
    assert (g.count, g.budget, g.excess) == (3, 3, 0)


def test_guard_strict_mode_raises(monkeypatch):
    monkeypatch.setenv("TRACE_GUARD", "strict")
    g = TraceGuard("t", budget=1)
    g.mark()
    with pytest.raises(RetraceError, match="trace #2 exceeds budget 1"):
        g.mark()
    assert g.count == 2  # the count still advances


def test_guard_warn_mode_logs(monkeypatch, caplog):
    monkeypatch.setenv("TRACE_GUARD", "warn")
    g = TraceGuard("t", budget=0)
    with caplog.at_level("WARNING", logger="retrace"):
        g.mark()
    assert any("exceeds budget" in r.message for r in caplog.records)


def test_guard_off_mode_is_silent(monkeypatch, caplog):
    monkeypatch.setenv("TRACE_GUARD", "off")
    g = TraceGuard("t", budget=0)
    with caplog.at_level("WARNING", logger="retrace"):
        g.mark()
    assert not caplog.records
    assert g.excess == 1  # still counted for /metrics


def test_guard_expect_window(monkeypatch):
    monkeypatch.setenv("TRACE_GUARD", "strict")
    g = TraceGuard("t", budget=10)
    with g.expect(1):
        g.mark()  # within the window's allowance
    with pytest.raises(RetraceError):
        with g.expect(0):
            g.mark()


def test_guarded_fn_delegates():
    g = TraceGuard("t")
    fn = guarded(lambda x: x + 1, g)
    assert fn(1) == 2
    assert fn.trace_guard is g


def test_guard_jit_integration_counts_traces_not_calls():
    g = TraceGuard("jit", budget=2)

    def f(x):
        g.mark()  # trace-time side effect
        return x * 2

    jf = jax.jit(f)
    jf(jnp.ones((4,)))
    jf(jnp.ones((4,)))          # cache hit: no new trace
    assert g.count == 1
    jf(jnp.ones((8,)))          # new shape: second trace
    assert (g.count, g.excess) == (2, 0)


# ---------------------------------------------------------------------------
# scripts/lint.py: the package must lint clean, every rule must fire
# ---------------------------------------------------------------------------

def _rules(findings):
    return [f.rule for f in findings]


def test_lint_package_is_clean():
    findings = lint.lint_package()
    assert findings == [], "\n".join(str(f) for f in findings)


def test_lint_host_sync_fixture():
    out = lint.lint_file(FIXTURES / "bad_host_sync.py",
                         rules=("host-sync",), rel="ops/fixture.py")
    assert _rules(out) == ["host-sync"] * 8
    # device_get, .item(), float(jnp...), int(device_get) twice, asarray,
    # np.array, .tolist()
    assert sorted(f.line for f in out) == [9, 10, 11, 12, 12, 13, 14, 15]
    # the tagged line (21) must not appear
    assert all(f.line != 21 for f in out)


def test_lint_wallclock_fixture():
    out = lint.lint_file(FIXTURES / "bad_wallclock.py",
                         rules=("wall-clock",), rel="obs/fixture.py")
    assert _rules(out) == ["wall-clock"]
    assert out[0].line == 7


def test_lint_env_read_fixture():
    out = lint.lint_file(FIXTURES / "bad_env.py",
                         rules=("env-read",), rel="serve/fixture.py")
    assert _rules(out) == ["env-read"] * 3
    assert sorted(f.line for f in out) == [7, 8, 9]  # writes not flagged


def test_lint_pallas_gate_fixtures():
    bad = lint.lint_file(FIXTURES / "bad_pallas.py",
                         rules=("pallas-gate",), rel="ops/fixture.py")
    assert _rules(bad) == ["pallas-gate"]
    good = lint.lint_file(FIXTURES / "good_pallas.py",
                          rules=("pallas-gate",), rel="ops/fixture.py")
    assert good == []


def test_lint_rule_scoping_by_path():
    """host-sync only applies to hot-path modules: the same fixture under
    a data-loading path produces no findings with default scoping."""
    hot = lint.lint_file(FIXTURES / "bad_host_sync.py",
                         rel="ops/fixture.py")
    cold = lint.lint_file(FIXTURES / "bad_host_sync.py",
                          rel="data/fixture.py")
    assert any(f.rule == "host-sync" for f in hot)
    assert all(f.rule != "host-sync" for f in cold)


def test_lint_wallclock_scoped_to_obs():
    out = lint.lint_file(FIXTURES / "bad_wallclock.py",
                         rel="train/fixture.py")
    assert all(f.rule != "wall-clock" for f in out)


def test_lint_main_exit_codes(capsys):
    # explicit fixture file -> all rules -> findings -> exit 1 (what CI
    # keys off; the in-process call covers the CLI without paying a
    # subprocess interpreter start)
    assert lint.main([str(FIXTURES / "bad_host_sync.py")]) == 1
    out = capsys.readouterr().out
    assert "[host-sync]" in out
    # whole package -> clean -> exit 0
    assert lint.main([]) == 0


# ---------------------------------------------------------------------------
# shardcheck: the golden matrix
# ---------------------------------------------------------------------------

def test_matrix_green():
    """Every recipe x ladder preset x {1x1, 2x1, 4x2} mesh (plus the MoE
    variant, plus the round-17 rung-down re-mesh shapes) validates with
    zero errors, entirely device-free."""
    reports = shardcheck.check_matrix()
    # 6 configs (5 ladder rungs incl. the 7B pod rung + moe'd 124m) x
    # (9 recipes x (3 meshes + 3 rung-down re-mesh cells) + 'single' at
    # 1x1 only)
    assert len(reports) == 6 * (9 * (3 + 3) + 1)
    bad = [r for r in reports if not r.ok]
    assert not bad, "\n\n".join(shardcheck.format_report(r) for r in bad)
    # the elastic cells are present, labeled, and on the shrunken grids
    rung = [r for r in reports if r.variant.startswith("rung_down:")]
    assert len(rung) == 6 * 9 * 3
    assert {r.variant for r in rung} == {
        "rung_down:2->1", "rung_down:3->2", "rung_down:5->4"}
    for r in rung:
        down = int(r.variant.split("->")[1])
        assert r.mesh["data"] == down
        assert all(s == 1 for a, s in r.mesh.items() if a != "data")


def test_1p5b_tp_cache_warns_but_passes():
    """gpt2_1p5b has 25 heads: under model=2 the decode cache cannot
    shard its kv-head axis — a legitimate WARN, never an error."""
    r = shardcheck.check_config(
        PRESETS["gpt2_1p5b"](), "tp",
        shardcheck.mesh_sizes_for("tp", (1, 2)), preset="gpt2_1p5b")
    assert r.ok
    assert any(f.rule == "cache" for f in r.warnings)


def test_abstract_mesh_matches_real_mesh():
    """The duck-typed AbstractMesh must drive the tables to the exact
    specs a real device mesh produces (8 virtual CPU devices, 4x2)."""
    sizes = {"data": 4, "seq": 1, "expert": 1, "model": 2, "pipe": 1}
    real = Mesh(np.array(jax.devices()[:8]).reshape(4, 1, 1, 2, 1), AXES)
    cfg = PRESETS["gpt2_124m"]()
    shapes = shardcheck.param_shapes(cfg)
    specs_fake = shd.params_pspecs(shapes, "fsdp_tp",
                                   shardcheck.AbstractMesh(sizes))
    specs_real = shd.params_pspecs(shapes, "fsdp_tp", real)
    assert jax.tree_util.tree_all(jax.tree_util.tree_map(
        lambda a, b: a == b, specs_fake, specs_real,
        is_leaf=lambda x: isinstance(x, P)))


def test_check_train_config_resolves_mesh():
    r = shardcheck.check_train_config(
        PRESETS["gpt2_124m"](), TrainConfig(parallelism="fsdp",
                                            batch_size=8))
    assert r.ok and r.recipe == "fsdp" and r.n_params > 100e6
    assert r.mesh["data"] == 8  # resolved from the 8 virtual CPU devices


def test_check_train_config_flags_indivisible_batch():
    """batch_size=2 cannot split across data=8 — the dryrun path must say
    so before a run wastes a TPU reservation discovering it."""
    r = shardcheck.check_train_config(
        PRESETS["gpt2_124m"](), TrainConfig(parallelism="fsdp",
                                            batch_size=2))
    assert any(f.rule == "divisibility" and f.table == "batch"
               for f in r.errors)


# ---------------------------------------------------------------------------
# shardcheck mutations: corrupt the tables, watch each rule fire
# ---------------------------------------------------------------------------

def test_mutation_dropped_tp_rule_flags_replicated_large(monkeypatch):
    """Deleting the tkn_emb TP rule reintroduces the round-1 bug (39% of
    the 124M params replicated per model shard) — replicated-large must
    catch it."""
    monkeypatch.setattr(shd, "_TP_RULES", tuple(
        r for r in shd._TP_RULES if r[0] != ("tkn_emb", "embedding")))
    r = shardcheck.check_config(
        PRESETS["gpt2_124m"](), "tp",
        shardcheck.mesh_sizes_for("tp", (1, 2)))
    hits = [f for f in r.errors if f.rule == "replicated-large"]
    assert hits and any("tkn_emb" in f.path for f in hits)
    assert not r.ok


def test_mutation_out_of_range_axis_flags_replicated_large(monkeypatch):
    """Flipping a rule's axis index past the tensor rank silently drops
    the sharding (spec_for_param bounds-checks) — the large c_attn
    kernels come back replicated and the checker flags them."""
    rules = tuple((suffix, 5) if suffix == ("c_attn", "kernel")
                  else (suffix, ax) for suffix, ax in shd._TP_RULES)
    monkeypatch.setattr(shd, "_TP_RULES", rules)
    r = shardcheck.check_config(
        PRESETS["gpt2_124m"](), "tp",
        shardcheck.mesh_sizes_for("tp", (1, 2)))
    assert any(f.rule == "replicated-large" and "c_attn" in f.path
               for f in r.errors)


def test_corrupt_specs_flag_structural_rules():
    """check_spec_tree catches nonexistent axes, axis reuse, and
    indivisible dims on any spec pytree."""
    sizes = {"data": 4, "seq": 1, "expert": 1, "model": 2, "pipe": 1}
    shapes = {"w": (6, 8), "v": (4, 4)}
    specs = {"w": P("bogus", "model"),    # unknown axis + 8 % 2 == 0 fine
             "v": P("data", "data")}      # reuse + 4 % 4 == 0 fine
    findings = shardcheck.check_spec_tree(specs, shapes, sizes)
    rules = {f.rule for f in findings}
    assert "axis-name" in rules and "axis-reuse" in rules

    div = shardcheck.check_spec(P(None, "model"), (8, 7), sizes,
                                table="params", path="w")
    assert [f.rule for f in div] == ["divisibility"]


def test_rank_overflow_flagged():
    sizes = {"data": 2, "seq": 1, "expert": 1, "model": 1, "pipe": 1}
    out = shardcheck.check_spec(P("data", None, None), (4, 4), sizes,
                                table="params", path="w")
    assert [f.rule for f in out] == ["rank"]


def test_indivisible_expert_grid_flagged():
    """16 experts minus 2 shared = 14 routed: an expert axis of 4 cannot
    divide them — the checker must flag what GSPMD would reject on
    hardware."""
    cfg = PRESETS["gpt2_124m"](moe=True, n_exp=16, n_shared=2, n_act=8)
    sizes = shardcheck.mesh_sizes_for("ep", (1, 4))
    r = shardcheck.check_config(cfg, "ep", sizes)
    assert any(f.rule == "divisibility" and "experts" in f.path
               for f in r.errors)


# ---------------------------------------------------------------------------
# shardcheck report plumbing + CLI
# ---------------------------------------------------------------------------

def test_report_json_round_trip():
    r = shardcheck.check_config(
        PRESETS["gpt2_124m"](), "fsdp",
        shardcheck.mesh_sizes_for("fsdp", (4, 1)))
    payload = json.loads(shardcheck.reports_to_json([r]))
    assert payload["ok"] and payload["checked"] == 1
    assert payload["reports"][0]["recipe"] == "fsdp"
    assert payload["reports"][0]["mesh"]["data"] == 4


def test_cli_green_and_red(monkeypatch, capsys, tmp_path):
    assert shardcheck.main(["--preset", "gpt2_124m", "--recipe", "fsdp_tp",
                            "--mesh", "4x2"]) == 0
    out = capsys.readouterr().out
    assert "OK" in out and "0 error(s)" in out

    json_path = tmp_path / "report.json"
    monkeypatch.setattr(shd, "_TP_RULES", ())
    assert shardcheck.main(["--preset", "gpt2_124m", "--recipe", "tp",
                            "--mesh", "1x2", "--json",
                            str(json_path)]) == 1
    payload = json.loads(json_path.read_text())
    assert not payload["ok"] and payload["errors"] > 0


def test_every_recipe_has_a_secondary_axis_mapping():
    """mesh_sizes_for must place the B grid factor on a real axis for
    every recipe (data-family recipes compose tp on it)."""
    for recipe in PARALLELISM_RECIPES:
        sizes = shardcheck.mesh_sizes_for(recipe, (2, 2))
        assert sum(1 for s in sizes.values() if s > 1) == 2
        assert set(sizes) == set(AXES)


# ---------------------------------------------------------------------------
# commscheck: explicit collective inventory (jaxpr walk + bytes math)
# ---------------------------------------------------------------------------

def test_collective_inventory_bytes_hand_computed():
    """One psum over a 2-device data axis: the inventory must price it at
    exactly the PER-SHARD operand aval (shard_map bodies see shard
    shapes), here (4, 4) f32 = 64 bytes."""
    from jax.experimental.shard_map import shard_map
    mesh = build_mesh(MeshPlan(data=2))

    def f(x):
        return jax.lax.psum(x, "data")

    sm = shard_map(f, mesh=mesh, in_specs=P("data"), out_specs=P())
    jaxpr = jax.make_jaxpr(sm)(jnp.zeros((8, 4), jnp.float32))
    inv = commscheck.collective_inventory(jaxpr)
    assert [(c["family"], c["prim"], c["axes"], c["count"], c["bytes"])
            for c in inv] == [("all_reduce", "psum2", ["data"], 1, 64)]


def test_collective_inventory_scan_weighting():
    """A psum inside a length-4 scan body executes 4x per step — the
    inventory multiplies count AND bytes by the trip count."""
    from jax.experimental.shard_map import shard_map
    mesh = build_mesh(MeshPlan(data=2))

    def f(x):
        def body(c, xs):
            return c + jax.lax.psum(xs, "data"), None
        out, _ = jax.lax.scan(body, jnp.zeros((4,), jnp.float32), x)
        return out

    # check_rep=False keeps the plain psum primitive (and sidesteps the
    # scan-carry replication-type check) — both spellings must count
    sm = shard_map(f, mesh=mesh, in_specs=P("data"), out_specs=P(),
                   check_rep=False)
    jaxpr = jax.make_jaxpr(sm)(jnp.zeros((8, 4), jnp.float32))
    inv = commscheck.collective_inventory(jaxpr)
    # per-shard leading dim 8/2=4 -> scan length 4; operand (4,) f32=16 B
    assert [(c["prim"], c["count"], c["bytes"]) for c in inv] == \
        [("psum", 4, 64)]


# ---------------------------------------------------------------------------
# commscheck: donation verification (aval-level aliasing)
# ---------------------------------------------------------------------------

def test_donation_report_all_consumed():
    def ok(a, b):
        return a + 1.0, b * 2

    tr = jax.jit(ok, donate_argnums=(0, 1)).trace(
        jax.ShapeDtypeStruct((8,), jnp.float32),
        jax.ShapeDtypeStruct((4,), jnp.int32))
    don = commscheck.donation_report(tr)
    assert (don["donated"], don["consumed"], don["n_missed"]) == (2, 2, 0)
    assert don["donated_bytes"] == 8 * 4 + 4 * 4


def test_donation_miss_flagged_as_error():
    """A donated buffer with no shape/dtype-matched output (the dtype
    changed under it) is a silent donation miss — rule donation-miss."""
    def bad(a):
        return a.astype(jnp.float32)

    tr = jax.jit(bad, donate_argnums=(0,)).trace(
        jax.ShapeDtypeStruct((8,), jnp.bfloat16))
    don = commscheck.donation_report(tr)
    assert (don["donated"], don["consumed"], don["n_missed"]) == (1, 0, 1)
    assert don["missed"] == [{"shape": [8], "dtype": "bfloat16"}]
    rep = commscheck.CommsReport(key="t", role="train", preset="p",
                                 recipe="single", mesh={})
    commscheck._donation_findings(rep, "step", don)
    assert [f.rule for f in rep.findings] == ["donation-miss"]
    assert not rep.ok


# ---------------------------------------------------------------------------
# commscheck: derived GSPMD model bytes vs hand-computed sizes
# ---------------------------------------------------------------------------

def test_derived_train_comms_bytes_hand_computed():
    cfg = PRESETS["gpt2_124m"]()
    sizes = shardcheck.mesh_sizes_for("fsdp", (2, 1))
    tcfg = TrainConfig(parallelism="fsdp", batch_size=4)
    entries, findings = commscheck.derived_train_comms(
        cfg, "fsdp", sizes, tcfg, accum=2)
    assert findings == []
    total = commscheck._n_params(cfg)
    by = {e["origin"]: e for e in entries}
    # fsdp grads: reduce-scatter of fp32 grads once per micro-step
    assert by["grads"]["family"] == "reduce_scatter"
    assert by["grads"]["bytes"] == total * 4 * 2
    # fsdp param gathers: bf16 params per micro-step (overlap=auto does
    # not hoist them out of the accumulation scan)
    act = jnp.dtype(tcfg.compute_dtype).itemsize
    assert by["param-gather"]["family"] == "all_gather"
    assert by["param-gather"]["bytes"] == total * act * 2
    assert by["param-gather"]["hoisted"] is False


def test_derived_sp_ring_matches_traced_ppermute_bytes():
    """The derived sp-ring formula must price the ring EXACTLY like the
    jaxpr says: per-step ppermute bytes at sp/4x2 match the traced
    zig-zag ring's scan-weighted inventory."""
    [r] = commscheck.check_cells(["train/gpt2_124m/sp/4x2"])
    assert r.traced and r.ok
    ring = [c for c in r.collectives if c["family"] == "ppermute"]
    derived = [d for d in r.derived if d["origin"] == "sp-ring"]
    assert len(ring) == 1 and len(derived) == 1
    assert ring[0]["bytes"] == derived[0]["bytes"]


def test_derived_pipe_1f1b_entry_hand_computed():
    """pp at 4x2 (pipe=2) under the auto schedule prices the interleaved
    hand-backs: S=2, vpp=n_layer/S=6, M=auto(min(B, 2S))=4 gives 25 fwd
    ticks ((M-1 over S rounds) x 12 chunks + drain) — each tick rolls one
    microbatch's activations, fwd + mirrored bwd."""
    cfg = PRESETS["gpt2_124m"]()
    sizes = shardcheck.mesh_sizes_for("pp", (4, 2))
    tcfg = TrainConfig(parallelism="pp", batch_size=4)
    entries, findings = commscheck.derived_train_comms(
        cfg, "pp", sizes, tcfg, accum=2)
    assert findings == []
    by = {e["origin"]: e for e in entries}
    assert "pipe-boundary" not in by
    e = by["pipe-1f1b"]
    assert e["family"] == "ppermute" and e["axis"] == "pipe"
    assert e["vpp"] == 6 and e["n_microbatches"] == 4
    assert e["ticks"] == 2 * 25
    act = jnp.dtype(tcfg.compute_dtype).itemsize
    tok_bytes = 1 * cfg.block_size * cfg.n_embd * act  # b_loc = 4/4
    assert e["bytes"] == 2 * 25 * 2 * tok_bytes // 4


def test_derived_pipe_carry_entry_when_schedule_forced():
    """pp_schedule='carry' keeps the round-15 boundary pricing: each of
    the pipe-1 stage boundaries crossed once per direction per
    micro-step with the full local batch."""
    import dataclasses
    cfg = dataclasses.replace(PRESETS["gpt2_124m"](), pp_schedule="carry")
    sizes = shardcheck.mesh_sizes_for("pp", (4, 2))
    tcfg = TrainConfig(parallelism="pp", batch_size=4)
    entries, _ = commscheck.derived_train_comms(
        cfg, "pp", sizes, tcfg, accum=2)
    by = {e["origin"]: e for e in entries}
    assert "pipe-1f1b" not in by
    act = jnp.dtype(tcfg.compute_dtype).itemsize
    tok_bytes = 1 * cfg.block_size * cfg.n_embd * act
    assert by["pipe-boundary"]["bytes"] == 2 * (2 - 1) * 2 * tok_bytes


def test_offload_cell_host_update_donation_all_consumed():
    """The offload audit cell: the traced host optax update must donate
    params + opt_state with every donated leaf consumed (in-place moment
    update in host RAM), zero collectives in the host program, and the
    derived model must carry both PCIe host-transfer legs at 4P bytes."""
    [r] = commscheck.check_cells(["train/gpt2_124m/fsdp/2x1/offload"])
    assert r.traced and r.ok, "\n".join(str(f) for f in r.findings)
    don = r.donation["host_update"]
    assert don["donated"] > 0
    assert don["missed"] == [] and don["donated"] == don["consumed"]
    p4 = commscheck._n_params(PRESETS["gpt2_124m"]()) * 4
    host = {e["origin"]: e for e in r.derived
            if e["family"] == "host_transfer"}
    assert host["offload-grads"]["direction"] == "to_host"
    assert host["offload-params"]["direction"] == "to_device"
    assert host["offload-grads"]["bytes"] == p4
    assert host["offload-params"]["bytes"] == p4


def test_7b_preset_validates_on_the_pod_rung_meshes():
    """The gpt2_7b preset's spec tables stay green on the pod-rung cells
    it ships on — pp (pipe=2), fsdp, fsdp_tp at 4x2 — and on the
    supervisor's rung-down re-mesh shape (data 4->2, elastic restart
    after a host loss)."""
    cfg = PRESETS["gpt2_7b"]()
    for recipe in ("pp", "fsdp", "fsdp_tp"):
        r = shardcheck.check_config(
            cfg, recipe, shardcheck.mesh_sizes_for(recipe, (4, 2)),
            preset="gpt2_7b")
        assert r.ok, shardcheck.format_report(r)
        if recipe == "pp":
            assert r.mesh["pipe"] == 2
    down = shardcheck.check_config(
        cfg, "pp", shardcheck.mesh_sizes_for("pp", (2, 1)),
        preset="gpt2_7b", variant="rung_down:4->2")
    assert down.ok, shardcheck.format_report(down)


def test_mutation_replicated_grads_flag_promised_reduce_scatter(
        monkeypatch):
    """Seeded mutation: a grads table that silently replicates under a
    sharded-grad recipe must raise promised-reduce-scatter (the silent
    all-reduce regression)."""
    monkeypatch.setattr(
        shd, "grads_pspecs",
        lambda shapes, specs, recipe, mesh: jax.tree_util.tree_map(
            lambda s: P(), specs, is_leaf=lambda x: isinstance(x, P)))
    cfg = PRESETS["gpt2_124m"]()
    sizes = shardcheck.mesh_sizes_for("fsdp", (2, 1))
    tcfg = TrainConfig(parallelism="fsdp", batch_size=4)
    entries, findings = commscheck.derived_train_comms(
        cfg, "fsdp", sizes, tcfg, accum=2)
    assert any(f.rule == "promised-reduce-scatter" and
               f.severity == "error" for f in findings)
    by = {e["origin"]: e for e in entries}
    assert by["grads"]["family"] == "all_reduce"  # the degraded class


# ---------------------------------------------------------------------------
# commscheck: trace-signature enumeration vs retrace budgets
# ---------------------------------------------------------------------------

def test_decode_signatures_within_budget_both_modes():
    wave = commscheck.check_cells(
        ["decode/gpt2_124m/single/1x1/wave"], trace_mode="off")[0]
    chunked = commscheck.check_cells(
        ["decode/gpt2_124m/single/1x1/chunked"], trace_mode="off")[0]
    assert wave.ok and chunked.ok
    ws = wave.signatures["enumerated"]
    assert ws["fused_step"] == 0 and ws["admit"] == len(ws["buckets"])
    assert ws["buckets"] == sorted(set(ws["buckets"]))  # distinct, sorted
    assert ws["spec_step"] == 1             # round-20 verify program
    cs = chunked.signatures["enumerated"]
    assert cs == {"step": 1, "fused_step": 1, "admit": 0, "spec_step": 1,
                  "promote": 1, "buckets": []}  # round-22: promote is
    # part of the static universe so the AOT store's warm walk covers it


def test_mutation_bucketing_bug_fails_signature_enumeration(monkeypatch):
    """Seeded mutation: an identity 'bucketing' that compiles one program
    per prompt length must fail the closed-form vs brute-force
    cross-check at lint time."""
    from distributed_pytorch_tpu.engine import decode as eng
    monkeypatch.setattr(eng, "prefill_bucket_for",
                        lambda n, mb, bs, ml: min(max(n, mb), ml))
    [r] = commscheck.check_cells(["decode/gpt2_124m/single/1x1/wave"],
                                 trace_mode="off")
    assert any(f.rule == "signature-enumeration" for f in r.findings)
    assert not r.ok


def test_mutation_extra_trace_signature_breaks_budget(monkeypatch):
    """Seeded mutation: a third step signature exceeds the TraceGuard
    budget of 1 — rule trace-budget."""
    from distributed_pytorch_tpu.engine import decode as eng
    real = eng.enumerate_trace_signatures

    def seeded(**kw):
        sigs = dict(real(**kw))
        sigs["step"] = 3
        return sigs

    monkeypatch.setattr(eng, "enumerate_trace_signatures", seeded)
    [r] = commscheck.check_cells(["decode/gpt2_124m/single/1x1/chunked"],
                                 trace_mode="off")
    assert any(f.rule == "trace-budget" and f.path == "step"
               for f in r.findings)
    assert not r.ok


# ---------------------------------------------------------------------------
# commscheck: golden round trip + seeded divergence
# ---------------------------------------------------------------------------

def _cell_diffs(golden, report):
    diffs = []
    commscheck._diff_value(report.key, golden["reports"][report.key],
                           report.to_dict(), diffs)
    return diffs


def test_commscheck_golden_round_trip():
    """Re-auditing golden cells reproduces the committed matrix byte for
    byte: same collectives, bytes, donation, signatures, findings."""
    golden = commscheck.load_golden()
    assert golden is not None and golden["ok"]
    for key in ("train/gpt2_124m/fsdp/2x1",
                "decode/gpt2_124m/single/1x1/chunked"):
        [r] = commscheck.check_cells([key])
        assert r.traced
        assert _cell_diffs(golden, r) == []


def test_mutation_extra_psum_diverges_from_golden(monkeypatch):
    """Seeded mutation: one extra collective in the traced step shows up
    as a golden diff — the refactor-gate property."""
    real = commscheck.collective_inventory

    def seeded(jaxpr):
        inv = real(jaxpr)
        inv.append({"family": "all_reduce", "prim": "psum",
                    "axes": ["data"], "count": 1, "bytes": 4096})
        return inv

    monkeypatch.setattr(commscheck, "collective_inventory", seeded)
    golden = commscheck.load_golden()
    [r] = commscheck.check_cells(["train/gpt2_124m/fsdp/2x1"])
    diffs = _cell_diffs(golden, r)
    assert diffs and any("collectives" in d for d in diffs)


def test_mutation_dropped_donation_diverges_and_errors(monkeypatch):
    """Seeded mutation: a donation miss both fails the cell (error
    finding) and diverges from the golden donation table."""
    real = commscheck.donation_report

    def seeded(traced):
        don = real(traced)
        if don["donated"]:
            don["consumed"] -= 1
            don["n_missed"] += 1
            don["missed"] = [{"shape": [1], "dtype": "float32"}]
        return don

    monkeypatch.setattr(commscheck, "donation_report", seeded)
    golden = commscheck.load_golden()
    [r] = commscheck.check_cells(["train/gpt2_124m/fsdp/2x1"])
    assert any(f.rule == "donation-miss" for f in r.findings)
    assert not r.ok
    diffs = _cell_diffs(golden, r)
    assert any("donation" in d for d in diffs)


def test_diff_golden_trace_mode_mismatch_short_circuits():
    payload = {"trace_mode": "off", "reports": {}}
    golden = {"trace_mode": "auto", "reports": {}}
    diffs = commscheck.diff_golden(payload, golden)
    assert len(diffs) == 1 and "trace_mode" in diffs[0]


def test_golden_covers_shardcheck_matrix_plus_engine_cells():
    """The committed golden must stay in lockstep with the audit scope:
    every train cell of the base matrix, the overlap A/B pair, and the
    four engine cells."""
    golden = commscheck.load_golden()
    keys = set(golden["reports"])
    assert "train/gpt2_124m/fsdp/2x1/overlap-accum1" in keys
    assert "train/gpt2_124m/fsdp/2x1/overlap-accum2" in keys
    decode = {k for k in keys if k.startswith("decode/")}
    assert len(decode) == len(commscheck.DECODE_CELLS)
    assert "train/gpt2_124m/fsdp/2x1/offload" in keys
    # 6 configs x (9 recipes x 3 meshes + single@1x1) + 2 overlap +
    # 1 offload + 4 engine cells
    assert len(keys) == 6 * (9 * 3 + 1) + 2 + 1 + 4
    assert golden["errors"] == 0 and golden["ok"]
