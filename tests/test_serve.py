"""Async scheduler (serve/scheduler.py) over the DecodeEngine: FCFS
no-starvation, bucket-grouped admission waves, mid-decode cancellation
freeing the slot within a step, bounded-queue shed (an error, never a
hang), queue-wait deadlines, and stream parity with the offline engine."""

import asyncio

import jax
import jax.numpy as jnp
import pytest

from distributed_pytorch_tpu.config import LLMConfig
from distributed_pytorch_tpu.engine import DecodeEngine
from distributed_pytorch_tpu.models.gpt import LLM
from distributed_pytorch_tpu.serve.scheduler import (EngineError,
                                                     Scheduler, ShedError)


def tiny_cfg(**kw):
    base = dict(vocab_size=97, block_size=64, n_embd=48, n_head=4,
                n_kv_heads=2, attn="gqa", n_layer=2, up_dim=64,
                non_linearity="swiglu", pos_emb="rope", dropout=0.0)
    base.update(kw)
    return LLMConfig(**base)


@pytest.fixture(scope="module")
def mv():
    cfg = tiny_cfg()
    model = LLM(cfg, attn_impl="naive")
    rng = jax.random.PRNGKey(0)
    x = jnp.zeros((1, cfg.block_size), jnp.int32)
    variables = dict(model.init({"params": rng, "dropout": rng}, x, x))
    return cfg, model, variables


def run_async(coro, timeout=300):
    """Every test is wrapped in a hard timeout: a scheduler bug must fail
    the test, not hang the suite (and CI's serve step runs under its own
    `timeout` for the same reason)."""
    return asyncio.run(asyncio.wait_for(coro, timeout))


def make_engine(mv, n_slots=2, **kw):
    _, model, variables = mv
    kw.setdefault("temperature", 0.0)
    kw.setdefault("min_bucket", 8)
    return DecodeEngine(model, variables, n_slots=n_slots, **kw)


# ----------------------------------------------------------------------
# FCFS / starvation
# ----------------------------------------------------------------------

def test_fcfs_no_starvation_behind_short_stream(mv):
    """A queued long request is admitted in submission order even while a
    stream of later short requests keeps arriving — FCFS means nothing
    starves."""

    async def main():
        eng = make_engine(mv, n_slots=1)
        sched = Scheduler(eng, max_queue=32)
        await sched.start()
        first = sched.submit([1, 2, 3], 2)
        long = sched.submit([4, 5, 6], 8)
        shorts = [sched.submit([7 + i], 2) for i in range(5)]
        handles = [first, long] + shorts
        await asyncio.gather(*(h.result() for h in handles))
        await sched.stop()
        return eng, sched, handles

    eng, sched, handles = run_async(main())
    admits = [h.admitted_at for h in handles]
    assert all(a is not None for a in admits), "a request starved"
    # single slot + same bucket for everyone: admission order must equal
    # submission order — in particular the long request admitted before
    # every short submitted after it
    assert admits == sorted(admits)
    assert all(h.retired.reason == "budget" for h in handles)
    # "max wait bounded": the whole run bounds every queue wait
    assert sched.metrics.queue_wait.max < 300
    assert sched.metrics.counters["admitted"] == len(handles)
    assert sched.metrics.counters["shed"] == 0


def test_admission_wave_groups_by_prefill_bucket(mv):
    """Within one admission wave, prompts are grouped by pow2 bucket so
    same-bucket prefills run back-to-back on one compiled trace; across
    the wave nothing is reordered beyond that (stable sort)."""

    async def main():
        eng = make_engine(mv, n_slots=4)
        sched = Scheduler(eng, max_queue=8)
        # queue BEFORE starting the loop: one wave admits all four
        h_big1 = sched.submit(list(range(1, 18)), 2)    # bucket 32
        h_small1 = sched.submit([1, 2, 3], 2)           # bucket 8
        h_big2 = sched.submit(list(range(1, 21)), 2)    # bucket 32
        h_small2 = sched.submit([4, 5], 2)              # bucket 8
        await sched.start()
        handles = [h_big1, h_small1, h_big2, h_small2]
        await asyncio.gather(*(h.result() for h in handles))
        await sched.stop()
        return eng, handles

    eng, (h_big1, h_small1, h_big2, h_small2) = run_async(main())
    # both bucket-8 prefills ran before both bucket-32 prefills
    assert max(h_small1.admitted_at, h_small2.admitted_at) \
        < min(h_big1.admitted_at, h_big2.admitted_at)
    # stable within a bucket: submission order preserved
    assert h_small1.admitted_at < h_small2.admitted_at
    assert h_big1.admitted_at < h_big2.admitted_at
    assert set(eng.admit_traces) == {8, 32}
    assert set(eng.admit_traces.values()) == {1}


# ----------------------------------------------------------------------
# cancellation
# ----------------------------------------------------------------------

def test_cancel_mid_decode_frees_slot_within_one_step(mv):
    async def main():
        eng = make_engine(mv, n_slots=1)
        sched = Scheduler(eng, max_queue=8)
        await sched.start()
        h = sched.submit([1, 2, 3], 40)
        got = []
        async for tok in h:
            got.append(tok)
            if len(got) == 3:
                break
        steps_at_cancel = eng.n_steps
        h.cancel()
        ret = await h.result()
        steps_done = eng.n_steps
        # the slot must be reusable immediately: a fresh request decodes
        b = sched.submit([9, 8, 7], 2)
        await b.result()
        await sched.stop()
        return eng, h, ret, got, steps_at_cancel, steps_done, b

    eng, h, ret, got, s0, s1, b = run_async(main())
    assert ret.reason == "cancelled"
    # the loop free-runs, so one step may be in flight when cancel lands
    # and one more may start before the flag is applied — but never the
    # remaining ~37 steps of budget
    assert s1 - s0 <= 2, f"cancel took {s1 - s0} steps to free the slot"
    assert eng.retire_counts["cancelled"] == 1
    assert len(h.tokens) < 10          # nowhere near the 40-token budget
    assert b.retired.reason == "budget"
    assert eng.n_live == 0


def test_cancel_while_queued_never_touches_engine(mv):
    async def main():
        eng = make_engine(mv, n_slots=1)
        sched = Scheduler(eng, max_queue=8)
        await sched.start()
        a = sched.submit([1, 2, 3], 30)
        await a.__anext__()                       # a holds the only slot
        q = sched.submit([4, 5], 10)              # parked in the queue
        q.cancel()
        ret = await q.result()
        a.cancel()
        await a.result()
        await sched.stop()
        return eng, sched, ret, q

    eng, sched, ret, q = run_async(main())
    assert ret.reason == "cancelled"
    assert q.admitted_at is None                  # never reached a slot
    assert eng.n_admitted == 1                    # only a touched the engine
    assert sched.metrics.counters["cancelled"] == 2


# ----------------------------------------------------------------------
# backpressure: bounded queue + deadlines, shed is an error not a hang
# ----------------------------------------------------------------------

def test_queue_bound_sheds_immediately(mv):
    async def main():
        eng = make_engine(mv, n_slots=1)
        sched = Scheduler(eng, max_queue=2)
        await sched.start()
        a = sched.submit([1, 2, 3], 40)
        await a.__anext__()                       # admitted: queue empty
        b = sched.submit([4], 2)
        c = sched.submit([5], 2)
        with pytest.raises(ShedError) as ei:
            sched.submit([6], 2)
        a.cancel()
        await asyncio.gather(a.result(), b.result(), c.result())
        await sched.stop()
        return sched, ei.value

    sched, err = run_async(main())
    assert err.cause == "queue_full"
    assert sched.metrics.counters["shed"] == 1
    assert sched.metrics.shed_counts == {"queue_full": 1}
    # the two queued requests still completed (bound ≠ starvation)
    assert sched.metrics.counters["completed"] == 2


def test_deadline_shed_surfaces_as_error(mv):
    async def main():
        eng = make_engine(mv, n_slots=1)
        sched = Scheduler(eng, max_queue=8)
        await sched.start()
        a = sched.submit([1, 2, 3], 30)
        await a.__anext__()
        b = sched.submit([4, 5], 10, deadline_s=0.0)  # can't make it
        with pytest.raises(ShedError) as ei:
            await b.result()
        a.cancel()
        await a.result()
        await sched.stop()
        return sched, ei.value

    sched, err = run_async(main())
    assert err.cause == "deadline"
    assert sched.metrics.shed_counts.get("deadline") == 1


def test_stop_sheds_queued_and_cancels_live(mv):
    async def main():
        eng = make_engine(mv, n_slots=1)
        sched = Scheduler(eng, max_queue=8)
        await sched.start()
        a = sched.submit([1, 2, 3], 40)
        await a.__anext__()
        b = sched.submit([4, 5], 10)              # still queued
        await sched.stop()
        assert a.retired is not None and a.retired.reason == "cancelled"
        with pytest.raises(ShedError) as ei:
            await b.result()
        assert ei.value.cause == "shutdown"
        with pytest.raises(ShedError):
            sched.submit([6], 2)                  # post-stop submit sheds
        return eng

    eng = run_async(main())
    assert eng.n_live == 0


# ----------------------------------------------------------------------
# engine failure: every pending stream errors (never hangs), health flips
# ----------------------------------------------------------------------

def test_step_loop_crash_fails_all_pending_and_flips_health(mv):
    """Regression: an exception escaping the background step loop must
    fail EVERY pending handle with an explicit EngineError — the live
    stream AND the queued one — flip `healthy` False (healthz 503), and
    shed later submits immediately. Before the fix, handles could wait
    forever on a loop that no longer existed."""

    async def main():
        eng = make_engine(mv, n_slots=1)
        calls = []
        orig_step = eng.step

        def dying_step():
            calls.append(1)
            if len(calls) >= 2:
                raise RuntimeError("device lost")
            return orig_step()

        eng.step = dying_step
        sched = Scheduler(eng, max_queue=8)
        await sched.start()
        a = sched.submit([1, 2, 3], 30)       # takes the only slot
        b = sched.submit([4, 5], 10)          # parked in the queue
        errors = []
        for h in (a, b):
            try:
                await h.result()
            except EngineError as e:
                errors.append(e)
        healthy = sched.healthy
        try:
            sched.submit([6], 2)
            post_shed = None
        except ShedError as e:
            post_shed = e
        await sched.stop()
        return sched, errors, healthy, post_shed

    sched, errors, healthy, post_shed = run_async(main(), timeout=60)
    assert len(errors) == 2, "a pending stream hung or finished silently"
    assert all("device lost" in str(e) for e in errors)
    assert healthy is False
    assert sched.failed is not None
    assert post_shed is not None and post_shed.cause == "engine_error"


def test_admission_crash_fails_wave_popped_requests(mv):
    """Regression for the subtle half of the bug: an admission wave pops
    requests off the queue into a loop-local list BEFORE admitting them.
    If `engine.admit` then raises, those requests are in neither `_live`
    nor `_queue` — the old crash guard missed them and their streams
    hung forever. The pending-handle registry must fail them too."""

    async def main():
        eng = make_engine(mv, n_slots=2)
        calls = []
        orig_admit = eng.admit

        def dying_admit(prompt, max_new):
            calls.append(1)
            if len(calls) >= 2:
                raise RuntimeError("admit exploded")
            return orig_admit(prompt, max_new)

        eng.admit = dying_admit
        sched = Scheduler(eng, max_queue=8)
        # queue BOTH before the loop starts: one wave pops both, the
        # second admit raises with request #2 in the wave-local list
        a = sched.submit([1, 2, 3], 4)
        b = sched.submit([4, 5], 4)
        await sched.start()
        errors = []
        for h in (a, b):
            try:
                await h.result()
            except EngineError as e:
                errors.append(e)
        await sched.stop()
        return errors

    errors = run_async(main(), timeout=60)
    assert len(errors) == 2, \
        "a wave-popped request's stream hung on an admission crash"


# ----------------------------------------------------------------------
# draining: admission stops, queued + live work still completes
# ----------------------------------------------------------------------

def test_drain_sheds_new_serves_queued_and_live(mv):
    async def main():
        eng = make_engine(mv, n_slots=1)
        sched = Scheduler(eng, max_queue=8)
        await sched.start()
        a = sched.submit([1, 2, 3], 8)        # live on the only slot
        b = sched.submit([4, 5], 4)           # queued
        await a.__anext__()
        assert not sched.draining
        sched.drain()
        try:
            sched.submit([6], 2)
            shed = None
        except ShedError as e:
            shed = e
        ra = await a.result()
        rb = await b.result()
        drained = sched.drained
        healthy = sched.healthy               # loop alive, just gated
        await sched.stop()
        return sched, shed, ra, rb, drained, healthy, a, b

    sched, shed, ra, rb, drained, healthy, a, b = run_async(main())
    assert shed is not None and shed.cause == "draining"
    assert sched.metrics.shed_counts.get("draining") == 1
    # drain never drops accepted work: the live stream AND the queued
    # one both deliver their full budgets
    assert ra.reason == "budget" and len(a.tokens) == 8
    assert rb.reason == "budget" and len(b.tokens) == 4
    assert drained is True
    assert healthy is True


# ----------------------------------------------------------------------
# block-level preemption: requeued, never shed
# ----------------------------------------------------------------------

def test_preempted_requests_requeue_not_shed(mv):
    """With a block pool too small for every live sequence's full output,
    the engine preempts mid-decode — the scheduler must resubmit the
    victim at the queue head and every request must still deliver its
    full budget: zero requests lost, zero shed."""

    async def main():
        # capacity 11 blocks; two 48-row sequences need 6 blocks each
        eng = make_engine(mv, n_slots=2, n_blocks=12)
        sched = Scheduler(eng, max_queue=16)
        await sched.start()
        handles = [sched.submit([i + 1, i + 2, i + 3], 45) for i in range(2)]
        await asyncio.gather(*(h.result() for h in handles))
        await sched.stop()
        return eng, sched, handles

    eng, sched, handles = run_async(main())
    assert eng.retire_counts["preempted"] >= 1, \
        "pool was sized to force preemption"
    m = sched.metrics
    assert m.counters["preempted"] == m.counters["requeued"] >= 1
    assert m.counters["shed"] == 0
    assert m.counters["completed"] == len(handles)
    for h in handles:
        assert h.retired.reason == "budget"
        assert len(h.tokens) == 45            # the full budget, seamless
        assert h.retired.prompt_len == 3      # original prompt, not resume
        assert h.retired.tokens[:3] == h.retired.tokens[:3]
        assert h.retired.tokens[3:] == h.tokens
    # preemption resumes hit the prefix cache (retained blocks)
    assert eng.prefix_hit_tokens > 0
    # gauges are exported through the bench summary
    s = m.summary()
    assert "serve_block_utilization" in s["gauges"]
    assert "serve_prefix_hit_rate" in s["gauges"]


def test_preemption_budget_ignores_consumer_lag(mv):
    """The resume budget must come from the scheduler-side served count,
    not the consumer-paced handle.tokens: a client that hasn't drained a
    single token when preemption lands must still receive EXACTLY its
    budget (no re-generated duplicates, no over-emission, no crash from a
    <=0 resume budget after repeated preemptions)."""

    async def main():
        eng = make_engine(mv, n_slots=2, n_blocks=12)
        sched = Scheduler(eng, max_queue=16)
        await sched.start()
        handles = [sched.submit([i + 1, i + 2, i + 3], 45) for i in range(2)]
        # do NOT drain: wait for retirement with the streams untouched,
        # so handle.tokens stays empty through every preemption/resume
        while any(h.retired is None for h in handles):
            await asyncio.sleep(0.01)
        assert all(len(h.tokens) == 0 for h in handles)  # truly undrained
        await asyncio.gather(*(h.result() for h in handles))
        await sched.stop()
        return eng, sched, handles

    eng, sched, handles = run_async(main())
    assert eng.retire_counts["preempted"] >= 1, \
        "pool was sized to force preemption"
    assert sched.metrics.counters["shed"] == 0
    for h in handles:
        assert h.retired.reason == "budget"
        assert len(h.tokens) == 45            # exactly the budget
        assert h.retired.prompt_len == 3
        assert h.retired.tokens[3:] == h.tokens


def test_truncated_prompt_reports_kept_prompt_len(mv):
    """A prompt >= max_len is truncated by the engine to its last
    max_len-1 tokens; the final record's prompt_len must point at the
    generated-output boundary WITHIN ret.tokens (slicing
    tokens[prompt_len:] yields exactly the generated stream), not the
    untruncated submitted length."""

    async def main():
        eng = make_engine(mv, n_slots=1)          # max_len = block_size = 64
        sched = Scheduler(eng, max_queue=4)
        await sched.start()
        h = sched.submit(list(range(1, 71)), 2)   # 70 tokens > max_len
        ret = await h.result()
        await sched.stop()
        return h, ret

    h, ret = run_async(main())
    assert ret.reason == "budget"
    assert ret.prompt_len == 63                   # the kept suffix
    assert len(ret.tokens) == 63 + 2
    assert ret.tokens[ret.prompt_len:] == h.tokens


# ----------------------------------------------------------------------
# stream parity with the offline engine
# ----------------------------------------------------------------------

def test_streams_match_offline_engine_greedy(mv):
    """Concurrent scheduler streams are bit-identical to the offline
    DecodeEngine run with the same per-request budgets (greedy)."""
    prompts = [[1, 2, 3], [5, 6, 7, 8, 9, 10, 11], [20] * 17, [42, 43],
               [9], [60, 61, 62, 63], [30] * 12, [2, 4, 6]]
    budgets = [2, 6, 3, 5, 4, 2, 6, 3]

    async def main():
        eng = make_engine(mv, n_slots=2)
        sched = Scheduler(eng, max_queue=16)
        await sched.start()
        handles = [sched.submit(p, b) for p, b in zip(prompts, budgets)]
        await asyncio.gather(*(h.result() for h in handles))
        await sched.stop()
        return sched, handles

    sched, handles = run_async(main())
    ref_eng = make_engine(mv, n_slots=2)
    refs = ref_eng.run(prompts, budgets)
    for p, b, h, ref in zip(prompts, budgets, handles, refs):
        assert h.retired.tokens == ref, f"stream diverged for prompt {p}"
        assert h.tokens == ref[len(p):]           # streamed = generated
        assert h.retired.reason == "budget"
        assert len(h.tokens) == b
    m = sched.metrics
    assert m.counters["admitted"] == len(prompts)
    assert m.ttft.count == len(prompts)
    assert m.itl.count > 0
    assert m.e2e.count == len(prompts)
    assert m.mean_occupancy > 0.5                 # 8 reqs through 2 slots


# ----------------------------------------------------------------------
# chunked prefill through the scheduler (round 12: decode priority)
# ----------------------------------------------------------------------

def test_chunked_decode_priority_live_stream_never_stalls(mv):
    """The chunked-prefill contract end-to-end: while a long prompt
    chunks into the fused step, every already-live stream emits a token
    on EVERY step — decode work is never preempted by prefill work. The
    per-step emission log is recorded inside the engine-step wrapper, so
    the assertion is exact, not timing-based."""

    async def main():
        eng = make_engine(mv, n_slots=2, prefill_chunk=16, block_size=8)
        log = []
        orig_step = eng.step

        def recording_step():
            res = orig_step()
            log.append((set(res.emitted), res.prefill_tokens))
            return res

        eng.step = recording_step
        sched = Scheduler(eng, max_queue=8)
        await sched.start()
        a = sched.submit([1, 2, 3], 30)
        async for _ in a:                    # A is live and decoding
            break
        b = sched.submit(list(range(1, 40)), 4)
        await asyncio.gather(a.result(), b.result())
        await sched.stop()
        return eng, sched, a, b, log

    eng, sched, a, b, log = run_async(main())
    a_id, b_id = a._req.seq_id, b._req.seq_id
    b_first = next(i for i, (em, _) in enumerate(log) if b_id in em)
    # B's 39-token prompt chunked in over several steps (decode priority
    # shrinks the 16-token budget to one 8-row block while A decodes)
    chunk_steps = [i for i, (_, pt) in enumerate(log[:b_first + 1]) if pt]
    assert len(chunk_steps) >= 3, \
        f"expected a multi-chunk prefill, got {chunk_steps}"
    # the pinned property: A emitted on every step of B's chunk-in
    # window (A retires on budget later, so it is live throughout)
    for i in range(chunk_steps[0], b_first + 1):
        assert a_id in log[i][0], f"live stream stalled at step {i}"
    # B's first token came from the fused step that ran its last chunk
    assert b_id not in {s for em, _ in log[:b_first] for s in em}
    # observability: the per-step histogram saw every chunk and sums to
    # the tokens actually prefilled
    h = sched.metrics.prefill_tokens_per_step.summary(unit="tok", scale=1.0)
    assert h["count"] == len(log)
    assert sched.metrics.prefill_tokens_per_step.sum == \
        eng.prefilled_tokens
    # greedy parity with the offline chunked engine
    ref_eng = make_engine(mv, n_slots=2, prefill_chunk=16, block_size=8)
    refs = ref_eng.run([[1, 2, 3], list(range(1, 40))], [30, 4])
    assert a.retired.tokens == refs[0]
    assert b.retired.tokens == refs[1]


def test_wave_admission_records_decode_stall(mv):
    """The decode_stall counter pins the wave baseline's failure mode: a
    monolithic admission that runs while streams are live books its full
    prefill wall-clock as stall time (the chunked path admits without
    running any prefill, so the same counter stays near zero there)."""

    async def main():
        eng = make_engine(mv, n_slots=2)
        sched = Scheduler(eng, max_queue=8)
        await sched.start()
        a = sched.submit([1, 2, 3], 20)
        async for _ in a:                    # A is live when B admits
            break
        b = sched.submit(list(range(1, 40)), 2)
        await asyncio.gather(a.result(), b.result())
        await sched.stop()
        return sched

    sched = run_async(main())
    assert sched.metrics.decode_stall_s > 0.0
    gauges = sched.metrics.summary()["gauges"]
    assert gauges["serve_decode_stall_ms"] > 0.0
    # wave mode books prefilled-tokens-per-ADMISSION into the histogram
    assert sched.metrics.prefill_tokens_per_step.count >= 2
