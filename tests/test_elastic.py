"""Elastic training (ISSUE 13): verified checkpoints (blake2b manifest,
torn-dir skip, corrupt fallback, retention) and the host-failure
supervisor's state machine (kill→gang restart/rejoin, heartbeat-timeout
detection, held-dead host→rung-down re-mesh).

Supervisor tests drive the REAL Supervisor watch loop against stub
worker processes (heartbeat + exit protocol only, no jax import per
worker) so they stay tier-1 sized; the full 2-process JAX kill/re-mesh
end-to-end lives in scripts/fault_inject_train.py (CI smoke leg)."""

import json
import os
import signal
import sys
import textwrap
import threading
import time

import jax
import pytest

from distributed_pytorch_tpu.config import LLMConfig, TrainConfig
from distributed_pytorch_tpu.parallel.mesh import rung_down
from distributed_pytorch_tpu.train import checkpoint as ckpt
from distributed_pytorch_tpu.train import supervisor as sup
from distributed_pytorch_tpu.train.loop import train

TINY = dict(vocab_size=256, block_size=32, n_embd=32, n_head=4,
            n_kv_heads=4, n_layer=2, up_dim=64)


def _tc(**kw):
    base = dict(dataset="synthetic", data_dir="bench_data",
                total_batch_size=2 * 2 * 32, batch_size=2,
                max_iters=5, parallelism="single", eval=False,
                log_interval=100, save_stats=False, learning_rate=1e-3,
                warmup_steps=2)
    base.update(kw)
    return TrainConfig(**base)


@pytest.fixture()
def in_tmp(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    return tmp_path


# ---------------------------------------------------------------------------
# Verified checkpoints.
# ---------------------------------------------------------------------------

def _mk_step(root, n, payload=b"x" * 256, manifest=True):
    """Hand-build one step dir: state/ payload + config.json
    (+ manifest)."""
    d = os.path.join(root, f"step_{n}")
    os.makedirs(os.path.join(d, "state"), exist_ok=True)
    with open(os.path.join(d, "state", "data.bin"), "wb") as f:
        f.write(payload)
    with open(os.path.join(d, "config.json"), "w") as f:
        json.dump({"step": n}, f)
    if manifest:
        ckpt.write_manifest(d)
    return d


def test_manifest_roundtrip_detects_flipped_byte(in_tmp):
    root = "ck"
    d = _mk_step(root, 10)
    assert ckpt.verify_manifest(d) == []
    assert ckpt.verify_manifest(d, deep=False) == []
    # flip one byte: size unchanged, so only the DEEP check can see it
    with open(os.path.join(d, "state", "data.bin"), "r+b") as f:
        f.seek(17)
        b = f.read(1)
        f.seek(17)
        f.write(bytes([b[0] ^ 0xFF]))
    assert ckpt.verify_manifest(d, deep=False) == []
    deep = ckpt.verify_manifest(d)
    assert deep and "blake2b mismatch" in deep[0]


def test_latest_step_dir_skips_torn_dirs(in_tmp):
    root = "ck"
    good = _mk_step(root, 1)
    # torn: orbax state/ never finalized (empty) — the crash-mid-async
    # shape; config.json exists because it is written eagerly
    torn = os.path.join(root, "step_2")
    os.makedirs(os.path.join(torn, "state"))
    with open(os.path.join(torn, "config.json"), "w") as f:
        json.dump({}, f)
    # truncated: manifest written, then a payload file lost bytes
    trunc = _mk_step(root, 3)
    with open(os.path.join(trunc, "state", "data.bin"), "r+b") as f:
        f.truncate(10)
    assert ckpt.latest_step_dir(root) == os.path.abspath(good)
    # legacy pre-manifest dirs (structurally complete) are still accepted
    legacy = _mk_step(root, 4, manifest=False)
    assert ckpt.latest_step_dir(root) == os.path.abspath(legacy)


def test_corrupt_newest_falls_back_to_previous_good(in_tmp):
    """Acceptance criterion: a flipped byte in the newest checkpoint is
    detected by the manifest and restore falls back to the previous good
    step dir with no operator intervention."""
    mc = LLMConfig(**TINY)
    stats = train(mc, _tc(max_iters=6, file_name="ver", ckpt_interval=2),
                  log=lambda s: None)
    root = os.path.join("checkpoints", "ver")
    last = ckpt.latest_step_dir(root)
    assert last is not None
    assert ckpt.verify_manifest(last) == []  # async saves got manifests

    # flip a byte in the newest dir's largest payload file
    victim, size = None, 0
    for dirpath, _, files in os.walk(last):
        for name in files:
            p = os.path.join(dirpath, name)
            if name != "manifest.json" and os.path.getsize(p) > size:
                victim, size = p, os.path.getsize(p)
    with open(victim, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]))

    abstract = jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), stats["state"])
    with pytest.raises(ckpt.CheckpointCorrupt):
        ckpt.restore_checkpoint(last, abstract)
    res = ckpt.restore_latest(root, abstract)
    assert res is not None
    state, path, skipped = res
    assert path != last and any(last in s for s in skipped)
    assert int(jax.device_get(state.step)) < \
        int(jax.device_get(stats["state"].step))

    # ...and a full resume through the trainer lands on the fallback
    resumed = train(mc, _tc(max_iters=6, file_name="ver", resume=True),
                    log=lambda s: None)
    assert resumed["train_losses"]  # continued, did not crash


def test_retention_prunes_oldest_verified_only(in_tmp):
    root = "ck"
    dirs = [_mk_step(root, n) for n in (1, 2, 3, 4)]
    pending = os.path.join(root, "step_5")  # manifest-less: in flight
    os.makedirs(os.path.join(pending, "state"))
    with open(os.path.join(pending, "state", "data.bin"), "wb") as f:
        f.write(b"y" * 64)

    assert ckpt.prune_checkpoints(root, keep=0) == []  # disabled
    deleted = ckpt.prune_checkpoints(root, keep=2)
    assert deleted == [os.path.abspath(d) for d in dirs[:2]]
    assert not os.path.exists(dirs[0]) and not os.path.exists(dirs[1])
    assert os.path.exists(dirs[2]) and os.path.exists(dirs[3])
    assert os.path.exists(pending)  # never touch unverified dirs
    # idempotent at the floor; the newest good dir always survives
    assert ckpt.prune_checkpoints(root, keep=2) == []
    # the manifest-less dir with non-empty state/ reads as legacy-complete
    # (pre-manifest saves stay restorable); restore_latest's deep verify +
    # fallback is the safety net if it is actually torn
    assert ckpt.latest_step_dir(root) == os.path.abspath(pending)


def test_keep_ckpts_knob_prunes_during_training(in_tmp):
    mc = LLMConfig(**TINY)
    train(mc, _tc(max_iters=8, file_name="kept", ckpt_interval=2,
                  keep_ckpts=2), log=lambda s: None)
    root = os.path.join("checkpoints", "kept")
    steps = sorted(int(d[5:]) for d in os.listdir(root)
                   if d.startswith("step_"))
    assert len(steps) == 2, steps
    assert ckpt.latest_step_dir(root) is not None


def test_rung_down_ladder():
    assert [rung_down(n) for n in (2, 3, 4, 5, 6, 8, 9)] == \
        [1, 2, 2, 4, 4, 4, 8]
    with pytest.raises(AssertionError):
        rung_down(1)
    # the supervisor's fs-only mirror must agree (it avoids importing
    # jax, so the function is duplicated — this pin keeps them honest)
    for n in range(2, 33):
        assert sup._rung_down(n) == rung_down(n)


# ---------------------------------------------------------------------------
# SIGINT graceful stop (satellite): Ctrl-C == SIGTERM path.
# ---------------------------------------------------------------------------

def test_sigint_checkpoints_and_resumes(in_tmp):
    mc = LLMConfig(**TINY)
    quiet = lambda s: None
    full = train(mc, _tc(max_iters=8, file_name="intfull"), log=quiet)

    fired = []

    def log_and_interrupt(s):
        if "iter" in s and not fired:
            fired.append(1)
            os.kill(os.getpid(), signal.SIGINT)

    interrupted = train(mc, _tc(max_iters=8, file_name="intrun",
                                log_interval=1), log=log_and_interrupt)
    assert fired
    assert len(interrupted["train_losses"]) < 9, "SIGINT did not stop"
    assert ckpt.latest_step_dir(os.path.join("checkpoints", "intrun"))

    resumed = train(mc, _tc(max_iters=8, file_name="intrun", resume=True),
                    log=quiet)
    assert resumed["train_losses"] == \
        full["train_losses"][-len(resumed["train_losses"]):]


# ---------------------------------------------------------------------------
# Supervisor state machine (stub workers — no jax in the gang).
# ---------------------------------------------------------------------------

# Stub worker: heartbeats per the supervisor env contract, exits 0 once
# the control file appears. argv: <mode>, mode 'freeze' beats once then
# hangs silently (a SIGSTOP-shaped failure the heartbeat watch must
# catch); 'ok' behaves.
_STUB = textwrap.dedent("""
    import json, os, sys, time
    hb = os.environ.get("SUPERVISOR_HB_FILE", "")
    interval = float(os.environ.get("SUPERVISOR_HB_INTERVAL_S", "0.1"))
    mode = sys.argv[1]
    stop_file = sys.argv[2]
    def beat(seq):
        tmp = hb + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"pid": os.getpid(), "seq": seq}, f)
        os.replace(tmp, hb)
    seq = 0
    while True:
        if hb and (mode != "freeze" or seq == 0):
            beat(seq)
        seq += 1
        if mode != "freeze" and os.path.exists(stop_file):
            sys.exit(0)
        time.sleep(interval)
""")


def _sup_cfg(tmp_path, hosts, **kw):
    base = dict(hosts=hosts, run_name="elastic", poll_s=0.02,
                hb_timeout_s=60.0, max_restarts=4, backoff_base_s=0.05,
                backoff_cap_s=0.1, remesh_deadline_s=0.4,
                hb_interval_s=0.05)
    base.update(kw)
    return sup.SupervisorConfig(**base)


def _run_supervisor(cfg, worker_cmd, timeout=30.0):
    """Run Supervisor.run() on a thread; returns (rc_getter, thread,
    supervisor)."""
    s = sup.Supervisor(cfg, worker_cmd=worker_cmd, log=lambda m: None)
    rc = {}

    def go():
        rc["code"] = s.run()

    t = threading.Thread(target=go, daemon=True)
    t.start()
    return rc, t, s


def _wait(predicate, timeout=20.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.02)
    raise TimeoutError(f"timed out waiting for {msg}")


def _state(run_dir):
    try:
        with open(os.path.join(run_dir, sup.STATE_FILE)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _events(run_dir):
    try:
        with open(os.path.join(run_dir, sup.TIMELINE_FILE)) as f:
            return [json.loads(line) for line in f if line.strip()]
    except (OSError, ValueError):
        return []


@pytest.fixture()
def stub(tmp_path):
    path = tmp_path / "stub_worker.py"
    path.write_text(_STUB)
    return str(path)


def test_supervisor_kill_then_gang_rejoin(in_tmp, stub):
    stop_file = os.path.join(str(in_tmp), "stop_ok")
    cfg = _sup_cfg(in_tmp, hosts=2)
    cmd = lambda slot, n, resume: [sys.executable, stub, "ok", stop_file]
    rc, t, s = _run_supervisor(cfg, cmd)
    run_dir = os.path.join("runs", "elastic")

    _wait(lambda: (_state(run_dir) or {}).get("status") == "running",
          msg="gang 1 up")
    st = _state(run_dir)
    assert st["n_hosts"] == 2 and len(st["workers"]) == 2
    victim = max(st["workers"], key=lambda w: w["slot"])
    os.kill(victim["os_pid"], signal.SIGKILL)

    # the victim keeps its slot (process id) in the restarted gang
    _wait(lambda: (_state(run_dir) or {}).get("generation", 1) >= 2
          and (_state(run_dir) or {}).get("status") == "running",
          msg="gang restart")
    st2 = _state(run_dir)
    assert {w["slot"] for w in st2["workers"]} == {0, 1}
    assert st2["n_hosts"] == 2  # same mesh: a restart, not a re-mesh

    open(stop_file, "w").close()
    t.join(timeout=20)
    assert not t.is_alive() and rc["code"] == sup.EXIT_OK
    names = [e["event"] for e in _events(run_dir)]
    assert "worker_down" in names and "gang_restart" in names \
        and "completed" in names
    down = next(e for e in _events(run_dir) if e["event"] == "worker_down")
    assert down["slot"] == victim["slot"] and down["reason"] == "exit_-9"


def test_supervisor_heartbeat_timeout_detection(in_tmp, stub):
    stop_file = os.path.join(str(in_tmp), "stop_ok")
    cfg = _sup_cfg(in_tmp, hosts=2, hb_timeout_s=0.5)
    # first incarnation (resume=False): slot 1 freezes after one beat —
    # alive for poll() but heartbeat-silent; later incarnations behave
    cmd = lambda slot, n, resume: [
        sys.executable, stub,
        "freeze" if (slot == 1 and not resume) else "ok", stop_file]
    rc, t, s = _run_supervisor(cfg, cmd)
    run_dir = os.path.join("runs", "elastic")

    _wait(lambda: any(e.get("reason") == "heartbeat_timeout"
                      for e in _events(run_dir)),
          msg="heartbeat timeout detection")
    open(stop_file, "w").close()
    t.join(timeout=20)
    assert not t.is_alive() and rc["code"] == sup.EXIT_OK
    down = next(e for e in _events(run_dir)
                if e.get("reason") == "heartbeat_timeout")
    assert down["slot"] == 1


def test_supervisor_held_host_remeshes_rung_down(in_tmp, stub):
    stop_file = os.path.join(str(in_tmp), "stop_ok")
    cfg = _sup_cfg(in_tmp, hosts=2)
    cmd = lambda slot, n, resume: [sys.executable, stub, "ok", stop_file]
    rc, t, s = _run_supervisor(cfg, cmd)
    run_dir = os.path.join("runs", "elastic")

    _wait(lambda: (_state(run_dir) or {}).get("status") == "running",
          msg="gang 1 up")
    st = _state(run_dir)
    victim = max(st["workers"], key=lambda w: w["slot"])
    # hold first (the host is NOT coming back), then SIGKILL
    with open(os.path.join(run_dir, f"hold_{victim['slot']}"), "w") as f:
        f.write("dead host\n")
    os.kill(victim["os_pid"], signal.SIGKILL)

    _wait(lambda: any(e["event"] == "remesh" for e in _events(run_dir)),
          msg="rung-down re-mesh")
    remesh = next(e for e in _events(run_dir) if e["event"] == "remesh")
    assert remesh["old_n"] == 2 and remesh["new_n"] == 1 == rung_down(2)

    _wait(lambda: (_state(run_dir) or {}).get("n_hosts") == 1
          and (_state(run_dir) or {}).get("status") == "running",
          msg="survivor gang up")
    open(stop_file, "w").close()
    t.join(timeout=20)
    assert not t.is_alive() and rc["code"] == sup.EXIT_OK
    assert (_state(run_dir) or {}).get("n_hosts") == 1
    # hold markers are cleared with the old topology
    assert not os.path.exists(os.path.join(run_dir,
                                           f"hold_{victim['slot']}"))


def test_supervisor_single_host_held_is_unrecoverable(in_tmp, stub):
    stop_file = os.path.join(str(in_tmp), "stop_never")
    cfg = _sup_cfg(in_tmp, hosts=1, remesh_deadline_s=0.2)
    cmd = lambda slot, n, resume: [sys.executable, stub, "ok", stop_file]
    rc, t, s = _run_supervisor(cfg, cmd)
    run_dir = os.path.join("runs", "elastic")

    _wait(lambda: (_state(run_dir) or {}).get("status") == "running",
          msg="gang up")
    st = _state(run_dir)
    with open(os.path.join(run_dir, "hold_0"), "w") as f:
        f.write("dead\n")
    os.kill(st["workers"][0]["os_pid"], signal.SIGKILL)
    t.join(timeout=20)
    assert not t.is_alive() and rc["code"] == sup.EXIT_NO_RUNG
    assert (_state(run_dir) or {}).get("status") == "failed"
