"""obs/ subsystem unit tests: trace-recorder ring bounds and
disabled-mode overhead, Chrome-trace/Perfetto export schema validity,
cross-process span stitching (ingest/re-base), the flight recorder's
ring + JSONL dump, and the shared jax.profiler wrapper's guard rails."""

import json
import os
import time

import jax.numpy as jnp
import pytest

from distributed_pytorch_tpu.obs import profile as obs_profile
from distributed_pytorch_tpu.obs.flight import FlightRecorder
from distributed_pytorch_tpu.obs.trace import (TraceRecorder, new_trace_id)


# ----------------------------------------------------------------------
# TraceRecorder
# ----------------------------------------------------------------------

def test_trace_ids_unique_and_short():
    ids = {new_trace_id() for _ in range(256)}
    assert len(ids) == 256
    assert all(len(t) == 16 for t in ids)


def test_ring_bound_and_dropped_counter():
    rec = TraceRecorder(capacity=16)
    tid = new_trace_id()
    for i in range(40):
        rec.add(f"s{i}", tid, t0=float(i), dur=0.1)
    assert len(rec) == 16
    assert rec.dropped == 40 - 16
    # the ring keeps the NEWEST spans
    names = [s["name"] for s in rec.snapshot()]
    assert names[0] == "s24" and names[-1] == "s39"


def test_disabled_records_nothing_and_is_cheap():
    rec = TraceRecorder(capacity=64, enabled=False)
    tid = new_trace_id()
    with rec.span("x", tid):
        pass
    rec.add("y", tid, t0=0.0, dur=1.0)
    rec.event("z", tid)
    assert len(rec) == 0
    # overhead bound: the disabled path is one attribute check — 100k
    # calls must stay far under the cost of a single fused decode step
    # per call (generous 5 µs/call bound absorbs CI jitter)
    n = 100_000
    t0 = time.perf_counter()
    for _ in range(n):
        rec.span("hot", tid)
        rec.event("hot", tid)
    per_call = (time.perf_counter() - t0) / (2 * n)
    assert per_call < 5e-6, f"disabled-recorder call cost {per_call:.2e}s"


def test_none_trace_id_is_noop_even_when_enabled():
    rec = TraceRecorder()
    rec.add("a", None, t0=0.0, dur=1.0)
    rec.event("b", None)
    with rec.span("c", None):
        pass
    assert len(rec) == 0


def test_span_context_manager_times_and_sets_attrs():
    rec = TraceRecorder()
    tid = new_trace_id()
    with rec.span("work", tid, cat="test", fixed=1) as sp:
        time.sleep(0.01)
        sp.set(extra="yes")
    (s,) = rec.spans_for(tid)
    assert s["name"] == "work" and s["cat"] == "test"
    assert s["dur"] >= 0.009
    assert s["attrs"] == {"fixed": 1, "extra": "yes"}


def test_spans_for_filters_and_orders():
    rec = TraceRecorder()
    t1, t2 = new_trace_id(), new_trace_id()
    rec.add("late", t1, t0=2.0, dur=0.1)
    rec.add("other", t2, t0=0.5, dur=0.1)
    rec.add("early", t1, t0=1.0, dur=0.1)
    assert [s["name"] for s in rec.spans_for(t1)] == ["early", "late"]


def test_summary_offsets_and_ingest_rebase():
    replica = TraceRecorder()
    tid = new_trace_id()
    replica.add("sched.queue", tid, t0=100.0, dur=0.005, cat="sched")
    replica.add("sched.decode", tid, t0=100.010, dur=0.040, cat="sched")
    summ = replica.summary(tid, base=100.0)
    assert summ[0]["off_ms"] == 0.0
    assert summ[1]["off_ms"] == pytest.approx(10.0, abs=1e-6)
    # the router re-bases on its own clock at the dispatch timestamp
    router = TraceRecorder()
    router.ingest(tid, summ, base=500.0, replica="r1")
    spans = router.spans_for(tid)
    assert spans[0]["t0"] == pytest.approx(500.0)
    assert spans[1]["t0"] == pytest.approx(500.010)
    assert all(s["attrs"]["replica"] == "r1" for s in spans)
    # malformed peer spans are skipped, never raised
    router.ingest(tid, [{"off_ms": "not-a-number"}], base=0.0)


def test_chrome_export_schema():
    rec = TraceRecorder()
    tid = new_trace_id()
    rec.add("router.request", tid, t0=1.0, dur=0.5, cat="router", n=1)
    rec.add("sched.decode", tid, t0=1.1, dur=0.3, cat="sched")
    doc = json.loads(json.dumps(rec.to_chrome(tid)))   # JSON-serializable
    assert isinstance(doc["traceEvents"], list)
    evs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert len(evs) == 2
    for e in evs:
        assert {"name", "ph", "ts", "dur", "pid", "tid",
                "args"} <= set(e)
        assert isinstance(e["ts"], (int, float))
        assert isinstance(e["dur"], (int, float))
        assert e["args"]["trace"] == tid
    # ts is microseconds
    assert evs[0]["ts"] == pytest.approx(1.0e6)
    assert evs[0]["dur"] == pytest.approx(0.5e6)
    # one thread-name metadata record per category lane
    assert {m["args"]["name"] for m in metas} == {"router", "sched"}


def test_trace_dump_jsonl_roundtrip(tmp_path):
    rec = TraceRecorder()
    tid = new_trace_id()
    rec.add("a", tid, t0=0.0, dur=1.0, k="v")
    path = rec.dump_jsonl(str(tmp_path / "sub" / "trace.jsonl"), tid)
    lines = [json.loads(ln) for ln in open(path)]
    assert lines[0]["name"] == "a" and lines[0]["attrs"] == {"k": "v"}


# ----------------------------------------------------------------------
# FlightRecorder
# ----------------------------------------------------------------------

def test_flight_ring_bound_and_totals():
    fl = FlightRecorder(capacity=8)
    for i in range(20):
        fl.record(step=i, step_ms=1.0)
    assert len(fl) == 8
    assert fl.total == 20
    assert fl.dropped == 12
    ent = fl.entries()
    assert [e["step"] for e in ent] == list(range(12, 20))
    assert all("t" in e for e in ent)
    assert [e["step"] for e in fl.entries(n=3)] == [17, 18, 19]


def test_flight_disabled_and_dump(tmp_path):
    fl = FlightRecorder(capacity=8, enabled=False)
    fl.record(step=1)
    assert len(fl) == 0 and fl.total == 0
    fl.enabled = True
    fl.record(step=1, n_live=3)
    path = fl.dump_jsonl(str(tmp_path / "timeline.jsonl"))
    (rec,) = [json.loads(ln) for ln in open(path)]
    assert rec["step"] == 1 and rec["n_live"] == 3


# ----------------------------------------------------------------------
# obs/profile.py — the shared jax.profiler wrapper
# ----------------------------------------------------------------------

def test_profile_dir_convention(tmp_path):
    d = obs_profile.profile_dir("myrun", root=str(tmp_path))
    assert d == os.path.join(str(tmp_path), "myrun", "profile")
    assert os.path.isdir(d)


def test_profile_capture_and_busy_guard(tmp_path):
    """ONE start/stop cycle covering the whole surface (each
    jax.profiler export costs seconds in a warm process, so the guard,
    context-manager, and artifact checks share a single capture)."""
    # disabled context manager: no capture, yields None
    with obs_profile.profile_trace(str(tmp_path / "x"), enabled=False) \
            as d:
        assert d is None
    assert obs_profile.active() is None
    out = str(tmp_path / "cap")
    d = obs_profile.start_profile(out)
    assert d == out and obs_profile.active() == out
    # the process-global profiler admits one capture at a time: both
    # direct start and the timed-capture helper bounce off the guard
    with pytest.raises(obs_profile.ProfilerBusy):
        obs_profile.start_profile(str(tmp_path / "other"))
    with pytest.raises(obs_profile.ProfilerBusy):
        obs_profile.capture(10, str(tmp_path / "other"))
    jnp.square(jnp.arange(64.0)).block_until_ready()   # traced work
    assert obs_profile.stop_profile() == out
    assert obs_profile.active() is None
    assert obs_profile.stop_profile() is None          # idempotent
    # the capture left a jax profiler artifact tree behind
    assert any(files for _, _, files in os.walk(out)), \
        "profiler capture wrote nothing"
