"""Quantized serving (ops/quant.py): int8 round-trip error bounds, the
pytree quantize/dequantize inverse, the matmul interception store, the
QUANT_KV/QUANT_W gates, and the int8 DecodeEngine measured against the
bf16 oracle on LOGITS (tokens can legitimately flip at a near-tie — the
acceptance contract is a bounded logits divergence, not token equality)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.config import LLMConfig
from distributed_pytorch_tpu.engine import DecodeEngine
from distributed_pytorch_tpu.models.gpt import LLM, init_cache
from distributed_pytorch_tpu.ops import quant


def tiny_cfg(**kw):
    base = dict(vocab_size=97, block_size=64, n_embd=48, n_head=4,
                n_kv_heads=2, attn="gqa", n_layer=2, up_dim=64,
                non_linearity="swiglu", pos_emb="rope", dropout=0.0,
                q_latent_dim=16, kv_latent_dim=16, rope_head_dim=8)
    base.update(kw)
    return LLMConfig(**base)


def build(cfg, seed=0):
    model = LLM(cfg, attn_impl="naive")
    rng = jax.random.PRNGKey(seed)
    x = jnp.zeros((1, cfg.block_size), jnp.int32)
    variables = model.init({"params": rng, "dropout": rng}, x, x)
    return model, {k: v for k, v in variables.items()}


# ---------------------------------------------------------------------------
# core quantize / dequantize
# ---------------------------------------------------------------------------

def test_roundtrip_error_bound():
    """Symmetric int8: |dequant(quant(x)) - x| <= scale/2 elementwise (half
    a quantization step), with the group amax representable exactly."""
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 3, 2, 16))
    codes, scale = quant.quantize_int8(x, axis=-1)
    assert codes.dtype == jnp.int8
    assert scale.shape == (4, 3, 2, 1)
    d = quant.dequantize_int8(codes, scale)
    err = np.abs(np.asarray(d - x))
    bound = np.asarray(scale) * 0.5 + 1e-7
    assert (err <= bound).all()
    # the per-group max hits the +-127 code exactly
    amax = np.max(np.abs(np.asarray(x)), axis=-1)
    np.testing.assert_allclose(
        np.max(np.abs(np.asarray(d)), axis=-1), amax, rtol=1e-6)


def test_zero_rows_stay_zero():
    """All-zero groups (dead cache slots) get scale 0 and dequantize to
    exact zeros — no NaN/inf from the guarded divide."""
    x = jnp.zeros((2, 3, 2, 8))
    codes, scale = quant.quantize_int8(x, axis=-1)
    assert not np.asarray(codes).any()
    d = quant.dequantize_int8(codes, scale)
    assert np.isfinite(np.asarray(d)).all() and not np.asarray(d).any()


def test_quantize_kv_shapes():
    k = jax.random.normal(jax.random.PRNGKey(1), (3, 5, 2, 16))
    codes, scale = quant.quantize_kv(k)
    assert codes.shape == k.shape and codes.dtype == jnp.int8
    assert scale.shape == (3, 5, 2, 1)


# ---------------------------------------------------------------------------
# pytree transforms + the interception store
# ---------------------------------------------------------------------------

def test_quantize_params_structure_and_inverse():
    cfg = tiny_cfg()
    _, variables = build(cfg)
    params = variables["params"]
    q = quant.quantize_params(params)
    # matmul kernels are in, with codes int8 + f32 per-output-channel scale
    leaf = q["block_0"]["attn"]["c_attn"]["kernel"]
    assert leaf["q8"].dtype == jnp.int8
    assert leaf["scale"].shape == (1, leaf["q8"].shape[1])
    assert "embedding" in q["tkn_emb"]  # tied lm head, per-vocab-row scale
    assert q["tkn_emb"]["embedding"]["scale"].shape == \
        (params["tkn_emb"]["embedding"].shape[0], 1)
    # biases / norms stay out (call sites keep bf16 for them)
    assert "bias" not in q["block_0"]["attn"]["c_attn"]
    assert "ln1" not in q["block_0"]
    # dequantize_params is the inverse up to the quantization step
    d = quant.dequantize_params(q)
    w = params["block_0"]["attn"]["c_attn"]["kernel"]
    step = np.asarray(q["block_0"]["attn"]["c_attn"]["kernel"]["scale"])
    err = np.abs(np.asarray(d["block_0"]["attn"]["c_attn"]["kernel"]) -
                 np.asarray(w))
    assert (err <= step * 0.5 + 1e-7).all()


def test_quantize_params_skips_expert_stacks():
    cfg = tiny_cfg(moe=True, n_exp=4, n_shared=1, n_act=2, aux_free=True)
    _, variables = build(cfg)
    q = quant.quantize_params(variables["params"])
    moe = q.get("block_0", {}).get("moe", {})
    assert "experts_fc" not in moe and "experts_proj" not in moe


def test_maybe_quantized_matmul_matches_dequant_reference():
    """(x @ codes) * scale must equal x @ dequant(codes) — the scale is
    per output channel, so the fold is exact algebra."""
    w = jax.random.normal(jax.random.PRNGKey(2), (32, 24)) * 0.05
    x = jax.random.normal(jax.random.PRNGKey(3), (4, 32))
    store = {"lin": {"kernel": dict(zip(("q8", "scale"),
                                        quant.quantize_int8(w, axis=0)))}}
    with quant.use_quantized_params(store):
        y = quant.maybe_quantized_matmul(x, ("lin", "kernel"))
        assert quant.maybe_quantized_matmul(x, ("lin", "missing")) is None
    assert quant.maybe_quantized_matmul(x, ("lin", "kernel")) is None  # inactive
    ref = x @ quant.dequantize_int8(store["lin"]["kernel"]["q8"],
                                    store["lin"]["kernel"]["scale"],
                                    x.dtype)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_gate_resolution():
    assert quant.resolve_gate("auto", True) and \
        not quant.resolve_gate("auto", False)
    assert quant.resolve_gate("on", False)
    assert not quant.resolve_gate("off", True)
    with pytest.raises(ValueError):
        quant.resolve_gate("maybe", True)


def test_quant_kv_usable_family():
    assert quant.quant_kv_usable(tiny_cfg(attn="gqa"))
    assert quant.quant_kv_usable(tiny_cfg(attn="mha"))
    assert not quant.quant_kv_usable(tiny_cfg(attn="mla"))
    with pytest.raises(ValueError):
        init_cache(tiny_cfg(attn="mla"), 1, 16, dtype=jnp.int8)


# ---------------------------------------------------------------------------
# the int8 engine vs the bf16 oracle
# ---------------------------------------------------------------------------

PROMPTS = [[1, 2, 3], [5, 6, 7, 8, 9, 10, 11], [20] * 17, [42, 43]]


def _teacher_forced_logits_err(model, variables, tokens, cache_dtype,
                               qparams=None, n_steps=8):
    """Max |logits_int8 - logits_f32| over a prefill + teacher-forced
    decode of `tokens` — the engine's exact computation, oracle-fed so
    both dtypes score identical inputs at every step."""
    import contextlib
    cfg = model.config
    c_ref = init_cache(cfg, 1, cfg.block_size, dtype=jnp.float32)
    c_q = init_cache(cfg, 1, cfg.block_size, dtype=cache_dtype)
    p = jnp.asarray(tokens[:4], jnp.int32)[None]
    ctx = (quant.use_quantized_params(qparams) if qparams is not None
           else contextlib.nullcontext())
    lf, _, c_ref = model.apply(variables, p, None, c_ref, 0,
                               deterministic=True)
    with ctx:
        lq, _, c_q = model.apply(variables, p, None, c_q, 0,
                                 deterministic=True)
    errs = [float(jnp.max(jnp.abs(lf - lq)))]
    pos = 4
    for t in tokens[4:4 + n_steps]:
        tt = jnp.asarray([[t]], jnp.int32)
        lf, _, c_ref = model.apply(variables, tt, None, c_ref, pos,
                                   deterministic=True)
        ctx = (quant.use_quantized_params(qparams) if qparams is not None
               else contextlib.nullcontext())
        with ctx:
            lq, _, c_q = model.apply(variables, tt, None, c_q, pos,
                                     deterministic=True)
        errs.append(float(jnp.max(jnp.abs(lf - lq))))
        pos += 1
    return max(errs)


def test_int8_cache_logits_tolerance():
    """int8 KV cache vs the f32 oracle, teacher-forced: the measured logits
    divergence stays within a small tolerance of the logit scale (measured
    ~1.5e-3 at this size; asserted with ~10x headroom)."""
    cfg = tiny_cfg()
    model, variables = build(cfg)
    from distributed_pytorch_tpu.models.generate import generate
    toks = generate(model, variables, jnp.asarray(PROMPTS[1], jnp.int32)[None],
                    10, temperature=0.0)[0].tolist()
    err = _teacher_forced_logits_err(model, variables, toks, jnp.int8)
    assert err <= 2e-2, f"int8 cache logits diverged by {err}"


def test_int8_weights_logits_tolerance():
    """Weight-only int8 (decode matmuls on codes + scales) vs the bf16
    oracle, teacher-forced on logits."""
    cfg = tiny_cfg()
    model, variables = build(cfg)
    from distributed_pytorch_tpu.models.generate import generate
    toks = generate(model, variables, jnp.asarray(PROMPTS[1], jnp.int32)[None],
                    10, temperature=0.0)[0].tolist()
    qparams = quant.quantize_params(variables["params"])
    err = _teacher_forced_logits_err(model, variables, toks, jnp.float32,
                                     qparams=qparams)
    assert err <= 5e-2, f"int8 weights logits diverged by {err}"


@pytest.mark.parametrize("kw", [dict(attn="gqa", n_kv_heads=2),
                                dict(attn="mha"),
                                dict(attn="mqa")], ids=["gqa", "mha", "mqa"])
def test_int8_engine_runs_and_caches_are_int8(kw):
    cfg = tiny_cfg(**kw)
    model, variables = build(cfg)
    eng = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                       min_bucket=8, cache_dtype="int8",
                       quantize_weights=True)
    assert eng.kv_quantized and eng.weights_quantized
    assert eng.caches[0]["k"].dtype == jnp.int8
    assert eng.caches[0]["k_scale"].dtype == jnp.float32
    ref = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                       min_bucket=8)
    outs = eng.run(PROMPTS, max_new_tokens=5)
    refs = ref.run(PROMPTS, max_new_tokens=5)
    # the quantized engine must preserve the serving contract (lengths,
    # one step trace); token equality is NOT asserted — near-ties may flip
    assert [len(o) for o in outs] == [len(r) for r in refs]
    assert eng.step_traces == 1


def test_int8_engine_mla_degrades_to_compute_dtype():
    """cache_dtype='int8' on an MLA model falls back to bf16/f32 instead of
    crashing (quant_kv_usable gate) — weight quantization still applies."""
    cfg = tiny_cfg(attn="mla")
    model, variables = build(cfg)
    eng = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                       min_bucket=8, cache_dtype="int8",
                       quantize_weights=True)
    assert not eng.kv_quantized
    assert eng.caches[0]["c_kv"].dtype != jnp.int8
    outs = eng.run(PROMPTS[:2], max_new_tokens=4)
    assert [len(o) for o in outs] == [len(p) + 4 for p in PROMPTS[:2]]


def test_int8_engine_tp_mesh_sharded_sidecars():
    """int8 engine under a tensor-parallel CPU mesh: the scale sidecars'
    kv-head axis shards over 'model' exactly like the code buffers
    (decode_cache_pspec sees the (B, S, n_kv, 1) layout), and greedy
    outputs match the unsharded int8 engine."""
    from distributed_pytorch_tpu.parallel.mesh import mesh_for

    if len(jax.devices()) < 2:
        pytest.skip("needs multi-device CPU platform")
    cfg = tiny_cfg()
    model, variables = build(cfg)
    ref = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                       min_bucket=8, cache_dtype="int8",
                       quantize_weights=True)
    refs = ref.run(PROMPTS, max_new_tokens=5)
    mesh = mesh_for("tp", tp_size=2)
    eng = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                       min_bucket=8, cache_dtype="int8",
                       quantize_weights=True, mesh=mesh, recipe="tp")
    assert eng.caches[0]["k"].sharding.spec[2] == "model"
    assert eng.caches[0]["k_scale"].sharding.spec[2] == "model"
    assert eng.run(PROMPTS, max_new_tokens=5) == refs


def test_quant_kv_env_gate(monkeypatch):
    """QUANT_KV=off pins bf16 despite an explicit int8 request; QUANT_KV=on
    forces int8 without one (the bench A/B contract)."""
    cfg = tiny_cfg()
    model, variables = build(cfg)
    monkeypatch.setenv("QUANT_KV", "off")
    eng = DecodeEngine(model, variables, n_slots=1, cache_dtype="int8")
    assert not eng.kv_quantized
    monkeypatch.setenv("QUANT_KV", "on")
    eng = DecodeEngine(model, variables, n_slots=1)
    assert eng.kv_quantized
    monkeypatch.setenv("QUANT_W", "on")
    eng = DecodeEngine(model, variables, n_slots=1)
    assert eng.weights_quantized


# ---------------------------------------------------------------------------
# serving-memory planning + bytes-model honesty
# ---------------------------------------------------------------------------

def test_serving_estimate_int8_smaller_and_slots_larger():
    from distributed_pytorch_tpu.train.memplan import (estimate_serving_gb,
                                                       plan_decode_slots)
    # realistic head_size (64): the f32 scale sidecar is 4/(2*64) of the
    # bf16 row, keeping the int8 cache just over half the bf16 bytes
    cfg = tiny_cfg(n_embd=256, n_head=4, n_kv_heads=2)
    bf16, bd16 = estimate_serving_gb(cfg, 32, cfg.block_size,
                                     cache_dtype_size=2)
    i8, bd8 = estimate_serving_gb(cfg, 32, cfg.block_size,
                                  cache_dtype_size=1)
    assert bd8["kv_cache"] < bd16["kv_cache"]
    # ~2x fewer cache bytes (the f32 scale sidecars keep it just under 2x)
    assert 0.5 <= bd8["kv_cache"] / bd16["kv_cache"] <= 0.6
    # the quantized-weight copy ADDS memory (prefill keeps bf16 weights)
    qw, bdq = estimate_serving_gb(cfg, 32, cfg.block_size,
                                  cache_dtype_size=1, quantize_weights=True)
    assert qw > i8
    n16 = plan_decode_slots(cfg, cfg.block_size, hbm_gb=0.01,
                            cache_dtype_size=2)
    n8 = plan_decode_slots(cfg, cfg.block_size, hbm_gb=0.01,
                           cache_dtype_size=1)
    assert n8 >= n16 > 0


def test_decode_step_bytes_true_itemsizes():
    from distributed_pytorch_tpu.train import metrics as M
    cfg = tiny_cfg(n_embd=256, n_head=4, n_kv_heads=2)  # head_size 64
    bf16 = M.decode_step_bytes(cfg, 32, 512, 2, 2)
    i8 = M.decode_step_bytes(cfg, 32, 512, 2, 1)
    # cache component halves (+ scale sidecars): identical shapes, ~2x
    # fewer cache bytes — the acceptance check
    kv16 = 32 * 513 * M.kv_bytes_per_token(cfg, 2)
    kv8 = 32 * 513 * M.kv_bytes_per_token(cfg, 1, kv_scales=True)
    assert bf16 - i8 == kv16 - kv8
    assert 0.5 <= kv8 / kv16 <= 0.6
    # weight-only int8 more than halves the weight read (codes + scales)
    qw = M.decode_step_bytes(cfg, 32, 512, 2, 1, quant_weights=True)
    assert qw < i8
    w16 = M.matmul_params_per_token(cfg) * 2
    w8 = (M.quantized_matmul_params_per_token(cfg)
          + M.quantized_matmul_out_channels(cfg) * 4)
    assert (i8 - qw) == (w16 - w8)
    # MoE: expert stacks stay at the bf16 price inside the quantized model
    moe = tiny_cfg(moe=True, n_exp=4, n_shared=1, n_act=2, aux_free=True)
    assert M.quantized_matmul_params_per_token(moe) < \
        M.matmul_params_per_token(moe)
