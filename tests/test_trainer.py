"""Trainer-core tests: LR schedule parity, optimizer grouping, loss descent,
grad-accum invariance. (SURVEY.md §4: the reference has no tests; its
closest artifacts are config asserts and saved loss curves.)"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.config import LLMConfig, TrainConfig
from distributed_pytorch_tpu.train.state import (
    create_train_state, lr_schedule, _decay_mask)
from distributed_pytorch_tpu.train.step import make_train_step

TINY = dict(vocab_size=128, block_size=32, n_embd=32, n_head=4,
            n_kv_heads=2, n_layer=2, up_dim=64)


def ref_get_lr(it, max_lr, warmup, max_iters):
    """Transcription of the reference LR formula (single-gpu/train.py:263-278)
    as the oracle."""
    min_lr = 0.1 * max_lr
    horizon = max_iters + 2
    if it < warmup:
        return max_lr * (it + 1) / warmup
    if it > horizon:
        return min_lr
    ratio = min((it - warmup) / (horizon - warmup), 1.0)
    return min_lr + 0.5 * (1 + math.cos(math.pi * ratio)) * (max_lr - min_lr)


def test_lr_schedule_matches_reference_formula():
    cfg = TrainConfig(learning_rate=3e-4, warmup_steps=10, max_iters=100)
    sched = lr_schedule(cfg)
    for it in [0, 1, 5, 9, 10, 11, 50, 99, 100, 101, 102, 103, 200]:
        expect = ref_get_lr(it, 3e-4, 10, 100)
        np.testing.assert_allclose(float(sched(it)), expect, rtol=1e-5,
                                   err_msg=f"iter {it}")


def test_decay_mask_rank_rule():
    """Weight decay applies iff rank >= 2 (reference model.py:623-626)."""
    params = {"w": jnp.zeros((4, 4)), "b": jnp.zeros((4,)),
              "emb": jnp.zeros((8, 2)), "scale": jnp.zeros(())}
    mask = _decay_mask(params)
    assert mask == {"w": True, "b": False, "emb": True, "scale": False}


@pytest.fixture()  # function-scoped: train_step donates its input state
def tiny_setup():
    mc = LLMConfig(**TINY)
    tc = TrainConfig(total_batch_size=4 * 32, batch_size=2, max_iters=50,
                     warmup_steps=2, learning_rate=1e-2, parallelism="single")
    model, tx, state, _ = create_train_state(mc, tc, None)
    step = make_train_step(model, tx, mc, tc, None, None)
    return mc, tc, model, tx, state, step


def _fake_batch(mc, accum, B, seed=0):
    rng = np.random.default_rng(seed)
    # learnable structure: ramp sequences
    starts = rng.integers(0, mc.vocab_size, size=(accum, B, 1))
    seq = (starts + np.arange(mc.block_size + 1)) % mc.vocab_size
    return (jnp.asarray(seq[..., :-1], jnp.int32),
            jnp.asarray(seq[..., 1:], jnp.int32))


def test_loss_decreases(tiny_setup):
    mc, tc, model, tx, state, step = tiny_setup
    x, y = _fake_batch(mc, 2, 2)
    first = None
    for i in range(30):
        state, m = step(state, x, y)
        if first is None:
            first = float(m["loss"])
    last = float(m["loss"])
    assert np.isfinite(last)
    assert last < first - 1.0, (first, last)


def test_metrics_finite_and_grad_norm_positive(tiny_setup):
    mc, tc, model, tx, state, step = tiny_setup
    x, y = _fake_batch(mc, 2, 2, seed=3)
    _, m = step(state, x, y)
    assert np.isfinite(float(m["loss"]))
    assert float(m["grad_norm"]) > 0


def test_grad_accum_invariance():
    """accum x B and 1 x (accum*B) produce the same update (the reference's
    grad-accum loop divides by accum_steps, train.py:341-342; ours must
    agree with the flat batch)."""
    mc = LLMConfig(**TINY)
    tc = TrainConfig(total_batch_size=4 * 32, batch_size=2, max_iters=10,
                     parallelism="single", compute_dtype="float32")
    model, _, state0, _ = create_train_state(mc, tc, None)
    # SGD: the update is linear in the grad, so accumulation-order float
    # noise stays O(eps) (AdamW's sign-like first step would amplify a
    # near-zero grad element into a +/-lr flip).
    import optax
    tx = optax.sgd(1e-2)
    from distributed_pytorch_tpu.train.state import TrainState
    mk = lambda: TrainState(step=jnp.zeros((), jnp.int32),
                            params=jax.tree_util.tree_map(jnp.copy,
                                                          state0.params),
                            opt_state=tx.init(state0.params),
                            moe_state=state0.moe_state)
    state_a, state_b = mk(), mk()
    step = make_train_step(model, tx, mc, tc, None, None)

    x, y = _fake_batch(mc, 2, 2, seed=7)  # (2, 2, T)
    xf = x.reshape(1, 4, mc.block_size)
    yf = y.reshape(1, 4, mc.block_size)

    state_a, ma = step(state_a, x, y)
    state_b, mb = step(state_b, xf, yf)
    np.testing.assert_allclose(float(ma["loss"]), float(mb["loss"]), rtol=1e-5)
    pa = jax.tree_util.tree_leaves(state_a.params)
    pb = jax.tree_util.tree_leaves(state_b.params)
    for a, b in zip(pa, pb):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_step_flops_policy_aware():
    """MFU accounting must not flatter the attention-only remat: its
    recompute term is strictly between no-remat and whole-block remat."""
    from distributed_pytorch_tpu.train import metrics as M
    base = dict(TINY)
    plain = LLMConfig(**base)
    blk = LLMConfig(**base, act_recomp=True, act_recomp_policy="block")
    att = LLMConfig(**base, act_recomp=True, act_recomp_policy="attn")
    f = lambda c: M.step_flops(c, tokens_per_step=1024, seq_len=32)
    assert f(plain) < f(att) < f(blk)
    assert f(blk) == pytest.approx(f(plain) * 4 / 3)


def test_moe_state_updates_during_training():
    """Aux-free bias must move during training (reference model.py:466-470)
    and live in the train state."""
    mc = LLMConfig(**TINY, moe=True, n_exp=4, n_shared=1, n_act=2,
                   aux_free=True, gamma=0.1)
    tc = TrainConfig(total_batch_size=2 * 32, batch_size=2, max_iters=10,
                     parallelism="single")
    model, tx, state, _ = create_train_state(mc, tc, None)
    step = make_train_step(model, tx, mc, tc, None, None)
    # np.array (never asarray): on CPU jax, asarray is a zero-copy VIEW
    # into the device buffer, which the donated step reuses -- the
    # 'before' snapshot would silently track the updated values
    bias0 = [np.array(b) for b in
             jax.tree_util.tree_leaves(state.moe_state)]
    assert bias0, "moe_state should be non-empty for aux_free MoE"
    x, y = _fake_batch(mc, 1, 2, seed=1)
    state, _ = step(state, x, y)
    bias1 = jax.tree_util.tree_leaves(state.moe_state)
    moved = any(not np.allclose(np.asarray(a), np.asarray(b))
                for a, b in zip(bias0, bias1))
    assert moved, "expert bias did not update"


@pytest.mark.parametrize("policy", ["block", "attn"])
def test_moe_train_step_under_act_recomp(policy):
    """Full train step with remat x MoE (reference kaggle-ddp.py:526-534
    hit an error in exactly this combination): one jitted step must run,
    produce a finite loss, and still update the aux-free bias."""
    mc = LLMConfig(**TINY, moe=True, n_exp=4, n_shared=1, n_act=2,
                   aux_free=True, gamma=0.1, act_recomp=True,
                   act_recomp_policy=policy)
    tc = TrainConfig(total_batch_size=2 * 32, batch_size=2, max_iters=10,
                     parallelism="single")
    model, tx, state, _ = create_train_state(mc, tc, None)
    step = make_train_step(model, tx, mc, tc, None, None)
    # np.array: a zero-copy asarray view would alias the donated buffer
    bias0 = [np.array(b) for b in
             jax.tree_util.tree_leaves(state.moe_state)]
    x, y = _fake_batch(mc, 1, 2, seed=1)
    state, m = step(state, x, y)
    assert np.isfinite(float(m["loss"]))
    bias1 = jax.tree_util.tree_leaves(state.moe_state)
    assert any(not np.allclose(np.asarray(a), np.asarray(b))
               for a, b in zip(bias0, bias1)), \
        "expert bias did not update under act_recomp"


@pytest.mark.parametrize("opt,lr", [("lion", 1e-3), ("adafactor", 3e-2)])
def test_alternative_optimizers_learn(opt, lr, tmp_path, monkeypatch):
    """Lion / Adafactor (exceeding the reference's AdamW-only surface,
    model.py:619-637): a short run must reduce loss, and the fsdp recipe's
    shape-matched opt-state sharding must accept their state pytrees."""
    monkeypatch.chdir(tmp_path)
    from distributed_pytorch_tpu.train.loop import train

    mc = LLMConfig(vocab_size=256, block_size=32, n_embd=32, n_head=4,
                   n_kv_heads=2, n_layer=2, up_dim=48)
    tc = TrainConfig(dataset="synthetic", data_dir=str(tmp_path / "d"),
                     total_batch_size=8 * 2 * 32, batch_size=2,
                     max_iters=60, parallelism="fsdp", optimizer=opt,
                     learning_rate=lr, warmup_steps=3, save_stats=False)
    stats = train(mc, tc, log=lambda s: None)
    first, last = stats["train_losses"][0], stats["train_losses"][-1]
    assert first - last > 0.4, f"{opt}: {first} -> {last}"
