"""End-to-end request tracing across the serving stack (ISSUE 9): the
`X-Trace-Id` header propagates router -> replica HTTP -> scheduler ->
engine, lifecycle spans land on ONE trace (a mid-stream replica kill
included — the failed-over stream stitches into a single timeline), the
replica's `/debug/trace/<id>` + `/debug/timeline` endpoints serve the
recorded evidence, `/metrics` carries the build-info provenance gauge,
and `POST /admin/profile` captures a device trace on a live replica.

Replicas are in-process ServeApp/Scheduler/DecodeEngine stacks on
localhost ports (the tests/test_router.py harness); every async body
runs under a hard wait_for so a tracing bug fails fast, never hangs."""

import asyncio
import json
import os
import time

import jax
import jax.numpy as jnp
import pytest

from distributed_pytorch_tpu.config import LLMConfig
from distributed_pytorch_tpu.engine import DecodeEngine
from distributed_pytorch_tpu.models.gpt import LLM
from distributed_pytorch_tpu.obs import trace as obs_trace
from distributed_pytorch_tpu.serve.router import Router, RouterApp
from distributed_pytorch_tpu.serve.scheduler import Scheduler
from distributed_pytorch_tpu.serve.server import ServeApp


def tiny_cfg(**kw):
    base = dict(vocab_size=97, block_size=64, n_embd=48, n_head=4,
                n_kv_heads=2, attn="gqa", n_layer=2, up_dim=64,
                non_linearity="swiglu", pos_emb="rope", dropout=0.0)
    base.update(kw)
    return LLMConfig(**base)


@pytest.fixture(scope="module")
def mv():
    cfg = tiny_cfg()
    model = LLM(cfg, attn_impl="naive")
    rng = jax.random.PRNGKey(0)
    x = jnp.zeros((1, cfg.block_size), jnp.int32)
    variables = dict(model.init({"params": rng, "dropout": rng}, x, x))
    return cfg, model, variables


def run_async(coro, timeout=300):
    return asyncio.run(asyncio.wait_for(coro, timeout))


class Rep:
    """In-process replica (the test_router.py harness): engine +
    scheduler + HTTP server; `step_delay` throttles the engine so a kill
    can land mid-stream; chunked prefill on so the traced prefill phase
    is the fused-chunk path."""

    def __init__(self, mv, *, port=0, n_slots=2, step_delay=0.0,
                 prefill_chunk=0):
        _, model, variables = mv
        self.eng = DecodeEngine(model, variables, n_slots=n_slots,
                                temperature=0.0, min_bucket=8,
                                prefill_chunk=prefill_chunk)
        if step_delay:
            orig = self.eng.step

            def slow_step():
                time.sleep(step_delay)
                return orig()

            self.eng.step = slow_step
        self.sched = Scheduler(self.eng, max_queue=32)
        self.app = ServeApp(self.sched, port=port)

    async def start(self):
        await self.sched.start()
        await self.app.start()
        return self

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self.app.port}"

    async def kill(self):
        self.app.abort()
        await self.sched.stop()

    async def stop(self):
        await self.app.stop()
        await self.sched.stop()


async def http_get(port, path):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {path} HTTP/1.1\r\nHost: t\r\n\r\n".encode())
    await writer.drain()
    data = await reader.read()
    writer.close()
    head, _, body = data.partition(b"\r\n\r\n")
    return int(head.split(b" ")[1]), body.decode()


async def http_post(port, path, obj, headers=None):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    body = json.dumps(obj).encode()
    extra = "".join(f"{k}: {v}\r\n" for k, v in (headers or {}).items())
    writer.write(f"POST {path} HTTP/1.1\r\nHost: t\r\n{extra}"
                 f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
    await writer.drain()
    return reader, writer


async def read_sse(reader, on_token=None):
    tokens, done = [], None
    while True:
        line = (await reader.readline()).decode().strip()
        if not line:
            continue
        assert line.startswith("data: "), line
        payload = line[len("data: "):]
        if payload == "[DONE]":
            break
        ev = json.loads(payload)
        if "token" in ev:
            tokens.append(ev["token"])
            if on_token is not None:
                await on_token(len(tokens))
        else:
            done = ev
            if "error" in ev:
                break
    return tokens, done


def span_names(spans):
    return [s["name"] for s in spans]


# ----------------------------------------------------------------------
# single replica: header propagation + lifecycle spans + /debug/trace
# ----------------------------------------------------------------------

def test_trace_id_propagates_and_spans_cover_lifecycle(mv):
    """A client-supplied X-Trace-Id comes back on the done event with a
    span summary covering queue -> (chunked) prefill -> decode ->
    retire, and /debug/trace/<id> replays the same trace — in summary
    and Perfetto form."""
    tid = obs_trace.new_trace_id()

    async def main():
        rep = await Rep(mv, prefill_chunk=16).start()
        reader, writer = await http_post(
            rep.app.port, "/v1/completions",
            {"prompt": [1, 2, 3, 4, 5], "max_tokens": 6},
            headers={"X-Trace-Id": tid})
        assert int((await reader.readline()).split(b" ")[1]) == 200
        while (await reader.readline()).strip():
            pass
        tokens, done = await read_sse(reader)
        writer.close()
        dbg = await http_get(rep.app.port, f"/debug/trace/{tid}")
        chrome = await http_get(rep.app.port,
                                f"/debug/trace/{tid}?fmt=chrome")
        missing = await http_get(rep.app.port, "/debug/trace/deadbeef00")
        await rep.stop()
        return tokens, done, dbg, chrome, missing

    tokens, done, (d_st, d_body), (c_st, c_body), (m_st, _) = \
        run_async(main())
    assert len(tokens) == 6
    assert done["done"] and done["trace_id"] == tid
    names = span_names(done["spans"])
    for want in ("sched.queue", "sched.prefill", "sched.decode",
                 "sched.retire", "replica.http"):
        assert want in names, f"{want} missing from {names}"
    # chunked prefill genuinely ran inside the prefill span's window
    prefill = next(s for s in done["spans"]
                   if s["name"] == "sched.prefill")
    assert prefill["attrs"]["prefilled"] == 5
    retire = next(s for s in done["spans"] if s["name"] == "sched.retire")
    assert retire["attrs"]["reason"] == "budget"
    # offsets are relative to request receipt: everything in-window
    assert all(s["off_ms"] >= 0 for s in done["spans"])
    # /debug/trace agrees
    assert d_st == 200
    dbg = json.loads(d_body)
    assert dbg["trace_id"] == tid
    assert set(span_names(done["spans"])) <= set(span_names(dbg["spans"]))
    # Perfetto export is well-formed
    assert c_st == 200
    doc = json.loads(c_body)
    evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert evs and all({"name", "ts", "dur", "pid", "tid"} <= set(e)
                       for e in evs)
    assert m_st == 404


def test_unfronted_server_mints_trace_id(mv):
    """No X-Trace-Id header: the replica mints one and the non-stream
    JSON body carries it plus the span summary."""

    async def main():
        rep = await Rep(mv).start()
        reader, writer = await http_post(
            rep.app.port, "/v1/completions",
            {"prompt": [7, 8, 9], "max_tokens": 4, "stream": False})
        data = await reader.read()
        writer.close()
        await rep.stop()
        head, _, body = data.partition(b"\r\n\r\n")
        return int(head.split(b" ")[1]), json.loads(body)

    status, body = run_async(main())
    assert status == 200
    assert len(body["tokens"]) == 4
    assert len(body["trace_id"]) == 16
    assert "sched.retire" in span_names(body["spans"])


# ----------------------------------------------------------------------
# the acceptance property: mid-stream kill -> ONE stitched trace
# ----------------------------------------------------------------------

def test_failover_produces_single_stitched_trace(mv):
    """Router-fronted request whose replica is killed mid-stream: the
    client sees one gapless stream, and /debug/trace/<id> on the ROUTER
    shows ONE trace whose spans cover router dispatch (the dead attempt
    marked failed), the failover re-dispatch, and BOTH replicas'
    scheduler spans (queue/prefill/decode) re-based onto the router's
    clock — plus the retire event from the finishing replica."""
    prompt, budget = [1, 2, 3], 24

    async def main():
        rep_a = await Rep(mv, step_delay=0.05).start()
        rep_b = await Rep(mv).start()
        router = Router([rep_a.addr], probe_interval_s=0.05,
                        backoff_base_s=0.05, connect_timeout_s=1.0)
        await router.start()
        app = RouterApp(router, port=0)
        await app.start()

        killed = asyncio.Event()

        async def on_token(i):
            if i == 4 and not killed.is_set():
                killed.set()
                router.add_replica(rep_b.addr)
                await router.probe_all()
                await rep_a.kill()

        reader, writer = await http_post(
            app.port, "/v1/completions",
            {"prompt": prompt, "max_tokens": budget})
        assert int((await reader.readline()).split(b" ")[1]) == 200
        while (await reader.readline()).strip():
            pass
        tokens, done = await read_sse(reader, on_token=on_token)
        writer.close()

        tid = done["trace_id"]
        dbg = await http_get(app.port, f"/debug/trace/{tid}")
        await app.stop()
        await router.stop()
        await rep_b.stop()
        return tokens, done, tid, dbg

    tokens, done, tid, (d_st, d_body) = run_async(main())
    # gapless full-budget stream (bit-parity is test_router.py's job)
    assert len(tokens) == done["n_tokens"] == 24
    assert done["failovers"] >= 1
    assert d_st == 200
    dbg = json.loads(d_body)
    assert dbg["trace_id"] == tid
    names = span_names(dbg["spans"])
    # router-side: the request span, >= 2 dispatch attempts (the dead
    # one marked replica_failure, the finisher done), the failover event
    assert "router.request" in names
    dispatches = [s for s in dbg["spans"]
                  if s["name"] == "router.dispatch"]
    assert len(dispatches) >= 2
    outcomes = {s["attrs"]["outcome"] for s in dispatches}
    assert "replica_failure" in outcomes and "done" in outcomes
    assert "router.failover" in names
    # replica-side spans were ingested from BOTH replicas onto this one
    # trace — the failed-over stream reads as one timeline
    replicas_seen = {s["attrs"].get("replica") for s in dbg["spans"]
                     if s["name"] == "replica.http"}
    assert len(replicas_seen) >= 1      # the finisher always reports
    for want in ("sched.queue", "sched.decode", "sched.retire"):
        assert want in names, f"{want} missing from stitched trace"
    # the FINISHING replica's retire is 'budget'; the killed replica may
    # also have left a 'cancelled' retire on the same trace (in-process
    # replicas share the recorder ring) — both belong to this request
    retires = [s["attrs"]["reason"] for s in dbg["spans"]
               if s["name"] == "sched.retire"]
    assert "budget" in retires


# ----------------------------------------------------------------------
# /debug/timeline + build info + /admin/profile
# ----------------------------------------------------------------------

def test_debug_timeline_after_load_burst(mv):
    """A scripted burst of concurrent requests must leave a step-level
    flight record: n_live reaching the burst width, emitted tokens, and
    bounded-ring metadata. The timeline is also dumped under runs/ —
    the artifact tier1.yml uploads from CI."""

    async def main():
        rep = await Rep(mv, n_slots=4).start()
        handles = [rep.sched.submit([i + 1, i + 2, i + 3], 8)
                   for i in range(6)]
        await asyncio.gather(*(h.result() for h in handles))
        status, body = await http_get(rep.app.port,
                                      "/debug/timeline?n=512")
        status2, body2 = await http_get(rep.app.port,
                                        "/debug/timeline?n=2")
        flight = rep.eng.flight
        await rep.stop()
        return status, json.loads(body), status2, json.loads(body2), \
            flight

    status, body, status2, body2, flight = run_async(main())
    assert status == 200
    entries = body["entries"]
    assert entries and body["n_steps"] == flight.total
    for e in entries:
        assert {"t", "step", "step_ms", "n_live", "prefill_tokens",
                "emitted", "blocks_in_use", "preemptions"} <= set(e)
    # the burst genuinely batched: some step decoded >= 2 streams and
    # tokens were emitted across the window
    assert max(e["n_live"] for e in entries) >= 2
    # wave mode samples each request's FIRST token at admission, so the
    # steps account for budget-1 tokens per request
    assert sum(e["emitted"] for e in entries) >= 6 * 7
    assert all(e["step_ms"] > 0 for e in entries)
    # ?n= bounds the payload
    assert status2 == 200 and len(body2["entries"]) == 2
    # persist for the CI artifact upload (runs/**/*.jsonl in tier1.yml)
    path = flight.dump_jsonl(
        os.path.join("runs", "ci_trace_e2e", "timeline.jsonl"))
    assert os.path.getsize(path) > 0


def test_build_info_gauges_on_metrics(mv):
    async def main():
        rep = await Rep(mv, prefill_chunk=16).start()
        router = Router([rep.addr], probe_interval_s=0.05)
        await router.start()
        app = RouterApp(router, port=0)
        await app.start()
        _, rep_metrics = await http_get(rep.app.port, "/metrics")
        _, router_metrics = await http_get(app.port, "/metrics")
        await app.stop()
        await router.stop()
        await rep.stop()
        return rep_metrics, router_metrics

    rep_metrics, router_metrics = run_async(main())
    line = next(ln for ln in rep_metrics.splitlines()
                if ln.startswith("serve_build_info{"))
    assert 'prefill_chunk="16"' in line
    assert 'kv_block="8"' in line
    assert 'cache_dtype="' in line
    assert f'jax="{jax.__version__}"' in line
    assert line.endswith(" 1")
    r_line = next(ln for ln in router_metrics.splitlines()
                  if ln.startswith("router_build_info{"))
    assert 'replicas="1"' in r_line


def test_admin_profile_captures_on_live_replica(mv, tmp_path):
    async def main():
        rep = await Rep(mv).start()
        rep.app.profile_dir = str(tmp_path / "cap")
        # keep the engine busy while the capture window is open
        h = rep.sched.submit([1, 2, 3], 16)
        reader, writer = await http_post(
            rep.app.port, "/admin/profile?duration_ms=50", {})
        data = await reader.read()
        writer.close()
        bad_reader, bad_writer = await http_post(
            rep.app.port, "/admin/profile?duration_ms=0", {})
        bad = await bad_reader.read()
        bad_writer.close()
        await h.result()
        await rep.stop()
        return data, bad

    data, bad = run_async(main())
    head, _, body = data.partition(b"\r\n\r\n")
    assert int(head.split(b" ")[1]) == 200, data
    out = json.loads(body)
    assert out["duration_ms"] == 50
    assert os.path.isdir(out["profile_dir"])
    assert any(files for _, _, files in os.walk(out["profile_dir"])), \
        "capture wrote no profiler artifacts"
    assert int(bad.split(b" ")[1]) == 400
