"""MLA weight-absorption and KV-cache decode parity tests.

The reference guards a train/eval divergence in MLA with a VAL_RUN flag
("HIDDEN IN PLAIN SIGHT: THIS BUG TOOK ~16 HRS TO DEBUG", reference
single-gpu/model.py:195,290). Our design removes the hazard structurally —
the decode path is an algebraically exact rewrite of the materialized path —
and these tests assert that equivalence: full-sequence logits computed with
materialized K/V must match logits computed token-by-token through the
absorbed/static-cache decode path, for every attention flavor.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.config import LLMConfig
from distributed_pytorch_tpu.models import LLM, init_cache

VOCAB, BLOCK = 64, 24


def cfg_for(attn, pos_emb):
    return LLMConfig(vocab_size=VOCAB, block_size=BLOCK, n_embd=32, n_head=4,
                     n_kv_heads=2, n_layer=2, up_dim=48, pos_emb=pos_emb,
                     attn=attn, non_linearity="gelu", dropout=0.0,
                     q_latent_dim=16, kv_latent_dim=16, rope_head_dim=8)


FLAVORS = [
    ("gqa", "rope"), ("gqa", "learn"), ("mha", "sin"), ("mqa", "rope"),
    ("mla", "rope"),   # FullMLA, decoupled rotary, absorbed decode
    ("mla", "learn"),  # NaiveMLA, absorbed decode
]


@pytest.mark.parametrize("attn,pos_emb", FLAVORS)
def test_incremental_decode_matches_full_forward(attn, pos_emb):
    """Feed a T-token prompt one token at a time through the static cache;
    the final-position logits at each step must equal the corresponding
    column of a single full forward pass (fp32, tolerance ~1e-5)."""
    cfg = cfg_for(attn, pos_emb)
    model = LLM(cfg)
    T = 10
    idx = jax.random.randint(jax.random.PRNGKey(1), (2, T), 0, VOCAB)
    tgt = jnp.zeros_like(idx)
    variables = model.init(jax.random.PRNGKey(0), idx, tgt)

    # full forward: logits for every position (targets given)
    full_logits, _, _ = model.apply(variables, idx, tgt)

    # incremental: one token at a time through the cache
    caches = init_cache(cfg, batch_size=2, max_len=BLOCK, dtype=jnp.float32)
    for t in range(T):
        logits_t, _, caches = model.apply(
            variables, idx[:, t:t + 1], caches=caches, pos=t)
        np.testing.assert_allclose(
            np.asarray(logits_t[:, 0]), np.asarray(full_logits[:, t]),
            atol=2e-5, rtol=2e-5,
            err_msg=f"decode mismatch at position {t} for {attn}/{pos_emb}")


@pytest.mark.parametrize("attn,pos_emb", [("gqa", "rope"), ("mla", "rope"),
                                          ("mla", "learn")])
def test_prompt_then_single_steps(attn, pos_emb):
    """Prefill an 6-token prompt in ONE call, then decode two more tokens
    singly; must match the full forward over all 8 tokens."""
    cfg = cfg_for(attn, pos_emb)
    model = LLM(cfg)
    idx = jax.random.randint(jax.random.PRNGKey(3), (1, 8), 0, VOCAB)
    tgt = jnp.zeros_like(idx)
    variables = model.init(jax.random.PRNGKey(0), idx, tgt)
    full_logits, _, _ = model.apply(variables, idx, tgt)

    caches = init_cache(cfg, batch_size=1, max_len=BLOCK, dtype=jnp.float32)
    # prefill (logits returned for last position only, reference model.py:694)
    logits_p, _, caches = model.apply(variables, idx[:, :6], caches=caches, pos=0)
    np.testing.assert_allclose(np.asarray(logits_p[:, -1]),
                               np.asarray(full_logits[:, 5]), atol=2e-5, rtol=2e-5)
    for t in (6, 7):
        logits_t, _, caches = model.apply(
            variables, idx[:, t:t + 1], caches=caches, pos=t)
        np.testing.assert_allclose(np.asarray(logits_t[:, 0]),
                                   np.asarray(full_logits[:, t]),
                                   atol=2e-5, rtol=2e-5)


def test_mla_latent_cache_is_compressed():
    """The MLA cache must store the kv_latent_dim-compressed c_kv, not
    per-head K/V (reference :204-211 — the point of MLA)."""
    cfg = cfg_for("mla", "rope")
    caches = init_cache(cfg, batch_size=2, max_len=BLOCK)
    assert set(caches[0].keys()) == {"c_kv", "k_r"}
    assert caches[0]["c_kv"].shape == (2, BLOCK, cfg.kv_latent_dim)
    assert caches[0]["k_r"].shape == (2, BLOCK, 1, cfg.rope_head_dim)
    cfg_n = cfg_for("mla", "learn")
    caches_n = init_cache(cfg_n, batch_size=2, max_len=BLOCK)
    assert set(caches_n[0].keys()) == {"c_kv"}
