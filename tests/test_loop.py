"""End-to-end `train()` loop contracts: checkpoint-resume continues the
exact uninterrupted run (data stream included), eval cadence cannot perturb
training, and run stats are persisted. (Round-1 verdict weak #4/#6 and
missing #5 — capabilities the loader/trainer had but never wired.)"""

import json
import os

import jax
import numpy as np
import pytest

from distributed_pytorch_tpu.config import LLMConfig, TrainConfig
from distributed_pytorch_tpu.train.loop import train

TINY = dict(vocab_size=256, block_size=32, n_embd=32, n_head=4,
            n_kv_heads=4, n_layer=2, up_dim=64)


def _tc(**kw):
    base = dict(dataset="synthetic", data_dir="bench_data",
                total_batch_size=2 * 2 * 32, batch_size=2,
                max_iters=5, parallelism="single", eval=False,
                log_interval=100, save_stats=False, learning_rate=1e-3,
                warmup_steps=2)
    base.update(kw)
    return TrainConfig(**base)


def _params(stats):
    return jax.device_get(stats["state"].params)


def _assert_tree_equal(a, b, atol=0.0):
    fa = jax.tree_util.tree_leaves(a)
    fb = jax.tree_util.tree_leaves(b)
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=atol)


@pytest.fixture()
def in_tmp(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    return tmp_path


def test_resume_matches_uninterrupted(in_tmp):
    """Resume from a mid-run interval checkpoint and land bit-for-bit on the
    uninterrupted run — proves the loader fast-forwards (round-1: a resumed
    run re-sampled the data stream from step 0). Both legs use the same
    max_iters so the cosine-LR horizon is identical; the first leg's
    ckpt_interval save plays the role of the interruption point."""
    mc = LLMConfig(**TINY)
    quiet = lambda s: None

    full = train(mc, _tc(max_iters=6, file_name="full"), log=quiet)

    # leaves exactly one mid-run checkpoint (at it=4 -> state.step 5)
    train(mc, _tc(max_iters=6, file_name="resumed", ckpt_interval=4),
          log=quiet)
    resumed = train(mc, _tc(max_iters=6, file_name="resumed", resume=True),
                    log=quiet)

    n = len(resumed["train_losses"])
    assert 0 < n < len(full["train_losses"])  # actually resumed mid-run
    assert full["train_losses"][-n:] == resumed["train_losses"]
    _assert_tree_equal(_params(full), _params(resumed))


def test_snapshot_per_leaf_reuse_and_metric(in_tmp):
    """Double-buffered async-save snapshot (train/checkpoint.py): repeated
    interval saves reuse the previous snapshot's buffers per leaf, record
    `ckpt_snapshot_ms`, and the persisted checkpoints stay correct (the
    donation-race copy semantics are preserved)."""
    from distributed_pytorch_tpu.train import checkpoint as ckpt

    mc = LLMConfig(**TINY)
    stats = train(mc, _tc(max_iters=6, file_name="snaprun",
                          ckpt_interval=2, save_stats=True),
                  log=lambda s: None)
    # three interval saves (it=2,4,6) -> three measured snapshot copies
    assert len(stats["ckpt_snapshot_ms"]) == 3
    assert all(ms >= 0.0 for ms in stats["ckpt_snapshot_ms"])
    assert abs(ckpt.last_snapshot_ms - stats["ckpt_snapshot_ms"][-1]) < 0.01
    # the stats json carries the metric too
    with open(os.path.join("checkpoints", "snaprun", "stats.json")) as f:
        assert "ckpt_snapshot_ms" in json.load(f)
    # the newest interval checkpoint restores to the final state: the
    # snapshot decoupled the saved buffers from the donated live state
    last = ckpt.latest_step_dir(os.path.join("checkpoints", "snaprun"))
    abstract = jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), stats["state"])
    restored = ckpt.restore_checkpoint(last, abstract)
    assert int(jax.device_get(restored.step)) == \
        int(jax.device_get(stats["state"].step))


def test_eval_cadence_does_not_perturb_training(in_tmp):
    """The training batch sequence (and thus final params) must be invariant
    to eval on/off — eval has its own loaders and step keys."""
    mc = LLMConfig(**TINY)
    quiet = lambda s: None
    off = train(mc, _tc(file_name="ev_off"), log=quiet)
    on = train(mc, _tc(file_name="ev_on", eval=True, eval_interval=2,
                       eval_iters=2), log=quiet)
    assert off["train_losses"] == on["train_losses"]
    _assert_tree_equal(_params(off), _params(on))


def test_stats_json_roundtrip(in_tmp):
    """stats.json (the reference's `<name>_stats.pt`) persists loss curves,
    throughput, param counts, and both configs — and loads back."""
    mc = LLMConfig(**TINY)
    stats = train(mc, _tc(file_name="statrun", save_stats=True, eval=True,
                          eval_interval=2, eval_iters=1),
                  log=lambda s: None)
    path = os.path.join("checkpoints", "statrun", "stats.json")
    assert os.path.exists(path)
    with open(path) as f:
        rec = json.load(f)
    assert rec["train_losses"] == stats["train_losses"]
    assert rec["val_losses"] == [list(p) for p in stats["val_losses"]] or \
        rec["val_losses"] == stats["val_losses"]
    assert rec["params_total"] > rec["params_active"] * 0  # present + ints
    assert rec["model_config"]["n_embd"] == TINY["n_embd"]
    assert rec["train_config"]["file_name"] == "statrun"
    assert len(rec["step_times"]) == len(stats["step_times"])


# ---------------------------------------------------------------------------
# Multi-host bring-up gate (round-3 VERDICT #2): every announcement style a
# real deployment uses must trigger initialize; a plain single-host run must
# not. `initialize` is mocked — these tests never touch a backend.
# ---------------------------------------------------------------------------

from distributed_pytorch_tpu.train.loop import (maybe_initialize_distributed,
                                                multihost_env_detected)


@pytest.mark.parametrize("env,expected", [
    ({}, False),                                             # plain laptop
    ({"JAX_COORDINATOR_ADDRESS": "10.0.0.2:8476"}, True),    # explicit env
    ({"JAX_NUM_PROCESSES": "4"}, True),
    ({"JAX_NUM_PROCESSES": "1"}, False),                 # semantically single
    ({"JAX_NUM_PROCESSES": "auto"}, True),               # malformed: fail loud
    ({"TPU_WORKER_HOSTNAMES": "t0,t1,t2,t3"}, True),         # Cloud TPU pod
    ({"TPU_WORKER_HOSTNAMES": "t0"}, False),                 # single-host slice
    ({"TPU_WORKER_HOSTNAMES": ""}, False),
    ({"MEGASCALE_COORDINATOR_ADDRESS": "head:8080"}, True),  # multislice
])
def test_multihost_env_detection(env, expected):
    assert multihost_env_detected(env) is expected


def test_initialize_called_on_pod_env(monkeypatch):
    from distributed_pytorch_tpu import compat
    calls = []
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "t0,t1")
    monkeypatch.setattr(compat, "distributed_is_initialized", lambda: False)
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda *a, **k: calls.append(1))
    maybe_initialize_distributed()
    assert calls == [1]


def test_initialize_skipped_when_already_up(monkeypatch):
    from distributed_pytorch_tpu import compat
    calls = []
    monkeypatch.setenv("JAX_NUM_PROCESSES", "2")
    monkeypatch.setattr(compat, "distributed_is_initialized", lambda: True)
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda *a, **k: calls.append(1))
    maybe_initialize_distributed()
    assert calls == []


def test_initialize_not_called_single_host(monkeypatch):
    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                "TPU_WORKER_HOSTNAMES", "MEGASCALE_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda *a, **k: (_ for _ in ()).throw(
                            AssertionError("must not initialize")))
    maybe_initialize_distributed()


def test_initialize_failure_is_fatal(monkeypatch):
    """A detected multi-process env with a failing initialize must abort,
    not silently train disconnected (the reference's torchrun likewise
    rendezvouses or dies, multi-gpu/ddp/train.py:19-25)."""
    from distributed_pytorch_tpu import compat
    monkeypatch.setenv("JAX_NUM_PROCESSES", "2")
    monkeypatch.setenv("JAX_PROCESS_ID", "not-an-int")
    monkeypatch.setattr(compat, "distributed_is_initialized", lambda: False)
    with pytest.raises(RuntimeError, match="disconnected"):
        maybe_initialize_distributed()


def test_sigterm_checkpoints_and_resume(in_tmp):
    """Preemption safety (SURVEY §5 failure-handling gap): SIGTERM mid-run
    checkpoints at the next boundary and exits cleanly; --resume continues
    and lands on the uninterrupted run's trajectory."""
    import os
    import signal
    import threading

    mc = LLMConfig(**TINY)
    quiet = lambda s: None

    full = train(mc, _tc(max_iters=8, file_name="sigfull"), log=quiet)

    # send ourselves SIGTERM from the first in-loop log line: by then the
    # handler is guaranteed installed (no race with state creation), and
    # the loop must defer action to the next boundary
    fired = []

    def log_and_kill(s):
        if "iter" in s and not fired:
            fired.append(1)
            os.kill(os.getpid(), signal.SIGTERM)

    interrupted = train(mc, _tc(max_iters=8, file_name="sigrun",
                                log_interval=1),
                        log=log_and_kill)
    assert fired, "training produced no log line to trigger from"
    n_done = len(interrupted["train_losses"])
    assert n_done < 9, "SIGTERM did not stop the run early"
    import glob
    assert glob.glob(os.path.join("checkpoints", "sigrun", "step_*")), \
        "no checkpoint written on SIGTERM"

    resumed = train(mc, _tc(max_iters=8, file_name="sigrun", resume=True),
                    log=quiet)
    assert resumed["train_losses"] == \
        full["train_losses"][-len(resumed["train_losses"]):]
    _assert_tree_equal(_params(full), _params(resumed))
