"""Data pipeline tests: prepare scripts -> .bin -> DataLoader round trip
(the reference has no tests for its ETL; SURVEY.md §4)."""

import os

import numpy as np
import pytest

from distributed_pytorch_tpu.data.loader import DataLoader, make_synthetic_bin
from distributed_pytorch_tpu.data import prepare_shakespeare, prepare_tinystories
from distributed_pytorch_tpu.data.prepare import get_tokenizer


CORPUS = "\n\n".join(
    f"Once upon a time there was a number {i}. It liked to count. The end."
    for i in range(200))


@pytest.fixture
def corpus_file(tmp_path):
    p = tmp_path / "corpus.txt"
    p.write_text(CORPUS, encoding="utf-8")
    return str(p)


def test_prepare_shakespeare_local(tmp_path, corpus_file):
    out = str(tmp_path / "shakespeare")
    prepare_shakespeare.main(["--out_dir", out, "--input", corpus_file])
    train = np.fromfile(os.path.join(out, "train.bin"), dtype=np.uint16)
    val = np.fromfile(os.path.join(out, "val.bin"), dtype=np.uint16)
    assert train.size > 0 and val.size > 0
    # 90/10 contiguous split (reference prepare.py:21-23)
    assert abs(train.size / (train.size + val.size) - 0.9) < 0.01


def test_prepare_tinystories_local(tmp_path, corpus_file):
    out = str(tmp_path / "tinystories")
    prepare_tinystories.main(["--out_dir", out, "--input", corpus_file])
    train = np.fromfile(os.path.join(out, "train.bin"), dtype=np.uint16)
    val = np.fromfile(os.path.join(out, "val.bin"), dtype=np.uint16)
    assert train.size > 0 and val.size > 0
    _, eot, _ = get_tokenizer()
    # every story is EOT-terminated (reference prepare.py:36)
    assert train[-1] == eot and val[-1] == eot


def test_prepared_bin_feeds_loader(tmp_path, corpus_file):
    out = str(tmp_path / "ts")
    prepare_tinystories.main(["--out_dir", out, "--input", corpus_file])
    loader = DataLoader(os.path.join(out, "train.bin"), batch_size=2,
                        block_size=16, grad_accum=2)
    x, y = loader.next_batch()
    assert x.shape == (2, 2, 16) and y.shape == (2, 2, 16)
    assert (np.asarray(x[:, :, 1:]) == np.asarray(y[:, :, :-1])).all()


def test_loader_deterministic_across_process_counts(tmp_path):
    """The counter-based RNG must give the same global batch regardless of
    who samples it (resharding-stable, unlike the reference's +rank seed
    offset, multi-gpu/ddp/train.py:28-29)."""
    path = make_synthetic_bin(str(tmp_path / "det_test.bin"),
                              n_tokens=2 ** 14)
    a = DataLoader(path, 4, 32, grad_accum=2, seed=7)
    b = DataLoader(path, 4, 32, grad_accum=2, seed=7)
    xa, ya = a.next_batch()
    xb, yb = b.next_batch()
    assert (np.asarray(xa) == np.asarray(xb)).all()


def test_prepare_fineweb_local(tmp_path, corpus_file):
    """fineweb prepare (the dataset the reference declares but never ships,
    single-gpu/train.sh:6): streaming writer produces loader-compatible
    bins with a deterministic 1% doc holdout."""
    from distributed_pytorch_tpu.data import prepare_fineweb
    out = str(tmp_path / "fineweb")
    prepare_fineweb.main(["--out_dir", out, "--input", corpus_file,
                          "--limit", "150"])
    train = np.fromfile(os.path.join(out, "train.bin"), dtype=np.uint16)
    val = np.fromfile(os.path.join(out, "val.bin"), dtype=np.uint16)
    assert train.size > 0 and val.size > 0
    _, eot, _ = get_tokenizer()
    assert train[-1] == eot and val[-1] == eot
    # docs 0 and 100 of the 150 -> exactly 2 val documents (2 EOTs)
    assert int((val == eot).sum()) == 2
    # and no leftover .part files (atomic promote)
    assert not [f for f in os.listdir(out) if ".part" in f]
    loader = DataLoader(os.path.join(out, "train.bin"), batch_size=2,
                        block_size=16, grad_accum=1)
    x, y = loader.next_batch()
    assert x.shape == (1, 2, 16)
