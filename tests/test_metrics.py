"""MFU / FLOPs accounting tests (train/metrics.py): the honesty of the
headline benchmark number rests on these formulas — MoE counts only active
experts, remat policies add exactly their recompute, and the per-token
matmul census matches a hand count."""

from distributed_pytorch_tpu.config import LLMConfig, flagship_gpt124m
from distributed_pytorch_tpu.train import metrics as M


def test_dense_matmul_census_hand_count():
    cfg = LLMConfig(vocab_size=100, block_size=32, n_embd=8, n_head=2,
                    n_kv_heads=2, n_layer=1, up_dim=16,
                    non_linearity="relu", pos_emb="learn", attn="mha")
    C, up, V = 8, 16, 100
    attn = C * (C + 2 * 2 * 4) + C * C      # fused qkv + out proj
    ffn = C * up + up * C                   # relu: single up projection
    expected = attn + ffn + V * C           # + tied lm head
    assert M.matmul_params_per_token(cfg) == expected


def test_swiglu_doubles_up_projection():
    base = dict(vocab_size=100, block_size=32, n_embd=8, n_head=2,
                n_kv_heads=2, n_layer=1, up_dim=16, pos_emb="learn",
                attn="mha")
    relu = M.matmul_params_per_token(LLMConfig(**base, non_linearity="relu"))
    swiglu = M.matmul_params_per_token(
        LLMConfig(**base, non_linearity="swiglu"))
    assert swiglu - relu == 8 * 16          # one extra (C, up) gate matrix


def test_moe_counts_only_active_experts():
    base = dict(vocab_size=100, block_size=32, n_embd=8, n_head=2,
                n_kv_heads=2, n_layer=1, up_dim=16, non_linearity="relu",
                pos_emb="learn", attn="mha")
    dense = M.matmul_params_per_token(LLMConfig(**base))
    moe = M.matmul_params_per_token(LLMConfig(
        **base, moe=True, n_exp=8, n_shared=1, n_act=3))
    one_mlp = 8 * 16 + 16 * 8
    router = 8 * 7                           # C x n_routed
    # 1 shared + 2 active routed = 3 MLPs vs the dense model's 1
    assert moe - dense == 2 * one_mlp + router


def test_remat_policy_flops():
    base = dict(vocab_size=100, block_size=32, n_embd=8, n_head=2,
                n_kv_heads=2, n_layer=2, up_dim=16, non_linearity="relu",
                pos_emb="learn", attn="mha")
    plain = M.step_flops(LLMConfig(**base), tokens_per_step=64, seq_len=32)
    block = M.step_flops(LLMConfig(**base, act_recomp=True,
                                   act_recomp_policy="block"),
                         tokens_per_step=64, seq_len=32)
    attn = M.step_flops(LLMConfig(**base, act_recomp=True,
                                  act_recomp_policy="attn"),
                        tokens_per_step=64, seq_len=32)
    # block remat re-runs the whole forward: 4/3 of the plain 3x-forward
    assert abs(block / plain - 4 / 3) < 1e-9
    # attention-only remat re-runs strictly less than the whole forward
    assert plain < attn < block


def test_flagship_flops_order_of_magnitude():
    """GPT-124M at 16384 tokens/step: ~6*N*tokens = ~1.2e13 FLOPs. The MFU
    denominator being off by 2x either way would misstate the headline."""
    cfg = flagship_gpt124m()
    flops = M.step_flops(cfg, tokens_per_step=16384, seq_len=1024)
    n_params = M.matmul_params_per_token(cfg)
    assert 110e6 < n_params < 135e6         # a true ~124M matmul census
    assert 0.9e13 < flops < 1.5e13