"""Config-ladder tests (BASELINE.json rungs: 350M zero1/2, 774M-1.5B
fsdp): every preset builds (shape-only, jax.eval_shape — no 1.5B compile
in tier-1), the static HBM planner returns an arithmetically-consistent
plan for its target recipe, and the --dryrun CLI path prints the plan.
The full 350M 2-step run on the CPU mesh is `slow` (XLA:CPU compile of a
24-layer model dominates tier-1's budget)."""

import jax
import numpy as np
import pytest

from distributed_pytorch_tpu.config import (PRESETS, TrainConfig, gpt2_350m,
                                            gpt2_774m, gpt2_1p5b)
from distributed_pytorch_tpu.train import memplan

# preset -> (param-count window, BASELINE ladder target recipe)
LADDER = {
    "gpt2_350m": ((330e6, 370e6), "zero2"),
    "gpt2_774m": ((740e6, 800e6), "fsdp"),
    "gpt2_1p5b": ((1.45e9, 1.65e9), "fsdp"),
}


@pytest.mark.parametrize("name", sorted(LADDER))
def test_preset_builds_and_param_count(name):
    cfg = PRESETS[name]()
    lo, hi = LADDER[name][0]
    n = memplan.param_count(cfg)  # eval_shape of the real init: shape-only
    assert lo < n < hi, f"{name}: {n / 1e6:.1f}M params outside window"
    # overrides pass through like flagship_gpt124m's
    assert PRESETS[name](n_layer=2).n_layer == 2


def test_preset_factories_exported():
    assert PRESETS["gpt2_350m"] is gpt2_350m
    assert PRESETS["gpt2_774m"] is gpt2_774m
    assert PRESETS["gpt2_1p5b"] is gpt2_1p5b


@pytest.mark.parametrize("name", sorted(LADDER))
def test_hbm_planner_returns_consistent_plan(name):
    """The plan's grad-accum arithmetic must satisfy the trainer's
    divisibility contract (train/loop.py) and the breakdown must reflect
    the recipe's sharding (ZeRO-3 divides params by dp, zero1/2 don't)."""
    cfg = PRESETS[name]()
    recipe = LADDER[name][1]
    tc = TrainConfig(total_batch_size=2 ** 19, parallelism=recipe)
    plan = memplan.plan_memory(cfg, tc, n_devices=8, hbm_gb=16.0,
                               preset_name=name)
    assert plan.micro_batch >= 1
    assert plan.grad_accum * plan.micro_batch * 8 * cfg.block_size \
        == tc.total_batch_size
    assert plan.est_peak_gb > 0 and plan.breakdown_gb["params"] > 0
    assert "micro_batch" not in plan.summary() or plan.summary()
    if recipe == "fsdp":
        # ZeRO-3: fp32 param shard per device = P*4/dp
        expect = memplan.param_count(cfg) * 4 / 8 / 2 ** 30
        np.testing.assert_allclose(plan.breakdown_gb["params"], expect,
                                   rtol=0.01)


def test_planner_prefers_no_remat_when_it_fits():
    """With a huge budget the planner must not pay remat FLOPs."""
    cfg = PRESETS["gpt2_350m"]()
    tc = TrainConfig(total_batch_size=2 ** 19, parallelism="fsdp")
    plan = memplan.plan_memory(cfg, tc, n_devices=8, hbm_gb=10000.0)
    assert not plan.act_recomp
    assert plan.micro_batch == 64  # largest candidate


def test_planner_honest_when_nothing_fits():
    cfg = PRESETS["gpt2_1p5b"]()
    tc = TrainConfig(total_batch_size=2 ** 19, parallelism="single")
    plan = memplan.plan_memory(cfg, tc, n_devices=1, hbm_gb=16.0)
    assert not plan.fits  # 1.5B fp32 + AdamW on one 16G chip: impossible


@pytest.mark.parametrize("preset,recipe", [("gpt2_350m", "zero2"),
                                           ("gpt2_774m", "fsdp")])
def test_dryrun_cli_prints_plan(capsys, preset, recipe):
    """Acceptance: `python -m distributed_pytorch_tpu --dryrun` for
    350M/zero2 and 774M/fsdp on the CPU mesh prints the HBM plan."""
    from distributed_pytorch_tpu.__main__ import main
    main(["--preset", preset, "--parallelism", recipe, "--dryrun",
          "--total_batch_size_str", "2**19"])
    out = capsys.readouterr().out
    assert "[hbm plan]" in out and f"{preset}/{recipe}" in out
    assert "micro_batch=" in out and "remat=" in out
    assert "est peak" in out


def test_dryrun_preset_flag_overridable(capsys):
    """Explicit flags must override preset fields (the reference's
    flag-routing contract extends to presets)."""
    from distributed_pytorch_tpu.__main__ import main
    main(["--preset", "gpt2_350m", "--n_layer", "2", "--parallelism",
          "zero2", "--dryrun", "--total_batch_size_str", "2**19"])
    out = capsys.readouterr().out
    assert "[hbm plan]" in out


def test_hbm_planner_accounts_moe():
    """MoE configs (round 7): stacked (E, ...) expert leaves divide by the
    'expert' mesh axis on top of the recipe's data sharding, and the
    dispatch buffers appear as their own breakdown term — so a --dryrun
    MoE plan is honest about both."""
    from distributed_pytorch_tpu.config import flagship_gpt124m

    cfg = flagship_gpt124m(moe=True, n_exp=8, n_shared=1, n_act=3,
                           up_dim=1024, moe_impl="grouped")
    n = memplan.param_count(cfg)

    est1, b1 = memplan.estimate_peak_gb(cfg, "fsdp", 8, "none", dp=4,
                                        ep=1, n_params=n)
    est2, b2 = memplan.estimate_peak_gb(cfg, "fsdp", 8, "none", dp=4,
                                        ep=2, n_params=n)
    assert "moe_dispatch" in b1 and b1["moe_dispatch"] > 0
    # ep=2 halves the expert share of params/opt/grads; dense params and
    # the grouped dispatch buffer (static worst case) don't shrink
    assert b2["params"] < b1["params"]
    assert b2["opt"] < b1["opt"]
    assert b2["moe_dispatch"] == b1["moe_dispatch"]
    e_params = memplan._expert_param_count(cfg)
    expect = ((n - e_params) / 4 + e_params / 8) * 4 / 2 ** 30
    np.testing.assert_allclose(b2["params"], expect, rtol=0.01)

    # scatter's capacity padding shows up bigger than grouped's packed
    # buffer at the same cf=2 defaults (2x rows vs k+shared packed rows),
    # and scatter's buffers DO shrink with ep
    import dataclasses as _dc
    cfg_s = _dc.replace(cfg, moe_impl="scatter")
    _, bs1 = memplan.estimate_peak_gb(cfg_s, "fsdp", 8, "none", dp=4,
                                      ep=1, n_params=n)
    _, bs2 = memplan.estimate_peak_gb(cfg_s, "fsdp", 8, "none", dp=4,
                                      ep=2, n_params=n)
    assert bs2["moe_dispatch"] < bs1["moe_dispatch"]


def test_hbm_planner_moe_plan_memory_uses_expert_axis():
    """plan_memory must thread the resolved 'expert' axis size through
    (ep composes with any recipe, parallel/mesh.resolve_plan)."""
    from distributed_pytorch_tpu.config import flagship_gpt124m

    cfg = flagship_gpt124m(moe=True, n_exp=8, n_shared=1, n_act=3,
                           up_dim=1024, moe_impl="grouped")
    tc2 = TrainConfig(total_batch_size=2 ** 19, parallelism="fsdp",
                      ep_size=2)
    p2 = memplan.plan_memory(cfg, tc2, n_devices=8, hbm_gb=16.0)
    assert "moe_dispatch" in p2.breakdown_gb
    # the chosen plan's breakdown must equal a direct estimate at the
    # RESOLVED axes — fsdp over 8 devices with ep_size=2 is dp=4 x ep=2
    n = memplan.param_count(cfg)
    policy = p2.act_recomp_policy if p2.act_recomp else "none"
    _, expect = memplan.estimate_peak_gb(cfg, "fsdp", p2.micro_batch,
                                         policy, dp=4, ep=2, n_params=n)
    assert p2.breakdown_gb == expect
    # and it must differ from an ep-ignorant estimate (ep=1 at dp=4)
    _, wrong = memplan.estimate_peak_gb(cfg, "fsdp", p2.micro_batch,
                                        policy, dp=4, ep=1, n_params=n)
    assert p2.breakdown_gb["params"] < wrong["params"]


def test_hbm_planner_prices_pipe_and_tp_axes():
    """Round 23: the 7B rung prices pp/tp honestly — each pipeline stage
    holds n_layer/pipe of the block params (plus the embedding on the
    worst stage), TP column/row-splits the matmul weights — and
    plan_memory threads the RESOLVED pipe/model axes through, so
    `memplan --recipe pp --pp-size 8` stops pricing 6.7B params
    unsharded on every chip."""
    cfg = PRESETS["gpt2_7b"]()
    n = memplan.param_count(cfg)
    emb = cfg.vocab_size * cfg.n_embd

    _, b1 = memplan.estimate_peak_gb(cfg, "pp", 1, "block", dp=2,
                                     n_params=n)
    _, b8 = memplan.estimate_peak_gb(cfg, "pp", 1, "block", dp=2,
                                     n_params=n, pipe=8)
    expect = ((n - emb) / 8 + emb) * 4 / 2 ** 30
    np.testing.assert_allclose(b8["params"], expect, rtol=0.01)
    assert b8["grads"] < b1["grads"]  # stage accumulators shrink too
    assert b8["acts"] == b1["acts"]   # 1F1B in-flight depth cancels layers

    # plan_memory resolves pipe from TrainConfig.pp_size (mesh.resolve_plan)
    tc = TrainConfig(total_batch_size=2 ** 19, parallelism="pp", pp_size=8)
    plan = memplan.plan_memory(cfg, tc, n_devices=16, hbm_gb=16.0,
                               offload=True)
    assert plan.fits  # the pod-rung pp row of scripts/train_pod.sh
    np.testing.assert_allclose(plan.breakdown_gb["params"], expect,
                               rtol=0.01)

    # fsdp_tp at the real tp axis: matmul weights divide by dp*tp
    tc_tp = TrainConfig(total_batch_size=2 ** 19, parallelism="fsdp_tp",
                        tp_size=4)
    p_tp = memplan.plan_memory(cfg, tc_tp, n_devices=16, hbm_gb=16.0,
                               offload=True)
    assert p_tp.fits
    expect_tp = ((n - emb) / 4 + emb) * 4 / 4 / 2 ** 30  # /tp then /dp
    np.testing.assert_allclose(p_tp.breakdown_gb["params"], expect_tp,
                               rtol=0.01)


@pytest.mark.slow
@pytest.mark.parametrize("preset,recipe", [("gpt2_350m", "zero2")])
def test_ladder_350m_two_steps_cpu_mesh(preset, recipe):
    """The 350M preset's transformer body (the full 24 x 1024 stack,
    ~300M of the rung's params) takes 2 optimizer steps on the 8-device
    CPU mesh under its target recipe. vocab/block are shrunk (8192/64) —
    XLA:CPU cannot compile the 50k-vocab lm-head in a test budget; the
    full-size rung is exercised by `--dryrun` (above) off-hardware and by
    the bench/sweep ladder legs on TPU."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from distributed_pytorch_tpu.parallel import sharding as shd
    from distributed_pytorch_tpu.parallel.mesh import build_mesh, resolve_plan
    from distributed_pytorch_tpu.train.state import create_train_state
    from distributed_pytorch_tpu.train.step import make_train_step

    mc = PRESETS[preset](block_size=64, vocab_size=8192)
    tc = TrainConfig(total_batch_size=8 * 64, batch_size=1,
                     parallelism=recipe)
    mesh = build_mesh(resolve_plan(recipe, 8))
    model, tx, state, sh = create_train_state(mc, tc, mesh)
    step = make_train_step(model, tx, mc, tc, mesh, sh)
    x = jax.random.randint(jax.random.PRNGKey(0), (1, 8, 64), 0,
                           mc.vocab_size, jnp.int32)
    bsh = NamedSharding(mesh, shd.batch_pspec(recipe, mesh,
                                              leading_accum=True))
    x = jax.device_put(x, bsh)
    losses = []
    for _ in range(2):
        state, m = step(state, x, x)
        losses.append(float(m["loss"]))
    assert all(np.isfinite(l) for l in losses)
