"""DecodeEngine (engine/decode.py): continuous batching over the slot
cache. Greedy engine output must be bit-identical to the one-shot
`generate` path per prompt regardless of admission/retirement order; the
fused step must trace exactly once across a ragged run; prefill traces are
bounded by the power-of-two buckets; and the whole thing runs under a tp
CPU mesh with a sharded cache."""

import jax
import jax.numpy as jnp
import pytest

from distributed_pytorch_tpu.config import LLMConfig
from distributed_pytorch_tpu.engine import DecodeEngine
from distributed_pytorch_tpu.models.generate import generate
from distributed_pytorch_tpu.models.gpt import LLM


def tiny_cfg(**kw):
    base = dict(vocab_size=97, block_size=64, n_embd=48, n_head=4,
                n_kv_heads=2, attn="gqa", n_layer=2, up_dim=64,
                non_linearity="swiglu", pos_emb="rope", dropout=0.0,
                q_latent_dim=16, kv_latent_dim=16, rope_head_dim=8)
    base.update(kw)
    return LLMConfig(**base)


def build(cfg, seed=0, attn_impl="naive"):
    model = LLM(cfg, attn_impl=attn_impl)
    rng = jax.random.PRNGKey(seed)
    x = jnp.zeros((1, cfg.block_size), jnp.int32)
    variables = model.init({"params": rng, "dropout": rng}, x, x)
    return model, {k: v for k, v in variables.items()}


PROMPTS = [[1, 2, 3], [5, 6, 7, 8, 9, 10, 11], [20] * 17, [42, 43], [9]]


@pytest.mark.parametrize("kw", [
    dict(attn="gqa", n_kv_heads=2, pos_emb="rope"),
    dict(attn="mla", pos_emb="rope"),
    dict(attn="mha", pos_emb="learn"),
], ids=["gqa-rope", "mla-rope", "mha-learn"])
def test_engine_matches_generate_greedy(kw):
    """Ragged continuous batching (5 prompts through 2 slots) is
    token-identical to decoding each prompt alone — slot reuse, pad rows,
    and neighbors at other positions must be invisible."""
    cfg = tiny_cfg(**kw)
    model, variables = build(cfg)
    eng = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                       min_bucket=8)
    outs = eng.run(PROMPTS, max_new_tokens=6)
    for p, o in zip(PROMPTS, outs):
        ref = generate(model, variables, jnp.asarray(p, jnp.int32)[None], 6,
                       temperature=0.0)[0].tolist()
        assert o == ref, f"engine diverged from generate for prompt {p}"


def test_single_step_trace_and_bucketed_prefill():
    """One compiled step function serves the whole ragged run (no
    per-admission retrace); prefill compiles once per power-of-two
    bucket."""
    cfg = tiny_cfg()
    model, variables = build(cfg)
    eng = DecodeEngine(model, variables, n_slots=3, temperature=0.0,
                       min_bucket=8)
    eng.run(PROMPTS, max_new_tokens=5)
    assert eng.step_traces == 1
    # prompt lens 3,7,17,2,1 -> buckets {8, 32}; each traced exactly once
    assert eng.admit_traces == {8: 3, 32: 1} or \
        set(eng.admit_traces.values()) == {1} and \
        set(eng.admit_traces) == {8, 32}
    # second run with the same buckets: zero new traces
    eng2_out = eng.run([[3, 1], [4, 1, 5, 9, 2, 6]], max_new_tokens=4)
    assert eng.step_traces == 1
    assert set(eng.admit_traces) == {8, 32}
    assert len(eng2_out) == 2


def test_engine_moe():
    cfg = tiny_cfg(moe=True, n_exp=4, n_shared=1, n_act=2, aux_free=True)
    model, variables = build(cfg)
    eng = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                       min_bucket=8)
    outs = eng.run(PROMPTS[:3], max_new_tokens=4)
    for p, o in zip(PROMPTS[:3], outs):
        ref = generate(model, variables, jnp.asarray(p, jnp.int32)[None], 4,
                       temperature=0.0)[0].tolist()
        assert o == ref


def test_eos_and_budget_retirement():
    """A sequence retires on EOS, the rest run to their budget; retired
    slots are reusable immediately."""
    cfg = tiny_cfg()
    model, variables = build(cfg)
    # discover the greedy continuation, then use its first generated token
    # as the 'EOS' id for one prompt
    ref = generate(model, variables, jnp.asarray([[1, 2, 3]], jnp.int32), 5,
                   temperature=0.0)[0].tolist()
    eos = ref[3]
    eng = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                       eos_id=eos, min_bucket=8)
    outs = eng.run([[1, 2, 3], [5, 6, 7, 8]], max_new_tokens=5)
    assert outs[0] == ref[:4]          # stopped at the EOS token
    assert len(outs[1]) in (4 + 5, 9)  # full budget unless EOS hit
    assert eng.free_slots == [0, 1]


def test_cache_full_retires_before_wrap():
    """A slot whose next write would wrap the ring retires instead of
    silently entering sliding-window territory."""
    cfg = tiny_cfg(block_size=16)
    model, variables = build(cfg)
    eng = DecodeEngine(model, variables, n_slots=1, temperature=0.0,
                       min_bucket=8)
    out = eng.run([[1, 2, 3, 4, 5]], max_new_tokens=1000)
    # every cache row fills (the final sampled token needs no row):
    # 5 prompt + 11 written + 1 unwritten = max_len + 1 tokens
    assert len(out[0]) == cfg.block_size + 1


def test_engine_tp_mesh_sharded_cache():
    """The engine decodes under a tensor-parallel CPU mesh: params laid
    out by the tp recipe tables, cache kv-head axis sharded over 'model',
    and greedy outputs identical to the unsharded engine."""
    from distributed_pytorch_tpu.parallel.mesh import mesh_for

    if len(jax.devices()) < 2:
        pytest.skip("needs multi-device CPU platform")
    cfg = tiny_cfg(attn="gqa", n_kv_heads=2, n_head=4)
    model, variables = build(cfg)
    ref_eng = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                           min_bucket=8)
    refs = ref_eng.run(PROMPTS[:4], max_new_tokens=5)

    mesh = mesh_for("tp", tp_size=2)
    eng = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                       min_bucket=8, mesh=mesh, recipe="tp")
    k_cache = eng.caches[0]["k"]  # (slots, S, n_kv, hs)
    spec = k_cache.sharding.spec
    assert spec[2] == "model", f"kv-head axis not tp-sharded: {spec}"
    outs = eng.run(PROMPTS[:4], max_new_tokens=5)
    assert outs == refs


@pytest.mark.parametrize("kw", [
    dict(attn="gqa", n_kv_heads=2, pos_emb="rope"),
    dict(attn="mla", pos_emb="rope"),
], ids=["gqa-rope", "mla-rope"])
def test_prefix_reuse_bit_identical(kw):
    """Prompts sharing a block-aligned prefix admit with a prefix-cache
    hit (only the suffix prefills) and still decode bit-identically to
    the one-shot oracle — shared blocks are immutable, positions line
    up, and the traced prefix length adds no prefill traces."""
    cfg = tiny_cfg(**kw)
    model, variables = build(cfg)
    eng = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                       min_bucket=8)
    shared = list(range(1, 25))                  # 3 full 8-blocks
    prompts = [shared + [30, 31], shared + [40], shared + [50, 51, 52]]
    outs = eng.run(prompts, max_new_tokens=6)
    for p, o in zip(prompts, outs):
        ref = generate(model, variables, jnp.asarray(p, jnp.int32)[None], 6,
                       temperature=0.0)[0].tolist()
        assert o == ref, f"prefix-reuse diverged for prompt {p}"
    # followers 2 and 3 hit the 24-token prefix
    assert eng.prefix_hit_tokens == 2 * 24
    assert eng.prefilled_tokens < sum(len(p) for p in prompts)
    assert eng.prefix_hit_rate > 0.5
    # reuse rides the SAME bucket traces (prefix length is traced)
    assert eng.step_traces == 1


def test_prefix_cache_off_is_the_baseline():
    cfg = tiny_cfg()
    model, variables = build(cfg)
    eng = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                       min_bucket=8, prefix_cache=False)
    shared = list(range(1, 25))
    prompts = [shared + [30, 31], shared + [40]]
    outs = eng.run(prompts, max_new_tokens=4)
    assert eng.prefix_hit_tokens == 0
    assert eng.prefilled_tokens == sum(len(p) for p in prompts)
    ref_eng = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                           min_bucket=8)
    assert outs == ref_eng.run(prompts, max_new_tokens=4)


def test_preemption_requeues_and_stays_bit_identical():
    """A pool too small for every live sequence's full output preempts
    the youngest mid-decode; run() requeues it (tokens so far become the
    prompt, retained blocks give a prefix hit) and the final outputs are
    STILL bit-identical to the oracle — preemption must be invisible in
    the tokens."""
    cfg = tiny_cfg()
    model, variables = build(cfg)
    # bs=8, max_len=64 -> 8 blocks/seq worst case; capacity 11 blocks
    # cannot hold two 6-block sequences once both grow
    eng = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                       min_bucket=8, n_blocks=12)
    prompts = [[1, 2, 3], [5, 6, 7, 8, 9, 10, 11]]
    outs = eng.run(prompts, max_new_tokens=40)
    assert eng.retire_counts["preempted"] >= 1, \
        "pool was sized to force preemption"
    for p, o in zip(prompts, outs):
        ref = generate(model, variables, jnp.asarray(p, jnp.int32)[None],
                       40, temperature=0.0)[0].tolist()
        assert o == ref, "preemption/resume changed the output"
    assert eng.block_pool.n_referenced == 0      # nothing leaked


def test_engine_paged_kernel_matches_naive(monkeypatch):
    """FLASH_DECODE=on drives the fused step through the PAGED kernel
    (interpret off-TPU) — tokens must match the FLASH_DECODE=off
    gather+naive engine exactly."""
    cfg = tiny_cfg()
    model, variables = build(cfg)
    monkeypatch.setenv("FLASH_DECODE", "off")
    ref_eng = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                           min_bucket=8)
    refs = ref_eng.run(PROMPTS[:3], max_new_tokens=5)
    monkeypatch.setenv("FLASH_DECODE", "on")
    eng = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                       min_bucket=8)
    assert eng.run(PROMPTS[:3], max_new_tokens=5) == refs


# ----------------------------------------------------------------------
# chunked prefill fused into the decode step (prefill_chunk > 0)
# ----------------------------------------------------------------------

# mixed mix on purpose: a trivial prompt, a multi-chunk long prompt, and
# a mid-size one — lengths chosen so prompt + budget stays under max_len
# (past it the engine retires 'cache_full' by design and the one-shot
# oracle no longer defines the answer)
CHUNK_PROMPTS = [[1, 2, 3], list(range(1, 40)), [7] * 10]


@pytest.mark.parametrize("cache_dtype", [None, "int8"],
                         ids=["native", "int8"])
@pytest.mark.parametrize("kw", [
    dict(attn="mha", n_kv_heads=4, pos_emb="learn"),
    dict(attn="gqa", n_kv_heads=2, pos_emb="rope"),
    dict(attn="mla", pos_emb="rope"),
], ids=["mha", "gqa", "mla"])
def test_chunked_matches_oneshot(kw, cache_dtype):
    """Chunked-vs-oneshot greedy bit-parity matrix: splitting a prompt
    into fused <=16-token chunks must be invisible in the tokens for
    dense/GQA/MLA and for the int8 KV cache (per-row scales make the
    quantization chunking-independent). The native legs are also pinned
    against the one-shot `generate` oracle; int8 legs against the wave
    engine (the int8-vs-bf16 tolerance is test_quant.py's contract)."""
    cfg = tiny_cfg(**kw)
    model, variables = build(cfg)
    kwargs = dict(n_slots=2, temperature=0.0, min_bucket=8, block_size=8,
                  cache_dtype=cache_dtype)
    wave = DecodeEngine(model, variables, **kwargs)
    refs = wave.run([list(p) for p in CHUNK_PROMPTS], max_new_tokens=12)
    if cache_dtype is None:
        for p, r in zip(CHUNK_PROMPTS, refs):
            assert r == generate(model, variables,
                                 jnp.asarray(p, jnp.int32)[None], 12,
                                 temperature=0.0)[0].tolist()
    eng = DecodeEngine(model, variables, prefill_chunk=16, **kwargs)
    outs = eng.run([list(p) for p in CHUNK_PROMPTS], max_new_tokens=12)
    assert outs == refs, "chunked prefill changed the greedy output"
    assert eng.fused_step_traces == 1
    assert eng.admit_traces == {}, "chunked admission must not prefill"


@pytest.mark.parametrize("prefix_cache", [True, False],
                         ids=["prefix-on", "prefix-off"])
def test_chunked_prefix_reuse_bit_identical(prefix_cache):
    """Chunking composes with radix prefix matching: a re-admitted prompt
    hits the blocks its own chunks registered (chunk boundaries register
    full blocks as they fill — not only at retirement) and skips straight
    to the tail, still bit-identical to the oracle; the prefix-off
    baseline re-chunks everything and must agree too."""
    cfg = tiny_cfg()
    model, variables = build(cfg)
    eng = DecodeEngine(model, variables, n_slots=1, temperature=0.0,
                       min_bucket=8, prefill_chunk=16, block_size=8,
                       prefix_cache=prefix_cache)
    p = list(range(1, 40))
    ref = generate(model, variables, jnp.asarray(p, jnp.int32)[None], 12,
                   temperature=0.0)[0].tolist()
    assert eng.run([list(p)], max_new_tokens=12)[0] == ref
    # second admission of the same prompt: block-aligned prefix served
    # from cache (the partial tail stays private, so < len(p))
    assert eng.run([list(p)], max_new_tokens=12)[0] == ref
    if prefix_cache:
        assert 0 < eng.prefix_hit_tokens < 2 * len(p)
    else:
        assert eng.prefix_hit_tokens == 0
        assert eng.prefilled_tokens == 2 * len(p)
    assert eng.fused_step_traces == 1


@pytest.mark.parametrize("prefix_cache", [True, False],
                         ids=["prefix-on", "prefix-off"])
def test_chunked_mid_prefill_preemption_bit_identical(prefix_cache):
    """A pool too small for a decode stream plus a multi-chunk prompt
    preempts the partial MID-PREFILL; run() requeues it and the resume
    (a prefix hit on its already-written blocks when the cache is on, a
    full re-chunk when off) still produces oracle-identical tokens."""
    cfg = tiny_cfg()
    model, variables = build(cfg)
    # bs=8: the 39-token prompt needs 5 blocks mid-prefill and 8 by
    # budget end, the short stream grows to 3 — 8 usable blocks force a
    # preemption while the long prompt is still chunking in
    eng = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                       min_bucket=8, prefill_chunk=16, block_size=8,
                       n_blocks=9, prefix_cache=prefix_cache)
    prompts = [[1, 2, 3], list(range(1, 40))]
    outs = eng.run([list(p) for p in prompts], max_new_tokens=20)
    assert eng.retire_counts["preempted"] >= 1, \
        "pool was sized to force a mid-prefill preemption"
    for p, o in zip(prompts, outs):
        ref = generate(model, variables, jnp.asarray(p, jnp.int32)[None],
                       20, temperature=0.0)[0].tolist()
        assert o == ref, "mid-prefill preemption changed the output"
    assert (eng.prefix_hit_tokens > 0) == prefix_cache
    assert eng.block_pool.n_referenced == 0      # nothing leaked


def test_chunked_single_fused_trace_across_prompt_mix():
    """ONE fused-step trace regardless of prompt mix: chunk slot, write
    offset, and valid length are traced arguments, so 1-token prompts,
    multi-chunk prompts, and back-to-back runs all share the compiled
    program — and chunked admission adds zero prefill traces."""
    cfg = tiny_cfg()
    model, variables = build(cfg)
    eng = DecodeEngine(model, variables, n_slots=3, temperature=0.0,
                       min_bucket=8, prefill_chunk=16, block_size=8)
    eng.run([[9], [1, 2, 3], list(range(1, 40)), [7] * 10, [42, 43]],
            max_new_tokens=5)
    assert eng.fused_step_traces == 1
    assert eng.step_traces <= 1          # pure-decode steps share one too
    assert eng.admit_traces == {}
    eng.run([[2, 4, 6], list(range(50, 80))], max_new_tokens=4)
    assert eng.fused_step_traces == 1
    assert eng.step_traces <= 1
    assert eng.admit_traces == {}


def test_chunked_engine_kernel_matches_naive(monkeypatch):
    """FLASH_DECODE=on drives the fused chunk through the paged chunk-
    prefill kernel (interpret off-TPU) and decode through the paged
    decode kernel — tokens must match the FLASH_DECODE=off gather+naive
    chunked engine exactly."""
    cfg = tiny_cfg()
    model, variables = build(cfg, attn_impl="auto")
    kwargs = dict(n_slots=2, temperature=0.0, min_bucket=8,
                  prefill_chunk=16, block_size=8)
    monkeypatch.setenv("FLASH_DECODE", "off")
    ref_eng = DecodeEngine(model, variables, **kwargs)
    refs = ref_eng.run([list(p) for p in CHUNK_PROMPTS], max_new_tokens=8)
    monkeypatch.setenv("FLASH_DECODE", "on")
    eng = DecodeEngine(model, variables, **kwargs)
    assert eng.run([list(p) for p in CHUNK_PROMPTS],
                   max_new_tokens=8) == refs


def test_engine_fsdp_mesh_runs():
    """fsdp recipe: params sharded over 'data', slot axis of the cache
    sharded over 'data' (2 slots x dp2)."""
    from distributed_pytorch_tpu.parallel.mesh import mesh_for

    if len(jax.devices()) < 2:
        pytest.skip("needs multi-device CPU platform")
    cfg = tiny_cfg()
    model, variables = build(cfg)
    mesh = mesh_for("fsdp", dp_size=2, devices=jax.devices()[:2])
    eng = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                       min_bucket=8, mesh=mesh, recipe="fsdp")
    spec = eng.caches[0]["k"].sharding.spec
    assert spec[0] == "data", f"slot axis not data-sharded: {spec}"
    outs = eng.run(PROMPTS[:2], max_new_tokens=4)
    ref_eng = DecodeEngine(model, variables, n_slots=2, temperature=0.0,
                           min_bucket=8)
    assert outs == ref_eng.run(PROMPTS[:2], max_new_tokens=4)
