"""Model forward/backward correctness across the full flavor matrix
(attention kind x positional embedding x dense/MoE), replacing the
reference's absent test suite (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.config import LLMConfig
from distributed_pytorch_tpu.models import LLM
from distributed_pytorch_tpu.models.gpt import count_params

VOCAB, BLOCK = 96, 32


def tiny_config(**kw):
    base = dict(vocab_size=VOCAB, block_size=BLOCK, n_embd=32, n_head=4,
                n_kv_heads=2, n_layer=2, up_dim=48, pos_emb="rope",
                attn="gqa", non_linearity="swiglu", dropout=0.0, moe=False,
                q_latent_dim=8, kv_latent_dim=8, rope_head_dim=4)
    base.update(kw)
    return LLMConfig(**base)


def init_and_forward(cfg, seed=0, B=2, T=16):
    model = LLM(cfg)
    rng = jax.random.PRNGKey(seed)
    idx = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, VOCAB)
    targets = jax.random.randint(jax.random.PRNGKey(2), (B, T), 0, VOCAB)
    variables = model.init(rng, idx, targets)
    logits, loss, _ = model.apply(variables, idx, targets,
                                  mutable=["moe_state"])[0] \
        if cfg.moe else model.apply(variables, idx, targets)
    return variables, logits, loss


@pytest.mark.parametrize("attn", ["mha", "mqa", "gqa", "mla"])
@pytest.mark.parametrize("pos_emb", ["learn", "sin", "rope"])
def test_forward_all_flavors(attn, pos_emb):
    cfg = tiny_config(attn=attn, pos_emb=pos_emb)
    _, logits, loss = init_and_forward(cfg)
    assert logits.shape == (2, 16, VOCAB)
    assert jnp.isfinite(loss)
    # untrained CE should be near ln(vocab)
    assert abs(float(loss) - np.log(VOCAB)) < 1.0


@pytest.mark.parametrize("nl", ["relu", "gelu", "silu", "swiglu", "glu",
                                "mish", "selu", "celu", "elu", "sigmoid",
                                "lrelu", "tanh", "swish"])
def test_all_activations(nl):
    cfg = tiny_config(non_linearity=nl)
    _, _, loss = init_and_forward(cfg)
    assert jnp.isfinite(loss)


def test_grads_finite_and_nonzero():
    cfg = tiny_config(attn="mla", pos_emb="rope")
    model = LLM(cfg)
    idx = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, VOCAB)
    tgt = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, VOCAB)
    variables = model.init(jax.random.PRNGKey(0), idx, tgt)

    def loss_fn(params):
        _, loss, _ = model.apply({"params": params}, idx, tgt)
        return loss

    grads = jax.grad(loss_fn)(variables["params"])
    leaves = jax.tree_util.tree_leaves(grads)
    assert all(jnp.all(jnp.isfinite(g)) for g in leaves)
    assert any(float(jnp.abs(g).max()) > 0 for g in leaves)


def test_loss_ignore_index():
    cfg = tiny_config()
    model = LLM(cfg)
    idx = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, VOCAB)
    tgt = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, VOCAB)
    variables = model.init(jax.random.PRNGKey(0), idx, tgt)
    _, loss_full, _ = model.apply(variables, idx, tgt)
    # masking half the targets changes the denominator, not finiteness
    tgt_masked = tgt.at[:, 8:].set(-1)
    _, loss_masked, _ = model.apply(variables, idx, tgt_masked)
    assert jnp.isfinite(loss_masked)
    assert not jnp.allclose(loss_full, loss_masked)


def test_weight_tying_and_init_scale():
    cfg = tiny_config()
    variables, _, _ = init_and_forward(cfg)
    params = variables["params"]
    # single embedding matrix serves both embed and head
    emb = params["tkn_emb"]["embedding"]
    assert emb.shape == (VOCAB, cfg.n_embd)
    std = float(jnp.std(emb))
    assert 0.01 < std < 0.03  # N(0, 0.02) init (reference model.py:579-586)


@pytest.mark.parametrize("policy", ["block", "attn"])
def test_act_recomp_matches_plain(policy):
    """Both remat granularities (whole-Block, reference model.py:677-680;
    attention-only, kaggle-ddp.py:526-534) are pure memory/FLOPs trades:
    loss and grads must match the plain model."""
    cfg = tiny_config()
    cfg_r = tiny_config(act_recomp=True, act_recomp_policy=policy)
    model, model_r = LLM(cfg), LLM(cfg_r)
    idx = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, VOCAB)
    tgt = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, VOCAB)
    variables = model.init(jax.random.PRNGKey(0), idx, tgt)
    _, loss, _ = model.apply(variables, idx, tgt)
    _, loss_r, _ = model_r.apply(variables, idx, tgt)
    assert jnp.allclose(loss, loss_r, atol=1e-5)

    def lf(m):
        def f(p):
            return m.apply({"params": p}, idx, tgt)[1]
        return f

    g = jax.grad(lf(model))(variables["params"])
    g_r = jax.grad(lf(model_r))(variables["params"])
    chex_close = jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-4), g, g_r)
    del chex_close


@pytest.mark.parametrize("policy", ["block", "attn"])
def test_act_recomp_moe_matches_plain(policy):
    """Remat x MoE — the exact combination the reference documents as
    erroring ("scary looking error when we add MoE in checkpoint",
    kaggle-ddp.py:526-534): a Block wrapped in nn.remat carries the mutable
    'moe_state' collection. Loss and grads must match the plain MoE model,
    and the aux-free bias update must still fire under remat."""
    kw = dict(moe=True, n_exp=4, n_shared=1, n_act=2, aux_free=True,
              alpha=1e-4, gamma=0.1, coeff=0.01)
    cfg = tiny_config(**kw)
    cfg_r = tiny_config(act_recomp=True, act_recomp_policy=policy, **kw)
    model, model_r = LLM(cfg), LLM(cfg_r)
    idx = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, VOCAB)
    tgt = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, VOCAB)
    variables = model.init(jax.random.PRNGKey(0), idx, tgt)

    (_, loss, _), _ = model.apply(variables, idx, tgt, mutable=["moe_state"])
    (_, loss_r, _), _ = model_r.apply(variables, idx, tgt,
                                      mutable=["moe_state"])
    assert jnp.allclose(loss, loss_r, atol=1e-5)

    def lf(m):
        def f(p):
            (_, l, _), _ = m.apply(
                {"params": p, "moe_state": variables["moe_state"]},
                idx, tgt, mutable=["moe_state"])
            return l
        return f

    g = jax.grad(lf(model))(variables["params"])
    g_r = jax.grad(lf(model_r))(variables["params"])
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-4), g, g_r)

    # training-mode apply (deterministic=False): the bias update mutates
    # moe_state INSIDE the remat region — the landmine case itself
    (_, loss_t, _), upd = model_r.apply(variables, idx, tgt,
                                        deterministic=False,
                                        mutable=["moe_state"])
    assert jnp.isfinite(loss_t)
    b0 = jax.tree_util.tree_leaves(variables["moe_state"])
    b1 = jax.tree_util.tree_leaves(upd["moe_state"])
    assert any(not np.allclose(np.asarray(a), np.asarray(b))
               for a, b in zip(b0, b1)), \
        "aux-free bias did not update under remat"


def test_count_params_dense_equals_total():
    cfg = tiny_config()
    variables, _, _ = init_and_forward(cfg)
    total, active = count_params(variables["params"], cfg)
    assert total == active
    assert total > 0
