"""Dropless grouped-matmul dispatch tests (ops/grouped_matmul.py): the
Pallas ragged kernel must be exact (fwd AND grads) against per-group numpy
matmuls; the full grouped dispatch must reproduce the 'dense' combine
oracle (loss + grads, zero dropped tokens by construction) on one device
and inside shard_map over the 8-device CPU meshes (ep, ep x dp via fsdp);
routing edge cases (empty experts, every token on one expert) must not
break tile metadata; and the scatter path's dropped-assignment metric must
read nonzero exactly when capacity drops happen."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.config import LLMConfig
from distributed_pytorch_tpu.models.mlp import MoE
from distributed_pytorch_tpu.ops import grouped_matmul as gm
from distributed_pytorch_tpu.parallel import context
from distributed_pytorch_tpu.parallel.mesh import build_mesh, resolve_plan

VOCAB = 64


def moe_config(**kw):
    base = dict(vocab_size=VOCAB, block_size=32, n_embd=32, n_head=4,
                n_kv_heads=2, n_layer=2, up_dim=48, pos_emb="rope",
                attn="gqa", non_linearity="swiglu", dropout=0.0,
                moe=True, n_exp=6, n_shared=2, n_act=4,
                coeff=0.01, aux_free=False, alpha=1e-4, gamma=1e-2)
    base.update(kw)
    return LLMConfig(**base)


# ---------------------------------------------------------------------------
# kernel-level parity: ragged gmm vs per-group numpy matmuls
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("sizes", [
    [10, 0, 5, 7],          # ragged incl. an empty group
    [0, 0, 22, 0],          # everything on one expert
    [1, 1, 1, 1],           # minimal groups, all padding
], ids=["ragged", "one_expert", "singletons"])
@pytest.mark.parametrize("scaled", [False, True], ids=["plain", "scaled"])
def test_gmm_matches_per_group_matmul(sizes, scaled):
    bm, E, K, N = 8, 4, 32, 48
    rng = np.random.default_rng(0)
    g = np.asarray(sizes, np.int32)
    A = int(g.sum())
    flat_e = jnp.asarray(np.repeat(np.arange(E), g).astype(np.int32))
    n_tiles = -(-A // bm) + E
    P = n_tiles * bm

    counts, pstart, starts, tile_group, tile_first = gm._gmm_metadata(
        flat_e, E, n_tiles, bm)
    # empty groups own zero tiles — the "skipped via scalar-prefetch"
    # property: within the used tile range, group e owns exactly
    # ceil(g_e / bm) tiles
    tg = np.asarray(tile_group)
    used = int(sum(-(-s // bm) for s in sizes))
    for e in range(E):
        assert int((tg[:used] == e).sum()) == -(-sizes[e] // bm)

    x_pad = np.zeros((P, K), np.float32)
    scales = np.zeros((P, 1), np.float32)
    ps = np.asarray(pstart)
    row_group = np.full(P, -1)
    j = 0
    for e in range(E):
        for r in range(g[e]):
            x_pad[ps[e] + r] = rng.normal(size=K)
            scales[ps[e] + r] = rng.normal()
            row_group[ps[e] + r] = e
            j += 1
    w = rng.normal(size=(E, K, N)).astype(np.float32)

    def f(x, w, s):
        return gm.gmm(x, w, tile_group, tile_first, counts,
                      scales=s if scaled else None, bm=bm, interpret=True)

    y = f(jnp.asarray(x_pad), jnp.asarray(w), jnp.asarray(scales))
    ref = np.zeros((P, N), np.float32)
    for r in range(P):
        e = row_group[r]
        if e >= 0:
            ref[r] = x_pad[r] @ w[e] * (scales[r] if scaled else 1.0)
    filled = row_group >= 0
    np.testing.assert_allclose(np.asarray(y)[filled], ref[filled],
                               rtol=1e-5, atol=1e-5)

    # grads: weight rows zeroed outside filled slots by chain rule; compare
    # against an explicit per-group reference loss
    dy = rng.normal(size=(P, N)).astype(np.float32)
    dy[~filled] = 0.0  # the dispatch guarantees zero cotangents off-group

    def loss(x, w, s):
        return (f(x, w, s) * jnp.asarray(dy)).sum()

    gx, gw, gs = jax.grad(loss, argnums=(0, 1, 2))(
        jnp.asarray(x_pad), jnp.asarray(w), jnp.asarray(scales))
    gw_ref = np.zeros_like(w)
    gx_ref = np.zeros_like(x_pad)
    gs_ref = np.zeros_like(scales)
    for r in range(P):
        e = row_group[r]
        if e < 0:
            continue
        sc = scales[r] if scaled else 1.0
        gx_ref[r] = (dy[r] * sc) @ w[e].T
        gw_ref[e] += np.outer(x_pad[r], dy[r] * sc)
        gs_ref[r] = (x_pad[r] @ w[e]) @ dy[r]
    np.testing.assert_allclose(np.asarray(gx)[filled], gx_ref[filled],
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw), gw_ref, rtol=1e-4, atol=1e-4)
    if scaled:
        np.testing.assert_allclose(np.asarray(gs)[filled], gs_ref[filled],
                                   rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# module-level parity: moe_impl='grouped' vs the 'dense' oracle
# ---------------------------------------------------------------------------

def _make(cfg, B=2, T=16, seed=0):
    moe = MoE(cfg)
    x = jax.random.normal(jax.random.PRNGKey(seed), (B, T, cfg.n_embd))
    variables = moe.init(jax.random.PRNGKey(1), x)
    return moe, variables, x


@pytest.mark.parametrize("aux_free", [True, False])
def test_grouped_matches_dense_oracle(aux_free):
    """Acceptance bar: grouped loss parity with the dense oracle <= 1e-5
    rel on CPU interpret mode, grads included, zero drops by
    construction."""
    cfg_d = moe_config(aux_free=aux_free, moe_impl="dense")
    cfg_g = moe_config(aux_free=aux_free, moe_impl="grouped")
    moe_d, variables, x = _make(cfg_d)
    (y_d, aux_d), _ = moe_d.apply(variables, x, mutable=["moe_state"])
    (y_g, aux_g), _ = MoE(cfg_g).apply(variables, x, mutable=["moe_state"])
    np.testing.assert_allclose(np.asarray(y_g), np.asarray(y_d),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(aux_g), float(aux_d), rtol=1e-6)

    def loss(params, cfg):
        (y, aux), _ = MoE(cfg).apply(
            {"params": params, "moe_state": variables["moe_state"]}, x,
            mutable=["moe_state"])
        return (y ** 2).sum() + aux

    g_d = jax.grad(lambda p: loss(p, cfg_d))(variables["params"])
    g_g = jax.grad(lambda p: loss(p, cfg_g))(variables["params"])
    for k in g_d:
        np.testing.assert_allclose(np.asarray(g_g[k]), np.asarray(g_d[k]),
                                   rtol=2e-4, atol=2e-5, err_msg=k)


def test_grouped_dropless_where_scatter_drops():
    """The config that makes scatter drop (capacity floor = k) must leave
    grouped bit-matching the dense oracle — dropless by construction."""
    cfg_d = moe_config(aux_free=False, moe_impl="dense")
    cfg_s = moe_config(aux_free=False, moe_impl="scatter",
                       capacity_factor=1e-9)
    cfg_g = moe_config(aux_free=False, moe_impl="grouped")
    moe_d, variables, x = _make(cfg_d)
    (y_d, _), _ = moe_d.apply(variables, x, mutable=["moe_state"])
    (y_s, _), _ = MoE(cfg_s).apply(variables, x, mutable=["moe_state"])
    (y_g, _), _ = MoE(cfg_g).apply(variables, x, mutable=["moe_state"])
    assert not np.allclose(np.asarray(y_s), np.asarray(y_d))  # scatter drops
    np.testing.assert_allclose(np.asarray(y_g), np.asarray(y_d),
                               rtol=1e-5, atol=1e-6)           # grouped doesn't


def test_grouped_all_tokens_one_expert():
    """Routing edge case: a huge aux-free bias forces one routed expert
    into every token's top-k (maximal group imbalance — one giant group,
    several empty ones). Selection-vs-gating parity must hold vs dense."""
    cfg_g = moe_config(aux_free=True, moe_impl="grouped")
    cfg_d = moe_config(aux_free=True, moe_impl="dense")
    moe_d, variables, x = _make(cfg_d)
    big = variables["moe_state"]["expert_bias"].at[0].set(1e4)
    variables = {"params": variables["params"],
                 "moe_state": {**variables["moe_state"],
                               "expert_bias": big}}
    (y_d, _), _ = moe_d.apply(variables, x, mutable=["moe_state"])
    (y_g, _), _ = MoE(cfg_g).apply(variables, x, mutable=["moe_state"])
    np.testing.assert_allclose(np.asarray(y_g), np.asarray(y_d),
                               rtol=1e-5, atol=1e-6)


def test_grouped_no_shared_experts():
    """n_shared=0: the dispatch must not emit always-on groups."""
    cfg_d = moe_config(aux_free=False, moe_impl="dense", n_shared=0,
                       n_act=2)
    cfg_g = moe_config(aux_free=False, moe_impl="grouped", n_shared=0,
                       n_act=2)
    moe_d, variables, x = _make(cfg_d)
    (y_d, _), _ = moe_d.apply(variables, x, mutable=["moe_state"])
    (y_g, _), _ = MoE(cfg_g).apply(variables, x, mutable=["moe_state"])
    np.testing.assert_allclose(np.asarray(y_g), np.asarray(y_d),
                               rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# sharded: shard_map over ('data', 'expert') on the 8-device CPU mesh
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("recipe,kw", [
    ("ep", {"ep_size": 2}),            # data=4 x expert=2
    ("ep", {"ep_size": 4}),            # data=2 x expert=4 (shared split)
    ("dp", {}),                        # data=8, expert axis dead
], ids=["ep2", "ep4", "dp_only"])
def test_grouped_dispatch_sharded_matches_oracle(recipe, kw):
    """The shard_map path (tokens data-sharded in, expert shards pack only
    their local assignments, one psum combines) must reproduce the
    unsharded dense oracle — fwd and grads."""
    cfg_d = moe_config(aux_free=False, moe_impl="dense")
    cfg_g = moe_config(aux_free=False, moe_impl="grouped")
    moe_d, variables, x = _make(cfg_d, B=4, T=16)
    (y_d, _), _ = moe_d.apply(variables, x, mutable=["moe_state"])

    mesh = build_mesh(resolve_plan(recipe, 8, ep_size=kw.get("ep_size", 1)))
    with context.use_mesh(mesh):
        (y_g, _), _ = MoE(cfg_g).apply(variables, x, mutable=["moe_state"])

        def loss(params):
            (y, aux), _ = MoE(cfg_g).apply(
                {"params": params, "moe_state": variables["moe_state"]}, x,
                mutable=["moe_state"])
            return (y ** 2).sum() + aux

        g_g = jax.grad(loss)(variables["params"])

    np.testing.assert_allclose(np.asarray(y_g), np.asarray(y_d),
                               rtol=1e-5, atol=1e-6)

    def loss_d(params):
        (y, aux), _ = moe_d.apply(
            {"params": params, "moe_state": variables["moe_state"]}, x,
            mutable=["moe_state"])
        return (y ** 2).sum() + aux

    g_d = jax.grad(loss_d)(variables["params"])
    for k in g_d:
        np.testing.assert_allclose(np.asarray(g_g[k]), np.asarray(g_d[k]),
                                   rtol=2e-4, atol=2e-5, err_msg=k)


def test_grouped_usable_gates():
    """The static gate must decline exactly the configs the kernel can't
    serve: pipeline-vmapped blocks, live 'model'/'seq' axes, re-entry."""
    cfg = moe_config(moe_impl="grouped")
    assert gm.grouped_usable(cfg, 4, jnp.float32)
    pp = dataclasses.replace(cfg, pp_stages=2, pp_microbatches=2)
    assert not gm.grouped_usable(pp, 4, jnp.float32)
    with context.expert_region():
        assert not gm.grouped_usable(cfg, 4, jnp.float32)
    mesh = build_mesh(resolve_plan("tp", 8, tp_size=2))
    with context.use_mesh(mesh):
        assert not gm.grouped_usable(cfg, 4, jnp.float32)  # model axis live
    mesh = build_mesh(resolve_plan("sp", 8, sp_size=2))
    with context.use_mesh(mesh):
        assert not gm.grouped_usable(cfg, 4, jnp.float32)  # seq axis live
    mesh = build_mesh(resolve_plan("dp", 8))
    with context.use_mesh(mesh):
        assert not gm.grouped_usable(cfg, 3, jnp.float32)  # B % dp != 0
        assert gm.grouped_usable(cfg, 8, jnp.float32)


def test_grouped_falls_back_to_dense_not_crash():
    """moe_impl='grouped' on a declined config (live 'model' axis) must
    degrade to the dense combine — same dropless numbers, no error."""
    cfg_d = moe_config(aux_free=False, moe_impl="dense")
    cfg_g = moe_config(aux_free=False, moe_impl="grouped")
    moe_d, variables, x = _make(cfg_d, B=4, T=16)
    (y_d, _), _ = moe_d.apply(variables, x, mutable=["moe_state"])
    mesh = build_mesh(resolve_plan("tp", 8, tp_size=2))
    with context.use_mesh(mesh):
        (y_g, _), _ = MoE(cfg_g).apply(variables, x, mutable=["moe_state"])
    np.testing.assert_allclose(np.asarray(y_g), np.asarray(y_d),
                               rtol=1e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# the dropped-assignment metric (satellite): scatter > 0, grouped == 0
# ---------------------------------------------------------------------------

def test_dropped_frac_metric_scatter_vs_grouped():
    cfg_s = moe_config(aux_free=False, moe_impl="scatter",
                       capacity_factor=1e-9)  # capacity floor: k slots
    cfg_g = moe_config(aux_free=False, moe_impl="grouped")
    moe_s, variables, x = _make(cfg_s)
    _, mut_s = moe_s.apply(variables, x, deterministic=False,
                           mutable=["moe_state"])
    assert float(mut_s["moe_state"]["dropped_frac"]) > 0.0
    _, mut_g = MoE(cfg_g).apply(variables, x, deterministic=False,
                                mutable=["moe_state"])
    assert float(mut_g["moe_state"]["dropped_frac"]) == 0.0


def test_dropped_frac_flows_into_step_metrics():
    """The train step must surface moe_dropped_frac for MoE models —
    nonzero under a drop-forcing scatter config, zero for grouped."""
    from distributed_pytorch_tpu.config import TrainConfig
    from distributed_pytorch_tpu.models import LLM
    from distributed_pytorch_tpu.train.state import create_train_state
    from distributed_pytorch_tpu.train.step import make_train_step

    losses = {}
    for impl, cf in [("scatter", 1e-9), ("grouped", 2.0)]:
        mc = moe_config(moe_impl=impl, capacity_factor=cf)
        tc = TrainConfig(total_batch_size=2 * 2 * 32, batch_size=2,
                         parallelism="single")
        model, tx, state, sh = create_train_state(mc, tc)
        step = make_train_step(model, tx, mc, tc)
        x = jax.random.randint(jax.random.PRNGKey(0), (1, 2, 32), 0, VOCAB,
                               jnp.int32)
        state, m = step(state, x, x)
        assert "moe_dropped_frac" in m
        losses[impl] = float(m["moe_dropped_frac"])
    assert losses["scatter"] > 0.0
    assert losses["grouped"] == 0.0
