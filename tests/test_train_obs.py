"""Training-side observability (ISSUE 10): the anomaly guard's
poisoned-step skip/record/resume contract, the live telemetry endpoint
(/metrics + /debug/timeline + /healthz answered MID-RUN), the
memplan-predicted-vs-measured watermark report in stats.json, the
atomic checkpoint-boundary stats refresh, and the disabled-mode
overhead bound (one attribute check, no allocation)."""

import glob
import json
import math
import os
import re
import threading
import time
import urllib.request

import jax
import numpy as np
import pytest

from distributed_pytorch_tpu.config import LLMConfig, TrainConfig
from distributed_pytorch_tpu.train.loop import train
from distributed_pytorch_tpu.train.state import create_train_state
from distributed_pytorch_tpu.train.step import make_train_step
from distributed_pytorch_tpu.train.telemetry import (AnomalyMonitor,
                                                     TrainMetrics,
                                                     TrainTelemetry)

TINY = dict(vocab_size=256, block_size=32, n_embd=32, n_head=4,
            n_kv_heads=4, n_layer=2, up_dim=64)


def _tc(**kw):
    base = dict(dataset="synthetic", data_dir="bench_data",
                total_batch_size=2 * 2 * 32, batch_size=2,
                max_iters=5, parallelism="single", eval=False,
                log_interval=100, save_stats=False, learning_rate=1e-3,
                warmup_steps=2)
    base.update(kw)
    return TrainConfig(**base)


@pytest.fixture()
def in_tmp(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    return tmp_path


@pytest.fixture(autouse=True)
def _private_compile_cache(tmp_path):
    """Point the persistent XLA compile cache at a fresh per-test dir.

    The suite-wide cache (conftest.py, /tmp/jax_test_ccache) persists
    across runs, and on jax 0.4.37 an executable DESERIALIZED from it
    can mis-handle the train step's donated buffers — observed as the
    optimizer update silently not landing (params returned unchanged
    with correct metrics), which is indistinguishable from the exact
    regression the skip-mode tests assert against. A fresh empty dir
    forces a real compile, making the bitwise assertions deterministic;
    everything is restored for the rest of the suite."""
    from jax.experimental.compilation_cache import compilation_cache as cc
    prev = jax.config.jax_compilation_cache_dir
    cc.reset_cache()
    jax.config.update("jax_compilation_cache_dir",
                      str(tmp_path / "ccache"))
    yield
    cc.reset_cache()
    jax.config.update("jax_compilation_cache_dir", prev)


def _tree_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# Anomaly guard: device side (train/step.py)
# ---------------------------------------------------------------------------

def test_anomaly_skip_withholds_update_bitwise(monkeypatch):
    """A poisoned (NaN loss + NaN grads) step under anomaly='skip'
    leaves params AND optimizer state bit-equal to the pre-step
    snapshot, flags the step in the metrics, and the next (clean) step
    trains normally — the run survives the batch."""
    monkeypatch.setenv("TRAIN_POISON_IT", "1")    # poison state.step == 1
    mc = LLMConfig(**TINY)
    tc = _tc(anomaly="skip")
    model, tx, state, _ = create_train_state(mc, tc, None)
    step = make_train_step(model, tx, mc, tc, None, None)
    rng = jax.random.PRNGKey(0)
    x = jax.random.randint(rng, (1, 2, 32), 0, TINY["vocab_size"])
    y = jax.random.randint(jax.random.fold_in(rng, 1), (1, 2, 32), 0,
                           TINY["vocab_size"])

    state, m0 = step(state, x, y)                 # step 0: clean
    assert float(m0["nonfinite"]) == 0.0
    assert float(m0["update_skipped"]) == 0.0
    snap_params = jax.device_get(state.params)
    snap_opt = jax.device_get(state.opt_state)

    state, m1 = step(state, x, y)                 # step 1: poisoned
    assert math.isnan(float(m1["loss"]))
    assert float(m1["nonfinite"]) == 1.0
    assert float(m1["update_skipped"]) == 1.0
    _tree_equal(jax.device_get(state.params), snap_params)
    _tree_equal(jax.device_get(state.opt_state), snap_opt)
    assert int(jax.device_get(state.step)) == 2   # step still advances

    state, m2 = step(state, x, y)                 # step 2: clean again
    assert math.isfinite(float(m2["loss"]))
    assert float(m2["update_skipped"]) == 0.0
    changed = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(
            jax.device_get(state.params)),
            jax.tree_util.tree_leaves(snap_params)))
    assert changed, "clean step after the skip did not train"


def test_anomaly_warn_keeps_metric_but_applies_update(monkeypatch):
    """'warn' flags the step but never rewrites the update — and 'off'
    strips the metric entirely (the zero-cost path)."""
    monkeypatch.setenv("TRAIN_POISON_IT", "0")
    mc = LLMConfig(**TINY)
    rng = jax.random.PRNGKey(0)
    x = jax.random.randint(rng, (1, 2, 32), 0, TINY["vocab_size"])

    tc = _tc(anomaly="warn")
    model, tx, state, _ = create_train_state(mc, tc, None)
    step = make_train_step(model, tx, mc, tc, None, None)
    state, m = step(state, x, x)
    assert float(m["nonfinite"]) == 1.0
    assert "update_skipped" not in m
    # the NaN update went through — that is what 'warn' means
    assert any(np.isnan(np.asarray(l)).any() for l in
               jax.tree_util.tree_leaves(jax.device_get(state.params)))

    tc_off = _tc(anomaly="off")
    model, tx, state, _ = create_train_state(mc, tc_off, None)
    step = make_train_step(model, tx, mc, tc_off, None, None)
    _, m = step(state, x, x)
    assert "nonfinite" not in m and "update_skipped" not in m


# ---------------------------------------------------------------------------
# Anomaly guard: loop + timeline (the ISSUE 10 satellite test)
# ---------------------------------------------------------------------------

def test_poisoned_batch_skipped_event_in_timeline_run_resumes(
        in_tmp, monkeypatch):
    """e2e through train(): the poisoned batch at iteration k is
    skipped, the anomaly event (with the batch's data-shard
    coordinates) lands in stats AND the dumped train_timeline.jsonl,
    and training resumes with finite loss."""
    k = 2
    monkeypatch.setenv("TRAIN_POISON_IT", str(k))
    mc = LLMConfig(**TINY)
    stats = train(mc, _tc(anomaly="skip", max_iters=5, log_interval=1,
                          file_name="poisonrun", save_stats=True),
                  log=lambda s: None)

    assert math.isnan(stats["train_losses"][k])
    assert all(math.isfinite(l) for l in stats["train_losses"][k + 1:])
    assert math.isfinite(stats["final_loss"])

    (ev,) = stats["anomalies"]
    assert ev["kind"] == "nonfinite" and ev["it"] == k and ev["skipped"]
    coords = ev["data_coords"]
    assert coords["batch_step"] == k
    assert coords["dataset"] == "synthetic"
    assert "seed" in coords and "dp_shards" in coords

    # the event rides the same timeline as the step records
    path = stats["artifacts"]["train_timeline"]
    lines = [json.loads(ln) for ln in open(path)]
    anomaly_lines = [l for l in lines if l.get("event") == "anomaly"]
    assert len(anomaly_lines) == 1 and anomaly_lines[0]["it"] == k
    step_lines = [l for l in lines if "loss" in l and "event" not in l]
    assert {l["it"] for l in step_lines} == set(range(6))
    # phase fields present on post-compile records
    steady = [l for l in step_lines if not l.get("compile_window")]
    assert steady and all("step_ms" in l and "data_ms" in l
                          for l in steady)
    # stats.json carries the anomaly ledger too
    rec = json.load(open(os.path.join("checkpoints", "poisonrun",
                                      "stats.json")))
    assert rec["n_anomalies"] == 1


def test_grad_spike_monitor_and_off_mode():
    mon = AnomalyMonitor("warn", spike_factor=5.0, min_history=4)
    for i in range(6):
        assert mon.observe(it=i, loss=1.0,
                           grad_norm=1.0 + 0.01 * i) is None
    ev = mon.observe(it=6, loss=1.0, grad_norm=50.0)
    assert ev is not None and ev["kind"] == "grad_spike"
    assert ev["rolling_median_grad_norm"] > 0
    # the spike did not feed the baseline: a same-size follow-up still trips
    assert mon.observe(it=7, loss=1.0, grad_norm=50.0)["kind"] == \
        "grad_spike"
    assert mon.observe(it=8, loss=float("nan"),
                       grad_norm=1.0)["kind"] == "nonfinite"
    assert len(mon.events) == 3
    off = AnomalyMonitor("off")
    assert off.observe(it=0, loss=float("nan"),
                       grad_norm=float("inf")) is None
    assert off.events == []


# ---------------------------------------------------------------------------
# Live telemetry endpoint: served MID-RUN (the ISSUE 10 e2e bar)
# ---------------------------------------------------------------------------

def _get(url: str, timeout: float = 10.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read()


def test_metrics_endpoint_serves_mid_run(in_tmp):
    """train(metrics_port=0) answers /metrics, /debug/timeline and
    /healthz while the loop is mid-run (the log callback parks the
    training thread at a boundary; the telemetry thread keeps
    serving), and stats.json carries the per-device
    {memplan_predicted_gb, measured_peak_gb, delta} rows."""
    mc = LLMConfig(**TINY)
    tc = _tc(max_iters=8, log_interval=2, metrics_port=0,
             save_stats=True, file_name="telrun")
    found = {"port": None}
    reached, release = threading.Event(), threading.Event()

    def cb(s):
        m = re.search(r"http://127\.0\.0\.1:(\d+)/metrics", s)
        if m:
            found["port"] = int(m.group(1))
        # park the loop at the first post-compile boundary: the run is
        # provably mid-flight while the main thread scrapes
        if s.startswith("iter") and found["port"] \
                and not reached.is_set():
            reached.set()
            release.wait(timeout=60)

    out = {}
    th = threading.Thread(
        target=lambda: out.update(stats=train(mc, tc, log=cb)),
        daemon=True)
    th.start()
    try:
        assert reached.wait(timeout=300), "run produced no boundary line"
        port = found["port"]
        text = _get(f"http://127.0.0.1:{port}/metrics").decode()
        assert "train_build_info" in text and 'run="telrun"' in text
        assert "train_step_seconds_bucket" in text
        assert 'train_events_total{event="steps"}' in text
        assert "train_iteration" in text

        tl = json.loads(_get(
            f"http://127.0.0.1:{port}/debug/timeline?n=8"))
        assert tl["n_steps"] >= 1 and tl["entries"]
        assert {"it", "loss", "grad_norm"} <= set(tl["entries"][-1])

        hz = json.loads(_get(f"http://127.0.0.1:{port}/healthz"))
        assert hz["ok"] and hz["run"] == "telrun" and hz["it"] >= 0
    finally:
        release.set()
    th.join(timeout=300)
    assert not th.is_alive(), "train thread did not finish"

    stats = out["stats"]
    assert stats["telemetry_port"] == found["port"]
    # the server is down after the run
    with pytest.raises(Exception):
        _get(f"http://127.0.0.1:{found['port']}/healthz", timeout=2)

    # memplan-vs-watermark rows: keys always present (values None on
    # backends without memory_stats — CPU), in BOTH stats.json homes
    for home in (os.path.join("checkpoints", "telrun", "stats.json"),
                 os.path.join("runs", "telrun", "stats.json")):
        rec = json.load(open(home))
        devs = rec["memplan"]["devices"]
        assert devs, "no per-device memplan rows"
        for d in devs:
            assert {"device", "memplan_predicted_gb", "measured_peak_gb",
                    "delta"} <= set(d)
        assert rec["memplan"]["predicted_gb"] is not None
    assert os.path.exists(os.path.join("runs", "telrun",
                                       "train_timeline.jsonl"))


# ---------------------------------------------------------------------------
# Disabled mode: the obs/ overhead bar
# ---------------------------------------------------------------------------

def test_disabled_telemetry_records_nothing_and_is_cheap():
    tel = TrainTelemetry(enabled=False)
    tel.record_step(it=0, loss=1.0)
    assert tel.flight.total == 0 and len(tel.flight) == 0
    # the loop guards every call site with `if tel.enabled:` — measure
    # that guard (same 5 µs/call bound test_obs.py holds obs/trace to)
    n = 100_000
    t0 = time.perf_counter()
    acc = 0
    for _ in range(n):
        if tel.enabled:
            acc += 1                               # pragma: no cover
    per_call = (time.perf_counter() - t0) / n
    assert acc == 0
    assert per_call < 5e-6, f"disabled-mode guard cost {per_call:.2e}s"


def test_telemetry_off_run_leaves_no_timeline(in_tmp):
    mc = LLMConfig(**TINY)
    stats = train(mc, _tc(max_iters=2, telemetry=False, metrics_port=0,
                          file_name="quietrun"), log=lambda s: None)
    assert "telemetry_port" not in stats
    assert "artifacts" not in stats
    assert not os.path.exists(os.path.join("runs", "quietrun",
                                           "train_timeline.jsonl"))
    # the memplan report is end-of-run only (no per-step cost): kept
    assert stats["memplan"]["devices"]


# ---------------------------------------------------------------------------
# Atomic stats refresh at checkpoint boundaries
# ---------------------------------------------------------------------------

def test_stats_refreshed_atomically_at_each_checkpoint(in_tmp):
    mc = LLMConfig(**TINY)
    seen = []

    def cb(s):
        if s.startswith("checkpoint (async)"):
            p = os.path.join("checkpoints", "ckrun", "stats.json")
            n = len(json.load(open(p))["train_losses"]) \
                if os.path.exists(p) else -1
            seen.append(n)

    stats = train(mc, _tc(max_iters=6, ckpt_interval=2, log_interval=2,
                          save_stats=True, file_name="ckrun"), log=cb)
    # three interval saves, each preceded by a readable refresh whose
    # loss curve grows — a SIGKILL between them loses at most one window
    assert len(seen) == 3
    assert seen[0] > 0 and seen == sorted(seen)
    # tmp+rename left no droppings
    assert not glob.glob(os.path.join("checkpoints", "ckrun", "*.tmp"))
    assert not glob.glob(os.path.join("runs", "ckrun", "*.tmp"))
    # the runs/ mirror matches the final record
    final = json.load(open(os.path.join("runs", "ckrun", "stats.json")))
    assert final["train_losses"] == stats["train_losses"]
    # and the timeline was refreshed at the boundaries too
    tl = os.path.join("runs", "ckrun", "train_timeline.jsonl")
    assert os.path.exists(tl)
    ck = [json.loads(l) for l in open(tl)
          if json.loads(l).get("event") == "ckpt"]
    assert len(ck) == 3 and all("ckpt_ms" in e for e in ck)


# ---------------------------------------------------------------------------
# TrainMetrics rendering
# ---------------------------------------------------------------------------

def test_train_metrics_prometheus_render():
    m = TrainMetrics()
    m.observe_phases(step_s=0.01, data_s=0.001, sync_s=0.002, ckpt_s=0.5)
    m.observe_phases(step_s=0.02)
    m.inc("steps", 4)
    m.anomaly("nonfinite")
    m.anomaly("grad_spike")
    m.anomaly("grad_spike")
    m.set_build_info(run="x", recipe="single")
    m.register_gauge("train_iteration", lambda: 7, "last iter")
    text = m.render_prometheus()
    for series in ("train_step_seconds_bucket", "train_data_seconds_sum",
                   "train_sync_seconds_count",
                   "train_ckpt_snapshot_seconds_count"):
        assert series in text
    assert 'train_events_total{event="steps"} 4' in text
    assert 'train_events_total{event="anomalies"} 3' in text
    assert 'train_anomalies_total{kind="nonfinite"} 1' in text
    assert 'train_anomalies_total{kind="grad_spike"} 2' in text
    assert 'recipe="single"' in text
    assert "train_iteration 7" in text
    assert m.step_s.count == 2
