"""Split-KV flash-decode kernel (ops/flash_decode.py) vs the naive einsum
oracle: parity across GQA ratios and ragged per-sequence cache lengths
(interpret mode on CPU), the usable gate's decline conditions, and the
dispatcher integration (FLASH_DECODE env routing in ops/attention_core)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.ops.attention_core import _naive_sdpa, sdpa
from distributed_pytorch_tpu.ops.flash_decode import (flash_decode,
                                                      flash_decode_usable)


def _mk(B, S, nh, nkv, hs, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, 1, nh, hs), dtype)
    k = jax.random.normal(ks[1], (B, S, nkv, hs), dtype)
    v = jax.random.normal(ks[2], (B, S, nkv, hs), dtype)
    return q, k, v


@pytest.mark.parametrize("nkv", [8, 4, 2, 1], ids=lambda n: f"nkv{n}")
def test_parity_gqa_ratios(nkv):
    """Kernel output matches the naive path <= 1e-5 for MHA through MQA,
    with every sequence at a different (ragged) cache length."""
    B, S, nh, hs = 4, 64, 8, 16
    q, k, v = _mk(B, S, nh, nkv, hs)
    cl = jnp.array([1, 7, 33, 64], jnp.int32)
    out = flash_decode(q[:, 0], k, v, cl, scale=hs ** -0.5, interpret=True)
    ref = _naive_sdpa(q, k, v, scale=hs ** -0.5, q_offset=cl - 1)[:, 0]
    assert flash_decode_usable(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_parity_block_split():
    """Multiple KV blocks per sequence: the online max/sum merge across
    grid steps must agree with the single-pass softmax."""
    B, S, nh, nkv, hs = 2, 256, 4, 2, 8
    q, k, v = _mk(B, S, nh, nkv, hs, seed=3)
    cl = jnp.array([100, 256], jnp.int32)
    for block_s in (8, 32, 64):
        out = flash_decode(q[:, 0], k, v, cl, scale=hs ** -0.5,
                           block_s=block_s, interpret=True)
        ref = _naive_sdpa(q, k, v, scale=hs ** -0.5, q_offset=cl - 1)[:, 0]
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)


def test_dead_slot_tail_blocks_fully_skipped():
    """A sequence one token into a 64-slot cache owns one 8-row KV block:
    NaN/inf garbage in every LATER block must not leak into the output —
    the numerical witness that tail blocks are fully predicated off
    (within the last partial block, masked lanes are computed-then-zeroed
    like every flash kernel, so the poison starts at the block boundary)."""
    B, S, nh, nkv, hs = 1, 64, 4, 4, 8
    q, k, v = _mk(B, S, nh, nkv, hs)
    k = k.at[:, 8:].set(jnp.nan)
    v = v.at[:, 8:].set(jnp.inf)
    cl = jnp.array([1], jnp.int32)
    out = flash_decode(q[:, 0], k, v, cl, scale=hs ** -0.5, block_s=8,
                       interpret=True)
    assert bool(jnp.isfinite(out).all())
    # one fully-attended slot: softmax weight 1.0 on v[:, 0]
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(v[:, 0]), atol=1e-5)


def test_usable_gate_declines():
    q, k, v = _mk(2, 64, 8, 4, 16)
    assert flash_decode_usable(q, k, v)
    # multi-token query (prefill shape) is not a decode call
    assert not flash_decode_usable(jnp.zeros((2, 4, 8, 16)), k, v)
    # odd head dim: no sublane tiling
    qo, ko, vo = _mk(2, 64, 8, 4, 12)
    assert not flash_decode_usable(qo, ko, vo)
    # unsplittable cache length
    qs, ks_, vs = _mk(2, 9, 8, 4, 16)
    assert not flash_decode_usable(qs, ks_, vs)
    # integer dtypes
    assert not flash_decode_usable(q.astype(jnp.int32), k, v)


def test_usable_gate_declines_under_live_mesh():
    """GSPMD cannot partition a pallas_call: any live multi-device mesh
    must route decode to the naive path."""
    from distributed_pytorch_tpu.parallel import context
    from distributed_pytorch_tpu.parallel.mesh import mesh_for
    q, k, v = _mk(2, 64, 8, 4, 16)
    mesh = mesh_for("dp")
    with context.use_mesh(mesh):
        assert not flash_decode_usable(q, k, v)
    assert flash_decode_usable(q, k, v)  # gate is contextual, not sticky


def test_sdpa_routes_decode_through_kernel(monkeypatch):
    """FLASH_DECODE=on routes single-token cached sdpa calls through the
    kernel (interpret off-TPU) and matches FLASH_DECODE=off bit-for-bit at
    test tolerance; 'off' pins the naive path."""
    B, S, nh, nkv, hs = 3, 64, 8, 2, 16
    q, k, v = _mk(B, S, nh, nkv, hs, seed=11)
    pos = jnp.array([4, 20, 63], jnp.int32)

    monkeypatch.setenv("FLASH_DECODE", "off")
    ref = sdpa(q, k, v, causal=True, q_offset=pos, decode=True)
    monkeypatch.setenv("FLASH_DECODE", "on")
    out = sdpa(q, k, v, causal=True, q_offset=pos, decode=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def _mk_q8(B, S, nh, nkv, hs, seed=0):
    """Random decode shapes with an int8-quantized cache: returns the
    quantized operands AND the dequantized reference K/V (what the kernel
    must reproduce exactly — quantization error is not the kernel's)."""
    from distributed_pytorch_tpu.ops.quant import dequantize_int8, quantize_kv
    q, k, v = _mk(B, S, nh, nkv, hs, seed=seed)
    kq, ks_ = quantize_kv(k)
    vq, vs = quantize_kv(v)
    kd = dequantize_int8(kq, ks_, q.dtype)
    vd = dequantize_int8(vq, vs, q.dtype)
    return q, kq, ks_, vq, vs, kd, vd


@pytest.mark.parametrize("nkv", [8, 4, 2, 1], ids=lambda n: f"nkv{n}")
def test_parity_int8_gqa_ratios(nkv):
    """int8-cache kernel vs the naive path on the DEQUANTIZED cache:
    <= 1e-5 for MHA through MQA at ragged per-sequence lengths — the
    in-kernel dequant (scales folded into score/probability tiles) is
    exact algebra, so the kernel owes the dequantized reference full
    parity."""
    B, S, nh, hs = 4, 64, 8, 16
    q, kq, ks_, vq, vs, kd, vd = _mk_q8(B, S, nh, nkv, hs)
    cl = jnp.array([1, 7, 33, 64], jnp.int32)
    out = flash_decode(q[:, 0], kq, vq, cl, scale=hs ** -0.5,
                       k_scale=ks_, v_scale=vs, interpret=True)
    ref = _naive_sdpa(q, kd, vd, scale=hs ** -0.5, q_offset=cl - 1)[:, 0]
    assert flash_decode_usable(q, kq, vq)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_parity_int8_block_split():
    """Online max/sum merge across multiple int8 KV blocks (each with its
    own scale rows) agrees with the single-pass softmax."""
    B, S, nh, nkv, hs = 2, 256, 4, 2, 8
    q, kq, ks_, vq, vs, kd, vd = _mk_q8(B, S, nh, nkv, hs, seed=3)
    cl = jnp.array([100, 256], jnp.int32)
    for block_s in (8, 32, 64):
        out = flash_decode(q[:, 0], kq, vq, cl, scale=hs ** -0.5,
                           k_scale=ks_, v_scale=vs, block_s=block_s,
                           interpret=True)
        ref = _naive_sdpa(q, kd, vd, scale=hs ** -0.5, q_offset=cl - 1)[:, 0]
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)


def test_sdpa_int8_kernel_vs_dequant_fallback(monkeypatch):
    """The dispatcher's two int8 routes agree: FLASH_DECODE=on runs the
    in-kernel-dequant path, FLASH_DECODE=off dequantizes up front and
    takes the naive path — same cache, same answer."""
    B, S, nh, nkv, hs = 3, 64, 8, 2, 16
    q, kq, ks_, vq, vs, _, _ = _mk_q8(B, S, nh, nkv, hs, seed=11)
    pos = jnp.array([4, 20, 63], jnp.int32)
    monkeypatch.setenv("FLASH_DECODE", "on")
    out = sdpa(q, kq, vq, causal=True, q_offset=pos, decode=True,
               k_scale=ks_, v_scale=vs)
    monkeypatch.setenv("FLASH_DECODE", "off")
    ref = sdpa(q, kq, vq, causal=True, q_offset=pos, decode=True,
               k_scale=ks_, v_scale=vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_int8_unsplittable_cache_falls_back(monkeypatch):
    """quant_usable-style degrade: an int8 cache whose S the kernel cannot
    tile (S=9) declines the kernel even under FLASH_DECODE=on and the
    dequant+naive fallback carries the call — degrade, don't crash."""
    from distributed_pytorch_tpu.ops.attention_core import _naive_sdpa
    from distributed_pytorch_tpu.ops.quant import dequantize_int8, quantize_kv
    B, S, nh, nkv, hs = 2, 9, 4, 2, 16
    q, k, v = _mk(B, S, nh, nkv, hs, seed=7)
    kq, ks_ = quantize_kv(k)
    vq, vs = quantize_kv(v)
    assert not flash_decode_usable(q, kq, vq)
    pos = jnp.array([3, 8], jnp.int32)
    monkeypatch.setenv("FLASH_DECODE", "on")
    out = sdpa(q, kq, vq, causal=True, q_offset=pos, decode=True,
               k_scale=ks_, v_scale=vs)
    ref = _naive_sdpa(q, dequantize_int8(kq, ks_, q.dtype),
                      dequantize_int8(vq, vs, q.dtype),
                      scale=hs ** -0.5, q_offset=pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_int8_dead_slot_tail_blocks_fully_skipped():
    """The int8 variant shares the cache_len block-skip: poisoned code/scale
    rows past the valid block must not leak (NaN scales would propagate
    through any touched lane)."""
    from distributed_pytorch_tpu.ops.quant import quantize_kv
    B, S, nh, nkv, hs = 1, 64, 4, 4, 8
    q, k, v = _mk(B, S, nh, nkv, hs)
    kq, ks_ = quantize_kv(k)
    vq, vs = quantize_kv(v)
    ks_ = ks_.at[:, 8:].set(jnp.nan)
    vs = vs.at[:, 8:].set(jnp.inf)
    cl = jnp.array([1], jnp.int32)
    out = flash_decode(q[:, 0], kq, vq, cl, scale=hs ** -0.5,
                       k_scale=ks_, v_scale=vs, block_s=8, interpret=True)
    assert bool(jnp.isfinite(out).all())


# ----------------------------------------------------------------------
# paged kernel (block-table scalar prefetch over the pool)
# ----------------------------------------------------------------------

def _mk_paged(B, n_max, bs, nh, nkv, hs, seed=0, extra_blocks=4):
    """Random pool + shuffled non-contiguous block tables: the logical
    view the kernel must reproduce comes from paged_gather (the oracle
    path the engine's naive fallback uses)."""
    import numpy as np_

    from distributed_pytorch_tpu.ops.block_pool import paged_gather
    n_blocks = 1 + B * n_max + extra_blocks      # + null block 0
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, 1, nh, hs))
    kp = jax.random.normal(ks[1], (n_blocks, bs, nkv, hs))
    vp = jax.random.normal(ks[2], (n_blocks, bs, nkv, hs))
    rng = np_.random.default_rng(seed)
    bt = jnp.asarray(rng.permutation(np_.arange(1, 1 + B * n_max))
                     .reshape(B, n_max).astype(np_.int32))
    return q, kp, vp, bt, paged_gather(kp, bt), paged_gather(vp, bt)


@pytest.mark.parametrize("nkv", [8, 4, 2, 1], ids=lambda n: f"nkv{n}")
def test_paged_parity_gqa_ratios(nkv):
    """Paged kernel vs the naive path on the GATHERED logical cache:
    <= 1e-5 for MHA through MQA at ragged per-sequence lengths, through
    shuffled (non-contiguous, non-monotone) block tables."""
    from distributed_pytorch_tpu.ops.flash_decode import (
        paged_flash_decode, paged_flash_decode_usable)
    B, n_max, bs, nh, hs = 4, 8, 8, 8, 16
    q, kp, vp, bt, kl, vl = _mk_paged(B, n_max, bs, nh, nkv, hs)
    cl = jnp.array([1, 7, 33, 64], jnp.int32)
    assert paged_flash_decode_usable(q, kp, vp, bt)
    out = paged_flash_decode(q[:, 0], kp, vp, bt, cl, scale=hs ** -0.5,
                             interpret=True)
    ref = _naive_sdpa(q, kl, vl, scale=hs ** -0.5, q_offset=cl - 1)[:, 0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("nkv", [8, 4, 2, 1], ids=lambda n: f"nkv{n}")
def test_paged_parity_int8(nkv):
    """int8-paged parity matrix: the scale-sidecar pools ride the same
    block-table index map and the in-kernel dequant owes the dequantized
    gathered reference full parity (exact algebra)."""
    from distributed_pytorch_tpu.ops.flash_decode import paged_flash_decode
    from distributed_pytorch_tpu.ops.quant import dequantize_int8, quantize_kv
    B, n_max, bs, nh, hs = 4, 8, 8, 8, 16
    q, kp, vp, bt, _, _ = _mk_paged(B, n_max, bs, nh, nkv, hs, seed=3)
    from distributed_pytorch_tpu.ops.block_pool import paged_gather
    kq, ks_ = quantize_kv(kp)
    vq, vs = quantize_kv(vp)
    cl = jnp.array([2, 9, 40, 64], jnp.int32)
    out = paged_flash_decode(q[:, 0], kq, vq, bt, cl, scale=hs ** -0.5,
                             k_scale=ks_, v_scale=vs, interpret=True)
    kd = dequantize_int8(paged_gather(kq, bt), paged_gather(ks_, bt), q.dtype)
    vd = dequantize_int8(paged_gather(vq, bt), paged_gather(vs, bt), q.dtype)
    ref = _naive_sdpa(q, kd, vd, scale=hs ** -0.5, q_offset=cl - 1)[:, 0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_paged_dead_blocks_fully_skipped():
    """Blocks past a sequence's last valid one must contribute nothing:
    poison every pool block the 1-token sequence does not own — the
    block-table clamp keeps the DMA on the last valid block, so NaN/inf
    elsewhere cannot leak."""
    from distributed_pytorch_tpu.ops.flash_decode import paged_flash_decode
    B, n_max, bs, nh, nkv, hs = 1, 8, 8, 4, 4, 8
    q, kp, vp, bt, _, _ = _mk_paged(B, n_max, bs, nh, nkv, hs)
    own = int(bt[0, 0])
    mask = jnp.arange(kp.shape[0]) != own
    kp = jnp.where(mask[:, None, None, None], jnp.nan, kp)
    vp = jnp.where(mask[:, None, None, None], jnp.inf, vp)
    out = paged_flash_decode(q[:, 0], kp, vp, bt,
                             jnp.array([1], jnp.int32), scale=hs ** -0.5,
                             interpret=True)
    assert bool(jnp.isfinite(out).all())
    # one fully-attended row: softmax weight 1.0 on the owned block's row 0
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(vp[own, 0]),
                               atol=1e-5)


def test_paged_usable_gate_declines():
    from distributed_pytorch_tpu.ops.flash_decode import \
        paged_flash_decode_usable
    q, kp, vp, bt, _, _ = _mk_paged(2, 4, 8, 8, 4, 16)
    assert paged_flash_decode_usable(q, kp, vp, bt)
    # prefill-shaped query
    assert not paged_flash_decode_usable(jnp.zeros((2, 4, 8, 16)), kp, vp, bt)
    # block size the hardware cannot tile (9 rows)
    q2, kp2, vp2, bt2, _, _ = _mk_paged(2, 4, 9, 8, 4, 16)
    assert not paged_flash_decode_usable(q2, kp2, vp2, bt2)
    # live multi-device mesh -> gather + naive carries sharded decode
    from distributed_pytorch_tpu.parallel import context
    from distributed_pytorch_tpu.parallel.mesh import mesh_for
    with context.use_mesh(mesh_for("dp")):
        assert not paged_flash_decode_usable(q, kp, vp, bt)
    assert paged_flash_decode_usable(q, kp, vp, bt)


def test_sdpa_paged_routes_kernel_vs_gather(monkeypatch):
    """The dispatcher's two paged routes agree: FLASH_DECODE=on runs the
    block-table kernel, 'off' gathers the logical view and takes the
    naive path — same pool, same tables, same answer (bf16 and int8)."""
    from distributed_pytorch_tpu.ops.quant import quantize_kv
    B, n_max, bs, nh, nkv, hs = 3, 8, 8, 8, 2, 16
    q, kp, vp, bt, _, _ = _mk_paged(B, n_max, bs, nh, nkv, hs, seed=11)
    pos = jnp.array([4, 20, 63], jnp.int32)
    monkeypatch.setenv("FLASH_DECODE", "on")
    out = sdpa(q, kp, vp, causal=True, q_offset=pos, decode=True,
               block_tables=bt)
    monkeypatch.setenv("FLASH_DECODE", "off")
    ref = sdpa(q, kp, vp, causal=True, q_offset=pos, decode=True,
               block_tables=bt)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)
    kq, ks_ = quantize_kv(kp)
    vq, vs = quantize_kv(vp)
    monkeypatch.setenv("FLASH_DECODE", "on")
    out8 = sdpa(q, kq, vq, causal=True, q_offset=pos, decode=True,
                k_scale=ks_, v_scale=vs, block_tables=bt)
    monkeypatch.setenv("FLASH_DECODE", "off")
    ref8 = sdpa(q, kq, vq, causal=True, q_offset=pos, decode=True,
                k_scale=ks_, v_scale=vs, block_tables=bt)
    np.testing.assert_allclose(np.asarray(out8), np.asarray(ref8),
                               atol=1e-5, rtol=1e-5)


def test_sdpa_decode_scalar_offset_under_jit(monkeypatch):
    """The legacy generate loop's traced SCALAR position broadcasts to the
    per-sequence cache_len vector inside the dispatcher."""
    B, S, nh, nkv, hs = 2, 32, 4, 4, 8
    q, k, v = _mk(B, S, nh, nkv, hs, seed=5)

    def run(p):
        return sdpa(q, k, v, causal=True, q_offset=p, decode=True)

    monkeypatch.setenv("FLASH_DECODE", "on")
    out = jax.jit(run)(jnp.int32(7))
    monkeypatch.setenv("FLASH_DECODE", "off")
    ref = jax.jit(run)(jnp.int32(7))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


# ----------------------------------------------------------------------
# chunk-prefill kernel (mixed prefill+decode path, round 12)
# ----------------------------------------------------------------------

def _mk_chunk(T, n_max, bs, nh, nkv, hs, seed=0):
    """One sequence's pool + shuffled block table for the chunk kernel:
    (1, T, nh, hs) query rows at global positions [off, off+T)."""
    import numpy as np_

    from distributed_pytorch_tpu.ops.block_pool import paged_gather
    n_blocks = 1 + n_max + 4                     # + null block 0
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (1, T, nh, hs))
    kp = jax.random.normal(ks[1], (n_blocks, bs, nkv, hs))
    vp = jax.random.normal(ks[2], (n_blocks, bs, nkv, hs))
    rng = np_.random.default_rng(seed)
    bt = jnp.asarray(rng.permutation(np_.arange(1, 1 + n_max))
                     .reshape(1, n_max).astype(np_.int32))
    return q, kp, vp, bt, paged_gather(kp, bt), paged_gather(vp, bt)


@pytest.mark.parametrize("off", [0, 8, 24], ids=lambda o: f"off{o}")
@pytest.mark.parametrize("nkv", [8, 4, 1], ids=lambda n: f"nkv{n}")
def test_chunk_prefill_parity_offsets(nkv, off):
    """paged_flash_prefill vs the naive path on the gathered logical
    view: a 16-row chunk at block-aligned offsets (fresh sequence, one
    prior block, three prior blocks) attends its prior context plus its
    own in-chunk causal prefix — MHA through MQA, shuffled tables."""
    from distributed_pytorch_tpu.ops.flash_decode import (
        paged_flash_prefill, paged_flash_prefill_usable)
    T, n_max, bs, nh, hs = 16, 8, 8, 8, 16
    q, kp, vp, bt, kl, vl = _mk_chunk(T, n_max, bs, nh, nkv, hs, seed=off)
    assert paged_flash_prefill_usable(q, kp, vp, bt)
    out = paged_flash_prefill(q, kp, vp, bt, jnp.int32(off),
                              scale=hs ** -0.5, interpret=True)
    ref = _naive_sdpa(q, kl, vl, scale=hs ** -0.5, q_offset=off)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_chunk_prefill_parity_int8():
    """int8 pools ride the chunk kernel's block-table index map; the
    in-kernel dequant owes the dequantized gathered oracle full parity
    (exact algebra, same as the decode kernel's contract)."""
    from distributed_pytorch_tpu.ops.block_pool import paged_gather
    from distributed_pytorch_tpu.ops.flash_decode import paged_flash_prefill
    from distributed_pytorch_tpu.ops.quant import dequantize_int8, quantize_kv
    T, n_max, bs, nh, nkv, hs = 16, 8, 8, 8, 4, 16
    q, kp, vp, bt, _, _ = _mk_chunk(T, n_max, bs, nh, nkv, hs, seed=3)
    kq, ks_ = quantize_kv(kp)
    vq, vs = quantize_kv(vp)
    out = paged_flash_prefill(q, kq, vq, bt, jnp.int32(8),
                              scale=hs ** -0.5, k_scale=ks_, v_scale=vs,
                              interpret=True)
    kd = dequantize_int8(paged_gather(kq, bt), paged_gather(ks_, bt), q.dtype)
    vd = dequantize_int8(paged_gather(vq, bt), paged_gather(vs, bt), q.dtype)
    ref = _naive_sdpa(q, kd, vd, scale=hs ** -0.5, q_offset=8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_chunk_prefill_trailing_blocks_fully_skipped():
    """Blocks past the chunk's last needed one must contribute nothing:
    the index-map clamp keeps the DMA on the last valid block, so poison
    beyond it cannot leak into the chunk's rows."""
    from distributed_pytorch_tpu.ops.flash_decode import paged_flash_prefill
    T, n_max, bs, nh, nkv, hs = 16, 8, 8, 4, 4, 8
    q, kp, vp, bt, _, _ = _mk_chunk(T, n_max, bs, nh, nkv, hs)
    off = 8                                      # rows [8, 24): blocks 0..2
    needed = {int(bt[0, j]) for j in range(3)}
    mask = ~jnp.isin(jnp.arange(kp.shape[0]), jnp.asarray(list(needed)))
    kp = jnp.where(mask[:, None, None, None], jnp.nan, kp)
    vp = jnp.where(mask[:, None, None, None], jnp.inf, vp)
    out = paged_flash_prefill(q, kp, vp, bt, jnp.int32(off),
                              scale=hs ** -0.5, interpret=True)
    assert bool(jnp.isfinite(out).all())


def test_chunk_prefill_usable_gate_declines():
    from distributed_pytorch_tpu.ops.flash_decode import \
        paged_flash_prefill_usable
    q, kp, vp, bt, _, _ = _mk_chunk(16, 8, 8, 8, 4, 16)
    assert paged_flash_prefill_usable(q, kp, vp, bt)
    # single-token (decode-shaped) query -> the decode kernel's job
    assert not paged_flash_prefill_usable(q[:, :1], kp, vp, bt)
    # chunk not a sublane multiple
    assert not paged_flash_prefill_usable(q[:, :12], kp, vp, bt)
    # batched chunks: one sequence at a time only
    q2 = jnp.concatenate([q, q], axis=0)
    assert not paged_flash_prefill_usable(q2, kp, vp, bt)
    # block size the hardware cannot tile (9 rows)
    q3, kp3, vp3, bt3, _, _ = _mk_chunk(16, 8, 9, 8, 4, 16)
    assert not paged_flash_prefill_usable(q3, kp3, vp3, bt3)
    # live multi-device mesh -> gather + naive carries sharded decode
    from distributed_pytorch_tpu.parallel import context
    from distributed_pytorch_tpu.parallel.mesh import mesh_for
    with context.use_mesh(mesh_for("dp")):
        assert not paged_flash_prefill_usable(q, kp, vp, bt)
    assert paged_flash_prefill_usable(q, kp, vp, bt)
