#!/bin/bash
# Single-host launcher: env-var config block rendered into CLI flags
# (the TPU counterpart of reference single-gpu/train.sh:6-46 — same
# pattern, one block of shell variables, conditional bool flags).
# Edit the block, then:  bash scripts/train.sh
set -euo pipefail
cd "$(dirname "$0")/.."

# --- Training configuration ---------------------------------------------
DATASET='tinystories'          # shakespeare | tinystories | fineweb | synthetic
TOTAL_BATCH_SIZE_STR="2**13"   # tokens per optimizer step (expression ok)
BATCH_SIZE=2                   # micro-batch sequences per device
MAX_ITERS=150000
LEARNING_RATE=7e-5
WARMUP_STEPS=500
GRAD_CLIP=0.9
EVAL=true
EVAL_INTERVAL=100
EVAL_ITERS=10
SAVE_MODEL=true
FILE_NAME="llm_model"
ACT_RECOMP=true
ACT_RECOMP_POLICY="attn"       # block | attn (attention-only recompute)

# --- Parallelism (replaces the reference's choice of trainer script) ----
PARALLELISM="single"           # single|dp|zero1|zero2|fsdp|tp|fsdp_tp|ep|sp
PLATFORM="auto"                # auto | tpu | cpu (cpu = smoke runs)
TP_SIZE=1
EP_SIZE=1
SP_SIZE=1
PP_SIZE=1

# --- Model configuration ------------------------------------------------
N_LAYER=12
N_EMBD=1024
VOCAB_SIZE=50304
BLOCK_SIZE=1024
DROPOUT=0.0                    # keep 0.0: fused attention + sp stay active
POS_EMB="rope"                 # learn | sin | rope

UP_DIM=768
NON_LINEARITY="swiglu"

ATTN="mla"                     # mha | mqa | gqa | mla
N_HEAD=8
N_KV_HEADS=4                   # gqa only
Q_LATENT_DIM=256               # mla only
KV_LATENT_DIM=256              # mla only
ROPE_HEAD_DIM=128              # mla + rope only

MOE=true
MOE_IMPL="scatter"             # dense | scatter (capacity-bounded dispatch)
N_EXP=16
N_SHARED=1
N_ACT=4
AUX_FREE=true
ALPHA=0.0001
GAMMA=0.001
COEFF=0.01

# --- Render and run -----------------------------------------------------
CMD=(python -m distributed_pytorch_tpu
    --dataset "$DATASET"
    --total_batch_size_str "$TOTAL_BATCH_SIZE_STR"
    --batch_size "$BATCH_SIZE"
    --max_iters "$MAX_ITERS"
    --learning_rate "$LEARNING_RATE"
    --warmup_steps "$WARMUP_STEPS"
    --grad_clip "$GRAD_CLIP"
    --eval_interval "$EVAL_INTERVAL"
    --eval_iters "$EVAL_ITERS"
    --file_name "$FILE_NAME"
    --act_recomp_policy "$ACT_RECOMP_POLICY"
    --parallelism "$PARALLELISM"
    --platform "$PLATFORM"
    --tp_size "$TP_SIZE" --ep_size "$EP_SIZE" --sp_size "$SP_SIZE" --pp_size "$PP_SIZE"
    --n_layer "$N_LAYER" --n_embd "$N_EMBD"
    --vocab_size "$VOCAB_SIZE" --block_size "$BLOCK_SIZE"
    --dropout "$DROPOUT" --pos_emb "$POS_EMB"
    --up_dim "$UP_DIM" --non_linearity "$NON_LINEARITY"
    --attn "$ATTN" --n_head "$N_HEAD" --n_kv_heads "$N_KV_HEADS"
    --moe_impl "$MOE_IMPL"
    --n_exp "$N_EXP" --n_shared "$N_SHARED" --n_act "$N_ACT"
    --alpha "$ALPHA" --gamma "$GAMMA" --coeff "$COEFF")

# conditional flags (reference train.sh:79-83 pattern)
[ "$EVAL" = true ] && CMD+=(--eval)
[ "$SAVE_MODEL" = true ] && CMD+=(--save_model)
[ "$ACT_RECOMP" = true ] && CMD+=(--act_recomp)
[ "$MOE" = true ] && CMD+=(--moe)
[ "$AUX_FREE" = true ] && CMD+=(--aux_free)
[ "$ATTN" = mla ] && CMD+=(--q_latent_dim "$Q_LATENT_DIM"
                           --kv_latent_dim "$KV_LATENT_DIM")
[ "$ATTN" = mla ] && [ "$POS_EMB" = rope ] && \
    CMD+=(--rope_head_dim "$ROPE_HEAD_DIM")

# extra flags win (argparse last-wins): bash scripts/train.sh --max_iters 10
CMD+=("$@")

echo "+ ${CMD[*]}"
exec "${CMD[@]}"
