#!/usr/bin/env bash
# Build the in-image real-text corpus (air-gapped stand-in for
# tinyshakespeare: concatenated English docs from site-packages +
# /usr/share/common-licenses) and tokenize it byte-level into
# data/realtext/{train,val}.bin. Zero-egress images can't fetch the
# reference's corpus URL (data/shakespeare/prepare.py:7-36); the
# prepare script's --input path exists for exactly this.
set -euo pipefail
SP=$(python -c "import site; print(site.getsitepackages()[0])")
OUT=${1:-data/realtext}
TMP=$(mktemp)
{ find "$SP" \( -name "*.md" -o -name "*.rst" -o -name "METADATA" \) -print0 2>/dev/null | sort -z | xargs -0 cat 2>/dev/null
  cat /usr/share/common-licenses/* 2>/dev/null; } | tr -d '\r' > "$TMP"
python -m distributed_pytorch_tpu.data.prepare_shakespeare \
    --input "$TMP" --tokenizer byte --out_dir "$OUT"
rm -f "$TMP"
