#!/bin/bash
# Multi-host (TPU pod / multi-slice) launcher.
#
# There is no torchrun on TPU: every host runs the SAME command and the
# processes rendezvous through jax.distributed.initialize() (see
# train/loop.py maybe_initialize_distributed — env-var gated, called
# before any backend probe). On Cloud TPU VMs the coordinator/process
# topology is auto-discovered from the TPU metadata, so plain
#     bash scripts/train_pod.sh            # on every host
# is enough. Off-TPU (CPU fleets, manual clusters) set the three envs:
#     JAX_COORDINATOR_ADDRESS=host0:1234 \
#     JAX_NUM_PROCESSES=4 JAX_PROCESS_ID=$i bash scripts/train_pod.sh
#
# Replaces reference multi-gpu/ddp/train.sh:49's
# `torchrun --standalone --nproc_per_node=N train.py ...` (single-node
# only); this one scales to multi-host, which the reference names as
# future work (README.md:12).
set -euo pipefail
cd "$(dirname "$0")/.."

# On Cloud TPU pods these are injected by the runtime; exporting an
# explicit trio here also works for manual bring-up.
export JAX_COORDINATOR_ADDRESS="${JAX_COORDINATOR_ADDRESS:-}"
export JAX_NUM_PROCESSES="${JAX_NUM_PROCESSES:-}"
export JAX_PROCESS_ID="${JAX_PROCESS_ID:-}"

# --- north-star config: FSDP GPT-124M on tinystories (BASELINE.json) ----
PARALLELISM="fsdp"
DATASET='tinystories'
TOTAL_BATCH_SIZE_STR="2**19"   # 0.5M tokens/step across the pod
BATCH_SIZE=8                   # micro-batch sequences PER HOST's devices
MAX_ITERS=20000
LEARNING_RATE=6e-4
WARMUP_STEPS=700
EVAL=true
EVAL_INTERVAL=250
EVAL_ITERS=20
SAVE_MODEL=true
FILE_NAME="gpt124m_fsdp"
CKPT_INTERVAL=1000             # mid-run checkpoints -> resumable

N_LAYER=12
N_EMBD=768
VOCAB_SIZE=50304
BLOCK_SIZE=1024
POS_EMB="rope"
UP_DIM=2048                    # swiglu 2/3 scaling: a true ~124M (config.flagship_gpt124m)
NON_LINEARITY="swiglu"
ATTN="mha"
N_HEAD=12

CMD=(python -m distributed_pytorch_tpu
    --parallelism "$PARALLELISM"
    --dataset "$DATASET"
    --total_batch_size_str "$TOTAL_BATCH_SIZE_STR"
    --batch_size "$BATCH_SIZE"
    --max_iters "$MAX_ITERS"
    --learning_rate "$LEARNING_RATE"
    --warmup_steps "$WARMUP_STEPS"
    --eval_interval "$EVAL_INTERVAL"
    --eval_iters "$EVAL_ITERS"
    --file_name "$FILE_NAME"
    --ckpt_interval "$CKPT_INTERVAL"
    --n_layer "$N_LAYER" --n_embd "$N_EMBD"
    --vocab_size "$VOCAB_SIZE" --block_size "$BLOCK_SIZE"
    --pos_emb "$POS_EMB" --up_dim "$UP_DIM"
    --non_linearity "$NON_LINEARITY"
    --attn "$ATTN" --n_head "$N_HEAD")
[ "$EVAL" = true ] && CMD+=(--eval)
[ "$SAVE_MODEL" = true ] && CMD+=(--save_model)

# extra flags win (argparse last-wins)
CMD+=("$@")

echo "+ ${CMD[*]}"
exec "${CMD[@]}"
