#!/usr/bin/env bash
# Pod-scale 7B launcher (round 23): supervisor-fronted rows of the
# gpt2_7b recipe grid — pp (interleaved-1F1B) x fsdp x fsdp_tp —
# mirroring hw_window.sh conventions (timeout-capped legs, tee'd logs,
# one timestamped capture dir).
#
# There is no torchrun on TPU, and since round 13 there is no bare
# worker either: the elastic supervisor (train/supervisor.py) spawns one
# worker per host slot, wires the JAX_* rendezvous env (fresh
# coordinator port per gang incarnation), and survives a mid-run host
# loss by gang-restarting from the last verified checkpoint.
#
# Two kinds of rows, because ZeRO-Offload (train/offload.py) is
# single-controller — the host update needs ONE process owning the whole
# mesh, so it applies on a v5e-8 (one host, 8 chips) and not across a
# DCN gang (resolve_offload fails loudly on OFFLOAD=on multi-process):
#   pp, fsdp  — single-controller v5e-8 rungs, OFFLOAD=on: the only way
#               7B prices under 16 GiB/chip on 8 chips (memplan:
#               fsdp 15.60 DNF -> 12.09 offloaded; pp pipe=8 17.81 DNF
#               -> 12.75 offloaded)
#   fsdp_tp   — the multi-host scale-out row, HOSTS x 4 chips, in-HBM
#               moments: capacity comes from more chips (12.75 GiB at
#               16 devices without offload)
# Run on the coordinator node:
#     bash scripts/train_pod.sh                      # all rows, HOSTS=4
#     ROWS=fsdp_tp HOSTS=8 bash scripts/train_pod.sh # one row, bigger gang
# CPU bring-up (no TPU attached): CPU_DEVICES=1 PLATFORM=cpu and the
# same command drives the 2-process smoke CI runs under tier1.yml.
#
# Each row is gated by its memplan pricing first — at the same mesh axes
# and offload mode the worker will actually use — so a row that fails
# the plan is skipped loudly instead of discovered 40 minutes into
# compile.
set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p pod_capture
TS=$(date -u +%m%d_%H%M)

HOSTS="${HOSTS:-4}"
ROWS="${ROWS:-pp fsdp fsdp_tp}"
PLATFORM="${PLATFORM:-auto}"
CPU_DEVICES="${CPU_DEVICES:-0}"
MAX_ITERS="${MAX_ITERS:-20000}"
LEG_TIMEOUT="${LEG_TIMEOUT:-14400}"

# shared 7B worker argv: preset seeds the model block; 2**19 tokens/step,
# micro-batch 1/device with block remat (memplan's fit point for
# 16 GiB/chip)
COMMON=(--preset gpt2_7b
    --dataset tinystories
    --platform "$PLATFORM"
    --total_batch_size_str "2**19"
    --batch_size 1
    --max_iters "$MAX_ITERS"
    --learning_rate 3e-4 --warmup_steps 2000
    --ckpt_interval 1000
    --act_recomp --act_recomp_policy block
    --eval --eval_interval 500 --eval_iters 10)

echo "[train_pod] 7B rung at $TS: rows='$ROWS' hosts=$HOSTS" \
    | tee "pod_capture/pod_${TS}.txt"

for ROW in $ROWS; do
    # pp runs pipe=8: at pipe=4 the per-stage fp32 grad accumulators
    # (not dp-sharded under pp) overshoot 16 GiB/chip by ~1 GiB even
    # with the moments offloaded — memplan prices 16.05 vs 12.75 GiB.
    case "$ROW" in
        pp)      FLAGS=(--parallelism pp --pp_size 8 --pp_schedule 1f1b)
                 PLAN=(--pp-size 8 --offload)
                 ROW_HOSTS=1 ROW_DEVS=8 ROW_OFFLOAD=on ;;
        fsdp)    FLAGS=(--parallelism fsdp)
                 PLAN=(--offload)
                 ROW_HOSTS=1 ROW_DEVS=8 ROW_OFFLOAD=on ;;
        fsdp_tp) FLAGS=(--parallelism fsdp_tp --tp_size 4)
                 PLAN=(--tp-size 4)
                 ROW_HOSTS=$HOSTS ROW_DEVS=$((HOSTS * 4)) ROW_OFFLOAD=auto ;;
        *) echo "[train_pod] unknown row '$ROW' (pp|fsdp|fsdp_tp)"; exit 2 ;;
    esac
    RUN="gpt2_7b_${ROW}"

    # 1) price the row before burning the reservation (rc=1 -> skip);
    #    the gate sees the same mesh axes and offload mode the worker
    #    will use
    if ! python -m distributed_pytorch_tpu.train.memplan \
            --preset gpt2_7b --recipe "$ROW" --devices "$ROW_DEVS" \
            ${PLAN[@]+"${PLAN[@]}"} \
            2>&1 | tee "pod_capture/memplan_${ROW}_${TS}.log"
    then
        echo "[train_pod] row $ROW does not price under HBM — skipped"
        continue
    fi

    # 2) the supervised run: gang of $ROW_HOSTS workers, elastic restart
    #    on host loss, AOT prewarm skipped automatically under offload
    SUP=(python -m distributed_pytorch_tpu.train.supervisor
        --hosts "$ROW_HOSTS" --run-name "$RUN")
    [ "$CPU_DEVICES" -gt 0 ] && SUP+=(--cpu-devices "$CPU_DEVICES")
    CMD=(env OFFLOAD="$ROW_OFFLOAD"
        "${SUP[@]}" -- "${COMMON[@]}" "${FLAGS[@]}" --file_name "$RUN")
    echo "+ ${CMD[*]}" | tee -a "pod_capture/pod_${TS}.txt"
    timeout "$LEG_TIMEOUT" "${CMD[@]}" \
        2>&1 | tee "pod_capture/${RUN}_${TS}.log"
    echo "[train_pod] row $ROW rc=$? -> pod_capture/${RUN}_${TS}.log" \
        | tee -a "pod_capture/pod_${TS}.txt"
done
echo "[train_pod] capture complete: pod_capture/pod_${TS}.txt"
