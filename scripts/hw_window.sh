#!/usr/bin/env bash
# One-shot hardware-window capture (round 5): the TPU tunnel comes and goes
# on hour timescales, so the moment a probe succeeds this script grabs, in
# priority order, everything the round needs from real silicon:
#   1. bench.py            — the headline MFU number (its mini-sweep already
#                            A/Bs flash/slab/streaming-CE legs plus the
#                            decode/serve bundle: flash-vs-naive, int8,
#                            paged-prefix serve_load_prefix, the round-12
#                            serve_load_chunked chunk-size sweep —
#                            BENCH_PREFILL_CHUNK 128/256/512 vs the wave
#                            baseline — and the round-20 serve_load_spec
#                            leg: speculative decoding BENCH_SPEC_K 2/4
#                            vs the spec-off baseline on the same seeded
#                            arrivals, and the round-21 serve_load_tier
#                            leg: host-RAM KV tier on/off with the HBM
#                            pool clamped to 0.1x working set, same
#                            seeded arrivals, and the round-22
#                            serve_spinup leg: replica start->first-token
#                            cold vs warmed from the AOT program store
#                            plus the train restart sub-leg
#                            (warm-faster / hit-rate-1 / greedy-parity
#                            accept booleans), and the round-24
#                            serve_load_classes leg: two-tenant two-class
#                            control-plane drive — interactive-SLO /
#                            lossless-batch-preempt / hot-tenant-capped
#                            accept booleans plus a fleetsim autoscale
#                            A/B; worst case ~75 min if the tunnel
#                            goes half-up mid-bench, so the cap is 90 min —
#                            bench always prints its JSON line if allowed
#                            to finish)
#   2. mfu_sweep blocks    — the flash block/layout/CE ablation inside the
#                            real train step (decides the dispatch default)
#   3. profile_step        — per-op device-time table of the best config
# Everything lands under hw_capture/ for analysis + PERF.md.
set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p hw_capture
TS=$(date -u +%m%d_%H%M)
echo "[hw_window] TPU window open at $TS" | tee hw_capture/last_window.txt

timeout 5400 python bench.py \
    > "hw_capture/bench_$TS.json" 2> "hw_capture/bench_$TS.log"
echo "[hw_window] bench rc=$? -> hw_capture/bench_$TS.json"
tail -c 400 "hw_capture/bench_$TS.json" || true

timeout 4500 python scripts/mfu_sweep.py --variants blocks --iters 8 \
    2>&1 | tee "hw_capture/sweep_$TS.log"
echo "[hw_window] sweep rc=$?"

# profile BOTH kernel paths (pallas first — it is the one the round ships
# if the sweep says it wins; xla is the round-4 baseline for comparison)
timeout 900 python scripts/profile_step.py --batch 16 --attn pallas \
    --trace_dir "hw_capture/trace_${TS}_pallas" \
    2>&1 | tee "hw_capture/profile_${TS}_pallas.log"
timeout 900 python scripts/profile_step.py --batch 16 --attn xla \
    --trace_dir "hw_capture/trace_${TS}_xla" \
    2>&1 | tee "hw_capture/profile_${TS}_xla.log"
echo "[hw_window] profiles done; capture complete"
