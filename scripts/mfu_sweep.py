"""MFU ablation sweep on the flagship bench config (round-4 VERDICT #1).

Times the jitted train_step in isolation (device-resident data, no host
loop) across the tuning axes the verdict names: batch size, attention
implementation, activation recomputation, loss path. Prints one line per
variant: ms/step, tokens/s, MFU, peak HBM.

Usage:  python scripts/mfu_sweep.py [--iters 8] [--variants all|quick]
"""

from __future__ import annotations

import argparse
import itertools
import os
import sys
import time

# runnable as `python scripts/mfu_sweep.py` without an installed package or
# PYTHONPATH: the repo root owns `distributed_pytorch_tpu`
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from distributed_pytorch_tpu.config import LLMConfig, TrainConfig
from distributed_pytorch_tpu.train import metrics as M
from distributed_pytorch_tpu.train.state import create_train_state
from distributed_pytorch_tpu.train.step import make_train_step


def _time_decode(slots: int, iters: int) -> dict:
    """Isolated fused decode step (round 8): `slots` sequences advance one
    token against a half-full slot cache. Decode is memory-bound, so the
    utilization column is MBU — bytes-moved model (params read once per
    step + valid KV rows, train/metrics.decode_step_bytes) over the chip's
    peak HBM bandwidth — printed where the train variants print MFU.
    FLASH_DECODE / FLASH_DECODE_BLOCK env knobs A/B the split-KV kernel
    against the naive einsum path per subprocess; SWEEP_CACHE_DTYPE=int8 /
    SWEEP_QUANT_W=1 add the round-9 quantized columns (int8 KV cache with
    in-kernel dequant, weight-only int8 matmuls) with the MBU bytes priced
    at the true itemsizes."""
    import contextlib

    import jax.numpy as jnp

    from distributed_pytorch_tpu.config import PRESETS
    from distributed_pytorch_tpu.models.gpt import LLM, init_cache
    from distributed_pytorch_tpu.ops.quant import (quantize_params,
                                                   use_quantized_params)

    preset = os.environ.get("SWEEP_PRESET", "gpt2_124m")
    cfg = PRESETS[preset]()
    dtype = jnp.bfloat16
    cache_dtype = jnp.int8 \
        if os.environ.get("SWEEP_CACHE_DTYPE", "") == "int8" else dtype
    quant_w = os.environ.get("SWEEP_QUANT_W", "") == "1"
    model = LLM(cfg, compute_dtype=dtype, attn_impl="auto")
    rng = jax.random.PRNGKey(0)
    dummy = jnp.zeros((1, cfg.block_size), jnp.int32)
    variables = jax.jit(model.init)({"params": rng, "dropout": rng},
                                    dummy, dummy)
    qparams = jax.jit(quantize_params)(variables["params"]) \
        if quant_w else None
    S = cfg.block_size
    cache_len = S // 2
    caches = init_cache(cfg, slots, S, dtype=cache_dtype)
    pos = jnp.full((slots,), cache_len, jnp.int32)
    tok = jnp.zeros((slots,), jnp.int32)

    @jax.jit
    def step(variables, caches, tok, pos, qparams):
        ctx = use_quantized_params(qparams) if qparams is not None \
            else contextlib.nullcontext()
        with ctx:
            logits, _, caches = model.apply(variables, tok[:, None], None,
                                            caches, pos, deterministic=True)
        nxt = jnp.argmax(logits[:, -1, :], -1).astype(jnp.int32)
        return caches, nxt, pos + 1

    caches, tok, pos = step(variables, caches, tok, pos, qparams)  # compile
    jax.device_get(tok)
    t0 = time.perf_counter()
    for _ in range(iters):
        caches, tok, pos = step(variables, caches, tok, pos, qparams)
    jax.device_get(tok)  # metrics-fetch sync (see time_variant note)
    dt = (time.perf_counter() - t0) / iters
    dsz = jnp.dtype(dtype).itemsize
    bts = M.decode_step_bytes(cfg, slots, cache_len + iters // 2, dsz,
                              jnp.dtype(cache_dtype).itemsize,
                              quant_weights=quant_w)
    bw = M.peak_hbm_bw_per_chip()
    mbu = bts / dt / bw if bw else float("nan")
    flash = os.environ.get("FLASH_DECODE", "auto")
    blk = os.environ.get("FLASH_DECODE_BLOCK", "512")
    cd = jnp.dtype(cache_dtype).name
    print(f"decode slots={slots:4d} cache={cache_len:5d} flash={flash:4s} "
          f"block={blk:>4s} kv={cd:8s} qw={quant_w!s:5s} | "
          f"{dt * 1e3:7.2f} ms/step | "
          f"{slots / dt:9.0f} tok/s | mbu {mbu:6.2%} | "
          f"{bts / 2 ** 20:6.0f} MiB/step [{preset}]", flush=True)
    return {"decode": True, "slots": slots, "ms": dt * 1e3, "mbu": mbu,
            "flash_decode": flash, "block": blk, "preset": preset,
            "cache_dtype": cd, "quant_w": quant_w}


def time_variant(batch: int, attn_impl: str, act_recomp: bool,
                 loss_impl: str, iters: int) -> dict | None:
    import os as _os
    if _os.environ.get("SWEEP_DECODE"):
        # decode leg: `batch` is the slot count; attn/remat/loss unused
        try:
            return _time_decode(batch, iters)
        except Exception as e:  # noqa: BLE001 — report like train variants
            print(f"decode slots={batch} FAILED: {type(e).__name__}: "
                  f"{str(e)[:120]}", flush=True)
            if any(s in str(e) for s in ("Out of memory", "VMEM", "vmem",
                                         "exceeds available")):
                sys.exit(3)
            return None

    from distributed_pytorch_tpu.config import PRESETS
    # per-subprocess env knobs (like FLASH_BLOCK_*): SWEEP_PRESET picks the
    # ladder rung, SWEEP_RECIPE the parallelism, SWEEP_MOE the MoE dispatch
    # impl (dense|scatter|grouped — swaps the FFN for the bench MoE),
    # SWEEP_EP the 'expert' mesh-axis size (OVERLAP/OVERLAP_RING/GMM_BLOCK_*
    # are read by the ops modules directly)
    preset = _os.environ.get("SWEEP_PRESET", "gpt2_124m")
    recipe = _os.environ.get("SWEEP_RECIPE", "single")
    moe_impl = _os.environ.get("SWEEP_MOE", "")
    ep_size = int(_os.environ.get("SWEEP_EP", "1"))
    pp_size = int(_os.environ.get("SWEEP_PP", "1"))
    cpu_devs = int(_os.environ.get("SWEEP_CPU_DEVICES", "0"))
    if cpu_devs:
        # pipeline legs on a dev box: carve virtual CPU devices so the
        # pipe axis is a real mesh axis (must precede any jax device op)
        from distributed_pytorch_tpu.compat import request_cpu_devices
        request_cpu_devices(cpu_devs)
    moe_kw = {}
    if moe_impl:
        # same MoE shape as bench.py's moe_* legs so the two measure the
        # same model (active params stay 124M-class)
        moe_kw = dict(moe=True, n_exp=8, n_shared=1, n_act=3, up_dim=1024,
                      moe_impl=moe_impl)
    if pp_size > 1:
        # the pipe mesh axis and the model's stacked-stage count are one
        # decision (train/loop.py links them the same way)
        moe_kw["pp_stages"] = pp_size
    if _os.environ.get("SWEEP_TINY") == "1":
        # CPU-provable shape for the pipeline legs: a 124M step takes
        # minutes per iteration on a dev box; the schedule A/B only
        # needs enough layers for vpp=2 chunks, not the real width
        moe_kw.update(n_layer=4, n_embd=256, n_head=4, n_kv_heads=4,
                      up_dim=512)
    model_cfg = PRESETS[preset](act_recomp=act_recomp,
                                act_recomp_policy="attn",
                                loss_impl=loss_impl, **moe_kw)
    n_dev = len(jax.devices()) if recipe != "single" else 1
    train_cfg = TrainConfig(
        dataset="synthetic", total_batch_size=batch * n_dev * 1024,
        batch_size=batch, max_iters=iters, parallelism=recipe,
        attn_impl=attn_impl, ep_size=ep_size, pp_size=pp_size,
        eval=False, save_model=False, save_stats=False,
        compute_dtype="bfloat16")

    try:
        mesh = None
        if recipe != "single":
            from distributed_pytorch_tpu.parallel.mesh import mesh_for
            mesh = mesh_for(recipe, ep_size=ep_size, pp_size=pp_size)
        model, tx, state, state_sh = create_train_state(model_cfg,
                                                        train_cfg, mesh)
        # the sweep honors the OFFLOAD knob the same way the loop's gate
        # does for an explicit 'on' — the 1f1b+offload A/B leg
        from distributed_pytorch_tpu.config import knob
        step = make_train_step(model, tx, model_cfg, train_cfg, mesh,
                               state_sh, offload=knob("OFFLOAD") == "on")
        rng = jax.random.PRNGKey(0)
        x = jax.random.randint(rng, (1, batch * n_dev, 1024), 0, 50304,
                               jnp.int32)
        y = jax.random.randint(rng, (1, batch * n_dev, 1024), 0, 50304,
                               jnp.int32)
        if mesh is not None:
            from jax.sharding import NamedSharding
            from distributed_pytorch_tpu.parallel import sharding as shd
            bsh = NamedSharding(mesh, shd.batch_pspec(recipe, mesh,
                                                      leading_accum=True))
            x = jax.device_put(x, bsh)
            y = jax.device_put(y, bsh)
        state, m = step(state, x, y)       # compile + warmup
        jax.device_get(m)
        # Sync via device_get of the step metrics, exactly like the trainer's
        # log-boundary sync (train/loop.py). Through the axon tunnel,
        # block_until_ready is NOT a reliable fence — a dispatch-only loop
        # timed ~2.5 ms/step (2600% "MFU"); fetching the metric values is.
        t0 = time.perf_counter()
        for _ in range(iters):
            state, m = step(state, x, y)
        jax.device_get(m)
        times = [(time.perf_counter() - t0) / iters]
    except Exception as e:  # OOM etc.
        print(f"batch={batch:3d} attn={attn_impl:6s} remat={act_recomp!s:5s} "
              f"loss={loss_impl:9s} FAILED: {type(e).__name__}: "
              f"{str(e)[:120]}", flush=True)
        # Deterministic failures (OOM, VMEM-exceeded Mosaic compiles) get a
        # distinct exit code so the parent doesn't burn a retry on a variant
        # that can never succeed — retries are for transient tunnel HTTP 500s.
        # bare RESOURCE_EXHAUSTED is NOT in this list: gRPC uses it for
        # transient tunnel quota/backpressure too — device OOM always says
        # "Out of memory" in its message
        msg = str(e)
        if any(s in msg for s in ("Out of memory", "VMEM", "vmem",
                                  "exceeds available")):
            sys.exit(3)
        return None

    dt = float(np.median(times))
    tokens = batch * n_dev * 1024
    flops = M.step_flops(model_cfg, tokens, 1024)
    peak = M.peak_flops_per_chip()
    mfu = flops / dt / (peak * n_dev) if peak else float("nan")
    hbm = M.device_memory_gb()
    # memplan predicted-vs-measured (ISSUE 10): price this exact variant
    # (batch/remat/recipe) and put the peak_bytes_in_use delta next to
    # the MFU column — the ladder sweep IS the ROADMAP's "validate
    # train/memplan.py against peak_bytes_in_use" instrument
    from distributed_pytorch_tpu.train import memplan
    try:
        mesh_sizes = dict(zip(mesh.axis_names, mesh.devices.shape)) \
            if mesh is not None else {}
        predicted, _ = memplan.predicted_train_peak_gb(
            model_cfg, train_cfg, mesh_sizes)
        predicted = round(predicted, 3)
    except Exception:  # noqa: BLE001 — the plan must never sink a variant
        predicted = None
    plan_delta = round(hbm - predicted, 3) \
        if (hbm is not None and predicted is not None) else None
    tag = "" if (preset, recipe) == ("gpt2_124m", "single") \
        else f" [{preset}/{recipe}]"
    if plan_delta is not None:
        tag += f" [plan {predicted:.2f}GB Δ{plan_delta:+.2f}]"
    if moe_impl:
        # MFU counts active-expert FLOPs; the overcompute factor says how
        # much the dispatch overspends delivering them (dense E/k x,
        # scatter ~cf x, grouped ~1 x — train/metrics.py)
        tag += (f" [moe={moe_impl} "
                f"overcompute={M.moe_overcompute_factor(model_cfg):.2f}x]")
    print(f"batch={batch:3d} attn={attn_impl:6s} remat={act_recomp!s:5s} "
          f"loss={loss_impl:9s} | {dt * 1e3:7.1f} ms | "
          f"{tokens / dt:9.0f} tok/s | mfu {mfu:6.2%} | "
          f"hbm {hbm or 0:5.2f}GB{tag}",
          flush=True)
    out = {"batch": batch, "attn": attn_impl, "remat": act_recomp,
           "loss": loss_impl, "ms": dt * 1e3, "mfu": mfu,
           "preset": preset, "recipe": recipe,
           "moe_impl": moe_impl or None,
           "memplan_predicted_gb": predicted, "measured_peak_gb": hbm,
           "memplan_delta_gb": plan_delta}
    # persist the variant as one train_timeline.jsonl record under
    # runs/ (the round-14 artifact convention: every leg's JSON points
    # at its on-disk timeline via "artifacts")
    try:
        from distributed_pytorch_tpu.obs.flight import FlightRecorder
        leg = (f"mfu_sweep/{preset}_{recipe}_b{batch}_{attn_impl}"
               f"_{'remat' if act_recomp else 'norem'}_{loss_impl}")
        fl = FlightRecorder(capacity=8)
        fl.record(**{k: v for k, v in out.items() if v is not None})
        out["artifacts"] = {"train_timeline": fl.dump_jsonl(
            os.path.join("runs", leg, "train_timeline.jsonl"))}
    except Exception:  # noqa: BLE001 — artifacts never sink the variant
        pass
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--variants", default="quick")
    ap.add_argument("--one", default=None,
                    help="internal: run ONE variant 'batch,attn,remat,loss' "
                         "in this process and exit")
    args = ap.parse_args()

    if args.one:
        b, a, r, l = args.one.split(",")
        ok = time_variant(int(b), a, r == "True", l, args.iters)
        sys.exit(0 if ok else 1)

    print(f"device: {jax.devices()[0].device_kind}, "
          f"backend: {jax.default_backend()}", flush=True)

    if args.variants == "quick":
        grid = [
            (16, "xla", False, "fused"),      # round-3 bench config + fused CE
            (16, "xla", False, "unchunked"),  # round-3 baseline
            (16, "pallas", False, "fused"),
            (32, "xla", False, "fused"),
            (32, "pallas", False, "fused"),
            (32, "xla", True, "fused"),
            (64, "pallas", True, "fused"),
            (64, "xla", True, "fused"),
        ]
    elif args.variants == "blocks":
        # flash-kernel block-size ablation inside the REAL train step (the
        # profile shows XLA attention burns ~150ms/step materializing f32
        # scores; this decides whether the in-house kernel replaces it and
        # at which tile size). FLASH_BLOCK_* is read by ops/flash_attention
        # at import, so each subprocess gets its own value.
        grid = [
            (16, "xla", False, "fused"),
            (16, "pallas", False, "fused", {"FLASH_BLOCK_Q": "128",
                                            "FLASH_BLOCK_K": "128"}),
            (16, "pallas", False, "fused", {"FLASH_BLOCK_Q": "256",
                                            "FLASH_BLOCK_K": "256"}),
            (16, "pallas", False, "fused", {"FLASH_BLOCK_Q": "256",
                                            "FLASH_BLOCK_K": "512"}),
            (16, "pallas", False, "fused", {"FLASH_BLOCK_Q": "512",
                                            "FLASH_BLOCK_K": "512"}),
            (16, "pallas", False, "fused", {"FLASH_BLOCK_Q": "512",
                                            "FLASH_BLOCK_K": "1024"}),
            (32, "pallas", False, "fused", {"FLASH_BLOCK_Q": "256",
                                            "FLASH_BLOCK_K": "512"}),
            (32, "pallas", False, "fused", {"FLASH_BLOCK_Q": "512",
                                            "FLASH_BLOCK_K": "512"}),
            # row-group (B*H flattened) blocking: grid steps / block_h
            (16, "pallas", False, "fused", {"FLASH_BLOCK_Q": "256",
                                            "FLASH_BLOCK_K": "512",
                                            "FLASH_BLOCK_H": "1"}),
            (16, "pallas", False, "fused", {"FLASH_BLOCK_Q": "256",
                                            "FLASH_BLOCK_K": "512",
                                            "FLASH_BLOCK_H": "24"}),
            # slab kernel layout (round 5): zero HBM transposes — A/B vs
            # the rows layout at the same tiles
            (16, "pallas", False, "fused", {"FLASH_LAYOUT": "slab",
                                            "FLASH_BLOCK_Q": "256",
                                            "FLASH_BLOCK_K": "512"}),
            (16, "pallas", False, "fused", {"FLASH_LAYOUT": "slab",
                                            "FLASH_BLOCK_Q": "512",
                                            "FLASH_BLOCK_K": "512"}),
            (16, "pallas", False, "fused", {"FLASH_LAYOUT": "slab",
                                            "FLASH_BLOCK_Q": "256",
                                            "FLASH_BLOCK_K": "256"}),
            # streaming pallas CE (ops/fused_ce.py) vs the chunked scan
            (16, "xla", False, "pallas"),
            (16, "xla", False, "pallas", {"CE_BLOCK_N": "1024"}),
            (16, "xla", False, "pallas", {"CE_BLOCK_N": "256",
                                          "CE_BLOCK_V": "4096"}),
            (16, "pallas", False, "pallas", {"FLASH_BLOCK_Q": "256",
                                             "FLASH_BLOCK_K": "512"}),
        ]
    elif args.variants == "overlap":
        # collective-matmul A/B on the real sharded train step
        # (ops/collective_matmul.py): GSPMD baseline vs uni/bidir rings vs
        # hoisted gathers is decided by OVERLAP/OVERLAP_RING env, per
        # subprocess. fsdp on every available chip.
        grid = [
            (8, "xla", False, "fused", {"SWEEP_RECIPE": "fsdp"}),
            (8, "xla", False, "fused", {"SWEEP_RECIPE": "fsdp",
                                        "OVERLAP": "on"}),
            (8, "xla", False, "fused", {"SWEEP_RECIPE": "fsdp",
                                        "OVERLAP": "on",
                                        "OVERLAP_RING": "uni"}),
            (16, "pallas", False, "fused", {"SWEEP_RECIPE": "fsdp"}),
            (16, "pallas", False, "fused", {"SWEEP_RECIPE": "fsdp",
                                            "OVERLAP": "on"}),
        ]
    elif args.variants == "moe":
        # MOE_IMPL A/B inside the real train step (ISSUE round 7): dense
        # combine vs capacity-scatter vs the dropless grouped kernel, on
        # one chip and under expert parallelism. The first TPU window runs
        # this to self-select the MoE dispatch default (the bench
        # mini-sweep's moe_* legs measure the same matrix end-to-end).
        grid = [
            (16, "xla", False, "fused", {"SWEEP_MOE": "dense"}),
            (16, "xla", False, "fused", {"SWEEP_MOE": "scatter"}),
            (16, "xla", False, "fused", {"SWEEP_MOE": "grouped"}),
            (16, "xla", False, "fused", {"SWEEP_MOE": "grouped",
                                         "GMM_BLOCK_M": "256"}),
            (16, "xla", False, "fused", {"SWEEP_MOE": "grouped",
                                         "GMM_BLOCK_N": "1024"}),
            (16, "xla", False, "fused", {"SWEEP_MOE": "scatter",
                                         "SWEEP_RECIPE": "ep",
                                         "SWEEP_EP": "2"}),
            (16, "xla", False, "fused", {"SWEEP_MOE": "grouped",
                                         "SWEEP_RECIPE": "ep",
                                         "SWEEP_EP": "2"}),
        ]
    elif args.variants == "decode":
        # flash-decode vs naive A/B inside the isolated fused decode step
        # (round 8): slot-count scaling (decode amortizes the weight read
        # over slots), split-KV tile ablation, and a ladder rung. The
        # printed column is MBU (memory-bandwidth utilization), not MFU.
        # Round 9 adds the int8 column next to each bf16 leg: int8 KV
        # (in-kernel dequant), weight-only int8, and both — the
        # quantized-serving A/B that decides the QUANT_* auto defaults.
        D = {"SWEEP_DECODE": "1"}
        I8 = {"SWEEP_CACHE_DTYPE": "int8"}
        grid = [
            (8, "auto", False, "fused", {**D, "FLASH_DECODE": "off"}),
            (8, "auto", False, "fused", {**D, "FLASH_DECODE": "on"}),
            (8, "auto", False, "fused", {**D, **I8, "FLASH_DECODE": "on"}),
            (32, "auto", False, "fused", {**D, "FLASH_DECODE": "off"}),
            (32, "auto", False, "fused", {**D, "FLASH_DECODE": "on"}),
            (32, "auto", False, "fused", {**D, **I8, "FLASH_DECODE": "off"}),
            (32, "auto", False, "fused", {**D, **I8, "FLASH_DECODE": "on"}),
            (32, "auto", False, "fused", {**D, "FLASH_DECODE": "on",
                                          "SWEEP_QUANT_W": "1"}),
            (32, "auto", False, "fused", {**D, **I8, "FLASH_DECODE": "on",
                                          "SWEEP_QUANT_W": "1"}),
            (32, "auto", False, "fused", {**D, "FLASH_DECODE": "on",
                                          "FLASH_DECODE_BLOCK": "256"}),
            (32, "auto", False, "fused", {**D, **I8, "FLASH_DECODE": "on",
                                          "FLASH_DECODE_BLOCK": "256"}),
            (32, "auto", False, "fused", {**D, "FLASH_DECODE": "on",
                                          "FLASH_DECODE_BLOCK": "1024"}),
            (128, "auto", False, "fused", {**D, "FLASH_DECODE": "on"}),
            (128, "auto", False, "fused", {**D, **I8, "FLASH_DECODE": "on",
                                           "SWEEP_QUANT_W": "1"}),
            (8, "auto", False, "fused", {**D, "FLASH_DECODE": "on",
                                         "SWEEP_PRESET": "gpt2_350m"}),
            (8, "auto", False, "fused", {**D, **I8, "FLASH_DECODE": "on",
                                         "SWEEP_QUANT_W": "1",
                                         "SWEEP_PRESET": "gpt2_350m"}),
        ]
    elif args.variants == "pipeline":
        # interleaved-1F1B vs carry vs 1f1b+offload inside the real pp
        # train step (ISSUE 19), on CPU-provable shapes: 2 virtual CPU
        # devices carve a pipe=2 mesh (on a TPU slice the same legs run
        # on real chips and SWEEP_CPU_DEVICES is ignored by the backend).
        # The bubble win itself needs silicon; what this proves anywhere
        # is schedule parity at equal config, the plan-delta column, and
        # the offload split-step cost (PCIe legs on hardware, host
        # round-trip on CPU).
        PP = {"SWEEP_RECIPE": "pp", "SWEEP_PP": "2",
              "SWEEP_CPU_DEVICES": "2", "SWEEP_TINY": "1"}
        grid = [
            (4, "xla", False, "fused", {**PP, "PP_SCHEDULE": "carry"}),
            (4, "xla", False, "fused", {**PP, "PP_SCHEDULE": "1f1b"}),
            (4, "xla", False, "fused", {**PP, "PP_SCHEDULE": "1f1b",
                                        "OFFLOAD": "on"}),
            (8, "xla", True, "fused", {**PP, "PP_SCHEDULE": "carry"}),
            (8, "xla", True, "fused", {**PP, "PP_SCHEDULE": "1f1b"}),
            (8, "xla", True, "fused", {**PP, "PP_SCHEDULE": "1f1b",
                                       "PP_VPP": "2"}),
            (8, "xla", True, "fused", {**PP, "PP_SCHEDULE": "1f1b",
                                       "OFFLOAD": "on"}),
        ]
    elif args.variants == "ladder":
        # the 350M-1.5B rungs (BASELINE.json): batch/remat per the static
        # HBM plan printed by --dryrun; OVERLAP on/off legs for each rung
        grid = [
            (16, "xla", True, "fused", {"SWEEP_PRESET": "gpt2_350m",
                                        "SWEEP_RECIPE": "zero2"}),
            (16, "xla", True, "fused", {"SWEEP_PRESET": "gpt2_350m",
                                        "SWEEP_RECIPE": "zero2",
                                        "OVERLAP": "on"}),
            (8, "xla", True, "fused", {"SWEEP_PRESET": "gpt2_774m",
                                       "SWEEP_RECIPE": "fsdp"}),
            (8, "xla", True, "fused", {"SWEEP_PRESET": "gpt2_774m",
                                       "SWEEP_RECIPE": "fsdp",
                                       "OVERLAP": "on"}),
            (2, "xla", True, "fused", {"SWEEP_PRESET": "gpt2_1p5b",
                                       "SWEEP_RECIPE": "fsdp"}),
            (2, "xla", True, "fused", {"SWEEP_PRESET": "gpt2_1p5b",
                                       "SWEEP_RECIPE": "fsdp",
                                       "OVERLAP": "on"}),
        ]
    else:
        grid = list(itertools.product((16, 32, 64), ("xla", "pallas"),
                                      (False, True), ("fused",)))

    # one subprocess per variant: peak_bytes_in_use is process-monotone, so
    # an in-process loop would report every variant's 'peak HBM' as the max
    # over all PRIOR variants (hiding exactly the remat/batch savings this
    # sweep measures); a variant that OOMs also can't take down the rest
    import os
    import subprocess
    for variant in grid:
        batch, attn, remat, loss = variant[:4]
        extra_env = variant[4] if len(variant) > 4 else {}
        cmd = [sys.executable, __file__, "--iters", str(args.iters),
               "--one", f"{batch},{attn},{remat},{loss}"]
        env = dict(os.environ, **extra_env)
        tag = ",".join(f"{k}={v}" for k, v in extra_env.items())
        if tag:
            print(f"[{tag}]", flush=True)
        # retry once on generic rc!=0: the tunnel's remote-compile service
        # throws transient HTTP 500s (observed on 4/8 variants in one pass).
        # rc=3 (deterministic OOM/VMEM failure, see time_variant) and
        # TIMEOUT are never retried — they fail identically on attempt 2
        # and would double a dead variant's wall-clock.
        for attempt in (1, 2):
            try:
                r = subprocess.run(cmd, timeout=1200, env=env)
                if r.returncode == 0:
                    break
                print(f"variant {batch},{attn},{remat},{loss}: "
                      f"rc={r.returncode} (attempt {attempt})", flush=True)
                if r.returncode == 3:
                    break  # deterministic OOM/VMEM: retrying can't help
            except subprocess.TimeoutExpired:
                print(f"variant {batch},{attn},{remat},{loss}: TIMEOUT "
                      f"(no retry)", flush=True)
                break


if __name__ == "__main__":
    main()
