#!/usr/bin/env python
"""Build the AOT program store ahead of time (ISSUE 18).

Walks a matrix of serving/training configurations and compiles every
program each one can request — the engine legs go through
`serve.__main__.build_engine` (the SAME spin-up path a replica runs, so
the produced keys equal a replica's by construction) and `warm_aot()`
(which walks `enumerate_trace_signatures` + the prefill buckets); the
train legs go through `aot_store.warm_train` (mirroring the loop
preamble). Ends with the manifest cross-check: a signature the store
doesn't cover, or a stale key no engine can request, exits 1.

Intended uses: image build time (bake the store next to the weights so
replica add-to-first-token is weight load, not compile), and the tier-1
CI job that proves a warmed serve smoke runs with aot_store_misses == 0.

    python scripts/aot_warm.py --store runs/aot_store
    python scripts/aot_warm.py --store S \
        --serve-leg "--demo --slots 2 --temperature 0.0" \
        --train-leg "--dataset synthetic --max_iters 2 ..."
"""

import argparse
import json
import os
import shlex
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# The default "demo matrix": the serve smoke's exact engine configs
# (scripts/serve_smoke.sh: --slots 2 --temperature 0.0, wave + chunked)
# and the fault-injection harness's tiny train config — everything the
# CI smokes can spin up warmed.
DEMO_SERVE_LEGS = (
    ("serve/demo/wave", "--demo --slots 2 --temperature 0.0"),
    ("serve/demo/chunked",
     "--demo --slots 2 --temperature 0.0 --prefill-chunk 32"),
)
DEMO_TRAIN_LEGS = (
    ("train/demo/single",
     "--dataset synthetic --platform cpu --parallelism single "
     "--file_name aot_demo --seed 7 --max_iters 2 --log_interval 1 "
     "--total_batch_size_str 64 --batch_size 1 --vocab_size 256 "
     "--block_size 32 --n_embd 32 --n_head 4 --n_kv_heads 2 "
     "--n_layer 2 --up_dim 48"),
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Pre-build the AOT program store for a matrix of "
                    "serve/train configs, then cross-check the "
                    "manifests against the static program enumeration")
    ap.add_argument("--store", required=True, help="store directory")
    ap.add_argument("--serve-leg", action="append", default=[],
                    metavar="ARGS", help="serve CLI args for one engine "
                    "config (repeatable; replaces the demo matrix)")
    ap.add_argument("--train-leg", action="append", default=[],
                    metavar="ARGS", help="train CLI args for one train "
                    "config (repeatable; replaces the demo matrix)")
    ap.add_argument("--skip-train", action="store_true",
                    help="serve legs only")
    ap.add_argument("--no-crosscheck", action="store_true")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="write the warm report ('-'=stdout)")
    args = ap.parse_args(argv)

    from distributed_pytorch_tpu.parallel import aot_store as aot_mod
    from distributed_pytorch_tpu.serve.__main__ import (build_args,
                                                        build_engine)

    serve_legs = ([("serve/cli", leg) for leg in args.serve_leg]
                  or list(DEMO_SERVE_LEGS))
    train_legs = ([("train/cli", leg) for leg in args.train_leg]
                  or list(DEMO_TRAIN_LEGS))
    if args.skip_train:
        train_legs = []

    store = aot_mod.AOTStore(args.store)
    report = {"store": args.store, "legs": []}
    for name, leg in serve_legs:
        t0 = time.perf_counter()
        sargs = build_args(shlex.split(leg) + ["--aot-store", args.store])
        eng, _, _, _ = build_engine(sargs, warm=False)
        # swap in the shared store so one ledger covers the whole matrix
        eng.aot_store = store
        before = (store.hits, store.misses)
        eng.warm_aot(origin="warm")
        report["legs"].append({
            "leg": name, "args": leg,
            "hits": store.hits - before[0],
            "misses": store.misses - before[1],
            "s": round(time.perf_counter() - t0, 2)})
        print(f"[aot_warm] {name}: +{store.misses - before[1]} compiled, "
              f"{store.hits - before[0]} already stored "
              f"({report['legs'][-1]['s']}s)")
    for name, leg in train_legs:
        t0 = time.perf_counter()
        before = (store.hits, store.misses)
        aot_mod.warm_train(store, shlex.split(leg))
        report["legs"].append({
            "leg": name, "args": leg,
            "hits": store.hits - before[0],
            "misses": store.misses - before[1],
            "s": round(time.perf_counter() - t0, 2)})
        print(f"[aot_warm] {name}: +{store.misses - before[1]} compiled, "
              f"{store.hits - before[0]} already stored "
              f"({report['legs'][-1]['s']}s)")

    report["stats"] = store.stats()
    errors = [] if args.no_crosscheck else aot_mod.crosscheck(store)
    report["crosscheck_errors"] = errors
    for e in errors:
        print(f"[aot_warm] crosscheck: {e}", file=sys.stderr)
    print(f"[aot_warm] {report['stats']['entries']} entr(ies), "
          f"{len(errors)} crosscheck error(s), "
          f"compile {report['stats']['compile_ms']:.0f}ms")
    if args.json == "-":
        print(json.dumps(report, indent=1, sort_keys=True))
    elif args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
