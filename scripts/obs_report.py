#!/usr/bin/env python
"""Replay a run dir's timelines into a cost-model report.

    python scripts/obs_report.py runs/<run> [--out DIR] [--json]

Loads every `*.jsonl` under the run dir (engine `timeline.jsonl`,
request `trace.jsonl`, `train_timeline.jsonl`,
`supervisor_timeline.jsonl`, replica-spin-up `spinup.jsonl` —
classified by record shape, so fault-inject log dirs with per-replica
timelines work too), computes per-phase distributions, fits the
PERF.md latency models (incl. the round-22 first-token split
TTFT ≈ load + compile + prefill), and writes `report.md` +
`cost_model.json` next to the inputs (or into --out).

Exit status: 0 on a usable report, 2 when the run dir is degenerate
(no timeline records at all — the CI gate for an empty smoke leg), 1
when the fitted step model misses the OBS_REPORT_MAX_MAE_PCT bar.
Deterministic and device-free: safe on any checkout, no jax import.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from distributed_pytorch_tpu.obs import replay  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("run_dir", help="runs/<run> directory to analyze")
    p.add_argument("--out", default=None,
                   help="artifact dir for report.md/cost_model.json "
                        "(default: the run dir)")
    p.add_argument("--json", action="store_true",
                   help="print the full analysis as one JSON line")
    args = p.parse_args(argv)
    if not os.path.isdir(args.run_dir):
        print(f"obs_report: no such run dir: {args.run_dir}",
              file=sys.stderr)
        return 2
    a = replay.write_report(args.run_dir, out_dir=args.out)
    if args.json:
        print(json.dumps(a, sort_keys=True))
    else:
        print(f"report:     {a['report_md']}")
        print(f"cost model: {a['cost_model_json']}")
        for kind in ("engine", "trace", "train", "supervisor",
                     "spinup"):
            n = len(a["files"][kind])
            if n:
                print(f"  {kind}: {n} file(s)")
        for note in a["notes"]:
            print(f"  warning: {note}")
    if a["degenerate"]:
        print("obs_report: DEGENERATE — no timeline records found",
              file=sys.stderr)
        return 2
    return 1 if a["notes"] else 0


if __name__ == "__main__":
    sys.exit(main())
