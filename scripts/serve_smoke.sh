#!/usr/bin/env bash
# Hermetic serving smoke: start the HTTP front-end on a demo model (no
# checkpoint needed), stream one SSE completion, read /healthz and
# /metrics, shut down — then repeat with chunked prefill enabled
# (--prefill-chunk: <=N prompt tokens fused into each decode step) so
# the chunked path gets an e2e HTTP exercise too. Pass --ckpt <dir> as
# $1/$2 to smoke a real checkpoint instead of the random-init demo
# model.
set -euo pipefail
cd "$(dirname "$0")/.."

PORT="${SERVE_PORT:-8311}"
SRC_ARGS=("--demo")
if [ "${1:-}" = "--ckpt" ]; then
  SRC_ARGS=("--ckpt" "$2")
fi

smoke_one() {  # $@ = extra server args
  python -m distributed_pytorch_tpu.serve "${SRC_ARGS[@]}" \
    --port "$PORT" --slots 2 --max-queue 8 --temperature 0.0 "$@" &
  SERVER_PID=$!
  trap 'kill $SERVER_PID 2>/dev/null || true' EXIT

  for _ in $(seq 1 60); do
    curl -sf "http://127.0.0.1:$PORT/healthz" >/dev/null 2>&1 && break
    sleep 1
  done
  curl -sf "http://127.0.0.1:$PORT/healthz"; echo

  echo "--- SSE stream ---"
  curl -sN -X POST "http://127.0.0.1:$PORT/v1/completions" \
    -d "{\"prompt\": ${PROMPT_JSON:-[1, 2, 3]}, \"max_tokens\": 8}"

  echo "--- /metrics (ttft + lifecycle) ---"
  curl -sf "http://127.0.0.1:$PORT/metrics" \
    | grep -E "${METRICS_GREP:-serve_ttft_seconds_count|serve_requests_total|serve_slot_occupancy}"

  kill $SERVER_PID 2>/dev/null || true
  wait $SERVER_PID 2>/dev/null || true
  trap - EXIT
}

echo "=== wave-prefill smoke ==="
smoke_one

echo "=== chunked-prefill smoke (--prefill-chunk 32) ==="
smoke_one --prefill-chunk 32

# speculative-decoding leg (round 20): one greedy request with the n-gram
# drafter enabled, on a repetitive prompt so the drafter can fire; the
# grep asserts the spec counters and acceptance-rate gauge are live on
# /metrics (their VALUES depend on the random-init demo model — presence
# plus a clean bit-exact stream is the smoke contract).
echo "=== speculative-decoding smoke (SPEC_DECODE=on) ==="
SPEC_DECODE=on SPEC_K=4 \
  PROMPT_JSON='[1, 2, 3, 1, 2, 3, 1, 2]' \
  METRICS_GREP='serve_spec_tokens_total|serve_spec_accepted_token_rate' \
  smoke_one

# AOT program-store leg (round 22): warm the store with scripts/aot_warm
# (the exact wave + chunked demo configs above), then serve each config
# out of it. The warmed server must read EVERY program from the store —
# the grep surfaces the hit/miss ledger, and the assert below pins
# misses == 0 and hits > 0 on both warmed runs (the zero-cold-start
# replica spin-up contract, on the same HTTP smoke path as the cold
# legs above). Demo-only: the matrix aot_warm bakes is the demo one.
if [ "${SRC_ARGS[0]}" = "--demo" ]; then
  echo "=== AOT program-store smoke (warmed spin-up) ==="
  AOT_DIR="$(mktemp -d)"
  trap 'rm -rf "$AOT_DIR"' EXIT
  python scripts/aot_warm.py --store "$AOT_DIR" --skip-train
  aot_leg() {  # $@ = extra server args; asserts hits>0, misses==0
    METRICS_GREP='aot_store' smoke_one --aot-store "$AOT_DIR" "$@" \
      | tee /tmp/aot_smoke_$$.txt
    grep -qE 'aot_store_programs_total\{event="hit"\} [1-9]' \
      /tmp/aot_smoke_$$.txt
    grep -qE 'aot_store_programs_total\{event="miss"\} 0$' \
      /tmp/aot_smoke_$$.txt
  }
  aot_leg
  aot_leg --prefill-chunk 32
  rm -rf "$AOT_DIR" /tmp/aot_smoke_$$.txt
  trap - EXIT
fi

# Router tier: 2 real replica processes behind the health-gated router,
# one SIGKILLed mid-Poisson-drive and replaced on the same port. The
# harness exits nonzero unless every request completed its full budget
# bit-identical to offline greedy OR was explicitly shed (zero silent
# failures), so this leg smoke-proves detection, failover, and rejoin
# end-to-end. Demo replicas only run with token-id prompts on --demo;
# the checkpoint variant smokes the single-server path above instead.
if [ "${SRC_ARGS[0]}" = "--demo" ]; then
  echo "=== router kill-and-replace smoke (2 replicas) ==="
  python scripts/fault_inject.py --replicas 2 --requests 12 \
    --budget-lo 6 --budget-hi 12 --mode kill
fi

echo "serve smoke OK"
