#!/usr/bin/env python
"""AST lint for repo invariants (ISSUE 12) — the failure modes that type
checkers and pyflakes can't see, each of which has bitten a round of this
repo:

* ``host-sync``   — `jax.device_get(...)`, `.item()`, `.tolist()`,
  `float(jnp...)` / `int(jax...)`, and `np.asarray(...)` /
  `np.array(...)` on the traced hot-path modules
  (train/step.py, engine/, models/, ops/). Each forces a
  device->host round trip that serializes the async dispatch pipeline the
  train loop and engine are built around. The deliberate sync boundaries
  (the engine's wave-admit first-token read and step-end token drain)
  carry a `# lint: allow(host-sync)` tag.
* ``wall-clock``  — `time.time()` inside obs/: timelines and span rings
  must be monotonic (an NTP slew mid-run makes wall-clock step durations
  negative). One allowed wall read anchors obs/flight.py's timeline.
* ``env-read``    — `os.environ` reads outside the knob registry
  (config.py ENV_KNOBS): every tunable must be registered so
  `python -m distributed_pytorch_tpu --knobs` shows the full surface and
  typos fail loudly (config.knob raises on unregistered names).
* ``pallas-gate`` — a module that issues `pallas_call` must define a
  `*_usable` capability gate: every kernel needs a declared fallback
  predicate or it crashes on CPU/older TPUs instead of falling back.
* ``knob-docs``   — README's env-knob table (the
  `<!-- knobs:begin -->` block) must byte-match the table generated
  from config.py's ENV_KNOBS registry — names, defaults, and docs; a
  knob added or re-defaulted without `--write-knob-docs` fails CI.

Scoping: walking the package applies each rule only where it means
something (see _rules_for). Explicitly listed files get EVERY rule —
that is how the fixture tests (tests/lint_fixtures/) prove each rule
fires. Suppress a deliberate violation with a trailing
`# lint: allow(<rule>)` comment on the offending line.

Usage::

    python scripts/lint.py                 # lint the package, exit 0/1
    python scripts/lint.py path.py ...     # lint files with ALL rules
    python scripts/lint.py --json          # machine-readable findings
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import os
import re
import sys
from typing import Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "distributed_pytorch_tpu")

RULES = ("host-sync", "wall-clock", "env-read", "pallas-gate")

# modules whose bodies run (mostly) under jit tracing — the host-sync scope
_HOT_PATHS = ("train/step.py", "engine/", "models/", "ops/")
_ALLOW_RE = re.compile(r"#\s*lint:\s*allow\(([^)]*)\)")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    file: str
    line: int
    detail: str

    def __str__(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule}] {self.detail}"


def _rules_for(rel: str) -> set[str]:
    rules: set[str] = set()
    if any(rel == p or (p.endswith("/") and rel.startswith(p))
           for p in _HOT_PATHS):
        rules.add("host-sync")
    if rel.startswith("obs/"):
        rules.add("wall-clock")
    if rel != "config.py":
        rules.add("env-read")
    rules.add("pallas-gate")
    return rules


def _attr_chain(node: ast.AST) -> Optional[str]:
    """'a.b.c' for nested Attribute/Name chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _Visitor(ast.NodeVisitor):
    def __init__(self, rel: str, rules: set[str], src_lines: list[str]):
        self.rel = rel
        self.rules = rules
        self.lines = src_lines
        self.findings: list[Finding] = []
        self.has_pallas: Optional[int] = None   # first pallas_call line
        self.has_usable_gate = False

    def _allowed(self, node: ast.AST, rule: str) -> bool:
        line = self.lines[node.lineno - 1] if \
            node.lineno <= len(self.lines) else ""
        m = _ALLOW_RE.search(line)
        return bool(m and rule in
                    [r.strip() for r in m.group(1).split(",")])

    def _flag(self, node: ast.AST, rule: str, detail: str) -> None:
        if rule in self.rules and not self._allowed(node, rule):
            self.findings.append(Finding(rule, self.rel, node.lineno,
                                         detail))

    # -- defs: pallas-gate bookkeeping ---------------------------------
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if node.name.endswith("_usable"):
            self.has_usable_gate = True
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id.endswith("_usable"):
                self.has_usable_gate = True
        self.generic_visit(node)

    # -- calls ---------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        chain = _attr_chain(node.func)

        if chain and chain.endswith("pallas_call") and \
                self.has_pallas is None:
            self.has_pallas = node.lineno

        if chain in ("jax.device_get", "np.asarray", "numpy.asarray",
                     "np.array", "numpy.array"):
            self._flag(node, "host-sync",
                       f"{chain}() forces a device->host sync on a "
                       f"traced hot path")
        elif isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("item", "tolist") and not node.args \
                and not node.keywords:
            self._flag(node, "host-sync",
                       f".{node.func.attr}() forces a device->host sync "
                       f"on a traced hot path")
        elif isinstance(node.func, ast.Name) and \
                node.func.id in ("float", "int") and len(node.args) == 1:
            arg = node.args[0]
            inner = _attr_chain(arg.func) if isinstance(arg, ast.Call) \
                else None
            if inner and inner.split(".")[0] in ("jax", "jnp"):
                self._flag(node, "host-sync",
                           f"{node.func.id}({inner}(...)) blocks on a "
                           f"device value")

        if chain == "time.time":
            self._flag(node, "wall-clock",
                       "time.time() in obs/ — use time.monotonic()/"
                       "perf_counter() (one anchored wall read allowed "
                       "with a lint tag)")

        if chain in ("os.environ.get", "os.getenv"):
            self._flag(node, "env-read",
                       f"{chain}() bypasses the knob registry — "
                       f"register in config.py and use config.knob()")
        self.generic_visit(node)

    # -- subscripts: os.environ["X"] reads -----------------------------
    def visit_Subscript(self, node: ast.Subscript) -> None:
        if isinstance(node.ctx, ast.Load) and \
                _attr_chain(node.value) == "os.environ":
            self._flag(node, "env-read",
                       "os.environ[...] read bypasses the knob registry "
                       "— register in config.py and use config.knob()")
        self.generic_visit(node)


def lint_file(path: str, rules: Optional[set[str]] = None,
              rel: Optional[str] = None) -> list[Finding]:
    rel = rel if rel is not None else os.path.relpath(path, PKG)
    rules = rules if rules is not None else _rules_for(rel)
    with open(path) as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding("syntax", rel, e.lineno or 0, str(e))]
    v = _Visitor(rel, rules, src.splitlines())
    v.visit(tree)
    if "pallas-gate" in rules and v.has_pallas is not None and \
            not v.has_usable_gate:
        line = v.has_pallas
        src_line = v.lines[line - 1] if line <= len(v.lines) else ""
        if not (_ALLOW_RE.search(src_line) and
                "pallas-gate" in _ALLOW_RE.search(src_line).group(1)):
            v.findings.append(Finding(
                "pallas-gate", rel, line,
                "module issues pallas_call but defines no *_usable "
                "capability gate (kernels need a declared fallback "
                "predicate)"))
    return v.findings


# ---------------------------------------------------------------------------
# knob-docs: README's env-knob table must match config.py's registry
# ---------------------------------------------------------------------------

KNOB_BEGIN = "<!-- knobs:begin -->"
KNOB_END = "<!-- knobs:end -->"
README = os.path.join(REPO, "README.md")


def knob_docs_block() -> str:
    """The generated README table: one row per registered knob (name,
    default, doc), sorted — regenerate with --write-knob-docs."""
    sys.path.insert(0, REPO)
    from distributed_pytorch_tpu import config
    rows = ["| knob | default | what it tunes |", "|---|---|---|"]
    for k in sorted(config.ENV_KNOBS.values(), key=lambda k: k.name):
        doc = k.doc.replace("|", "\\|")   # literal pipes break md cells
        rows.append(f"| `{k.name}` | `{k.default}` | {doc} |")
    return "\n".join([KNOB_BEGIN] + rows + [KNOB_END])


def check_knob_docs(readme: str = README) -> list[Finding]:
    """Doc-drift rule: the README block between the knobs markers must
    equal the table generated from config.ENV_KNOBS — a knob added,
    renamed, or re-defaulted without a doc update fails CI."""
    with open(readme) as f:
        text = f.read()
    rel = os.path.relpath(readme, REPO)
    b, e = text.find(KNOB_BEGIN), text.find(KNOB_END)
    if b < 0 or e < 0:
        return [Finding("knob-docs", rel, 1,
                        f"README has no {KNOB_BEGIN}..{KNOB_END} block — "
                        f"run scripts/lint.py --write-knob-docs")]
    current = text[b:e + len(KNOB_END)]
    want = knob_docs_block()
    if current != want:
        line = text[:b].count("\n") + 1
        cur_rows = set(current.splitlines())
        drift = [r for r in want.splitlines() if r not in cur_rows]
        stale = [r for r in current.splitlines()
                 if r not in set(want.splitlines())]
        detail = ("README knob table drifted from config.ENV_KNOBS "
                  f"({len(drift)} missing/changed, {len(stale)} stale "
                  "row(s)) — run scripts/lint.py --write-knob-docs")
        if drift:
            detail += f"; e.g. missing: {drift[0][:120]}"
        return [Finding("knob-docs", rel, line, detail)]
    return []


def write_knob_docs(readme: str = README) -> bool:
    """Regenerate the README block in place; True if the file changed."""
    with open(readme) as f:
        text = f.read()
    b, e = text.find(KNOB_BEGIN), text.find(KNOB_END)
    if b < 0 or e < 0:
        raise SystemExit(f"{readme}: no {KNOB_BEGIN}..{KNOB_END} block "
                         "to rewrite — add the markers first")
    new = text[:b] + knob_docs_block() + text[e + len(KNOB_END):]
    if new != text:
        with open(readme, "w") as f:
            f.write(new)
        return True
    return False


def lint_package(root: str = PKG) -> list[Finding]:
    findings: list[Finding] = []
    for dirpath, _, files in sorted(os.walk(root)):
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            findings += lint_file(os.path.join(dirpath, name))
    return findings


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python scripts/lint.py",
        description="AST lint for repo invariants (host-sync, wall-clock,"
                    " env-read, pallas-gate)")
    ap.add_argument("files", nargs="*",
                    help="lint these files with EVERY rule; default: "
                    "walk distributed_pytorch_tpu/ with scoped rules")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    ap.add_argument("--write-knob-docs", action="store_true",
                    help="regenerate README's env-knob table from "
                    "config.ENV_KNOBS and exit")
    args = ap.parse_args(argv)

    if args.write_knob_docs:
        changed = write_knob_docs()
        print(f"knob docs: {'rewrote' if changed else 'unchanged'} "
              f"{os.path.relpath(README, REPO)}")
        return 0

    if args.files:
        findings = []
        for f in args.files:
            findings += lint_file(f, rules=set(RULES),
                                  rel=os.path.relpath(f, REPO))
    else:
        findings = lint_package() + check_knob_docs()

    if args.json:
        print(json.dumps({"ok": not findings,
                          "findings": [dataclasses.asdict(f)
                                       for f in findings]}, indent=2))
    else:
        for f in findings:
            print(f)
        n = len(findings)
        scope = f"{len(args.files)} file(s)" if args.files else "package"
        print(f"lint: {scope}, {n} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
