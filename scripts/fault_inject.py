#!/usr/bin/env python
"""Fault-injection harness for the replicated serving tier: spawn N real
replica processes (demo model, greedy), drive seeded Poisson traffic
through the health-gated router, then KILL one replica mid-drive
(SIGKILL — no goodbye) and restart it on the same port. Asserts the
ROADMAP's scale-out exit criteria:

* **zero failed requests**: every submitted request either completes its
  FULL budget or is EXPLICITLY shed (`ShedError` with a cause) — no
  hangs, no truncated streams, no silent drops;
* **failover idempotency**: every completed stream — including the ones
  failed over mid-decode — is bit-identical to an offline greedy run of
  the same engine (gapless, duplicate-free);
* **~linear aggregate throughput** (with --baseline): delivered tok/s
  over N replicas vs the same drive against one.

Modes: `--mode kill` (default) SIGKILLs the victim mid-drive;
`--mode drain` performs a draining restart instead (stop admission, let
slots retire, then replace) and additionally asserts ZERO shed — a
drain must be lossless. `--mode none` is the fault-free control.

Used three ways: standalone (`python scripts/fault_inject.py`), as the
2-replica kill-and-replace leg in scripts/serve_smoke.sh, and by the
bench.py `serve_load_router` leg (`--json` prints one machine-readable
line). Replica subprocesses pin the CPU backend (`--cpu`) so the drive
is tunnel-independent; on a TPU host drop --cpu to place one replica
per chip.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def build_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--replicas", type=int, default=3)
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--requests", type=int, default=48)
    p.add_argument("--prompt-lo", type=int, default=3)
    p.add_argument("--prompt-hi", type=int, default=24)
    p.add_argument("--budget-lo", type=int, default=8)
    p.add_argument("--budget-hi", type=int, default=24)
    p.add_argument("--load", type=float, default=1.2,
                   help="offered load vs the probed aggregate service "
                        "rate (>1 saturates: the queue genuinely fills)")
    p.add_argument("--mode", choices=["kill", "drain", "none"],
                   default="kill")
    p.add_argument("--kill-at-frac", type=float, default=0.3,
                   help="inject the fault after this fraction of "
                        "requests has been submitted")
    p.add_argument("--restart-after-s", type=float, default=1.0)
    p.add_argument("--retry-budget", type=int, default=4)
    p.add_argument("--baseline", action="store_true",
                   help="also drive a single replica (same per-slot "
                        "load) and report the scaling ratio")
    p.add_argument("--no-cpu", dest="cpu", action="store_false",
                   help="let replicas take the default backend (TPU "
                        "when the tunnel is up); default pins CPU")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--timeout-s", type=float, default=420.0)
    p.add_argument("--json", action="store_true",
                   help="print one JSON line (for bench.py) instead of "
                        "the human log")
    p.add_argument("--log-dir", type=str, default="",
                   help="keep replica logs here (default: a tempdir)")
    return p.parse_args(argv)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ReplicaProc:
    """One replica subprocess on a fixed port (fixed so a replacement
    can take over the dead one's address — the router re-probes the
    same name)."""

    def __init__(self, port: int, slots: int, cpu: bool, log_path: str):
        self.port = port
        self.slots = slots
        self.cpu = cpu
        self.log_path = log_path
        self.proc: subprocess.Popen | None = None

    def spawn(self) -> "ReplicaProc":
        cmd = [sys.executable, "-m", "distributed_pytorch_tpu.serve",
               "--demo", "--temperature", "0.0", "--port", str(self.port),
               "--slots", str(self.slots), "--max-queue", "64"]
        if self.cpu:
            cmd.append("--cpu")
        self.log = open(self.log_path, "ab")
        self.proc = subprocess.Popen(cmd, cwd=REPO, stdout=self.log,
                                     stderr=subprocess.STDOUT)
        return self

    def kill(self) -> None:
        """SIGKILL: the replica gets no chance to flush, close, or shed
        — the failure the router must absorb."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.send_signal(signal.SIGKILL)
            self.proc.wait()

    def terminate(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()
        try:
            self.log.close()
        except Exception:
            pass

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self.port}"


async def _healthz(port: int, timeout=2.0) -> tuple[int, dict]:
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection("127.0.0.1", port), timeout)
    try:
        writer.write(b"GET /healthz HTTP/1.1\r\nHost: h\r\n\r\n")
        await writer.drain()
        raw = await asyncio.wait_for(reader.read(), timeout)
    finally:
        writer.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    return int(head.split(b" ")[1]), json.loads(body or b"{}")


async def _fetch_timeline(port: int, path: str, timeout=3.0) -> int:
    """Pull a replica's step-level flight recorder (`GET
    /debug/timeline`) and persist it as JSONL in the log dir — the
    post-hoc record of what the engine was doing around the injected
    fault. Best-effort: returns the entry count (0 on any failure)."""
    try:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection("127.0.0.1", port), timeout)
        try:
            writer.write(b"GET /debug/timeline?n=4096 HTTP/1.1\r\n"
                         b"Host: h\r\n\r\n")
            await writer.drain()
            raw = await asyncio.wait_for(reader.read(), timeout)
        finally:
            writer.close()
        body = json.loads(raw.partition(b"\r\n\r\n")[2] or b"{}")
        entries = body.get("entries", [])
        if entries:
            with open(path, "w") as f:
                for e in entries:
                    f.write(json.dumps(e) + "\n")
        return len(entries)
    except Exception:  # noqa: BLE001 — artifacts never fail the harness
        return 0


async def _wait_up(port: int, timeout_s: float = 120.0) -> None:
    deadline = time.perf_counter() + timeout_s
    while time.perf_counter() < deadline:
        try:
            status, _ = await _healthz(port)
            if status == 200:
                return
        except Exception:
            pass
        await asyncio.sleep(0.25)
    raise TimeoutError(f"replica on :{port} never became healthy")


def _workload(args):
    import numpy as np
    npr = np.random.default_rng(args.seed)
    # demo model: vocab 1024, block 256 — keep prompt+budget well inside
    reqs = [(list(map(int, npr.integers(1, 1024,
                                        int(npr.integers(args.prompt_lo,
                                                         args.prompt_hi))))),
             int(npr.integers(args.budget_lo, args.budget_hi)))
            for _ in range(args.requests)]
    return npr, reqs


async def _probe_rate(router, reqs) -> float:
    """Warm every replica's compile cache and probe delivered tok/s for
    one request — the drive's offered-rate denominator."""
    from distributed_pytorch_tpu.serve.router import Router  # noqa: F401
    names = list(router.replicas)
    tok_s = []
    for name in names:
        # pin the dispatch by excluding everyone else
        exclude = {n for n in names if n != name}
        rep = router.pick(exclude=exclude)
        t0 = time.perf_counter()
        n = 0
        async for ev in router._stream_once(rep, reqs[0][0], 16, None):
            if "token" in ev:
                n += 1
        tok_s.append(n / (time.perf_counter() - t0))
    return sum(tok_s)


async def _drive(router, reqs, arrivals, timeout_s: float):
    """Poisson-submit every request through the router; classify each as
    completed / shed / failed. 'failed' is the criterion the harness
    exists to keep at zero: an exception that is not an explicit shed,
    or a stream that ended without its done event."""
    from distributed_pytorch_tpu.serve.scheduler import ShedError

    async def one(prompt, budget):
        tokens, done = [], None
        async for ev in router.stream(prompt, budget):
            if "token" in ev:
                tokens.append(ev["token"])
            else:
                done = ev
        return tokens, done

    start = time.perf_counter()
    tasks = []
    for (prompt, budget), at in zip(reqs, arrivals):
        delay = start + at - time.perf_counter()
        if delay > 0:
            await asyncio.sleep(delay)
        tasks.append(asyncio.ensure_future(
            asyncio.wait_for(one(prompt, budget), timeout_s)))
    results = await asyncio.gather(*tasks, return_exceptions=True)
    dt = time.perf_counter() - start
    completed, shed, failed = [], [], []
    for i, r in enumerate(results):
        if isinstance(r, ShedError):
            shed.append((i, r.cause))
        elif isinstance(r, BaseException):
            failed.append((i, repr(r)))
        else:
            tokens, done = r
            if done is None or not done.get("done") \
                    or len(tokens) != reqs[i][1]:
                failed.append((i, f"truncated: {len(tokens)}/{reqs[i][1]}"
                                  f" done={done}"))
            else:
                completed.append((i, tokens, done))
    return completed, shed, failed, dt


def _offline_ref(reqs):
    """Bit-exact reference: the SAME demo model the replicas serve, run
    through the offline engine in this process."""
    from distributed_pytorch_tpu.engine import DecodeEngine
    from distributed_pytorch_tpu.serve.__main__ import _demo_model
    model, variables, _, _ = _demo_model()
    eng = DecodeEngine(model, variables, n_slots=4, temperature=0.0)
    return eng.run([p for p, _ in reqs], [b for _, b in reqs])


async def _run_leg(args, n_replicas: int, inject: bool, log_dir: str,
                   tag: str) -> dict:
    from distributed_pytorch_tpu.serve.router import Router

    reps = [ReplicaProc(_free_port(), args.slots, args.cpu,
                        os.path.join(log_dir, f"{tag}_replica{i}.log"))
            .spawn()
            for i in range(n_replicas)]
    victim = reps[-1] if inject else None
    try:
        await asyncio.gather(*(_wait_up(r.port) for r in reps))
        router = Router([r.addr for r in reps],
                        retry_budget=args.retry_budget,
                        probe_interval_s=0.2, fail_threshold=2,
                        backoff_base_s=0.25, backoff_cap_s=2.0,
                        fleet_poll_interval_s=0.2)
        await router.start()

        npr, reqs = _workload(args)
        agg_tok_s = await _probe_rate(router, reqs)
        mean_budget = (args.budget_lo + args.budget_hi) / 2
        rate = args.load * agg_tok_s / mean_budget
        arrivals = list(npr.exponential(1.0 / rate,
                                        size=len(reqs)).cumsum())

        fault_task = None
        if inject:
            k = max(1, int(args.kill_at_frac * len(reqs)))
            fault_at = arrivals[k - 1]

            async def fault():
                await asyncio.sleep(fault_at)
                # land the fault while the victim is mid-stream (streams
                # at these sizes are short; killing between them would
                # test detection but never failover): wait until its own
                # healthz shows live slots, then strike
                deadline = time.perf_counter() + 30
                while time.perf_counter() < deadline:
                    try:
                        _, body = await _healthz(victim.port)
                        if body.get("live_slots", 0) >= 1:
                            break
                    except Exception:
                        break
                    await asyncio.sleep(0.02)
                if args.mode == "drain":
                    await router.drain(victim.addr)
                    # wait for quiescence (healthz reports drained)
                    while True:
                        try:
                            _, body = await _healthz(victim.port)
                            if body.get("drained"):
                                break
                        except Exception:
                            break
                        await asyncio.sleep(0.2)
                victim.kill()
                await asyncio.sleep(args.restart_after_s)
                victim.spawn()                # same port: rejoins by probe

            fault_task = asyncio.ensure_future(fault())

        completed, shed, failed, dt = await _drive(
            router, reqs, arrivals, args.timeout_s)
        if fault_task is not None:
            await fault_task
        snapshot = router.snapshot()
        metrics = router.metrics.summary()
        router._update_slo()   # fold the drive's final counts in before
        # reading the gauges (the probe loop stops with the router)
        slo = router.slo.snapshot()
        fleet_replicas = len(router.fleet_snapshots())
        await router.stop()
        # persist each live replica's step timeline before teardown —
        # the flight-recorder view of the drive (and, on the restarted
        # victim, of the post-rejoin traffic)
        artifacts = {}
        for i, r in enumerate(reps):
            p = os.path.join(log_dir, f"{tag}_replica{i}_timeline.jsonl")
            if await _fetch_timeline(r.port, p):
                artifacts[f"replica{i}_timeline"] = p
    finally:
        for r in reps:
            r.terminate()

    refs = _offline_ref(reqs)
    mismatches = [i for i, tokens, _ in completed
                  if tokens != refs[i][len(reqs[i][0]):]]
    toks_out = sum(len(t) for _, t, _ in completed)
    return {"replicas": n_replicas, "mode": args.mode if inject else
            "none", "requests": len(reqs),
            "completed": len(completed), "shed": len(shed),
            "failed": len(failed), "failed_detail": failed[:5],
            "shed_by_cause": metrics.get("shed_by_cause", {}),
            "parity_mismatches": len(mismatches),
            "failovers": metrics["failovers"],
            "retries": metrics["retries"],
            "replica_down": metrics["replica_down"],
            "replica_up": metrics["replica_up"],
            "tokens_per_sec": round(toks_out / dt, 1),
            "offered_rps": round(rate, 2),
            "probe_agg_tok_s": round(agg_tok_s, 1),
            "drive_s": round(dt, 2),
            "ttft_p50_ms": metrics["ttft"].get("p50_ms"),
            "ttft_p99_ms": metrics["ttft"].get("p99_ms"),
            "itl_p50_ms": metrics["itl"].get("p50_ms"),
            "itl_p99_ms": metrics["itl"].get("p99_ms"),
            "slo": slo,
            "fleet_metrics_replicas": fleet_replicas,
            "artifacts": artifacts,
            "replica_states": snapshot}


async def _amain(args) -> dict:
    log_dir = args.log_dir or os.path.join(
        REPO, "runs", f"fault_inject_{int(time.time())}")
    os.makedirs(log_dir, exist_ok=True)
    out = await _run_leg(args, args.replicas, args.mode != "none",
                         log_dir, "multi")
    if args.baseline:
        base = await _run_leg(args, 1, False, log_dir, "single")
        out["baseline_tokens_per_sec"] = base["tokens_per_sec"]
        out["baseline_shed"] = base["shed"]
        out["baseline_failed"] = base["failed"]
        if base["tokens_per_sec"]:
            out["scaling_x"] = round(
                out["tokens_per_sec"] / base["tokens_per_sec"], 2)
    # the exit criteria: nothing failed, every completed stream
    # bit-identical to offline greedy; a drain must additionally be
    # lossless (no shed at all — admission moved, nothing dropped)
    out["ok"] = (out["failed"] == 0 and out["parity_mismatches"] == 0
                 and (args.mode != "drain" or out["shed"] == 0))
    # SLO criterion (kill only): the mid-stream kill must BURN latency
    # budget — the failover gap is a client-visible >threshold sample —
    # without EXHAUSTING the availability budget (every request still
    # completed or was explicitly shed)
    if args.mode == "kill":
        slo = out.get("slo", {})
        out["slo_latency_burned"] = any(
            max(slo.get(n, {}).get("burn_rate", {"0": 0.0}).values()) > 0
            for n in ("ttft_p99", "itl_p99"))
        out["slo_availability_budget_remaining"] = slo.get(
            "availability", {}).get("budget_remaining", 1.0)
        out["ok"] = (out["ok"] and out["slo_latency_burned"]
                     and out["slo_availability_budget_remaining"] > 0)
    # the router runs IN this process: its dispatch/failover spans (one
    # trace per request, failed-over streams stitched) dump here too
    try:
        from distributed_pytorch_tpu.obs import trace as obs_trace
        rec = obs_trace.get_recorder()
        if len(rec):
            out.setdefault("artifacts", {})["router_trace"] = \
                rec.dump_jsonl(os.path.join(log_dir, "router_trace.jsonl"))
    except Exception:  # noqa: BLE001 — artifacts never fail the harness
        pass
    # replay the drive's artifacts (replica timelines + router trace)
    # into the per-phase report + fitted cost model
    try:
        from distributed_pytorch_tpu.obs import replay
        rep = replay.write_report(log_dir)
        out.setdefault("artifacts", {})["report_md"] = rep["report_md"]
        out["artifacts"]["cost_model_json"] = rep["cost_model_json"]
    except Exception:  # noqa: BLE001 — artifacts never fail the harness
        pass
    # the ~linear-scaling criterion needs a core per replica process +
    # one for the driver; report the host honestly so a 1-core CI box's
    # ~1x never reads as a scaling failure of the router itself
    try:
        out["host_cores"] = len(os.sched_getaffinity(0))
    except AttributeError:  # pragma: no cover — non-Linux
        out["host_cores"] = os.cpu_count() or 1
    out["log_dir"] = log_dir
    return out


def main(argv=None) -> int:
    args = build_args(argv)
    if args.cpu:
        # same live-config pin the replicas use (the offline reference
        # runs in THIS process)
        import jax
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
    out = asyncio.run(_amain(args))
    if args.json:
        print(json.dumps(out))
    else:
        print(f"[fault_inject] mode={out['mode']} replicas="
              f"{out['replicas']} requests={out['requests']}: "
              f"{out['completed']} completed, {out['shed']} shed, "
              f"{out['failed']} FAILED, "
              f"{out['parity_mismatches']} parity mismatches, "
              f"{out['failovers']} failovers, "
              f"{out['tokens_per_sec']} tok/s "
              f"(logs: {out['log_dir']})")
        if "scaling_x" in out:
            print(f"[fault_inject] scaling vs 1 replica: "
                  f"{out['scaling_x']}x "
                  f"({out['baseline_tokens_per_sec']} tok/s single)")
        print(f"[fault_inject] {'OK' if out['ok'] else 'VIOLATION'}")
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
