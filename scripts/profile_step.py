"""Capture + analyze a TPU profile of the flagship train step (VERDICT #1a).

Runs a few steps of the bench config under jax.profiler, then parses the
xplane protobuf with tensorboard_plugin_profile's converter and prints the
op-level time breakdown — no TensorBoard UI needed (this container has no
browser). The output is the evidence for which kernel eats the step.

Usage: python scripts/profile_step.py [--batch 16] [--attn auto] [--remat]
"""

from __future__ import annotations

import argparse
import glob
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def capture(batch: int, attn_impl: str, remat: bool, loss_impl: str,
            trace_dir: str, iters: int = 6) -> None:
    from distributed_pytorch_tpu.config import LLMConfig, TrainConfig
    from distributed_pytorch_tpu.train.state import create_train_state
    from distributed_pytorch_tpu.train.step import make_train_step

    from distributed_pytorch_tpu.config import flagship_gpt124m
    model_cfg = flagship_gpt124m(act_recomp=remat, act_recomp_policy="attn",
                                 loss_impl=loss_impl)
    train_cfg = TrainConfig(
        dataset="synthetic", total_batch_size=batch * 1024,
        batch_size=batch, max_iters=iters, parallelism="single",
        attn_impl=attn_impl, eval=False, save_model=False, save_stats=False,
        compute_dtype="bfloat16")

    model, tx, state, _ = create_train_state(model_cfg, train_cfg)
    step = make_train_step(model, tx, model_cfg, train_cfg, None, None)
    rng = jax.random.PRNGKey(0)
    x = jax.random.randint(rng, (1, batch, 1024), 0, 50304, jnp.int32)
    y = jax.random.randint(rng, (1, batch, 1024), 0, 50304, jnp.int32)
    state, m = step(state, x, y)
    jax.block_until_ready(m)           # compile outside the trace

    with jax.profiler.trace(trace_dir):
        for _ in range(iters):
            state, m = step(state, x, y)
        jax.block_until_ready(m)


def analyze(trace_dir: str, top: int = 25) -> None:
    """Parse the newest xplane.pb and print per-op device time.

    Reads the XSpace proto directly (tensorflow.tsl xplane_pb2 — the
    tensorboard-plugin converter in this image is ABI-mismatched with its
    TF build): for the device plane, aggregate event durations by op name
    on each line and print the busiest line's breakdown."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    xplanes = sorted(glob.glob(os.path.join(
        trace_dir, "plugins/profile/*/*.xplane.pb")))
    assert xplanes, f"no xplane.pb under {trace_dir}"
    space = xplane_pb2.XSpace()
    with open(xplanes[-1], "rb") as f:
        space.ParseFromString(f.read())

    device_planes = [p for p in space.planes
                     if "TPU" in p.name or "/device" in p.name.lower()]
    planes = device_planes or list(space.planes)
    for plane in planes:
        ev_names = {m.id: m.name for m in plane.event_metadata.values()}
        best_line, best_tot = None, 0
        per_line = {}
        for line in plane.lines:
            agg: dict[str, float] = {}
            for ev in line.events:
                name = ev_names.get(ev.metadata_id, "?")
                agg[name] = agg.get(name, 0.0) + ev.duration_ps / 1e6  # us
            tot = sum(agg.values())
            per_line[line.name] = (tot, agg)
            if tot > best_tot:
                best_line, best_tot = line.name, tot
        if not best_line:
            continue
        print(f"\n=== plane {plane.name!r}: busiest line {best_line!r} "
              f"({best_tot / 1e3:.1f} ms total) ===")
        tot, agg = per_line[best_line]
        for name, t in sorted(agg.items(), key=lambda kv: -kv[1])[:top]:
            print(f"{t:12.1f} us  {100 * t / tot:5.1f}%  {name[:90]}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--attn", default="auto")
    ap.add_argument("--remat", action="store_true")
    ap.add_argument("--loss", default="fused")
    ap.add_argument("--trace_dir", default="",
                    help="default: the obs/profile.py convention "
                         "runs/profile_step/profile")
    ap.add_argument("--analyze_only", action="store_true")
    args = ap.parse_args()
    if not args.trace_dir:
        import os as _os
        import sys as _sys
        _sys.path.insert(0, _os.path.dirname(_os.path.dirname(
            _os.path.abspath(__file__))))
        from distributed_pytorch_tpu.obs.profile import profile_dir
        args.trace_dir = profile_dir("profile_step")

    if not args.analyze_only:
        print(f"device: {jax.devices()[0].device_kind}", flush=True)
        capture(args.batch, args.attn, args.remat, args.loss,
                args.trace_dir)
    analyze(args.trace_dir)


if __name__ == "__main__":
    main()
