#!/usr/bin/env python
"""Fault-injection harness for ELASTIC TRAINING (the train-side mirror of
scripts/fault_inject.py): drive a seeded multi-process CPU training run
under the supervisor (train/supervisor.py), SIGKILL a victim worker
mid-run, and assert the ROADMAP's pod-scale exit criteria:

* **run completed** — the supervisor gang-restarts the workers and the
  run reaches max_iters (supervisor exit code 0);
* **zero lost run** — the restarted gang REJOINED from a verified
  checkpoint (it did not silently start over from step 0);
* **bitwise rejoin parity** (`--mode kill`) — the post-rejoin loss
  trajectory is bit-identical to an uninterrupted baseline on the same
  mesh: deterministic step math + the counter-based loader leave no
  trace of the fault in the training math;
* **rung-down re-mesh** (`--mode kill-hold`) — the victim's slot is
  additionally HELD (hold file = "this host is not coming back"), so
  past the deadline the supervisor re-meshes the survivors one dp rung
  down (2 hosts → 1), restores the SAME checkpoint onto the smaller
  mesh, and the leg must resume from the last verified step and
  converge. Bitwise parity is NOT asserted here: a different dp degree
  reorders reductions (tests/test_multihost.py pins that to ~rtol 2e-4).

`--mode none` is the fault-free control. `--json` prints one
machine-readable line (bench/CI); artifacts (supervisor timeline,
worker logs, stats.json) stay under --log-dir.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def build_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--hosts", type=int, default=2)
    p.add_argument("--mode", choices=["kill", "kill-hold", "none"],
                   default="kill")
    p.add_argument("--recipe", choices=["fsdp", "pp"], default="fsdp",
                   help="worker parallelism: fsdp (dp over hosts) or pp "
                        "(interleaved-1F1B pipeline over hosts; kill-hold "
                        "is fsdp-only — a 1-host rung cannot hold a "
                        "2-stage pipe)")
    p.add_argument("--max-iters", type=int, default=40)
    p.add_argument("--ckpt-interval", type=int, default=5)
    p.add_argument("--seed", type=int, default=1729)
    p.add_argument("--remesh-deadline-s", type=float, default=2.0)
    p.add_argument("--timeout-s", type=float, default=600.0)
    p.add_argument("--json", action="store_true",
                   help="print one JSON line (for bench/CI) instead of "
                        "the human log")
    p.add_argument("--log-dir", type=str, default="",
                   help="working dir for checkpoints/runs/logs "
                        "(default: runs/fault_inject_train_<ts>)")
    args = p.parse_args(argv)
    if args.recipe == "pp" and args.mode == "kill-hold":
        p.error("--recipe pp does not support --mode kill-hold (the "
                "rung-down re-mesh shrinks to 1 host, which cannot hold "
                "a 2-stage pipeline)")
    return args


# Tiny model, the tests/test_multihost.py experiment scaled for speed.
# total_batch_size 128 divides both meshes: 2 hosts × 1 device → dp=2,
# grad_accum 2; after the rung-down re-mesh dp=1 → grad_accum 4 — the
# GLOBAL batch (and the counter-based loader's coverage) is unchanged,
# which is exactly why the re-meshed leg continues the same experiment.
def _train_argv(args, run_name: str) -> list[str]:
    recipe = getattr(args, "recipe", "fsdp")
    extra = []
    if recipe == "pp":
        # 2 hosts x 1 device -> pipe=2 (pp_size carves the mesh, the
        # loop links pp_stages to it), one layer per stage, the
        # interleaved-1F1B schedule (models/pipeline.py) — the CI smoke
        # that the gang restart replays the SAME pipeline timeline
        extra = ["--pp_size", "2", "--pp_schedule", "1f1b"]
    return ["--dataset", "synthetic", "--platform", "cpu",
            "--parallelism", recipe, *extra,
            "--file_name", run_name,
            "--seed", str(args.seed),
            "--max_iters", str(args.max_iters),
            "--ckpt_interval", str(args.ckpt_interval),
            "--log_interval", "1",
            "--total_batch_size_str", "128", "--batch_size", "1",
            "--vocab_size", "256", "--block_size", "32",
            "--n_embd", "32", "--n_head", "4", "--n_kv_heads", "2",
            "--n_layer", "2", "--up_dim", "48"]


def _read_json(path: str):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _read_jsonl(path: str) -> list[dict]:
    try:
        with open(path) as f:
            return [json.loads(line) for line in f if line.strip()]
    except (OSError, ValueError):
        return []


def _inject_fault(proc: subprocess.Popen, workdir: str, run_name: str,
                  hold: bool, timeout_s: float) -> dict:
    """Wait until the run has a VERIFIED checkpoint (the supervisor's
    state file reports `resumed_from`), then SIGKILL the highest worker
    slot — mid-run, no goodbye. `hold` additionally marks the slot as
    unrestartable BEFORE the kill, forcing the rung-down path."""
    run_dir = os.path.join(workdir, "runs", run_name)
    state_path = os.path.join(run_dir, "supervisor_state.json")
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"supervisor exited (rc={proc.returncode}) before the "
                f"fault could be injected — raise --max-iters")
        st = _read_json(state_path)
        if st and st.get("status") == "running" and st.get("resumed_from"):
            workers = [w for w in st.get("workers", []) if w.get("alive")]
            if workers:
                victim = max(workers, key=lambda w: w["slot"])
                if hold:
                    # hold BEFORE the kill: the supervisor must observe
                    # the slot as unrestartable when it handles the death
                    with open(os.path.join(
                            run_dir, f"hold_{victim['slot']}"), "w") as f:
                        f.write("fault_inject_train: host is gone\n")
                os.kill(victim["os_pid"], signal.SIGKILL)
                return {"victim_slot": victim["slot"],
                        "victim_pid": victim["os_pid"],
                        "killed_after_ckpt": st["resumed_from"],
                        "generation": st["generation"]}
        time.sleep(0.05)
    raise TimeoutError("no verified checkpoint appeared before the "
                       "injection deadline")


def _run_leg(args, workdir: str, run_name: str, hosts: int,
             inject: str) -> dict:
    """One supervised run; returns {rc, state, timeline, stats, fault}."""
    cmd = [sys.executable, "-m",
           "distributed_pytorch_tpu.train.supervisor",
           "--hosts", str(hosts), "--run-name", run_name,
           "--cpu-devices", "1", "--poll-s", "0.05",
           "--backoff-base-s", "0.2", "--backoff-cap-s", "1.0",
           "--remesh-deadline-s", str(args.remesh_deadline_s),
           "--", *_train_argv(args, run_name)]
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    log_path = os.path.join(workdir, f"{run_name}_supervisor.log")
    with open(log_path, "w") as logf:
        proc = subprocess.Popen(cmd, cwd=workdir, env=env,
                                stdout=logf, stderr=subprocess.STDOUT)
    fault = None
    try:
        if inject != "none":
            fault = _inject_fault(proc, workdir, run_name,
                                  hold=(inject == "kill-hold"),
                                  timeout_s=args.timeout_s)
        rc = proc.wait(timeout=args.timeout_s)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    run_dir = os.path.join(workdir, "runs", run_name)
    return {
        "rc": rc,
        "fault": fault,
        "state": _read_json(os.path.join(run_dir,
                                         "supervisor_state.json")),
        "timeline": _read_jsonl(os.path.join(run_dir,
                                             "supervisor_timeline.jsonl")),
        "stats": _read_json(os.path.join(workdir, "checkpoints", run_name,
                                         "stats.json")),
        "supervisor_log": log_path,
    }


def _replay_report(run_dir: str) -> dict:
    """Deterministic timeline replay (obs/replay.py) over one leg's run
    dir — per-phase distributions + fitted cost model next to the
    timelines it came from. Best-effort: artifacts never fail the
    harness."""
    try:
        from distributed_pytorch_tpu.obs import replay
        rep = replay.write_report(run_dir)
        return {"report_md": rep["report_md"],
                "cost_model_json": rep["cost_model_json"]}
    except Exception:  # noqa: BLE001
        return {}


def main(argv=None) -> int:
    args = build_args(argv)
    workdir = args.log_dir or os.path.join(
        REPO, "runs", f"fault_inject_train_{int(time.time())}")
    os.makedirs(workdir, exist_ok=True)

    # Baseline: the SAME experiment (same mesh, same seed) uninterrupted.
    base = _run_leg(args, workdir, "baseline", args.hosts, inject="none")
    base_losses = (base["stats"] or {}).get("train_losses") or []

    out = {"mode": args.mode, "hosts": args.hosts,
           "recipe": args.recipe,
           "max_iters": args.max_iters,
           "ckpt_interval": args.ckpt_interval,
           "baseline_completed": base["rc"] == 0,
           "baseline_iters": len(base_losses),
           "log_dir": workdir}

    out["baseline_report"] = _replay_report(
        os.path.join(workdir, "runs", "baseline"))

    if args.mode == "none":
        out["run_completed"] = base["rc"] == 0
        out["ok"] = out["run_completed"] and len(base_losses) > 0
    else:
        leg = _run_leg(args, workdir, "faulted", args.hosts,
                       inject=args.mode)
        out["faulted_report"] = _replay_report(
            os.path.join(workdir, "runs", "faulted"))
        losses = (leg["stats"] or {}).get("train_losses") or []
        state = leg["state"] or {}
        events = {e.get("event") for e in leg["timeline"]}
        n = len(losses)
        out["fault"] = leg["fault"]
        out["supervisor_rc"] = leg["rc"]
        out["events"] = sorted(events)
        out["run_completed"] = leg["rc"] == 0 \
            and state.get("status") == "completed"
        # the final stats.json is written by the post-fault incarnation:
        # a non-empty loss list SHORTER than the baseline's proves the
        # gang rejoined mid-run from a checkpoint, not from step 0
        out["resume_iters"] = n
        out["zero_lost_run"] = (out["run_completed"] and 0 < n
                                and n < len(base_losses)
                                and state.get("resumed_from") is not None)
        if args.mode == "kill":
            # same mesh before/after the gang restart → the rejoined
            # trajectory must be BIT-IDENTICAL to the baseline's tail
            out["rejoin_loss_bitwise_parity"] = (
                out["zero_lost_run"] and base_losses[-n:] == losses)
            out["ok"] = (out["run_completed"] and out["zero_lost_run"]
                         and out["rejoin_loss_bitwise_parity"])
        else:  # kill-hold → rung-down re-mesh
            remesh = [e for e in leg["timeline"]
                      if e.get("event") == "remesh"]
            out["remeshed"] = (len(remesh) == 1
                               and state.get("n_hosts")
                               == remesh[0].get("new_n"))
            out["remesh"] = remesh[0] if remesh else None
            out["resumed_from_verified"] = bool(
                remesh and remesh[0].get("resumed_from"))
            final = losses[-1] if losses else None
            out["final_loss"] = final
            # a different dp degree reorders reductions — assert the leg
            # CONVERGES (finite, below the run's starting loss), not bits
            out["converged"] = (final is not None and final == final
                                and base_losses
                                and final < base_losses[0])
            out["ok"] = (out["run_completed"] and out["zero_lost_run"]
                         and out["remeshed"]
                         and out["resumed_from_verified"]
                         and out["converged"])

    try:
        out["host_cores"] = len(os.sched_getaffinity(0))
    except AttributeError:  # pragma: no cover — non-Linux
        out["host_cores"] = os.cpu_count() or 1

    if args.json:
        print(json.dumps(out))
    else:
        keys = [k for k in ("run_completed", "zero_lost_run",
                            "rejoin_loss_bitwise_parity", "remeshed",
                            "resumed_from_verified", "converged")
                if k in out]
        flags = " ".join(f"{k}={out[k]}" for k in keys)
        print(f"[fault_inject_train] mode={args.mode} hosts={args.hosts} "
              f"iters={args.max_iters}: {flags} (artifacts: {workdir})")
        print(f"[fault_inject_train] {'OK' if out['ok'] else 'VIOLATION'}")
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
