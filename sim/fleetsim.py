"""Seeded discrete-event fleet simulator for the serving control plane.

Purpose: prove the control-plane POLICIES (serve/control.py) at a scale
no CPU test rig can reach — hundreds of simulated replicas, millions of
simulated requests — before they meet real traffic. The simulator is
evidence about the deployed policy, not a fork of it:

* the policy objects are the LIVE classes — `TokenBucketFairness`,
  `ClassPolicy`, `Autoscaler` from serve/control.py and `SLOTracker`
  from obs/slo.py — driven through their injected `now_fn` clocks by
  the event heap. There is no re-implementation to drift.
* service times come from the replay-fitted cost model
  (obs/replay.py `sim_tables`): prefill = a + b * prompt_tokens,
  decode = flat per-token step (ITL is flat in occupancy — PERF.md
  round 10), replica boot = AOT-store spin-up walls (round 22).
* requests are simulated at REQUEST granularity (admit / first-token /
  finish / preempt events, ~3-4 heap events per request), which is what
  makes millions of requests tractable; token-level behaviour is
  implied by the fitted step time.

Three seeded A/B scenarios (`--ab`) mirror the acceptance criteria:

* fairness  — one hot tenant at ~6x fair share vs four well-behaved
  tenants, token-bucket fairness off vs on.
* autoscale — a 10x Poisson ramp, fixed fleet vs forecast autoscaler.
* preemption — mixed-class overload at 1.3x capacity with interactive
  bursts, class policy + voluntary preemption off vs on.

Every arm reports bootstrap confidence intervals (seeded resampling
over reservoir-sampled TTFTs and per-second shed counts) so A/B deltas
come with error bars, and `accept` booleans encode the claims.

Determinism: a single `random.Random(seed)` stream per arm, no wall
clock anywhere near the output, sorted-keys JSON. The same command line
produces byte-identical output — tier-1 CI runs `--smoke --seed 0`
twice and diffs the files.
"""

from __future__ import annotations

import argparse
import heapq
import json
import math
import os
import random
import zlib
from typing import Callable, Optional

from distributed_pytorch_tpu.config import knob
from distributed_pytorch_tpu.obs.replay import load_cost_model, sim_tables
from distributed_pytorch_tpu.obs.slo import SLOTracker, default_targets
from distributed_pytorch_tpu.serve.control import (
    Autoscaler, ClassPolicy, FleetSample, TokenBucketFairness)

# ----------------------------------------------------------------------
# deterministic helpers
# ----------------------------------------------------------------------


def derive_seed(*parts) -> int:
    """Stable sub-seed from string parts (crc32, NOT hash() — string
    hashing is salted per process and would break the byte-diff gate)."""
    return zlib.crc32("|".join(str(p) for p in parts).encode("utf-8"))


class Reservoir:
    """Classic reservoir sampler: a capped, uniformly-representative
    sample of an unbounded observation stream, deterministic given the
    rng and insertion order. Keeps percentile/bootstrap costs bounded
    at millions of requests."""

    def __init__(self, cap: int, rng: random.Random):
        self.cap = cap
        self.rng = rng
        self.n = 0
        self.buf: list[float] = []

    def add(self, v: float) -> None:
        self.n += 1
        if len(self.buf) < self.cap:
            self.buf.append(v)
        else:
            j = self.rng.randrange(self.n)
            if j < self.cap:
                self.buf[j] = v


def pctl(sorted_vals: list[float], q: float) -> float:
    """Linear-interpolated percentile of a pre-sorted list."""
    if not sorted_vals:
        return 0.0
    f = q * (len(sorted_vals) - 1)
    lo = int(math.floor(f))
    hi = min(len(sorted_vals) - 1, lo + 1)
    return sorted_vals[lo] + (sorted_vals[hi] - sorted_vals[lo]) * (f - lo)


def bootstrap_ci(samples: list, stat_fn: Callable[[list], float],
                 n_boot: int, rng: random.Random,
                 lo_q: float = 0.025, hi_q: float = 0.975
                 ) -> tuple[float, float]:
    """Percentile-bootstrap CI of `stat_fn` over `samples` (seeded
    resampling with replacement)."""
    if not samples:
        return 0.0, 0.0
    n = len(samples)
    stats = sorted(
        stat_fn([samples[rng.randrange(n)] for _ in range(n)])
        for _ in range(n_boot))
    return pctl(stats, lo_q), pctl(stats, hi_q)


def ci_disjoint(a: tuple[float, float], b: tuple[float, float]) -> bool:
    return a[1] < b[0] or b[1] < a[0]


# ----------------------------------------------------------------------
# simulated fleet
# ----------------------------------------------------------------------

#: per-class draw ranges (prompt tokens, decode budget) for synthetic
#: traffic — interactive is short/chatty, batch is long-form.
CLASS_SHAPES = {
    "interactive": {"prompt": (32, 256), "budget": (16, 64)},
    "batch": {"prompt": (128, 1024), "budget": (64, 256)},
}


class SimReq:
    __slots__ = ("rid", "tenant", "cls", "slo_class", "prompt_len",
                 "budget", "t_submit", "served", "resumed", "admitted_at",
                 "epoch", "first_tok_t", "got_first", "cur_prefill_s",
                 "preempts")

    def __init__(self, rid, tenant, cls, slo_class, prompt_len, budget,
                 t_submit):
        self.rid = rid
        self.tenant = tenant
        self.cls = cls                 # true class (metrics)
        self.slo_class = slo_class     # class the policy sees
        self.prompt_len = prompt_len
        self.budget = budget
        self.t_submit = t_submit
        self.served = 0
        self.resumed = False
        self.admitted_at = 0.0
        self.epoch = 0
        self.first_tok_t = 0.0
        self.got_first = False
        self.cur_prefill_s = 0.0
        self.preempts = 0


class SimReplica:
    __slots__ = ("idx", "n_slots", "queue", "live", "state")

    def __init__(self, idx: int, n_slots: int):
        self.idx = idx
        self.n_slots = n_slots
        self.queue: list[SimReq] = []       # class-ordered (ClassPolicy)
        self.live: dict[int, SimReq] = {}   # rid -> req
        self.state = "serving"

    @property
    def load(self) -> int:
        return len(self.live) + len(self.queue)


def mean_service_s(tables: dict, p_interactive: float) -> float:
    """Expected slot-seconds per request under the class mix — the
    calibration constant that converts replica counts to capacity rps."""
    step_s = tables["decode_step_ms"] / 1000.0
    a_s = tables["prefill_a_ms"] / 1000.0
    b_s = tables["prefill_b_ms_per_token"] / 1000.0
    total = 0.0
    for cls, p in (("interactive", p_interactive),
                   ("batch", 1.0 - p_interactive)):
        shape = CLASS_SHAPES[cls]
        prompt = sum(shape["prompt"]) / 2.0
        budget = sum(shape["budget"]) / 2.0
        total += p * (a_s + b_s * prompt + budget * step_s)
    return total


def capacity_rps(tables: dict, n_replicas: int, n_slots: int,
                 p_interactive: float) -> float:
    return n_replicas * n_slots / mean_service_s(tables, p_interactive)


class FleetSim:
    """One simulated arm: a fleet of slot-limited replicas behind a
    least-loaded dispatcher, Poisson arrivals, and the live policy
    objects wired to the event-heap clock."""

    TICK_S = 1.0          # autoscaler / SLO sampling cadence
    PICK_SAMPLE = 16      # dispatcher scans this many replicas when the
    #                       fleet is larger (best-of-k ~= least-loaded)

    def __init__(self, *, tables: dict, seed: int, n_replicas: int,
                 duration_s: float, lam_fn: Callable[[float], float],
                 p_interactive_fn: Callable[[float], float],
                 tenants: list[tuple[str, float]],
                 n_slots: int = 8, max_queue: int = 64,
                 fairness_rate: float = 0.0,
                 fairness_burst: Optional[float] = None,
                 class_policy: bool = True,
                 autoscaler: Optional[Autoscaler] = None,
                 boot_s: float = 2.0,
                 reservoir_cap: int = 4000):
        self.tables = tables
        self.rng = random.Random(seed)
        self.now = 0.0
        self.duration_s = duration_s
        self.lam_fn = lam_fn
        self.p_int_fn = p_interactive_fn
        self.n_slots = n_slots
        self.max_queue = max_queue
        self.class_policy = class_policy
        self.boot_s = boot_s
        self.step_s = tables["decode_step_ms"] / 1000.0
        self.prefill_a_s = tables["prefill_a_ms"] / 1000.0
        self.prefill_b_s = tables["prefill_b_ms_per_token"] / 1000.0

        clock = lambda: self.now  # noqa: E731 — the injected sim clock
        self.fairness = TokenBucketFairness(
            rate_tokens_s=fairness_rate,
            burst=fairness_burst if fairness_burst is not None
            else max(1.0, fairness_rate * 2.0),
            now_fn=clock)
        self.autoscaler = autoscaler
        self.slo = SLOTracker(targets=default_targets(),
                              windows_s=(5.0, 30.0, 120.0), now_fn=clock)
        self.slo_ttft_s = float(knob("SLO_TTFT_P99_S"))

        self.reps = [SimReplica(i, n_slots) for i in range(n_replicas)]
        self.serving: list[SimReplica] = list(self.reps)
        self.n_booting = 0
        self.start_replicas = n_replicas
        self.peak_replicas = n_replicas
        self.first_scale_up_t: Optional[float] = None

        # tenant draw table
        tot_w = sum(w for _, w in tenants)
        acc = 0.0
        self.tenant_cdf: list[tuple[float, str]] = []
        for name, w in tenants:
            acc += w / tot_w
            self.tenant_cdf.append((acc, name))

        # counters
        self.arrivals = 0
        self.completed = {"interactive": 0, "batch": 0}
        self.shed = {}                        # cause -> n
        self.shed_by_cls = {}                 # "cause|cls" -> n
        self.preempted = 0
        self.preempted_by_cls = {}            # cls -> n
        self.preempted_then_shed = 0
        self.resumed_completed = 0
        self.tenant_stats = {name: {"offered": 0, "admitted": 0,
                                    "rejected": 0, "completed": 0}
                             for name, _ in tenants}
        self.ttft_good = 0
        self.ttft_total = 0
        seconds = int(duration_s) + 2
        self.arr_sec = [0] * seconds
        self.shed_cap_sec = [0] * seconds     # queue_full only
        self.max_queue_depth = 0
        self.worst_burn_peak = 0.0

        # reservoirs: TTFT per class, plus hot/other tenant split
        self.res: dict[str, Reservoir] = {}
        self.reservoir_cap = reservoir_cap

        self.heap: list = []
        self._seq = 0
        self._rid = 0

    # -- event plumbing -------------------------------------------------

    def push(self, t: float, kind: str, a=None, b=None) -> None:
        heapq.heappush(self.heap, (t, self._seq, kind, a, b))
        self._seq += 1

    def reservoir(self, key: str) -> Reservoir:
        r = self.res.get(key)
        if r is None:
            r = self.res[key] = Reservoir(self.reservoir_cap, self.rng)
        return r

    def _sec(self, arr: list, t: float) -> int:
        return min(len(arr) - 1, int(t))

    # -- traffic --------------------------------------------------------

    def _draw_tenant(self) -> str:
        r = self.rng.random()
        for edge, name in self.tenant_cdf:
            if r <= edge:
                return name
        return self.tenant_cdf[-1][1]

    def _draw_request(self) -> SimReq:
        p_int = self.p_int_fn(self.now)
        cls = "interactive" if self.rng.random() < p_int else "batch"
        shape = CLASS_SHAPES[cls]
        prompt = self.rng.randint(*shape["prompt"])
        budget = self.rng.randint(*shape["budget"])
        tenant = self._draw_tenant()
        # with the class policy off (A/B control arm) everything runs
        # as one FCFS class and nothing is preemptible
        slo_class = cls if self.class_policy else "interactive"
        self._rid += 1
        return SimReq(self._rid, tenant, cls, slo_class, prompt, budget,
                      self.now)

    def _schedule_next_arrival(self) -> None:
        lam = max(1e-9, self.lam_fn(self.now))
        t = self.now + self.rng.expovariate(lam)
        if t < self.duration_s:
            self.push(t, "arrival")

    def _record_shed(self, cause: str, req: SimReq) -> None:
        self.shed[cause] = self.shed.get(cause, 0) + 1
        k = f"{cause}|{req.cls}"
        self.shed_by_cls[k] = self.shed_by_cls.get(k, 0) + 1
        if cause == "queue_full":
            self.shed_cap_sec[self._sec(self.shed_cap_sec, self.now)] += 1
        if req.resumed:
            self.preempted_then_shed += 1

    def _on_arrival(self) -> None:
        self._schedule_next_arrival()
        req = self._draw_request()
        self.arrivals += 1
        self.arr_sec[self._sec(self.arr_sec, self.now)] += 1
        ts = self.tenant_stats[req.tenant]
        ts["offered"] += 1
        # router edge: tenant fairness first — the LIVE policy object
        if not self.fairness.admit(req.tenant):
            ts["rejected"] += 1
            self._record_shed("rate_limited", req)
            return
        ts["admitted"] += 1
        rep = self._pick_replica()
        if rep is None or len(rep.queue) >= self.max_queue:
            self._record_shed("queue_full", req)
            return
        rep.queue.insert(
            ClassPolicy.insert_index(rep.queue, req.slo_class), req)
        if req.slo_class == "interactive":
            self._maybe_preempt(rep)
        self._drain(rep)

    def _pick_replica(self) -> Optional[SimReplica]:
        serving = self.serving
        if not serving:
            return None
        if len(serving) <= self.PICK_SAMPLE:
            cands = serving
        else:
            n = len(serving)
            cands = [serving[self.rng.randrange(n)]
                     for _ in range(self.PICK_SAMPLE)]
        return min(cands, key=lambda r: (r.load, r.idx))

    # -- replica mechanics ---------------------------------------------

    def _drain(self, rep: SimReplica) -> None:
        while rep.queue and len(rep.live) < rep.n_slots:
            self._admit(rep, rep.queue.pop(0))

    def _admit(self, rep: SimReplica, req: SimReq) -> None:
        # resume is a radix/host-tier prefix hit: only the constant
        # prefill term is paid again (PERF.md rounds 14/17)
        prefill = (self.prefill_a_s if req.resumed
                   else self.prefill_a_s + self.prefill_b_s
                   * req.prompt_len)
        req.cur_prefill_s = prefill
        req.admitted_at = self.now
        req.first_tok_t = self.now + prefill + self.step_s
        remaining = req.budget - req.served
        rep.live[req.rid] = req
        self.push(self.now + prefill + remaining * self.step_s,
                  "finish", rep.idx, (req.rid, req.epoch))

    def _record_ttft(self, req: SimReq) -> None:
        if req.got_first:
            return
        req.got_first = True
        v = req.first_tok_t - req.t_submit
        self.ttft_total += 1
        if v <= self.slo_ttft_s:
            self.ttft_good += 1
        ms = v * 1000.0
        self.reservoir(f"ttft|{req.cls}").add(ms)
        self.reservoir(f"ttft_tenant|{req.tenant}").add(ms)

    def _maybe_preempt(self, rep: SimReplica) -> None:
        """Voluntary class preemption — the scheduler's policy calls,
        verbatim, against the sim queue/live structures."""
        if not self.class_policy:
            return
        free = rep.n_slots - len(rep.live)
        n_int = ClassPolicy.queued_interactive(rep.queue)
        live_batch = [r for r in rep.live.values()
                      if r.slo_class == "batch"]
        k = ClassPolicy.preempt_count(n_int, free, len(live_batch))
        for victim in ClassPolicy.pick_victims(live_batch, k):
            self._evict(rep, victim)

    def _evict(self, rep: SimReplica, req: SimReq) -> None:
        decoded = 0
        t_decode = self.now - (req.admitted_at + req.cur_prefill_s)
        if t_decode > 0:
            remaining = req.budget - req.served
            decoded = min(remaining - 1,
                          int(t_decode / self.step_s) + 1)
            decoded = max(0, decoded)
        if decoded >= 1:
            self._record_ttft(req)       # first token already streamed
        req.served += decoded
        req.epoch += 1                   # invalidates the finish event
        del rep.live[req.rid]
        req.resumed = True
        req.preempts += 1
        self.preempted += 1
        self.preempted_by_cls[req.cls] = \
            self.preempted_by_cls.get(req.cls, 0) + 1
        rep.queue.insert(
            ClassPolicy.insert_index(rep.queue, req.slo_class,
                                     resumed=True), req)

    def _on_finish(self, rep_idx: int, payload) -> None:
        rid, epoch = payload
        rep = self.reps[rep_idx]
        req = rep.live.get(rid)
        if req is None or req.epoch != epoch:
            return                       # stale event (preempted)
        del rep.live[rid]
        self._record_ttft(req)
        self.completed[req.cls] += 1
        self.tenant_stats[req.tenant]["completed"] += 1
        if req.preempts:
            self.resumed_completed += 1
        self._drain(rep)

    # -- autoscaling ----------------------------------------------------

    def _fleet_sample(self) -> FleetSample:
        n = len(self.serving)
        live = sum(len(r.live) for r in self.serving)
        qdepth = sum(len(r.queue) for r in self.serving)
        occ = live / max(1, n * self.n_slots)
        shed_all = sum(self.shed.values())
        shed_cap = shed_all - self.shed.get("rate_limited", 0)
        recent = shed_cap - getattr(self, "_shed_seen", 0)
        self._shed_seen = shed_cap
        return FleetSample(t=self.now, n_replicas=n,
                           n_booting=self.n_booting, occupancy=occ,
                           queue_depth=qdepth,
                           worst_burn=self.slo.worst_burn(),
                           shed_recent=recent)

    def _on_tick(self) -> None:
        if self.now + self.TICK_S < self.duration_s:
            self.push(self.now + self.TICK_S, "tick")
        shed_all = sum(self.shed.values())
        done = sum(self.completed.values())
        self.slo.update({
            "ttft_p99": (self.ttft_good, self.ttft_total),
            "availability": (done, done + shed_all),
        })
        s = self._fleet_sample()
        self.max_queue_depth = max(self.max_queue_depth, s.queue_depth)
        self.worst_burn_peak = max(self.worst_burn_peak, s.worst_burn)
        if self.autoscaler is None:
            return
        delta = self.autoscaler.decide(s)
        if delta > 0:
            if self.first_scale_up_t is None:
                self.first_scale_up_t = self.now
            for _ in range(delta):
                self.n_booting += 1
                self.push(self.now + self.boot_s, "boot")
        elif delta < 0:
            for rep in reversed(self.serving):
                if not rep.live and not rep.queue:
                    rep.state = "removed"
                    self.serving.remove(rep)
                    break

    def _on_boot(self) -> None:
        self.n_booting -= 1
        rep = SimReplica(len(self.reps), self.n_slots)
        self.reps.append(rep)
        self.serving.append(rep)
        self.peak_replicas = max(self.peak_replicas, len(self.serving))
        self._drain(rep)

    # -- main loop ------------------------------------------------------

    def run(self) -> dict:
        self.push(0.0, "tick")
        self._schedule_next_arrival()
        heap = self.heap
        while heap:
            t, _, kind, a, b = heapq.heappop(heap)
            self.now = t
            if kind == "arrival":
                self._on_arrival()
            elif kind == "finish":
                self._on_finish(a, b)
            elif kind == "tick":
                self._on_tick()
            elif kind == "boot":
                self._on_boot()
        return self.summary()

    # -- reporting ------------------------------------------------------

    def _ttft_summary(self, key: str, boot_rng: random.Random,
                      n_boot: int) -> dict:
        res = self.res.get(key)
        if res is None or not res.buf:
            return {"n": 0, "p50_ms": 0.0, "p99_ms": 0.0,
                    "p99_ci_ms": [0.0, 0.0]}
        buf = sorted(res.buf)
        lo, hi = bootstrap_ci(res.buf, lambda s: pctl(sorted(s), 0.99),
                              n_boot, boot_rng)
        return {"n": res.n,
                "p50_ms": round(pctl(buf, 0.50), 2),
                "p99_ms": round(pctl(buf, 0.99), 2),
                "p99_ci_ms": [round(lo, 2), round(hi, 2)]}

    def shed_rate_ci(self, boot_rng: random.Random,
                     n_boot: int) -> tuple[float, list[float]]:
        """Capacity-shed rate (queue_full / arrivals) with a per-second
        block-bootstrap CI — seconds are the resampling unit so the CI
        respects the burstiness of the arrival process."""
        pairs = [(s, a) for s, a in zip(self.shed_cap_sec, self.arr_sec)
                 if a > 0]
        total_arr = sum(a for _, a in pairs)
        rate = (sum(s for s, _ in pairs) / total_arr) if total_arr else 0.0

        def stat(sample):
            arr = sum(a for _, a in sample)
            return (sum(s for s, _ in sample) / arr) if arr else 0.0

        lo, hi = bootstrap_ci(pairs, stat, n_boot, boot_rng)
        return rate, [round(lo, 4), round(hi, 4)]

    def summary(self, n_boot: int = 200) -> dict:
        boot_rng = random.Random(derive_seed("bootstrap", self._rid,
                                             self.arrivals))
        done = sum(self.completed.values())
        shed_all = sum(self.shed.values())
        cap_rate, cap_ci = self.shed_rate_ci(boot_rng, n_boot)
        out = {
            "arrivals": self.arrivals,
            "completed": dict(sorted(self.completed.items())),
            "in_flight": self.arrivals - done - shed_all,
            "shed": dict(sorted(self.shed.items())),
            "shed_by_class": dict(sorted(self.shed_by_cls.items())),
            "shed_rate": round(shed_all / max(1, self.arrivals), 4),
            "capacity_shed_rate": round(cap_rate, 4),
            "capacity_shed_rate_ci": cap_ci,
            "preempted": self.preempted,
            "preempted_by_class":
                dict(sorted(self.preempted_by_cls.items())),
            "preempted_then_shed": self.preempted_then_shed,
            "resumed_completed": self.resumed_completed,
            "ttft_ms": {cls: self._ttft_summary(f"ttft|{cls}",
                                                boot_rng, n_boot)
                        for cls in ("interactive", "batch")},
            "tenants": {name: dict(st) for name, st in
                        sorted(self.tenant_stats.items())},
            "fairness": self.fairness.snapshot(),
            "replicas": {
                "start": self.start_replicas,
                "peak": self.peak_replicas,
                "final": len(self.serving),
                "first_scale_up_t_s":
                    (round(self.first_scale_up_t, 1)
                     if self.first_scale_up_t is not None else None),
                "scaled_up": (self.autoscaler.scaled_up
                              if self.autoscaler else 0),
                "scaled_down": (self.autoscaler.scaled_down
                                if self.autoscaler else 0),
            },
            "max_queue_depth": self.max_queue_depth,
            "worst_burn_peak": round(self.worst_burn_peak, 3),
        }
        # tenant-split TTFT (fairness scenario reads these)
        for key in sorted(self.res):
            if key.startswith("ttft_tenant|"):
                out.setdefault("ttft_ms_by_tenant", {})[
                    key.split("|", 1)[1]] = \
                    self._ttft_summary(key, boot_rng, n_boot)
        return out


# ----------------------------------------------------------------------
# scenarios (the A/B arms of the acceptance criteria)
# ----------------------------------------------------------------------


def scenario_fairness(tables: dict, seed: int, n_replicas: int,
                      duration_s: float, reservoir_cap: int) -> dict:
    """One hot tenant at 6x its fair share, four polite tenants;
    fairness off vs on. Claim: the bucket caps the hot tenant while the
    others' p99 TTFT stays within SLO."""
    n_slots = 8
    tenants = [("hot", 0.6), ("t1", 0.1), ("t2", 0.1), ("t3", 0.1),
               ("t4", 0.1)]
    cap = capacity_rps(tables, n_replicas, n_slots, 1.0)
    offered = 1.5 * cap
    fair_share = cap / len(tenants)
    arms = {}
    for arm, rate in (("fairness_off", 0.0), ("fairness_on", fair_share)):
        sim = FleetSim(
            tables=tables, seed=derive_seed(seed, "fairness", arm),
            n_replicas=n_replicas, duration_s=duration_s,
            lam_fn=lambda t: offered,
            p_interactive_fn=lambda t: 1.0,   # single class: isolate
            tenants=tenants, n_slots=n_slots,  # fairness from classes
            fairness_rate=rate, fairness_burst=max(1.0, rate * 0.5),
            reservoir_cap=reservoir_cap)
        arms[arm] = sim.run()

    def others_p99(arm):
        per_t = arms[arm].get("ttft_ms_by_tenant", {})
        vals = [per_t[t]["p99_ms"] for t in ("t1", "t2", "t3", "t4")
                if t in per_t]
        cis = [per_t[t]["p99_ci_ms"] for t in ("t1", "t2", "t3", "t4")
               if t in per_t]
        if not vals:
            return 0.0, (0.0, 0.0)
        worst = max(range(len(vals)), key=lambda i: vals[i])
        return vals[worst], tuple(cis[worst])

    slo_ms = float(knob("SLO_TTFT_P99_S")) * 1000.0
    off_p99, off_ci = others_p99("fairness_off")
    on_p99, on_ci = others_p99("fairness_on")
    hot = arms["fairness_on"]["tenants"]["hot"]
    hot_admit_rps = hot["admitted"] / duration_s
    return {
        "offered_rps": round(offered, 1),
        "capacity_rps": round(cap, 1),
        "fair_share_rps": round(fair_share, 1),
        "arms": arms,
        "others_worst_p99_ms": {"fairness_off": off_p99,
                                "fairness_on": on_p99},
        "accept": {
            "hot_tenant_capped": hot_admit_rps <= fair_share * 1.1,
            "others_slo_held": on_p99 <= slo_ms,
            "ci_disjoint_others_p99": ci_disjoint(on_ci, off_ci),
        },
    }


def scenario_autoscale(tables: dict, seed: int, n_replicas: int,
                       duration_s: float, reservoir_cap: int) -> dict:
    """A 10x linear ramp against a fixed fleet vs the forecast
    autoscaler. Claim: the fixed fleet sheds >20%, the autoscaler keeps
    shed ~0 by scaling BEFORE the knee."""
    n_slots = 8
    n0 = max(4, n_replicas // 10)
    cap0 = capacity_rps(tables, n0, n_slots, 0.5)
    lam0 = 0.6 * cap0

    def lam_fn(t):
        return lam0 * (1.0 + 9.0 * min(1.0, t / duration_s))

    boot_s = tables.get("boot_s", 2.0)
    arms = {}
    for arm in ("autoscale_off", "autoscale_on"):
        scaler = None
        if arm == "autoscale_on":
            scaler = Autoscaler(min_replicas=n0, max_replicas=n_replicas,
                                lead_s=15.0, cooldown_s=2.0,
                                slope_window_s=30.0)
        sim = FleetSim(
            tables=tables, seed=derive_seed(seed, "autoscale", arm),
            n_replicas=n0, duration_s=duration_s, lam_fn=lam_fn,
            p_interactive_fn=lambda t: 0.5,
            tenants=[("t0", 1.0)], n_slots=n_slots,
            autoscaler=scaler, boot_s=boot_s,
            reservoir_cap=reservoir_cap)
        arms[arm] = sim.run()
    off, on = arms["autoscale_off"], arms["autoscale_on"]
    knee = float(knob("AUTOSCALE_KNEE_OCCUPANCY"))
    # the time the OFF fleet first sheds is when demand crossed the
    # knee at fixed capacity; scaling must have started before that
    first_up = on["replicas"]["first_scale_up_t_s"]
    # demand(t)/cap0 > knee  =>  t* from the linear ramp
    t_knee = duration_s * (knee * cap0 / lam0 - 1.0) / 9.0
    return {
        "start_replicas": n0,
        "max_replicas": n_replicas,
        "ramp": "10x linear",
        "boot_s": boot_s,
        "t_knee_s": round(t_knee, 1),
        "arms": arms,
        "accept": {
            "off_shed_gt_20pct": off["capacity_shed_rate"] > 0.20,
            "on_shed_near_zero": on["capacity_shed_rate"] < 0.01,
            "ci_disjoint_shed_rate": ci_disjoint(
                tuple(on["capacity_shed_rate_ci"]),
                tuple(off["capacity_shed_rate_ci"])),
            "scaled_before_knee": (first_up is not None
                                   and first_up < t_knee),
        },
    }


def scenario_preemption(tables: dict, seed: int, n_replicas: int,
                        duration_s: float, reservoir_cap: int) -> dict:
    """Mixed-class overload at 1.3x capacity with interactive bursts;
    class policy + voluntary preemption off vs on. Claim: preemption
    holds interactive p99 TTFT within SLO while batch absorbs every
    preemption and no started batch stream is lost."""
    n_slots = 8
    cap = capacity_rps(tables, n_replicas, n_slots, 0.5)
    offered = 1.3 * cap

    def p_int_fn(t):
        # interactive share oscillates 0.2..0.8 (20 s period): the
        # bursts are what forces slot contention and preemption
        return 0.5 + 0.3 * math.sin(2.0 * math.pi * t / 20.0)

    arms = {}
    for arm, on in (("preempt_off", False), ("preempt_on", True)):
        sim = FleetSim(
            tables=tables, seed=derive_seed(seed, "preempt", arm),
            n_replicas=n_replicas, duration_s=duration_s,
            lam_fn=lambda t: offered, p_interactive_fn=p_int_fn,
            tenants=[("t0", 1.0)], n_slots=n_slots,
            class_policy=on, reservoir_cap=reservoir_cap)
        arms[arm] = sim.run()
    off, on_ = arms["preempt_off"], arms["preempt_on"]
    slo_ms = float(knob("SLO_TTFT_P99_S")) * 1000.0
    on_int = on_["ttft_ms"]["interactive"]
    off_int = off["ttft_ms"]["interactive"]
    return {
        "offered_rps": round(offered, 1),
        "capacity_rps": round(cap, 1),
        "arms": arms,
        "accept": {
            "interactive_slo_held": on_int["p99_ms"] <= slo_ms,
            "batch_zero_lost": on_["preempted_then_shed"] == 0,
            "batch_absorbs_all_preemptions":
                on_["preempted_by_class"].get("interactive", 0) == 0,
            "ci_disjoint_interactive_p99": ci_disjoint(
                tuple(on_int["p99_ci_ms"]), tuple(off_int["p99_ci_ms"])),
        },
    }


SCENARIOS = {
    "fairness": scenario_fairness,
    "autoscale": scenario_autoscale,
    "preemption": scenario_preemption,
}


# ----------------------------------------------------------------------
# CLI
# ----------------------------------------------------------------------


def resolve_tables(cost_model_path: Optional[str]) -> dict:
    cm = None
    if cost_model_path and os.path.exists(cost_model_path):
        cm = load_cost_model(cost_model_path)
    return sim_tables(cm)


def run_report(*, seed: int, n_replicas: int, duration_s: float,
               cost_model: Optional[str], smoke: bool,
               scenarios: Optional[list[str]] = None) -> dict:
    tables = resolve_tables(cost_model)
    reservoir_cap = 500 if smoke else 4000
    report = {
        "meta": {
            "mode": "smoke" if smoke else "ab",
            "seed": seed,
            "replicas": n_replicas,
            "duration_s": duration_s,
            "tables": {k: tables[k] for k in sorted(tables)},
            "policies": ["ClassPolicy", "TokenBucketFairness",
                         "Autoscaler", "SLOTracker"],
            "version": 1,
        },
        "scenarios": {},
    }
    for name in (scenarios or sorted(SCENARIOS)):
        report["scenarios"][name] = SCENARIOS[name](
            tables, seed, n_replicas, duration_s, reservoir_cap)
    report["accept"] = {
        f"{name}.{k}": v
        for name, sc in sorted(report["scenarios"].items())
        for k, v in sorted(sc["accept"].items())}
    return report


def build_args() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m sim.fleetsim",
        description="seeded discrete-event fleet simulator for the "
                    "serving control plane (policy A/Bs with bootstrap "
                    "CIs; byte-deterministic under --seed)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny deterministic run (CI gate: run twice, "
                         "diff bytes)")
    ap.add_argument("--ab", action="store_true",
                    help="full policy A/B at --replicas scale")
    ap.add_argument("--seed", type=int, default=None,
                    help=f"rng seed (default: SIM_SEED knob = "
                         f"{knob('SIM_SEED')})")
    ap.add_argument("--replicas", type=int, default=None,
                    help=f"simulated fleet size (default: SIM_REPLICAS "
                         f"knob = {knob('SIM_REPLICAS')})")
    ap.add_argument("--duration", type=float, default=None,
                    help=f"simulated seconds per arm (default: "
                         f"SIM_DURATION_S knob = "
                         f"{knob('SIM_DURATION_S')})")
    ap.add_argument("--scenario", action="append",
                    choices=sorted(SCENARIOS),
                    help="run only this scenario (repeatable)")
    ap.add_argument("--cost-model", default="runs/replay/cost_model.json",
                    help="replay-fitted cost model json; falls back to "
                         "built-in default tables when absent")
    ap.add_argument("--out", default=None,
                    help="also write the JSON report to this path")
    return ap


def main(argv: Optional[list[str]] = None) -> int:
    args = build_args().parse_args(argv)
    seed = args.seed if args.seed is not None else int(knob("SIM_SEED"))
    if args.smoke:
        n_replicas = args.replicas or 10
        duration_s = args.duration or 10.0
    else:
        n_replicas = (args.replicas if args.replicas is not None
                      else int(knob("SIM_REPLICAS")))
        duration_s = (args.duration if args.duration is not None
                      else float(knob("SIM_DURATION_S")))
    report = run_report(seed=seed, n_replicas=n_replicas,
                        duration_s=duration_s,
                        cost_model=args.cost_model, smoke=args.smoke,
                        scenarios=args.scenario)
    text = json.dumps(report, sort_keys=True, indent=2) + "\n"
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    print(text, end="")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
