"""Discrete-event fleet simulator for the serving control plane.

`sim.fleetsim` replays Poisson traffic against 100-1000 simulated
replicas — millions of simulated requests on a 1-core dev box — running
the SAME policy objects as the live router (serve/control.py's
TokenBucketFairness / ClassPolicy / Autoscaler and obs/slo.py's
SLOTracker, all clock-injected), with service times from replay-fitted
cost_model.json tables (obs/replay.py). Seeded and wall-clock-free: the
same seed produces byte-identical output, which tier-1 CI gates on.
"""
