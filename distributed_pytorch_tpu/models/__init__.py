"""Model library: one canonical Flax implementation of the reference's LLM
family (reference single-gpu/model.py — which the reference duplicates four
more times inside its kaggle scripts; here it exists exactly once)."""

from distributed_pytorch_tpu.models.gpt import LLM, Block, init_cache  # noqa: F401
from distributed_pytorch_tpu.models.attention import GQA, NaiveMLA, FullMLA, Attention  # noqa: F401
from distributed_pytorch_tpu.models.mlp import MLP, MoE  # noqa: F401
from distributed_pytorch_tpu.models.pipeline import (  # noqa: F401
    stack_block_params,
    unstack_block_params,
)
