"""Attention flavors: GQA (unifying MHA/MQA/GQA) and Multi-head Latent
Attention (MLA), with and without decoupled RoPE.

Reference parity map:
* `GQA`      — reference single-gpu/model.py:98-155 (fused qkv projection,
               optional RoPE, KV-cache append, SDPA).
* `NaiveMLA` — reference `NaiveMHLA` model.py:157-235 (MLA without RoPE,
               latent KV cache).
* `FullMLA`  — reference `FullMHLA` model.py:237-345 (DeepSeek-V2 MLA with
               decoupled RoPE: NoPE content path + single shared rotary key
               head; scores scaled by 1/sqrt(hs+dhr); cache {'c_kv','k_r'}).
* `Attention` — dispatch (model.py:347-363): mha/mqa/gqa -> GQA; mla ->
               NaiveMLA (pos_emb != 'rope') or FullMLA (pos_emb == 'rope').

TPU-first design notes (intentional divergences, documented per SURVEY §7):

1. **Training path materializes per-head K/V** from the latents and calls the
   fused SDPA/flash kernel — large batched matmuls that tile onto the MXU —
   instead of the reference's chain of small latent-space matmuls with an
   explicitly materialized O(T^2) mask (model.py:225-226,333-334).

2. **Weight absorption** (reference model.py:178-202,283-297) becomes the
   *decode* path: queries are pulled into the KV-latent space
   (q_abs = q @ W_uk_h^T) so each new token attends directly over the cached
   compressed c_kv, and per-head outputs are expanded back through W_uv
   before W_o. Unlike the reference — whose absorbed matrices double-apply
   the query down/up projections in `NaiveMHLA` (k_eff includes
   W_dq^T W_uq^T, model.py:196) and fold W_o into a per-head output slice
   (model.py:197) — this absorption is the algebraically exact DeepSeek-V2
   rewrite, so materialized-vs-absorbed equivalence is asserted by unit test
   (tests/test_mla.py) rather than guarded by a VAL_RUN flag (the
   reference's "16 hrs to debug" train/eval divergence, model.py:195,290).

3. Functional, static-shape KV caches: fixed (B, S_max, ...) buffers updated
   with `dynamic_update_slice` at position `pos`, because XLA requires static
   shapes — replacing the reference's concat-and-grow caches (model.py:137-142).

4. Paged decode caches (ops/block_pool.py): when `block_tables` is passed,
   the cache leaves are (n_blocks, block_size, ...) POOLS shared by every
   sequence, and writes/reads indirect through per-sequence block tables —
   `paged_update` replaces the ring write, the flash kernel prefetches the
   table, and the naive/absorbed paths read a `paged_gather`ed logical view
   (identical values at identical logical positions, so they are
   bit-compatible with the contiguous cache). The contiguous path below
   stays for training and the one-shot generate oracle.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from distributed_pytorch_tpu.config import LLMConfig
from distributed_pytorch_tpu.ops.attention_core import sdpa
from distributed_pytorch_tpu.ops.rope import apply_rotary_emb, slice_rows

Cache = dict[str, jnp.ndarray]

_DENSE_INIT = nn.initializers.normal(stddev=0.02)


class _OverlapDense(nn.Module):
    """nn.Dense twin (identical param tree — kernel/bias under this
    module's name — init, and dtype semantics) whose matmul is offered to
    the collective-matmul dispatcher (ops/collective_matmul.py) first.

    Used for the fused qkv and attention out-projection: under an active
    OVERLAP=on ZeRO-3 step their param all-gathers run as ppermute rings
    fused with the matmul (closing the round-6 ROADMAP gap — the MLP and
    lm-head already ring; these two call sites were the last GSPMD-default
    gathers). Everywhere else the dispatcher declines and the plain `@`
    below is bit-identical to nn.Dense."""

    features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", _DENSE_INIT,
                            (x.shape[-1], self.features), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros,
                          (self.features,), jnp.float32)
        kd = kernel.astype(self.dtype)
        # weight-only int8 decode (ops/quant.py): when the engine's step
        # runs under use_quantized_params, the matmul reads int8 codes +
        # per-output-channel scales instead of the bf16 kernel; everywhere
        # else the lookup misses and nothing changes
        from distributed_pytorch_tpu.ops.quant import maybe_quantized_matmul
        y = maybe_quantized_matmul(x, (*self.path, "kernel"))
        if y is None:
            from distributed_pytorch_tpu.ops.collective_matmul import (
                maybe_overlap_matmul)
            y = maybe_overlap_matmul(x, kd, names=(self.name, "kernel"))
        if y is None:
            y = x @ kd
        return y + bias.astype(self.dtype)


def _update_cache(cache_arr: jnp.ndarray, new: jnp.ndarray, pos) -> jnp.ndarray:
    """Write `new` (B, T, ...) into the static buffer at [:, pos:pos+T].

    `pos` is the GLOBAL token position: a static int (prefill), a traced
    scalar, or a per-sequence (B,) array (slot-based ragged decode —
    independent sequences in a batch sit at different positions). Traced
    positions write modulo the buffer length: the cache is a RING — once
    the window fills, the new row lands on the slot holding the oldest
    entry. One O(1) dynamic-slice write per token replaces the legacy
    roll-by-one window's O(S) HBM shift (generate.py pre-round-8), and is
    content-identical to it: both keep exactly the last S entries, and
    attention is permutation-invariant over fully-valid slots."""
    new = new.astype(cache_arr.dtype)
    zeros = (0,) * (new.ndim - 2)
    S = cache_arr.shape[1]
    if isinstance(pos, int):
        return jax.lax.dynamic_update_slice(cache_arr, new, (0, pos, *zeros))
    pos = jnp.asarray(pos, jnp.int32)
    start = jax.lax.rem(pos, jnp.int32(S))
    if pos.ndim == 0:
        return jax.lax.dynamic_update_slice(cache_arr, new,
                                            (jnp.int32(0), start, *zeros))
    # per-sequence slots: one row-write per sequence
    return jax.vmap(
        lambda c, n, p: jax.lax.dynamic_update_slice(c, n, (p, *zeros))
    )(cache_arr, new, start)


class GQA(nn.Module):
    """Grouped-query attention; n_kv_heads == n_head gives MHA, == 1 MQA.

    Follows reference model.py:98-155: one fused qkv projection of width
    n_embd + 2*n_kv_heads*head_size (with bias, as reference :112-114), RoPE
    on q/k when pos_emb == 'rope', output projection + residual dropout.
    """

    config: LLMConfig
    attn_impl: str = "auto"

    @nn.compact
    def __call__(self, x, freqs, cache: Optional[Cache] = None, pos=0, *,
                 deterministic: bool = True, block_tables=None):
        cfg = self.config
        B, T, C = x.shape
        nh, nkvh, hs = cfg.n_head, cfg.n_kv_heads, cfg.head_size

        qkv = _OverlapDense(C + 2 * nkvh * hs, x.dtype, name="c_attn")(x)
        q, k, v = jnp.split(qkv, [C, C + nkvh * hs], axis=-1)
        q = q.reshape(B, T, nh, hs)
        k = k.reshape(B, T, nkvh, hs)
        v = v.reshape(B, T, nkvh, hs)

        if cfg.pos_emb == "rope":
            f = slice_rows(freqs, pos, T)
            q = apply_rotary_emb(q, f)
            k = apply_rotary_emb(k, f)

        new_cache = None
        q_offset = 0
        k_scale = v_scale = None
        if cache is not None:
            # paged caches write through the block table, contiguous ones
            # through the O(1) ring write — same rows, one indirection
            upd = _update_cache
            if block_tables is not None:
                from distributed_pytorch_tpu.ops.block_pool import \
                    paged_update

                def upd(arr, new, p):
                    return paged_update(arr, new, p, block_tables)
            if "k_scale" in cache:
                # int8 cache: quantize on the write — codes land in the
                # int8 buffers, per-(row, kv-head) scales in the f32
                # sidecars, all via the same O(1) row writes
                from distributed_pytorch_tpu.ops.quant import quantize_kv
                k_q, k_s = quantize_kv(k)
                v_q, v_s = quantize_kv(v)
                k = upd(cache["k"], k_q, pos)
                v = upd(cache["v"], v_q, pos)
                k_scale = upd(cache["k_scale"], k_s, pos)
                v_scale = upd(cache["v_scale"], v_s, pos)
                new_cache = {"k": k, "k_scale": k_scale,
                             "v": v, "v_scale": v_scale}
            else:
                k = upd(cache["k"], k, pos)
                v = upd(cache["v"], v, pos)
                new_cache = {"k": k, "v": v}
            q_offset = pos

        drop_rng = None
        if cfg.dropout > 0.0 and not deterministic:
            drop_rng = self.make_rng("dropout")
        y = sdpa(q, k if (k_scale is not None or block_tables is not None)
                 else k.astype(q.dtype),
                 v if (v_scale is not None or block_tables is not None)
                 else v.astype(q.dtype),
                 causal=True, q_offset=q_offset, dropout_rate=cfg.dropout,
                 dropout_rng=drop_rng, impl=self.attn_impl,
                 decode=cache is not None, k_scale=k_scale, v_scale=v_scale,
                 block_tables=block_tables)
        y = y.reshape(B, T, C)
        y = _OverlapDense(C, x.dtype, name="c_proj")(y)
        y = nn.Dropout(cfg.dropout, deterministic=deterministic)(y)
        return y, new_cache


def _qmm(mod: nn.Module, x: jnp.ndarray, kernel: jnp.ndarray,
         name: str) -> jnp.ndarray:
    """`x @ kernel` with the weight-only-int8 store consulted first
    (ops/quant.py): under an engine decode step with quantized params the
    matmul reads int8 codes + per-output-channel scales; everywhere else
    it is the plain cast-and-matmul."""
    from distributed_pytorch_tpu.ops.quant import maybe_quantized_matmul
    y = maybe_quantized_matmul(x, (*mod.path, name))
    return y if y is not None else x @ kernel.astype(x.dtype)


def _mla_kernels(mod: nn.Module, cfg: LLMConfig, C: int, *, rope: bool) -> dict:
    """Declare the MLA projection kernels (all bias-free, reference
    model.py:165-170,250-263). Declared via self.param (not nn.Dense) because
    the decode path contracts W_uk/W_uv against the cache in absorbed form."""
    nlq, nlkv = cfg.q_latent_dim, cfg.kv_latent_dim
    ks = {
        "W_dq": mod.param("W_dq", _DENSE_INIT, (C, nlq), jnp.float32),
        "W_uq": mod.param("W_uq", _DENSE_INIT, (nlq, C), jnp.float32),
        "W_dkv": mod.param("W_dkv", _DENSE_INIT, (C, nlkv), jnp.float32),
        "W_uk": mod.param("W_uk", _DENSE_INIT, (nlkv, C), jnp.float32),
        "W_uv": mod.param("W_uv", _DENSE_INIT, (nlkv, C), jnp.float32),
        "W_o": mod.param("W_o", _DENSE_INIT, (C, C), jnp.float32),
    }
    if rope:
        dhr = cfg.rope_head_dim
        ks["W_qr"] = mod.param("W_qr", _DENSE_INIT, (nlq, cfg.n_head * dhr),
                               jnp.float32)
        ks["W_kr"] = mod.param("W_kr", _DENSE_INIT, (C, dhr), jnp.float32)
    return ks


def _absorbed_decode(q_c, c_kv, kuk, kuv, pos, scale, extra_scores=None):
    """Shared MLA decode: attend over the compressed latent cache with exact
    weight absorption (module docstring note 2).

    q_c: (B,T,nh,hs) content queries; c_kv: (B,S,nlkv) latent cache buffer;
    kuk/kuv: (nlkv, C) up-projections; extra_scores: optional (B,nh,T,S)
    additive term (FullMLA's decoupled-rotary scores, reference
    model.py:320-326). Returns (B, T, nh*hs) pre-W_o output."""
    B, T, nh, hs = q_c.shape
    S = c_kv.shape[1]
    dt = q_c.dtype
    nlkv = kuk.shape[0]
    kuk_h = kuk.reshape(nlkv, nh, hs).astype(dt)
    kuv_h = kuv.reshape(nlkv, nh, hs).astype(dt)
    # q_abs[b,t,n,l] = q . W_uk_h^T : attend in latent space
    q_abs = jnp.einsum("btnh,lnh->btnl", q_c, kuk_h)
    attn = jnp.einsum("btnl,bsl->bnts", q_abs, c_kv.astype(dt))
    if extra_scores is not None:
        attn = attn + extra_scores
    attn = attn * scale
    attn = jnp.where(_causal_cache_mask(pos, T, S)[:, None], attn, -jnp.inf)
    attn = jax.nn.softmax(attn.astype(jnp.float32), axis=-1).astype(dt)
    out_lat = jnp.einsum("bnts,bsl->btnl", attn, c_kv.astype(dt))
    return jnp.einsum("btnl,lnh->btnh", out_lat, kuv_h).reshape(B, T, nh * hs)


def _causal_cache_mask(pos, T: int, S: int) -> jnp.ndarray:
    """(B|1, T, S) bool mask: query at global position pos+i attends cache
    slots j <= pos+i. `pos` scalar or per-sequence (B,) array. Under the
    ring cache (global pos >= S) every slot is valid — slot indices never
    exceed S-1, so the comparison degenerates to all-true, matching the
    legacy roll window's fully-valid buffer."""
    qpos = (jnp.reshape(jnp.asarray(pos, jnp.int32), (-1, 1, 1))
            + jnp.arange(T)[None, :, None])
    kpos = jnp.arange(S)[None, None, :]
    return qpos >= kpos


class NaiveMLA(nn.Module):
    """MLA without RoPE (reference `NaiveMHLA`, model.py:157-235).

    Projections (all bias-free, reference :165-170): W_dq (C->q_latent),
    W_uq (q_latent->C), W_dkv (C->kv_latent), W_uk/W_uv (kv_latent->C),
    W_o (C->C). Cache stores only the compressed c_kv (B, S, kv_latent)
    (reference :204-211). Decode uses exact weight absorption (see module
    docstring note 2).
    """

    config: LLMConfig
    attn_impl: str = "auto"

    @nn.compact
    def __call__(self, x, freqs, cache: Optional[Cache] = None, pos=0, *,
                 deterministic: bool = True, block_tables=None):
        cfg = self.config
        B, T, C = x.shape
        nh, hs = cfg.n_head, cfg.head_size
        dt = x.dtype

        ks = _mla_kernels(self, cfg, C, rope=False)
        q = _qmm(self, _qmm(self, x, ks["W_dq"], "W_dq"), ks["W_uq"], "W_uq")
        q = q.reshape(B, T, nh, hs)
        new_c_kv = _qmm(self, x, ks["W_dkv"], "W_dkv")  # (B, T, nlkv)

        if cache is None:
            # Training/full-sequence: materialize per-head K/V -> fused SDPA.
            k = (new_c_kv @ ks["W_uk"].astype(dt)).reshape(B, T, nh, hs)
            v = (new_c_kv @ ks["W_uv"].astype(dt)).reshape(B, T, nh, hs)
            drop_rng = None
            if cfg.dropout > 0.0 and not deterministic:
                drop_rng = self.make_rng("dropout")
            y = sdpa(q, k, v, causal=True, dropout_rate=cfg.dropout,
                     dropout_rng=drop_rng, impl=self.attn_impl)
            y = y.reshape(B, T, C)
            new_cache = None
        else:
            if block_tables is not None:
                from distributed_pytorch_tpu.ops.block_pool import (
                    paged_gather, paged_update)
                pool = paged_update(cache["c_kv"], new_c_kv, pos,
                                    block_tables)
                new_cache = {"c_kv": pool}
                # absorbed decode attends the logical view; rows past each
                # sequence's extent are causally masked to weight 0
                c_kv = paged_gather(pool, block_tables)
            else:
                c_kv = _update_cache(cache["c_kv"], new_c_kv, pos)
                new_cache = {"c_kv": c_kv}
            from distributed_pytorch_tpu.ops.quant import \
                maybe_dequantized_param
            kuk = maybe_dequantized_param((*self.path, "W_uk"), ks["W_uk"])
            kuv = maybe_dequantized_param((*self.path, "W_uv"), ks["W_uv"])
            y = _absorbed_decode(q, c_kv, kuk, kuv, pos,
                                 1.0 / jnp.sqrt(float(hs)))

        y = _qmm(self, y, ks["W_o"], "W_o")
        y = nn.Dropout(cfg.dropout, deterministic=deterministic)(y)
        return y, new_cache


class FullMLA(nn.Module):
    """DeepSeek-V2 MLA with decoupled RoPE (reference `FullMHLA`,
    model.py:237-345).

    Content (NoPE) path through latents exactly as NaiveMLA; rotary path adds
    per-head rotary queries W_qr (q_latent -> nh*dhr) and a single shared
    rotary key head W_kr (C -> dhr) (reference :258-259). Scores are
    q_c.k_c + q_r.k_r scaled by 1/sqrt(hs+dhr) (reference :326). Cache:
    {'c_kv': (B,S,nlkv), 'k_r': (B,S,1,dhr)} (reference :343).
    """

    config: LLMConfig
    attn_impl: str = "auto"

    @nn.compact
    def __call__(self, x, freqs, cache: Optional[Cache] = None, pos=0, *,
                 deterministic: bool = True, block_tables=None):
        cfg = self.config
        B, T, C = x.shape
        nh, hs = cfg.n_head, cfg.head_size
        dhr = cfg.rope_head_dim
        dt = x.dtype

        ks = _mla_kernels(self, cfg, C, rope=True)
        f = slice_rows(freqs, pos, T)

        c_q = _qmm(self, x, ks["W_dq"], "W_dq")                    # (B,T,nlq)
        q_c = _qmm(self, c_q, ks["W_uq"], "W_uq").reshape(B, T, nh, hs)
        q_r = apply_rotary_emb(
            _qmm(self, c_q, ks["W_qr"], "W_qr").reshape(B, T, nh, dhr), f)
        new_c_kv = _qmm(self, x, ks["W_dkv"], "W_dkv")             # (B,T,nlkv)
        new_k_r = apply_rotary_emb(
            _qmm(self, x, ks["W_kr"], "W_kr")[:, :, None, :], f)

        scale = 1.0 / jnp.sqrt(float(hs + dhr))

        if cache is None:
            k_c = (new_c_kv @ ks["W_uk"].astype(dt)).reshape(B, T, nh, hs)
            v = (new_c_kv @ ks["W_uv"].astype(dt)).reshape(B, T, nh, hs)
            # Concatenate content+rotary features -> ONE fused SDPA call with
            # joint scale (equivalent to reference's attn_c + attn_r sum,
            # model.py:320-326, but flash-kernel friendly).
            q_cat = jnp.concatenate([q_c, q_r], axis=-1)
            k_cat = jnp.concatenate(
                [k_c, jnp.broadcast_to(new_k_r, (B, T, nh, dhr))], axis=-1)
            # fused kernels need equal head dims: zero-pad v to hs+dhr and
            # slice the output back (exact — padded cols contribute nothing)
            v_pad = jnp.pad(v, ((0, 0), (0, 0), (0, 0), (0, dhr)))
            drop_rng = None
            if cfg.dropout > 0.0 and not deterministic:
                drop_rng = self.make_rng("dropout")
            y = sdpa(q_cat, k_cat, v_pad, causal=True, scale=scale,
                     dropout_rate=cfg.dropout, dropout_rng=drop_rng,
                     impl=self.attn_impl)
            y = y[..., :hs].reshape(B, T, C)
            new_cache = None
        else:
            if block_tables is not None:
                from distributed_pytorch_tpu.ops.block_pool import (
                    paged_gather, paged_update)
                ckv_pool = paged_update(cache["c_kv"], new_c_kv, pos,
                                        block_tables)
                kr_pool = paged_update(cache["k_r"], new_k_r, pos,
                                       block_tables)
                new_cache = {"c_kv": ckv_pool, "k_r": kr_pool}
                c_kv = paged_gather(ckv_pool, block_tables)
                k_r = paged_gather(kr_pool, block_tables)
            else:
                c_kv = _update_cache(cache["c_kv"], new_c_kv, pos)
                k_r = _update_cache(cache["k_r"], new_k_r, pos)
                new_cache = {"c_kv": c_kv, "k_r": k_r}
            # decoupled-rotary scores; single shared key head broadcasts
            attn_r = jnp.einsum("btnh,bskh->bnts", q_r, k_r.astype(dt))
            from distributed_pytorch_tpu.ops.quant import \
                maybe_dequantized_param
            kuk = maybe_dequantized_param((*self.path, "W_uk"), ks["W_uk"])
            kuv = maybe_dequantized_param((*self.path, "W_uv"), ks["W_uv"])
            y = _absorbed_decode(q_c, c_kv, kuk, kuv, pos,
                                 scale, extra_scores=attn_r)

        y = _qmm(self, y, ks["W_o"], "W_o")
        y = nn.Dropout(cfg.dropout, deterministic=deterministic)(y)
        return y, new_cache


def Attention(config: LLMConfig, attn_impl: str = "auto",
              name: str = "attn") -> nn.Module:
    """Flavor dispatch (reference model.py:347-363): mha/mqa/gqa -> GQA;
    mla -> FullMLA when pos_emb == 'rope' else NaiveMLA.

    A factory (not a wrapper module) so the flavor module sits directly at
    `block_i/attn/` in the param tree with no redundant nesting level."""
    if config.attn in ("mha", "mqa", "gqa"):
        return GQA(config, attn_impl, name=name)
    if config.pos_emb == "rope":
        return FullMLA(config, attn_impl, name=name)
    return NaiveMLA(config, attn_impl, name=name)


def init_attn_cache(config: LLMConfig, batch_size: int, max_len: int,
                    dtype=jnp.float32) -> Cache:
    """Per-layer static-shape KV cache buffers (see module docstring note 3).

    `dtype=jnp.int8` builds the quantized cache (ops/quant.py): int8 code
    buffers plus f32 per-(row, kv-head) scale sidecars — the (B, S, n_kv,
    1) layout keeps `sharding.decode_cache_pspec` placing the kv-head axis
    over 'model' and slots over 'data' exactly like the code buffers.
    GQA family only; gate with `quant_kv_usable` (MLA falls back to bf16)."""
    B, S = batch_size, max_len
    if config.attn in ("mha", "mqa", "gqa"):
        shape = (B, S, config.n_kv_heads, config.head_size)
        if jnp.dtype(dtype) == jnp.int8:
            sc = (B, S, config.n_kv_heads, 1)
            return {"k": jnp.zeros(shape, jnp.int8),
                    "k_scale": jnp.zeros(sc, jnp.float32),
                    "v": jnp.zeros(shape, jnp.int8),
                    "v_scale": jnp.zeros(sc, jnp.float32)}
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
    if jnp.dtype(dtype) == jnp.int8:
        raise ValueError(
            "int8 KV cache supports the GQA family only (quant_kv_usable "
            "gates this; MLA latent caches stay in the compute dtype)")
    cache = {"c_kv": jnp.zeros((B, S, config.kv_latent_dim), dtype)}
    if config.pos_emb == "rope":
        cache["k_r"] = jnp.zeros((B, S, 1, config.rope_head_dim), dtype)
    return cache


def init_paged_attn_cache(config: LLMConfig, n_blocks: int, block_size: int,
                          dtype=jnp.float32) -> Cache:
    """Per-layer paged KV POOL buffers (module docstring note 4): the same
    leaves as `init_attn_cache` with the (B, S) row axes replaced by
    (n_blocks, block_size) — `sharding.decode_cache_pspec` still places
    the kv-head axis over 'model' and the leading (now block) axis over
    'data'. Block 0 is the null block (ops/block_pool.py)."""
    nb, bs = n_blocks, block_size
    if config.attn in ("mha", "mqa", "gqa"):
        shape = (nb, bs, config.n_kv_heads, config.head_size)
        if jnp.dtype(dtype) == jnp.int8:
            sc = (nb, bs, config.n_kv_heads, 1)
            return {"k": jnp.zeros(shape, jnp.int8),
                    "k_scale": jnp.zeros(sc, jnp.float32),
                    "v": jnp.zeros(shape, jnp.int8),
                    "v_scale": jnp.zeros(sc, jnp.float32)}
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
    if jnp.dtype(dtype) == jnp.int8:
        raise ValueError(
            "int8 KV cache supports the GQA family only (quant_kv_usable "
            "gates this; MLA latent caches stay in the compute dtype)")
    cache = {"c_kv": jnp.zeros((nb, bs, config.kv_latent_dim), dtype)}
    if config.pos_emb == "rope":
        cache["k_r"] = jnp.zeros((nb, bs, 1, config.rope_head_dim), dtype)
    return cache
