"""Pipeline parallelism: interleaved per-layer schedule, GSPMD-native.

The last member of the reference's "5D parallelism" goal
(/root/reference/README.md:7) — it has no code there. TPU-first design
instead of torch-style stage processes + P2P sends:

* The transformer blocks are STACKED on a leading layer axis (`nn.vmap`
  over `Block` with `variable_axes={'params': 0}`), so "which stage owns
  which layers" is ordinary array sharding: PartitionSpec ('pipe', ...)
  on that axis (parallel/sharding.py). No per-stage process code.
* Each scan tick applies ALL layers at once — layer i to pipeline slot i —
  on a (L, b, T, C) activation buffer, then rotates the buffer one slot
  with `jnp.roll` on the layer axis. Under a live 'pipe' mesh axis the
  roll's shard-boundary rows lower to an ICI collective-permute; rows that
  stay on-device are local copies. This is the interleaved ("looping")
  pipeline schedule: device s holds layers [s*L/S, (s+1)*L/S) as L/S
  virtual stages, so the bubble is (L-1)/(ticks) of one *layer* each, not
  of a whole stage.
* Microbatches: the (B, T) batch splits into M slices; slice m enters the
  buffer at tick m and exits fully processed at tick m + L - 1. Total
  ticks = M + L - 1; per tick a device computes its L/S layers on b=B/M
  sequences. Speedup ≈ S * M / (M + L - 1).
* The tick loop is `nn.scan` with `variable_broadcast='params'` (one set
  of weights for every tick) and per-tick dropout rngs; gradients flow
  through scan, vmap, and roll with no custom VJPs.

MoE composes since round 5: the aux-free bias rides `nn.scan`'s
`variable_carry` across ticks (per-layer-stacked by `nn.vmap`), and bubble
slots are masked out of the load statistics (see _PipeTick). Still not
supported: KV-cached decoding (restore pipeline checkpoints with
pp_stages=1 to sample; see train/checkpoint.py).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_pytorch_tpu.config import LLMConfig


def _pipe_constraint(t: jnp.ndarray) -> jnp.ndarray:
    """Pin an (L, b, ...) pipeline buffer to the mesh: layer axis over
    'pipe', and — when pp composes with dp and the microbatch divides —
    the batch axis over 'data', so each device computes only its batch
    slice of its layers every tick (same ambient-mesh pattern as the MoE
    dispatch constraint, models/mlp.py)."""
    from distributed_pytorch_tpu.parallel import context
    mesh = context.get_mesh()
    if mesh is None:
        return t
    axes: list = [None] * t.ndim
    if "pipe" in mesh.axis_names and mesh.shape["pipe"] > 1 \
            and t.shape[0] % mesh.shape["pipe"] == 0:
        axes[0] = "pipe"
    if t.ndim >= 2 and "data" in mesh.axis_names \
            and mesh.shape["data"] > 1 and t.shape[1] % mesh.shape["data"] == 0:
        axes[1] = "data"
    if all(a is None for a in axes):
        return t
    return jax.lax.with_sharding_constraint(t, NamedSharding(mesh, P(*axes)))


class _PipeTick(nn.Module):
    """One pipeline tick: inject the incoming microbatch into slot 0, apply
    layer i to slot i for all i at once (vmapped Block), emit slot L-1 as a
    finished microbatch, rotate the buffer.

    `tick` (scanned alongside the microbatch stream) marks which slots hold
    a real microbatch: slot i is valid iff 0 <= tick - i < M. MoE blocks
    get that validity as `stats_weight = valid / M`, zeroing the aux loss
    and the aux-free bias update for bubble slots whose all-zero tokens
    would otherwise route deterministically and skew the load statistics.
    The 1/M scaling makes the per-OPTIMIZER-STEP totals microbatch-count-
    invariant: the bias moves by gamma * mean-over-microbatches(delta)
    per step (matching the loop model's single full-batch gamma step
    instead of taking M full-size steps — round-5 ADVICE), and the summed
    aux term is already the per-microbatch mean (run_pipeline adds it
    without a further /M)."""

    config: LLMConfig
    attn_impl: str = "auto"
    deterministic: bool = True
    n_microbatches: int = 1

    @nn.compact
    def __call__(self, buf, x_in, tick, freqs):
        from distributed_pytorch_tpu.models.gpt import Block
        cfg = self.config
        L = cfg.n_layer
        buf = _pipe_constraint(buf.at[0].set(x_in))
        slot_mb = tick - jnp.arange(L)                   # microbatch in slot i
        valid = ((slot_mb >= 0) & (slot_mb < self.n_microbatches)
                 ).astype(jnp.float32) / self.n_microbatches  # (L,)
        # both remat granularities apply per virtual stage, mirroring the
        # loop model (gpt.py): 'attn' via Block's own remat_attn, 'block'
        # by wrapping the vmapped Block
        remat_attn = cfg.act_recomp and cfg.act_recomp_policy == "attn"
        block_cls = Block
        if cfg.act_recomp and cfg.act_recomp_policy == "block":
            block_cls = nn.remat(Block, prevent_cse=False)
        VBlock = nn.vmap(
            block_cls,
            variable_axes={"params": 0, "moe_state": 0},
            split_rngs={"params": True, "dropout": True},
            in_axes=(0, None, None, None, 0),
            out_axes=(0, None, 0),
            axis_size=cfg.n_layer,
        )
        # cache is None (decoding is unsupported under pp); aux is (L,),
        # already masked to valid slots via stats_weight
        y, _, aux = VBlock(cfg, self.attn_impl, self.deterministic,
                           remat_attn, name="stack")(buf, freqs, None, 0,
                                                     valid)
        y = _pipe_constraint(y)
        out = y[-1]
        return jnp.roll(y, 1, axis=0), (out, jnp.sum(aux))


def run_pipeline(parent: nn.Module, cfg: LLMConfig, attn_impl: str,
                 deterministic: bool, x: jnp.ndarray,
                 freqs) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Run the block stack as a pipeline; returns (hidden, total_aux).

    Must be called from inside the LLM's @nn.compact __call__ (submodules
    are created against `parent`'s scope, under the name 'blocks').

    MoE composition: `total_aux` is the sum over layers of the MEAN
    per-microbatch aux loss — at M=1 bit-identical to the loop model's
    full-batch aux; at M>1 the load statistics are per-microbatch, the
    same granularity the reference's DDP training has per-rank (no aux
    sync anywhere in kaggle-zero*.py). The aux-free bias updates once per
    (layer, microbatch) with the delta scaled by 1/M (stats_weight in
    _PipeTick), so per optimizer step the bias moves by gamma * the mean
    microbatch delta — invariant to M, matching the loop model beyond
    M=1 (round-5 ADVICE); bubble slots are masked out entirely
    (stats_weight=0), so no zero-token routing pollutes either statistic."""
    B, T, C = x.shape
    L = cfg.n_layer
    M = cfg.pp_microbatches
    if M <= 0:  # auto: enough microbatches to keep the bubble small
        M = min(B, 2 * cfg.pp_stages)
        while B % M:
            M -= 1
    assert B % M == 0, (
        f"pp_microbatches {M} must divide batch size {B}")
    b = B // M
    ticks = M + L - 1

    mb = x.reshape(M, b, T, C)
    pad = jnp.zeros((L - 1, b, T, C), x.dtype)
    xs_in = jnp.concatenate([mb, pad], axis=0)          # (ticks, b, T, C)

    # moe_state rides the scan carry so per-tick bias updates accumulate —
    # but ONLY when the caller made it mutable (training). In read-only
    # applies (eval/estimate_loss) flax drops immutable collections from
    # the carry output, so carrying would mismatch the scan's carry pytree;
    # broadcast is correct there (no writes to thread).
    if parent.is_mutable_collection("moe_state"):
        state_kw: dict = {"variable_carry": "moe_state",
                          "variable_broadcast": "params"}
    else:
        state_kw = {"variable_broadcast": ["params", "moe_state"]}
    ScanTick = nn.scan(
        _PipeTick,
        split_rngs={"params": False, "dropout": True},
        in_axes=(0, 0, nn.broadcast),
        out_axes=0,
        length=ticks,
        **state_kw,
    )
    buf0 = _pipe_constraint(jnp.zeros((L, b, T, C), x.dtype))
    _, (outs, aux_per_tick) = ScanTick(
        cfg, attn_impl, deterministic, M,
        name="blocks", parent=parent)(buf0, xs_in,
                                      jnp.arange(ticks, dtype=jnp.int32),
                                      freqs)
    # outs[t] is valid for t >= L-1: microbatch t-(L-1) fully processed;
    # aux_per_tick sums per-layer aux already weighted by 1/M
    # (stats_weight), so the plain sum IS the per-microbatch mean
    return outs[L - 1:].reshape(B, T, C), jnp.sum(aux_per_tick)


def stack_block_params(params: dict, n_layer: int) -> dict:
    """Restructure loop-model params (block_0..block_{L-1} siblings) into
    the pipeline layout ({'blocks': {'stack': <leading-L leaves>}}), leaving
    all other entries (tkn_emb, ln_f, pos_emb) untouched.

    Used at state init so a pipeline run starts from bit-identical weights
    to the loop/oracle model (nn.vmap's split param rngs would otherwise
    give different init values) — this is what makes the pp-vs-single
    parity test meaningful."""
    blocks = [params[f"block_{i}"] for i in range(n_layer)]
    stacked = jax.tree_util.tree_map(lambda *ls: jnp.stack(ls, axis=0),
                                     *blocks)
    out = {k: v for k, v in params.items() if not k.startswith("block_")}
    out["blocks"] = {"stack": stacked}
    return out


def unstack_block_params(params: dict, n_layer: int) -> dict:
    """Inverse of stack_block_params (pipeline checkpoint -> loop layout,
    e.g. to sample from a pp-trained model with pp_stages=1)."""
    stacked = params["blocks"]["stack"]
    out = {k: v for k, v in params.items() if k != "blocks"}
    for i in range(n_layer):
        out[f"block_{i}"] = jax.tree_util.tree_map(lambda l, i=i: l[i],
                                                   stacked)
    return out
