"""Pipeline parallelism: interleaved-1F1B + per-layer carry schedules,
GSPMD-native.

The last member of the reference's "5D parallelism" goal
(/root/reference/README.md:7) — it has no code there. Two schedules share
one stacked-parameter layout (so checkpoints, sharding specs and the
stack/unstack converters are schedule-agnostic):

* '1f1b' (default for dense models): the interleaved-1F1B schedule
  (Megatron-LM, PAPERS.md). Each of the S stages holds `vpp` virtual
  chunks of Lc = L/(S*vpp) layers; chunk q (layers [q*Lc, (q+1)*Lc))
  belongs to stage q mod S, and microbatch m = g*S + j runs chunk q at
  tick g*S*vpp + j + q. The activation buffer is (S, b, T, C) — one slot
  per STAGE, not per layer — rotated one slot per tick (jnp.roll, an ICI
  collective-permute under a live 'pipe' axis; the wrap row S-1 -> 0 IS
  the chunk hand-back of the interleaved schedule). The backward is
  autodiff's exact reverse of the forward scan — the mirrored 1F1B
  cooldown — so per optimizer step the timeline is warmup, fwd/bwd
  steady state, cooldown with bubble fraction (S-1)/(S-1 + vpp*M)
  ~ (S-1)/(vpp*M): `vpp*M` work slots against the carry schedule's
  all-L-layers-every-tick buffer. See schedule_timeline() for the
  per-(tick, stage) phase rows train/telemetry.py records.
* 'carry': the round-5 per-layer carry schedule below — still the MoE
  path (its per-tick validity masking keeps the router load statistics
  exact) and the fallback when L % (S*vpp) != 0.

TPU-first design instead of torch-style stage processes + P2P sends:

* The transformer blocks are STACKED on a leading layer axis (`nn.vmap`
  over `Block` with `variable_axes={'params': 0}`), so "which stage owns
  which layers" is ordinary array sharding: PartitionSpec ('pipe', ...)
  on that axis (parallel/sharding.py). No per-stage process code.
* Each scan tick applies ALL layers at once — layer i to pipeline slot i —
  on a (L, b, T, C) activation buffer, then rotates the buffer one slot
  with `jnp.roll` on the layer axis. Under a live 'pipe' mesh axis the
  roll's shard-boundary rows lower to an ICI collective-permute; rows that
  stay on-device are local copies. This is the interleaved ("looping")
  pipeline schedule: device s holds layers [s*L/S, (s+1)*L/S) as L/S
  virtual stages, so the bubble is (L-1)/(ticks) of one *layer* each, not
  of a whole stage.
* Microbatches: the (B, T) batch splits into M slices; slice m enters the
  buffer at tick m and exits fully processed at tick m + L - 1. Total
  ticks = M + L - 1; per tick a device computes its L/S layers on b=B/M
  sequences. Speedup ≈ S * M / (M + L - 1).
* The tick loop is `nn.scan` with `variable_broadcast='params'` (one set
  of weights for every tick) and per-tick dropout rngs; gradients flow
  through scan, vmap, and roll with no custom VJPs.

MoE composes since round 5: the aux-free bias rides `nn.scan`'s
`variable_carry` across ticks (per-layer-stacked by `nn.vmap`), and bubble
slots are masked out of the load statistics (see _PipeTick). Still not
supported: KV-cached decoding (restore pipeline checkpoints with
pp_stages=1 to sample; see train/checkpoint.py).
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_pytorch_tpu.config import LLMConfig, knob


def _pipe_constraint(t: jnp.ndarray) -> jnp.ndarray:
    """Pin an (L, b, ...) pipeline buffer to the mesh: layer axis over
    'pipe', and — when pp composes with dp and the microbatch divides —
    the batch axis over 'data', so each device computes only its batch
    slice of its layers every tick (same ambient-mesh pattern as the MoE
    dispatch constraint, models/mlp.py)."""
    from distributed_pytorch_tpu.parallel import context
    mesh = context.get_mesh()
    if mesh is None:
        return t
    axes: list = [None] * t.ndim
    if "pipe" in mesh.axis_names and mesh.shape["pipe"] > 1 \
            and t.shape[0] % mesh.shape["pipe"] == 0:
        axes[0] = "pipe"
    if t.ndim >= 2 and "data" in mesh.axis_names \
            and mesh.shape["data"] > 1 and t.shape[1] % mesh.shape["data"] == 0:
        axes[1] = "data"
    if all(a is None for a in axes):
        return t
    return jax.lax.with_sharding_constraint(t, NamedSharding(mesh, P(*axes)))


def resolve_vpp(cfg: LLMConfig) -> int:
    """Virtual chunks per stage for the 1f1b schedule: the PP_VPP knob,
    else cfg.pp_vpp, else auto = n_layer/pp_stages (one-layer chunks — the
    carry schedule's interleave granularity, minimal bubble)."""
    vpp = knob("PP_VPP") or cfg.pp_vpp
    if vpp <= 0:
        vpp = max(1, cfg.n_layer // max(cfg.pp_stages, 1))
    return vpp


def resolve_schedule(cfg: LLMConfig) -> str:
    """'1f1b' | 'carry' for this config. Resolution order: PP_SCHEDULE
    knob > cfg.pp_schedule > 'auto'. Auto picks 1f1b whenever it is
    admissible (dense model, chunk count divides the layer count) and
    falls back to carry otherwise; asking for 1f1b explicitly when it is
    not admissible fails loudly instead of silently degrading."""
    choice = knob("PP_SCHEDULE") or cfg.pp_schedule
    if choice not in ("auto", "carry", "1f1b"):
        raise ValueError(f"unknown pp schedule {choice!r} "
                         "(expected auto|carry|1f1b)")
    vpp = resolve_vpp(cfg)
    admissible = (not cfg.moe
                  and cfg.n_layer % (max(cfg.pp_stages, 1) * vpp) == 0)
    if choice == "1f1b" and not admissible:
        raise ValueError(
            f"pp_schedule=1f1b needs a dense model with pp_stages*vpp "
            f"({cfg.pp_stages}*{vpp}) dividing n_layer ({cfg.n_layer}); "
            f"MoE models use the carry schedule (its per-tick validity "
            f"masking keeps the router load statistics exact)")
    if choice == "auto":
        return "1f1b" if admissible else "carry"
    return choice


@dataclasses.dataclass(frozen=True)
class _Schedule:
    """Static interleaved-1F1B tick table (pure numpy — computed once per
    trace, baked into the program as scan xs)."""

    n_stages: int
    vpp: int
    n_microbatches: int
    ticks: int
    q_idx: np.ndarray       # (ticks, S) chunk each stage computes (a
                            # stage-owned dummy chunk on idle ticks)
    valid: np.ndarray       # (ticks, S) bool: real microbatch in flight
    mb_idx: np.ndarray      # (ticks, S) microbatch per stage (-1 = idle)
    inject: np.ndarray      # (ticks,) 1 when a microbatch enters stage 0
    inject_src: np.ndarray  # (ticks,) which microbatch (0 on no-op ticks)
    exit_ticks: np.ndarray  # (M,) tick whose stage-(S-1) output finishes m


def _build_1f1b_schedule(S: int, vpp: int, M: int) -> _Schedule:
    """Interleaved-1F1B placement: chunk q (of S*vpp) belongs to stage
    q mod S; microbatch m = g*S + j computes chunk q at tick
    g*S*vpp + j + q. Per (stage, tick) the decomposition
    u = t - s = S*(g*vpp + v) + j is unique, so a stage computes at most
    one (chunk, microbatch) per tick, every chunk's input is exactly the
    previous tick's roll-neighbour output (or the injected embedding for
    chunk 0), and the schedule is valid for ANY M (not only S | M)."""
    n_chunks = S * vpp
    g_last, j_last = (M - 1) // S, (M - 1) % S
    ticks = g_last * n_chunks + j_last + n_chunks
    q_idx = np.zeros((ticks, S), np.int32)
    valid = np.zeros((ticks, S), bool)
    mb_idx = np.full((ticks, S), -1, np.int32)
    inject = np.zeros((ticks,), np.int32)
    inject_src = np.zeros((ticks,), np.int32)
    exit_ticks = np.zeros((M,), np.int32)
    for t in range(ticks):
        for s in range(S):
            u = t - s
            if u < 0:
                q_idx[t, s] = s  # idle: compute the stage's own chunk 0
                continue
            j, r = u % S, u // S
            v, g = r % vpp, r // vpp
            m = g * S + j
            q = v * S + s
            q_idx[t, s] = q
            if m < M:
                valid[t, s] = True
                mb_idx[t, s] = m
                if q == 0:
                    inject[t] = 1
                    inject_src[t] = m
                if q == n_chunks - 1:
                    exit_ticks[m] = t
    return _Schedule(S, vpp, M, ticks, q_idx, valid, mb_idx, inject,
                     inject_src, exit_ticks)


def schedule_timeline(n_stages: int, vpp: int, n_microbatches: int
                      ) -> tuple[list, dict]:
    """Per-(tick, stage) phase rows of one 1f1b optimizer step + a bubble
    summary — the payload train/loop.py hands train/telemetry.py and the
    CPU A/B test checks against the (S-1)/(vpp*M) model.

    The forward half comes straight from the static schedule table; the
    backward half is its exact mirror (autodiff reverses the forward
    scan tick-for-tick — reverse-mode through `jnp.roll` is a roll the
    other way, so the cooldown is the mirrored warmup). Rows:
    {tick, stage, chunk, microbatch, phase('fwd'|'bwd')}. Summary:
    {ticks, busy_per_stage, bubble_frac, bubble_model} where
    bubble_frac = 1 - busy/ticks (measured on the table) and
    bubble_model = (S-1)/(vpp*M) (the Megatron interleaved-1F1B model —
    the denominators differ by the warmup slots, which is why the test
    bar is 20%, not equality)."""
    sched = _build_1f1b_schedule(n_stages, vpp, n_microbatches)
    fwd = [{"tick": int(t), "stage": int(s),
            "chunk": int(sched.q_idx[t, s]),
            "microbatch": int(sched.mb_idx[t, s]), "phase": "fwd"}
           for t in range(sched.ticks) for s in range(n_stages)
           if sched.valid[t, s]]
    total = 2 * sched.ticks
    bwd = [{**row, "tick": total - 1 - row["tick"], "phase": "bwd"}
           for row in fwd]
    rows = sorted(fwd + bwd, key=lambda r: (r["tick"], r["stage"]))
    busy = 2 * n_microbatches * vpp
    summary = {
        "schedule": "1f1b", "n_stages": n_stages, "vpp": vpp,
        "n_microbatches": n_microbatches, "ticks": total,
        "busy_per_stage": busy,
        "bubble_frac": round(1.0 - busy / total, 6),
        "bubble_model": round((n_stages - 1)
                              / max(vpp * n_microbatches, 1), 6),
    }
    return rows, summary


def _run_1f1b(parent: nn.Module, cfg: LLMConfig, attn_impl: str,
              deterministic: bool, x: jnp.ndarray, freqs,
              M: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The interleaved-1F1B apply path (see module docstring for the
    schedule math). Parameters stay in the carry schedule's stacked
    layout — params['blocks']['stack'] with a leading (L,) axis — read
    directly from the bound scope and regrouped (S*vpp, Lc, ...)
    chunk-major, so checkpoints and sharding specs are schedule-agnostic;
    each tick gathers the (S,)-vector of active chunks (a dynamic-slice
    per stage under GSPMD). The (S, b, T, C) activation buffer rolls one
    stage per tick; the wrap row IS the interleave hand-back. Backward is
    autodiff through the forward scan — the mirrored 1F1B cooldown."""
    from distributed_pytorch_tpu.models.gpt import Block
    B, T, C = x.shape
    S, L = cfg.pp_stages, cfg.n_layer
    vpp = resolve_vpp(cfg)
    n_chunks = S * vpp
    Lc = L // n_chunks
    b = B // M
    sched = _build_1f1b_schedule(S, vpp, M)

    stacked = parent.variables["params"]["blocks"]["stack"]
    chunks = jax.tree_util.tree_map(
        lambda leaf: leaf.reshape((n_chunks, Lc) + leaf.shape[1:]), stacked)
    mb = x.reshape(M, b, T, C)

    remat_attn = cfg.act_recomp and cfg.act_recomp_policy == "attn"
    block = Block(cfg, attn_impl, deterministic, remat_attn)
    need_rng = (not deterministic) and cfg.dropout > 0

    def apply_layer(p, h, key):
        rngs = {"dropout": key} if need_rng else None
        out, _, _ = block.apply({"params": p}, h, freqs, rngs=rngs)
        return out

    if cfg.act_recomp and cfg.act_recomp_policy == "block":
        apply_layer = jax.checkpoint(apply_layer, prevent_cse=False)

    if need_rng:
        tick_keys = jax.random.split(parent.make_rng("dropout"),
                                     sched.ticks)
    else:
        tick_keys = jnp.zeros((sched.ticks, 2), jnp.uint32)
    stage_ids = jnp.arange(S, dtype=jnp.int32)

    def tick_fn(buf, xs):
        q_t, inj, src, key = xs
        incoming = jnp.take(mb, src, axis=0)
        buf = buf.at[0].set(jnp.where(inj > 0, incoming, buf[0]))
        h = _pipe_constraint(buf)
        params_t = jax.tree_util.tree_map(
            lambda c: jnp.take(c, q_t, axis=0), chunks)  # (S, Lc, ...)
        for layer in range(Lc):
            p_l = jax.tree_util.tree_map(
                lambda c, layer=layer: c[:, layer], params_t)
            if need_rng:  # one dropout stream per (tick, stage, layer)
                keys_s = jax.vmap(
                    lambda i, layer=layer: jax.random.fold_in(
                        jax.random.fold_in(key, i), layer))(stage_ids)
            else:
                keys_s = jnp.zeros((S,), jnp.uint32)
            h = jax.vmap(apply_layer)(p_l, h, keys_s)
        h = _pipe_constraint(h)
        return jnp.roll(h, 1, axis=0), h[-1]

    buf0 = _pipe_constraint(jnp.zeros((S, b, T, C), x.dtype))
    xs = (jnp.asarray(sched.q_idx), jnp.asarray(sched.inject),
          jnp.asarray(sched.inject_src), tick_keys)
    _, outs = jax.lax.scan(tick_fn, buf0, xs)
    final = jnp.take(outs, jnp.asarray(sched.exit_ticks), axis=0)
    return final.reshape(B, T, C), jnp.float32(0.0)


class _PipeTick(nn.Module):
    """One pipeline tick: inject the incoming microbatch into slot 0, apply
    layer i to slot i for all i at once (vmapped Block), emit slot L-1 as a
    finished microbatch, rotate the buffer.

    `tick` (scanned alongside the microbatch stream) marks which slots hold
    a real microbatch: slot i is valid iff 0 <= tick - i < M. MoE blocks
    get that validity as `stats_weight = valid / M`, zeroing the aux loss
    and the aux-free bias update for bubble slots whose all-zero tokens
    would otherwise route deterministically and skew the load statistics.
    The 1/M scaling makes the per-OPTIMIZER-STEP totals microbatch-count-
    invariant: the bias moves by gamma * mean-over-microbatches(delta)
    per step (matching the loop model's single full-batch gamma step
    instead of taking M full-size steps — round-5 ADVICE), and the summed
    aux term is already the per-microbatch mean (run_pipeline adds it
    without a further /M)."""

    config: LLMConfig
    attn_impl: str = "auto"
    deterministic: bool = True
    n_microbatches: int = 1

    @nn.compact
    def __call__(self, buf, x_in, tick, freqs):
        from distributed_pytorch_tpu.models.gpt import Block
        cfg = self.config
        L = cfg.n_layer
        buf = _pipe_constraint(buf.at[0].set(x_in))
        slot_mb = tick - jnp.arange(L)                   # microbatch in slot i
        valid = ((slot_mb >= 0) & (slot_mb < self.n_microbatches)
                 ).astype(jnp.float32) / self.n_microbatches  # (L,)
        # both remat granularities apply per virtual stage, mirroring the
        # loop model (gpt.py): 'attn' via Block's own remat_attn, 'block'
        # by wrapping the vmapped Block
        remat_attn = cfg.act_recomp and cfg.act_recomp_policy == "attn"
        block_cls = Block
        if cfg.act_recomp and cfg.act_recomp_policy == "block":
            block_cls = nn.remat(Block, prevent_cse=False)
        VBlock = nn.vmap(
            block_cls,
            variable_axes={"params": 0, "moe_state": 0},
            split_rngs={"params": True, "dropout": True},
            in_axes=(0, None, None, None, 0),
            out_axes=(0, None, 0),
            axis_size=cfg.n_layer,
        )
        # cache is None (decoding is unsupported under pp); aux is (L,),
        # already masked to valid slots via stats_weight
        y, _, aux = VBlock(cfg, self.attn_impl, self.deterministic,
                           remat_attn, name="stack")(buf, freqs, None, 0,
                                                     valid)
        y = _pipe_constraint(y)
        out = y[-1]
        return jnp.roll(y, 1, axis=0), (out, jnp.sum(aux))


def run_pipeline(parent: nn.Module, cfg: LLMConfig, attn_impl: str,
                 deterministic: bool, x: jnp.ndarray,
                 freqs) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Run the block stack as a pipeline; returns (hidden, total_aux).

    Must be called from inside the LLM's @nn.compact __call__ (submodules
    are created against `parent`'s scope, under the name 'blocks').

    MoE composition: `total_aux` is the sum over layers of the MEAN
    per-microbatch aux loss — at M=1 bit-identical to the loop model's
    full-batch aux; at M>1 the load statistics are per-microbatch, the
    same granularity the reference's DDP training has per-rank (no aux
    sync anywhere in kaggle-zero*.py). The aux-free bias updates once per
    (layer, microbatch) with the delta scaled by 1/M (stats_weight in
    _PipeTick), so per optimizer step the bias moves by gamma * the mean
    microbatch delta — invariant to M, matching the loop model beyond
    M=1 (round-5 ADVICE); bubble slots are masked out entirely
    (stats_weight=0), so no zero-token routing pollutes either statistic."""
    B, T, C = x.shape
    L = cfg.n_layer
    M = cfg.pp_microbatches
    if M <= 0:  # auto: enough microbatches to keep the bubble small
        M = min(B, 2 * cfg.pp_stages)
        while B % M:
            M -= 1
    assert B % M == 0, (
        f"pp_microbatches {M} must divide batch size {B}")
    # Init ALWAYS runs the carry path: nn.scan(nn.vmap(Block)) creates the
    # stacked params['blocks']['stack'] tree, and keeping that the single
    # creator makes the param layout (and so checkpoints/sharding specs)
    # schedule-invariant. The 1f1b apply path reads the same tree back.
    if not parent.is_initializing() and resolve_schedule(cfg) == "1f1b":
        return _run_1f1b(parent, cfg, attn_impl, deterministic, x, freqs, M)
    b = B // M
    ticks = M + L - 1

    mb = x.reshape(M, b, T, C)
    pad = jnp.zeros((L - 1, b, T, C), x.dtype)
    xs_in = jnp.concatenate([mb, pad], axis=0)          # (ticks, b, T, C)

    # moe_state rides the scan carry so per-tick bias updates accumulate —
    # but ONLY when the caller made it mutable (training). In read-only
    # applies (eval/estimate_loss) flax drops immutable collections from
    # the carry output, so carrying would mismatch the scan's carry pytree;
    # broadcast is correct there (no writes to thread).
    if parent.is_mutable_collection("moe_state"):
        state_kw: dict = {"variable_carry": "moe_state",
                          "variable_broadcast": "params"}
    else:
        state_kw = {"variable_broadcast": ["params", "moe_state"]}
    ScanTick = nn.scan(
        _PipeTick,
        split_rngs={"params": False, "dropout": True},
        in_axes=(0, 0, nn.broadcast),
        out_axes=0,
        length=ticks,
        **state_kw,
    )
    buf0 = _pipe_constraint(jnp.zeros((L, b, T, C), x.dtype))
    _, (outs, aux_per_tick) = ScanTick(
        cfg, attn_impl, deterministic, M,
        name="blocks", parent=parent)(buf0, xs_in,
                                      jnp.arange(ticks, dtype=jnp.int32),
                                      freqs)
    # outs[t] is valid for t >= L-1: microbatch t-(L-1) fully processed;
    # aux_per_tick sums per-layer aux already weighted by 1/M
    # (stats_weight), so the plain sum IS the per-microbatch mean
    return outs[L - 1:].reshape(B, T, C), jnp.sum(aux_per_tick)


def stack_block_params(params: dict, n_layer: int) -> dict:
    """Restructure loop-model params (block_0..block_{L-1} siblings) into
    the pipeline layout ({'blocks': {'stack': <leading-L leaves>}}), leaving
    all other entries (tkn_emb, ln_f, pos_emb) untouched.

    Used at state init so a pipeline run starts from bit-identical weights
    to the loop/oracle model (nn.vmap's split param rngs would otherwise
    give different init values) — this is what makes the pp-vs-single
    parity test meaningful."""
    blocks = [params[f"block_{i}"] for i in range(n_layer)]
    stacked = jax.tree_util.tree_map(lambda *ls: jnp.stack(ls, axis=0),
                                     *blocks)
    out = {k: v for k, v in params.items() if not k.startswith("block_")}
    out["blocks"] = {"stack": stacked}
    return out


def unstack_block_params(params: dict, n_layer: int) -> dict:
    """Inverse of stack_block_params (pipeline checkpoint -> loop layout,
    e.g. to sample from a pp-trained model with pp_stages=1)."""
    stacked = params["blocks"]["stack"]
    out = {k: v for k, v in params.items() if k != "blocks"}
    for i in range(n_layer):
        out[f"block_{i}"] = jax.tree_util.tree_map(lambda l, i=i: l[i],
                                                   stacked)
    return out
