"""Feed-forward layers: dense MLP (13 activations incl. swiglu) and
DeepSeekMoE with aux-loss-free balancing.

Reference parity map:
* `MLP` — reference single-gpu/model.py:365-398: bias-free up/down
  projections; swiglu as ONE fused 2*up_dim projection split in half
  (reference :371-373,389-391); otherwise an activation map of 12 choices.
  Divergence: the reference's 'glu' entry is shape-inconsistent (nn.GLU
  halves the feature dim, so its c_proj would reject the result); here
  'glu' is implemented like swiglu but with a sigmoid gate, which is what
  GLU means — documented rather than reproduced as a crash.
* `MoE` — reference model.py:409-506 (DeepSeekMoE, arXiv:2412.19437 flavor):
  first n_shared experts always-on bypassing the router; top-k routing over
  the remaining n_routed experts (n_act INCLUDES shared, reference :425);
  two balancing modes: (a) aux-loss-free — a non-learned bias added to
  router logits for top-k *selection only*, gates from un-biased logits
  (reference :451-458), bias nudged toward uniform load at speed gamma
  during training (reference :466-470), plus complementary aux loss
  alpha * n_routed * sum(pi*fi) (reference :472-474); (b) classic aux loss
  coeff * n_routed * sum(pi*fi) (reference :476-487).

TPU-first design (SURVEY §7 hard part (a)):
* Expert weights are STACKED with a leading (n_exp, ...) axis — one pytree
  leaf per projection, shardable over an 'expert' mesh axis for expert
  parallelism (capability absent from the reference, whose dispatch is a
  data-dependent Python loop over experts, model.py:489-506).
* Dispatch is static-shape, three modes (LLMConfig.moe_impl):
  - 'dense' evaluates every routed expert on every token and combines with
    a (tokens, n_routed) gate matrix that is zero outside the top-k —
    bitwise-equal semantics to the reference loop (no capacity limit, no
    token dropping) at n_routed/k extra FLOPs; good for small expert
    counts and as the semantics oracle.
  - 'scatter' is the capacity-bounded sort-based dispatch: assignments are
    stable-sorted by expert, each expert takes its first
    `capacity = ceil(capacity_factor * N*k/E)` tokens into an (E, cap, C)
    buffer (later tokens are DROPPED, GShard-style position priority —
    the dropped fraction is surfaced as the `dropped_frac` moe_state
    metric / `moe_dropped_frac` train metric), expert FFNs run batched
    over the leading expert axis, and results scatter-add back weighted
    by their gates. O(active) FLOPs like the reference's Python loop
    (model.py:489-506) but static-shape for XLA; the (E, cap, C) buffers
    carry a 'expert'-axis sharding constraint so under the ep recipe
    GSPMD turns dispatch/return into all-to-alls over the expert mesh
    axis.
  - 'grouped' is the dropless Pallas ragged grouped-matmul dispatch
    (ops/grouped_matmul.py, MegaBlocks arXiv:2211.15841 flavor): tokens
    stay packed in one expert-sorted buffer (no capacity padding, zero
    dropped assignments), every expert's x_e @ W_e streams weight tiles
    per group, the shared experts ride the same kernel as always-on
    groups, and the combine gates are applied at the kernel's output
    write. Falls back to 'dense' — identical semantics, more FLOPs —
    where the kernel can't run (pipeline-vmapped blocks, live 'model' or
    'seq' mesh axes, non-lane-aligned widths; see grouped_usable).
* The aux-free bias is cross-batch mutable state; it lives in the 'moe_state'
  variable collection, carried in the train state. Under pjit the batch is
  global, so load statistics (and hence the bias update) are computed over
  the GLOBAL batch — unlike the reference, where each DDP rank's bias
  drifts independently (no sync anywhere in kaggle-zero*.py). Documented
  intentional improvement.
"""

from __future__ import annotations

import math
from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_pytorch_tpu.config import LLMConfig

_DENSE_INIT = nn.initializers.normal(stddev=0.02)


def _activation(name: str) -> Callable[[jnp.ndarray], jnp.ndarray]:
    name = name.lower()
    table = {
        "relu": jax.nn.relu,
        "gelu": lambda x: jax.nn.gelu(x, approximate=False),
        "swish": jax.nn.silu,
        "silu": jax.nn.silu,
        "mish": jax.nn.mish,
        "selu": jax.nn.selu,
        "celu": jax.nn.celu,
        "elu": jax.nn.elu,
        "sigmoid": jax.nn.sigmoid,
        "lrelu": lambda x: jax.nn.leaky_relu(x, negative_slope=0.01),
        "tanh": jnp.tanh,
    }
    return table.get(name, lambda x: jax.nn.gelu(x, approximate=False))


def _is_gated(name: str) -> bool:
    return name.lower() in ("swiglu", "glu")


def mlp_apply(x: jnp.ndarray, w_fc: jnp.ndarray, w_proj: jnp.ndarray,
              non_linearity: str, *, overlap: bool = False,
              qnames: tuple | None = None) -> jnp.ndarray:
    """Apply one MLP given its kernels; shared by dense MLP and experts.

    Gated variants ('swiglu'/'glu'): w_fc is (C, 2*up_dim), split in half,
    h = act(x1) * x2 (reference model.py:389-391). Others: (C, up_dim).

    `overlap=True` (dense MLP only — expert kernels are 3D/vmapped) offers
    both matmuls to the collective-matmul dispatcher
    (ops/collective_matmul.py): under an active OVERLAP=on ZeRO-3 step the
    param all-gather runs as a ppermute ring fused with the matmul;
    otherwise the dispatcher declines and the plain `@` below is
    bit-identical to the pre-overlap code path.

    `qnames=(fc_path, proj_path)` (dense MLP only) offers both matmuls to
    the weight-only-int8 store first (ops/quant.py): under an engine
    decode step with quantized params they read int8 codes +
    per-output-channel scales (applied before the gating split — exact,
    the scale is per column of the fused fc output); elsewhere the lookup
    misses and nothing changes.
    """
    h = None
    if qnames is not None:
        from distributed_pytorch_tpu.ops.quant import maybe_quantized_matmul
        h = maybe_quantized_matmul(x, qnames[0])
    if h is None and overlap:
        from distributed_pytorch_tpu.ops.collective_matmul import (
            maybe_overlap_matmul)
        h = maybe_overlap_matmul(x, w_fc, names=("c_fc",))
    if h is None:
        h = x @ w_fc
    if _is_gated(non_linearity):
        x1, x2 = jnp.split(h, 2, axis=-1)
        gate = jax.nn.silu(x1) if non_linearity.lower() == "swiglu" \
            else jax.nn.sigmoid(x1)
        h = gate * x2
    else:
        h = _activation(non_linearity)(h)
    y = None
    if qnames is not None:
        from distributed_pytorch_tpu.ops.quant import maybe_quantized_matmul
        y = maybe_quantized_matmul(h, qnames[1])
    if y is None and overlap:
        from distributed_pytorch_tpu.ops.collective_matmul import (
            maybe_overlap_matmul)
        y = maybe_overlap_matmul(h, w_proj, names=("c_proj",))
    if y is None:
        y = h @ w_proj
    return y


class MLP(nn.Module):
    """Dense feed-forward block (reference model.py:365-398)."""

    config: LLMConfig

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True):
        cfg = self.config
        C, up = cfg.n_embd, cfg.up_dim
        fc_out = 2 * up if _is_gated(cfg.non_linearity) else up
        w_fc = self.param("c_fc", _DENSE_INIT, (C, fc_out), jnp.float32)
        w_proj = self.param("c_proj", _DENSE_INIT, (up, C), jnp.float32)
        y = mlp_apply(x, w_fc.astype(x.dtype), w_proj.astype(x.dtype),
                      cfg.non_linearity, overlap=True,
                      qnames=((*self.path, "c_fc"), (*self.path, "c_proj")))
        return nn.Dropout(cfg.dropout, deterministic=deterministic)(y)


def _expert_constraint(t: jnp.ndarray) -> jnp.ndarray:
    """Pin a (n_experts, capacity, ...) dispatch buffer to the mesh: expert
    axis over 'expert' (GSPMD lowers dispatch/return as all-to-alls over
    ICI instead of gathering all tokens onto every expert shard) and the
    capacity axis over 'data'. The latter is what keeps per-device dispatch
    memory independent of dp size (round-3 VERDICT #4): global capacity
    grows with the global batch, but each device holds only its
    cap/dp slice — without it, a dp x ep mesh materializes
    (E/ep, cf*N_global*k/E, C) per device."""
    from distributed_pytorch_tpu.parallel import context
    mesh = context.get_mesh()
    if mesh is None:
        return t
    axes: list = [None] * t.ndim
    if "expert" in mesh.axis_names and mesh.shape["expert"] > 1:
        axes[0] = "expert"
    if t.ndim >= 2 and "data" in mesh.axis_names \
            and mesh.shape["data"] > 1 and t.shape[1] % mesh.shape["data"] == 0:
        axes[1] = "data"
    if all(a is None for a in axes):
        return t
    return jax.lax.with_sharding_constraint(t, NamedSharding(mesh, P(*axes)))


def scatter_dispatch(x_flat: jnp.ndarray, topk_idx: jnp.ndarray,
                     topk_gates: jnp.ndarray, experts_fc: jnp.ndarray,
                     experts_proj: jnp.ndarray, *, non_linearity: str,
                     capacity: int) -> jnp.ndarray:
    """Capacity-bounded sort-based routed-expert dispatch.

    x_flat (N, C); topk_idx/topk_gates (N, k) over E routed experts whose
    stacked kernels are experts_fc (E, C, fc_out) / experts_proj (E, up, C).
    Returns (N, C). Tokens beyond an expert's `capacity` are dropped
    (earlier tokens win — GShard position priority); with capacity >=
    max expert load this is numerically the reference loop
    (single-gpu/model.py:489-506) up to summation order.
    """
    N, k = topk_idx.shape
    E = experts_fc.shape[0]
    dt = x_flat.dtype

    flat_e = topk_idx.reshape(-1)                          # (N*k,)
    flat_g = topk_gates.reshape(-1).astype(jnp.float32)
    flat_t = jnp.arange(N * k, dtype=jnp.int32) // k       # owning token

    order = jnp.argsort(flat_e, stable=True)               # group by expert
    se, st, sg = flat_e[order], flat_t[order], flat_g[order]

    counts = jnp.zeros((E,), jnp.int32).at[flat_e].add(1)
    starts = jnp.cumsum(counts) - counts                   # segment offsets
    pos = jnp.arange(N * k, dtype=jnp.int32) - starts[se]  # rank within expert
    keep = pos < capacity

    # slot in the flattened (E*capacity) buffer; dropped assignments all
    # land in one overflow cell that is sliced away
    slot = jnp.where(keep, se * capacity + pos, E * capacity)
    buf_tok = jnp.zeros((E * capacity + 1,), jnp.int32).at[slot].set(st)
    buf_gate = jnp.zeros((E * capacity + 1,), jnp.float32).at[slot].set(sg)
    tok_grid = buf_tok[:-1].reshape(E, capacity)
    gate_grid = buf_gate[:-1].reshape(E, capacity)
    # unfilled slots keep token 0 with gate 0: computed then zeroed — wasted
    # lanes, never wrong

    xg = _expert_constraint(x_flat[tok_grid])              # (E, cap, C)

    def one(wf, wp, xe):
        return mlp_apply(xe, wf.astype(dt), wp.astype(dt), non_linearity)

    y = jax.vmap(one)(experts_fc, experts_proj, xg)        # (E, cap, C)
    y = _expert_constraint(y * gate_grid[..., None].astype(dt))

    return jnp.zeros_like(x_flat).at[tok_grid.reshape(-1)].add(
        y.reshape(E * capacity, -1))


class MoE(nn.Module):
    """DeepSeekMoE layer (reference model.py:409-506). Returns (y, aux_loss).

    Expert parameters are stacked: experts_fc (n_exp, C, fc_out) and
    experts_proj (n_exp, up, C); expert e of the reference's ModuleList is
    slice [e]. First n_shared experts are shared (always active)."""

    config: LLMConfig

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True, stats_weight=None):
        """`stats_weight` gates the cross-batch statistics (aux loss and the
        aux-free bias update) without touching the token outputs: the
        pipeline schedule (models/pipeline.py) passes 0.0 for buffer slots
        holding no real microbatch so their deterministic zero-token routing
        can't pollute the load balance, and 1/M for valid slots so the
        per-optimizer-step bias movement and aux total are microbatch-
        count-invariant. None/1.0 elsewhere."""
        cfg = self.config
        B, T, C = x.shape
        sw = 1.0 if stats_weight is None else stats_weight
        up = cfg.up_dim
        n_exp, n_shared = cfg.n_exp, cfg.n_shared
        n_routed, k = cfg.n_routed, cfg.n_act_routed
        fc_out = 2 * up if _is_gated(cfg.non_linearity) else up
        dt = x.dtype

        experts_fc = self.param("experts_fc", _DENSE_INIT,
                                (n_exp, C, fc_out), jnp.float32)
        experts_proj = self.param("experts_proj", _DENSE_INIT,
                                  (n_exp, up, C), jnp.float32)
        gate_kernel = self.param("gate", _DENSE_INIT, (C, n_routed), jnp.float32)

        x_flat = x.reshape(-1, C)  # (N, C)
        n_tokens = x_flat.shape[0]

        use_grouped = False
        if cfg.moe_impl == "grouped":
            from distributed_pytorch_tpu.ops.grouped_matmul import \
                grouped_usable
            use_grouped = grouped_usable(cfg, B, dt)

        # ---------------- shared expert path (reference :440-445) ----------
        def one_expert(wf, wp):
            return mlp_apply(x_flat, wf.astype(dt), wp.astype(dt),
                             cfg.non_linearity)

        if n_shared > 0 and not use_grouped:
            shared_out = jax.vmap(one_expert)(
                experts_fc[:n_shared], experts_proj[:n_shared]).sum(axis=0)
        else:
            # grouped: shared experts ride the grouped kernel as always-on
            # groups (one group per shared expert, every token, gate 1.0)
            shared_out = jnp.zeros_like(x_flat)

        # ---------------- router (fp32 for numerics) -----------------------
        router_logits = (x_flat.astype(jnp.float32)
                         @ gate_kernel.astype(jnp.float32))  # (N, n_routed)

        if cfg.aux_free:
            bias = self.variable(
                "moe_state", "expert_bias",
                lambda: jnp.zeros((n_routed,), jnp.float32))
            biased = router_logits + bias.value
            _, topk_idx = jax.lax.top_k(biased, k)
            # gates from UN-biased logits of the selected experts (ref :457-458)
            topk_orig = jnp.take_along_axis(router_logits, topk_idx, axis=1)
            topk_gates = jax.nn.softmax(topk_orig, axis=1)
            one_hot = jax.nn.one_hot(topk_idx, n_routed, dtype=jnp.float32)
            fi = jax.lax.stop_gradient(one_hot.sum(axis=(0, 1)) / n_tokens)
            if not deterministic and self.is_mutable_collection("moe_state"):
                # online bias update toward uniform load (reference :466-470);
                # fi here is over the GLOBAL batch under pjit. `sw` zeroes
                # the step for pipeline bubble slots.
                delta = 1.0 / n_routed - fi
                bias.value = bias.value + cfg.gamma * delta * sw
            pi = jax.nn.softmax(router_logits, axis=1).mean(axis=0)
            aux_loss = cfg.alpha * n_routed * jnp.sum(pi * fi)
        else:
            _, topk_idx = jax.lax.top_k(router_logits, k)
            topk_vals = jnp.take_along_axis(router_logits, topk_idx, axis=1)
            topk_gates = jax.nn.softmax(topk_vals, axis=1)
            one_hot = jax.nn.one_hot(topk_idx, n_routed, dtype=jnp.float32)
            fi = jax.lax.stop_gradient(one_hot.sum(axis=(0, 1)) / n_tokens)
            pi = jax.nn.softmax(router_logits, axis=1).mean(axis=0)
            aux_loss = cfg.coeff * n_routed * jnp.sum(pi * fi)

        # ---------------- routed dispatch (see module docstring) -----------
        dropped_frac = jnp.float32(0.0)
        if cfg.moe_impl == "scatter":
            capacity = max(k, math.ceil(
                cfg.capacity_factor * n_tokens * k / n_routed))
            # round up so the buffers' capacity axis is divisible by the
            # 'data' mesh axis and _expert_constraint can shard it (extra
            # slots only ever reduce drops, never change kept tokens)
            from distributed_pytorch_tpu.parallel import context
            mesh = context.get_mesh()
            if mesh is not None and "data" in mesh.axis_names:
                dp = mesh.shape["data"]
                capacity = -(-capacity // dp) * dp
            routed_out = scatter_dispatch(
                x_flat, topk_idx, topk_gates,
                experts_fc[n_shared:], experts_proj[n_shared:],
                non_linearity=cfg.non_linearity, capacity=capacity)
            # assignments past an expert's capacity are silently dropped
            # (GShard position priority) — surface the fraction so the
            # drop is visible in train logs / bench JSON. 'grouped' and
            # 'dense' are dropless by construction and report 0.
            load = jnp.zeros((n_routed,), jnp.int32).at[
                topk_idx.reshape(-1)].add(1)
            dropped_frac = (jnp.maximum(load - capacity, 0).sum()
                            / jnp.float32(n_tokens * k))
        elif use_grouped:
            from distributed_pytorch_tpu.ops.grouped_matmul import \
                grouped_dispatch
            # includes the shared experts as always-on groups (shared_out
            # above is zeros on this path)
            routed_out = grouped_dispatch(
                x_flat, topk_idx, topk_gates, experts_fc, experts_proj,
                non_linearity=cfg.non_linearity, n_shared=n_shared)
        else:
            # combine[t, e] = gate weight of expert e for token t (0 if
            # unrouted)
            combine = (one_hot * topk_gates[..., None]).sum(axis=1)  # (N, E)
            all_routed = jax.vmap(one_expert)(
                experts_fc[n_shared:], experts_proj[n_shared:])  # (E, N, C)
            routed_out = jnp.einsum("enc,ne->nc", all_routed,
                                    combine.astype(dt))

        # cross-batch metric state, carried like the aux-free bias; only
        # real microbatches write (sw=0 pipeline bubble slots hold zero
        # tokens whose deterministic routing would fake a drop rate)
        drop_var = self.variable("moe_state", "dropped_frac",
                                 lambda: jnp.float32(0.0))
        if not deterministic and self.is_mutable_collection("moe_state"):
            sw_arr = jnp.asarray(sw, jnp.float32)
            drop_var.value = jnp.where(sw_arr > 0, dropped_frac,
                                       drop_var.value)

        y = (shared_out + routed_out).reshape(B, T, C)
        return y, aux_loss.astype(jnp.float32) * sw
