"""Feed-forward layers: dense MLP (13 activations incl. swiglu) and
DeepSeekMoE with aux-loss-free balancing.

Reference parity map:
* `MLP` — reference single-gpu/model.py:365-398: bias-free up/down
  projections; swiglu as ONE fused 2*up_dim projection split in half
  (reference :371-373,389-391); otherwise an activation map of 12 choices.
  Divergence: the reference's 'glu' entry is shape-inconsistent (nn.GLU
  halves the feature dim, so its c_proj would reject the result); here
  'glu' is implemented like swiglu but with a sigmoid gate, which is what
  GLU means — documented rather than reproduced as a crash.
* `MoE` — reference model.py:409-506 (DeepSeekMoE, arXiv:2412.19437 flavor):
  first n_shared experts always-on bypassing the router; top-k routing over
  the remaining n_routed experts (n_act INCLUDES shared, reference :425);
  two balancing modes: (a) aux-loss-free — a non-learned bias added to
  router logits for top-k *selection only*, gates from un-biased logits
  (reference :451-458), bias nudged toward uniform load at speed gamma
  during training (reference :466-470), plus complementary aux loss
  alpha * n_routed * sum(pi*fi) (reference :472-474); (b) classic aux loss
  coeff * n_routed * sum(pi*fi) (reference :476-487).

TPU-first design (SURVEY §7 hard part (a)):
* Expert weights are STACKED with a leading (n_exp, ...) axis — one pytree
  leaf per projection, shardable over an 'expert' mesh axis for expert
  parallelism (capability absent from the reference, whose dispatch is a
  data-dependent Python loop over experts, model.py:489-506).
* Dispatch is static-shape. 'dense' mode evaluates every routed expert on
  every token and combines with a (tokens, n_routed) gate matrix that is
  zero outside the top-k — bitwise-equal semantics to the reference loop
  (no capacity limit, no token dropping) at n_routed/k extra FLOPs; good
  for small expert counts and as the semantics oracle. A capacity-bounded
  sort-based 'scatter' mode for large expert counts is planned
  (TrainConfig validates moe_impl until it lands).
* The aux-free bias is cross-batch mutable state; it lives in the 'moe_state'
  variable collection, carried in the train state. Under pjit the batch is
  global, so load statistics (and hence the bias update) are computed over
  the GLOBAL batch — unlike the reference, where each DDP rank's bias
  drifts independently (no sync anywhere in kaggle-zero*.py). Documented
  intentional improvement.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

from distributed_pytorch_tpu.config import LLMConfig

_DENSE_INIT = nn.initializers.normal(stddev=0.02)


def _activation(name: str) -> Callable[[jnp.ndarray], jnp.ndarray]:
    name = name.lower()
    table = {
        "relu": jax.nn.relu,
        "gelu": lambda x: jax.nn.gelu(x, approximate=False),
        "swish": jax.nn.silu,
        "silu": jax.nn.silu,
        "mish": jax.nn.mish,
        "selu": jax.nn.selu,
        "celu": jax.nn.celu,
        "elu": jax.nn.elu,
        "sigmoid": jax.nn.sigmoid,
        "lrelu": lambda x: jax.nn.leaky_relu(x, negative_slope=0.01),
        "tanh": jnp.tanh,
    }
    return table.get(name, lambda x: jax.nn.gelu(x, approximate=False))


def _is_gated(name: str) -> bool:
    return name.lower() in ("swiglu", "glu")


def mlp_apply(x: jnp.ndarray, w_fc: jnp.ndarray, w_proj: jnp.ndarray,
              non_linearity: str) -> jnp.ndarray:
    """Apply one MLP given its kernels; shared by dense MLP and experts.

    Gated variants ('swiglu'/'glu'): w_fc is (C, 2*up_dim), split in half,
    h = act(x1) * x2 (reference model.py:389-391). Others: (C, up_dim).
    """
    h = x @ w_fc
    if _is_gated(non_linearity):
        x1, x2 = jnp.split(h, 2, axis=-1)
        gate = jax.nn.silu(x1) if non_linearity.lower() == "swiglu" \
            else jax.nn.sigmoid(x1)
        h = gate * x2
    else:
        h = _activation(non_linearity)(h)
    return h @ w_proj


class MLP(nn.Module):
    """Dense feed-forward block (reference model.py:365-398)."""

    config: LLMConfig

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True):
        cfg = self.config
        C, up = cfg.n_embd, cfg.up_dim
        fc_out = 2 * up if _is_gated(cfg.non_linearity) else up
        w_fc = self.param("c_fc", _DENSE_INIT, (C, fc_out), jnp.float32)
        w_proj = self.param("c_proj", _DENSE_INIT, (up, C), jnp.float32)
        y = mlp_apply(x, w_fc.astype(x.dtype), w_proj.astype(x.dtype),
                      cfg.non_linearity)
        return nn.Dropout(cfg.dropout, deterministic=deterministic)(y)


class MoE(nn.Module):
    """DeepSeekMoE layer (reference model.py:409-506). Returns (y, aux_loss).

    Expert parameters are stacked: experts_fc (n_exp, C, fc_out) and
    experts_proj (n_exp, up, C); expert e of the reference's ModuleList is
    slice [e]. First n_shared experts are shared (always active)."""

    config: LLMConfig

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True):
        cfg = self.config
        B, T, C = x.shape
        up = cfg.up_dim
        n_exp, n_shared = cfg.n_exp, cfg.n_shared
        n_routed, k = cfg.n_routed, cfg.n_act_routed
        fc_out = 2 * up if _is_gated(cfg.non_linearity) else up
        dt = x.dtype

        experts_fc = self.param("experts_fc", _DENSE_INIT,
                                (n_exp, C, fc_out), jnp.float32)
        experts_proj = self.param("experts_proj", _DENSE_INIT,
                                  (n_exp, up, C), jnp.float32)
        gate_kernel = self.param("gate", _DENSE_INIT, (C, n_routed), jnp.float32)

        x_flat = x.reshape(-1, C)  # (N, C)
        n_tokens = x_flat.shape[0]

        # ---------------- shared expert path (reference :440-445) ----------
        def one_expert(wf, wp):
            return mlp_apply(x_flat, wf.astype(dt), wp.astype(dt),
                             cfg.non_linearity)

        if n_shared > 0:
            shared_out = jax.vmap(one_expert)(
                experts_fc[:n_shared], experts_proj[:n_shared]).sum(axis=0)
        else:
            shared_out = jnp.zeros_like(x_flat)

        # ---------------- router (fp32 for numerics) -----------------------
        router_logits = (x_flat.astype(jnp.float32)
                         @ gate_kernel.astype(jnp.float32))  # (N, n_routed)

        if cfg.aux_free:
            bias = self.variable(
                "moe_state", "expert_bias",
                lambda: jnp.zeros((n_routed,), jnp.float32))
            biased = router_logits + bias.value
            _, topk_idx = jax.lax.top_k(biased, k)
            # gates from UN-biased logits of the selected experts (ref :457-458)
            topk_orig = jnp.take_along_axis(router_logits, topk_idx, axis=1)
            topk_gates = jax.nn.softmax(topk_orig, axis=1)
            one_hot = jax.nn.one_hot(topk_idx, n_routed, dtype=jnp.float32)
            fi = jax.lax.stop_gradient(one_hot.sum(axis=(0, 1)) / n_tokens)
            if not deterministic and self.is_mutable_collection("moe_state"):
                # online bias update toward uniform load (reference :466-470);
                # fi here is over the GLOBAL batch under pjit.
                delta = 1.0 / n_routed - fi
                bias.value = bias.value + cfg.gamma * delta
            pi = jax.nn.softmax(router_logits, axis=1).mean(axis=0)
            aux_loss = cfg.alpha * n_routed * jnp.sum(pi * fi)
        else:
            _, topk_idx = jax.lax.top_k(router_logits, k)
            topk_vals = jnp.take_along_axis(router_logits, topk_idx, axis=1)
            topk_gates = jax.nn.softmax(topk_vals, axis=1)
            one_hot = jax.nn.one_hot(topk_idx, n_routed, dtype=jnp.float32)
            fi = jax.lax.stop_gradient(one_hot.sum(axis=(0, 1)) / n_tokens)
            pi = jax.nn.softmax(router_logits, axis=1).mean(axis=0)
            aux_loss = cfg.coeff * n_routed * jnp.sum(pi * fi)

        # combine[t, e] = gate weight of expert e for token t (0 if unrouted)
        combine = (one_hot * topk_gates[..., None]).sum(axis=1)  # (N, n_routed)

        # ---------------- routed dispatch (dense; see module docstring) ----
        all_routed = jax.vmap(one_expert)(
            experts_fc[n_shared:], experts_proj[n_shared:])  # (E, N, C)
        routed_out = jnp.einsum("enc,ne->nc", all_routed, combine.astype(dt))

        y = (shared_out + routed_out).reshape(B, T, C)
        return y, aux_loss.astype(jnp.float32)
