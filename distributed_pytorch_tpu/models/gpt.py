"""The LLM: token/positional embeddings, pre-LN transformer blocks, weight-
tied LM head, CE loss with MoE aux-loss accumulation, KV-cached decoding.

Reference parity map (single-gpu/model.py):
* `Block` — :508-533: pre-LN attention + (MLP | MoE) with residuals; returns
  (x, cache, aux_loss), aux_loss = 0.0 for dense blocks (:530).
* `LLM`   — :535-747: token embedding + one of three positional schemes
  (:541-552: 'learn' = learned table, 'sin' = fixed sinusoidal buffer,
  'rope' = precomputed rotary angles), dropout, n_layer blocks, final LN,
  weight-tied lm_head (:559-560), N(0, 0.02) init for all dense/embedding
  weights (:579-586), forward with cache-offset start_pos (:641-650),
  per-layer aux-loss accumulation added as total_aux/n_layer (:687-692),
  last-position-only logits when targets are absent (:694).

TPU-first notes:
* Parameters are fp32; compute runs in `compute_dtype` (bf16 on TPU) — pure
  bf16 matmuls with fp32 master weights replaces the reference's
  fp16 autocast + GradScaler (SURVEY §5 mixed-precision divergence).
* `act_recomp` wraps each Block in `nn.remat` (reference wraps Blocks in
  torch checkpoint, model.py:677-680), trading FLOPs for HBM.
* Caches are fixed-size buffers + a `pos` index (XLA static shapes), created
  by `init_cache`; `pos` replaces the reference's len-of-cache start_pos.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from distributed_pytorch_tpu.config import LLMConfig
from distributed_pytorch_tpu.models.attention import Attention, init_attn_cache
from distributed_pytorch_tpu.models.mlp import MLP, MoE
from distributed_pytorch_tpu.ops.losses import (fused_cross_entropy,
                                                sp_fused_cross_entropy,
                                                unchunked_cross_entropy)
from distributed_pytorch_tpu.ops.rope import precompute_rope_freqs, slice_rows

_EMBED_INIT = nn.initializers.normal(stddev=0.02)


class Block(nn.Module):
    """Pre-LN transformer block (reference model.py:508-533).

    `deterministic` is a module attribute (not a call arg) so the whole
    block can be wrapped in `nn.remat` without static-argnum plumbing.
    `remat_attn` remats only the attention sublayer — the reference's
    deliberate kaggle-script granularity (kaggle-ddp.py:526-534): the
    O(T^2) score tensor is recomputed in backward, the O(T) FFN/MoE
    activations stay saved."""

    config: LLMConfig
    attn_impl: str = "auto"
    deterministic: bool = True
    remat_attn: bool = False

    @nn.compact
    def __call__(self, x, freqs, cache=None, pos=0, stats_weight=None,
                 block_tables=None):
        cfg = self.config
        deterministic = self.deterministic
        ln1 = nn.LayerNorm(dtype=x.dtype, param_dtype=jnp.float32, name="ln1")
        ln2 = nn.LayerNorm(dtype=x.dtype, param_dtype=jnp.float32, name="ln2")
        attn = Attention(cfg, self.attn_impl)
        if self.remat_attn:
            # remat over a function whose only remat argument is the hidden
            # state; freqs/cache/pos ride the closure (captured residuals,
            # cheap) so the flavor modules' keyword-only `deterministic`
            # needs no static-argnum plumbing. Param path stays `attn`.
            def attn_fn(mdl, h):
                return mdl(h, freqs, cache, pos, deterministic=deterministic,
                           block_tables=block_tables)
            attn_out, new_cache = nn.remat(attn_fn, prevent_cse=False)(
                attn, ln1(x))
        else:
            attn_out, new_cache = attn(ln1(x), freqs, cache, pos,
                                       deterministic=deterministic,
                                       block_tables=block_tables)
        x = x + attn_out
        if cfg.moe:
            moe_out, aux_loss = MoE(cfg, name="moe")(
                ln2(x), deterministic=deterministic,
                stats_weight=stats_weight)
            x = x + moe_out
        else:
            aux_loss = jnp.float32(0.0)
            x = x + MLP(cfg, name="mlp")(ln2(x), deterministic=deterministic)
        return x, new_cache, aux_loss


def _sin_table(block_size: int, n_embd: int) -> jnp.ndarray:
    """Fixed sinusoidal table (reference model.py:544-550)."""
    position = jnp.arange(block_size, dtype=jnp.float32)[:, None]
    div_term = jnp.exp(jnp.arange(0, n_embd, 2, dtype=jnp.float32)
                       * (-math.log(10000.0) / n_embd))
    angles = position * div_term  # (T, C/2)
    tab = jnp.zeros((block_size, n_embd), jnp.float32)
    tab = tab.at[:, 0::2].set(jnp.sin(angles))
    tab = tab.at[:, 1::2].set(jnp.cos(angles))
    return tab


class LLM(nn.Module):
    """The full model (reference model.py:535-747)."""

    config: LLMConfig
    compute_dtype: Any = jnp.float32
    attn_impl: str = "auto"

    @nn.compact
    def __call__(self, idx, targets=None, caches=None, pos=0, *,
                 deterministic: bool = True, logits_idx=None,
                 block_tables=None, all_logits: bool = False):
        """`pos` is the global position of idx[:, 0] — a static int, a
        traced scalar, or a per-sequence (B,) array (slot-based ragged
        decode; each sequence in the batch sits at its own cache
        position). `logits_idx` (B,) selects which position's logits to
        return when targets is None (default: the last) — the bucketed
        prefill path, where right-padded prompts end at different rows;
        `all_logits=True` returns every position's logits instead (the
        speculative verify step scores all K+1 draft positions at once).
        `block_tables` (B, max_blocks) int32 marks the caches as PAGED
        pools (init_paged_cache); reads and writes then indirect through
        the table (ops/block_pool.py)."""
        cfg = self.config
        B, T = idx.shape
        dt = self.compute_dtype

        tkn_emb = nn.Embed(cfg.vocab_size, cfg.n_embd,
                           embedding_init=_EMBED_INIT,
                           param_dtype=jnp.float32, dtype=dt, name="tkn_emb")
        x = tkn_emb(idx)
        freqs = None

        if cfg.pos_emb == "rope":
            d = cfg.rope_head_dim if cfg.attn == "mla" else cfg.head_size
            # constant under jit; XLA folds it (reference precomputes a
            # complex buffer, model.py:567-577)
            freqs = precompute_rope_freqs(d, cfg.block_size)
        elif cfg.pos_emb == "learn":
            pos_tab = self.param("pos_emb", _EMBED_INIT,
                                 (cfg.block_size, cfg.n_embd), jnp.float32)
            p = slice_rows(pos_tab, pos, T).astype(dt)
            x = x + (p if p.ndim == 3 else p[None])  # per-seq rows vs shared
        elif cfg.pos_emb == "sin":
            tab = _sin_table(cfg.block_size, cfg.n_embd)
            p = slice_rows(tab, pos, T).astype(dt)
            x = x + (p if p.ndim == 3 else p[None])

        x = nn.Dropout(cfg.dropout, deterministic=deterministic)(x)

        if cfg.pp_stages > 1:
            # pipeline-parallel block stack (models/pipeline.py): stacked
            # layer axis over the 'pipe' mesh axis, microbatch tick loop
            if caches is not None:
                raise ValueError(
                    "pipeline-parallel models don't support KV-cached "
                    "decoding; restore the checkpoint with pp_stages=1 "
                    "(train/checkpoint.py unstacks the block params) to "
                    "sample from it")
            from distributed_pytorch_tpu.models.pipeline import run_pipeline
            x, total_aux = run_pipeline(self, cfg, self.attn_impl,
                                        deterministic, x, freqs)
            new_caches = [None] * cfg.n_layer
        else:
            if caches is None:
                caches = [None] * cfg.n_layer

            block_cls = Block
            remat_attn = False
            if cfg.act_recomp:
                if cfg.act_recomp_policy == "attn":
                    remat_attn = True  # attention-only (kaggle-ddp.py:526-534)
                else:
                    # Whole-block remat (reference model.py:677-680).
                    block_cls = nn.remat(Block, prevent_cse=False)

            new_caches = []
            total_aux = jnp.float32(0.0)
            for i in range(cfg.n_layer):
                blk = block_cls(cfg, self.attn_impl, deterministic,
                                remat_attn, name=f"block_{i}")
                x, new_cache, aux = blk(x, freqs, caches[i], pos,
                                        block_tables=block_tables)
                new_caches.append(new_cache)
                total_aux = total_aux + aux

        x = nn.LayerNorm(dtype=dt, param_dtype=jnp.float32, name="ln_f")(x)

        if targets is not None:
            # Weight-tied CE with ignore_index=-1 (reference :559-560, :689),
            # fp32-accumulated. The fused path never materializes the
            # (B, T, V) logits (ops/losses.py); under a live 'seq' axis the
            # chunk scan runs per-device over the local T shard inside
            # shard_map (sp_fused_cross_entropy).
            from distributed_pytorch_tpu.parallel import context
            emb_mat = tkn_emb.embedding.astype(dt)  # (V, C)
            loss_impl = cfg.loss_impl

            def logits_fn(x_c, emb):
                # lm-head gather as a collective matmul (the (V, C)
                # embedding is the largest single param ZeRO-3 shards):
                # under OVERLAP=on the per-chunk logits matmul rings the
                # vocab shards; the dispatcher declines everywhere else
                # and the default plain matmul is bit-identical
                from distributed_pytorch_tpu.ops.collective_matmul import (
                    maybe_overlap_matmul)
                from distributed_pytorch_tpu.ops.losses import \
                    _default_logits
                y = maybe_overlap_matmul(x_c, emb,
                                         names=("tkn_emb", "embedding"),
                                         transpose_b=True,
                                         out_dtype=jnp.float32)
                return y if y is not None else _default_logits(x_c, emb)
            if loss_impl == "pallas":
                # Streaming-kernel gates: no vocab-parallel embedding (tp
                # shards V and the kernel's logsumexp is per-shard-local),
                # no live 'seq' axis (T is sequence-sharded), shapes the
                # kernel tiles, and a TPU backend (interpret on CPU is
                # test-only slow). Otherwise degrade to the chunked path.
                from distributed_pytorch_tpu.ops.fused_ce import (
                    pallas_ce_usable, pallas_cross_entropy)
                mesh = context.get_mesh()
                tp = mesh.shape.get("model", 1) if mesh is not None else 1
                dp = mesh.shape.get("data", 1) if mesh is not None else 1
                n_local = (x.shape[0] // dp) * x.shape[1]
                if (context.seq_axis_size() <= 1 and tp == 1
                        and x.shape[0] % dp == 0
                        and jax.default_backend() == "tpu"
                        and pallas_ce_usable(n_local, x.shape[-1], x.dtype)):
                    main_loss = pallas_cross_entropy(x, emb_mat, targets)
                else:
                    loss_impl = "fused"
            if loss_impl == "fused" and context.seq_axis_size() > 1:
                # live 'seq' axis: chunk over the LOCAL T shard inside
                # shard_map (ops/losses.py sp_fused_cross_entropy) instead
                # of materializing seq-sharded full logits. Gates: no
                # vocab-parallel embedding, B divisible by dp, T by sp.
                mesh = context.get_mesh()
                tp = mesh.shape.get("model", 1)
                dp = mesh.shape.get("data", 1)
                sp = context.seq_axis_size()
                if (tp == 1 and x.shape[0] % dp == 0
                        and x.shape[1] % sp == 0):
                    main_loss = sp_fused_cross_entropy(
                        x, emb_mat, targets, chunk=cfg.loss_chunk)
                else:
                    main_loss = unchunked_cross_entropy(
                        x, emb_mat, targets, logits_fn=logits_fn)
            elif loss_impl == "fused":
                main_loss = fused_cross_entropy(
                    x, emb_mat, targets, chunk=cfg.loss_chunk,
                    logits_fn=logits_fn)
            elif loss_impl != "pallas":
                main_loss = unchunked_cross_entropy(
                    x, emb_mat, targets, logits_fn=logits_fn)
            loss = main_loss + total_aux / cfg.n_layer
            # full logits stay available to callers (tests, analysis); when
            # unused — as in the trainer, which takes only `loss` — XLA
            # dead-code-eliminates this matmul.
            logits = tkn_emb.attend(x)
        else:
            if all_logits:
                sel = x                            # every position (verify)
            elif logits_idx is None:
                sel = x[:, -1:, :]                 # last position only (:694)
            else:
                # bucketed prefill: each sequence's true last token sits at
                # its own row of the right-padded buffer
                sel = jnp.take_along_axis(
                    x, jnp.reshape(logits_idx, (-1, 1, 1)).astype(jnp.int32),
                    axis=1)
            # weight-only int8 decode: the tied lm-head matmul — the
            # single largest weight read of a decode step — reads int8
            # codes + per-vocab-row scales when the engine's quantized
            # store is active (ops/quant.py); otherwise the plain attend
            from distributed_pytorch_tpu.ops.quant import \
                maybe_quantized_matmul
            logits = maybe_quantized_matmul(
                sel, ("tkn_emb", "embedding"), transpose_b=True)
            if logits is None:
                logits = tkn_emb.attend(sel)       # (B, 1, V)
            loss = None

        return logits, loss, new_caches


def init_cache(config: LLMConfig, batch_size: int,
               max_len: Optional[int] = None, dtype=jnp.float32):
    """Create the per-layer static KV-cache pytree for decoding.

    `dtype` should match the model's compute_dtype (fp32 default mirrors
    LLM's; pass bfloat16 for bf16 inference). The buffers are RINGS under
    traced positions (models/attention.py `_update_cache`): decoding past
    `max_len` overwrites the oldest slot in O(1) — the static-shape
    equivalent of the reference's trim-to-block_size-1 sliding window
    (model.py:711-730), without the legacy roll's O(S) shift per token.
    """
    max_len = max_len or config.block_size
    return [init_attn_cache(config, batch_size, max_len, dtype)
            for _ in range(config.n_layer)]


def init_paged_cache(config: LLMConfig, n_blocks: int, block_size: int,
                     dtype=jnp.float32):
    """Per-layer paged KV-cache pytree: one (n_blocks, block_size, ...)
    pool set per layer, shared by every sequence through per-sequence
    block tables (engine/decode.py owns the tables; one table serves all
    layers because block ids are allocated for the whole layer stack at
    once). Pass the tables to `LLM.__call__(block_tables=...)`."""
    from distributed_pytorch_tpu.models.attention import init_paged_attn_cache
    return [init_paged_attn_cache(config, n_blocks, block_size, dtype)
            for _ in range(config.n_layer)]


def count_params(params, config: LLMConfig) -> tuple[int, int]:
    """(total, active) parameter counts (reference get_num_params,
    model.py:588-617): active counts shared experts + n_act_routed routed
    experts per MoE block, everything else fully."""
    sizes = jax.tree_util.tree_map(lambda x: int(x.size), params)
    flat = jax.tree_util.tree_flatten_with_path(sizes)[0]
    total = sum(v for _, v in flat)
    if not config.moe:
        return total, total
    inactive = 0
    n_routed, k = config.n_routed, config.n_act_routed
    for path, size in flat:
        keys = [getattr(p, "key", getattr(p, "name", "")) for p in path]
        if any(k_ in ("experts_fc", "experts_proj") for k_ in keys):
            per_expert = size // config.n_exp
            inactive += per_expert * (n_routed - k)
    return total, total - inactive
