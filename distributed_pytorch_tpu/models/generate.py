"""KV-cached autoregressive generation: temperature + top-k sampling.

Reference parity (`LLM.generate`, single-gpu/model.py:700-747):
* prompt cropped to the last block_size tokens (reference :704-709);
* per-step: forward the last token only against the KV cache, scale logits
  by temperature, filter to top-k, sample (reference :733-743);
* when the cache fills, the reference trims every layer's cache to
  block_size-1 — a sliding window (reference :711-730).

TPU-first design (SURVEY §7 hard part (c) — static shapes for XLA):
* caches are fixed (B, S, ...) buffers + integer positions (models/gpt.py
  `init_cache`); the whole decode loop is ONE `lax.scan` inside ONE jit —
  no per-token retrace, no concat-and-grow;
* the sliding window is a RING: the cache write lands at `pos % S`
  (models/attention.py `_update_cache`), so a full window costs one O(1)
  row write instead of the pre-round-8 roll-by-one's O(S) HBM shift of
  every layer's buffer per token. Content-equivalent to the roll (both
  keep exactly the last S entries; attention is permutation-invariant over
  valid slots), and when `T0 + max_new_tokens <= max_len` — the common
  case — nothing window-related is traced at all;
* `prompt_len` (B,) enables BUCKETED prompts: right-pad each prompt to a
  shared shape (sample.py buckets to powers of two so repeated prompts
  reuse one trace), prefill reads logits at each sequence's true last row
  (`logits_idx`), and decode continues from per-sequence positions — pad
  rows are overwritten by the first decode steps and causally masked until
  then, so the output tokens are bit-identical to an unpadded decode;
* sampling uses a counter-based PRNG key folded per step (reproducible
  regardless of batch size), `jax.lax.top_k` + mask for the top-k filter,
  and `jax.random.categorical` for the multinomial draw; temperature == 0.0
  selects greedy argmax (an extension; the reference divides by zero).

For serving-style continuous batching (admit/retire sequences into a
long-lived slot cache) use `engine.DecodeEngine`, which builds on the same
per-sequence position machinery.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from distributed_pytorch_tpu.models.gpt import init_cache


def sample_token(logits: jnp.ndarray, rng, *, temperature: float = 1.0,
                 top_k: Optional[int] = None) -> jnp.ndarray:
    """Sample token ids from (B, V) logits (reference model.py:736-743)."""
    logits = logits.astype(jnp.float32)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    # top_k in (None, 0) means no truncation (the CLI passes 0 for "off")
    if top_k is not None and 0 < top_k < logits.shape[-1]:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def make_generate_fn(model, max_new_tokens: int, *, temperature: float = 1.0,
                     top_k: Optional[int] = None,
                     max_len: Optional[int] = None, cache_dtype=None):
    """Build a jitted `generate(variables, prompt, rng[, prompt_len])
    -> (B, T0 + new)`.

    `variables` is the flax variable dict ({'params': ..., ['moe_state': ...]});
    `prompt` (B, T0) int32, T0 <= block_size (crop host-side first — static
    shapes). `prompt_len` (B,) int32 marks each row's true length when the
    prompt buffer is right-padded (bucketed shapes); generated tokens then
    start at out[:, T0:] while out[b, prompt_len[b]:T0] holds the pad tail.
    The returned function is traced once per (B, T0) shape (plus once more
    for the prompt_len variant).
    """
    cfg = model.config
    max_len = max_len or cfg.block_size
    assert max_len <= cfg.block_size, (
        f"max_len {max_len} exceeds block_size {cfg.block_size}: the "
        f"rope/learned/sin position tables only cover block_size rows "
        f"(positions beyond would silently clamp)")
    cache_dtype = cache_dtype or model.compute_dtype

    if max_new_tokens <= 0:  # reference range(0) no-op, model.py:703
        return lambda variables, prompt, rng, prompt_len=None: prompt

    def apply_step(variables, idx, caches, pos, logits_idx=None):
        logits, _, caches = model.apply(variables, idx, None, caches, pos,
                                        deterministic=True,
                                        logits_idx=logits_idx)
        return logits[:, -1, :], caches

    @jax.jit
    def generate(variables: Any, prompt: jnp.ndarray, rng,
                 prompt_len=None) -> jnp.ndarray:
        B, T0 = prompt.shape
        assert T0 <= max_len, (
            f"prompt length {T0} exceeds cache size {max_len}; crop to the "
            f"last block_size tokens first (reference model.py:704-709)")
        caches = init_cache(cfg, B, max_len, dtype=cache_dtype)

        # Prefill: one full-sequence forward populates every layer's cache.
        if prompt_len is None:
            logits, caches = apply_step(variables, prompt, caches, 0)
            # one shared scalar position: the whole batch advances in
            # lockstep and each cache update is a single fused row write
            pos0 = jnp.int32(T0)
        else:
            lens = jnp.asarray(prompt_len, jnp.int32)
            logits, caches = apply_step(variables, prompt, caches, 0,
                                        logits_idx=lens - 1)
            pos0 = lens  # (B,): per-sequence slot positions from here on
        tok = sample_token(logits, jax.random.fold_in(rng, 0),
                           temperature=temperature, top_k=top_k)

        def step(carry, i):
            tok, caches, pos = carry
            logits, caches = apply_step(variables, tok[:, None], caches, pos)
            nxt = sample_token(logits, jax.random.fold_in(rng, i),
                               temperature=temperature, top_k=top_k)
            return (nxt, caches, pos + 1), tok

        (last, _, _), toks = jax.lax.scan(
            step, (tok, caches, pos0),
            jnp.arange(1, max_new_tokens, dtype=jnp.int32))
        # toks: (max_new_tokens - 1, B) — each step emits its *incoming*
        # token; the final sampled token closes the sequence.
        new = jnp.concatenate([toks.T, last[:, None]], axis=1) \
            if max_new_tokens > 1 else last[:, None]
        return jnp.concatenate([prompt, new], axis=1)

    return generate


def generate(model, variables: Any, prompt, max_new_tokens: int, *,
             rng=None, temperature: float = 1.0, top_k: Optional[int] = None,
             max_len: Optional[int] = None) -> jnp.ndarray:
    """Convenience one-shot wrapper (reference `LLM.generate` call shape).

    Crops the prompt to the last `block_size` tokens host-side, builds the
    jitted loop, and runs it. For repeated sampling at fixed shapes, build
    once with `make_generate_fn` and reuse.
    """
    if rng is None:
        rng = jax.random.PRNGKey(0)
    prompt = jnp.asarray(prompt, jnp.int32)
    if prompt.ndim == 1:
        prompt = prompt[None]
    cfg = model.config
    if prompt.shape[1] > cfg.block_size:
        prompt = prompt[:, -cfg.block_size:]
    fn = make_generate_fn(model, max_new_tokens, temperature=temperature,
                          top_k=top_k, max_len=max_len)
    return fn(variables, prompt, rng)
