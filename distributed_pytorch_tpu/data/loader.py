"""Memmap token loader.

Reference parity (`DataLoader`, single-gpu/train.py:210-254): np.memmap of a
raw uint16 token file; every batch = B *uniform-random* start offsets (not
sequential epochs); y is x shifted by one. The reference decorrelates DDP
ranks purely via a +rank seed offset (multi-gpu/ddp/train.py:28-29); here
every process samples from one counter-based RNG stream keyed by
(seed, step, accum-slot, row) so the global batch is identical regardless of
process count — resharding-stable and resumable (a capability the reference
lacks: its loader state is unrecoverable RNG).

TPU-first: the loader returns the whole optimizer-step batch (accum, B, T)
and places it into its mesh shards in one `device_put` — per-host, each
process materializes only its addressable slice (multi-host path via
`jax.make_array_from_process_local_data`).
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np
from jax.sharding import NamedSharding


def make_synthetic_bin(path: str, n_tokens: int = 2 ** 20,
                       vocab_size: int = 50304, seed: int = 1729) -> str:
    """Write a synthetic uint16 token file with mild Markov structure (so
    loss can actually decrease — pure uniform noise has nothing to learn).
    Used by tests and by bench.py when no prepared dataset exists (this
    environment has no network egress for the real downloads)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    rng = np.random.default_rng(seed)
    eff_vocab = min(vocab_size, 1024)
    # tokens follow a noisy ramp: next ~ prev + small step (mod eff_vocab),
    # with 5% uniform-noise resets
    walk = np.cumsum(rng.integers(-3, 4, size=n_tokens)) % eff_vocab
    noise = rng.integers(0, eff_vocab, size=n_tokens)
    toks = np.where(rng.random(n_tokens) < 0.05, noise, walk)
    # write-to-temp + atomic rename: a killed run can't leave a partial
    # .bin, and concurrent processes (multi-host shared data_dir) see
    # either the old complete file or the new one, never a torn write
    tmp = f"{path}.tmp.{os.getpid()}"
    toks.astype(np.uint16).tofile(tmp)
    os.replace(tmp, path)
    return path


class DataLoader:
    """Random-offset batch sampler over a uint16 token memmap."""

    def __init__(self, file_path: str, batch_size: int, block_size: int, *,
                 grad_accum: int = 1, seed: int = 1729,
                 mesh=None, pspec=None, backend: str = "auto"):
        self.tokens = np.memmap(file_path, dtype=np.uint16, mode="r")
        assert len(self.tokens) > block_size + 1, (
            f"dataset {file_path} too small: {len(self.tokens)} tokens "
            f"<= block_size+1")  # reference train.py:221-222
        self.B, self.T, self.A = batch_size, block_size, grad_accum
        self.seed = seed
        self.step = 0
        self.mesh = mesh
        self.pspec = pspec
        self._sharding = (NamedSharding(mesh, pspec)
                         if mesh is not None and pspec is not None else None)
        # native C++ sampler (csrc/sampler.cpp: mmap + threaded gather +
        # background prefetch); the numpy path computes the SAME
        # Philox4x32-10 stream, so the backends are interchangeable
        assert backend in ("auto", "native", "numpy")
        self._native = None
        if backend in ("auto", "native"):
            from distributed_pytorch_tpu.data import native
            try:
                self._native = native.NativeSampler(file_path)
            except OSError:
                if backend == "native":
                    raise
        self.backend = "native" if self._native is not None else "numpy"

    def _sample(self, step: int, rows: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Gather (len(rows), T) x/y pairs for global batch-row ids `rows` at
        `step`. Counter-based (Philox4x32-10) keyed on (seed, step, row): any
        process can materialize any subset of the global batch
        deterministically."""
        rows = np.asarray(rows)
        if self._native is not None:
            full = len(rows) == self.A * self.B and \
                np.array_equal(rows, np.arange(self.A * self.B))
            if full:  # contiguous global batch: prefetched path
                return self._native.sample(self.seed, step, len(rows), self.T)
            return self._native.sample_rows(self.seed, step, rows, self.T)
        from distributed_pytorch_tpu.data.native import philox_offsets
        hi = len(self.tokens) - self.T - 1
        offsets = philox_offsets(self.seed, step, rows, hi)
        idx = offsets[:, None] + np.arange(self.T + 1)[None, :]
        seqs = self.tokens[idx].astype(np.int32)
        return seqs[:, :-1], seqs[:, 1:]

    def next_batch(self, step: Optional[int] = None):
        """Return (x, y), each (A, B, T) int32, sharded onto the mesh."""
        step = self.step if step is None else step
        self.step = step + 1

        if self._sharding is None:
            rows = np.arange(self.A * self.B)
            x, y = self._sample(step, rows)
            shp = (self.A, self.B, self.T)
            return x.reshape(shp), y.reshape(shp)

        # Sharded: materialize each addressable shard directly from the
        # memmap — on multi-host, a process never touches rows it doesn't
        # own; on one process this is just a sharded device_put.
        sh = self._sharding
        global_shape = (self.A, self.B, self.T)

        def shard(index, which: int):
            a_sl, b_sl, t_sl = index
            accums = np.arange(self.A)[a_sl]
            rows = np.arange(self.B)[b_sl]
            grid = (accums[:, None] * self.B + rows[None, :]).reshape(-1)
            x, y = self._sample(step, grid)
            shp = (len(accums), len(rows), self.T)
            out = (x, y)[which].reshape(shp)
            return out[..., t_sl]

        xs = jax.make_array_from_callback(global_shape, sh,
                                          lambda i: shard(i, 0))
        ys = jax.make_array_from_callback(global_shape, sh,
                                          lambda i: shard(i, 1))
        return xs, ys
