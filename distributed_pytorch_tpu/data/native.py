"""ctypes binding for the native C++ sampler (csrc/sampler.cpp), with a
bit-identical vectorized NumPy fallback.

Build model: the shared library is compiled on demand with g++ (no
pybind11 in this image; plain `extern "C"` + ctypes) and cached next to
the source, keyed by a content hash of the source plus the compiler
version — never by mtime, so a fresh clone always compiles from the
committed source and an edited sampler.cpp always rebuilds. The build
directory is untracked (.gitignore). Environments without a toolchain fall
back to `philox_offsets` / pure-numpy gathers transparently — the
DataLoader behaves identically either way because both implementations
compute the same Philox4x32-10 stream (asserted by tests/test_native.py).
"""

from __future__ import annotations

import ctypes
import functools
import hashlib
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "csrc", "sampler.cpp")
_BUILD_DIR = os.path.join(os.path.dirname(_SRC), "build")


@functools.lru_cache(maxsize=1)
def _lib_path() -> Optional[str]:
    """Cache path keyed on sha256(source) + g++ version: a stale or
    unverifiable committed binary can never shadow the committed source."""
    if not os.path.exists(_SRC):
        return None
    h = hashlib.sha256()
    with open(_SRC, "rb") as f:
        h.update(f.read())
    try:
        ver = subprocess.run(["g++", "--version"], capture_output=True,
                             timeout=30).stdout.split(b"\n", 1)[0]
    except Exception:
        ver = b"no-gxx"
    h.update(ver)
    return os.path.join(_BUILD_DIR, f"libsampler-{h.hexdigest()[:16]}.so")

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False

_M0 = np.uint64(0xD2511F53)
_M1 = np.uint64(0xCD9E8D57)
_W0 = np.uint32(0x9E3779B9)
_W1 = np.uint32(0xBB67AE85)
_MASK32 = np.uint64(0xFFFFFFFF)


def philox_offsets(seed: int, step: int, rows: np.ndarray,
                   hi: int) -> np.ndarray:
    """Philox4x32-10 offsets in [0, hi) for global batch-row ids `rows` at
    (seed, step). Bit-identical to csrc/sampler.cpp sample_offset()."""
    rows = np.asarray(rows, np.uint32)
    c0 = rows.astype(np.uint64)
    c1 = np.full_like(c0, np.uint64(step & 0xFFFFFFFF))
    c2 = np.full_like(c0, np.uint64((step >> 32) & 0xFFFFFFFF))
    c3 = np.zeros_like(c0)
    k0 = seed & 0xFFFFFFFF          # python ints: explicit mod-2^32 adds
    k1 = (seed >> 32) & 0xFFFFFFFF
    for _ in range(10):
        p0 = _M0 * c0          # 64-bit products (c in [0, 2^32))
        p1 = _M1 * c2
        hi0, lo0 = p0 >> np.uint64(32), p0 & _MASK32
        hi1, lo1 = p1 >> np.uint64(32), p1 & _MASK32
        c0, c1, c2, c3 = (hi1 ^ c1 ^ np.uint64(k0), lo1,
                          hi0 ^ c3 ^ np.uint64(k1), lo0)
        k0 = (k0 + 0x9E3779B9) & 0xFFFFFFFF
        k1 = (k1 + 0xBB67AE85) & 0xFFFFFFFF
    u = (c1 << np.uint64(32)) | c0
    return (u % np.uint64(hi)).astype(np.int64)


def _build_lib() -> Optional[str]:
    """Compile csrc/sampler.cpp -> build/libsampler-<hash>.so if missing."""
    path = _lib_path()
    if path is None:
        return None
    if os.path.exists(path):
        return path
    os.makedirs(_BUILD_DIR, exist_ok=True)
    tmp = path + f".tmp{os.getpid()}"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
           _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, path)
        return path
    except Exception:
        if os.path.exists(tmp):
            os.unlink(tmp)
        return None


def _load_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    with _lib_lock:
        if _lib is not None or _lib_failed:
            return _lib
        path = _build_lib()
        if path is None:
            _lib_failed = True
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            _lib_failed = True
            return None
        lib.dl_open.restype = ctypes.c_void_p
        lib.dl_open.argtypes = [ctypes.c_char_p]
        lib.dl_close.argtypes = [ctypes.c_void_p]
        lib.dl_num_tokens.restype = ctypes.c_uint64
        lib.dl_num_tokens.argtypes = [ctypes.c_void_p]
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
        lib.dl_sample.restype = ctypes.c_int
        lib.dl_sample.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                  ctypes.c_uint64, ctypes.c_uint32,
                                  ctypes.c_uint32, i32p, i32p]
        lib.dl_sample_rows.restype = ctypes.c_int
        lib.dl_sample_rows.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                       ctypes.c_uint64, u32p,
                                       ctypes.c_uint32, ctypes.c_uint32,
                                       i32p, i32p]
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        lib.dl_sample_offsets.restype = None
        lib.dl_sample_offsets.argtypes = [ctypes.c_uint64, ctypes.c_uint64,
                                          u32p, ctypes.c_uint32,
                                          ctypes.c_uint64, i64p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load_lib() is not None


def native_offsets(seed: int, step: int, rows: np.ndarray,
                   hi: int) -> np.ndarray:
    """The C++ sample_offset() stream for `rows` — the native counterpart of
    `philox_offsets`, exported for direct bit-identity testing."""
    lib = _load_lib()
    if lib is None:
        raise OSError("native sampler library unavailable")
    rows = np.ascontiguousarray(rows, np.uint32)
    out = np.empty(len(rows), np.int64)
    lib.dl_sample_offsets(seed, step, rows, len(rows), hi, out)
    return out


class NativeSampler:
    """Handle over the C++ loader. Raises OSError if the library or file
    can't be opened — callers (DataLoader) decide on fallback."""

    def __init__(self, path: str):
        lib = _load_lib()
        if lib is None:
            raise OSError("native sampler library unavailable")
        self._lib = lib
        self._h = lib.dl_open(path.encode())
        if not self._h:
            raise OSError(f"dl_open failed for {path}")

    @property
    def n_tokens(self) -> int:
        return int(self._lib.dl_num_tokens(self._h))

    def sample(self, seed: int, step: int, n_rows: int, T: int):
        """Full contiguous global batch (rows 0..n_rows), with background
        prefetch of step+1 inside the library."""
        x = np.empty((n_rows, T), np.int32)
        y = np.empty((n_rows, T), np.int32)
        rc = self._lib.dl_sample(self._h, seed, step, n_rows, T, x, y)
        if rc != 0:
            raise ValueError("dataset too small for block size")
        return x, y

    def sample_rows(self, seed: int, step: int, rows: np.ndarray, T: int):
        """Arbitrary row subset (multi-host shard materialization)."""
        rows = np.ascontiguousarray(rows, np.uint32)
        n = len(rows)
        x = np.empty((n, T), np.int32)
        y = np.empty((n, T), np.int32)
        rc = self._lib.dl_sample_rows(self._h, seed, step, rows, n, T, x, y)
        if rc != 0:
            raise ValueError("dataset too small for block size")
        return x, y

    def close(self):
        if self._h:
            self._lib.dl_close(self._h)
            self._h = None

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass
