"""Data pipeline: offline prepare scripts producing uint16 GPT-2-BPE `.bin`
shards (format-compatible with the reference's data/*/prepare.py) + a
memmap-backed random-sampling loader that places batches directly into
their mesh shards."""

from distributed_pytorch_tpu.data.loader import DataLoader, make_synthetic_bin  # noqa: F401
