"""Prepare FineWeb: streaming shard tokenization → uint16 train.bin/val.bin.

The reference DECLARES fineweb as its default dataset ("Has 10B tokens",
single-gpu/train.sh:6; `Trainconfig` Literal, single-gpu/train.py:31) but
ships no prepare script for it (SURVEY.md §2e) — this one exceeds the
reference by existing. Design differences from the tinystories script,
forced by scale:

* HF `HuggingFaceFW/fineweb` is streamed (`streaming=True`): tokens are
  appended to the .bins shard-by-shard, so preparing a 10B-token corpus
  never needs the dataset (or the ids column) in RAM or on disk at once.
* deterministic 1% val holdout: every 100th document goes to val — a
  streaming-stable split (no global shuffle exists in a stream; the
  reference's seed-1729 `train_test_split` needs the full dataset local).
* `--limit N` stops after N documents (smoke tests / sub-corpora).
* `--input FILE` treats a local text file (blank-line-separated documents)
  as the corpus for air-gapped runs — this environment has no egress, so
  the HF path errors gracefully with that pointer.

Output is the loader's raw-uint16 format, same as every other prepare
script (reference data/shakespeare/prepare.py:30-36).
"""

from __future__ import annotations

import argparse
import os

import numpy as np

from distributed_pytorch_tpu.data.prepare import get_tokenizer

VAL_EVERY = 100  # 1% deterministic holdout


class _BinWriter:
    """Append uint16 tokens to <path>.part, atomically promote on close."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self.tmp = f"{path}.part.{os.getpid()}"
        self.f = open(self.tmp, "wb")
        self.n = 0

    def append(self, ids) -> None:
        arr = np.asarray(ids, dtype=np.uint16)
        arr.tofile(self.f)
        self.n += arr.size

    def close(self) -> None:
        self.f.close()
        os.replace(self.tmp, self.path)
        print(f"[prepare] wrote {self.path}: {self.n:,} tokens")

    def abort(self) -> None:
        """Discard the partial .part file — a truncated corpus must never
        be promoted to train.bin (later runs would silently train on it)."""
        self.f.close()
        if os.path.exists(self.tmp):
            os.remove(self.tmp)


def _documents(args):
    """Yield document strings from --input or the streamed HF dataset."""
    if args.input:
        with open(args.input, encoding="utf-8") as f:
            blocks = f.read().split("\n\n")
        for b in blocks:
            if b.strip():
                yield b.strip()
        return
    try:
        from datasets import load_dataset
        ds = load_dataset("HuggingFaceFW/fineweb", name=args.config,
                          split="train", streaming=True)
    except Exception as e:
        raise SystemExit(
            f"[prepare] cannot stream HuggingFaceFW/fineweb ({e}). "
            "In an air-gapped environment, pass --input FILE with a local "
            "corpus (blank-line-separated documents).") from e
    for ex in ds:
        yield ex["text"]


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="Prepare FineWeb .bins")
    p.add_argument("--out_dir", default="data/fineweb")
    p.add_argument("--config", default="sample-10BT",
                   help="fineweb subset (sample-10BT matches the "
                        "reference's '10B tokens' claim)")
    p.add_argument("--input", default=None,
                   help="local corpus file; skips the HF stream")
    p.add_argument("--tokenizer", default="auto",
                   choices=["auto", "gpt2", "byte"])
    p.add_argument("--limit", type=int, default=0,
                   help="stop after N documents (0 = all)")
    args = p.parse_args(argv)

    encode, eot, name = get_tokenizer(args.tokenizer)
    train = _BinWriter(os.path.join(args.out_dir, "train.bin"))
    val = _BinWriter(os.path.join(args.out_dir, "val.bin"))
    try:
        for i, text in enumerate(_documents(args)):
            if args.limit and i >= args.limit:
                break
            ids = encode(text)
            ids.append(eot)
            (val if i % VAL_EVERY == 0 else train).append(ids)
            if (i + 1) % 10000 == 0:
                print(f"[prepare] {i + 1:,} docs, "
                      f"{train.n + val.n:,} tokens ({name})")
    except BaseException:
        # promote only on clean completion; a stream that died mid-corpus
        # leaves no .bin behind rather than a silently truncated one
        train.abort()
        val.abort()
        raise
    train.close()
    val.close()


if __name__ == "__main__":
    main()
