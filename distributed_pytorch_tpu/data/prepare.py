"""Shared ETL helpers for the dataset prepare scripts.

Reference parity: both reference prepare scripts tokenize with tiktoken's
GPT-2 BPE and write RAW uint16 token files (`data/shakespeare/prepare.py:
7-36`, `data/tinystories/prepare.py:13-52`) — the exact format this
package's DataLoader memmaps, so .bin files prepared by either codebase
are interchangeable.

Tokenizer resolution order: tiktoken GPT-2 BPE (the reference's choice) →
HuggingFace GPT2TokenizerFast (local cache only) → byte-level fallback
(vocab 256; keeps the pipeline runnable in air-gapped environments like
this one, with a loud warning since the vocabulary differs).
"""

from __future__ import annotations

import os
import sys
from typing import Callable, Optional

import numpy as np

GPT2_EOT = 50256


def get_tokenizer(prefer: str = "auto"):
    """Return (encode_fn, eot_id, name). encode_fn: str -> list[int]."""
    if prefer in ("auto", "gpt2"):
        try:
            import tiktoken
            enc = tiktoken.get_encoding("gpt2")
            enc.encode("probe")  # force lazy vocab fetch now
            return (lambda s: enc.encode_ordinary(s)), GPT2_EOT, "gpt2-bpe"
        except Exception:
            pass
        try:
            os.environ.setdefault("HF_HUB_OFFLINE", "1")
            os.environ.setdefault("TRANSFORMERS_OFFLINE", "1")
            from transformers import GPT2TokenizerFast
            tok = GPT2TokenizerFast.from_pretrained("gpt2")
            return (lambda s: tok.encode(s)), GPT2_EOT, "gpt2-bpe-hf"
        except Exception:
            pass
        if prefer == "gpt2":
            raise RuntimeError(
                "GPT-2 BPE unavailable: tiktoken could not fetch its vocab "
                "(no network?) and no local HuggingFace gpt2 cache exists. "
                "Use --tokenizer byte for an air-gapped run.")
    if prefer in ("auto", "byte"):
        print("[prepare] WARNING: GPT-2 BPE unavailable (no network, no "
              "cache) — falling back to byte-level tokens (vocab 256). "
              "Models trained on these bins need vocab_size >= 257.",
              file=sys.stderr)
        return (lambda s: list(s.encode("utf-8"))), 256, "byte"
    raise ValueError(f"unknown tokenizer preference {prefer!r}")


def write_bin(tokens, path: str) -> int:
    """Write a uint16 raw token file (reference prepare.py:30-36 format)."""
    arr = np.asarray(tokens, dtype=np.uint16)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    arr.tofile(path)
    print(f"[prepare] wrote {path}: {arr.size:,} tokens")
    return arr.size


def read_text(input_path: Optional[str], url: str, cache_path: str) -> str:
    """Load corpus text: local --input file if given, else download `url`
    to `cache_path` (reference downloads unconditionally,
    data/shakespeare/prepare.py:10-15)."""
    if input_path:
        with open(input_path, encoding="utf-8") as f:
            return f.read()
    if not os.path.exists(cache_path):
        import urllib.request
        os.makedirs(os.path.dirname(cache_path) or ".", exist_ok=True)
        print(f"[prepare] downloading {url}")
        # download to a temp name, promote atomically: an interrupted fetch
        # must not leave a partial file that later runs silently reuse
        tmp = cache_path + ".part"
        urllib.request.urlretrieve(url, tmp)
        os.replace(tmp, cache_path)
    with open(cache_path, encoding="utf-8") as f:
        return f.read()
