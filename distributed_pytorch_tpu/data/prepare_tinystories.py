"""Prepare TinyStories: HF `roneneldan/TinyStories` → parallel GPT-2 BPE
tokenize (+ EOT append per story) → 1% val split (seed 1729) → uint16
train.bin/val.bin.

Reference parity (`data/tinystories/prepare.py:13-52`): same dataset, same
1% split with the same seed, same EOT-50256 story delimiter, same parallel
`.map` tokenization, same raw-uint16 output. Additions: `--input` treats a
local text file (one story per blank-line-separated block) as the corpus
for air-gapped runs, `--limit` for smoke tests.
"""

from __future__ import annotations

import argparse
import os

import numpy as np

from distributed_pytorch_tpu.data.prepare import get_tokenizer, write_bin


def _stories_from_file(path: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        blocks = f.read().split("\n\n")
    return [b.strip() for b in blocks if b.strip()]


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="Prepare TinyStories .bins")
    p.add_argument("--out_dir", default="data/tinystories")
    p.add_argument("--input", default=None,
                   help="local corpus file (blank-line-separated stories); "
                        "skips the HF download")
    p.add_argument("--tokenizer", default="auto",
                   choices=["auto", "gpt2", "byte"])
    p.add_argument("--limit", type=int, default=0,
                   help="use only the first N stories (smoke tests)")
    p.add_argument("--num_proc", type=int,
                   default=max((os.cpu_count() or 2) // 2, 1))
    args = p.parse_args(argv)

    encode, eot, name = get_tokenizer(args.tokenizer)

    if args.input:
        stories = _stories_from_file(args.input)
        if args.limit:
            stories = stories[:args.limit]
        rng = np.random.default_rng(1729)  # reference split seed
        idx = rng.permutation(len(stories))
        n_val = max(len(stories) // 100, 1)  # 1% val (reference :22-23)
        val_ids = set(idx[:n_val].tolist())
        splits = {
            "train": [s for i, s in enumerate(stories) if i not in val_ids],
            "val": [s for i, s in enumerate(stories) if i in val_ids],
        }
        for split, items in splits.items():
            toks: list[int] = []
            for s in items:
                toks.extend(encode(s))
                toks.append(eot)
            write_bin(toks, os.path.join(args.out_dir, f"{split}.bin"))
        print(f"[prepare] {len(splits['train'])} train / "
              f"{len(splits['val'])} val stories ({name})")
        return

    # HF path (reference data/tinystories/prepare.py:13-52)
    from datasets import load_dataset
    ds = load_dataset("roneneldan/TinyStories", num_proc=args.num_proc)
    full = ds["train"]
    if args.limit:
        full = full.select(range(args.limit))
    split_ds = full.train_test_split(test_size=0.01, seed=1729,
                                     shuffle=True)
    named = {"train": split_ds["train"], "val": split_ds["test"]}

    def tokenize(example):
        ids = encode(example["text"])
        ids.append(eot)  # reference appends EOT per story (:36)
        return {"ids": ids, "len": len(ids)}

    for split, dset in named.items():
        tokenized = dset.map(tokenize, remove_columns=["text"],
                             num_proc=args.num_proc,
                             desc=f"tokenizing {split}")
        total = int(np.sum(tokenized["len"], dtype=np.int64))
        # stream Arrow batches into a memmap of the output file — the full
        # ids column as Python lists would be tens of GB for the real
        # dataset (nanoGPT-style batched write)
        path = os.path.join(args.out_dir, f"{split}.bin")
        os.makedirs(args.out_dir or ".", exist_ok=True)
        out = np.memmap(path, dtype=np.uint16, mode="w+", shape=(total,))
        pos = 0
        for batch in tokenized.with_format("numpy").iter(batch_size=1024):
            flat = np.concatenate(list(batch["ids"])).astype(np.uint16)
            out[pos:pos + flat.size] = flat
            pos += flat.size
        out.flush()
        print(f"[prepare] wrote {path}: {total:,} tokens")


if __name__ == "__main__":
    main()
