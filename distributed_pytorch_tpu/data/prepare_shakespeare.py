"""Prepare tinyshakespeare: download → GPT-2 BPE → 90/10 split →
uint16 train.bin/val.bin.

Reference parity (`data/shakespeare/prepare.py:7-36`): same source URL,
same 90/10 contiguous split, same raw-uint16 output format. Additions:
`--input` for an air-gapped local corpus and `--out_dir` (the reference
hardcodes its own directory).
"""

from __future__ import annotations

import argparse
import os

from distributed_pytorch_tpu.data.prepare import (get_tokenizer, read_text,
                                                  write_bin)

URL = ("https://raw.githubusercontent.com/karpathy/char-rnn/master/data/"
       "tinyshakespeare/input.txt")


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="Prepare tinyshakespeare .bins")
    p.add_argument("--out_dir", default="data/shakespeare")
    p.add_argument("--input", default=None,
                   help="local corpus text file (skips the download)")
    p.add_argument("--tokenizer", default="auto",
                   choices=["auto", "gpt2", "byte"])
    args = p.parse_args(argv)

    text = read_text(args.input, URL, os.path.join(args.out_dir, "input.txt"))
    encode, _, name = get_tokenizer(args.tokenizer)
    tokens = encode(text)
    print(f"[prepare] tokenized {len(text):,} chars -> {len(tokens):,} "
          f"tokens ({name})")
    n = len(tokens)
    split = int(n * 0.9)  # reference: first 90% train (prepare.py:21-23)
    write_bin(tokens[:split], os.path.join(args.out_dir, "train.bin"))
    write_bin(tokens[split:], os.path.join(args.out_dir, "val.bin"))


if __name__ == "__main__":
    main()
