"""Rotary position embeddings (RoPE).

Reference parity: `LLMconfig.apply_rotary_emb` + `LLM._precompute_freqs_cis`
(reference single-gpu/model.py:77-96,567-577): theta base 10000, pairs taken
*adjacently* along the head dim (x reshaped to (..., hs//2, 2)), rotation by
complex multiply.

TPU-first divergence: no complex dtypes. XLA on TPU lowers complex arithmetic
to pairs of real ops anyway, and Pallas kernels can't consume complex inputs;
we precompute real (cos, sin) tables and rotate with two fused multiplies.
Numerics are identical (same pairing, same angles).
"""

from __future__ import annotations

import jax.numpy as jnp


def precompute_rope_freqs(dim: int, max_seq_len: int, base: float = 10000.0,
                          dtype=jnp.float32) -> jnp.ndarray:
    """Return a (max_seq_len, dim//2, 2) table of (cos, sin) angles.

    Matches reference _precompute_freqs_cis (model.py:567-577):
    theta_i = base^(-2i/dim), angle[t, i] = t * theta_i.
    """
    assert dim % 2 == 0, "head dimension must be even"
    theta = 1.0 / (base ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    seq = jnp.arange(max_seq_len, dtype=jnp.float32)
    freqs = jnp.outer(seq, theta)  # (T, dim//2)
    return jnp.stack([jnp.cos(freqs), jnp.sin(freqs)], axis=-1).astype(dtype)


def slice_rows(table: jnp.ndarray, pos, length: int) -> jnp.ndarray:
    """table[pos : pos+length] along axis 0, supporting traced `pos`
    (KV-cached decode), a per-sequence (B,) position array (slot-based
    ragged decode — returns a leading batch axis, (B, length, ...)), and
    the static pos==0 fast path. Shared by RoPE freq / positional-embedding
    lookups. Out-of-table positions clamp to the last row
    (dynamic_slice semantics) — the sliding-window behavior once the ring
    cache wraps past the table."""
    import jax
    if isinstance(pos, int) and pos == 0:
        return table[:length]
    pos = jnp.asarray(pos)
    if pos.ndim == 1:
        return jax.vmap(lambda p: jax.lax.dynamic_slice_in_dim(
            table, p, length, axis=0))(pos)
    return jax.lax.dynamic_slice_in_dim(table, pos, length, axis=0)


def apply_rotary_emb(x: jnp.ndarray, freqs: jnp.ndarray) -> jnp.ndarray:
    """Rotate pairs (x[..., 2i], x[..., 2i+1]) by the angles in `freqs`.

    x: (B, T, H, hs); freqs: (T, hs//2, 2) slice of the precomputed table
    (caller slices [start_pos : start_pos+T] for KV-cached decoding, like
    reference model.py:660), or a per-sequence (B, T, hs//2, 2) slice when
    sequences in the batch sit at different positions (slot-based ragged
    decode). Computation in fp32, cast back to x.dtype (matching reference
    `x.float()` ... `type_as(x)`).
    """
    B, T, H, hs = x.shape
    xf = x.astype(jnp.float32).reshape(B, T, H, hs // 2, 2)
    x_re, x_im = xf[..., 0], xf[..., 1]
    if freqs.ndim == 4:               # per-sequence rows
        cos = freqs[:, :, None, :, 0]  # (B, T, 1, hs//2)
        sin = freqs[:, :, None, :, 1]
    else:
        cos = freqs[None, :, None, :, 0]  # (1, T, 1, hs//2)
        sin = freqs[None, :, None, :, 1]
    out_re = x_re * cos - x_im * sin
    out_im = x_re * sin + x_im * cos
    out = jnp.stack([out_re, out_im], axis=-1).reshape(B, T, H, hs)
    return out.astype(x.dtype)
