"""Collective matmul: ZeRO-3 param all-gathers and grad reduce-scatters
fused into ppermute rings that overlap with the matmuls consuming them.

Under the param-sharded recipes (fsdp / fsdp_tp / sp), every Block matmul
needs the full weight while storage holds only a 1/dp shard: GSPMD's
default schedule emits a blocking all-gather before the matmul and a
blocking reduce-scatter after the grad matmul, and at the 350M-1.5B ladder
scales those collectives become the step's critical path (BASELINE.json
north star). Megatron-LM (arXiv:2104.04473) and GSPMD's own collective-
matmul pass (arXiv:2105.04663 §3.4) both show the fix: decompose the
matmul over weight shards so each ring hop's ppermute is in flight while
the previous shard's partial matmul runs on the MXU.

Primitives (all shard_map bodies over the 'data' mesh axis, wrapped in ONE
custom_vjp at the logical level so forward and backward each get their own
dedicated ring):

* **all-gather ⊗ matmul** (forward / recompute): `y = x @ W` with W
  data-sharded on the contraction dim (K-ring: each arriving shard
  multiplies its x column block into a running accumulator) or on the
  output dim (N-ring: each arriving shard writes its output column block).
* **matmul ⊗ reduce-scatter** (grad path): `dW = x^T @ dy` where each hop
  computes the partial block owned by the accumulator's final destination
  and adds it to the acc arriving from the left neighbor — true ZeRO-2/3
  reduce-scatter semantics, overlapped.
* **bidirectional ring**: shards circulate clockwise AND counter-clockwise
  (ceil((dp-1)/2) sequential hops instead of dp-1), using both ICI
  directions — `OVERLAP_RING=uni|bidir` selects, default bidir.

Dispatch: `maybe_overlap_matmul` returns None (caller keeps its plain
GSPMD matmul, bit-identical to before this module existed) unless ALL of:
`OVERLAP` resolves to 'on' (env var wins over TrainConfig.overlap; 'auto'
currently falls back to the known-good GSPMD path until a hardware number
exists — flip `_AUTO_RESOLVES_TO` after the first TPU window), the ambient
recipe is ZeRO-3-family, the mesh has a live 'data' axis, the param's
recipe spec actually shards it over 'data', shapes divide, and we are not
inside an sp shard_map region or a hoisted-gather scan (train/step.py).

The 'model' axis composes when it lands on the matmul's OUTPUT dim (the
megatron column-parallel case, e.g. c_fc under fsdp_tp): the ring runs
per tp shard and dx picks up one psum over 'model'. 'model' on the
contraction dim disqualifies (row-parallel matmuls keep the GSPMD path).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from distributed_pytorch_tpu import compat, config
from distributed_pytorch_tpu.parallel import context
from distributed_pytorch_tpu.parallel.sharding import spec_for_param

# Recipes whose params are data-sharded (mirrors sharding._PARAM_SHARDED;
# re-declared here so an import cycle can't form through parallel.sharding).
_ZERO3_RECIPES = ("fsdp", "fsdp_tp", "sp")

# What 'auto' means today: GSPMD. The first TPU window that measures
# OVERLAP=on faster flips this to "on" (bench.py / mfu_sweep.py carry the
# A/B legs so no code change is needed to take the measurement).
_AUTO_RESOLVES_TO = "off"


def resolve_mode(config_mode: str = "auto") -> str:
    """'on' | 'off' after applying env-var precedence and the auto default.

    The OVERLAP env var (on/off/auto) wins over the TrainConfig field so
    bench/sweep legs can A/B without a config plumb-through."""
    mode = config.knob("OVERLAP") or config_mode
    if mode not in ("auto", "on", "off"):
        raise ValueError(f"OVERLAP must be auto|on|off, got {mode!r}")
    return _AUTO_RESOLVES_TO if mode == "auto" else mode


def _ring_style() -> bool:
    """True = bidirectional (both ICI directions, ~half the sequential
    hops); env OVERLAP_RING=uni forces the one-way ring for A/B."""
    return config.knob("OVERLAP_RING") != "uni"


# ---------------------------------------------------------------------------
# ring drivers (run inside shard_map)
# ---------------------------------------------------------------------------

def _ring_visit(w_l, axis: str, dp: int, bidir: bool,
                visit: Callable[[jnp.ndarray, jnp.ndarray], None]) -> None:
    """Call `visit(src, shard)` once per ring source, issuing each hop's
    ppermute BEFORE the previous shard's compute so XLA's async
    collective-permute overlaps the transfer with the matmul (`src` is the
    traced origin device of the shard on the 'data' ring)."""
    idx = jax.lax.axis_index(axis)
    if dp <= 2 or not bidir:
        perm = [(i, (i + 1) % dp) for i in range(dp)]
        pend = jax.lax.ppermute(w_l, axis, perm) if dp > 1 else None
        visit(idx, w_l)
        for s in range(1, dp):
            cur = pend
            pend = jax.lax.ppermute(cur, axis, perm) if s < dp - 1 else None
            visit((idx - s) % dp, cur)
        return
    # bidirectional: right ring carries sources idx-1..idx-n_right,
    # left ring idx+1..idx+n_left; ceil((dp-1)/2) sequential hops
    n_right = dp // 2
    n_left = dp - 1 - n_right
    perm_r = [(i, (i + 1) % dp) for i in range(dp)]
    perm_l = [(i, (i - 1) % dp) for i in range(dp)]
    pend_r = jax.lax.ppermute(w_l, axis, perm_r)
    pend_l = jax.lax.ppermute(w_l, axis, perm_l) if n_left else None
    visit(idx, w_l)
    for h in range(1, n_right + 1):
        cur_r, cur_l = pend_r, pend_l
        pend_r = jax.lax.ppermute(cur_r, axis, perm_r) if h < n_right \
            else None
        pend_l = jax.lax.ppermute(cur_l, axis, perm_l) if h < n_left \
            else None
        visit((idx - h) % dp, cur_r)
        if h <= n_left:
            visit((idx + h) % dp, cur_l)


def _ring_reduce_scatter(partial_fn: Callable[[jnp.ndarray], jnp.ndarray],
                         axis: str, dp: int) -> jnp.ndarray:
    """matmul ⊗ reduce-scatter: `partial_fn(tgt)` computes this device's
    partial for ring block `tgt`; the accumulator travels i -> i+1 each hop
    and lands home fully reduced after dp-1 hops. The ppermute is issued
    before the next partial's matmul, so transfer overlaps compute."""
    idx = jax.lax.axis_index(axis)
    if dp == 1:
        return partial_fn(idx)
    perm = [(i, (i + 1) % dp) for i in range(dp)]
    acc = partial_fn((idx + dp - 1) % dp)
    for s in range(1, dp):
        acc_in = jax.lax.ppermute(acc, axis, perm)      # in flight...
        p = partial_fn((idx + dp - 1 - s) % dp)         # ...during this
        acc = acc_in + p
    return acc


# ---------------------------------------------------------------------------
# the custom-vjp collective matmul (logical level)
# ---------------------------------------------------------------------------

def _dot2(a, b):
    return jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _dot2_tn(a, b):
    """a^T @ b with f32 accumulation: (m, k), (m, n) -> (k, n)."""
    return jax.lax.dot_general(a, b, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


@functools.lru_cache(maxsize=256)
def _build_cm(mesh: Mesh, w_spec: P, transpose_b: bool, data_on_k: bool,
              model_on_n: bool, seq_live: bool, bidir: bool,
              out_dtype_name: Optional[str]):
    """One custom_vjp collective matmul per static configuration.

    Logical contract: y = x @ W where W = w.T when transpose_b (w is the
    stored param, e.g. the (V, C) embedding for the (C, V) lm head).
    x: (B, T, K); w 2D with `w_spec` its recipe PartitionSpec. `data_on_k`:
    whether 'data' lands on W's contraction dim (K-ring) or output dim
    (N-ring). `model_on_n`: W additionally 'model'-sharded on its output
    dim (and y/dy carry that sharding)."""
    dp = mesh.shape["data"]
    seq = "seq" if seq_live else None
    x_spec = P("data", seq, None)
    y_spec = P("data", seq, "model" if model_on_n else None)

    def _orient(w_s):
        return w_s.T if transpose_b else w_s            # (K_part, N_part)

    def fwd_local(x_l, w_l):
        B, T, K = x_l.shape
        x2 = x_l.reshape(B * T, K)
        box = {}

        if data_on_k:
            def visit(src, w_s):
                w2 = _orient(w_s)                       # (Kc, N_loc)
                kc = w2.shape[0]
                x_blk = jax.lax.dynamic_slice_in_dim(x2, src * kc, kc,
                                                     axis=1)
                c = _dot2(x_blk, w2)
                box["acc"] = c if "acc" not in box else box["acc"] + c
        else:
            def visit(src, w_s):
                w2 = _orient(w_s)                       # (K, Nc)
                nc = w2.shape[1]
                if "acc" not in box:
                    box["acc"] = jnp.zeros((B * T, nc * dp), jnp.float32)
                box["acc"] = jax.lax.dynamic_update_slice(
                    box["acc"], _dot2(x2, w2), (0, src * nc))

        _ring_visit(w_l, "data", dp, bidir, visit)
        y2 = box["acc"]
        dt = jnp.dtype(out_dtype_name) if out_dtype_name else x_l.dtype
        return y2.reshape(B, T, y2.shape[-1]).astype(dt)

    def dx_local(dy_l, w_l):
        B, T, N = dy_l.shape
        dy2 = dy_l.reshape(B * T, N).astype(jnp.float32)
        box = {}

        if data_on_k:
            # W^T is output-sharded on K: N-style ring writing K blocks
            def visit(src, w_s):
                w2 = _orient(w_s)                       # (Kc, N_loc)
                kc = w2.shape[0]
                if "acc" not in box:
                    box["acc"] = jnp.zeros((B * T, kc * dp), jnp.float32)
                box["acc"] = jax.lax.dynamic_update_slice(
                    box["acc"], _dot2(dy2, w2.astype(jnp.float32).T),
                    (0, src * kc))
        else:
            # W^T contraction-sharded on N: accumulate over dy column blocks
            def visit(src, w_s):
                w2 = _orient(w_s)                       # (K, Nc)
                nc = w2.shape[1]
                dy_blk = jax.lax.dynamic_slice_in_dim(dy2, src * nc, nc,
                                                      axis=1)
                c = _dot2(dy_blk, w2.astype(jnp.float32).T)
                box["acc"] = c if "acc" not in box else box["acc"] + c

        _ring_visit(w_l, "data", dp, bidir, visit)
        dx2 = box["acc"]
        if model_on_n:
            # each tp shard contracted only its N/tp slice of dy
            dx2 = jax.lax.psum(dx2, "model")
        return dx2.reshape(B, T, dx2.shape[-1])

    def dw_local(x_l, dy_l):
        B, T, K = x_l.shape
        x2 = x_l.reshape(B * T, K)
        dy2 = dy_l.reshape(B * T, -1)

        if data_on_k:
            kc = K // dp

            def partial(tgt):
                x_blk = jax.lax.dynamic_slice_in_dim(x2, tgt * kc, kc,
                                                     axis=1)
                return _dot2_tn(x_blk, dy2)             # (kc, N_loc) f32
        else:
            nglob = dy2.shape[1]
            nc = nglob // dp

            def partial(tgt):
                dy_blk = jax.lax.dynamic_slice_in_dim(dy2, tgt * nc, nc,
                                                      axis=1)
                return _dot2_tn(x2, dy_blk)             # (K, nc) f32

        dw = _ring_reduce_scatter(partial, "data", dp)
        if seq_live:
            dw = jax.lax.psum(dw, "seq")                # sum over T shards
        return dw.T if transpose_b else dw

    fwd_sm = compat.shard_map(fwd_local, mesh=mesh,
                              in_specs=(x_spec, w_spec), out_specs=y_spec)
    dx_sm = compat.shard_map(dx_local, mesh=mesh,
                             in_specs=(y_spec, w_spec), out_specs=x_spec)
    dw_sm = compat.shard_map(dw_local, mesh=mesh,
                             in_specs=(x_spec, y_spec), out_specs=w_spec)

    @jax.custom_vjp
    def cm(x, w):
        return fwd_sm(x, w)

    def cm_fwd(x, w):
        return fwd_sm(x, w), (x, w)

    def cm_bwd(res, dy):
        x, w = res
        dx = dx_sm(dy, w).astype(x.dtype)
        dw = dw_sm(x, dy).astype(w.dtype)
        return dx, dw

    cm.defvjp(cm_fwd, cm_bwd)
    return cm


# ---------------------------------------------------------------------------
# dispatcher
# ---------------------------------------------------------------------------

def maybe_overlap_matmul(x: jnp.ndarray, w: jnp.ndarray, *,
                         names: tuple[str, ...],
                         transpose_b: bool = False,
                         out_dtype=None) -> Optional[jnp.ndarray]:
    """y = x @ w (x @ w.T when transpose_b) through the collective-matmul
    ring, or None when the caller should keep its plain GSPMD matmul.

    `names`: the param's path suffix (e.g. ('c_fc',) or
    ('tkn_emb', 'embedding')) — fed to the SAME spec table the recipe uses
    (parallel/sharding.spec_for_param) so the ring's in_specs cannot drift
    from how the param is actually stored."""
    mode, recipe = context.overlap_state()
    if resolve_mode(mode) != "on" or recipe not in _ZERO3_RECIPES:
        return None
    if context.gathers_hoisted() or context.in_sp_region():
        return None
    mesh = context.get_mesh()
    if mesh is None or w.ndim != 2 or x.ndim != 3:
        return None
    dp = mesh.shape.get("data", 1)
    if dp <= 1 or x.shape[0] % dp != 0:
        return None
    sp = mesh.shape.get("seq", 1)
    seq_live = sp > 1
    if seq_live and x.shape[1] % sp != 0:
        return None

    spec = spec_for_param(names, tuple(w.shape), recipe, mesh)
    axes = tuple(spec) + (None,) * (2 - len(tuple(spec)))
    if "data" not in axes:
        return None                                     # recipe left w whole
    data_w_axis = axes.index("data")
    # map the stored-orientation axis onto the logical matmul: w is (K, N),
    # or (N, K) when transpose_b
    data_on_k = (data_w_axis == 0) != transpose_b
    model_on_n = False
    if "model" in axes:
        model_w_axis = axes.index("model")
        if (model_w_axis == 0) != transpose_b:
            return None                                 # row-parallel: GSPMD
        model_on_n = True
    # contraction dim must agree between x and w
    k_w_axis = 1 if transpose_b else 0
    if x.shape[-1] != w.shape[k_w_axis]:
        return None

    cm = _build_cm(mesh, spec, transpose_b, data_on_k, model_on_n,
                   seq_live, _ring_style(),
                   jnp.dtype(out_dtype).name if out_dtype else None)
    return cm(x, w)
