"""Pallas TPU ragged grouped matmul: dropless MoE expert dispatch.

Why: the 'scatter' dispatch (models/mlp.py) is XLA-legal but pays twice —
it materializes (E, capacity, C) gather/scatter buffers in HBM on BOTH
sides of the expert FFNs, and it silently DROPS routed assignments past
`capacity` (GShard position priority). GSPMD lowers the ep recipe's
dispatch/return as all-to-alls but leaves the per-expert matmuls padded
and dense (arXiv:2105.04663 §3.3) — exactly the waste a ragged grouped
kernel removes (MegaBlocks, arXiv:2211.15841).

Layout: routed assignments are stable-sorted by expert into ONE packed
buffer whose groups are padded only to the next token-tile boundary
(bm rows, not `capacity`), so the buffer holds every assignment — dropless
by construction. A scalar-prefetch array maps each bm-row tile to its
expert, so the kernel streams exactly one expert's weight tile per grid
step and empty experts get ZERO grid steps (they own no tiles). The
combine weights (router gates) are applied at the second matmul's output
write, so the scatter-add back to (N, C) is the only HBM round trip on
the return path.

Kernels (all f32-accumulated; operands stay in the input dtype so the MXU
runs at full rate; structure mirrors ops/fused_ce.py):

* forward  — grid (token_tiles, n_tiles): one (bm, K) x tile and the
  owning expert's (K, bn) weight tile are resident; output written once,
  optionally scaled per row by the combine gate.
* backward dx (token-major) — grid (token_tiles, k_tiles):
  dx = (dy * gate) @ W_e^T, streamed over K tiles of the same expert tile
  the forward read.
* backward dW (group-major) — grid (k_tiles, n_tiles, token_tiles), token
  tiles innermost: consecutive tiles of one expert hit the SAME output
  block, which stays resident in VMEM and accumulates
  dW_e += x_tile^T @ (dy_tile * gate); the block flushes when the group
  changes. Experts that own no tiles are never visited — their dW is
  masked to zero afterwards.

Sharding: under a live mesh the dispatch runs inside shard_map over
('data', 'expert') (specs from parallel/sharding.moe_dispatch_specs).
Tokens ride in data-sharded (they already are — zero dispatch
collectives); each expert shard packs ONLY the assignments routed to its
local experts (non-local assignments keep their slot with gate 0, so they
cost tile-rounding FLOPs but contribute nothing) and one psum over
'expert' combines the partial outputs. This replaces the scatter path's
all-to-all pair with a single combine-reduction: under XLA's static
shapes a dropless all-to-all needs worst-case (every assignment to one
shard) buffers, which is the replicated layout anyway — the psum costs
the same bytes as the return all-to-all + gather it replaces and keeps
the dropless guarantee.

Shared experts reuse the same kernel as always-on groups: the dispatch
prepends one group per shared expert containing every token with gate
1.0, so shared + routed experts stream through one packed kernel pair
and one combine scatter-add.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributed_pytorch_tpu import compat, config
from distributed_pytorch_tpu.parallel import context

DEFAULT_BLOCK_M = config.knob("GMM_BLOCK_M")   # token rows
DEFAULT_BLOCK_N = config.knob("GMM_BLOCK_N")   # out features
DEFAULT_BLOCK_K = config.knob("GMM_BLOCK_K")   # contraction


def _pick(n: int, preferred: int, step: int) -> int:
    """Largest divisor of n that is <= preferred and a multiple of `step`;
    n itself when no such divisor exists (tiny test dims)."""
    b = min(preferred, n)
    b -= b % step
    while b > step and n % b != 0:
        b -= step
    return b if (b >= step and n % b == 0) else n


def _dot(a, b):
    return jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _dot_nt(a, b):
    """a @ b^T with f32 accumulation: (m, n), (k, n) -> (m, k)."""
    return jax.lax.dot_general(a, b, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _dot_tn(a, b):
    """a^T @ b with f32 accumulation: (m, k), (m, n) -> (k, n)."""
    return jax.lax.dot_general(a, b, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------

def _fwd_kernel_scaled(g_ref, x_ref, w_ref, s_ref, o_ref):
    del g_ref  # consumed by the index maps (weight-tile selection)
    o = _dot(x_ref[:], w_ref[0]) * s_ref[:]
    o_ref[:] = o.astype(o_ref.dtype)


def _fwd_kernel(g_ref, x_ref, w_ref, o_ref):
    del g_ref
    o_ref[:] = _dot(x_ref[:], w_ref[0]).astype(o_ref.dtype)


def _dx_kernel_scaled(g_ref, dy_ref, w_ref, s_ref, o_ref):
    del g_ref
    d = dy_ref[:].astype(jnp.float32) * s_ref[:]
    o_ref[:] = _dot_nt(d.astype(dy_ref.dtype), w_ref[0]).astype(o_ref.dtype)


def _dx_kernel(g_ref, dy_ref, w_ref, o_ref):
    del g_ref
    o_ref[:] = _dot_nt(dy_ref[:], w_ref[0]).astype(o_ref.dtype)


def _dw_kernel(g_ref, f_ref, x_ref, dy_ref, *rest):
    # rest = (s_ref?, dw_ref) — gate operand present only in scaled calls
    if len(rest) == 2:
        s_ref, dw_ref = rest
        dy = dy_ref[:].astype(jnp.float32) * s_ref[:]
    else:
        (dw_ref,) = rest
        dy = dy_ref[:]
    del g_ref
    i = pl.program_id(2)
    part = _dot_tn(x_ref[:], dy.astype(x_ref.dtype))

    @pl.when(f_ref[i] == 1)
    def _():
        dw_ref[:] = part[None].astype(dw_ref.dtype)

    @pl.when(f_ref[i] == 0)
    def _():
        dw_ref[:] = dw_ref[:] + part[None].astype(dw_ref.dtype)


def _fwd_call(x_pad, w, scales, tile_group, bm, interpret):
    P, K = x_pad.shape
    E, _, N = w.shape
    num_tiles = P // bm
    bn = _pick(N, DEFAULT_BLOCK_N, 8 if interpret else 128)
    in_specs = [
        pl.BlockSpec((bm, K), lambda i, j, g: (i, 0)),
        pl.BlockSpec((1, K, bn), lambda i, j, g: (g[i], 0, j)),
    ]
    args = [tile_group, x_pad, w]
    kern = _fwd_kernel
    if scales is not None:
        in_specs.append(pl.BlockSpec((bm, 1), lambda i, j, g: (i, 0)))
        args.append(scales)
        kern = _fwd_kernel_scaled
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(num_tiles, N // bn),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, g: (i, j)),
    )
    return pl.pallas_call(
        kern, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((P, N), x_pad.dtype),
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("parallel", "parallel")),
        interpret=interpret,
    )(*args)


def _dx_call(dy, w, scales, tile_group, bm, interpret):
    P, N = dy.shape
    E, K, _ = w.shape
    num_tiles = P // bm
    bk = _pick(K, DEFAULT_BLOCK_K, 8 if interpret else 128)
    in_specs = [
        pl.BlockSpec((bm, N), lambda i, k, g: (i, 0)),
        pl.BlockSpec((1, bk, N), lambda i, k, g: (g[i], k, 0)),
    ]
    args = [tile_group, dy, w]
    kern = _dx_kernel
    if scales is not None:
        in_specs.append(pl.BlockSpec((bm, 1), lambda i, k, g: (i, 0)))
        args.append(scales)
        kern = _dx_kernel_scaled
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(num_tiles, K // bk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bm, bk), lambda i, k, g: (i, k)),
    )
    return pl.pallas_call(
        kern, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((P, K), dy.dtype),
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("parallel", "parallel")),
        interpret=interpret,
    )(*args)


def _dw_call_impl(x_pad, dy, scales, tile_group, tile_first, n_experts,
                  bm, interpret):
    P, K = x_pad.shape
    _, N = dy.shape
    num_tiles = P // bm
    step = 8 if interpret else 128
    bk = _pick(K, DEFAULT_BLOCK_K, step)
    bn = _pick(N, DEFAULT_BLOCK_N, step)
    in_specs = [
        pl.BlockSpec((bm, bk), lambda k, j, i, g, f: (i, k)),
        pl.BlockSpec((bm, bn), lambda k, j, i, g, f: (i, j)),
    ]
    args = [tile_group, tile_first, x_pad, dy]
    if scales is not None:
        in_specs.append(pl.BlockSpec((bm, 1), lambda k, j, i, g, f: (i, 0)))
        args.append(scales)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(K // bk, N // bn, num_tiles),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bk, bn),
                               lambda k, j, i, g, f: (g[i], k, j)),
    )
    return pl.pallas_call(
        _dw_kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_experts, K, N), jnp.float32),
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*args)


# ---------------------------------------------------------------------------
# custom VJP over the tile-aligned buffer
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(6,))
def _gmm(x_pad, w, scales, tile_group, tile_first, counts, static):
    """y[r] = (x_pad[r] @ w[expert_of_tile(r)]) * scales[r].

    x_pad (P, K): tile-aligned expert-sorted rows (P = num_tiles * bm);
    w (E, K, N); scales (P, 1) f32 or None; tile_group/tile_first
    (num_tiles,) int32 metadata from _gmm_metadata; counts (E,) int32 real
    rows per group (dW masking). static = (bm, interpret)."""
    bm, interpret = static
    return _fwd_call(x_pad, w, scales, tile_group, bm, interpret)


def _gmm_fwd(x_pad, w, scales, tile_group, tile_first, counts, static):
    y = _gmm(x_pad, w, scales, tile_group, tile_first, counts, static)
    return y, (x_pad, w, scales, tile_group, tile_first, counts)


def _gmm_bwd(static, res, dy):
    bm, interpret = static
    x_pad, w, scales, tile_group, tile_first, counts = res
    ds = None
    if scales is not None:
        # gate cotangent needs the unscaled product; recompute it rather
        # than storing a second (P, N) buffer from forward (same
        # recompute-over-store trade as fused_ce's lse-based backward)
        y_us = _fwd_call(x_pad, w, None, tile_group, bm, interpret)
        ds = jnp.sum(dy.astype(jnp.float32) * y_us.astype(jnp.float32),
                     axis=-1, keepdims=True)
    dx = _dx_call(dy, w, scales, tile_group, bm, interpret)
    dw = _dw_call_impl(x_pad, dy, scales, tile_group, tile_first,
                       w.shape[0], bm, interpret)
    # experts owning zero tiles were never visited — their blocks hold
    # whatever the buffer started with, not zeros
    dw = jnp.where(counts[:, None, None] > 0, dw, 0.0)
    return (dx.astype(x_pad.dtype), dw.astype(w.dtype), ds, None, None,
            None)


_gmm.defvjp(_gmm_fwd, _gmm_bwd)


def gmm(x_pad: jnp.ndarray, w: jnp.ndarray, tile_group: jnp.ndarray,
        tile_first: jnp.ndarray, counts: jnp.ndarray, *,
        scales: Optional[jnp.ndarray] = None, bm: int,
        interpret: bool) -> jnp.ndarray:
    """Ragged grouped matmul over a tile-aligned expert-sorted buffer."""
    return _gmm(x_pad, w, scales, tile_group, tile_first, counts,
                (bm, interpret))


# ---------------------------------------------------------------------------
# dispatch metadata + the full routed/shared dispatch
# ---------------------------------------------------------------------------

def _gmm_metadata(flat_e: jnp.ndarray, n_groups: int, n_tiles: int,
                  bm: int):
    """(counts, slot_for_sorted_rank, tile_group, tile_first) for a flat
    expert-id vector. Groups are padded to the next bm multiple; tile t
    belongs to the group whose padded region covers rows [t*bm, (t+1)*bm).
    Empty groups own zero tiles (skipped entirely); trailing unused tiles
    resolve to the last group — their rows carry gate 0, so they add
    nothing anywhere (forward, dx, dW)."""
    counts = jnp.zeros((n_groups,), jnp.int32).at[flat_e].add(1)
    padded = -(-counts // bm) * bm
    pstart = jnp.cumsum(padded) - padded                   # padded offsets
    tile_start = pstart // bm                              # (E,)
    t = jnp.arange(n_tiles, dtype=jnp.int32)
    tile_group = (jnp.searchsorted(tile_start, t, side="right") - 1
                  ).astype(jnp.int32)
    tile_first = (t == tile_start[tile_group]).astype(jnp.int32)
    starts = jnp.cumsum(counts) - counts                   # packed offsets
    return counts, pstart, starts, tile_group, tile_first


def _pack_rows(x_flat, flat_e, flat_t, flat_g, n_groups, bm):
    """Sort assignments by expert and place them in the tile-aligned
    buffer. Returns (x_pad, row_tok, row_gate, metadata...). Unfilled
    slots keep token 0 with gate 0: computed then zeroed — wasted lanes,
    never wrong (same trick as scatter_dispatch)."""
    A = flat_e.shape[0]
    n_tiles = -(-A // bm) + n_groups
    P = n_tiles * bm

    order = jnp.argsort(flat_e, stable=True)
    se, st = flat_e[order], flat_t[order]
    sg = flat_g[order]

    counts, pstart, starts, tile_group, tile_first = _gmm_metadata(
        flat_e, n_groups, n_tiles, bm)
    pos = jnp.arange(A, dtype=jnp.int32) - starts[se]      # rank in group
    slot = pstart[se] + pos                                # unique, < P

    row_tok = jnp.zeros((P,), jnp.int32).at[slot].set(st)
    row_gate = jnp.zeros((P, 1), jnp.float32).at[slot, 0].set(sg)
    x_pad = x_flat[row_tok]
    return x_pad, row_tok, row_gate, counts, tile_group, tile_first


def _apply_activation(h: jnp.ndarray, non_linearity: str) -> jnp.ndarray:
    """The MLP nonlinearity on the packed hidden buffer (models/mlp.py
    mlp_apply semantics; imported lazily to avoid an ops<->models cycle)."""
    from distributed_pytorch_tpu.models.mlp import _activation, _is_gated
    if _is_gated(non_linearity):
        x1, x2 = jnp.split(h, 2, axis=-1)
        gate = jax.nn.silu(x1) if non_linearity.lower() == "swiglu" \
            else jax.nn.sigmoid(x1)
        return gate * x2
    return _activation(non_linearity)(h)


def _local_grouped_dispatch(x_flat, topk_idx, topk_gates, experts_fc,
                            experts_proj, *, non_linearity: str,
                            n_shared: int, expert_axis: bool,
                            bm: int, interpret: bool) -> jnp.ndarray:
    """Per-device dropless dispatch over the LOCAL expert slice.

    Expert ids are global: [0, n_shared) shared (every token, gate 1.0),
    [n_shared, n_shared + n_routed) routed. With a live 'expert' axis each
    shard keeps only assignments whose global id falls in its slice;
    non-local assignments stay in the buffer re-tagged to the last local
    group with gate 0 (zero contribution, tile-rounding FLOPs only)."""
    with context.expert_region():
        N, C = x_flat.shape
        k = topk_idx.shape[1]
        E_loc = experts_fc.shape[0]
        dt = x_flat.dtype

        lo = jnp.int32(0)
        if expert_axis:
            lo = jax.lax.axis_index("expert") * E_loc

        tok = jnp.arange(N, dtype=jnp.int32)
        ids = [jnp.full((N,), e, jnp.int32) for e in range(n_shared)]
        gts = [jnp.ones((N,), jnp.float32) for _ in range(n_shared)]
        toks = [tok for _ in range(n_shared)]
        ids.append((topk_idx + n_shared).astype(jnp.int32).reshape(-1))
        gts.append(topk_gates.astype(jnp.float32).reshape(-1))
        toks.append(jnp.repeat(tok, k))
        flat_e = jnp.concatenate(ids)
        flat_g = jnp.concatenate(gts)
        flat_t = jnp.concatenate(toks)

        local = (flat_e >= lo) & (flat_e < lo + E_loc)
        flat_e = jnp.where(local, flat_e - lo, E_loc - 1)
        flat_g = jnp.where(local, flat_g, 0.0)

        x_pad, row_tok, row_gate, counts, tile_group, tile_first = \
            _pack_rows(x_flat, flat_e, flat_t, flat_g, E_loc, bm)

        h = gmm(x_pad, experts_fc.astype(dt), tile_group, tile_first,
                counts, bm=bm, interpret=interpret)
        h = _apply_activation(h, non_linearity)
        y = gmm(h, experts_proj.astype(dt), tile_group, tile_first,
                counts, scales=row_gate, bm=bm, interpret=interpret)

        out = jnp.zeros_like(x_flat).at[row_tok].add(y)
        if expert_axis:
            out = jax.lax.psum(out, "expert")
        return out


def grouped_usable(cfg, batch_size: int, dtype) -> bool:
    """Static gate for the grouped path. False -> callers fall back to the
    'dense' combine (identical dropless semantics, E/k x the FLOPs) — the
    same degrade-don't-crash contract as loss_impl='pallas' (gpt.py)."""
    if getattr(cfg, "pp_stages", 1) > 1:
        # the pipeline vmaps Blocks over the layer axis; neither shard_map
        # nor pallas_call composes with that on this jax
        return False
    if context.in_expert_region() or context.in_sp_region():
        return False
    fc_out = 2 * cfg.up_dim \
        if cfg.non_linearity.lower() in ("swiglu", "glu") else cfg.up_dim
    lane = 128 if jax.default_backend() == "tpu" else 8
    if any(d % lane for d in (cfg.n_embd, cfg.up_dim, fc_out)):
        return False
    if jax.default_backend() == "tpu" and \
            jnp.dtype(dtype) not in (jnp.dtype(jnp.float32),
                                     jnp.dtype(jnp.bfloat16)):
        return False
    mesh = context.get_mesh()
    if mesh is not None:
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        if sizes.get("model", 1) > 1 or sizes.get("seq", 1) > 1:
            return False  # tp shards fc_out, sp shards T: scatter/dense
        if batch_size % sizes.get("data", 1):
            return False
        if cfg.n_exp % sizes.get("expert", 1):
            return False
    return True


def grouped_dispatch(x_flat: jnp.ndarray, topk_idx: jnp.ndarray,
                     topk_gates: jnp.ndarray, experts_fc: jnp.ndarray,
                     experts_proj: jnp.ndarray, *, non_linearity: str,
                     n_shared: int = 0,
                     interpret: Optional[bool] = None) -> jnp.ndarray:
    """Dropless grouped-matmul MoE dispatch (module docstring).

    x_flat (N, C); topk_idx/topk_gates (N, k) over the ROUTED experts;
    experts_fc/experts_proj (n_exp, ...) stacked kernels INCLUDING the
    n_shared leading shared experts. Returns shared + routed outputs
    combined, (N, C). Gate with `grouped_usable` first."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # small tiles keep the tile-rounding waste proportionate on the tiny
    # interpret-mode test shapes; hardware uses the MXU-sized default
    bm = 8 if interpret else DEFAULT_BLOCK_M

    mesh = context.get_mesh()
    local = functools.partial(
        _local_grouped_dispatch, non_linearity=non_linearity,
        n_shared=n_shared, bm=bm, interpret=interpret)

    if mesh is None or all(
            mesh.shape.get(ax, 1) <= 1 for ax in ("data", "expert")):
        return local(x_flat, topk_idx, topk_gates, experts_fc,
                     experts_proj, expert_axis=False)

    from distributed_pytorch_tpu.parallel.sharding import moe_dispatch_specs
    tok_spec, w_spec, out_spec = moe_dispatch_specs()
    body = compat.shard_map(
        functools.partial(local, expert_axis=True),
        mesh=mesh,
        in_specs=(tok_spec, tok_spec, tok_spec, w_spec, w_spec),
        out_specs=out_spec,
    )
    return body(x_flat, topk_idx, topk_gates, experts_fc, experts_proj)
