"""Paged KV-cache block pool: host-side allocator + device-side ops.

The fixed (n_slots, S) slot cache pays for the worst case twice: HBM holds
S rows per slot even when the mean sequence is a tenth of that, and a
prompt shared by a thousand requests is prefilled a thousand times. The
vLLM treatment (PagedAttention; PAPERS.md) fixes both with one level of
indirection: KV rows live in fixed-size BLOCKS drawn from a global pool,
each sequence owns an ordered list of block ids (its *block table*), and
immutable full blocks are content-addressed so identical prompt prefixes
resolve to the *same* physical blocks.

Three layers, smallest first:

* **Device ops** (`paged_update`, `paged_gather`): the (n_blocks, bs, ...)
  pool is a plain jax array; a token write is a 2-index scatter through
  the block table (the paged generalization of models/attention.py's O(1)
  ring write), a logical view for the naive/einsum attention paths is one
  advanced-indexing gather — the same bytes the slot cache streamed. The
  flash path skips the gather entirely: ops/flash_decode.py's paged kernel
  DMAs blocks straight from the pool through a block-table scalar
  prefetch. Physical block 0 is the NULL block: retired slots' table rows
  are zeroed, so the fused step's unavoidable dead-slot write lands in a
  row nothing ever reads — the paged replacement for "masked until the
  next occupant overwrites".
* **`BlockPool`**: free-list allocator with per-block refcounts. Blocks
  referenced by live sequences can be shared (a reused prefix); blocks at
  refcount 0 that are *registered* in the prefix index are retained on an
  LRU instead of freed — `alloc()` evicts the oldest only when the free
  list is dry, so HBM that would sit idle caches prefixes for free.
  `alloc()` returning None (everything referenced) is the engine's
  preemption trigger.
* **Prefix index** (`lookup`/`register`): content-addressed full blocks
  keyed by the CHAIN (parent_digest, block_tokens) — a flattened radix
  tree: the parent's ancestry is folded into a fixed-size digest (so a
  key hashes in O(block_size), not O(prefix)); looking up a prompt walks
  key-by-key from the root, so a hit at depth d proves the whole d-block
  prefix matches and an evicted ancestor automatically unreaches its
  descendants (they age out of the LRU).
  Only FULL blocks are ever registered; the partial tail of a sequence is
  always private — sharing is copy-on-write at block granularity (a fork
  allocates a fresh tail block instead of appending to a shared one).

Everything host-side is plain Python on the engine's single thread — the
allocator is bookkeeping, never a device sync.
"""

from __future__ import annotations

import collections
import hashlib
from typing import Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

#: physical block 0 is never allocated: zeroed table rows route dead-slot
#: writes here (see module docstring)
NULL_BLOCK = 0


class NoFreeBlocks(RuntimeError):
    """The pool has no free or evictable block — every block is referenced
    by a live sequence. At admission this means "stay queued"; during
    decode the engine preempts a victim instead."""


# ---------------------------------------------------------------------------
# device-side paged-cache ops
# ---------------------------------------------------------------------------

def paged_update(pool: jnp.ndarray, new: jnp.ndarray, pos,
                 block_tables: jnp.ndarray) -> jnp.ndarray:
    """Write `new` (B, T, ...) rows into the (n_blocks, bs, ...) pool at
    logical positions [pos, pos+T) of each sequence, addressed through
    `block_tables` (B, max_blocks) int32.

    Three shapes, mirroring `_update_cache`'s prefill/decode split plus
    the spec-verify short window:
    * T == 1 (fused decode step): `pos` is per-sequence (B,); one 2-index
      scatter writes every live slot's row. Tail blocks are never shared,
      so concurrent writers cannot collide (dead slots all land in the
      null block — harmless, nothing reads it).
    * T > 1 with per-sequence (B,) `pos` (speculative verify): each slot
      writes T = K+1 consecutive rows starting at its own offset. The
      window is unrolled into T per-slot scatters; a row whose table
      index would run off the table routes to the null block, so the
      traced program is safe for any pos without a bounds retrace.
    * T > 1 with scalar `pos` (bucketed prefill): B == 1, `pos`
      block-aligned (the reused-prefix length), T a multiple of the block
      size; whole blocks are scattered in one shot. Pad rows land in
      blocks private to this sequence and are causally masked exactly as
      in the slot cache.
    """
    new = new.astype(pool.dtype)
    B, T = new.shape[:2]
    bs = pool.shape[1]
    if T == 1:
        p = jnp.broadcast_to(jnp.asarray(pos, jnp.int32).reshape(-1), (B,))
        blk = jnp.take_along_axis(block_tables, (p // bs)[:, None],
                                  axis=1)[:, 0]
        return pool.at[blk, p % bs].set(new[:, 0], mode="drop")
    if jnp.asarray(pos).ndim >= 1:
        # spec-verify window: per-slot start offsets, T small (K+1)
        p = jnp.broadcast_to(jnp.asarray(pos, jnp.int32).reshape(-1), (B,))
        W = block_tables.shape[1]
        for i in range(T):
            pi = p + i
            q = pi // bs
            blk = jnp.take_along_axis(
                block_tables, jnp.minimum(q, W - 1)[:, None], axis=1)[:, 0]
            blk = jnp.where(q < W, blk, NULL_BLOCK)
            pool = pool.at[blk, pi % bs].set(new[:, i], mode="drop")
        return pool
    assert B == 1, "paged prefill writes one sequence at a time"
    assert T % bs == 0, f"prefill length {T} not a multiple of block {bs}"
    p0 = jnp.asarray(pos, jnp.int32).reshape(())
    nblk = T // bs
    blks = jax.lax.dynamic_slice(block_tables[0], (p0 // bs,), (nblk,))
    vals = new[0].reshape((nblk, bs) + new.shape[2:])
    return pool.at[blks].set(vals, mode="drop")


def paged_gather(pool: jnp.ndarray, block_tables: jnp.ndarray) -> jnp.ndarray:
    """Materialize the logical (B, max_blocks*bs, ...) view of each
    sequence's cache for the naive/einsum attention paths. Rows past a
    sequence's extent map through null/stale blocks and carry garbage —
    exactly like the slot cache's retired rows, they are causally masked
    to weight 0.0 before they can touch the output."""
    B, n_max = block_tables.shape
    g = pool[block_tables]                      # (B, n_max, bs, ...)
    return g.reshape((B, n_max * pool.shape[1]) + pool.shape[2:])


# ---------------------------------------------------------------------------
# host-side allocator + prefix index
# ---------------------------------------------------------------------------

#: ancestry digest of the empty prefix (the radix root)
ROOT_DIGEST = b"\x00" * 16


def _child_digest(parent: bytes, block: tuple) -> bytes:
    h = hashlib.blake2b(parent, digest_size=16)
    # host-side chain-key hashing over concrete python ints — never traced
    h.update(np.asarray(block, np.int64).tobytes())  # lint: allow(host-sync)
    return h.digest()


def chain_keys(tokens, block_size: int, n_blocks: int,
               parent=ROOT_DIGEST) -> list:
    """Chain keys for the first `n_blocks` FULL blocks of `tokens`:
    key_i = (digest_{i-1}, tokens of block i), where digest_i folds
    block i into its parent's digest. The digest stands in for the whole
    ancestry, so a key encodes the prefix up to and including its block
    (equal keys imply equal content at equal positions, up to blake2b
    collisions) while hashing in O(block_size) — the naive nested-tuple
    key made one admission's lookup+register pass O(n^2 * block_size)
    host-side for an n-block prompt."""
    keys = []
    for i in range(n_blocks):
        block = tuple(int(t) for t in tokens[i * block_size:(i + 1) * block_size])
        keys.append((parent, block))
        parent = _child_digest(parent, block)
    return keys


class BlockPool:
    """Refcounted block allocator with an LRU prefix cache.

    Block states (disjoint):
    * free        — on the free list, content garbage;
    * referenced  — refcount >= 1 live sequences own it (possibly shared);
    * cached      — refcount 0 but registered in the prefix index: content
                    retained, evictable LRU-first when the free list runs
                    dry.

    The null block (id 0) is reserved and never enters any state.
    """

    def __init__(self, n_blocks: int, block_size: int):
        assert n_blocks >= 2, "pool needs the null block plus one real one"
        self.n_blocks = n_blocks
        self.block_size = block_size
        self._free: collections.deque[int] = collections.deque(
            range(1, n_blocks))
        self._ref: dict[int, int] = {}           # block -> refcount (>= 1)
        self._key_of: dict[int, tuple] = {}      # registered block -> key
        self._index: dict[tuple, int] = {}       # chain key -> block
        self._lru: collections.OrderedDict[int, None] = \
            collections.OrderedDict()            # cached blocks, oldest first
        # eviction hook: called as on_evict(blk, key) the moment a cached
        # block is about to be recycled, BEFORE its contents are
        # overwritten — ops/kv_tier.py demotes the block to host RAM
        # here. None (default) keeps plain drop-at-eviction semantics.
        self.on_evict = None
        # lifetime counters (engine metrics read these)
        self.n_evicted = 0
        self.n_allocs = 0

    # -- capacity accounting -------------------------------------------
    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_cached(self) -> int:
        return len(self._lru)

    @property
    def n_referenced(self) -> int:
        return len(self._ref)

    @property
    def capacity(self) -> int:
        """Allocatable blocks (the null block is not one)."""
        return self.n_blocks - 1

    @property
    def utilization(self) -> float:
        """Referenced fraction of the pool (cached blocks are reclaimable,
        so they don't count as used)."""
        return self.n_referenced / self.capacity if self.capacity else 0.0

    # -- alloc / free ---------------------------------------------------
    def alloc(self) -> Optional[int]:
        """A fresh private block (refcount 1), evicting the LRU cached
        block when the free list is empty. None when every block is
        referenced — the caller preempts or stays queued."""
        if self._free:
            blk = self._free.popleft()
        elif self._lru:
            blk, _ = self._lru.popitem(last=False)   # oldest cached
            key = self._key_of.pop(blk)
            self._index.pop(key, None)
            self.n_evicted += 1
            if self.on_evict is not None:
                # second-tier demotion: the block is refcount-0 and its
                # contents still intact — the hook copies them out before
                # this alloc's owner overwrites the rows
                self.on_evict(blk, key)
        else:
            return None
        self._ref[blk] = 1
        self.n_allocs += 1
        return blk

    def alloc_many(self, n: int) -> Optional[list[int]]:
        """n fresh blocks or None (all-or-nothing: a partial admission
        would leak refs)."""
        got: list[int] = []
        for _ in range(n):
            blk = self.alloc()
            if blk is None:
                for b in got:
                    self.release(b)
                return None
            got.append(blk)
        return got

    def ref(self, blk: int) -> None:
        """Take a reference on a cached or already-referenced block (a
        prefix hit sharing it with a new sequence)."""
        if blk in self._ref:
            self._ref[blk] += 1
            return
        assert blk in self._lru, f"block {blk} is neither live nor cached"
        del self._lru[blk]
        self._ref[blk] = 1

    def release(self, blk: int) -> None:
        """Drop one reference. At refcount 0 a registered block is
        retained on the LRU (prefix cache); an unregistered one goes back
        to the free list."""
        n = self._ref[blk] - 1
        if n:
            self._ref[blk] = n
            return
        del self._ref[blk]
        if blk in self._key_of:
            self._lru[blk] = None                # most-recently released
        else:
            self._free.append(blk)

    def release_all(self, blocks: Iterable[int]) -> None:
        """Release a sequence's blocks tail-first, so when eviction comes
        the deepest (least shareable) blocks go before their ancestors —
        the chain walk needs ancestors to reach descendants at all."""
        for blk in reversed(list(blocks)):
            self.release(blk)

    # -- prefix index ---------------------------------------------------
    def lookup(self, key: tuple) -> Optional[int]:
        """Block holding this chain key's content, or None. Touches the
        LRU so a hit streak keeps a hot prefix resident."""
        blk = self._index.get(key)
        if blk is not None and blk in self._lru:
            self._lru.move_to_end(blk)
        return blk

    def register(self, blk: int, key: tuple) -> None:
        """Publish a full, immutable, referenced block under its chain
        key. First writer wins: a concurrent identical prefill keeps its
        private copy unregistered (it frees normally on release)."""
        if key in self._index or blk in self._key_of:
            return
        assert blk in self._ref, "only referenced blocks can be registered"
        self._index[key] = blk
        self._key_of[blk] = key
