"""Hot-path numerical ops: RoPE, fused-attention dispatch (XLA / Pallas /
ring), and MoE token dispatch. These are the TPU-native stand-ins for the
reference's delegated CUDA kernels (F.scaled_dot_product_attention, fused
AdamW, NCCL collectives — see SURVEY.md §2 native-code note)."""

from distributed_pytorch_tpu.ops.rope import (  # noqa: F401
    precompute_rope_freqs,
    apply_rotary_emb,
)
from distributed_pytorch_tpu.ops.attention_core import sdpa  # noqa: F401
