"""Hot-path numerical ops: RoPE, fused-attention dispatch (XLA / Pallas /
ring), and MoE token dispatch. These are the TPU-native stand-ins for the
reference's delegated CUDA kernels (F.scaled_dot_product_attention, fused
AdamW, NCCL collectives — see SURVEY.md §2 native-code note)."""

from distributed_pytorch_tpu.ops.rope import (  # noqa: F401
    precompute_rope_freqs,
    apply_rotary_emb,
)
from distributed_pytorch_tpu.ops.attention_core import sdpa  # noqa: F401
from distributed_pytorch_tpu.ops.losses import (  # noqa: F401
    fused_cross_entropy,
    unchunked_cross_entropy,
)
# NB: the `flash_attention` FUNCTION is deliberately not re-exported here —
# binding it on the package would shadow the `ops.flash_attention`
# submodule attribute (import it from the submodule directly).
from distributed_pytorch_tpu.ops.flash_attention import (  # noqa: F401
    flash_attention_lse,
    flash_attention_usable,
)
from distributed_pytorch_tpu.ops.ring_attention import sp_sdpa  # noqa: F401
