"""Pallas TPU fused cross-entropy: the lm-head matmul and the softmax/CE
reduction in one streaming kernel — logits NEVER exist in HBM.

Why: the chunked-CE scan (ops/losses.py) still materializes each
(B, chunk, V) fp32 logits block in HBM and re-reads it for logsumexp /
target-gather / backward; on the v5e profile that bucket is ~77 ms/step of
the 264 ms flagship step (PERF.md round 4) vs a ~25 ms FLOPs floor. This
kernel streams (token_block, vocab_block) tiles through VMEM with an
online logsumexp, so HBM traffic is just x, W and the per-token outputs —
the softmax never round-trips.

Structure (FlashAttention-2 applied to the vocab axis; reference CE is
`F.cross_entropy` over full logits, single-gpu/model.py:687-692):

* forward — grid (n_token_blocks, n_vocab_blocks), vocab innermost: one
  (bn, C) x tile is resident while (bv, C) W tiles stream; VMEM scratch
  holds running max m, normalizer l, and the target logit; the last vocab
  step emits per-token nll = lse - logit[target] and lse.
* backward dx — same grid: recomputes the score tile from the saved lse,
  p = exp(s - lse), dlogits = (p - onehot(target)) * d_nll, accumulates
  dx += dlogits @ W_tile in VMEM scratch.
* backward dW — transposed grid (n_vocab_blocks, n_token_blocks): one W
  tile resident, x tiles stream, accumulates dW_tile += dlogits^T @ x.

The vocab is zero-padded (host-side, ~1 MB copy) to a multiple of the
vocab block so no tile ever reads out of bounds; padded columns are masked
to -1e30 before the max. All accumulation is f32; matmul operands stay in
the input dtype (bf16 on TPU) so the MXU runs at full rate.

Sharding: tokens are independent, so under a live mesh the wrapper runs
the kernel inside shard_map over the 'data' axis (W replicated in-spec;
shard_map's transpose psums the W cotangent across shards). Vocab-parallel
lm_head (tp) and sequence-parallel T are NOT supported — callers gate on
model==1 and seq==1 (gpt.py does) and fall back to the chunked path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from distributed_pytorch_tpu import config
from distributed_pytorch_tpu.compat import tpu_compiler_params

DEFAULT_BLOCK_N = config.knob("CE_BLOCK_N")     # tokens
DEFAULT_BLOCK_V = config.knob("CE_BLOCK_V")     # vocab

_NEG_INF = -1e30

_SEMANTICS = tpu_compiler_params(
    dimension_semantics=("parallel", "arbitrary"))


def _dot(a, b, trans_b=False):
    dims = (((1,), (1 if trans_b else 0,)), ((), ()))
    return jax.lax.dot_general(a, b, dims,
                               preferred_element_type=jnp.float32)


def _dot_t(a, b):
    """a^T @ b with f32 accumulation."""
    return jax.lax.dot_general(a, b, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _score_tile(x, w, j, bv, vocab_size):
    """(bn, bv) f32 logits tile with padded columns masked to -1e30.
    Returns (s, col) where col is the global vocab index per column."""
    s = _dot(x, w, trans_b=True)                          # (bn, bv) f32
    col = j * bv + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(col < vocab_size, s, _NEG_INF)
    return s, col


# ---------------------------------------------------------------------------
# forward: per-token nll + lse
# ---------------------------------------------------------------------------

def _fwd_kernel(x_ref, w_ref, t_ref, nll_ref, lse_ref, m_ref, l_ref, tgt_ref,
                *, bv, vocab_size):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        tgt_ref[:] = jnp.zeros_like(tgt_ref)

    s, col = _score_tile(x_ref[:], w_ref[:], j, bv, vocab_size)
    m_prev, l_prev = m_ref[:], l_ref[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    l_ref[:] = l_prev * jnp.exp(m_prev - m_new) \
        + jnp.sum(jnp.exp(s - m_new), axis=-1, keepdims=True)
    m_ref[:] = m_new
    # target logit: exactly one vocab tile contains column t per row
    t = t_ref[:]                                          # (bn, 1) int32
    tgt_ref[:] = tgt_ref[:] + jnp.sum(
        jnp.where(col == t, s, 0.0), axis=-1, keepdims=True)

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        lse = m_ref[:] + jnp.log(jnp.maximum(l_ref[:], 1e-30))
        lse_ref[:] = lse
        nll_ref[:] = lse - tgt_ref[:]


def _fwd(x, w_pad, t, bn, bv, vocab_size, interpret):
    n, c = x.shape
    v_pad = w_pad.shape[0]
    nll, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, bv=bv, vocab_size=vocab_size),
        grid=(n // bn, v_pad // bv),
        in_specs=[
            pl.BlockSpec((bn, c), lambda i, j: (i, 0)),
            pl.BlockSpec((bv, c), lambda i, j: (j, 0)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bn, 1), jnp.float32),
            pltpu.VMEM((bn, 1), jnp.float32),
            pltpu.VMEM((bn, 1), jnp.float32),
        ],
        compiler_params=_SEMANTICS,
        interpret=interpret,
    )(x, w_pad, t)
    return nll, lse


# ---------------------------------------------------------------------------
# backward: dx (token-major) and dW (vocab-major), both recompute p from lse
# ---------------------------------------------------------------------------

def _dlogits(x, w, t, lse, coef, j, bv, vocab_size):
    """(bn, bv) dlogits tile: (p - onehot(target)) * coef, padded cols 0."""
    s, col = _score_tile(x, w, j, bv, vocab_size)
    p = jnp.exp(s - lse)                    # padded cols: exp(-1e30-lse)=0
    return (p - jnp.where(col == t, 1.0, 0.0)) * coef


def _bwd_dx_kernel(x_ref, w_ref, t_ref, lse_ref, coef_ref, dx_ref, dx_acc,
                   *, bv, vocab_size):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        dx_acc[:] = jnp.zeros_like(dx_acc)

    w = w_ref[:]
    dl = _dlogits(x_ref[:], w, t_ref[:], lse_ref[:], coef_ref[:], j, bv,
                  vocab_size)
    dx_acc[:] = dx_acc[:] + _dot(dl.astype(w.dtype), w)

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        dx_ref[:] = dx_acc[:].astype(dx_ref.dtype)


def _bwd_dw_kernel(x_ref, w_ref, t_ref, lse_ref, coef_ref, dw_ref, dw_acc,
                   *, bv, vocab_size):
    i = pl.program_id(1)
    j = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        dw_acc[:] = jnp.zeros_like(dw_acc)

    x = x_ref[:]
    dl = _dlogits(x, w_ref[:], t_ref[:], lse_ref[:], coef_ref[:], j, bv,
                  vocab_size)
    dw_acc[:] = dw_acc[:] + _dot_t(dl.astype(x.dtype), x)

    @pl.when(i == pl.num_programs(1) - 1)
    def _():
        dw_ref[:] = dw_acc[:].astype(dw_ref.dtype)


def _bwd(x, w_pad, t, lse, coef, bn, bv, vocab_size, interpret):
    n, c = x.shape
    v_pad = w_pad.shape[0]
    dx = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, bv=bv, vocab_size=vocab_size),
        grid=(n // bn, v_pad // bv),
        in_specs=[
            pl.BlockSpec((bn, c), lambda i, j: (i, 0)),
            pl.BlockSpec((bv, c), lambda i, j: (j, 0)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bn, c), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, c), x.dtype),
        scratch_shapes=[pltpu.VMEM((bn, c), jnp.float32)],
        compiler_params=_SEMANTICS,
        interpret=interpret,
    )(x, w_pad, t, lse, coef)

    dw = pl.pallas_call(
        functools.partial(_bwd_dw_kernel, bv=bv, vocab_size=vocab_size),
        grid=(v_pad // bv, n // bn),
        in_specs=[
            pl.BlockSpec((bn, c), lambda j, i: (i, 0)),
            pl.BlockSpec((bv, c), lambda j, i: (j, 0)),
            pl.BlockSpec((bn, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda j, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bv, c), lambda j, i: (j, 0)),
        out_shape=jax.ShapeDtypeStruct((v_pad, c), w_pad.dtype),
        scratch_shapes=[pltpu.VMEM((bv, c), jnp.float32)],
        compiler_params=_SEMANTICS,
        interpret=interpret,
    )(x, w_pad, t, lse, coef)
    return dx, dw


# ---------------------------------------------------------------------------
# custom VJP over per-token nll
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ce_nll(x, w, t, bn, bv, vocab_size, interpret):
    """Per-token nll (n, 1) f32. x (n, C); w (V, C); t (n, 1) int32.
    Rows whose target lies outside [0, V) get nll = lse (their target
    logit contribution is 0) — callers mask ignored rows OUTSIDE, which
    also zeroes their cotangent so the backward ignores them."""
    w_pad = _pad_vocab(w, bv)
    nll, _ = _fwd(x, w_pad, t, bn, bv, vocab_size, interpret)
    return nll


def _ce_nll_fwd(x, w, t, bn, bv, vocab_size, interpret):
    w_pad = _pad_vocab(w, bv)
    nll, lse = _fwd(x, w_pad, t, bn, bv, vocab_size, interpret)
    return nll, (x, w, t, lse)


def _ce_nll_bwd(bn, bv, vocab_size, interpret, res, d_nll):
    x, w, t, lse = res
    w_pad = _pad_vocab(w, bv)
    coef = d_nll.astype(jnp.float32)                     # (n, 1)
    dx, dw_pad = _bwd(x, w_pad, t, lse, coef, bn, bv, vocab_size, interpret)
    return dx, dw_pad[: w.shape[0]], None


_ce_nll.defvjp(_ce_nll_fwd, _ce_nll_bwd)


def _pad_vocab(w, bv):
    v = w.shape[0]
    v_pad = -(-v // bv) * bv
    if v_pad == v:
        return w
    return jnp.pad(w, ((0, v_pad - v), (0, 0)))


def _pick(n: int, preferred: int) -> int:
    """Largest divisor of n that is <= preferred and a multiple of 8;
    0 when no such divisor exists (incl. n == 0, e.g. an eval batch
    smaller than the data-axis size leaving zero local tokens)."""
    if n < 8:
        return 0
    b = min(preferred, n)
    while b > 8 and n % b != 0:
        b -= 8
    return b if (n % b == 0 and b % 8 == 0) else 0


def pallas_ce_usable(n_tokens: int, n_embd: int, dtype) -> bool:
    """Static gate: shapes/dtypes the kernel handles."""
    if dtype not in (jnp.float32, jnp.bfloat16):
        return False
    if n_embd % 128 != 0:          # lane-dim multiple (C is the minor dim)
        return False
    return bool(_pick(n_tokens, DEFAULT_BLOCK_N))


def pallas_cross_entropy(x: jnp.ndarray, embedding: jnp.ndarray,
                         targets: jnp.ndarray, *, ignore_index: int = -1,
                         interpret: bool = False) -> jnp.ndarray:
    """Mean CE over valid targets; drop-in for fused_cross_entropy
    (ops/losses.py) with the same (B, T, C)/(V, C)/(B, T) signature.

    Under a live multi-device mesh the kernel runs inside shard_map over
    the 'data' axis (tokens are independent; W rides in replicated and its
    cotangent is psum'd by the shard_map transpose). Gate with
    `pallas_ce_usable` and seq==1/model==1 before calling.
    """
    B, T, C = x.shape
    mask = targets != ignore_index
    safe_t = jnp.where(mask, targets, -2)   # never matches a vocab column

    def local_nll(x_loc, w, t_loc):
        n = x_loc.shape[0] * x_loc.shape[1]
        bn = _pick(n, DEFAULT_BLOCK_N)
        assert bn, (
            f"pallas_cross_entropy: local token count {n} has no tile "
            f"divisor (multiple of 8, <= {DEFAULT_BLOCK_N}) — gate with "
            "pallas_ce_usable() and fall back to fused_cross_entropy")
        # vocab tiles need no divisor — the vocab is padded to a bv
        # multiple and padded columns are masked; bv just needs the
        # sublane multiple-of-8
        v = embedding.shape[0]
        bv = min(DEFAULT_BLOCK_V, -(-v // 8) * 8)
        nll = _ce_nll(x_loc.reshape(n, C), w,
                      t_loc.reshape(n, 1).astype(jnp.int32),
                      bn, bv, v, interpret)
        return nll.reshape(x_loc.shape[0], x_loc.shape[1])

    from distributed_pytorch_tpu.parallel import context
    mesh = context.get_mesh()
    if mesh is not None and mesh.shape.get("data", 1) > 1 \
            and not context.in_sp_region():
        from distributed_pytorch_tpu import compat
        nll = compat.shard_map(
            lambda xs, w, ts: local_nll(xs, w, ts),
            mesh=mesh,
            in_specs=(P("data"), P(), P("data")),
            out_specs=P("data"),
        )(x, embedding, safe_t)
    else:
        nll = local_nll(x, embedding, safe_t)

    denom = jnp.maximum(mask.sum(), 1)
    return jnp.where(mask, nll, 0.0).sum() / denom
