"""Pallas TPU flash-attention kernel (blockwise online softmax in VMEM).

Stub for now: `flash_attention_usable` returns False so the dispatcher in
ops/attention_core.py falls through to the XLA fused path. The real kernel
lands with the Pallas milestone; the interface is fixed here so callers
don't change.
"""

from __future__ import annotations

import jax.numpy as jnp


def flash_attention_usable(q, k, v, *, causal: bool = True) -> bool:
    return False


def flash_attention(q, k, v, *, scale: float, causal: bool = True,
                    q_offset=0) -> jnp.ndarray:
    raise NotImplementedError("Pallas flash attention not yet implemented")
