"""Pallas TPU flash attention: blockwise online-softmax in VMEM, with a
hand-written FlashAttention-2-style backward (custom VJP).

This is the framework's native-kernel replacement for the fused attention
the reference delegates to `F.scaled_dot_product_attention` (reference
single-gpu/model.py:149). Design (per the Pallas TPU playbook):

* The (batch, head) pair is flattened into one ROW axis and the grid is
  (rows/block_h, q_blocks, kv_blocks), `dimension_semantics=('parallel',
  'parallel', 'arbitrary')`. Each grid step processes `block_h` rows'
  (block_q x block_k) score tiles batched through the MXU, streaming ONE
  (block_k, D) K/V tile per row; the online-softmax state (running max m,
  normalizer l, f32 accumulator) lives in VMEM scratch that persists
  across the innermost kv dimension. VMEM use is constant in sequence
  length — attention probabilities never exist in HBM, so memory is O(T)
  instead of O(T^2) and sequences of 32k+ compile.
* Why a row-group block: at the flagship shape (B16 H12 T1024 D64) with
  128x128 tiles the grid is ~12k steps/layer of ~2 MFLOP each and
  per-grid-step overhead dominates the kernel (v5e micro-bench, PERF.md
  round 4 — 128x128 lost ~50 ms/call to 256x512 from grid-step count
  alone). Grouping `block_h` rows per step divides the step count again
  without changing total VPU/MXU work.
* Causal masking is positional (qpos >= kpos), so the KV length S may
  exceed the query length T (prefill into a longer zero-filled cache
  buffer): the zero tail is always masked. Blocks strictly above the
  causal frontier are skipped: compute is predicated with `pl.when` and
  their index maps clamp to the last visible block so no fresh DMA is
  issued for skipped tiles.
* Backward = two kernels (FlashAttention-2): dq accumulates over kv tiles;
  dk/dv accumulate over q tiles; both recompute p from the saved
  logsumexp instead of storing probabilities.
* GQA never materializes repeated K/V: with `rep = nh // nkv > 1` the
  row group is 1 and the kv BlockSpec index maps send query row r to kv
  row r // rep, so the same kv tile serves the whole group straight from
  HBM (a materialized repeat would multiply KV bytes by the group size at
  exactly the long-S scales this kernel targets). The backward emits
  per-query-row dk/dv and group-sums them host-side. Head dims must be
  sublane multiples (hs % 8); there is no padding path — odd head dims
  fall back to the XLA impl via `flash_attention_usable`.

The public entry points keep the interface the dispatcher
(ops/attention_core.py) fixed while this was a stub: `flash_attention` and
`flash_attention_usable`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributed_pytorch_tpu import config

# Tile-size knobs (read at import so scripts/mfu_sweep.py --variants blocks
# can A/B them per subprocess without an API change). 256x512 q/kv tiles and
# an 8-row group are the provisional v5e winners pending the on-hardware
# block sweep (PERF.md round 4).
DEFAULT_BLOCK_Q = config.knob("FLASH_BLOCK_Q")
DEFAULT_BLOCK_K = config.knob("FLASH_BLOCK_K")
DEFAULT_BLOCK_H = config.knob("FLASH_BLOCK_H")

# Kernel layout (round 5): 'rows' flattens (B, H) into grid rows and needs
# a BTNH -> (B*H, T, D) HBM transpose per operand per call — the profile's
# 44 ms/step "layout copies" bucket (PERF.md r4). 'slab' reads the model's
# natural (B, T, N*H) slabs directly (contiguous DMA, zero HBM transposes)
# and relayouts head-major in VMEM; it also handles GQA in-kernel (no
# materialized K/V repeat in HBM, group-sum of dk/dv at the write step).
# Default stays 'rows' — the only layout that has compiled on real TPU
# hardware so far — until the on-hardware sweep (mfu_sweep --variants
# blocks, FLASH_LAYOUT legs) proves the slab path.
DEFAULT_LAYOUT = config.knob("FLASH_LAYOUT")

_NEG_INF = -1e30  # large-negative instead of -inf: keeps masked rows NaN-free

from distributed_pytorch_tpu.compat import tpu_compiler_params, vma_of

_SEMANTICS = tpu_compiler_params(
    dimension_semantics=("parallel", "parallel", "arbitrary"))


def _last_visible_kv(i, block_q: int, block_k: int):
    """Index of the last kv block the q tile `i` attends into (causal)."""
    return jax.lax.div(i * block_q + block_q - 1, block_k)


def _first_visible_q(j, block_q: int, block_k: int):
    """Index of the first q block that attends into kv tile `j` (causal)."""
    return jax.lax.div(j * block_k, block_q)


def _mask_scores(s, i, j, block_q, block_k):
    """Causal mask for one (g, block_q, block_k) score tile. Positions are
    absolute: qpos = i*block_q + row, kpos = j*block_k + col; a query
    attends keys with kpos <= qpos (reference model.py:225-226 triu
    semantics with offset 0)."""
    qpos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    kpos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
    return jnp.where(qpos >= kpos, s, _NEG_INF)


def _bdot(a, b, trans_b=False):
    """Row-batched matmul with f32 accumulation: a (g, m, k) @ b (g, k, n)
    — or b (g, n, k) when trans_b — over the shared leading group dim."""
    dims = (((2,), (2 if trans_b else 1,)), ((0,), (0,)))
    return jax.lax.dot_general(a, b, dims,
                               preferred_element_type=jnp.float32)


def _bdot_t(a, b):
    """Row-batched a^T @ b: a (g, m, n), b (g, m, k) -> (g, n, k)."""
    return jax.lax.dot_general(a, b, (((1,), (1,)), ((0,), (0,))),
                               preferred_element_type=jnp.float32)


def _mix_bits(seed0, seed1, row, qp, kp):
    """Counter-based uint32 hash (murmur3-finalizer style) over already-
    broadcast (attention row, query position, key position) uint32 arrays
    plus the caller seed. Pure jnp int ops: runs identically in the
    compiled kernel (VPU), in interpret mode (pltpu.prng_* has no CPU
    lowering), in the ring-attention einsum hops, and in plain host code
    (tests replay the exact mask for an oracle comparison)."""
    u32 = lambda a: jnp.asarray(a).astype(jnp.uint32)  # noqa: E731
    x = u32(row) * jnp.uint32(0x9E3779B1)
    x = x ^ (u32(qp) * jnp.uint32(0x85EBCA6B))
    x = x ^ (u32(kp) * jnp.uint32(0xC2B2AE35))
    x = x ^ u32(seed0)
    x = x + u32(seed1) * jnp.uint32(0x27D4EB2F)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def dropout_threshold(rate: float) -> jnp.ndarray:
    """uint32 threshold with P(bits < t) = rate."""
    return jnp.uint32(min(int(rate * 2.0 ** 32), 2 ** 32 - 1))


def fold_seed_for_data_shard(seed, didx):
    """Decorrelate a (2,) int32 dropout seed across 'data' shards (each
    shard holds different samples at the same shard-local batch rows). ONE
    definition shared by the sp ring hops (ops/ring_attention.py) and the
    test-side host replay, so the fold can't drift between them."""
    return seed ^ (jnp.asarray(didx).astype(jnp.int32)
                   * jnp.int32(0x9E3779B9 - 2 ** 32))


def _dropout_bits(seed0, seed1, row0, q0, k0, shape):
    """_mix_bits keyed on the ABSOLUTE coordinates of every element of a
    (rows, q, k) tile starting at (row0, q0, k0). Absolute-position keying
    makes the mask independent of block sizes and of which kernel's grid
    order regenerates it."""
    u32 = lambda a: jnp.asarray(a).astype(jnp.uint32)  # noqa: E731
    row = u32(row0) + jax.lax.broadcasted_iota(jnp.uint32, shape, 0)
    qp = u32(q0) + jax.lax.broadcasted_iota(jnp.uint32, shape, 1)
    kp = u32(k0) + jax.lax.broadcasted_iota(jnp.uint32, shape, 2)
    return _mix_bits(seed0, seed1, row, qp, kp)


def _dropout_mask(seed_ref, r, i, j, shape, block_q: int, block_k: int,
                  rate: float):
    """Scaled keep-mask for one (g, block_q, block_k) score tile,
    regenerated bit-identically in forward and both backward kernels.
    P(drop) = rate via a uint32 threshold; survivors are pre-scaled by
    1/(1-rate) (inverted dropout, the reference's
    F.scaled_dot_product_attention semantics)."""
    g = shape[0]
    bits = _dropout_bits(seed_ref[0], seed_ref[1], r * g, i * block_q,
                         j * block_k, shape)
    return ((bits >= dropout_threshold(rate)).astype(jnp.float32)
            / (1.0 - rate))


def _sds(shape, dtype, like):
    """ShapeDtypeStruct carrying `like`'s varying-manual-axes set: pallas
    calls inside shard_map (the ring-attention hop path) must declare how
    their outputs vary across mesh axes."""
    vma = vma_of(like)
    if vma is None:  # jax without vma tracking
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


def _kv_spec(rep: int, g: int, block_q: int, block_k: int, D: int,
             causal: bool):
    """Shared K/V BlockSpec for the forward and dq grids (both iterate
    (row-group r, q-tile i, kv-tile j)): GQA (g == 1) maps query row r to
    kv row r // rep — no materialized repeat — and skipped upper-triangle
    tiles clamp to the causal frontier so the revolving-buffer DMA sees an
    unchanged index (no fetch). One definition keeps forward and backward
    kv fetches in lockstep."""
    def kv_idx(r, i, j):
        jc = j if not causal \
            else jnp.minimum(j, _last_visible_kv(i, block_q, block_k))
        return (r if rep == 1 else r // rep, jc, 0)

    return pl.BlockSpec((g if rep == 1 else 1, block_k, D), kv_idx)


# VMEM budget for one grid step's tiles + scratch + f32 score intermediates.
# v5e has ~128 MiB VMEM/core; leave half for Mosaic's own buffers and
# double-buffering slack so an oversized block/group config degrades (smaller
# row group, or XLA fallback via the usable gate) instead of hard-failing
# compilation with a Mosaic VMEM-exceeded error (round-4 ADVICE).
_VMEM_BUDGET = config.knob("FLASH_VMEM_BUDGET_MB") * 2 ** 20


def _vmem_bytes(g: int, gk: int, bq: int, bk: int, D: int,
                dsize: int) -> int:
    """Worst-case-kernel (dkv backward) VMEM estimate for one grid step:
    double-buffered I/O tiles + f32 accumulator scratch + the f32 score/
    prob/dscore intermediates the kernel body materializes."""
    score = 3 * g * bq * bk * 4
    fwd = (2 * (2 * g * bq * D + 2 * gk * bk * D) * dsize
           + (g * bq * D + 2 * g * bq) * 4 + score)
    bwd = (2 * (2 * g * bq * D + 2 * gk * bk * D + 2 * g * bk * D) * dsize
           + 2 * g * bk * D * 4 + 4 * g * bq * 4 + score)
    return max(fwd, bwd)


def _pick_group(n_rows: int, rep: int, preferred: int,
                block_q: int = 0, block_k: int = 0, D: int = 0,
                dsize: int = 2) -> int:
    """Row-group size: a divisor of n_rows, 1 unless kv rows map 1:1
    (rep == 1 — with grouped rows a GQA group would need strided kv
    tiles). When block sizes are known, the group shrinks until the
    per-step VMEM estimate fits the budget."""
    if rep != 1:
        return 1
    g = min(preferred, n_rows)
    while g > 1 and n_rows % g != 0:
        g -= 1
    g = max(g, 1)
    if block_q and block_k and D:
        req = g
        while g > 1 and _vmem_bytes(g, g, block_q, block_k, D,
                                    dsize) > _VMEM_BUDGET:
            g -= 1
            while g > 1 and n_rows % g != 0:
                g -= 1
        if g != req and (req, g, block_q, block_k) not in _SHRINK_WARNED:
            # once per unique config: this runs at TRACE time, and repeated
            # jit traces / vmap would otherwise spam a bare stderr print
            # for every retrace (round-5 ADVICE)
            _SHRINK_WARNED.add((req, g, block_q, block_k))
            import warnings
            warnings.warn(
                f"[flash] row group shrunk {req} -> {g} to fit the "
                f"{_VMEM_BUDGET >> 20} MiB VMEM budget at blocks "
                f"({block_q}, {block_k})", RuntimeWarning, stacklevel=2)
    return max(g, 1)


_SHRINK_WARNED: set = set()


# ---------------------------------------------------------------------------
# shared tile math (ONE copy of the FlashAttention-2 numerics — the rows
# and slab kernel faces differ only in how tiles are loaded/stored)
# ---------------------------------------------------------------------------

def _fwd_tile(q, k, v, r, i, j, seed_ref, m_ref, l_ref, acc_ref, *, scale,
              block_q, block_k, causal, rate):
    """Online-softmax update for one (g, bq, D)x(g, bk, D) tile pair.
    Operands stay in input dtype (bf16 on TPU): the MXU accumulates in f32
    via preferred_element_type — casting inputs up would force slow fp32
    MXU passes."""
    s = _bdot(q, k, trans_b=True) * scale               # (g, bq, bk) f32
    if causal:
        s = _mask_scores(s, i, j, block_q, block_k)
    m_prev, l_prev = m_ref[:], l_ref[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)
    m_ref[:] = m_new
    # normalizer accumulates the UNdropped p (torch drops the
    # already-normalized attention weights); only the value accumulation
    # sees the mask
    l_ref[:] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    if rate > 0.0:
        p = p * _dropout_mask(seed_ref, r, i, j, p.shape, block_q,
                              block_k, rate)
    acc_ref[:] = acc_ref[:] * alpha + _bdot(p.astype(v.dtype), v)


def _fwd_finalize(m_ref, l_ref, acc_ref):
    """(normalized out (g, bq, D) f32, lse (g, bq, 1) f32)."""
    l_safe = jnp.maximum(l_ref[:], 1e-30)
    return acc_ref[:] / l_safe, m_ref[:] + jnp.log(l_safe)


def _dq_tile(q, k, v, do, lse, delta, r, i, j, seed_ref, dq_acc, *, scale,
             block_q, block_k, causal, rate):
    """dq accumulation for one tile: ds = p * (M/(1-r) * (dO V^T) - delta);
    rowsum(dP*P) still equals rowsum(dO*O) = delta because O was computed
    with the SAME mask."""
    s = _bdot(q, k, trans_b=True) * scale
    if causal:
        s = _mask_scores(s, i, j, block_q, block_k)
    p = jnp.exp(s - lse)                                # (g, bq, bk) f32
    dp = _bdot(do, v, trans_b=True)
    if rate > 0.0:
        dp = dp * _dropout_mask(seed_ref, r, i, j, dp.shape, block_q,
                                block_k, rate)
    ds = p * (dp - delta)
    dq_acc[:] = dq_acc[:] + _bdot(ds.astype(k.dtype), k)


def _dkv_tile(q, k, v, do, lse, delta, r, i, j, seed_ref, dk_acc, dv_acc,
              *, scale, block_q, block_k, causal, rate):
    """dk/dv accumulation for one tile; the dropout mask is regenerated
    with the same canonical (r, i, j) coords as forward/dq, NOT this
    kernel's transposed grid order."""
    s = _bdot(q, k, trans_b=True) * scale               # (g, bq, bk) f32
    if causal:
        s = _mask_scores(s, i, j, block_q, block_k)
    p = jnp.exp(s - lse)
    if rate > 0.0:
        mask = _dropout_mask(seed_ref, r, i, j, p.shape, block_q, block_k,
                             rate)
        dv_acc[:] = dv_acc[:] + _bdot_t((p * mask).astype(do.dtype), do)
        dp = _bdot(do, v, trans_b=True) * mask
    else:
        dv_acc[:] = dv_acc[:] + _bdot_t(p.astype(do.dtype), do)
        dp = _bdot(do, v, trans_b=True)
    ds = p * (dp - delta)
    dk_acc[:] = dk_acc[:] + _bdot_t(ds.astype(q.dtype), q)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref,
                m_ref, l_ref, *, scale, block_q, block_k, causal, rate):
    r, i, j = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    last_j = _last_visible_kv(i, block_q, block_k) if causal \
        else pl.num_programs(2) - 1

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when(j <= last_j)
    def _():
        _fwd_tile(q_ref[:], k_ref[:], v_ref[:], r, i, j, seed_ref, m_ref,
                  l_ref, acc_ref, scale=scale, block_q=block_q,
                  block_k=block_k, causal=causal, rate=rate)

    @pl.when(j == pl.num_programs(2) - 1)
    def _():
        o, lse = _fwd_finalize(m_ref, l_ref, acc_ref)
        o_ref[:] = o.astype(o_ref.dtype)
        lse_ref[:] = lse


_SEED_SPEC = pl.BlockSpec(memory_space=pltpu.SMEM)


def _fwd(q, k, v, seed, scale, block_q, block_k, g, interpret, causal=True,
         rate=0.0):
    """q (N, T, D) rows = flattened (B, H); k/v (Nkv, S, D) with
    rep = N // Nkv -> out (N, T, D), lse (N, T, 1). `seed` (2,) int32
    feeds the in-kernel dropout PRNG (ignored at rate == 0)."""
    N, T, D = q.shape
    S, Nkv = k.shape[1], k.shape[0]
    rep = N // Nkv
    nq, nk = T // block_q, S // block_k

    kv_spec = _kv_spec(rep, g, block_q, block_k, D, causal)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, causal=causal, rate=rate),
        grid=(N // g, nq, nk),
        in_specs=[
            _SEED_SPEC,
            pl.BlockSpec((g, block_q, D), lambda r, i, j: (r, i, 0)),
            kv_spec,
            kv_spec,
        ],
        out_specs=[
            pl.BlockSpec((g, block_q, D), lambda r, i, j: (r, i, 0)),
            # trailing singleton lane dim: TPU blocks need the last two dims
            # (8,128)-divisible OR equal to the array dims; (bq, 1) with
            # array (..., T, 1) qualifies.
            pl.BlockSpec((g, block_q, 1), lambda r, i, j: (r, i, 0)),
        ],
        out_shape=[
            _sds((N, T, D), q.dtype, q),
            _sds((N, T, 1), jnp.float32, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((g, block_q, D), jnp.float32),
            pltpu.VMEM((g, block_q, 1), jnp.float32),
            pltpu.VMEM((g, block_q, 1), jnp.float32),
        ],
        compiler_params=_SEMANTICS,
        interpret=interpret,
    )(seed, q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward (FlashAttention-2: recompute p from lse; delta = rowsum(do * o))
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, dq_acc, *, scale, block_q, block_k,
                   causal, rate):
    r, i, j = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    last_j = _last_visible_kv(i, block_q, block_k) if causal \
        else pl.num_programs(2) - 1

    @pl.when(j == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @pl.when(j <= last_j)
    def _():
        _dq_tile(q_ref[:], k_ref[:], v_ref[:], do_ref[:], lse_ref[:],
                 delta_ref[:], r, i, j, seed_ref, dq_acc, scale=scale,
                 block_q=block_q, block_k=block_k, causal=causal, rate=rate)

    @pl.when(j == pl.num_programs(2) - 1)
    def _():
        dq_ref[:] = (dq_acc[:] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, dk_acc, dv_acc, *, scale,
                    block_q, block_k, causal, rate):
    r, j, i = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    first_i = _first_visible_q(j, block_q, block_k) if causal else 0

    @pl.when(i == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(i >= first_i)
    def _():
        _dkv_tile(q_ref[:], k_ref[:], v_ref[:], do_ref[:], lse_ref[:],
                  delta_ref[:], r, i, j, seed_ref, dk_acc, dv_acc,
                  scale=scale, block_q=block_q, block_k=block_k,
                  causal=causal, rate=rate)

    @pl.when(i == pl.num_programs(2) - 1)
    def _():
        dk_ref[:] = (dk_acc[:] * scale).astype(dk_ref.dtype)
        dv_ref[:] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_impl(scale, block_q, block_k, g, interpret, causal, rate, res, do,
              dlse=None):
    """Shared backward: dlse (N, T, 1) is the cotangent of the logsumexp
    output when the caller differentiates through it (the ring merge does;
    plain flash_attention passes None). Math: with L = sum(do*out) +
    sum(dlse*lse), ds = p * (dp - delta + dlse) — i.e. dlse just shifts
    the per-row delta term, since d lse/d s_j = p_j."""
    q, k, v, seed, out, lse = res
    N, T, D = q.shape
    S, Nkv = k.shape[1], k.shape[0]
    rep = N // Nkv
    nq, nk = T // block_q, S // block_k
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)                     # (N, T, 1)
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)

    kv_spec = _kv_spec(rep, g, block_q, block_k, D, causal)

    def q_row(r, i, j):
        return (r, i, 0)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, causal=causal, rate=rate),
        grid=(N // g, nq, nk),
        in_specs=[
            _SEED_SPEC,
            pl.BlockSpec((g, block_q, D), q_row),
            kv_spec,
            kv_spec,
            pl.BlockSpec((g, block_q, D), q_row),
            pl.BlockSpec((g, block_q, 1), q_row),
            pl.BlockSpec((g, block_q, 1), q_row),
        ],
        out_specs=pl.BlockSpec((g, block_q, D), q_row),
        out_shape=_sds((N, T, D), q.dtype, q),
        scratch_shapes=[pltpu.VMEM((g, block_q, D), jnp.float32)],
        compiler_params=_SEMANTICS,
        interpret=interpret,
    )(seed, q, k, v, do, lse, delta)

    def q_idx(r, j, i):
        # clamp sub-frontier q tiles (skipped compute) to an already-visible
        # index so no fresh DMA is issued
        ic = i if not causal \
            else jnp.maximum(i, _first_visible_q(j, block_q, block_k))
        return (r, ic, 0)

    # dkv grid is (row-group, kv-tile j, q-tile i): kv tiles are the
    # resident operand (indexed by j directly, no causal clamp needed)
    kv_block = (g if rep == 1 else 1, block_k, D)

    def kv_row(r, j, i):
        return (r if rep == 1 else r // rep, j, 0)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, causal=causal, rate=rate),
        grid=(N // g, nk, nq),
        in_specs=[
            _SEED_SPEC,
            pl.BlockSpec((g, block_q, D), q_idx),
            pl.BlockSpec(kv_block, kv_row),
            pl.BlockSpec(kv_block, kv_row),
            pl.BlockSpec((g, block_q, D), q_idx),
            pl.BlockSpec((g, block_q, 1), q_idx),
            pl.BlockSpec((g, block_q, 1), q_idx),
        ],
        out_specs=[
            # per-QUERY-row dk/dv tiles (kv tiles are shared across a GQA
            # group, so writes would collide at the kv row count);
            # group-summed below
            pl.BlockSpec((g, block_k, D), lambda r, j, i: (r, j, 0)),
            pl.BlockSpec((g, block_k, D), lambda r, j, i: (r, j, 0)),
        ],
        out_shape=[
            _sds((N, S, D), k.dtype, q),
            _sds((N, S, D), v.dtype, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((g, block_k, D), jnp.float32),
            pltpu.VMEM((g, block_k, D), jnp.float32),
        ],
        compiler_params=_SEMANTICS,
        interpret=interpret,
    )(seed, q, k, v, do, lse, delta)
    if rep > 1:
        # query rows r and r+1 ... sharing kv row r // rep are consecutive,
        # so the group-sum is a plain reshape-reduce to the kv row count
        dk = dk.reshape(Nkv, rep, S, D).sum(axis=1)
        dv = dv.reshape(Nkv, rep, S, D).sum(axis=1)
    return dq, dk, dv, None  # seed (int32) gets no cotangent


# ---------------------------------------------------------------------------
# slab layout: kernels read (B, T, N*H) directly — no HBM transposes
# ---------------------------------------------------------------------------

def _load_hbd(ref, n: int, D: int, rep: int = 1):
    """(1, t, n*D) ref -> (n*rep, t, D) head-major tile: the VMEM relayout
    that replaces the rows layout's per-call HBM transpose. GQA expands the
    kv heads here, in VMEM, where the repeat costs bandwidth the MXU pass
    was going to spend anyway — never in HBM."""
    t = ref[0].reshape(ref.shape[1], n, D).transpose(1, 0, 2)
    if rep > 1:
        t = jnp.repeat(t, rep, axis=0)
    return t


def _slab_fwd_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref,
                     m_ref, l_ref, *, scale, block_q, block_k, nh, nkv, D,
                     causal, rate):
    b, i, j = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    last_j = _last_visible_kv(i, block_q, block_k) if causal \
        else pl.num_programs(2) - 1

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when(j <= last_j)
    def _():
        # dropout keying: tile row index b with group nh gives row0 = b*nh
        # — the same absolute attention row as the rows layout, so the two
        # layouts draw identical masks
        _fwd_tile(_load_hbd(q_ref, nh, D), _load_hbd(k_ref, nkv, D, nh // nkv),
                  _load_hbd(v_ref, nkv, D, nh // nkv), b, i, j, seed_ref,
                  m_ref, l_ref, acc_ref, scale=scale, block_q=block_q,
                  block_k=block_k, causal=causal, rate=rate)

    @pl.when(j == pl.num_programs(2) - 1)
    def _():
        o, lse = _fwd_finalize(m_ref, l_ref, acc_ref)   # (nh, bq, D)
        o_ref[0] = o.transpose(1, 0, 2).reshape(
            o.shape[1], nh * D).astype(o_ref.dtype)
        lse_ref[0] = lse[:, :, 0].T


def _slab_bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                        delta_ref, dq_ref, dq_acc, *, scale, block_q,
                        block_k, nh, nkv, D, causal, rate):
    b, i, j = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    last_j = _last_visible_kv(i, block_q, block_k) if causal \
        else pl.num_programs(2) - 1

    @pl.when(j == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @pl.when(j <= last_j)
    def _():
        _dq_tile(_load_hbd(q_ref, nh, D), _load_hbd(k_ref, nkv, D, nh // nkv),
                 _load_hbd(v_ref, nkv, D, nh // nkv), _load_hbd(do_ref, nh, D),
                 lse_ref[0].T[:, :, None], delta_ref[0].T[:, :, None],
                 b, i, j, seed_ref, dq_acc, scale=scale, block_q=block_q,
                 block_k=block_k, causal=causal, rate=rate)

    @pl.when(j == pl.num_programs(2) - 1)
    def _():
        dq = (dq_acc[:] * scale).transpose(1, 0, 2)
        dq_ref[0] = dq.reshape(dq.shape[0], nh * D).astype(dq_ref.dtype)


def _slab_bwd_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                         delta_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                         scale, block_q, block_k, nh, nkv, D, causal, rate):
    b, j, i = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    first_i = _first_visible_q(j, block_q, block_k) if causal else 0
    rep = nh // nkv

    @pl.when(i == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(i >= first_i)
    def _():
        _dkv_tile(_load_hbd(q_ref, nh, D), _load_hbd(k_ref, nkv, D, rep),
                  _load_hbd(v_ref, nkv, D, rep), _load_hbd(do_ref, nh, D),
                  lse_ref[0].T[:, :, None], delta_ref[0].T[:, :, None],
                  b, i, j, seed_ref, dk_acc, dv_acc, scale=scale,
                  block_q=block_q, block_k=block_k, causal=causal,
                  rate=rate)

    @pl.when(i == pl.num_programs(2) - 1)
    def _():
        dk = dk_acc[:] * scale                          # (nh, bk, D)
        dv = dv_acc[:]
        if rep > 1:
            # GQA group-sum folded into the write step (the rows layout
            # does this host-side over per-query-row HBM outputs)
            dk = dk.reshape(nkv, rep, dk.shape[1], D).sum(axis=1)
            dv = dv.reshape(nkv, rep, dv.shape[1], D).sum(axis=1)
        dk_ref[0] = dk.transpose(1, 0, 2).reshape(
            dk.shape[1], nkv * D).astype(dk_ref.dtype)
        dv_ref[0] = dv.transpose(1, 0, 2).reshape(
            dv.shape[1], nkv * D).astype(dv_ref.dtype)


def _slab_fwd(q, k, v, seed, scale, block_q, block_k, interpret,
              causal, rate, nh, nkv, D):
    """q (B, T, nh*D) slabs; k/v (B, S, nkv*D) -> out (B, T, nh*D),
    lse (B, T, nh)."""
    B, T, _ = q.shape
    S = k.shape[1]
    nq, nk = T // block_q, S // block_k

    def q_row(b, i, j):
        return (b, i, 0)

    def kv_row(b, i, j):
        jc = j if not causal \
            else jnp.minimum(j, _last_visible_kv(i, block_q, block_k))
        return (b, jc, 0)

    return pl.pallas_call(
        functools.partial(_slab_fwd_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, nh=nh, nkv=nkv, D=D,
                          causal=causal, rate=rate),
        grid=(B, nq, nk),
        in_specs=[
            _SEED_SPEC,
            pl.BlockSpec((1, block_q, nh * D), q_row),
            pl.BlockSpec((1, block_k, nkv * D), kv_row),
            pl.BlockSpec((1, block_k, nkv * D), kv_row),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, nh * D), q_row),
            pl.BlockSpec((1, block_q, nh), q_row),
        ],
        out_shape=[
            _sds((B, T, nh * D), q.dtype, q),
            _sds((B, T, nh), jnp.float32, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((nh, block_q, D), jnp.float32),
            pltpu.VMEM((nh, block_q, 1), jnp.float32),
            pltpu.VMEM((nh, block_q, 1), jnp.float32),
        ],
        compiler_params=_SEMANTICS,
        interpret=interpret,
    )(seed, q, k, v)


def _slab_bwd(scale, block_q, block_k, interpret, causal, rate, nh, nkv, D,
              res, do, dlse=None):
    q, k, v, seed, out, lse = res
    B, T, _ = q.shape
    S = k.shape[1]
    nq, nk = T // block_q, S // block_k
    do3 = do.reshape(B, T, nh, D).astype(jnp.float32)
    out3 = out.reshape(B, T, nh, D).astype(jnp.float32)
    delta = jnp.sum(do3 * out3, axis=-1)                # (B, T, nh) f32
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)

    def q_row(b, i, j):
        return (b, i, 0)

    def kv_clamped(b, i, j):
        jc = j if not causal \
            else jnp.minimum(j, _last_visible_kv(i, block_q, block_k))
        return (b, jc, 0)

    dq = pl.pallas_call(
        functools.partial(_slab_bwd_dq_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, nh=nh, nkv=nkv, D=D,
                          causal=causal, rate=rate),
        grid=(B, nq, nk),
        in_specs=[
            _SEED_SPEC,
            pl.BlockSpec((1, block_q, nh * D), q_row),
            pl.BlockSpec((1, block_k, nkv * D), kv_clamped),
            pl.BlockSpec((1, block_k, nkv * D), kv_clamped),
            pl.BlockSpec((1, block_q, nh * D), q_row),
            pl.BlockSpec((1, block_q, nh), q_row),
            pl.BlockSpec((1, block_q, nh), q_row),
        ],
        out_specs=pl.BlockSpec((1, block_q, nh * D), q_row),
        out_shape=_sds((B, T, nh * D), q.dtype, q),
        scratch_shapes=[pltpu.VMEM((nh, block_q, D), jnp.float32)],
        compiler_params=_SEMANTICS,
        interpret=interpret,
    )(seed, q, k, v, do, lse, delta)

    def kv_row(b, j, i):
        return (b, j, 0)

    def q_clamped(b, j, i):
        ic = i if not causal \
            else jnp.maximum(i, _first_visible_q(j, block_q, block_k))
        return (b, ic, 0)

    dk, dv = pl.pallas_call(
        functools.partial(_slab_bwd_dkv_kernel, scale=scale,
                          block_q=block_q, block_k=block_k, nh=nh, nkv=nkv,
                          D=D, causal=causal, rate=rate),
        grid=(B, nk, nq),
        in_specs=[
            _SEED_SPEC,
            pl.BlockSpec((1, block_q, nh * D), q_clamped),
            pl.BlockSpec((1, block_k, nkv * D), kv_row),
            pl.BlockSpec((1, block_k, nkv * D), kv_row),
            pl.BlockSpec((1, block_q, nh * D), q_clamped),
            pl.BlockSpec((1, block_q, nh), q_clamped),
            pl.BlockSpec((1, block_q, nh), q_clamped),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, nkv * D), kv_row),
            pl.BlockSpec((1, block_k, nkv * D), kv_row),
        ],
        out_shape=[
            _sds((B, S, nkv * D), k.dtype, q),
            _sds((B, S, nkv * D), v.dtype, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((nh, block_k, D), jnp.float32),
            pltpu.VMEM((nh, block_k, D), jnp.float32),
        ],
        compiler_params=_SEMANTICS,
        interpret=interpret,
    )(seed, q, k, v, do, lse, delta)
    return dq, dk, dv, None


def _make_slab_lse(nh: int, nkv: int, D: int):
    """custom_vjp closure over the static head geometry (cached per
    geometry via _slab_lse_for so jit tracing reuses one vjp instance)."""

    @functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
    def slab_lse(q, k, v, seed, scale, block_q, block_k, interpret, causal,
                 rate):
        return _slab_fwd(q, k, v, seed, scale, block_q, block_k, interpret,
                         causal, rate, nh, nkv, D)

    def fwd(q, k, v, seed, scale, block_q, block_k, interpret, causal,
            rate):
        out, lse = _slab_fwd(q, k, v, seed, scale, block_q, block_k,
                             interpret, causal, rate, nh, nkv, D)
        return (out, lse), (q, k, v, seed, out, lse)

    def bwd(scale, block_q, block_k, interpret, causal, rate, res, cts):
        do, dlse = cts
        return _slab_bwd(scale, block_q, block_k, interpret, causal, rate,
                         nh, nkv, D, res, do, dlse=dlse)

    slab_lse.defvjp(fwd, bwd)
    return slab_lse


@functools.lru_cache(maxsize=64)
def _slab_lse_for(nh: int, nkv: int, D: int):
    return _make_slab_lse(nh, nkv, D)


def slab_attention_usable(B, T, S, nh, nkv, hs, dtype,
                          block_q: int = 0, block_k: int = 0) -> bool:
    """Gate for the slab layout: lane-aligned head slabs ((n*hs) % 128),
    sublane-aligned blocks, and the (nh, bq, bk) f32 score tile + scratch
    within the VMEM budget."""
    if (nh * hs) % 128 != 0 or (nkv * hs) % 128 != 0 or hs % 8 != 0:
        return False
    bq = block_q or _pick_block(T, DEFAULT_BLOCK_Q)
    bk = block_k or _pick_block(S, DEFAULT_BLOCK_K)
    if not (bq and bk):
        return False
    dsize = jnp.dtype(dtype).itemsize
    # GQA: _load_hbd jnp.repeat-expands K/V to nh heads IN VMEM (only the
    # HBM tiles stay at nkv), so the budget must count the post-repeat
    # intermediates at nh — gk=nkv here under-estimated exactly the
    # overflow this gate exists to prevent (round-5 ADVICE)
    return _vmem_bytes(nh, nh, bq, bk, hs, dsize) <= _VMEM_BUDGET


# One custom_vjp serves both public entries: (out, lse) with the lse
# output differentiable (the ring merge needs d/dlse; when a caller
# ignores lse, jax hands back a zero cotangent and the backward reduces
# to plain FlashAttention-2). `seed` is a traced (2,) int32 operand (no
# cotangent); `rate` is static.

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10))
def _flash_lse(q, k, v, seed, scale, block_q, block_k, g, interpret,
               causal, rate):
    return _fwd(q, k, v, seed, scale, block_q, block_k, g, interpret,
                causal, rate)


def _flash_lse_fwd(q, k, v, seed, scale, block_q, block_k, g, interpret,
                   causal, rate):
    out, lse = _fwd(q, k, v, seed, scale, block_q, block_k, g, interpret,
                    causal, rate)
    return (out, lse), (q, k, v, seed, out, lse)


def _flash_lse_bwd(scale, block_q, block_k, g, interpret, causal, rate,
                   res, cts):
    do, dlse = cts
    return _bwd_impl(scale, block_q, block_k, g, interpret, causal, rate,
                     res, do, dlse=dlse)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


# ---------------------------------------------------------------------------
# public entry points (interface fixed by ops/attention_core.py)
# ---------------------------------------------------------------------------

def _pick_block(n: int, preferred: int) -> int:
    """Largest divisor of n that is <= preferred and a multiple of 8."""
    b = min(preferred, n)
    while b > 8 and (n % b != 0):
        b -= 8
    return b if n % b == 0 else 0


def flash_attention_usable(q, k, v, *, causal: bool = True) -> bool:
    """Static gate for the dispatcher: shapes/dtypes this kernel handles
    (causal and full attention both supported since round 4)."""
    B, T, nh, hs = q.shape
    S = k.shape[1]
    if q.dtype not in (jnp.float32, jnp.bfloat16):
        return False
    if T < 8 or S < 8:
        return False  # decode-step shapes: the naive path is fine
    if hs % 8 != 0:
        return False
    bq = _pick_block(T, DEFAULT_BLOCK_Q)
    bk = _pick_block(S, DEFAULT_BLOCK_K)
    if not (bq and bk):
        return False
    # even a group of 1 must fit the per-step VMEM budget
    dsize = jnp.dtype(q.dtype).itemsize
    rows_ok = _vmem_bytes(1, 1, bq, bk, hs, dsize) <= _VMEM_BUDGET
    if DEFAULT_LAYOUT == "slab":
        nkv = k.shape[2]
        return rows_ok or slab_attention_usable(B, T, S, nh, nkv, hs,
                                                q.dtype)
    return rows_ok


def flash_attention_lse(q, k, v, *, scale: float, causal: bool = True,
                        block_q: int = 0, block_k: int = 0,
                        block_h: int = 0, layout: str | None = None,
                        dropout_rate: float = 0.0, dropout_rng=None,
                        interpret: bool = False):
    """Flash attention returning (out, lse) over BTNH-layout tensors.

    out: (B, T, nh, hs); lse: (B, T, nh) f32 logsumexp of the scaled
    scores — DIFFERENTIABLE (custom vjp folds d/dlse into the delta
    term). This is the building block for ring attention's cross-chunk
    online-softmax merge (ops/ring_attention.py): each chunk contributes
    a normalized partial (out_c, lse_c) pair and the merge is plain jnp.
    `causal=False` computes full (unmasked) attention — the visible
    off-diagonal chunks of a causal ring.

    `dropout_rate` > 0 applies attention-weight dropout INSIDE the kernel
    (reference model.py:149-151 SDPA dropout): normalized weights are
    masked/rescaled via the TPU per-core PRNG, reseeded per score tile
    from `dropout_rng` so forward and backward regenerate identical bits
    (no mask tensor ever exists in HBM). NOTE: lse is computed from the
    UNdropped scores (it is the true logsumexp). The sp ring path applies
    dropout in its einsum hops with GLOBAL-position keying instead
    (ops/ring_attention.py _hop_dropout_mask); flash hops stay rate==0.
    """
    B, T, nh, hs = q.shape
    S, nkv = k.shape[1], k.shape[2]
    assert hs % 8 == 0, "head dim must be a multiple of 8 (sublane)"
    assert nh % nkv == 0, "query heads must be a multiple of kv heads"
    rep = nh // nkv

    block_q = block_q or _pick_block(T, DEFAULT_BLOCK_Q)
    block_k = block_k or _pick_block(S, DEFAULT_BLOCK_K)
    assert block_q and T % block_q == 0 and block_k and S % block_k == 0, (
        f"no usable block split for T={T}, S={S} — gate with "
        f"flash_attention_usable first")

    rate = float(dropout_rate)
    if rate > 0.0:
        assert dropout_rng is not None, \
            "dropout_rate > 0 requires a dropout_rng key"
        assert rate < 1.0
        seed = jax.random.randint(dropout_rng, (2,), -2 ** 31, 2 ** 31 - 1,
                                  jnp.int32)
    else:
        seed = jnp.zeros((2,), jnp.int32)

    if layout is None:
        layout = DEFAULT_LAYOUT
    if layout == "slab" and slab_attention_usable(
            B, T, S, nh, nkv, hs, q.dtype, block_q, block_k):
        # (B, T, N, H) -> (B, T, N*H) is a FREE reshape of the model's
        # natural layout: zero HBM transposes in or out
        fn = _slab_lse_for(nh, nkv, hs)
        out, lse = fn(q.reshape(B, T, nh * hs), k.reshape(B, S, nkv * hs),
                      v.reshape(B, S, nkv * hs), seed, float(scale),
                      block_q, block_k, interpret, causal, rate)
        return out.reshape(B, T, nh, hs), lse

    g = block_h or _pick_group(B * nh, rep, DEFAULT_BLOCK_H, block_q,
                               block_k, hs, jnp.dtype(q.dtype).itemsize)
    assert (B * nh) % g == 0 and (g == 1 or rep == 1), (
        f"row group {g} must divide B*nh={B * nh} and needs nh == n_kv")

    # BTNH -> (B*H, T, D) row-major rows for group-blocked grids
    qt = jnp.transpose(q, (0, 2, 1, 3)).reshape(B * nh, T, hs)
    kt = jnp.transpose(k, (0, 2, 1, 3)).reshape(B * nkv, S, hs)
    vt = jnp.transpose(v, (0, 2, 1, 3)).reshape(B * nkv, S, hs)
    out, lse = _flash_lse(qt, kt, vt, seed, float(scale), block_q, block_k,
                          g, interpret, causal, rate)
    out = jnp.transpose(out.reshape(B, nh, T, hs), (0, 2, 1, 3))
    lse = jnp.transpose(lse.reshape(B, nh, T), (0, 2, 1))
    return out, lse


def flash_attention(q, k, v, *, scale: float, causal: bool = True,
                    q_offset=0, block_q: int = 0, block_k: int = 0,
                    block_h: int = 0, layout: str | None = None,
                    dropout_rate: float = 0.0, dropout_rng=None,
                    interpret: bool = False) -> jnp.ndarray:
    """Flash attention over BTNH-layout tensors.

    q: (B, T, nh, hs); k, v: (B, S, nkv, hs) with nkv | nh. `q_offset`
    must be a static 0 (prefill/training; the dispatcher routes
    cached-decode offsets — including traced ones — to the naive path).
    GQA kv heads are shared via the kernel's index maps; K/V are never
    materialized per query head. `dropout_rate`/`dropout_rng` enable
    in-kernel attention-weight dropout (see flash_attention_lse).
    """
    assert isinstance(q_offset, int) and q_offset == 0, (
        "flash kernel requires a static q_offset == 0; cached-decode "
        "offsets must use the naive path")
    out, _ = flash_attention_lse(q, k, v, scale=scale, causal=causal,
                                 block_q=block_q, block_k=block_k,
                                 block_h=block_h, layout=layout,
                                 dropout_rate=dropout_rate,
                                 dropout_rng=dropout_rng,
                                 interpret=interpret)
    return out
