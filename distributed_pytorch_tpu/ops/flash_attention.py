"""Pallas TPU flash attention: blockwise online-softmax in VMEM, with a
hand-written FlashAttention-2-style backward (custom VJP).

This is the framework's native-kernel replacement for the fused attention
the reference delegates to `F.scaled_dot_product_attention` (reference
single-gpu/model.py:149). Design (per the Pallas TPU playbook):

* Grid (B, H, q_blocks, kv_blocks), `dimension_semantics=('parallel',
  'parallel', 'parallel', 'arbitrary')`. Each grid step streams ONE
  (block_k, D) K/V tile through the MXU; the online-softmax state (running
  max m, normalizer l, f32 accumulator) lives in VMEM scratch that persists
  across the innermost kv dimension. VMEM use is constant in sequence
  length — attention probabilities never exist in HBM, so memory is O(T)
  instead of O(T^2) and sequences of 32k+ compile.
* Causal masking is positional (qpos >= kpos), so the KV length S may
  exceed the query length T (prefill into a longer zero-filled cache
  buffer): the zero tail is always masked. Blocks strictly above the
  causal frontier are skipped: compute is predicated with `pl.when` and
  their index maps clamp to the last visible block so no fresh DMA is
  issued for skipped tiles.
* Backward = two kernels (FlashAttention-2): dq accumulates over kv tiles;
  dk/dv accumulate over q tiles; both recompute p from the saved
  logsumexp instead of storing probabilities.
* GQA never materializes repeated K/V: the kv BlockSpec index maps send
  query head h to kv head h // group, so the same kv tile serves the whole
  group straight from HBM (a materialized repeat would multiply KV bytes by
  the group size at exactly the long-S scales this kernel targets). The
  backward emits per-query-head dk/dv and group-sums them host-side.
  Head dims must be sublane multiples (hs % 8); there is no padding path —
  odd head dims fall back to the XLA impl via `flash_attention_usable`.

The public entry points keep the interface the dispatcher
(ops/attention_core.py) fixed while this was a stub: `flash_attention` and
`flash_attention_usable`.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# At 128x128 the grid is B*H*(T/128)^2 ~= 12k steps/layer of ~2 MFLOP each
# and per-grid-step overhead dominates (v5e micro-bench, PERF.md round 4:
# 128x128 lost to 256x512 by ~50ms/call even with host-upload noise washing
# out kernel differences). 256x512 is the provisional winner; env knobs let
# scripts/mfu_sweep.py A/B block sizes in the real train step without an
# API change.
DEFAULT_BLOCK_Q = int(os.environ.get("FLASH_BLOCK_Q", "256"))
DEFAULT_BLOCK_K = int(os.environ.get("FLASH_BLOCK_K", "512"))

_NEG_INF = -1e30  # large-negative instead of -inf: keeps masked rows NaN-free

_SEMANTICS = pltpu.CompilerParams(
    dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"))


def _last_visible_kv(i, block_q: int, block_k: int):
    """Index of the last kv block the q tile `i` attends into (causal)."""
    return jax.lax.div(i * block_q + block_q - 1, block_k)


def _first_visible_q(j, block_q: int, block_k: int):
    """Index of the first q block that attends into kv tile `j` (causal)."""
    return jax.lax.div(j * block_k, block_q)


def _mask_scores(s, i, j, block_q, block_k):
    """Causal mask for one (block_q, block_k) score tile. Positions are
    absolute: qpos = i*block_q + row, kpos = j*block_k + col; a query
    attends keys with kpos <= qpos (reference model.py:225-226 triu
    semantics with offset 0)."""
    qpos = i * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    kpos = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(qpos >= kpos, s, _NEG_INF)


def _dot(a, b, trans_b=False):
    dims = (((1,), (1 if trans_b else 0,)), ((), ()))
    return jax.lax.dot_general(a, b, dims,
                               preferred_element_type=jnp.float32)


def _dot_t(a, b):
    """a^T @ b with f32 accumulation."""
    return jax.lax.dot_general(a, b, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _sds(shape, dtype, like):
    """ShapeDtypeStruct carrying `like`'s varying-manual-axes set: pallas
    calls inside shard_map (the ring-attention hop path) must declare how
    their outputs vary across mesh axes."""
    vma = getattr(jax.typeof(like), "vma", None)
    if vma is None:  # jax without vma tracking
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale, block_q, block_k, causal):
    i, j = pl.program_id(2), pl.program_id(3)
    last_j = _last_visible_kv(i, block_q, block_k) if causal \
        else pl.num_programs(3) - 1

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when(j <= last_j)
    def _():
        # operands stay in input dtype (bf16 on TPU): the MXU accumulates in
        # f32 via preferred_element_type — casting inputs up would force
        # slow fp32 MXU passes
        q, k, v = q_ref[0, 0], k_ref[0, 0], v_ref[0, 0]
        s = _dot(q, k, trans_b=True) * scale             # (bq, bk) f32
        if causal:
            s = _mask_scores(s, i, j, block_q, block_k)
        m_prev, l_prev = m_ref[:], l_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + _dot(p.astype(v.dtype), v)

    @pl.when(j == pl.num_programs(3) - 1)
    def _():
        l_safe = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = m_ref[:] + jnp.log(l_safe)


def _fwd(q, k, v, scale, block_q, block_k, interpret, causal=True):
    """q (B,H,T,D), k/v (B,Hkv,S,D), Hkv | H -> out (B,H,T,D), lse (B,H,T,1)."""
    B, H, T, D = q.shape
    S = k.shape[2]
    rep = H // k.shape[1]
    nq, nk = T // block_q, S // block_k

    def kv_idx(b, h, i, j):
        # GQA: query head h reads kv head h // rep — no materialized repeat.
        # Skipped upper-triangle tiles clamp to the causal frontier so the
        # revolving-buffer DMA sees an unchanged index (no fetch).
        if not causal:
            return (b, h // rep, j, 0)
        return (b, h // rep,
                jnp.minimum(j, _last_visible_kv(i, block_q, block_k)), 0)

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, causal=causal),
        grid=(B, H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, D), kv_idx),
            pl.BlockSpec((1, 1, block_k, D), kv_idx),
        ],  # k/v arrays keep their Hkv head count; kv_idx maps the group
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            # trailing singleton lane dim: TPU blocks need the last two dims
            # (8,128)-divisible OR equal to the array dims; (bq, 1) with
            # array (..., T, 1) qualifies.
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            _sds((B, H, T, D), q.dtype, q),
            _sds((B, H, T, 1), jnp.float32, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=_SEMANTICS,
        interpret=interpret,
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward (FlashAttention-2: recompute p from lse; delta = rowsum(do * o))
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc, *, scale, block_q, block_k, causal):
    i, j = pl.program_id(2), pl.program_id(3)
    last_j = _last_visible_kv(i, block_q, block_k) if causal \
        else pl.num_programs(3) - 1

    @pl.when(j == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @pl.when(j <= last_j)
    def _():
        q, k, v, do = q_ref[0, 0], k_ref[0, 0], v_ref[0, 0], do_ref[0, 0]
        s = _dot(q, k, trans_b=True) * scale
        if causal:
            s = _mask_scores(s, i, j, block_q, block_k)
        p = jnp.exp(s - lse_ref[0, 0])                  # (bq, bk) f32
        dp = _dot(do, v, trans_b=True)
        ds = p * (dp - delta_ref[0, 0])
        dq_acc[:] = dq_acc[:] + _dot(ds.astype(k.dtype), k)

    @pl.when(j == pl.num_programs(3) - 1)
    def _():
        dq_ref[0, 0] = (dq_acc[:] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, block_q,
                    block_k, causal):
    j, i = pl.program_id(2), pl.program_id(3)
    first_i = _first_visible_q(j, block_q, block_k) if causal else 0

    @pl.when(i == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(i >= first_i)
    def _():
        q, k, v, do = q_ref[0, 0], k_ref[0, 0], v_ref[0, 0], do_ref[0, 0]
        s = _dot(q, k, trans_b=True) * scale            # (bq, bk) f32
        if causal:
            s = _mask_scores(s, i, j, block_q, block_k)
        p = jnp.exp(s - lse_ref[0, 0])
        dv_acc[:] = dv_acc[:] + _dot_t(p.astype(do.dtype), do)
        dp = _dot(do, v, trans_b=True)
        ds = p * (dp - delta_ref[0, 0])
        dk_acc[:] = dk_acc[:] + _dot_t(ds.astype(q.dtype), q)

    @pl.when(i == pl.num_programs(3) - 1)
    def _():
        dk_ref[0, 0] = (dk_acc[:] * scale).astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_impl(scale, block_q, block_k, interpret, causal, res, do,
              dlse=None):
    """Shared backward: dlse (B,H,T,1) is the cotangent of the logsumexp
    output when the caller differentiates through it (the ring merge does;
    plain flash_attention passes None). Math: with L = sum(do*out) +
    sum(dlse*lse), ds = p * (dp - delta + dlse) — i.e. dlse just shifts
    the per-row delta term, since d lse/d s_j = p_j."""
    q, k, v, out, lse = res
    B, H, T, D = q.shape
    S, Hkv = k.shape[2], k.shape[1]
    rep = H // Hkv
    nq, nk = T // block_q, S // block_k
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)                     # (B,H,T,1)
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)

    def kv_idx(b, h, i, j):
        if not causal:
            return (b, h // rep, j, 0)
        return (b, h // rep,
                jnp.minimum(j, _last_visible_kv(i, block_q, block_k)), 0)

    def q_row(b, h, i, j):
        return (b, h, i, 0)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, causal=causal),
        grid=(B, H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), q_row),
            pl.BlockSpec((1, 1, block_k, D), kv_idx),
            pl.BlockSpec((1, 1, block_k, D), kv_idx),
            pl.BlockSpec((1, 1, block_q, D), q_row),
            pl.BlockSpec((1, 1, block_q, 1), q_row),
            pl.BlockSpec((1, 1, block_q, 1), q_row),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D), q_row),
        out_shape=_sds((B, H, T, D), q.dtype, q),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=_SEMANTICS,
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    def q_idx(b, h, j, i):
        # clamp sub-frontier q tiles (skipped compute) to an already-visible
        # index so no fresh DMA is issued
        if not causal:
            return (b, h, i, 0)
        return (b, h, jnp.maximum(i, _first_visible_q(j, block_q, block_k)),
                0)

    def kv_row(b, h, j, i):
        return (b, h // rep, j, 0)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, causal=causal),
        grid=(B, H, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), q_idx),
            pl.BlockSpec((1, 1, block_k, D), kv_row),
            pl.BlockSpec((1, 1, block_k, D), kv_row),
            pl.BlockSpec((1, 1, block_q, D), q_idx),
            pl.BlockSpec((1, 1, block_q, 1), q_idx),
            pl.BlockSpec((1, 1, block_q, 1), q_idx),
        ],
        out_specs=[
            # per-QUERY-head dk/dv tiles (kv tiles are shared across the
            # group, so writes would collide at the kv head count);
            # group-summed below
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, j, i: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, j, i: (b, h, j, 0)),
        ],
        out_shape=[
            _sds((B, H, S, D), k.dtype, q),
            _sds((B, H, S, D), v.dtype, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=_SEMANTICS,
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    if rep > 1:
        # jnp.repeat is interleaved: query head h <- kv head h // rep
        dk = dk.reshape(B, Hkv, rep, S, D).sum(axis=2)
        dv = dv.reshape(B, Hkv, rep, S, D).sum(axis=2)
    return dq, dk, dv


# One custom_vjp serves both public entries: (out, lse) with the lse
# output differentiable (the ring merge needs d/dlse; when a caller
# ignores lse, jax hands back a zero cotangent and the backward reduces
# to plain FlashAttention-2).

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_lse(q, k, v, scale, block_q, block_k, interpret, causal):
    return _fwd(q, k, v, scale, block_q, block_k, interpret, causal)


def _flash_lse_fwd(q, k, v, scale, block_q, block_k, interpret, causal):
    out, lse = _fwd(q, k, v, scale, block_q, block_k, interpret, causal)
    return (out, lse), (q, k, v, out, lse)


def _flash_lse_bwd(scale, block_q, block_k, interpret, causal, res, cts):
    do, dlse = cts
    return _bwd_impl(scale, block_q, block_k, interpret, causal, res, do,
                     dlse=dlse)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


# ---------------------------------------------------------------------------
# public entry points (interface fixed by ops/attention_core.py)
# ---------------------------------------------------------------------------

def _pick_block(n: int, preferred: int) -> int:
    """Largest divisor of n that is <= preferred and a multiple of 8."""
    b = min(preferred, n)
    while b > 8 and (n % b != 0):
        b -= 8
    return b if n % b == 0 else 0


def flash_attention_usable(q, k, v, *, causal: bool = True) -> bool:
    """Static gate for the dispatcher: shapes/dtypes this kernel handles
    (causal and full attention both supported since round 4)."""
    B, T, nh, hs = q.shape
    S = k.shape[1]
    if q.dtype not in (jnp.float32, jnp.bfloat16):
        return False
    if T < 8 or S < 8:
        return False  # decode-step shapes: the naive path is fine
    if hs % 8 != 0:
        return False
    return bool(_pick_block(T, DEFAULT_BLOCK_Q)
                and _pick_block(S, DEFAULT_BLOCK_K))


def flash_attention_lse(q, k, v, *, scale: float, causal: bool = True,
                        block_q: int = 0, block_k: int = 0,
                        interpret: bool = False):
    """Flash attention returning (out, lse) over BTNH-layout tensors.

    out: (B, T, nh, hs); lse: (B, T, nh) f32 logsumexp of the scaled
    scores — DIFFERENTIABLE (custom vjp folds d/dlse into the delta
    term). This is the building block for ring attention's cross-chunk
    online-softmax merge (ops/ring_attention.py): each chunk contributes
    a normalized partial (out_c, lse_c) pair and the merge is plain jnp.
    `causal=False` computes full (unmasked) attention — the visible
    off-diagonal chunks of a causal ring.
    """
    B, T, nh, hs = q.shape
    S, nkv = k.shape[1], k.shape[2]
    assert hs % 8 == 0, "head dim must be a multiple of 8 (sublane)"
    assert nh % nkv == 0, "query heads must be a multiple of kv heads"

    block_q = block_q or _pick_block(T, DEFAULT_BLOCK_Q)
    block_k = block_k or _pick_block(S, DEFAULT_BLOCK_K)
    assert block_q and T % block_q == 0 and block_k and S % block_k == 0, (
        f"no usable block split for T={T}, S={S} — gate with "
        f"flash_attention_usable first")

    # BTNH -> BHTD for tile-contiguous blocks
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    out, lse = _flash_lse(qt, kt, vt, float(scale), block_q, block_k,
                          interpret, causal)
    return (jnp.transpose(out, (0, 2, 1, 3)),
            jnp.transpose(lse[..., 0], (0, 2, 1)))


def flash_attention(q, k, v, *, scale: float, causal: bool = True,
                    q_offset=0, block_q: int = 0, block_k: int = 0,
                    interpret: bool = False) -> jnp.ndarray:
    """Flash attention over BTNH-layout tensors.

    q: (B, T, nh, hs); k, v: (B, S, nkv, hs) with nkv | nh. `q_offset`
    must be a static 0 (prefill/training; the dispatcher routes
    cached-decode offsets — including traced ones — to the naive path).
    GQA kv heads are shared via the kernel's index maps; K/V are never
    materialized per query head.
    """
    assert isinstance(q_offset, int) and q_offset == 0, (
        "flash kernel requires a static q_offset == 0; cached-decode "
        "offsets must use the naive path")
    out, _ = flash_attention_lse(q, k, v, scale=scale, causal=causal,
                                 block_q=block_q, block_k=block_k,
                                 interpret=interpret)
    return out
