"""Host-RAM second tier for the paged KV cache (the ZeRO-Offload thesis
applied to serving, PAPERS.md).

The block pool's prefix cache is capped at HBM size: a refcount-0
registered block that falls off the HBM LRU is simply gone, and the next
request for that prefix pays a full prefill. This module turns that
eviction into a DEMOTION — the block's KV contents (every cache leaf,
int8 scale sidecars included) move to a host-side pool with its own
block budget and LRU, keyed by the SAME chain key the radix index uses —
and turns a later radix hit on a demoted chain into a PROMOTION: a
single batched `jax.device_put` of the chain's host blocks plus one
fixed-shape jitted copy program per block into freshly allocated HBM
blocks. One PCIe copy buys back a prefill; the host/HBM size ratio
multiplies the effective prefix cache.

Transport unit: a block chain at a block-aligned offset — exactly the
interface the ROADMAP's disaggregated-prefill item will later point
across hosts, which is why this lives as its own module instead of
inline pool code.

Placement contract:

* **Demote** (`snapshot_block` + `HostTier.demote`): one `device_get` of
  the evicted block's rows across every cache leaf. Runs on the host
  thread that owns the engine, at pool-eviction time — the block is
  refcount-0 and immutable (only FULL registered blocks are ever
  evicted), so the copy races nothing.
* **Promote** (`make_promote_block_fn`): the copy program has a FIXED
  shape — one (block_size, ...) row-set per cache leaf plus a scalar
  block id — so promoting a chain of any length reuses one compiled
  program (budgeted in the engine's trace guards and audited by
  parallel/commscheck.py; the fused step itself never traces anything
  new). The HBM pool buffers are donated, so promotion recycles the
  cache allocation in place exactly like the step families do on TPU.

Host storage is plain numpy (there is no pinned-memory API to ask for
portably through JAX; on TPU hosts `device_put` from numpy stages
through pinned buffers anyway, and on CPU the "transfer" is a copy),
sized in BLOCKS so the budget composes with `train/memplan.py`'s
bytes-per-block pricing.
"""

from __future__ import annotations

import collections
from typing import Any, Optional

import jax
import numpy as np


def tree_block_bytes(host_block) -> int:
    """Total bytes of one demoted block across every cache leaf."""
    return sum(int(leaf.nbytes) for leaf in
               jax.tree_util.tree_leaves(host_block))


def snapshot_block(caches, blk: int):
    """Pull one block's rows out of the device cache pytree: the demote
    transport. One transfer for all layers/leaves (k, v, scale sidecars,
    MLA latents — whatever the cache holds) — THE deliberate
    device->host sync of the demote path."""
    rows = jax.tree_util.tree_map(lambda pool: pool[blk], caches)
    return jax.device_get(rows)  # lint: allow(host-sync)


def make_promote_block_fn(*, on_trace=None):
    """The single promote copy program: write one staged block's rows
    into HBM block `blk` of every cache leaf. Fixed shapes — (bs, ...)
    rows + scalar block id — so every promotion of every chain shares
    ONE compiled program; the engine jits it with the cache buffers
    donated (TPU), recycling the pool allocation in place."""

    def promote_block(caches, rows, blk):
        if on_trace is not None:
            on_trace()  # trace-time side effect
        return jax.tree_util.tree_map(
            lambda pool, r: pool.at[blk].set(r.astype(pool.dtype)),
            caches, rows)

    return promote_block


class HostTier:
    """Host-RAM block store with its own budget and LRU: the second tier
    behind the HBM pool's refcount-0 prefix cache.

    Entries are keyed by the radix CHAIN key — (parent_digest,
    block_tokens), see ops/block_pool.py — so a host hit carries the
    same proof an HBM hit does: the whole prefix up to and including
    this block matches. Overflow drops the LRU entry (counted — the
    only way tier-managed KV is ever lost), promotion CONSUMES the
    entry (exactly one copy of a block's KV exists across the two
    tiers; a later eviction simply demotes it again).

    >>> tier = HostTier(capacity_blocks=256)
    >>> tier.demote(key, snapshot_block(caches, blk))
    >>> if tier.contains(key): rows = tier.pop(key)
    """

    def __init__(self, capacity_blocks: int):
        assert capacity_blocks >= 1, "host tier needs a positive budget"
        self.capacity = capacity_blocks
        self._store: collections.OrderedDict[tuple, Any] = \
            collections.OrderedDict()          # chain key -> host rows
        # lifetime counters (engine properties / serve metrics read these)
        self.n_demoted = 0        # blocks demoted into the tier
        self.n_promoted = 0       # blocks promoted back to HBM
        self.n_dropped = 0        # blocks lost to the host LRU cap
        self.n_hits = 0           # probe hits (contains -> True)
        self.n_misses = 0         # probe misses
        self.demoted_bytes = 0
        self.promoted_bytes = 0
        # per-promotion byte sizes since the last drain — the scheduler
        # feeds these to the promote-bytes histogram
        self._promote_events: list[int] = []

    # -- capacity accounting -------------------------------------------
    @property
    def n_blocks(self) -> int:
        return len(self._store)

    @property
    def occupancy(self) -> float:
        return len(self._store) / self.capacity if self.capacity else 0.0

    @property
    def hit_rate(self) -> float:
        """Lifetime fraction of tier probes that hit (probes happen only
        after an HBM radix miss, so this is the second-tier save rate)."""
        probes = self.n_hits + self.n_misses
        return self.n_hits / probes if probes else 0.0

    # -- tier state machine --------------------------------------------
    def contains(self, key: tuple) -> bool:
        """Probe for a chain key (counted: the tier hit-rate gauge)."""
        hit = key in self._store
        if hit:
            self.n_hits += 1
        else:
            self.n_misses += 1
        return hit

    def demote(self, key: tuple, host_rows) -> None:
        """Store one evicted block's rows under its chain key, dropping
        the LRU entry when the budget is exceeded. Re-demoting a key the
        tier already holds just refreshes its LRU position (the content
        is identical — chain keys are content addresses)."""
        if key in self._store:
            self._store.move_to_end(key)
            return
        self._store[key] = host_rows
        self.n_demoted += 1
        self.demoted_bytes += tree_block_bytes(host_rows)
        while len(self._store) > self.capacity:
            self._store.popitem(last=False)    # oldest demoted chain tail
            self.n_dropped += 1

    def pop(self, key: tuple):
        """Consume a demoted block for promotion: returns its host rows
        and removes the entry (the HBM copy becomes the only one)."""
        host_rows = self._store.pop(key)
        nbytes = tree_block_bytes(host_rows)
        self.n_promoted += 1
        self.promoted_bytes += nbytes
        self._promote_events.append(nbytes)
        return host_rows

    def drain_promote_events(self) -> list:
        """Per-promotion byte sizes since the last drain (and reset) —
        the serve metrics' promote-bytes histogram samples."""
        out, self._promote_events = self._promote_events, []
        return out

    def counters(self) -> dict:
        """Stable counter snapshot (bench JSON / metrics sync read this
        instead of poking attributes one by one)."""
        return {"demoted": self.n_demoted, "promoted": self.n_promoted,
                "dropped": self.n_dropped, "hits": self.n_hits,
                "misses": self.n_misses,
                "demoted_bytes": self.demoted_bytes,
                "promoted_bytes": self.promoted_bytes,
                "resident_blocks": len(self._store)}
