"""Symmetric int8 quantization for the serving path: KV cache + weights.

PERF.md round-8's bytes-moved model says decode is bandwidth-bound — a
124M bf16 model is ~250 MB of weights per step and the KV cache adds ~20%
more at half occupancy — so the round-9 lever is halving those bytes:

* **int8 KV cache**: `_update_cache`'s ring write (models/attention.py)
  quantizes each incoming K/V row to int8 codes plus a float32 scale
  sidecar that rides the cache pytree (same slot/kv-head shardings via
  `sharding.decode_cache_pspec` — the sidecar keeps the (B, S, n_kv, 1)
  layout so the kv-head axis shards over 'model' exactly like the codes).
  Scales are per-(cache-row, kv-head), i.e. one scale per written token
  per kv head, reduced over the head-dim channel: the only granularity
  consistent with O(1) incremental ring writes — a per-channel-over-time
  scale would need a full-buffer requantization whenever a new token
  raised the running max. The flash-decode kernel DMAs the int8 blocks
  plus their scale rows and dequantizes in VMEM registers (the scale
  folds into the score/probability tiles — the MXU tiles operate on cast
  codes, never on a materialized dequantized buffer); the naive fallback
  dequantizes the buffers up front.
* **weight-only int8**: `quantize_params` turns every 2D matmul kernel
  (fused qkv, out-projections, MLP up/down, MLA projections, the tied
  lm-head embedding) into int8 codes + a per-output-channel float32
  scale. The decode step runs `y = (x @ codes) * scale` — the cast
  happens in VMEM on the way into the MXU, the scale on the (B, 1, out)
  output — algebraically exact given the codes. Prefill keeps the bf16
  originals (quantization error is paid once per generated token, not
  amplified over a long prompt). Stacked MoE expert kernels and the
  pp-stacked 'blocks' layout are excluded (decode never touches pp;
  expert quantization is future work — unquantized call sites simply
  keep their bf16 matmul, which is always correct).

Gates follow the `FLASH_DECODE`/`OVERLAP` contract: `QUANT_KV` /
`QUANT_W` = `auto|on|off`, read per call so tests and bench legs can flip
them per subprocess. 'auto' defers to the caller's explicit request
(`DecodeEngine(cache_dtype='int8', quantize_weights=True)`, sample.py
flags) and therefore resolves to OFF until someone asks — quantization
changes numerics, so no path turns it on silently before a silicon A/B
exists. 'on'/'off' force it for the bench/sweep legs. `quant_kv_usable`
is the degrade-don't-crash predicate: where int8 KV isn't supported (MLA
latent caches — already ~8x compressed; int8 there compounds error) the
engine falls back to bf16 instead of crashing, like
`flash_decode_usable`/`grouped_usable`.
"""

from __future__ import annotations

import contextlib
from typing import Any, Mapping, Optional

import jax
import jax.numpy as jnp

from distributed_pytorch_tpu import config

# int8 symmetric range: +-127 (the -128 code is unused so the grid is
# symmetric and dequant is a pure scale multiply)
_Q_MAX = 127.0


def kv_quant_mode() -> str:
    """'auto' | 'on' | 'off' — read per call (tests monkeypatch env)."""
    return config.knob("QUANT_KV")


def weight_quant_mode() -> str:
    return config.knob("QUANT_W")


def resolve_gate(mode: str, requested: bool) -> bool:
    """Apply the auto|on|off contract: 'auto' follows the caller's explicit
    request (default off — quantization never turns on silently), 'on' and
    'off' force, e.g. from a bench leg's env."""
    if mode not in ("auto", "on", "off"):
        raise ValueError(f"quant mode must be auto|on|off, got {mode!r}")
    if mode == "auto":
        return requested
    return mode == "on"


def quant_kv_usable(cfg) -> bool:
    """Static gate: int8 KV is supported for the GQA family (mha/mqa/gqa)
    whose cache rows are per-head vectors a row-wise scale covers. MLA's
    latent cache declines — callers fall back to the bf16 cache
    (degrade-don't-crash), never to an error."""
    return getattr(cfg, "attn", None) in ("mha", "mqa", "gqa")


# ---------------------------------------------------------------------------
# core quantize / dequantize
# ---------------------------------------------------------------------------

def quantize_int8(x: jnp.ndarray, axis) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int8: codes = round(x / scale), scale = amax/127 reduced
    over `axis` (kept as size-1 dims so dequant is a broadcast multiply).
    All-zero groups get scale 0 and codes 0 (dequant returns exact zeros —
    dead cache slots stay clean)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=axis, keepdims=True)
    scale = amax / _Q_MAX
    inv = jnp.where(scale > 0, 1.0 / jnp.maximum(scale, 1e-30), 0.0)
    codes = jnp.clip(jnp.round(xf * inv), -_Q_MAX, _Q_MAX).astype(jnp.int8)
    return codes, scale


def dequantize_int8(codes: jnp.ndarray, scale: jnp.ndarray,
                    dtype=jnp.float32) -> jnp.ndarray:
    return (codes.astype(jnp.float32) * scale).astype(dtype)


def quantize_kv(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Quantize new K/V rows (B, T, n_kv, hs) for the ring write: int8
    codes + per-(row, kv-head) scales (B, T, n_kv, 1) — the sidecar that
    rides the cache pytree."""
    return quantize_int8(x, axis=-1)


# ---------------------------------------------------------------------------
# weight-only int8: pytree transforms
# ---------------------------------------------------------------------------

# 2D matmul param names eligible for weight-only int8. All are (in, out)
# kernels scaled per output channel, except the tied embedding (V, C)
# whose lm-head matmul contracts C — its "output channel" is the vocab
# row. MoE expert stacks (3D) and anything under the pp 'blocks' layout
# are excluded (see module docstring).
_KERNEL_NAMES = frozenset((
    "kernel", "c_fc", "c_proj",
    "W_dq", "W_uq", "W_dkv", "W_uk", "W_uv", "W_o", "W_qr", "W_kr",
))


def _quant_axis(names: tuple[str, ...], ndim: int) -> Optional[int]:
    """Reduction axis for one param leaf, or None when it stays bf16."""
    if not names or names[0] == "blocks" or ndim != 2:
        return None
    last = names[-1]
    if last == "embedding":
        return 1      # (V, C): scale per vocab row (lm-head output channel)
    if last in _KERNEL_NAMES:
        return 0      # (in, out): scale per output channel
    return None


def quantize_params(params: Mapping) -> dict:
    """params pytree -> sparse nested dict of {'q8': int8, 'scale': f32}
    leaves for every eligible matmul kernel (biases, norms, expert stacks
    pass through untouched by NOT appearing — call sites that find no
    entry keep their bf16 matmul)."""
    def rec(node, names):
        if isinstance(node, Mapping):
            out = {}
            for k, v in node.items():
                sub = rec(v, names + (k,))
                if sub is not None:
                    out[k] = sub
            return out or None
        ax = _quant_axis(names, getattr(node, "ndim", 0))
        if ax is None:
            return None
        codes, scale = quantize_int8(node, axis=ax)
        return {"q8": codes, "scale": scale}
    return rec(params, ()) or {}


def dequantize_params(qtree: Mapping, dtype=jnp.float32) -> dict:
    """Inverse transform: the sparse quantized tree -> same-structured tree
    of dequantized dense arrays (the reference for parity tests)."""
    def rec(node):
        if isinstance(node, Mapping) and "q8" in node and "scale" in node:
            return dequantize_int8(node["q8"], node["scale"], dtype)
        return {k: rec(v) for k, v in node.items()}
    return rec(qtree)


# ---------------------------------------------------------------------------
# ambient quantized-weight store (the engine's decode step enters this
# around model.apply; call sites consult it by param path)
# ---------------------------------------------------------------------------

_ACTIVE: Optional[Mapping] = None


@contextlib.contextmanager
def use_quantized_params(store: Optional[Mapping]):
    """Trace-time context (the parallel.context.use_mesh idiom): make a
    quantized-param store visible to the matmul call sites for the
    duration of a model.apply trace. Pass the store THROUGH the jitted
    function's arguments (never close over concrete arrays — they would
    bake into the executable as constants)."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = store or None
    try:
        yield
    finally:
        _ACTIVE = prev


def _lookup(names: tuple[str, ...]):
    node = _ACTIVE
    if node is None:
        return None
    for n in names:
        if not isinstance(node, Mapping) or n not in node:
            return None
        node = node[n]
    if isinstance(node, Mapping) and "q8" in node:
        return node
    return None


def maybe_quantized_matmul(x: jnp.ndarray, names, *,
                           transpose_b: bool = False) -> Optional[jnp.ndarray]:
    """`x @ W` from the active quantized store, or None when no store is
    active / the path has no entry (caller keeps its bf16 matmul).

    The codes cast to x.dtype in VMEM on the way into the MXU; the
    per-output-channel scale is applied to the (small) decode-shaped
    output in f32 — `(x @ codes) * scale` is algebraically exact given
    the codes. `transpose_b` is the tied-embedding lm head: codes (V, C),
    scale per vocab row."""
    qt = _lookup(tuple(names))
    if qt is None:
        return None
    codes, scale = qt["q8"], qt["scale"]
    w = codes.astype(x.dtype)
    if transpose_b:
        y = jnp.einsum("...c,vc->...v", x, w)
        s = scale.reshape(-1)          # (V,)
    else:
        y = x @ w
        s = scale.reshape(-1)          # (out,)
    return (y.astype(jnp.float32) * s).astype(x.dtype)


def maybe_dequantized_param(names, fallback: jnp.ndarray,
                            dtype=None) -> jnp.ndarray:
    """The active store's dequantized weight for `names`, else `fallback`
    unchanged — for call sites that contract a kernel in a reshaped form
    (MLA's absorbed W_uk/W_uv) where folding the scale into the matmul
    output isn't a plain broadcast."""
    qt = _lookup(tuple(names))
    if qt is None:
        return fallback
    return dequantize_int8(qt["q8"], qt["scale"],
                           dtype or fallback.dtype)
