"""Fused scaled-dot-product attention dispatch.

This is the framework's named equivalent of the reference's delegated
`F.scaled_dot_product_attention` CUDA kernel (reference
single-gpu/model.py:149). Implementations:

* 'xla'    — `jax.nn.dot_product_attention`: XLA fuses QK^T+softmax+PV and
             tiles onto the MXU; supports GQA (n_kv_heads dividing n_head)
             without materializing repeated KV.
* 'pallas' — hand-written TPU flash-attention kernel (ops/flash_attention.py),
             blockwise online softmax in VMEM.
* 'naive'  — explicit einsum path; supports attention-weight dropout, KV-cache
             offset masks (scalar or per-sequence arrays), and arbitrary
             masks. The decode fallback and the reference semantics oracle
             in tests.
* decode fast path — single-token KV-cached calls route to the split-KV
             Pallas flash-decode kernel (ops/flash_decode.py) when
             `flash_decode_usable` holds (FLASH_DECODE=auto|on|off;
             'auto' = TPU only), else fall through to 'naive'.
* 'auto'   — pallas on TPU when shapes allow, else xla. dropout>0 routes
             to the pallas kernel's IN-KERNEL dropout on TPU (round 5 —
             parity with CUDA SDPA dropout, reference model.py:149-151);
             non-flash shapes / non-TPU fall back to naive.

Layout convention: q (B, T, nh, hs); k, v (B, S, n_kv, hs) — "BTNH", the
layout jax.nn.dot_product_attention and the Pallas kernel both want, avoiding
the reference's transpose dance to (B, nh, T, hs).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def _shard_map_over_data(fn, q, has_rng: bool = False):
    """Batch-parallel shard_map wrapper for a pallas call under a live
    multi-device mesh: GSPMD cannot partition a pallas_call (it would
    replicate the compute after all-gathering the operands), so on dp/fsdp
    meshes the kernel runs per data shard with explicitly local batches.
    Returns None when no wrap is needed (single device) or when the gates
    don't hold (head-sharded tp activations, pipeline vmap bodies, batch
    not divisible) — those paths keep the unwrapped call/XLA fallback."""
    from distributed_pytorch_tpu.parallel import context
    mesh = context.get_mesh()
    if mesh is None or context.in_sp_region():
        return None
    dp = mesh.shape.get("data", 1)
    if (dp <= 1 or mesh.shape.get("model", 1) > 1
            or mesh.shape.get("pipe", 1) > 1
            or q.shape[0] % dp != 0 or q.shape[0] // dp < 1):
        return None
    from jax.sharding import PartitionSpec as P
    spec = P("data", None, None, None)

    if has_rng:
        def body(a, b, c, rng):
            with context.sp_region():   # suppress nested sp/wrap routing
                # per-data-shard masks: each shard holds different samples
                # at the same local batch rows
                rng = jax.random.fold_in(rng, jax.lax.axis_index("data"))
                return fn(a, b, c, rng)

        from distributed_pytorch_tpu import compat
        return compat.shard_map(body, mesh=mesh,
                                in_specs=(spec, spec, spec, P()),
                                out_specs=spec)

    def body(a, b, c):
        with context.sp_region():
            return fn(a, b, c)

    from distributed_pytorch_tpu import compat
    return compat.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                            out_specs=spec)


def _naive_sdpa(q, k, v, *, scale, q_offset, dropout_rate=0.0,
                dropout_rng=None, causal=True):
    """Reference-semantics einsum attention with cache-offset causal mask.

    Mask matches reference model.py:225-226: query global position =
    q_offset + i may attend key positions j <= q_offset + i. `q_offset`
    may be a per-sequence (B,) array (slot-based ragged decode: each
    sequence in the batch sits at its own cache position).
    """
    B, T, nh, hs = q.shape
    S, nkv = k.shape[1], k.shape[2]
    if nkv != nh:
        rep = nh // nkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    attn = jnp.einsum("btnh,bsnh->bnts", qf, kf) * scale
    if causal:
        qpos = (jnp.reshape(jnp.asarray(q_offset, jnp.int32), (-1, 1, 1))
                + jnp.arange(T)[None, :, None])     # (B|1, T, 1)
        kpos = jnp.arange(S)[None, None, :]
        mask = qpos >= kpos  # (B|1, T, S)
        attn = jnp.where(mask[:, None], attn, -jnp.inf)
    attn = jax.nn.softmax(attn, axis=-1)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, attn.shape)
        attn = jnp.where(keep, attn / (1.0 - dropout_rate), 0.0)
    out = jnp.einsum("bnts,bsnh->btnh", attn.astype(v.dtype), v)
    return out


def sdpa(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
         causal: bool = True,
         scale: Optional[float] = None,
         q_offset: int | jnp.ndarray = 0,
         dropout_rate: float = 0.0,
         dropout_rng=None,
         impl: str = "auto",
         decode: bool = False,
         k_scale: Optional[jnp.ndarray] = None,
         v_scale: Optional[jnp.ndarray] = None,
         block_tables: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Scaled dot-product attention over (B, T, N, H)-layout tensors.

    `q_offset` is the global position of q[:, 0] (nonzero during KV-cached
    decode, cf. reference start_pos plumbing at model.py:641-650).
    `decode=True` marks a KV-cached call (prefill or single-token): it is
    exempt from the ring/ulysses fail-loud check below — decoding is never
    sequence-parallel, even when a prompt exactly fills the cache and the
    shapes look like a training step.

    `k_scale`/`v_scale` (B, S, n_kv, 1) mark an int8-quantized KV cache
    (ops/quant.py): k/v hold int8 codes. The flash-decode kernel
    dequantizes in VMEM (half the cache DMA); every other path
    dequantizes the buffers up front and proceeds unchanged.

    `block_tables` (B, max_blocks) int32 marks k/v (and the scale
    sidecars) as PAGED pools (ops/block_pool.py): single-token decode
    routes to the paged flash kernel (block-table scalar prefetch — no
    gather, no full-buffer stream); every other path materializes the
    logical per-sequence view with one `paged_gather` and proceeds
    unchanged — the gathered view holds identical values at identical
    logical positions, so downstream numerics match the contiguous cache.
    """
    hs = q.shape[-1]
    scale = (1.0 / hs ** 0.5) if scale is None else scale

    if impl not in ("auto", "pallas", "xla", "naive", "ring", "zigzag",
                    "ulysses"):
        raise ValueError(f"unknown attention impl {impl!r}; expected "
                         "'auto' | 'pallas' | 'xla' | 'naive' | 'ring' | "
                         "'zigzag' | 'ulysses'")

    use_dropout = dropout_rate > 0.0 and dropout_rng is not None

    if block_tables is not None:
        # paged KV cache: kernel first (single-token decode), else gather
        # the logical view and fall through to the shared routing below
        if (decode and causal and q.shape[1] == 1 and not use_dropout
                and impl in ("auto", "pallas", "xla")):
            from distributed_pytorch_tpu.ops.flash_decode import (
                decode_mode, paged_flash_decode, paged_flash_decode_usable)
            mode = decode_mode()
            if (mode == "on" or (mode == "auto" and _on_tpu())) \
                    and paged_flash_decode_usable(q, k, v, block_tables):
                cl = jnp.broadcast_to(jnp.reshape(
                    jnp.asarray(q_offset, jnp.int32), (-1,)) + 1,
                    (q.shape[0],))
                out = paged_flash_decode(q[:, 0], k, v, block_tables, cl,
                                         scale=scale, k_scale=k_scale,
                                         v_scale=v_scale,
                                         interpret=not _on_tpu())
                return out[:, None]
        # mixed prefill+decode path: a multi-token chunk (or a whole
        # bucketed-wave suffix) of ONE sequence, written at q_offset and
        # attending causally over the sequence's own prior blocks — the
        # chunk kernel streams those blocks through the table prefetch
        # instead of gathering the whole logical view
        if (decode and causal and q.shape[1] > 1 and q.shape[0] == 1
                and not use_dropout and impl in ("auto", "pallas", "xla")):
            from distributed_pytorch_tpu.ops.flash_decode import (
                decode_mode, paged_flash_prefill, paged_flash_prefill_usable)
            mode = decode_mode()
            if (mode == "on" or (mode == "auto" and _on_tpu())) \
                    and paged_flash_prefill_usable(q, k, v, block_tables):
                off = jnp.reshape(jnp.asarray(q_offset, jnp.int32), (-1,))[0]
                return paged_flash_prefill(q, k, v, block_tables, off,
                                           scale=scale, k_scale=k_scale,
                                           v_scale=v_scale,
                                           interpret=not _on_tpu())
        from distributed_pytorch_tpu.ops.block_pool import paged_gather
        k = paged_gather(k, block_tables)
        v = paged_gather(v, block_tables)
        if k_scale is not None:
            k_scale = paged_gather(k_scale, block_tables)
            v_scale = paged_gather(v_scale, block_tables)
        if k.dtype != jnp.int8:
            k = k.astype(q.dtype)
            v = v.astype(q.dtype)

    # KV-cached single-token decode: the memory-bound fast path. The
    # split-KV Pallas kernel (ops/flash_decode.py) streams each sequence's
    # VALID cache rows exactly once (per-sequence cache_len scalar-prefetch
    # skips dead slots entirely) instead of the naive einsum's full-buffer
    # read + per-query-head K/V repeat. Same degrade-don't-crash contract
    # as loss_impl='pallas': the usable gate falls back to the naive path.
    if (decode and causal and q.shape[1] == 1 and not use_dropout
            and impl in ("auto", "pallas", "xla")):
        from distributed_pytorch_tpu.ops.flash_decode import (
            decode_mode, flash_decode, flash_decode_usable)
        mode = decode_mode()
        if (mode == "on" or (mode == "auto" and _on_tpu())) \
                and flash_decode_usable(q, k, v):
            # valid rows per sequence: the query's global position + 1,
            # capped at the buffer length (ring cache wrapped)
            cl = jnp.minimum(
                jnp.reshape(jnp.asarray(q_offset, jnp.int32), (-1,)) + 1,
                k.shape[1])
            cl = jnp.broadcast_to(cl, (q.shape[0],))
            out = flash_decode(q[:, 0], k, v, cl, scale=scale,
                               k_scale=k_scale, v_scale=v_scale,
                               interpret=not _on_tpu())
            return out[:, None]

    if k_scale is not None:
        # int8 cache on a non-kernel path (prefill, kernel gate declined,
        # FLASH_DECODE=off): dequantize up front — identical semantics to
        # a bf16 cache holding the dequantized values, more HBM traffic.
        from distributed_pytorch_tpu.ops.quant import dequantize_int8
        k = dequantize_int8(k, k_scale, q.dtype)
        v = dequantize_int8(v, v_scale, q.dtype)

    # Sequence parallelism: when the ambient mesh (parallel/context.py) has
    # a live 'seq' axis and shapes allow, full-sequence causal attention
    # runs as ring/Ulysses over explicit 'seq' collectives instead of
    # letting GSPMD all-gather the whole sequence per device.
    # NOTE: this routing is a trace-time decision — the ambient mesh is not
    # part of jax.jit's cache key. Callers must establish context.use_mesh
    # BEFORE the first (tracing) call of their jitted function, as the
    # trainer's step builders do (train/step.py); a function first traced
    # without the mesh keeps its GSPMD full-gather path.
    from distributed_pytorch_tpu.parallel import context
    sp = context.seq_axis_size()
    sp_live = sp > 1 and not context.in_sp_region()

    if sp_live and impl in ("auto", "ring", "zigzag", "ulysses"):
        static_zero = isinstance(q_offset, int) and q_offset == 0
        mesh = context.get_mesh()
        dp = mesh.shape["data"]
        T, S, B = q.shape[1], k.shape[1], q.shape[0]
        sp_ok = (causal and static_zero and T == S and T % sp == 0
                 and B % dp == 0 and T // sp > 0)
        if sp_ok:
            from distributed_pytorch_tpu.ops.ring_attention import sp_sdpa
            if impl == "ulysses":
                sp_impl = "ulysses"
            elif impl == "ring":
                sp_impl = "ring"      # explicit: contiguous schedule
            else:                     # 'auto'/'zigzag': load-balanced
                sp_impl = "zigzag"    # (falls back to ring inside when
                                      # the stripe split doesn't divide)
            if (sp_impl == "ulysses"
                    and (q.shape[2] % sp or k.shape[2] % sp)):
                sp_impl = "zigzag"  # head counts not sp-divisible
            # dropout composes with sp since round 5: the ring/zig-zag
            # einsum hops draw a global-position-keyed mask (sp_sdpa);
            # ulysses reroutes to zigzag inside when rate > 0
            return sp_sdpa(q, k, v, scale=scale, causal=causal,
                           impl=sp_impl,
                           dropout_rate=dropout_rate if use_dropout else 0.0,
                           dropout_rng=dropout_rng)
    if impl in ("ring", "zigzag", "ulysses"):
        # De-trap (round-3 VERDICT #9): an explicit ring/ulysses request
        # on training-like shapes (full causal self-attention) with NO
        # live 'seq' axis means the caller traced without
        # context.use_mesh — the old silent GSPMD-full-gather fallback
        # hid exactly the bug the ambient-mesh design risks. Fail loud.
        # Decode-shaped calls (T != S, cache offsets) legitimately fall
        # back: decoding isn't sequence-parallel even in sp training.
        training_like = (causal and not decode
                         and q.shape[1] == k.shape[1]
                         and q.shape[1] > 1
                         and isinstance(q_offset, int) and q_offset == 0)
        if training_like and sp <= 1 and not context.in_sp_region():
            raise ValueError(
                f"attn_impl={impl!r} requested but no live 'seq' mesh "
                "axis is visible at trace time. Establish the mesh "
                "around tracing (parallel.context.use_mesh, as the "
                "trainer's step builders do) or use the 'sp' recipe; "
                "a silent fallback here would lose sequence "
                "parallelism without any signal.")
        impl = "auto"  # shapes don't allow sp (e.g. decode steps)

    if use_dropout:
        # the flash kernel applies attention-weight dropout IN-KERNEL
        # (round-5: mask bits regenerated per tile, never in HBM) — the
        # reference's fused-SDPA-with-dropout equivalent (model.py:149-151).
        # XLA's fused attention has no dropout, so non-flash shapes fall to
        # the naive einsum path; honoring the caller's dropout beats
        # honoring their impl choice.
        if impl in ("auto", "pallas") and _on_tpu():
            from distributed_pytorch_tpu.ops.flash_attention import (
                flash_attention, flash_attention_usable)
            static_zero = isinstance(q_offset, int) and q_offset == 0
            if static_zero and flash_attention_usable(q, k, v, causal=causal):
                def fn(a, b, c, rng):
                    return flash_attention(a, b, c, scale=scale,
                                           causal=causal,
                                           dropout_rate=dropout_rate,
                                           dropout_rng=rng)
                wrapped = _shard_map_over_data(fn, q, has_rng=True)
                if wrapped is not None:
                    return wrapped(q, k, v, dropout_rng)
                return fn(q, k, v, dropout_rng)
        impl = "naive"
    elif impl == "auto":
        # XLA's fused attention is at parity with the Pallas kernel for
        # short sequences; beyond ~4k keys XLA materializes the O(T*S)
        # score matrix (OOM by 32k) while the flash kernel stays O(T).
        long_seq = k.shape[1] > 4096
        impl = "pallas" if (_on_tpu() and long_seq) else "xla"

    if impl == "pallas":
        from distributed_pytorch_tpu.ops.flash_attention import flash_attention_usable, flash_attention
        static_zero = isinstance(q_offset, int) and q_offset == 0
        if static_zero and flash_attention_usable(q, k, v, causal=causal):
            fn = functools.partial(flash_attention, scale=scale,
                                   causal=causal)
            wrapped = _shard_map_over_data(fn, q)
            if wrapped is not None:
                return wrapped(q, k, v)
            return fn(q, k, v)
        impl = "xla"

    if impl == "xla":
        is_static_zero_offset = isinstance(q_offset, int) and q_offset == 0
        if is_static_zero_offset:
            return jax.nn.dot_product_attention(
                q, k, v, scale=scale, is_causal=causal, implementation="xla")
        impl = "naive"  # offset masks -> explicit path

    return _naive_sdpa(q, k, v, scale=scale, q_offset=q_offset,
                       dropout_rate=dropout_rate, dropout_rng=dropout_rng,
                       causal=causal)
