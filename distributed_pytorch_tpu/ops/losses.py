"""Cross-entropy losses for the weight-tied LM head.

The reference computes `F.cross_entropy(logits.view(-1, V), targets)` over
fully materialized logits (reference single-gpu/model.py:687-692). At
GPT-vocab scale that materialization is the single biggest activation in the
step: (B, T, V) fp32 is ~3.3 GB for B=16, T=1024, V=50304 — plus the
log-softmax intermediate and d_logits in backward. On a v5e this
memory-bound tail was the prime suspect for the round-3 MFU gap
(VERDICT round 3, weak #1).

`fused_cross_entropy` never materializes the full logits: the sequence axis
is split into chunks and a `lax.scan` computes each chunk's
`logsumexp(logits) - logit[target]` under `jax.checkpoint`, so both forward
and backward hold at most one (B, chunk, V) block at a time. The lm-head
matmul itself runs in the compute dtype with fp32 accumulation
(`preferred_element_type`), which is MXU-native and slightly *better*
numerics than the reference's cast-then-log_softmax.

Sharding: chunking slices T while keeping the (B, chunk) token dims, so a
'data'-sharded batch stays sharded inside every chunk (all devices active
every scan iteration) and GSPMD's handling of a sharded embedding (tp
vocab-parallel psum, fsdp all-gather — hoisted out of the scan as
loop-invariant) is unchanged. Under a live 'seq' axis
`sp_fused_cross_entropy` runs the same chunk scan per device over the
LOCAL T shard inside shard_map and psums the (sum, count) pair — no
seq-sharded full-logits materialization (gpt.py routes on
`context.seq_axis_size()`).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _default_logits(x: jnp.ndarray, embedding: jnp.ndarray) -> jnp.ndarray:
    """x (..., C) @ embedding^T (V, C) -> (..., V) fp32 — the plain GSPMD
    lm-head matmul. Callers may override with `logits_fn` (gpt.py routes
    the collective-matmul ring through it under OVERLAP=on)."""
    return jax.lax.dot_general(
        x, embedding, (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)


def unchunked_cross_entropy(x: jnp.ndarray, embedding: jnp.ndarray,
                            targets: jnp.ndarray, *,
                            ignore_index: int = -1,
                            logits_fn=None) -> jnp.ndarray:
    """Mean CE over valid targets, full (B, T, V) logits (semantics oracle;
    mirrors reference model.py:687-692 incl. ignore_index=-1)."""
    logits = (logits_fn or _default_logits)(x, embedding)  # (B, T, V) fp32
    mask = targets != ignore_index
    safe = jnp.where(mask, targets, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    denom = jnp.maximum(mask.sum(), 1)
    return jnp.where(mask, nll, 0.0).sum() / denom


def _chunk_for(T: int, V: int, target_tokens: int = 128,
               min_chunk: int = 16) -> int:
    """Largest divisor of T that is <= target_tokens (0 = don't chunk).

    Chunking only pays when the full logits block is big; tiny vocabularies
    (tests) or short sequences skip it so the scan overhead never hurts the
    small-model path. A divisor below `min_chunk` (awkward T, e.g. prime)
    would degrade to a near-per-token scan — fall back to unchunked
    instead."""
    if T <= target_tokens or V < 8192:
        return 0
    for c in range(target_tokens, min_chunk - 1, -1):
        if T % c == 0 and T // c > 1:
            return c
    return 0


def _nll_sum_chunked(x: jnp.ndarray, embedding: jnp.ndarray,
                     targets: jnp.ndarray, ignore_index: int,
                     chunk: int, logits_fn=None):
    """(sum of nll over valid targets, valid count) with the T axis chunked
    through a rematerialized scan — the shared core of fused_cross_entropy
    and the sequence-parallel local body. Falls back to one unchunked block
    when chunking can't help (tiny T/V or non-dividing chunk)."""
    B, T, C = x.shape
    V = embedding.shape[0]
    if chunk <= 0:
        chunk = _chunk_for(T, V)

    def block_nll(x_c, t_c):
        logits = (logits_fn or _default_logits)(x_c, embedding)
        # (B, chunk, V) fp32
        mask = t_c != ignore_index
        safe = jnp.where(mask, t_c, 0)
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
        nll = lse - tgt
        return jnp.where(mask, nll, 0.0).sum(), mask.sum()

    if chunk <= 0 or T % chunk != 0 or T // chunk <= 1:
        return block_nll(x, targets)
    n_chunks = T // chunk

    # (n_chunks, B, chunk, ...): scan iterates T-slices, B stays a real dim
    # so its 'data' sharding survives inside every chunk.
    xs = jnp.moveaxis(x.reshape(B, n_chunks, chunk, C), 1, 0)
    ts = jnp.moveaxis(targets.reshape(B, n_chunks, chunk), 1, 0)

    ckpt_nll = jax.checkpoint(block_nll)

    # accumulate via stacked scan OUTPUTS, not the carry: a scalar-zero
    # carry would be unvarying over the mesh axes while the chunk sums vary
    # (shard_map vma typing), and (n_chunks,) scalars are free
    def body(carry, xt):
        return carry, ckpt_nll(*xt)

    _, (sums, counts) = jax.lax.scan(body, None, (xs, ts))
    return sums.sum(), counts.sum()


def sp_fused_cross_entropy(x: jnp.ndarray, embedding: jnp.ndarray,
                           targets: jnp.ndarray, *,
                           ignore_index: int = -1,
                           chunk: int = 0) -> jnp.ndarray:
    """Sequence-parallel chunked CE: each device chunk-scans its LOCAL
    (B/dp, T/sp) token shard inside shard_map, then the sum/count pair is
    psum'd over ('data', 'seq') for the global mean.

    This replaces the round-4 fallback where any live 'seq' axis demoted
    the loss to unchunked full-logits CE — a (B, T/sp, V) fp32
    materialization per device, the largest activation at GPT vocab and
    exactly the long-context configs sp exists for (round-4 VERDICT
    weak #6). Here every device stays active through its own chunk scan
    and at most (B/dp, chunk, V) logits exist per device at a time.

    Callers gate on: live 'seq' axis, no vocab-parallel embedding (tp —
    the replicated in_spec would all-gather a 'model'-sharded embedding),
    and B divisible by dp (gpt.py)."""
    from distributed_pytorch_tpu.parallel import context

    mesh = context.get_mesh()
    assert mesh is not None and context.seq_axis_size() > 1

    def local_body(x_l, emb, t_l):
        # the caller's chunk is sized against the GLOBAL T; inside
        # shard_map the shard is T/sp, so a non-dividing chunk must be
        # re-derived locally (not silently degrade to one full-logits
        # block — the exact materialization this path removes)
        t_local = x_l.shape[1]
        c = chunk if (chunk > 0 and t_local % chunk == 0
                      and t_local // chunk > 1) else 0
        s, n = _nll_sum_chunked(x_l, emb, t_l, ignore_index, c)
        s = jax.lax.psum(s, ("data", "seq"))
        n = jax.lax.psum(n, ("data", "seq"))
        return s / jnp.maximum(n, 1)

    from jax.sharding import PartitionSpec as P

    from distributed_pytorch_tpu import compat
    fn = compat.shard_map(
        local_body, mesh=mesh,
        in_specs=(P("data", "seq", None), P(None, None), P("data", "seq")),
        out_specs=P())
    return fn(x, embedding, targets)


def fused_cross_entropy(x: jnp.ndarray, embedding: jnp.ndarray,
                        targets: jnp.ndarray, *,
                        ignore_index: int = -1,
                        chunk: int = 0, logits_fn=None) -> jnp.ndarray:
    """Chunked weight-tied CE: logits are computed (and re-computed in
    backward) one T-chunk at a time; the (B, T, V) block never exists.

    x: (B, T, C) hidden states (compute dtype); embedding: (V, C);
    targets: (B, T) int with `ignore_index` masking. `chunk=0` picks a
    divisor of T automatically (or falls back to the unchunked oracle when
    chunking can't help). `logits_fn(x_chunk, embedding)` overrides the
    per-chunk lm-head matmul (collective-matmul routing, gpt.py).
    """
    B, T, C = x.shape
    V = embedding.shape[0]
    if chunk <= 0:
        chunk = _chunk_for(T, V)
    if chunk <= 0 or T % chunk != 0 or T // chunk <= 1:
        return unchunked_cross_entropy(x, embedding, targets,
                                       ignore_index=ignore_index,
                                       logits_fn=logits_fn)
    total, count = _nll_sum_chunked(x, embedding, targets, ignore_index,
                                    chunk, logits_fn=logits_fn)
    return total / jnp.maximum(count, 1)
