"""Sequence/context-parallel attention over the 'seq' mesh axis: ring
attention (ppermute KV rotation + online softmax) and the Ulysses
all-to-all head<->sequence reshard variant.

This is a capability the reference lacks entirely (SURVEY.md §5
"Long-context: entirely absent" — its max context is block_size with an
O(T^2) materialized mask, model.py:225). Design per the scaling-book /
Ring Attention (arXiv:2310.01889) and DeepSpeed-Ulysses (arXiv:2309.14509)
recipes:

* **Ring**: every device holds a (B, T/sp, H, D) shard of q/k/v. For sp
  steps, each device attends its local q against the resident kv chunk and
  accumulates with the online-softmax recurrence (running max m,
  normalizer l, f32 accumulator — the same math as the Pallas flash
  kernel, ops/flash_attention.py), then rotates k/v one hop around the
  ring with `jax.lax.ppermute` over ICI neighbors. KV chunks whose global
  positions lie entirely in the causal future are SKIPPED with a
  per-device `lax.cond`: a device spends no matmul FLOPs on a chunk the
  mask would zero anyway (on average (sp-1)/2 of sp hops skip). NOTE the
  honest accounting: under this CONTIGUOUS layout the last ring device is
  visible on every hop and each ppermute synchronizes the ring, so step
  *latency* stays sp x chunk_time — the cond saves energy/FLOPs and frees
  compute for co-scheduled work, not wall-clock. Recovering latency needs
  a load-balanced (zig-zag/striped) sequence layout where every device
  holds one early and one late stripe — future work, it changes the
  loader's T-sharding contract. Each step is wrapped in `jax.checkpoint`
  so the backward rematerializes the per-chunk probabilities instead of
  storing sp O((T/sp)^2) slabs.
* **Ulysses**: `all_to_all` resharding (B, T/sp, H, D) -> (B, T, H/sp, D),
  ONE local full-sequence causal attention per head subset (which can use
  the Pallas flash kernel), then the inverse all_to_all. Cheaper compute
  (no redundant masked blocks), but requires sp | H (and sp | n_kv_heads),
  and moves activations twice over the interconnect.

Both are *local* functions meant to run inside `shard_map`; `sp_sdpa`
wraps them for the dispatcher, reading the ambient mesh
(parallel/context.py).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from distributed_pytorch_tpu import compat

_NEG_INF = -1e30


def _local_scores(q, k, scale):
    """(B, Tq, H, D) x (B, Tk, Hkv, D) -> (B, H, Tq, Tk) f32 scores, with
    GQA kv-head repeat."""
    nh, nkv = q.shape[2], k.shape[2]
    if nkv != nh:
        k = jnp.repeat(k, nh // nkv, axis=2)
    return jnp.einsum("bqhd,bkhd->bhqk", q, k,
                      preferred_element_type=jnp.float32) * scale


def _hop_dropout_mask(shape, qo, ko, nh, rate, seed):
    """Scaled keep-mask for one (B, H, Tq, Tk) chunk, keyed on GLOBAL
    (attention row, query position, key position) via the flash kernel's
    counter-based hash (ops/flash_attention._mix_bits): every device and
    every hop regenerates consistent, non-overlapping bits from the same
    seed, so across the 'seq' axis the merged mask is one coherent
    full-sequence draw — exact-parity testable against a host replay.
    (Across 'data' shards the seed is deliberately folded per shard by
    sp_sdpa, so masks are NOT dp-size-invariant — row keys are
    shard-local.)"""
    from distributed_pytorch_tpu.ops.flash_attention import (
        _mix_bits, dropout_threshold)
    row = (jax.lax.broadcasted_iota(jnp.uint32, shape, 0) * jnp.uint32(nh)
           + jax.lax.broadcasted_iota(jnp.uint32, shape, 1))
    qp = (jnp.asarray(qo).astype(jnp.uint32)
          + jax.lax.broadcasted_iota(jnp.uint32, shape, 2))
    kp = (jnp.asarray(ko).astype(jnp.uint32)
          + jax.lax.broadcasted_iota(jnp.uint32, shape, 3))
    bits = _mix_bits(seed[0], seed[1], row, qp, kp)
    return ((bits >= dropout_threshold(rate)).astype(jnp.float32)
            / (1.0 - rate))


def _chunk_update(carry, q, k, v, qo, ko, scale, causal, rate=0.0,
                  seed=None):
    """One online-softmax accumulation of local q against one kv chunk.

    qo/ko: global token offsets of the q and kv chunks (traced scalars).
    carry: (acc (B,H,Tq,D) f32, m (B,H,Tq,1) f32, l (B,H,Tq,1) f32).
    `rate` > 0 applies attention-weight dropout to the value accumulation
    only (the normalizer keeps the undropped p — torch SDPA semantics);
    the mask is global-position-keyed (_hop_dropout_mask) so the merged
    result is full-sequence dropout, not per-chunk.
    """
    acc, m, l = carry
    B, Tq, nh, D = q.shape
    Tk = k.shape[1]
    s = _local_scores(q, k, scale)                     # (B,H,Tq,Tk)
    if causal:
        qpos = qo + jnp.arange(Tq)[:, None]
        kpos = ko + jnp.arange(Tk)[None, :]
        s = jnp.where((qpos >= kpos)[None, None], s, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new)                             # (B,H,Tq,Tk)
    nkv = v.shape[2]
    if nkv != nh:
        v = jnp.repeat(v, nh // nkv, axis=2)
    l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    if rate > 0.0:
        p = p * _hop_dropout_mask(p.shape, qo, ko, nh, rate, seed)
    pv = jnp.einsum("bhqk,bkhd->bhqd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    acc = acc * alpha + pv
    return acc, m_new, l


def _init_carry(q, nh: int, Tq: int):
    """Zeroed online-softmax carry (acc, m, l) for Tq query rows, pcast to
    q's varying-axis set: the hop-skipping lax.cond requires both branches
    to agree on varying-manual-axis types inside shard_map, and the
    computed branch's outputs inherit the inputs' varying set."""
    B, D = q.shape[0], q.shape[3]
    acc = jnp.zeros((B, nh, Tq, D), jnp.float32)
    m = jnp.full((B, nh, Tq, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((B, nh, Tq, 1), jnp.float32)
    vma = compat.vma_of(q)
    acc, m, l = (compat.pcast_varying(t, vma) for t in (acc, m, l))
    return acc, m, l


# --- flash-kernel hop path: per-hop (out, lse) pairs merged online --------
#
# The einsum hop (_chunk_update) materializes a (B, H, Tq, Tk) probability
# slab per hop — O((T/sp)^2) transient HBM, recomputed in backward via
# jax.checkpoint. When shapes allow, each hop instead runs the Pallas flash
# kernel (ops/flash_attention.py) in causal mode for the diagonal chunk and
# full mode for visible off-diagonal chunks: probabilities never leave
# VMEM, and the kernel's custom vjp recomputes them blockwise in backward
# (no jax.checkpoint wrapper needed). The cross-chunk merge is the standard
# normalized-pair recurrence over (out, lse) — differentiable because the
# kernel's lse output carries gradients (the dlse term folds into delta).

def _flash_ring_ok(q, k, v) -> bool:
    from distributed_pytorch_tpu.ops import attention_core as core
    from distributed_pytorch_tpu.ops.flash_attention import (
        flash_attention_usable)
    return core._on_tpu() and flash_attention_usable(q, k, v)


def _init_flash_carry(q, nh: int, Tq: int):
    B, D = q.shape[0], q.shape[3]
    out = jnp.zeros((B, Tq, nh, D), jnp.float32)
    lse = jnp.full((B, Tq, nh), _NEG_INF, jnp.float32)
    vma = compat.vma_of(q)
    out, lse = (compat.pcast_varying(t, vma) for t in (out, lse))
    return out, lse


def _merge_flash(carry, out_c, lse_c):
    out, lse = carry
    new_lse = jnp.logaddexp(lse, lse_c)
    w_old = jnp.exp(lse - new_lse)[..., None]
    w_new = jnp.exp(lse_c - new_lse)[..., None]
    return out * w_old + out_c.astype(jnp.float32) * w_new, new_lse


def _flash_hop(carry, q, k, v, scale, causal_mode: bool):
    from distributed_pytorch_tpu.ops.flash_attention import (
        flash_attention_lse)
    out_c, lse_c = flash_attention_lse(q, k, v, scale=scale,
                                       causal=causal_mode)
    return _merge_flash(carry, out_c, lse_c)


def ring_attention_local(q, k, v, *, scale: float, axis_name: str = "seq",
                         sp: int, causal: bool = True, rate: float = 0.0,
                         seed=None) -> jnp.ndarray:
    """Ring attention body (call inside shard_map). q/k/v: local
    (B, T/sp, H|Hkv, D) shards, contiguous sequence layout (shard i holds
    global positions [i*Tloc, (i+1)*Tloc)). `rate`/`seed`: global-keyed
    attention-weight dropout in the einsum hops (the flash-hop path is
    rate==0 only — its per-call mask coords aren't global)."""
    idx = jax.lax.axis_index(axis_name)
    B, Tloc, nh, D = q.shape
    qo = idx * Tloc
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    if causal and rate == 0.0 and _flash_ring_ok(q, k, v):
        # flash-kernel hops: O(Tloc) memory per hop, VMEM softmax. The
        # diagonal is trace-time static: hop s=0 holds the device's OWN kv
        # chunk (ko == qo uniformly), every later hop is either fully
        # visible (ko < qo) or entirely future (skip) — so the causal
        # kernel appears exactly once and hops 1..sp-1 carry a single cond
        carry = _init_flash_carry(q, nh, Tloc)
        carry = _flash_hop(carry, q, k, v, scale, True)   # s=0: diagonal
        for s in range(1, sp):
            k = jax.lax.ppermute(k, axis_name, perm)
            v = jax.lax.ppermute(v, axis_name, perm)
            ko = ((idx - s) % sp) * Tloc
            carry = jax.lax.cond(
                ko > qo,                     # entirely in the causal future
                lambda c, *xs: c,
                lambda c, q_, k_, v_: _flash_hop(c, q_, k_, v_, scale,
                                                 False),
                carry, q, k, v)
        out, _ = carry
        return out.astype(q.dtype)

    acc, m, l = _init_carry(q, nh, Tloc)

    step_fn = jax.checkpoint(functools.partial(_chunk_update, scale=scale,
                                               causal=causal, rate=rate,
                                               seed=seed))

    carry = (acc, m, l)
    for s in range(sp):
        # after s hops the resident chunk originated at ring position
        # (idx - s) mod sp
        ko = ((idx - s) % sp) * Tloc
        if causal:
            # skip chunks entirely in this device's causal future: the
            # predicate is per-device (idx is traced) and the branches
            # contain no collectives, so the cond is SPMD-legal inside
            # shard_map; the ppermute below still runs every hop on every
            # device, keeping the ring schedule uniform
            visible = ko <= qo + Tloc - 1
            carry = jax.lax.cond(
                visible,
                lambda c, *xs: step_fn(c, *xs),
                lambda c, *xs: c,
                carry, q, k, v, qo, ko)
        else:
            carry = step_fn(carry, q, k, v, qo, ko)
        if s < sp - 1:
            k = jax.lax.ppermute(k, axis_name, perm)
            v = jax.lax.ppermute(v, axis_name, perm)

    acc, m, l = carry
    out = acc / jnp.maximum(l, 1e-30)
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def zigzag_ring_attention_local(q, k, v, *, scale: float,
                                axis_name: str = "seq",
                                sp: int, rate: float = 0.0,
                                seed=None) -> jnp.ndarray:
    """Load-balanced ("zig-zag") causal ring attention body.

    The contiguous layout's flaw: device sp-1 holds the latest positions
    and is causally visible on every hop, so per-hop barriers pin step
    latency at sp x chunk_time even with hop skipping. Here the sequence
    is pre-permuted (see `zigzag_permutation`) so device i holds stripe i
    (early) AND stripe 2sp-1-i (late), each of length T/(2sp): every
    device's total visible work across the ring is identical
    ((2sp+1) stripe-pairs), so the causal triangle is spread evenly and
    wall-clock approaches the balanced optimum instead of 2x it.

    Local layout: q/k/v = [stripe_lo, stripe_hi] concatenated on the
    sequence axis. Each hop updates two (q half, kv half) carries with
    per-pair lax.cond visibility (b <= a at stripe granularity; the
    positional mask inside _chunk_update handles the b == a diagonal).
    """
    idx = jax.lax.axis_index(axis_name)
    B, Tloc, nh, D = q.shape
    Ts = Tloc // 2
    a_lo = idx * Ts                      # global offset of early stripe
    a_hi = (2 * sp - 1 - idx) * Ts       # global offset of late stripe
    q_lo, q_hi = q[:, :Ts], q[:, Ts:]
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    use_flash = rate == 0.0 and _flash_ring_ok(q_lo, k[:, :Ts], v[:, :Ts])

    if use_flash:
        # Stripe diagonals are trace-time static too: they occur ONLY at
        # s=0 (a device's own stripes — pairs (lo,lo) and (hi,hi) causal,
        # (hi,lo) fully visible since a_hi > b for every b < sp*Ts, and
        # (lo,hi) always future); for s >= 1 the four pairs are each
        # either fully visible or future — a single cond per pair.
        def visible_update(carry, q_part, kv_k, kv_v, qo, ko):
            return jax.lax.cond(
                ko > qo,
                lambda c: c,
                lambda c: _flash_hop(c, q_part, kv_k, kv_v, scale, False),
                carry)

        c_lo = _init_flash_carry(q, nh, Ts)
        c_hi = _init_flash_carry(q, nh, Ts)
        k_lo, k_hi = k[:, :Ts], k[:, Ts:]
        v_lo, v_hi = v[:, :Ts], v[:, Ts:]
        c_lo = _flash_hop(c_lo, q_lo, k_lo, v_lo, scale, True)   # (lo,lo)
        c_hi = _flash_hop(c_hi, q_hi, k_hi, v_hi, scale, True)   # (hi,hi)
        c_hi = _flash_hop(c_hi, q_hi, k_lo, v_lo, scale, False)  # (hi,lo)
        # (lo,hi) is always in the future — skipped statically
        for s in range(1, sp):
            k = jax.lax.ppermute(k, axis_name, perm)
            v = jax.lax.ppermute(v, axis_name, perm)
            j = (idx - s) % sp           # origin device of resident kv
            b_lo = j * Ts
            b_hi = (2 * sp - 1 - j) * Ts
            k_lo, k_hi = k[:, :Ts], k[:, Ts:]
            v_lo, v_hi = v[:, :Ts], v[:, Ts:]
            # statically decidable pairs: (hi, lo') is ALWAYS visible
            # (a_hi >= sp*Ts > any early stripe) and (lo, hi') is always
            # future; the two remaining pairs need a runtime cond
            c_lo = visible_update(c_lo, q_lo, k_lo, v_lo, a_lo, b_lo)
            c_hi = _flash_hop(c_hi, q_hi, k_lo, v_lo, scale, False)
            c_hi = visible_update(c_hi, q_hi, k_hi, v_hi, a_hi, b_hi)
        return jnp.concatenate([c_lo[0], c_hi[0]], axis=1).astype(q.dtype)

    step_fn = jax.checkpoint(functools.partial(_chunk_update,
                                               scale=scale, causal=True,
                                               rate=rate, seed=seed))

    def masked_update(carry, q_part, kv_k, kv_v, qo, ko):
        return jax.lax.cond(
            ko <= qo,                    # stripe-granular visibility
            lambda c: step_fn(c, q_part, kv_k, kv_v, qo, ko),
            lambda c: c,
            carry)

    c_lo, c_hi = _init_carry(q, nh, Ts), _init_carry(q, nh, Ts)
    for s in range(sp):
        j = (idx - s) % sp               # origin device of resident kv
        b_lo = j * Ts
        b_hi = (2 * sp - 1 - j) * Ts
        k_lo, k_hi = k[:, :Ts], k[:, Ts:]
        v_lo, v_hi = v[:, :Ts], v[:, Ts:]
        for ko, kk, vv in ((b_lo, k_lo, v_lo), (b_hi, k_hi, v_hi)):
            c_lo = masked_update(c_lo, q_lo, kk, vv, a_lo, ko)
            c_hi = masked_update(c_hi, q_hi, kk, vv, a_hi, ko)
        if s < sp - 1:
            k = jax.lax.ppermute(k, axis_name, perm)
            v = jax.lax.ppermute(v, axis_name, perm)

    def finish(carry):
        acc, m, l = carry
        out = acc / jnp.maximum(l, 1e-30)
        return jnp.einsum("bhqd->bqhd", out)

    return jnp.concatenate([finish(c_lo), finish(c_hi)],
                           axis=1).astype(q.dtype)


def zigzag_permutation(T: int, sp: int):
    """(perm, inv_perm) index arrays mapping natural sequence order to the
    zig-zag shard layout: shard i's rows = [stripe_i, stripe_{2sp-1-i}],
    stripe length T/(2sp)."""
    import numpy as np
    Ts = T // (2 * sp)
    parts = []
    for i in range(sp):
        parts.append(np.arange(i * Ts, (i + 1) * Ts))
        parts.append(np.arange((2 * sp - 1 - i) * Ts, (2 * sp - i) * Ts))
    perm = np.concatenate(parts)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(T)
    return perm, inv


def ulysses_attention_local(q, k, v, *, scale: float, axis_name: str = "seq",
                            sp: int, causal: bool = True,
                            attn_impl: str = "auto") -> jnp.ndarray:
    """Ulysses body (call inside shard_map): all_to_all heads<->sequence,
    local full-sequence attention (impl='auto' engages the Pallas flash
    kernel at long T; context.sp_region blocks re-entry into the sp path),
    inverse all_to_all. Requires sp | nh and sp | n_kv_heads."""
    from distributed_pytorch_tpu.ops.attention_core import sdpa

    # (B, T/sp, H, D) -> (B, T, H/sp, D): split heads, gather sequence
    qg = jax.lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1,
                            tiled=True)
    kg = jax.lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1,
                            tiled=True)
    vg = jax.lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1,
                            tiled=True)
    out = sdpa(qg, kg, vg, causal=causal, scale=scale, impl=attn_impl)
    # (B, T, H/sp, D) -> (B, T/sp, H, D)
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)


def sp_sdpa(q, k, v, *, scale: float, causal: bool = True,
            impl: str = "ring", attn_impl: str = "auto",
            dropout_rate: float = 0.0, dropout_rng=None) -> jnp.ndarray:
    """Dispatcher entry: run ring/Ulysses attention over the ambient mesh's
    'seq' axis via shard_map. q (B,T,nh,hs), k/v (B,S,nkv,hs) are LOGICAL
    (full-sequence) arrays inside the enclosing jit; shard_map splits them
    (B over 'data', T over 'seq').

    Requires S == T (training/prefill full-sequence shapes; KV-cached
    decode never routes here)."""
    from distributed_pytorch_tpu.parallel import context

    mesh = context.get_mesh()
    sp = context.seq_axis_size()
    assert mesh is not None and sp > 1
    assert q.shape[1] == k.shape[1], (
        "sequence-parallel attention requires q and kv of equal length "
        f"(got {q.shape[1]} vs {k.shape[1]})")

    rate = float(dropout_rate)
    if rate > 0.0:
        assert dropout_rng is not None, \
            "dropout_rate > 0 requires a dropout_rng key"
        seed = jax.random.randint(dropout_rng, (2,), -2 ** 31, 2 ** 31 - 1,
                                  jnp.int32)
        if impl == "ulysses":
            # the ring hops' global-position-keyed mask has no ulysses
            # equivalent (the local call sees permuted head subsets);
            # zig-zag/ring give the same math with exact dropout
            impl = "zigzag" if (causal and q.shape[1] % (2 * sp) == 0) \
                else "ring"
    else:
        seed = jnp.zeros((2,), jnp.int32)

    zigzag = False
    if impl == "ulysses":
        nkv = k.shape[2]
        assert q.shape[2] % sp == 0 and nkv % sp == 0, (
            f"ulysses needs sp={sp} dividing n_head={q.shape[2]} and "
            f"n_kv_heads={nkv}; use ring attention instead")
        body = functools.partial(ulysses_attention_local, scale=scale,
                                 sp=sp, causal=causal, attn_impl=attn_impl)
    elif impl == "zigzag" and causal and q.shape[1] % (2 * sp) == 0:
        # load-balanced zig-zag ring (latency ~optimal; see the local fn's
        # docstring) — semantically identical to the contiguous ring. The
        # dispatcher's 'auto' resolves here; an explicit impl='ring' keeps
        # the contiguous schedule reachable for A/B and debugging.
        zigzag = True
        body = functools.partial(zigzag_ring_attention_local, scale=scale,
                                 sp=sp, rate=rate)
    else:
        body = functools.partial(ring_attention_local, scale=scale, sp=sp,
                                 causal=causal, rate=rate)

    def shard_body(a, b, c, seed_rep):
        with context.sp_region():   # no recursive sp routing inside
            if rate > 0.0:
                # decorrelate masks across 'data' shards; the 'seq' axis
                # is deliberately NOT folded — global-position keying
                # already makes seq shards consistent
                from distributed_pytorch_tpu.ops.flash_attention import (
                    fold_seed_for_data_shard)
                seed_rep = fold_seed_for_data_shard(
                    seed_rep, jax.lax.axis_index("data"))
            return body(a, b, c, seed=seed_rep) if rate > 0.0 \
                else body(a, b, c)

    spec = P("data", "seq", None, None)
    fn = compat.shard_map(shard_body, mesh=mesh,
                          in_specs=(spec, spec, spec, P(None)),
                          out_specs=spec)
    if zigzag:
        perm, inv = zigzag_permutation(q.shape[1], sp)
        out = fn(q[:, perm], k[:, perm], v[:, perm], seed)
        return out[:, inv]
    return fn(q, k, v, seed)
